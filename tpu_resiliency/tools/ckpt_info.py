"""Operator view of a local-checkpoint root: holdings, coverage, health.

The local tier's layout is self-describing (``checkpoint/local_manager.py``:
``root/s{session}/r{rank}/iter_NNNNNNN_{owner}_local.ckpt`` — the directory
names the *holder*, the filename the *owner*), so coverage — the property
``find_latest`` needs (some live holder for every owner's shard) — can be
audited offline from the filesystem alone, without the job's comm group. This
is the post-mortem twin of the in-job coverage check: "which iteration could a
restarted world actually resume from, and what is replication costing me?"

``--cold <dir>`` joins the durable cold tier (``checkpoint/coldtier.py``) to
the audit: archived owners count toward per-iteration coverage (the in-job
ladder's third rung, rendered per iteration as local / erasure-reconstructible
/ cold), sessions that exist only in the object store are auditable from an
empty workdir, and ``--verify`` re-checks every archived artifact against its
cold manifest's whole-file digest.

``--verify`` additionally stream-verifies every container's checksums
(format v2 per-leaf CRCs + trailer digest, ``checkpoint/format.py``), prints a
per-file verdict, and exits 1 on any mismatch — an operator preflight before
trusting a root for restart, and a CI gate after fault-injection runs.

``--world <ranks> --plan`` renders the elastic reshard plan the given target
world would execute (``checkpoint/reshard.py``): per target rank, each leaf's
source cells with owner ranks, byte ranges, and the local-slice vs peer-fetch
split implied by what's on disk — without loading a single tensor. Exits 1
when any needed range has no surviving source container ("coverage
impossible", naming the missing ranks).

Usage::

    python -m tpu_resiliency.tools.ckpt_info /ssd/ckpt-root
    python -m tpu_resiliency.tools.ckpt_info /ssd/ckpt-root --session 1
    python -m tpu_resiliency.tools.ckpt_info /ssd/ckpt-root --verify
    python -m tpu_resiliency.tools.ckpt_info /ssd/ckpt-root \
        --cold /backup/cold --verify
    python -m tpu_resiliency.tools.ckpt_info /ssd/ckpt-root --world 0,1,2 --plan
    python -m tpu_resiliency.tools.ckpt_info /ssd/ckpt-root --world 0,1,2,3 \
        --plan --axes dp=2,tp=2
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import re
import sys
from typing import Optional

from tpu_resiliency.checkpoint.local_manager import (
    _BLOCK_RE,
    _CORRUPT_RE,
    _FILE_RE,
)
from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe

_SESSION_RE = re.compile(r"^s(\d+)$")
_RANK_RE = re.compile(r"^r(\d+)$")


@dataclasses.dataclass
class SessionInfo:
    session: int
    #: rank dirs present (the world this root has seen)
    ranks: set
    #: iteration -> owner -> set of holder ranks
    holdings: dict
    #: iteration -> total bytes across all copies
    bytes_by_iter: dict
    #: leftover .dirty temp files (crashed mid-save)
    dirty: list
    #: quarantined *.corrupt files (checksum-failed containers kept for
    #: forensics by the recovery ladder)
    quarantined: list = dataclasses.field(default_factory=list)
    #: container files eligible for --verify: [(path, holder, iter, owner)]
    files: list = dataclasses.field(default_factory=list)
    #: erasure block artifacts: iteration -> owner -> {index: set(holders)}
    #: plus the code's k per (iteration, owner) — k-of-n coverage input
    blocks: dict = dataclasses.field(default_factory=dict)
    block_k: dict = dataclasses.field(default_factory=dict)
    #: block artifact files: [(path, holder, iter, owner, index)]
    block_files: list = dataclasses.field(default_factory=list)
    #: cold-tier coverage (``--cold``): iteration -> set of owners whose
    #: containers the object store archives with a valid manifest
    cold: dict = dataclasses.field(default_factory=dict)

    @property
    def owners(self) -> set:
        out = set()
        for by_owner in self.holdings.values():
            out |= set(by_owner)
        for by_owner in self.blocks.values():
            out |= set(by_owner)
        for owners in self.cold.values():
            out |= set(owners)
        return out

    def reconstructible(self, it: int) -> set:
        """Owners whose shard k-of-n erasure blocks can reassemble at ``it``
        (≥ k distinct surviving block indices)."""
        out = set()
        for owner, by_index in self.blocks.get(it, {}).items():
            if len(by_index) >= self.block_k.get((it, owner), 1 << 30):
                out.add(owner)
        return out

    def covered_iterations(self, world: Optional[set] = None) -> list:
        """Iterations where every rank of ``world`` finds its shard held
        somewhere — a full container on some holder, enough erasure blocks
        to reconstruct one, or (with ``--cold``) an archived copy in the
        cold tier (the offline analogue of ``_covered_iterations`` with its
        third rung).

        Coverage is **group-relative**: a restarted group resumes from the
        newest iteration whose owner set covers *that group* — after an
        elastic shrink the surviving world legitimately resumes from data the
        full original world could not. Default world: everything the
        filesystem shows (rank dirs plus every owner ever named), i.e. the
        original full world."""
        world = (self.ranks | self.owners) if world is None else set(world)
        its = set(self.holdings) | set(self.blocks) | set(self.cold)
        return sorted(
            it
            for it in its
            if world
            <= (
                set(self.holdings.get(it, ()))
                | self.reconstructible(it)
                | set(self.cold.get(it, ()))
            )
        )


def scan(root: str, session: Optional[int] = None) -> list[SessionInfo]:
    """Offline-but-live-safe: a training job's retention pruning can unlink
    files between listing and stat'ing, so every per-entry touch tolerates
    disappearance (the audit then simply reflects the post-prune state)."""
    sessions = []
    try:
        snames = sorted(os.listdir(root))
    except OSError:
        return []  # root itself unlinked mid-audit: post-prune state is "empty"
    for sname in snames:
        sm = _SESSION_RE.match(sname)
        if not sm or (session is not None and int(sm.group(1)) != session):
            continue
        info = SessionInfo(int(sm.group(1)), set(), {}, {}, [])
        sdir = os.path.join(root, sname)
        try:
            rnames = sorted(os.listdir(sdir))
        except OSError:
            continue  # session dir unlinked between the two listings
        for rname in rnames:
            rm = _RANK_RE.match(rname)
            if not rm:
                continue
            holder = int(rm.group(1))
            info.ranks.add(holder)
            rdir = os.path.join(sdir, rname)
            try:
                fnames = os.listdir(rdir)
            except OSError:
                continue
            for fname in fnames:
                if fname.endswith(".dirty"):
                    info.dirty.append(os.path.join(rdir, fname))
                    continue
                if _CORRUPT_RE.match(fname):
                    info.quarantined.append(os.path.join(rdir, fname))
                    continue
                bm = _BLOCK_RE.match(fname)
                if bm:
                    it, owner, index, k, m = (int(g) for g in bm.groups())
                    fpath = os.path.join(rdir, fname)
                    try:
                        size = os.path.getsize(fpath)
                    except OSError:
                        continue
                    info.blocks.setdefault(it, {}).setdefault(
                        owner, {}
                    ).setdefault(index, set()).add(holder)
                    info.block_k[(it, owner)] = k
                    info.bytes_by_iter[it] = info.bytes_by_iter.get(it, 0) + size
                    info.block_files.append((fpath, holder, it, owner, index))
                    continue
                fm = _FILE_RE.match(fname)
                if not fm:
                    continue
                fpath = os.path.join(rdir, fname)
                try:
                    size = os.path.getsize(fpath)
                except OSError:
                    continue  # pruned mid-scan
                it, owner = int(fm.group(1)), int(fm.group(2))
                info.holdings.setdefault(it, {}).setdefault(owner, set()).add(holder)
                info.bytes_by_iter[it] = info.bytes_by_iter.get(it, 0) + size
                info.files.append((fpath, holder, it, owner))
        sessions.append(info)
    return sorted(sessions, key=lambda s: s.session)


def render(info: SessionInfo, out=None, world: Optional[set] = None) -> None:
    out = sys.stdout if out is None else out
    audit_world = sorted((info.ranks | info.owners) if world is None else world)
    covered = info.covered_iterations(set(audit_world))
    cold_note = (
        f", {len(info.cold)} in cold tier" if info.cold else ""
    )
    print(
        f"session {info.session}: auditing world={audit_world} "
        f"({len(info.holdings)} iterations on disk{cold_note})",
        file=out,
    )
    for it in sorted(set(info.holdings) | set(info.blocks) | set(info.cold)):
        by_owner = info.holdings.get(it, {})
        recon = info.reconstructible(it)
        cold_owners = set(info.cold.get(it, ()))
        missing = sorted(set(audit_world) - set(by_owner) - recon - cold_owners)
        copies = sum(len(h) for h in by_owner.values())
        mb = info.bytes_by_iter.get(it, 0) / 1e6
        status = "COVERED" if it in covered else f"missing owners {missing}"
        mirrors = copies - len(by_owner)
        nblocks = sum(
            len(holders)
            for by_index in info.blocks.get(it, {}).values()
            for holders in by_index.values()
        )
        ec = (
            f", {nblocks} erasure blocks"
            f" (reconstructible: {sorted(recon)})" if nblocks else ""
        )
        cd = f", cold: {sorted(cold_owners)}" if cold_owners else ""
        print(
            f"  iter {it:7d}: owners {sorted(by_owner)}, "
            f"{mirrors} mirror copies{ec}{cd}, {mb:.1f} MB  [{status}]",
            file=out,
        )
    if covered:
        print(
            f"  resumable from: iter {covered[-1]} (newest covered for "
            f"world {audit_world})",
            file=out,
        )
    else:
        print(
            f"  resumable from: NOTHING for world {audit_world}", file=out
        )
    if info.holdings:
        # Coverage is group-relative: after an elastic shrink, the surviving
        # group resumes from data the full world cannot. Name the group the
        # newest iteration WOULD serve, so a "NOTHING" verdict isn't misread.
        newest = max(info.holdings)
        owners = sorted(info.holdings[newest])
        if newest not in covered:
            print(
                f"  note: iter {newest} covers a (shrunk) world of {owners} — "
                f"re-audit with --world {','.join(map(str, owners))}",
                file=out,
            )
    for path in info.dirty:
        print(f"  WARNING torn save temp: {path}", file=out)
    for path in info.quarantined:
        print(f"  WARNING quarantined corrupt container: {path}", file=out)


def verify(sessions: list[SessionInfo], out=None, cold=None) -> int:
    """Stream-verify every container (and erasure block artifact) in
    ``sessions`` (bounded memory, one line per file); returns the number of
    corrupt files. v3 container verdicts are chunk-granular: a corrupt file
    names the exact ``leaf/chunk`` that failed, an intact one reports its
    manifest geometry. With ``cold`` (``{session: ColdTier}``, the ``--cold``
    wiring) every archived artifact is additionally checked against its cold
    manifest's whole-file digest."""
    from tpu_resiliency.checkpoint import format as ckpt_format
    from tpu_resiliency.checkpoint.coding import strategy as ckpt_coding
    from tpu_resiliency.exceptions import CheckpointError

    out = sys.stdout if out is None else out
    counts = {"ok": 0, "unverified": 0, "corrupt": 0}
    for info in sessions:
        print(
            f"session {info.session}: verifying {len(info.files)} "
            f"container(s), {len(info.block_files)} erasure block(s)",
            file=out,
        )
        for path, holder, it, owner in sorted(info.files):
            status, detail = ckpt_format.verify_file(path)
            counts[status] += 1
            print(f"  [{status.upper():10s}] {path}: {detail}", file=out)
        for path, holder, it, owner, index in sorted(info.block_files):
            try:
                with open(path, "rb") as f:
                    header, block = ckpt_coding.parse_block(f.read(), source=path)
                status, detail = "ok", (
                    f"block {header['index']} of k={header['k']} m={header['m']} "
                    f"(owner {header['owner']}, {block.nbytes} bytes)"
                )
            except (CheckpointError, OSError) as e:
                status, detail = "corrupt", str(e)
            counts[status] += 1
            print(f"  [{status.upper():10s}] {path}: {detail}", file=out)
        tier = (cold or {}).get(info.session)
        if tier is not None:
            mans = tier.manifests()
            narts = sum(len(per) for per in mans.values())
            print(
                f"session {info.session}: verifying {narts} cold "
                f"artifact(s)",
                file=out,
            )
            for it in sorted(mans):
                for owner in sorted(mans[it]):
                    status, detail = tier.verify(it, owner)
                    counts[status] += 1
                    print(
                        f"  [{status.upper():10s}] cold "
                        f"s{info.session}/iter {it} owner {owner}: {detail}",
                        file=out,
                    )
    print(
        f"verified: {counts['ok']} ok, {counts['unverified']} unverified, "
        f"{counts['corrupt']} corrupt",
        file=out,
    )
    return counts["corrupt"]


def render_chunks(sessions: list[SessionInfo], out=None) -> int:
    """The ``--chunks`` view: per container, the chunk manifest geometry and
    every failing chunk's (leaf, chunk) coordinates — what an operator reads
    before deciding whether a damaged shard is worth a ranged repair. Exit 1
    on any bad chunk or manifest-less corrupt file."""
    from tpu_resiliency.checkpoint import format as ckpt_format

    out = sys.stdout if out is None else out
    bad_files = 0
    for info in sessions:
        print(
            f"session {info.session}: chunk manifests for {len(info.files)} "
            f"container(s)",
            file=out,
        )
        for path, holder, it, owner in sorted(info.files):
            rep = ckpt_format.chunk_report(path)
            if rep["chunk_size"] is None:
                tag = "NO-MANIFEST"
                if rep["status"] == "corrupt":
                    bad_files += 1
                    tag = "CORRUPT"
                print(
                    f"  [{tag}] {path}: {rep['detail']} "
                    f"(pre-chunk container — whole-file verdict only)",
                    file=out,
                )
                continue
            nchunks = sum(leaf["chunks"] for leaf in rep["leaves"])
            bad = [
                (li, c)
                for li, leaf in enumerate(rep["leaves"])
                for c in leaf["bad"]
            ]
            if bad:
                bad_files += 1
                print(
                    f"  [CORRUPT] {path}: {len(bad)}/{nchunks} chunk(s) bad "
                    f"@ {rep['chunk_size']} B: "
                    + ", ".join(f"leaf {li} chunk {c}" for li, c in bad[:8])
                    + (" ..." if len(bad) > 8 else ""),
                    file=out,
                )
            else:
                print(
                    f"  [OK] {path}: {nchunks} chunk(s) @ "
                    f"{rep['chunk_size']} B across {len(rep['leaves'])} "
                    f"leaves, all verified",
                    file=out,
                )
    return 1 if bad_files else 0


def render_plan(
    info: SessionInfo,
    world: set,
    axes: Optional[dict] = None,
    iteration: Optional[int] = None,
    out=None,
) -> int:
    """Compute and render the reshard plan for ``world`` against the newest
    layout-bearing iteration (or ``iteration``); returns the exit code (1 on
    uncovered ranges or no plannable iteration). Header reads only — no
    tensor bytes are touched."""
    from tpu_resiliency.checkpoint import format as ckpt_format
    from tpu_resiliency.checkpoint import reshard
    from tpu_resiliency.exceptions import CheckpointError

    out = sys.stdout if out is None else out
    target_ranks = sorted(world)
    candidates = sorted(info.holdings, reverse=True)
    if iteration is not None:
        candidates = [it for it in candidates if it == iteration]
    for it in candidates:
        # Any container of the iteration carries the full layout; take the first
        # readable one.
        source = None
        for path, holder, fit, owner in sorted(info.files):
            if fit != it:
                continue
            try:
                meta = ckpt_format.read_header(path).get("meta", {})
                source = reshard.extract_layout(meta)
            except CheckpointError:
                continue
            if source is not None:
                break
        if source is None:
            print(f"iter {it}: no readable layout-bearing container", file=out)
            continue
        try:
            target = source.retarget(target_ranks, axes=axes)
            plan = reshard.build_plan(source, target)
        except CheckpointError as e:
            print(f"iter {it}: cannot plan — {e}", file=out)
            return 1
        available = set(info.holdings[it])
        local_owners = {
            r: {
                o
                for o, holders in info.holdings[it].items()
                if r in holders
            }
            for r in target_ranks
        }
        print(
            f"session {info.session} iter {it}: reshard plan "
            f"{plan.source.world_size} -> {plan.target.world_size} ranks "
            f"({plan.direction}), source axes {dict(plan.source.axes)} -> "
            f"target axes {dict(plan.target.axes)}",
            file=out,
        )
        for r in target_ranks:
            rp = plan.for_rank(r)
            held = local_owners.get(r, set())
            print(
                f"  target rank {r}: {len(rp.segments)} cell(s), "
                f"{rp.nbytes} bytes",
                file=out,
            )
            for seg in rp.segments:
                via = (
                    "local" if set(seg.owners) & held
                    else ("peer-fetch" if set(seg.owners) & available
                          else "UNCOVERED")
                )
                spans = ", ".join(
                    f"[{rg.src_off}+{rg.nbytes})->[{rg.dst_off})"
                    for rg in seg.ranges[:4]
                )
                if len(seg.ranges) > 4:
                    spans += f", ... {len(seg.ranges) - 4} more"
                print(
                    f"    leaf {seg.leaf}: owners {list(seg.owners)} "
                    f"{seg.nbytes} B via {via}  {spans}",
                    file=out,
                )
        summary = plan.summary(local_owners=local_owners)
        print(
            f"  split: {summary['local_bytes']} B local, "
            f"{summary['peer_bytes']} B peer-fetched, "
            f"{summary['ranges']} range(s)",
            file=out,
        )
        missing = plan.missing_sources(available)
        if missing:
            names = sorted({r for rs in missing.values() for r in rs})
            print(
                f"  UNCOVERED: no surviving copy of source rank(s) {names} "
                f"(leaves {sorted(missing)})",
                file=out,
            )
            return 1
        print(f"  coverage: OK for world {target_ranks}", file=out)
        return 0
    print(
        "no plannable iteration (no containers carry reshard layout meta — "
        "save with save(..., layout=...))",
        file=out,
    )
    return 1


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Audit a tpu-resiliency local-checkpoint root offline"
    )
    def world_spec(text: str) -> set:
        try:
            out = {int(r) for r in text.split(",") if r.strip()}
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"want comma-separated rank ids, got {text!r}"
            )
        if not out:
            raise argparse.ArgumentTypeError("empty world")
        return out

    ap.add_argument("root")
    ap.add_argument("--session", type=int, help="only this session id")
    ap.add_argument(
        "--world",
        type=world_spec,
        help="audit coverage for this comma-separated rank set (default: every "
        "rank/owner the filesystem shows — the original full world)",
    )
    ap.add_argument(
        "--verify",
        action="store_true",
        help="stream-verify every container's checksums (per-leaf CRCs, v3 "
        "chunk manifests, trailer digest) and every erasure block artifact; "
        "print per-file verdicts; exit 1 on any mismatch",
    )
    ap.add_argument(
        "--cold",
        metavar="DIR",
        help="also scan this cold-tier object-store root (the launcher's "
        "--cold-dir): archived owners join the per-iteration coverage "
        "ledger as a third rung, cold-only sessions become auditable from "
        "an empty workdir, and --verify re-checks every archived artifact "
        "against its cold manifest digest",
    )
    ap.add_argument(
        "--chunks",
        action="store_true",
        help="render per-container chunk-manifest verdicts (chunk size, "
        "chunk count, exact (leaf, chunk) coordinates of any corruption); "
        "exit 1 on any bad chunk",
    )

    def axes_spec(text: str) -> dict:
        out = {}
        try:
            for part in text.split(","):
                if not part.strip():
                    continue
                name, size = part.split("=")
                out[name.strip()] = int(size)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"want name=size[,name=size...], got {text!r}"
            )
        if not out:
            raise argparse.ArgumentTypeError("empty axes spec")
        return out

    ap.add_argument(
        "--plan",
        action="store_true",
        help="render the elastic reshard plan for the --world target ranks "
        "(per-target-rank source cells, byte ranges, local vs peer-fetch "
        "split) without loading tensors; exit 1 if any range is uncovered",
    )
    ap.add_argument(
        "--axes",
        type=axes_spec,
        default=None,
        help="target mesh split for --plan, e.g. dp=2,tp=2 (default: the "
        "source layout with dp rescaled to the --world size)",
    )
    ap.add_argument(
        "--iteration", type=int, default=None,
        help="plan against this iteration (default: newest layout-bearing)",
    )
    args = ap.parse_args(argv)
    world = args.world
    if args.plan and world is None:
        print("--plan requires --world (the target rank set)", file=sys.stderr)
        return 2
    if not os.path.isdir(args.root):
        print(f"not a checkpoint root: {args.root}", file=sys.stderr)
        return 1
    sessions = scan(args.root, session=args.session)
    cold_tiers = {}
    if args.cold:
        if not os.path.isdir(args.cold):
            print(f"not a cold-tier root: {args.cold}", file=sys.stderr)
            return 1
        from tpu_resiliency.checkpoint.coldtier import (
            ColdTier,
            FilesystemStore,
        )

        store = FilesystemStore(args.cold)
        cold_ids = set()
        for key in store.list():
            km = re.match(r"^s(\d+)/", key)
            if km:
                cold_ids.add(int(km.group(1)))
        for sid in sorted(cold_ids):
            if args.session is not None and sid != args.session:
                continue
            tier = ColdTier(store, session=sid)
            coverage = tier.coverage()
            if not coverage:
                continue  # keys but no valid manifest: nothing trustworthy
            cold_tiers[sid] = tier
            for info in sessions:
                if info.session == sid:
                    info.cold = coverage
                    break
            else:
                # Cold-only session — the restore-anywhere case: an empty
                # (or freshly provisioned) workdir still audits what a new
                # job could bootstrap from the object store.
                stub = SessionInfo(sid, set(), {}, {}, [])
                stub.cold = coverage
                sessions.append(stub)
        sessions.sort(key=lambda s: s.session)
    if not sessions:
        print("no sessions found", file=sys.stderr)
        return 1
    if args.plan:
        rc = [0]

        def emit_plan():
            # One session per plan render (pass --session to disambiguate).
            rc[0] = max(
                render_plan(
                    info, world, axes=args.axes, iteration=args.iteration
                )
                for info in sessions
            )

        if pipe_safe(emit_plan):
            return SIGPIPE_EXIT
        return rc[0]
    if args.verify:
        corrupt = [0]

        def emit_verify():
            corrupt[0] = verify(sessions, cold=cold_tiers)

        if pipe_safe(emit_verify):
            return SIGPIPE_EXIT
        return 1 if corrupt[0] else 0
    if args.chunks:
        rc_c = [0]

        def emit_chunks():
            rc_c[0] = render_chunks(sessions)

        if pipe_safe(emit_chunks):
            return SIGPIPE_EXIT
        return rc_c[0]

    def emit():
        for info in sessions:
            render(info, world=world)

    if pipe_safe(emit):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
