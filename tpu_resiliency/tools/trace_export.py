"""Events JSONL → Chrome trace-event JSON, viewable in Perfetto.

The structured event stream (``utils/events.py`` + ``utils/tracing.py``) already
carries everything a causal view needs — span begin/end pairs with ids, process
identity, wall-clock timestamps. This tool is the renderer: it converts one
run's JSONL file into the Chrome trace-event format that ``ui.perfetto.dev``
(or ``chrome://tracing``) loads directly, so "what actually happened during
that restart" becomes a picture — the launcher's round span, the rendezvous
wait inside it, each worker's iteration/barrier spans beneath, and every plain
event as an instant marker on the row it belongs to.

Mapping:

- matched ``span_begin``/``span_end`` (same envelope ``span_id``) → one
  complete ``"X"`` slice with the begin payload + duration as args, plus a
  computed ``self_time_ms`` (duration minus the union of child-span overlap:
  the part only that span's own code explains);
- unmatched ``span_begin`` (process died mid-span — exactly the interesting
  case) → an ``"X"`` slice running to the last event's timestamp, flagged
  ``unfinished``;
- every other record → an instant ``"i"`` marker;
- per-pid ``"M"`` metadata rows naming each process by its dominant source;
- spans named in ``critical_ids`` (``tpu-critpath``'s dominant chain) get a
  distinct ``cname`` + ``critical_path: true`` arg, so Perfetto shows the
  chain that gated the episode without manual inspection.

Usage::

    python -m tpu_resiliency.tools.trace_export run_events.jsonl -o run.trace.json
    python -m tpu_resiliency.tools.trace_export run_events.jsonl   # stdout
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from typing import Optional

from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe
from tpu_resiliency.utils.events import RESERVED_KEYS, read_events


def _payload(rec: dict) -> dict:
    return {k: v for k, v in rec.items() if k not in RESERVED_KEYS}


def _tid(rec: dict) -> int:
    # One row per rank inside a process; rank-less records (launcher, monitors)
    # share row 0 of their pid.
    rank = rec.get("rank")
    return rank if isinstance(rank, int) else 0


def _self_times(spans: list[dict]) -> dict[tuple, float]:
    """``(pid, span_id) -> self seconds``: each span's duration minus the
    union of its children's overlap (children = spans whose begin carried
    this span's id as ``parent_id`` — cross-process children count, the
    parenting is env-propagated). The number an optimizer actually needs:
    where must a fix land to move this span."""
    from tpu_resiliency.utils.goodput import (
        merge_intervals,
        subtract_intervals,
        total_seconds,
    )

    by_parent: dict[str, list[tuple[float, float]]] = {}
    for s in spans:
        parent = s.get("parent_id")
        if parent:
            by_parent.setdefault(parent, []).append((s["t0"], s["t1"]))
    out: dict[tuple, float] = {}
    for s in spans:
        children = [
            (max(c0, s["t0"]), min(c1, s["t1"]))
            for c0, c1 in by_parent.get(s.get("span_id") or "", [])
            if c1 > s["t0"] and c0 < s["t1"]
        ]
        if children:
            own = subtract_intervals(
                merge_intervals([(s["t0"], s["t1"])]), merge_intervals(children)
            )
            out[(s["pid"], s["span_id"])] = total_seconds(own)
        else:
            out[(s["pid"], s["span_id"])] = max(0.0, s["t1"] - s["t0"])
    return out


def to_chrome_trace(records: list[dict], critical_ids=None) -> dict:
    """Convert parsed event records to a Chrome trace-event document.

    ``critical_ids``: span ids on a ``tpu-critpath`` dominant chain — those
    slices get a distinct color and a ``critical_path`` arg."""
    critical_ids = critical_ids or set()
    records = [
        r for r in records
        if isinstance(r.get("ts"), (int, float)) and isinstance(r.get("kind"), str)
    ]
    records.sort(key=lambda r: r["ts"])
    if not records:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = records[0]["ts"]
    t_last = records[-1]["ts"]

    def us(ts: float) -> float:
        return (ts - t0) * 1e6

    events: list[dict] = []
    #: collected span slices, completed in a second pass so self-time can see
    #: every child before any slice is rendered
    spans: list[dict] = []
    #: (pid, span_id) -> begin record; span ids are unique per span but scoping
    #: by pid keeps a forked child that inherited its parent's stack harmless.
    open_spans: dict[tuple, dict] = {}
    source_by_pid: Counter = Counter()

    for rec in records:
        pid = rec.get("pid", 0)
        source_by_pid[(pid, rec.get("source", "?"))] += 1
        kind = rec["kind"]
        p = _payload(rec)
        if kind == "span_begin" and rec.get("span_id"):
            open_spans[(pid, rec["span_id"])] = rec
            continue
        if kind == "span_end" and rec.get("span_id"):
            begin = open_spans.pop((pid, rec["span_id"]), None)
            if begin is None:
                # End without begin (stream truncated at the head): degrade to
                # an instant so the error/duration survives in the view.
                events.append({
                    "name": str(p.get("span", "span")), "cat": rec.get("source", "?"),
                    "ph": "i", "s": "t", "ts": us(rec["ts"]),
                    "pid": pid, "tid": _tid(rec), "args": p,
                })
                continue
            bp = _payload(begin)
            spans.append({
                "name": str(bp.get("span", "span")),
                "cat": begin.get("source", "?"),
                "pid": pid,
                "tid": _tid(begin),
                "span_id": rec["span_id"],
                "parent_id": bp.get("parent_id"),
                "t0": begin["ts"],
                "t1": rec["ts"],
                "finished": True,
                "args": {**bp, **p},
            })
            continue
        # Plain event → instant marker, thread-scoped.
        events.append({
            "name": kind, "cat": rec.get("source", "?"),
            "ph": "i", "s": "t", "ts": us(rec["ts"]),
            "pid": pid, "tid": _tid(rec),
            "args": {k: v for k, v in p.items()},
        })

    # A span the process never closed (it crashed inside — the signal an
    # operator is usually hunting) renders as a slice to end-of-stream:
    # open-ended, never silently dropped, and colored distinctly (cname) so
    # the crashed-mid-span slice jumps out of a busy trace.
    for (pid, sid), begin in open_spans.items():
        bp = _payload(begin)
        spans.append({
            "name": str(bp.get("span", "span")),
            "cat": begin.get("source", "?"),
            "pid": pid,
            "tid": _tid(begin),
            "span_id": sid,
            "parent_id": bp.get("parent_id"),
            "t0": begin["ts"],
            "t1": t_last,
            "finished": False,
            "args": {**bp, "unfinished": True},
        })

    selfs = _self_times(spans)
    for s in spans:
        args = {
            **s["args"], "span_id": s["span_id"],
            "self_time_ms": round(selfs.get((s["pid"], s["span_id"]), 0.0) * 1e3, 3),
        }
        args.pop("span", None)
        slice_ev = {
            "name": s["name"], "cat": s["cat"],
            "ph": "X", "ts": us(s["t0"]),
            "dur": max(0.0, us(s["t1"]) - us(s["t0"])),
            "pid": s["pid"], "tid": s["tid"], "args": args,
        }
        if not s["finished"]:
            slice_ev["cname"] = "terrible"
        if s["span_id"] in critical_ids:
            # Distinct from the unfinished red: the chain that gated the
            # episode reads off the trace without manual inspection.
            args["critical_path"] = True
            if s["finished"]:
                slice_ev["cname"] = "thread_state_runnable"
        events.append(slice_ev)

    # Name each pid row by its dominant event source (launcher/worker/monitor).
    dominant: dict[int, tuple[str, int]] = {}
    for (pid, source), n in source_by_pid.items():
        if pid not in dominant or n > dominant[pid][1]:
            dominant[pid] = (source, n)
    for pid, (source, _) in sorted(dominant.items()):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{source} (pid {pid})"},
        })

    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert a tpu-resiliency events JSONL file to Chrome "
        "trace-event JSON (load in ui.perfetto.dev)"
    )
    ap.add_argument("events_file")
    ap.add_argument(
        "-o", "--output", default=None,
        help="write the trace here (default: stdout)",
    )
    ap.add_argument(
        "--indent", type=int, default=None,
        help="pretty-print with this indent (default: compact)",
    )
    args = ap.parse_args(argv)
    # read_events tolerates unreadable files (shared-stream semantics); a CLI
    # invocation on a missing/denied path must fail visibly instead.
    try:
        with open(args.events_file):
            pass
    except OSError as e:
        print(f"cannot read events file: {e}", file=sys.stderr)
        return 1
    trace = to_chrome_trace(read_events(args.events_file))
    if not trace["traceEvents"]:
        print("no events to export", file=sys.stderr)
        return 1
    doc = json.dumps(trace, indent=args.indent, default=repr)
    if args.output:
        with open(args.output, "w") as f:
            f.write(doc + "\n")
        n_spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
        n_open = sum(
            1 for e in trace["traceEvents"]
            if e.get("ph") == "X" and e.get("args", {}).get("unfinished")
        )
        open_note = (
            f", {n_open} UNFINISHED (a process died mid-span)" if n_open else ""
        )
        print(
            f"wrote {args.output}: {len(trace['traceEvents'])} trace events "
            f"({n_spans} spans{open_note}) — load in ui.perfetto.dev"
        )
        return 0
    if pipe_safe(lambda: print(doc)):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
