"""Events JSONL → Chrome trace-event JSON, viewable in Perfetto.

The structured event stream (``utils/events.py`` + ``utils/tracing.py``) already
carries everything a causal view needs — span begin/end pairs with ids, process
identity, wall-clock timestamps. This tool is the renderer: it converts one
run's JSONL file into the Chrome trace-event format that ``ui.perfetto.dev``
(or ``chrome://tracing``) loads directly, so "what actually happened during
that restart" becomes a picture — the launcher's round span, the rendezvous
wait inside it, each worker's iteration/barrier spans beneath, and every plain
event as an instant marker on the row it belongs to.

Mapping:

- matched ``span_begin``/``span_end`` (same envelope ``span_id``) → one
  complete ``"X"`` slice with the begin payload + duration as args;
- unmatched ``span_begin`` (process died mid-span — exactly the interesting
  case) → an ``"X"`` slice running to the last event's timestamp, flagged
  ``unfinished``;
- every other record → an instant ``"i"`` marker;
- per-pid ``"M"`` metadata rows naming each process by its dominant source.

Usage::

    python -m tpu_resiliency.tools.trace_export run_events.jsonl -o run.trace.json
    python -m tpu_resiliency.tools.trace_export run_events.jsonl   # stdout
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from typing import Optional

from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe
from tpu_resiliency.utils.events import RESERVED_KEYS, read_events


def _payload(rec: dict) -> dict:
    return {k: v for k, v in rec.items() if k not in RESERVED_KEYS}


def _tid(rec: dict) -> int:
    # One row per rank inside a process; rank-less records (launcher, monitors)
    # share row 0 of their pid.
    rank = rec.get("rank")
    return rank if isinstance(rank, int) else 0


def to_chrome_trace(records: list[dict]) -> dict:
    """Convert parsed event records to a Chrome trace-event document."""
    records = [
        r for r in records
        if isinstance(r.get("ts"), (int, float)) and isinstance(r.get("kind"), str)
    ]
    records.sort(key=lambda r: r["ts"])
    if not records:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = records[0]["ts"]
    t_last = records[-1]["ts"]

    def us(ts: float) -> float:
        return (ts - t0) * 1e6

    events: list[dict] = []
    #: (pid, span_id) -> begin record; span ids are unique per span but scoping
    #: by pid keeps a forked child that inherited its parent's stack harmless.
    open_spans: dict[tuple, dict] = {}
    source_by_pid: Counter = Counter()

    for rec in records:
        pid = rec.get("pid", 0)
        source_by_pid[(pid, rec.get("source", "?"))] += 1
        kind = rec["kind"]
        p = _payload(rec)
        if kind == "span_begin" and rec.get("span_id"):
            open_spans[(pid, rec["span_id"])] = rec
            continue
        if kind == "span_end" and rec.get("span_id"):
            begin = open_spans.pop((pid, rec["span_id"]), None)
            if begin is None:
                # End without begin (stream truncated at the head): degrade to
                # an instant so the error/duration survives in the view.
                events.append({
                    "name": str(p.get("span", "span")), "cat": rec.get("source", "?"),
                    "ph": "i", "s": "t", "ts": us(rec["ts"]),
                    "pid": pid, "tid": _tid(rec), "args": p,
                })
                continue
            bp = _payload(begin)
            args = {**bp, **p, "span_id": rec["span_id"]}
            args.pop("span", None)
            events.append({
                "name": str(bp.get("span", "span")),
                "cat": begin.get("source", "?"),
                "ph": "X",
                "ts": us(begin["ts"]),
                "dur": max(0.0, us(rec["ts"]) - us(begin["ts"])),
                "pid": pid,
                "tid": _tid(begin),
                "args": args,
            })
            continue
        # Plain event → instant marker, thread-scoped.
        events.append({
            "name": kind, "cat": rec.get("source", "?"),
            "ph": "i", "s": "t", "ts": us(rec["ts"]),
            "pid": pid, "tid": _tid(rec),
            "args": {k: v for k, v in p.items()},
        })

    # A span the process never closed (it crashed inside — the signal an
    # operator is usually hunting) renders as a slice to end-of-stream:
    # open-ended, never silently dropped, and colored distinctly (cname) so
    # the crashed-mid-span slice jumps out of a busy trace.
    for (pid, sid), begin in open_spans.items():
        bp = _payload(begin)
        args = {**bp, "span_id": sid, "unfinished": True}
        args.pop("span", None)
        events.append({
            "name": str(bp.get("span", "span")), "cat": begin.get("source", "?"),
            "ph": "X", "ts": us(begin["ts"]),
            "dur": max(0.0, us(t_last) - us(begin["ts"])),
            "pid": pid, "tid": _tid(begin), "args": args,
            "cname": "terrible",
        })

    # Name each pid row by its dominant event source (launcher/worker/monitor).
    dominant: dict[int, tuple[str, int]] = {}
    for (pid, source), n in source_by_pid.items():
        if pid not in dominant or n > dominant[pid][1]:
            dominant[pid] = (source, n)
    for pid, (source, _) in sorted(dominant.items()):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{source} (pid {pid})"},
        })

    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert a tpu-resiliency events JSONL file to Chrome "
        "trace-event JSON (load in ui.perfetto.dev)"
    )
    ap.add_argument("events_file")
    ap.add_argument(
        "-o", "--output", default=None,
        help="write the trace here (default: stdout)",
    )
    ap.add_argument(
        "--indent", type=int, default=None,
        help="pretty-print with this indent (default: compact)",
    )
    args = ap.parse_args(argv)
    # read_events tolerates unreadable files (shared-stream semantics); a CLI
    # invocation on a missing/denied path must fail visibly instead.
    try:
        with open(args.events_file):
            pass
    except OSError as e:
        print(f"cannot read events file: {e}", file=sys.stderr)
        return 1
    trace = to_chrome_trace(read_events(args.events_file))
    if not trace["traceEvents"]:
        print("no events to export", file=sys.stderr)
        return 1
    doc = json.dumps(trace, indent=args.indent, default=repr)
    if args.output:
        with open(args.output, "w") as f:
            f.write(doc + "\n")
        n_spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
        n_open = sum(
            1 for e in trace["traceEvents"]
            if e.get("ph") == "X" and e.get("args", {}).get("unfinished")
        )
        open_note = (
            f", {n_open} UNFINISHED (a process died mid-span)" if n_open else ""
        )
        print(
            f"wrote {args.output}: {len(trace['traceEvents'])} trace events "
            f"({n_spans} spans{open_note}) — load in ui.perfetto.dev"
        )
        return 0
    if pipe_safe(lambda: print(doc)):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
