"""Live introspection of a coordination store: what is the job waiting on?

Connects to a running KV server (the launcher-hosted store, or a standalone
one) and reports the operator-relevant state without disturbing the job:
round-trip health, key census by top-level prefix, live barrier states
(who arrived, who is absent — the "why is my rendezvous stuck" question),
and a staleness scan over heartbeat keys. Everything rides existing store
ops plus two introspection-only ones (``keys``, ``barriers``) that never
move values — a census of a 4096-rank job's store costs key *names*, not
megabytes of payloads. Auth: ``$TPU_RESILIENCY_STORE_KEY``, same as every
other client.

The reference's TCPStore offers no introspection at all — debugging its
rendezvous means reading launcher logs.

Usage::

    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511
    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511 --prefix launcher-jobs/
    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511 --stale hb/ --max-age 10
    # live blocked-collective census: arrived/missing/absent + waiter ages
    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511 --barriers
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from collections import Counter
from typing import Optional

from tpu_resiliency.exceptions import StoreError
from tpu_resiliency.platform.store import AUTH_KEY_ENV, KVClient
from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe


def report(client: KVClient, prefix: str, stale_prefix: Optional[str],
           max_age: float, out=None) -> None:
    out = sys.stdout if out is None else out
    t0 = time.perf_counter()
    alive = client.ping()
    rtt_ms = (time.perf_counter() - t0) * 1e3
    print(f"ping: {'ok' if alive else 'FAILED'} ({rtt_ms:.1f} ms)", file=out)
    total = client.num_keys()
    names = client.keys(prefix)
    scope = f"under {prefix!r}" if prefix else "total"
    print(f"keys: {len(names)} {scope} ({total} in store)", file=out)
    census = Counter(
        k[len(prefix):].split("/", 1)[0] if "/" in k[len(prefix):] else "(flat)"
        for k in names
    )
    for part, n in census.most_common(20):
        print(f"  {part}/: {n}", file=out)
    barriers = client.barrier_names()
    print(f"barriers: {len(barriers)} live", file=out)
    for name in barriers[:20]:
        st = client.barrier_status(name)
        if st is None:
            continue
        arrived = sorted(st["arrived"])
        waiting_on = st["world_size"] - len(arrived) - len(st["absent"])
        detail = f"gen {st['generation']}, arrived {arrived}"
        if st["absent"]:
            detail += f", absent {sorted(st['absent'])}"
        print(
            f"  {name}: {len(arrived)}/{st['world_size']} "
            f"({'COMPLETE' if waiting_on <= 0 else f'waiting on {waiting_on}'}; "
            f"{detail})",
            file=out,
        )
    if stale_prefix is not None:
        stale = client.stale_keys(stale_prefix, max_age)
        if stale:
            print(
                f"stale under {stale_prefix!r} (>{max_age:.0f}s):", file=out
            )
            for k, age in sorted(stale.items(), key=lambda kv: -kv[1]):
                print(f"  {k}: {age:.1f}s", file=out)
        else:
            print(
                f"stale under {stale_prefix!r} (>{max_age:.0f}s): none", file=out
            )


def report_barriers(client: KVClient, prefix: str, out=None) -> None:
    """The live barrier census (``barrier_census`` op): every in-progress
    round's arrived ranks with waiter ages, the missing ranks the round is
    blocked on, and proxied-absent ranks — the "who never arrived" view an
    operator needs while the job is still wedged."""
    out = sys.stdout if out is None else out
    census = client.barrier_census(prefix)
    scope = f" under {prefix!r}" if prefix else ""
    print(f"open barrier rounds{scope}: {len(census)}", file=out)
    for name in sorted(census):
        b = census[name]
        arrived = b.get("arrived") or {}
        waiters = ", ".join(
            f"r{k} waiting {v:.1f}s" if isinstance(v, (int, float)) else f"r{k}"
            for k, v in sorted(arrived.items(), key=lambda kv: str(kv[0]))
        )
        print(
            f"  {name}: gen {b.get('generation')}, "
            f"{len(arrived)}/{b.get('world_size')} arrived "
            f"(open {b.get('open_age_s', 0):.1f}s)",
            file=out,
        )
        if waiters:
            print(f"    arrived: {waiters}", file=out)
        if b.get("missing"):
            print(f"    MISSING: {b['missing']} (the ranks everyone is "
                  f"blocked on)", file=out)
        if b.get("absent"):
            print(f"    absent (proxied dead): {b['absent']}", file=out)


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Introspect a live tpu-resiliency coordination store"
    )
    ap.add_argument("endpoint", help="HOST:PORT of the KV server")
    ap.add_argument("--prefix", default="", help="census keys under this prefix")
    ap.add_argument(
        "--stale", metavar="PREFIX",
        help="also scan touch-stamps under PREFIX for staleness",
    )
    ap.add_argument("--max-age", type=float, default=10.0)
    ap.add_argument(
        "--barriers", action="store_true",
        help="render only the live barrier census: per wait key, who arrived "
        "(with waiter ages), who is missing, who was proxied absent",
    )
    args = ap.parse_args(argv)
    host, _, port_s = args.endpoint.partition(":")
    try:
        port = int(port_s)
    except ValueError:
        ap.error(f"want HOST:PORT, got {args.endpoint!r}")
    try:
        # Fail fast on a dead endpoint: a diagnostics tool must not sit in
        # the client's default 60-attempt reconnect ladder.
        client = KVClient(
            host or "127.0.0.1",
            port,
            connect_retries=3,
            auth_key=os.environ.get(AUTH_KEY_ENV) or None,
        )
    except StoreError as e:
        print(str(e), file=sys.stderr)
        return 1
    try:
        body = (
            (lambda: report_barriers(client, args.prefix)) if args.barriers
            else (lambda: report(client, args.prefix, args.stale, args.max_age))
        )
        if pipe_safe(body):
            return SIGPIPE_EXIT
    except (OSError, StoreError) as e:
        print(f"store at {args.endpoint} failed mid-report: {e}", file=sys.stderr)
        return 1
    finally:
        try:
            client.close()
        except Exception:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
