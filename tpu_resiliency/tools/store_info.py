"""Live introspection of a coordination store: what is the job waiting on?

Connects to a running KV server (the launcher-hosted store, or a standalone
one) and reports the operator-relevant state without disturbing the job:
round-trip health, key census by top-level prefix, live barrier states
(who arrived, who is absent — the "why is my rendezvous stuck" question),
and a staleness scan over heartbeat keys. Everything rides existing store
ops plus two introspection-only ones (``keys``, ``barriers``) that never
move values — a census of a 4096-rank job's store costs key *names*, not
megabytes of payloads. Auth: ``$TPU_RESILIENCY_STORE_KEY``, same as every
other client.

The reference's TCPStore offers no introspection at all — debugging its
rendezvous means reading launcher logs.

Usage::

    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511
    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511 --prefix launcher-jobs/
    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511 --stale hb/ --max-age 10
    # live blocked-collective census: arrived/missing/absent + waiter ages
    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511 --barriers
    # live op telemetry: serving backend, per-op latency, hot prefixes,
    # park depth, dedup rate; against a clique: shard map + per-shard totals
    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511 --stats
    # explicit shard list (or let a single endpoint auto-expand from the
    # clique's published store-clique/endpoints key)
    python -m tpu_resiliency.tools.store_info 127.0.0.1:29511,127.0.0.1:29512 --stats
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from collections import Counter
from typing import Optional

from tpu_resiliency.exceptions import StoreError
from tpu_resiliency.platform.store import AUTH_KEY_ENV, KVClient
from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe


def report(client: KVClient, prefix: str, stale_prefix: Optional[str],
           max_age: float, out=None) -> None:
    out = sys.stdout if out is None else out
    t0 = time.perf_counter()
    alive = client.ping()
    rtt_ms = (time.perf_counter() - t0) * 1e3
    print(f"ping: {'ok' if alive else 'FAILED'} ({rtt_ms:.1f} ms)", file=out)
    total = client.num_keys()
    names = client.keys(prefix)
    scope = f"under {prefix!r}" if prefix else "total"
    print(f"keys: {len(names)} {scope} ({total} in store)", file=out)
    census = Counter(
        k[len(prefix):].split("/", 1)[0] if "/" in k[len(prefix):] else "(flat)"
        for k in names
    )
    for part, n in census.most_common(20):
        print(f"  {part}/: {n}", file=out)
    barriers = client.barrier_names()
    print(f"barriers: {len(barriers)} live", file=out)
    for name in barriers[:20]:
        st = client.barrier_status(name)
        if st is None:
            continue
        arrived = sorted(st["arrived"])
        waiting_on = st["world_size"] - len(arrived) - len(st["absent"])
        detail = f"gen {st['generation']}, arrived {arrived}"
        if st["absent"]:
            detail += f", absent {sorted(st['absent'])}"
        print(
            f"  {name}: {len(arrived)}/{st['world_size']} "
            f"({'COMPLETE' if waiting_on <= 0 else f'waiting on {waiting_on}'}; "
            f"{detail})",
            file=out,
        )
    if stale_prefix is not None:
        stale = client.stale_keys(stale_prefix, max_age)
        if stale:
            print(
                f"stale under {stale_prefix!r} (>{max_age:.0f}s):", file=out
            )
            for k, age in sorted(stale.items(), key=lambda kv: -kv[1]):
                print(f"  {k}: {age:.1f}s", file=out)
        else:
            print(
                f"stale under {stale_prefix!r} (>{max_age:.0f}s): none", file=out
            )


def report_barriers(client: KVClient, prefix: str, out=None) -> None:
    """The live barrier census (``barrier_census`` op): every in-progress
    round's arrived ranks with waiter ages, the missing ranks the round is
    blocked on, and proxied-absent ranks — the "who never arrived" view an
    operator needs while the job is still wedged."""
    out = sys.stdout if out is None else out
    census = client.barrier_census(prefix)
    scope = f" under {prefix!r}" if prefix else ""
    print(f"open barrier rounds{scope}: {len(census)}", file=out)
    for name in sorted(census):
        b = census[name]
        arrived = b.get("arrived") or {}
        waiters = ", ".join(
            f"r{k} waiting {v:.1f}s" if isinstance(v, (int, float)) else f"r{k}"
            for k, v in sorted(arrived.items(), key=lambda kv: str(kv[0]))
        )
        print(
            f"  {name}: gen {b.get('generation')}, "
            f"{len(arrived)}/{b.get('world_size')} arrived "
            f"(open {b.get('open_age_s', 0):.1f}s)",
            file=out,
        )
        if waiters:
            print(f"    arrived: {waiters}", file=out)
        if b.get("missing"):
            print(f"    MISSING: {b['missing']} (the ranks everyone is "
                  f"blocked on)", file=out)
        if b.get("absent"):
            print(f"    absent (proxied dead): {b['absent']}", file=out)


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GB"


def report_stats(client: KVClient, out=None) -> int:
    """Render the live ``store_stats`` document (``tpu-store-stats-1``): the
    serving backend (``epoll``; a pre-epoll thread-per-connection server has
    no field and renders ``threaded``), the shard map and per-shard op totals
    when the endpoint is a clique (the document is then the AGGREGATE across
    shards, quantiles worst-shard), the per-op latency table (queue wait vs
    handle split), hot key prefixes, connection/park/dedup state. Returns an
    exit code: 1 when the server predates the op (version skew — the error
    is one round trip, never a retry budget) or runs with stats disabled."""
    out = sys.stdout if out is None else out
    try:
        doc = client.store_stats()
    except StoreError as e:
        print(f"store does not answer store_stats (pre-telemetry server?): {e}",
              file=sys.stderr)
        return 1
    if not doc.get("enabled", False):
        detail = doc.get("error", "stats_enabled=False")
        print(f"store stats disabled: {detail}", file=out)
        print(
            f"conns: {doc.get('conns', '?')} live   "
            f"parked: {doc.get('parked', '?')}   "
            f"keys: {doc.get('keys', '?')}",
            file=out,
        )
        return 1
    b = doc.get("bytes") or {}
    dd = doc.get("dedup") or {}
    smap = doc.get("shard_map") or {}
    backend = doc.get("backend", "threaded")
    if smap:
        ha = ""
        if smap.get("replicate"):
            ha = (
                f"   replicated (successor = shard+1 mod n), "
                f"epoch {smap.get('epoch', 0)}"
            )
        print(
            f"backend: {backend}   shards: {smap.get('nshards')} "
            f"({smap.get('hash')} keyspace hash; quantiles are worst-shard)"
            f"{ha}",
            file=out,
        )
    else:
        print(f"backend: {backend}", file=out)
    print(
        f"store stats (up {doc.get('uptime_s', 0):.0f}s): "
        f"conns {doc.get('conns', 0)} live / {doc.get('conns_peak', 0)} peak "
        f"/ {doc.get('conns_total', 0)} total   parked {doc.get('parked', 0)}   "
        f"open barriers {doc.get('barriers_open', 0)}   keys {doc.get('keys', 0)}",
        file=out,
    )
    shards = doc.get("shards") or []
    if shards:
        print("per-shard op totals:", file=out)
        print(
            f"    {'endpoint':<22} {'backend':<10} {'ops':>10} {'err':>6} "
            f"{'bytes in':>10} {'bytes out':>10} {'conns':>6} {'keys':>8}",
            file=out,
        )
        for row in shards:
            print(
                f"    {row.get('endpoint', '?'):<22} "
                f"{row.get('backend', '?'):<10} "
                f"{row.get('ops_total', 0):>10} "
                f"{row.get('errors_total', 0):>6} "
                f"{_fmt_bytes(row.get('bytes_in', 0)):>10} "
                f"{_fmt_bytes(row.get('bytes_out', 0)):>10} "
                f"{row.get('conns', 0):>6} {row.get('keys', 0):>8}",
                file=out,
            )
            # HA annotations from merge_stats_docs: a dead shard names the
            # successor replica absorbing its keyspace; the successor lists
            # who it is covering for and how many ops it absorbed.
            if row.get("absorbed_by"):
                print(
                    f"      UNREACHABLE — keyspace absorbed by successor "
                    f"{row['absorbed_by']}",
                    file=out,
                )
            if row.get("absorbing"):
                covered = ", ".join(str(e) for e in row["absorbing"])
                extra = ""
                if row.get("failover_ops"):
                    extra = f" ({row['failover_ops']} failover ops served)"
                print(f"      absorbing for: {covered}{extra}", file=out)
    fo = doc.get("failover") or {}
    if fo.get("ops"):
        by = fo.get("by_shard") or {}
        detail = ", ".join(
            f"shard {k}: {v}" for k, v in sorted(by.items(), key=lambda kv: str(kv[0]))
        )
        print(f"failover ops absorbed: {fo['ops']} ({detail})", file=out)
    print(
        f"bytes: in {_fmt_bytes(b.get('in', 0))}, out {_fmt_bytes(b.get('out', 0))}"
        f"   dedup: {dd.get('hits', 0)}/{dd.get('lookups', 0)} hits "
        f"({100.0 * dd.get('hit_rate', 0.0):.1f}%)",
        file=out,
    )
    ops = doc.get("ops") or {}
    if ops:
        print("ops (handle = dispatch time; wait = socket -> dispatch):", file=out)
        print(
            f"    {'op':<16} {'count':>9} {'err':>5} {'p50':>9} {'p95':>9} "
            f"{'max':>9} {'wait p95':>9} {'bytes in':>10}",
            file=out,
        )
        ranked = sorted(ops.items(), key=lambda kv: -kv[1].get("count", 0))
        for op, row in ranked:
            h = row.get("handle") or {}
            w = row.get("wait") or {}
            print(
                f"    {op:<16} {row.get('count', 0):>9} "
                f"{row.get('errors', 0):>5} "
                f"{h.get('p50_us', 0):>7.1f}us {h.get('p95_us', 0):>7.1f}us "
                f"{h.get('max_us', 0):>7.1f}us {w.get('p95_us', 0):>7.1f}us "
                f"{_fmt_bytes(row.get('bytes_in', 0)):>10}",
                file=out,
            )
    hot = doc.get("hot_prefixes") or []
    if hot:
        print("hot key prefixes (space-saving top-K; count may over-estimate "
              "by err):", file=out)
        for row in hot[:10]:
            err = f" (±{row['err']})" if row.get("err") else ""
            print(f"    {row['prefix']:<40} ~{row['count']}{err}", file=out)
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Introspect a live tpu-resiliency coordination store"
    )
    ap.add_argument(
        "endpoint",
        help="HOST:PORT of the KV server, or a comma-separated shard list "
        "HOST:PORT,HOST:PORT (a clique). A single endpoint that fronts a "
        "clique is auto-expanded from its published shard map unless "
        "--no-discover",
    )
    ap.add_argument(
        "--no-discover", action="store_true",
        help="inspect exactly the given endpoint even if it advertises a "
        "clique (per-shard debugging)",
    )
    ap.add_argument("--prefix", default="", help="census keys under this prefix")
    ap.add_argument(
        "--stale", metavar="PREFIX",
        help="also scan touch-stamps under PREFIX for staleness",
    )
    ap.add_argument("--max-age", type=float, default=10.0)
    ap.add_argument(
        "--barriers", action="store_true",
        help="render only the live barrier census: per wait key, who arrived "
        "(with waiter ages), who is missing, who was proxied absent",
    )
    ap.add_argument(
        "--stats", action="store_true",
        help="render only the live op-telemetry document (store_stats op): "
        "per-op latency with queue-wait/handle split, bytes in/out, dedup "
        "hit rate, park depth, hot key prefixes; exit 1 when the store is "
        "unreachable, predates the op, or runs with stats disabled",
    )
    args = ap.parse_args(argv)
    from tpu_resiliency.platform.shardstore import (
        ShardedKVClient,
        parse_endpoints,
        probe_clique_spec,
    )

    try:
        endpoints = parse_endpoints(args.endpoint)
    except ValueError:
        ap.error(f"want HOST:PORT[,HOST:PORT...], got {args.endpoint!r}")
    auth_key = os.environ.get(AUTH_KEY_ENV) or None
    if len(endpoints) == 1 and not args.no_discover:
        # One probe: does this endpoint front a clique? If so, aggregate the
        # whole thing instead of reporting only the connected shard.
        spec = probe_clique_spec(*endpoints[0], auth_key=auth_key)
        if spec:
            endpoints = parse_endpoints(spec)
            print(f"endpoint fronts a {len(endpoints)}-shard clique: {spec}",
                  file=sys.stderr)
    try:
        # Fail fast on a dead endpoint: a diagnostics tool must not sit in
        # the client's default 60-attempt reconnect ladder.
        if len(endpoints) > 1:
            client = ShardedKVClient(
                endpoints, connect_retries=3, auth_key=auth_key,
            )
        else:
            client = KVClient(
                endpoints[0][0] or "127.0.0.1",
                endpoints[0][1],
                connect_retries=3,
                auth_key=auth_key,
            )
    except StoreError as e:
        print(str(e), file=sys.stderr)
        return 1
    try:
        rc = 0
        if args.stats:
            def body() -> None:
                nonlocal rc
                rc = report_stats(client)
        elif args.barriers:
            body = lambda: report_barriers(client, args.prefix)  # noqa: E731
        else:
            body = lambda: report(  # noqa: E731
                client, args.prefix, args.stale, args.max_age
            )
        if pipe_safe(body):
            return SIGPIPE_EXIT
        if rc:
            return rc
    except (OSError, StoreError) as e:
        print(f"store at {args.endpoint} failed mid-report: {e}", file=sys.stderr)
        return 1
    finally:
        try:
            client.close()
        except Exception:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
