"""``tpu-fleet``: render the fleet view offline (or from a live fleetd).

The operator-side twin of ``tpu-fleetd``: takes the ``tpu-fleet-snapshot-1``
document the daemon persists (``--snapshot fleet.json``) — or fetches one
from a live fleetd (``--url http://host:port``) — and renders the scoreboard,
SLO ranking, or incident feed as tables. Offline by design: the snapshot is
self-contained, so a postmortem needs no running fleet.

Usage::

    tpu-fleet scoreboard --snapshot fleet.json
    tpu-fleet slo --snapshot fleet.json
    tpu-fleet incidents --snapshot fleet.json --job trainer-a
    tpu-fleet scoreboard --url http://127.0.0.1:9400
    tpu-fleet slo --snapshot fleet.json --format json | jq .

``--format json`` emits the selected view's sub-document (the same
``tpu-fleet-*-1`` schema the daemon serves) instead of tables — the
scripting-side contract, stable where the table layout is not.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Optional

from tpu_resiliency.fleet.aggregator import SNAPSHOT_SCHEMA
from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe


def _fmt_ratio(v) -> str:
    return f"{v:.3f}" if isinstance(v, (int, float)) else "-"


def _fmt_s(v) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    return f"{v * 1e3:.0f}ms" if v < 1.0 else f"{v:.1f}s"


def _table(rows: list[list[str]], header: list[str], out) -> None:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    line = "  ".join(h.ljust(w) for h, w in zip(header, widths))
    print(line, file=out)
    print("-" * len(line), file=out)
    for r in rows:
        print("  ".join(str(c).ljust(w) for c, w in zip(r, widths)), file=out)


def render_scoreboard(doc: dict, out=None) -> None:
    out = sys.stdout if out is None else out
    gp = doc.get("goodput") or {}
    fleet = gp.get("fleet") or {}
    print(
        f"fleet: {fleet.get('jobs', 0)} job(s), "
        f"{fleet.get('reachable', 0)} reachable, "
        f"goodput_ratio={_fmt_ratio(fleet.get('goodput_ratio'))}",
        file=out,
    )
    rows = []
    for r in gp.get("jobs") or []:
        phases = r.get("phases") or {}
        rows.append([
            r.get("job", "?"), r.get("status", "?"),
            _fmt_ratio(r.get("goodput_ratio")),
            r.get("steps") if r.get("steps") is not None else "-",
            _fmt_s(phases.get("train")), _fmt_s(phases.get("restart")),
            _fmt_s(phases.get("ckpt_stall")),
            r.get("error") or "",
        ])
    if rows:
        _table(
            rows,
            ["job", "status", "goodput", "steps", "train", "restart",
             "ckpt_stall", "detail"],
            out,
        )


def render_slo(doc: dict, out=None) -> None:
    out = sys.stdout if out is None else out
    slo = doc.get("slo") or {}
    rows = []
    for r in slo.get("jobs") or []:
        share = r.get("restart_share")
        ttd, ttr = r.get("time_to_detect_s") or {}, r.get("time_to_recover_s") or {}
        rows.append([
            r.get("job", "?"), r.get("status", "?"),
            f"{share * 100:.1f}%" if isinstance(share, (int, float)) else "-",
            _fmt_s(r.get("restart_s")),
            r.get("restarts") if r.get("restarts") is not None else "-",
            r.get("incidents") if r.get("incidents") is not None else "-",
            _fmt_s(ttd.get("p95")), _fmt_s(ttr.get("p95")),
        ])
    print("SLO ranking (worst first: time-in-restart share)", file=out)
    if rows:
        _table(
            rows,
            ["job", "status", "restart%", "restart_s", "restarts",
             "incidents", "detect_p95", "recover_p95"],
            out,
        )
    else:
        print("no jobs", file=out)


def render_incidents(doc: dict, job: Optional[str] = None, out=None) -> None:
    out = sys.stdout if out is None else out
    feed = (doc.get("incidents") or {}).get("incidents") or []
    if job is not None:
        feed = [i for i in feed if i.get("job") == job]
    scope = f" for job {job!r}" if job else ""
    print(f"{len(feed)} incident(s){scope} (newest first)", file=out)
    for inc in feed:
        slo = inc.get("slo") or {}
        ranks = inc.get("ranks") or []
        print(
            f"  [{inc.get('job', '?')}] {inc.get('id', '?')}: "
            f"{inc.get('trigger', '?')} -> {inc.get('outcome', '?')}"
            + (f" ranks={ranks}" if ranks else "")
            + (f" detect={_fmt_s(slo.get('time_to_detect_s'))}"
               f" recover={_fmt_s(slo.get('time_to_recover_s'))}"
               if slo else ""),
            file=out,
        )


def load_snapshot(args) -> dict:
    if args.url:
        with urllib.request.urlopen(
            f"{args.url.rstrip('/')}/fleet/snapshot", timeout=10
        ) as r:
            doc = json.load(r)
    else:
        with open(args.snapshot) as f:
            doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != SNAPSHOT_SCHEMA:
        raise ValueError(
            f"not a {SNAPSHOT_SCHEMA} document "
            f"(got schema {doc.get('schema') if isinstance(doc, dict) else None!r})"
        )
    return doc


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-fleet",
        description="Render a fleet snapshot (tpu-fleetd --snapshot output or "
        "a live fleetd's /fleet/snapshot) as operator tables.",
    )
    ap.add_argument(
        "view", nargs="?", default="scoreboard",
        choices=("scoreboard", "slo", "incidents"),
        help="which fleet view to render (default: scoreboard)",
    )
    ap.add_argument("--snapshot", default=None, help="fleet snapshot JSON file")
    ap.add_argument(
        "--url", default=None,
        help="live fleetd base URL (fetches /fleet/snapshot instead of --snapshot)",
    )
    ap.add_argument(
        "--job", default=None,
        help="incidents view: slice the feed to one job",
    )
    ap.add_argument(
        "--format", choices=("table", "json"), default="table",
        help="table renders for operators; json emits the selected view's "
        "sub-document verbatim (stable tpu-fleet-*-1 schema, for scripting)",
    )
    args = ap.parse_args(argv)
    if bool(args.snapshot) == bool(args.url):
        print("exactly one of --snapshot / --url is required", file=sys.stderr)
        return 2
    try:
        doc = load_snapshot(args)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"cannot load fleet snapshot: {e}", file=sys.stderr)
        return 1

    def emit() -> None:
        if args.format == "json":
            section = {"scoreboard": "goodput", "slo": "slo",
                       "incidents": "incidents"}[args.view]
            sub = doc.get(section) or {}
            if args.view == "incidents" and args.job is not None:
                sub = dict(sub)
                sub["incidents"] = [
                    i for i in sub.get("incidents") or []
                    if i.get("job") == args.job
                ]
            json.dump(sub, sys.stdout, indent=2, sort_keys=True)
            print()
        elif args.view == "scoreboard":
            render_scoreboard(doc)
        elif args.view == "slo":
            render_slo(doc)
        else:
            render_incidents(doc, job=args.job)

    if pipe_safe(emit):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
