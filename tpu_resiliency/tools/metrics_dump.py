"""Aggregate a finished run's events JSONL into metrics.

The post-hoc twin of the live :class:`~tpu_resiliency.utils.metrics.MetricsSink`
— same kind→metric mapping (``utils/metrics.py:observe_record``), replayed over
a JSONL file instead of fed per ``record()`` call, so an operator answers "how
many restarts, p95 rendezvous time, checkpoint save latency" from the artifact
a run leaves behind, without a scrape pipeline and without replaying raw JSONL
by hand.

Usage::

    python -m tpu_resiliency.tools.metrics_dump run_events.jsonl            # report
    python -m tpu_resiliency.tools.metrics_dump run_events.jsonl --format prom
    python -m tpu_resiliency.tools.metrics_dump run_events.jsonl --format json -o m.json
    python -m tpu_resiliency.tools.metrics_dump run_events.jsonl --goodput  # attribution

``--goodput`` renders the time-attribution ledger (``utils/goodput.py``)
instead of the metrics report: wall clock classified into train / ckpt_stall /
restart / incident / unattributed, the goodput ratio, and per-rank rows — the
offline twin of the launcher's live ``/goodput`` endpoint, computed from the
same stream by the same ledger.

``--bytes`` renders the byte-flow ledger (``utils/byteflow.py``) instead:
every byte moved attributed to (purpose, direction, peer) — replicate /
retrieve / reshard / store / ckpt_write — reconciled against the per-family
byte counters with the unaccounted residue called out. This is the gate
instrument for the replication byte-economy work ("5-10× fewer bytes" must
show up HERE, not in a hand-picked counter).

``--job`` slices fleet-scope inputs back to one job post-hoc: on an events
JSONL it keeps only records stamped with that job identity
($TPU_RESILIENCY_JOB, set by launchers under ``--fleet-dir``); the input may
also be a metrics *snapshot* document (``MetricsRegistry.snapshot`` format —
e.g. the ``metrics`` section of a ``tpu-fleetd`` snapshot), in which case the
series carrying the matching ``job=`` label are kept (the ``fleet:*``
cross-job totals, which belong to no single job, are dropped from the slice).
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Optional

from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe
from tpu_resiliency.utils.events import read_events
from tpu_resiliency.utils.metrics import MetricsRegistry, aggregate


def _counter_total(reg: MetricsRegistry, name: str) -> float:
    snap = reg.snapshot()["metrics"].get(name, [])
    return sum(e.get("value", 0.0) for e in snap)


def load_snapshot_doc(path: str) -> Optional[dict]:
    """Parse ``path`` as a metrics snapshot document, or None when it is not
    one (an events JSONL line also parses as a dict — only a whole-file JSON
    object with a ``metrics`` dict is a snapshot)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None
    if isinstance(doc, dict) and isinstance(doc.get("metrics"), dict):
        return doc
    return None


def slice_snapshot_job(doc: dict, job: str) -> dict:
    """One job's slice of a fleet-merged snapshot: series whose ``job`` label
    matches, with the label dropped (the slice IS that job's view — keeping
    it would make the slice unmergeable with the job's own snapshots);
    ``fleet:*`` totals and other-job series are excluded."""
    out: dict = {"ts": doc.get("ts"), "metrics": {}}
    for name, entries in (doc.get("metrics") or {}).items():
        if name.startswith("fleet:") or not isinstance(entries, list):
            continue
        kept = []
        for e in entries:
            if not isinstance(e, dict):
                continue
            labels = dict(e.get("labels") or {})
            if labels.pop("job", None) != job:
                continue
            kept.append({**e, "labels": labels})
        if kept:
            out["metrics"][name] = kept
    return out


def _fmt_s(v: float) -> str:
    if math.isnan(v):
        return "-"
    return f"{v * 1e3:.1f} ms" if v < 1.0 else f"{v:.2f} s"


def _latency_lines(reg: MetricsRegistry, family: str, label: str) -> list[str]:
    """p50/p95 per labelled series of one histogram family, stably ordered."""
    out = []
    for labels, h in sorted(reg.histograms(family).items()):
        name = dict(labels).get(label, "?")
        out.append(
            f"    {name}: n={h.count} p50={_fmt_s(h.quantile(0.5))} "
            f"p95={_fmt_s(h.quantile(0.95))} max={_fmt_s(h.quantile(1.0))}"
        )
    return out


def render_report(reg: MetricsRegistry, out=None) -> None:
    """The operator summary: restarts, rendezvous latency, checkpoint latency."""
    out = sys.stdout if out is None else out
    snap = reg.snapshot()["metrics"]

    total = _counter_total(reg, "tpu_events_total")
    print(f"events: {int(total)}", file=out)

    print("restarts:", file=out)
    restarts = {
        dict(e["labels"]).get("layer", "?"): e["value"]
        for e in snap.get("tpu_restarts_total", [])
    }
    print(f"    in-job requested: {int(restarts.get('injob', 0))}", file=out)
    print(f"    in-process signalled: {int(restarts.get('inprocess', 0))}", file=out)
    for name, label in (
        ("tpu_rendezvous_rounds_total", "rendezvous rounds"),
        ("tpu_worker_failures_total", "worker failures"),
        ("tpu_rank_terminations_total", "rank terminations"),
        ("tpu_budget_exhausted_total", "budget exhaustions"),
        ("tpu_ckpt_saves_total", "checkpoint saves"),
        ("tpu_ckpt_save_failures_total", "checkpoint save failures"),
    ):
        n = _counter_total(reg, name)
        if n:
            print(f"    {label}: {int(n)}", file=out)
    # Labelled restart-machinery rows: warm-spare promotion attempts by
    # outcome (worker_promoted events), fast-path rendezvous, compile cache.
    for name, label in (
        ("tpu_spare_promotions_total", "warm-spare promotions"),
        ("tpu_rendezvous_fast_path_total", "fast-path rendezvous"),
        ("tpu_compile_cache_total", "compile cache"),
    ):
        by_outcome = {
            dict(e["labels"]).get("outcome", "?"): e["value"]
            for e in snap.get(name, [])
        }
        if by_outcome:
            detail = " ".join(
                f"{k}={int(v)}" for k, v in sorted(by_outcome.items())
            )
            print(f"    {label}: {detail}", file=out)

    # Autoscale controller: decisions by action/outcome + forecast accuracy.
    decisions = snap.get("tpu_autoscale_decisions_total", [])
    if decisions:
        rows = sorted(
            (dict(e["labels"]).get("action", "?"),
             dict(e["labels"]).get("outcome", "?"), e["value"])
            for e in decisions
        )
        detail = " ".join(f"{a}/{o}={int(v)}" for a, o, v in rows)
        print(f"autoscale decisions: {detail}", file=out)
        for labels, h in sorted(
            reg.histograms("tpu_autoscale_predicted_vs_realized").items()
        ):
            if not h.count:
                continue
            action = dict(labels).get("action", "?")
            print(
                f"    forecast error ({action}): n={h.count} "
                f"mean={h.sum / h.count:+.3f}s "
                f"p95={h.quantile(0.95):+.3f}s",
                file=out,
            )
    rescinds = _counter_total(reg, "tpu_preemption_rescinded_total")
    if rescinds:
        print(f"    preemption notices rescinded: {int(rescinds)}", file=out)

    span_lines = _latency_lines(reg, "tpu_span_seconds", "span")
    if span_lines:
        print("span durations (p50/p95):", file=out)
        for line in span_lines:
            print(line, file=out)
    timing_lines = _latency_lines(reg, "tpu_timing_seconds", "name")
    if timing_lines:
        print("timed blocks (p50/p95):", file=out)
        for line in timing_lines:
            print(line, file=out)

    # Step timing (tpu_step_seconds: consecutive iteration_start deltas).
    step_hists = reg.histograms("tpu_step_seconds")
    if step_hists:
        h = next(iter(step_hists.values()))
        if h.count:
            print(
                f"training steps: n={h.count} p50={_fmt_s(h.quantile(0.5))} "
                f"p95={_fmt_s(h.quantile(0.95))}",
                file=out,
            )

    # The two headline latencies, called out by name so a fleet dashboard's
    # first question needs no knowledge of span naming conventions.
    rdzv = reg.histograms("tpu_span_seconds").get((("span", "rendezvous.round"),))
    if rdzv is not None and rdzv.count:
        print(
            f"rendezvous round duration: n={rdzv.count} "
            f"p50={_fmt_s(rdzv.quantile(0.5))} p95={_fmt_s(rdzv.quantile(0.95))}",
            file=out,
        )
    ckpt = [
        (dict(labels)["name"], h)
        for labels, h in reg.histograms("tpu_timing_seconds").items()
        if dict(labels).get("name", "").startswith("ckpt.") and h.count
    ]
    if ckpt:
        worst = {name: h.quantile(0.95) for name, h in ckpt}
        total_p50 = sum(h.quantile(0.5) for _, h in ckpt)
        print(
            f"checkpoint save/load latency: phases={sorted(worst)} "
            f"sum(p50)={_fmt_s(total_p50)}",
            file=out,
        )


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Aggregate a tpu-resiliency events JSONL file into metrics"
    )
    ap.add_argument("events_file")
    ap.add_argument(
        "--format", choices=("report", "prom", "json"), default="report",
        help="report: human summary (default); prom: Prometheus text "
        "exposition; json: quantile snapshot",
    )
    ap.add_argument(
        "-o", "--output", default=None,
        help="write here instead of stdout (json format: atomic write)",
    )
    ap.add_argument(
        "--goodput", action="store_true",
        help="render the time-attribution ledger (train/ckpt_stall/restart/"
        "incident/unattributed + goodput ratio) instead of the metrics "
        "report; --format json emits the same attribution document the "
        "launcher's live /goodput endpoint serves",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="with --goodput: a second events JSONL to compare against — "
        "renders per-phase deltas and the goodput-ratio delta (this run "
        "minus the baseline), the arithmetic the autoscale chaos scenario "
        "gates on",
    )
    ap.add_argument(
        "--bytes", action="store_true", dest="bytes_flow",
        help="render the byte-flow ledger (bytes attributed to purpose/"
        "direction/peer, reconciled against the per-family byte counters "
        "with the residue called out) instead of the metrics report; "
        "--format json emits the tpu-byteflow-1 document",
    )
    ap.add_argument(
        "--job", default=None,
        help="slice a fleet-scope input back to one job: on an events JSONL, "
        "keep only records stamped with this job identity (launcher "
        "--fleet-dir); on a metrics snapshot document, keep only series "
        "carrying the matching job= label (fleet:* totals dropped)",
    )
    args = ap.parse_args(argv)
    if args.baseline and not args.goodput:
        print("--baseline requires --goodput", file=sys.stderr)
        return 2
    if args.bytes_flow and (args.goodput or args.baseline):
        print("--bytes and --goodput are mutually exclusive", file=sys.stderr)
        return 2
    try:
        with open(args.events_file):
            pass
    except OSError as e:
        print(f"cannot read events file: {e}", file=sys.stderr)
        return 1
    snapshot_doc = load_snapshot_doc(args.events_file) if args.job else None
    if snapshot_doc is not None:
        if args.goodput or args.bytes_flow:
            print(
                "--goodput/--bytes need an events stream, not a metrics "
                "snapshot",
                file=sys.stderr,
            )
            return 2
        reg = MetricsRegistry()
        try:
            reg.merge(slice_snapshot_job(snapshot_doc, args.job))
        except (ValueError, TypeError) as e:
            print(f"cannot slice snapshot: {e}", file=sys.stderr)
            return 1
        return _emit_registry(reg, args)
    records = read_events(args.events_file)
    if args.job is not None:
        records = [r for r in records if r.get("job") == args.job]
        if not records:
            print(f"no events for job {args.job!r}", file=sys.stderr)
            return 1
    if not records:
        print("no events to aggregate", file=sys.stderr)
        return 1
    if args.bytes_flow:
        from tpu_resiliency.utils.byteflow import ByteFlowLedger, render_table

        ledger = ByteFlowLedger()
        ledger.observe_many(records)
        summary = ledger.summary()
        # Belt and suspenders: the same stream through the independent
        # counter mapping — any drift names an emitter one side misreads.
        recon = ledger.reconcile(aggregate(records))

        def emit_bytes() -> None:
            if args.format == "json":
                json.dump({**summary, "reconcile": recon}, sys.stdout, indent=2)
                sys.stdout.write("\n")
            else:
                render_table(summary, reconcile=recon)

        if args.output:
            with open(args.output, "w") as f:
                old, sys.stdout = sys.stdout, f
                try:
                    emit_bytes()
                finally:
                    sys.stdout = old
            print(f"wrote {args.output}")
            return 0
        if pipe_safe(emit_bytes):
            return SIGPIPE_EXIT
        return 0
    if args.goodput:
        from tpu_resiliency.utils.goodput import (
            GoodputLedger,
            compare,
            render_compare,
            render_table,
        )

        ledger = GoodputLedger()
        ledger.observe_many(records)
        summary = ledger.summary()
        comparison = None
        if args.baseline:
            try:
                base_records = read_events(args.baseline)
            except OSError as e:
                print(f"cannot read baseline events file: {e}", file=sys.stderr)
                return 1
            if not base_records:
                print("no baseline events to aggregate", file=sys.stderr)
                return 1
            base = GoodputLedger()
            base.observe_many(base_records)
            comparison = compare(summary, base.summary())

        def emit_goodput() -> None:
            if args.format == "json":
                json.dump(
                    comparison if comparison is not None else summary,
                    sys.stdout, indent=2,
                )
                sys.stdout.write("\n")
            elif comparison is not None:
                render_compare(comparison)
            else:
                render_table(summary)

        if args.output:
            with open(args.output, "w") as f:
                old, sys.stdout = sys.stdout, f
                try:
                    emit_goodput()
                finally:
                    sys.stdout = old
            print(f"wrote {args.output}")
            return 0
        if pipe_safe(emit_goodput):
            return SIGPIPE_EXIT
        return 0
    return _emit_registry(aggregate(records), args)


def _emit_registry(reg: MetricsRegistry, args) -> int:
    """Render a built registry per --format/--output (the shared tail of the
    events-aggregation and snapshot-slice paths)."""
    if args.format == "json" and args.output:
        reg.write_json(args.output)
        print(f"wrote {args.output}")
        return 0

    def emit() -> None:
        if args.format == "prom":
            sys.stdout.write(reg.to_prometheus())
        elif args.format == "json":
            json.dump(reg.snapshot(), sys.stdout, indent=2, default=repr)
            sys.stdout.write("\n")
        else:
            render_report(reg)

    if args.output:
        with open(args.output, "w") as f:
            old, sys.stdout = sys.stdout, f
            try:
                emit()
            finally:
                sys.stdout = old
        print(f"wrote {args.output}")
        return 0
    if pipe_safe(emit):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
