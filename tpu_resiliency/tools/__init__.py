"""Operator-facing CLI tools over the framework's artifacts and streams."""

from __future__ import annotations

import os
import sys
from typing import Callable

#: 128 + SIGPIPE: the conventional shell exit status of a pipe-truncated tool,
#: so ``tool | head`` scripting can tell a truncated run from a complete one.
SIGPIPE_EXIT = 141


def pipe_safe(emit: Callable[[], None]) -> bool:
    """Run ``emit`` (stdout-printing CLI body) with ``| head``-citizenship.

    Flushes inside the guard: with block-buffered stdout the writes that die
    on a closed pipe may be the interpreter-exit flush, after ``main``
    returned — so the flush must happen where the handler can see it. On a
    broken pipe, stdout is redirected to devnull so shutdown cannot re-raise.

    Returns True when the consumer vanished mid-output (callers exit
    :data:`SIGPIPE_EXIT` per the SIGPIPE convention, not 0 — a truncated
    report must not read as a complete one).
    """
    try:
        emit()
        sys.stdout.flush()
        return False
    except BrokenPipeError:
        fd = os.open(os.devnull, os.O_WRONLY)
        try:
            os.dup2(fd, sys.stdout.fileno())
        finally:
            os.close(fd)
        return True
