"""Operator-facing CLI tools over the framework's artifacts and streams."""

from __future__ import annotations

import os
import sys
from typing import Callable


def pipe_safe(emit: Callable[[], None]) -> None:
    """Run ``emit`` (stdout-printing CLI body) with ``| head``-citizenship.

    Flushes inside the guard: with block-buffered stdout the writes that die
    on a closed pipe may be the interpreter-exit flush, after ``main``
    returned — so the flush must happen where the handler can see it. On a
    broken pipe, stdout is redirected to devnull so shutdown cannot re-raise.
    """
    try:
        emit()
        sys.stdout.flush()
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
