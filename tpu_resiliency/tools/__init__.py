"""Operator-facing CLI tools over the framework's artifacts and streams."""
