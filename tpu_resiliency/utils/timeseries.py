"""Bounded in-process time-series rings (the watchtower's TSDB).

Every signal the system serves today is either a live snapshot (``/metrics``,
``/storez``, ``/goodput``) or post-hoc forensics; nothing retains *history*,
so nothing can answer "is step time trending up?" or "is the SLO burning?".
This module is the smallest structure that can: a :class:`SeriesRing` is a
fixed-capacity ring of ``(ts, value)`` samples, and a :class:`SeriesStore`
keys rings by metric family + labels — a few hundred floats per family, never
a database. The alert engine (``telemetry/watchtower.py``) feeds rings off the
``observe_record`` bridge and evaluates rules over the window/quantile/EWMA
helpers below.

Determinism contract: rings are pure containers — append order in, append
order out, no wall-clock reads — so replaying the same record stream through
the same feed code reproduces ring contents (and therefore every rule
verdict) exactly. All helpers are pure functions over sample lists for the
same reason.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable, List, Optional, Tuple

Sample = Tuple[float, float]


class SeriesRing:
    """Fixed-capacity ring of ``(ts, value)`` samples in append order.

    Appends are O(1): once full, the oldest sample is overwritten. Reads
    return copies (callers iterate outside the writer's lock).
    """

    __slots__ = ("capacity", "_buf", "_head", "_n")

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._buf: List[Optional[Sample]] = [None] * self.capacity
        self._head = 0  # next write slot
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def observe(self, ts: float, value: float) -> None:
        self._buf[self._head] = (float(ts), float(value))
        self._head = (self._head + 1) % self.capacity
        if self._n < self.capacity:
            self._n += 1

    def samples(
        self, start: Optional[float] = None, end: Optional[float] = None
    ) -> List[Sample]:
        """All retained samples (append order), optionally windowed to
        ``start < ts <= end`` — the half-open window rule evaluation uses so
        a sample sits in exactly one adjacent window."""
        if self._n < self.capacity:
            out = [s for s in self._buf[: self._n]]
        else:
            out = self._buf[self._head:] + self._buf[: self._head]
        return [
            s for s in out
            if s is not None
            and (start is None or s[0] > start)
            and (end is None or s[0] <= end)
        ]

    def last(self) -> Optional[Sample]:
        if self._n == 0:
            return None
        return self._buf[(self._head - 1) % self.capacity]


class SeriesStore:
    """Rings keyed by ``(family, sorted labels)`` — the in-process TSDB.

    Thread-safe for concurrent feed/query (one lock; operations are tiny).
    """

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._rings: dict = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(family: str, labels: Optional[dict]) -> tuple:
        return (family, tuple(sorted((labels or {}).items())))

    def series(self, family: str, **labels) -> SeriesRing:
        """The ring for one family+labels, created on first touch."""
        key = self._key(family, labels)
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = SeriesRing(self.capacity)
            return ring

    def observe(self, family: str, ts: float, value: float, **labels) -> None:
        self.series(family, **labels).observe(ts, value)

    def query(
        self,
        family: str,
        start: Optional[float] = None,
        end: Optional[float] = None,
        **labels,
    ) -> List[Sample]:
        """Windowed samples for one series; empty if the series never fed."""
        key = self._key(family, labels)
        with self._lock:
            ring = self._rings.get(key)
        return [] if ring is None else ring.samples(start=start, end=end)

    def families(self) -> List[tuple]:
        with self._lock:
            return sorted(self._rings)

    def sizes(self) -> dict:
        """``{"family{k=v,...}": n_samples}`` — the /alerts doc's ring census."""
        with self._lock:
            items = list(self._rings.items())
        out = {}
        for (family, labels), ring in items:
            tag = ",".join(f"{k}={v}" for k, v in labels)
            out[f"{family}{{{tag}}}" if tag else family] = len(ring)
        return out


# -- pure helpers over sample lists -----------------------------------------

def rate(samples: Iterable[Sample]) -> Optional[float]:
    """Per-second increase across a counter-style window.

    Counter resets (a value drop — restarted emitter) contribute the
    post-reset value, matching Prometheus ``rate()`` semantics.
    """
    samples = list(samples)
    if len(samples) < 2:
        return None
    t0, t1 = samples[0][0], samples[-1][0]
    if t1 <= t0:
        return None
    total, prev = 0.0, samples[0][1]
    for _, v in samples[1:]:
        total += (v - prev) if v >= prev else v
        prev = v
    return total / (t1 - t0)


def quantile_over_time(samples: Iterable[Sample], q: float) -> Optional[float]:
    """Linear-interpolated quantile of the window's values."""
    vals = sorted(v for _, v in samples)
    if not vals:
        return None
    if len(vals) == 1:
        return vals[0]
    q = min(1.0, max(0.0, q))
    pos = q * (len(vals) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(vals) - 1)
    return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)


def mean_over_time(samples: Iterable[Sample]) -> Optional[float]:
    vals = [v for _, v in samples]
    if not vals:
        return None
    return sum(vals) / len(vals)


def ewma(samples: Iterable[Sample], alpha: float = 0.3) -> Optional[float]:
    """Exponentially-weighted moving average over the window, append order."""
    out = None
    for _, v in samples:
        out = v if out is None else out + alpha * (v - out)
    return out


def mad(samples: Iterable[Sample]) -> Optional[float]:
    """Median absolute deviation of the window's values (robust spread)."""
    vals = [v for _, v in samples]
    if not vals:
        return None
    med = quantile_over_time([(0.0, v) for v in vals], 0.5)
    dev = [(0.0, abs(v - med)) for v in vals]
    return quantile_over_time(dev, 0.5)


def robust_zscore(x: float, samples: Iterable[Sample]) -> Optional[float]:
    """``(x - median) / (1.4826 * MAD)`` — the step-anomaly rule's core.

    The 1.4826 factor makes MAD a consistent sigma estimate under normality.
    A zero-MAD window (a perfectly steady history — exactly the baseline a
    straggler spike must register against) floors the scale at 1% of the
    median's magnitude instead of going infinite; an all-zero window still
    returns None (no scale exists at all).
    """
    samples = list(samples)
    if len(samples) < 2:
        return None
    med = quantile_over_time(samples, 0.5)
    spread = mad(samples)
    if spread is None:
        return None
    if spread <= 0.0:
        spread = 0.01 * abs(med)
        if spread <= 0.0:
            return None
    return (x - med) / (1.4826 * spread)
