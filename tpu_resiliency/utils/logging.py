"""Rank-annotated logging helpers.

Analogue of the reference's ``RankMonitorLogger`` rank-prefixed format
(``fault_tolerance/rank_monitor_server.py:48-95``) generalized for the whole package.
"""

from __future__ import annotations

import logging
import os
import sys

_FMT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


def get_logger(name: str, level: int | str | None = None) -> logging.Logger:
    """Return a package logger, configuring a stderr handler once per process."""
    root = logging.getLogger("tpu_resiliency")
    if not root.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FMT))
        root.addHandler(handler)
        env_level = os.environ.get("TPU_RESILIENCY_LOG_LEVEL", "INFO")
        root.setLevel(env_level)
    logger = logging.getLogger(name if name.startswith("tpu_resiliency") else f"tpu_resiliency.{name}")
    if level is not None:
        logger.setLevel(level)
    return logger


class RankLoggerAdapter(logging.LoggerAdapter):
    """Prefixes every message with the rank (and optional role) emitting it."""

    def __init__(self, logger: logging.Logger, rank: int | None = None, role: str = ""):
        super().__init__(logger, {})
        self.rank = rank
        self.role = role

    def process(self, msg, kwargs):
        rank = self.rank if self.rank is not None else os.environ.get("RANK", "?")
        prefix = f"[{self.role}]" if self.role else ""
        return f"{prefix}[rank={rank}] {msg}", kwargs
