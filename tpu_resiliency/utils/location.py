"""Process-global last-known-location beacon: where IS this rank right now?

The first question of every hang postmortem — "where was the job stuck?" —
is unanswerable from a heartbeat gap alone. This module keeps one cheap,
thread-safe record of the process's current location in the training
topology, updated by the layers that already know it:

- **section**: the monitor client's ``start_section``/``end_section``
  (``watchdog/monitor_client.py``) — setup / step / checkpointing.
- **step**: the in-process wrapper's ``iteration_start``
  (``inprocess/wrap.py``) and any loop that calls :func:`note_step`.
- **barrier**: the store client's blocking ``barrier_join``
  (``platform/store.py``) — the collective tag a rank is waiting in.

The beacon rides every ``HeartbeatMsg``/``SectionMsg`` to the rank monitor
(:meth:`snapshot` is the wire payload), so at detection time the watchdog can
say *"heartbeat gap exceeded 45s; last seen in section=step
barrier=rdzv/round-3 for 612s"* instead of just "heartbeat gap exceeded".

Timestamps are ``time.monotonic()``. On Linux ``CLOCK_MONOTONIC`` is
system-wide, so the monitor process on the same host can age a beacon
against its own clock; cross-host consumers must use the ``*_age_s`` fields
computed at send time and never compare raw stamps.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional


class LocationBeacon:
    """Thread-safe last-known-location record (one per process)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: LIFO of (name, entered_at) — sections nest (setup > step)
        self._sections: list[tuple[str, float]] = []
        self._step: Optional[int] = None
        self._step_at: float = 0.0
        #: LIFO of (tag, entered_at) — barrier joins can nest through retries
        self._barriers: list[tuple[str, float]] = []

    # -- writers -----------------------------------------------------------

    def enter_section(self, name: str) -> None:
        with self._lock:
            self._sections.append((str(name), time.monotonic()))

    def exit_section(self, name: Optional[str] = None) -> None:
        """Pop ``name`` (innermost match) or, with ``None``, everything."""
        with self._lock:
            if name is None:
                self._sections.clear()
                return
            for i in range(len(self._sections) - 1, -1, -1):
                if self._sections[i][0] == name:
                    del self._sections[i]
                    return

    def note_step(self, iteration: int) -> None:
        with self._lock:
            self._step = int(iteration)
            self._step_at = time.monotonic()

    def enter_barrier(self, tag: str) -> None:
        with self._lock:
            self._barriers.append((str(tag), time.monotonic()))

    def exit_barrier(self, tag: Optional[str] = None) -> None:
        with self._lock:
            if tag is None:
                self._barriers.clear()
                return
            for i in range(len(self._barriers) - 1, -1, -1):
                if self._barriers[i][0] == tag:
                    del self._barriers[i]
                    return

    @contextmanager
    def barrier(self, tag: str):
        self.enter_barrier(tag)
        try:
            yield
        finally:
            self.exit_barrier(tag)

    def reset(self) -> None:
        with self._lock:
            self._sections.clear()
            self._barriers.clear()
            self._step = None
            self._step_at = 0.0

    # -- the wire payload --------------------------------------------------

    def snapshot(self) -> dict:
        """The beacon payload heartbeats/sections carry to the monitor.

        ``entered_at`` is the monotonic instant the process entered its
        *current* (most blocking-relevant) location: the innermost open
        barrier when one exists, else the innermost section, else the last
        step marker. The per-field ``*_age_s`` values are computed here so a
        consumer on another clock domain still gets usable ages.
        """
        now = time.monotonic()
        with self._lock:
            section = self._sections[-1] if self._sections else None
            barrier = self._barriers[-1] if self._barriers else None
            step, step_at = self._step, self._step_at
        out: dict = {"v": 1}
        entered = None
        if step is not None:
            out["step"] = step
            out["step_age_s"] = round(max(0.0, now - step_at), 3)
            entered = step_at
        if section is not None:
            out["section"] = section[0]
            out["section_age_s"] = round(max(0.0, now - section[1]), 3)
            entered = section[1]
        if barrier is not None:
            out["barrier"] = barrier[0]
            out["barrier_age_s"] = round(max(0.0, now - barrier[1]), 3)
            entered = barrier[1]
        if entered is not None:
            out["entered_at"] = entered
        return out


#: the process beacon — importers share one so every layer's writes compose
_beacon = LocationBeacon()


def get_beacon() -> LocationBeacon:
    return _beacon


def snapshot() -> dict:
    return _beacon.snapshot()


def note_step(iteration: int) -> None:
    _beacon.note_step(iteration)


def enter_section(name: str) -> None:
    _beacon.enter_section(name)


def exit_section(name: Optional[str] = None) -> None:
    _beacon.exit_section(name)


def barrier(tag: str):
    """Context manager tagging the active barrier/collective."""
    return _beacon.barrier(tag)


def describe(loc: Optional[dict], age_s: Optional[float] = None) -> str:
    """One human fragment from a beacon payload: ``section=step
    barrier=rdzv/round-3 for 612s`` (empty string for no payload). ``age_s``
    overrides the payload's own age (a consumer that knows how long ago the
    beacon was *received* passes beacon-age + staleness)."""
    if not isinstance(loc, dict):
        return ""
    parts = []
    if loc.get("section") is not None:
        parts.append(f"section={loc['section']}")
    if loc.get("step") is not None:
        parts.append(f"step={loc['step']}")
    if loc.get("barrier") is not None:
        parts.append(f"barrier={loc['barrier']}")
    if not parts:
        return ""
    if age_s is None:
        for key in ("barrier_age_s", "section_age_s", "step_age_s"):
            if isinstance(loc.get(key), (int, float)):
                age_s = loc[key]
                break
    if isinstance(age_s, (int, float)):
        parts.append(f"for {age_s:.0f}s")
    return " ".join(parts)
