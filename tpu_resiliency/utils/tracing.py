"""Cross-process causal tracing over the structured event stream.

One restart's causal chain — fault detected → abort → rendezvous round →
respawn / spare promotion → first step resumed — crosses at least three
processes (worker, monitor, launcher agent) and often several hosts. Log lines
interleave them; this module stitches them: a **trace id** minted once at the
launcher names the whole run, and **spans** (paired ``span_begin``/``span_end``
events carrying a span id and a parent id) nest the run's phases into a tree
that ``tools/trace_export.py`` renders as a Chrome/Perfetto trace.

Propagation mirrors the events layer's own env wiring
(``TPU_RESILIENCY_EVENTS_FILE``): the trace id rides ``$TPU_RESILIENCY_TRACE_ID``
and the spawner's active span rides ``$TPU_RESILIENCY_PARENT_SPAN``, so a worker
spawned inside the launcher's ``launcher.round`` span parents its own spans (and
every plain ``record()`` event) to that round without any code in the worker —
``utils/events.py`` stamps the inherited context onto each record.

Usage::

    from tpu_resiliency.utils.tracing import ensure_trace_id, span

    ensure_trace_id()                     # launcher entry: mint + export
    with span("launcher", "launcher.round", round=3):
        ...                               # record() calls here carry this span
        env.update(child_env())           # explicit per-child propagation

Spans are observability, not control flow: every operation here is best-effort
and an exception inside the wrapped block still emits a ``span_end`` with
``ok=False`` and the error before re-raising.
"""

from __future__ import annotations

import os
import secrets
import threading
import time
from contextlib import contextmanager
from typing import Any, Optional

from tpu_resiliency.utils import events
from tpu_resiliency.utils.events import record

#: Re-exported from events (the envelope owner) — one name, one place.
TRACE_ID_ENV = events.TRACE_ID_ENV
PARENT_SPAN_ENV = events.PARENT_SPAN_ENV

_tls = threading.local()


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def trace_id() -> Optional[str]:
    """The run's trace id, or None when no launcher/test ever minted one."""
    return os.environ.get(TRACE_ID_ENV) or None


def ensure_trace_id() -> str:
    """Mint (once) and export the run's trace id.

    Called at the launcher entry point; exporting via ``os.environ`` means every
    process the launcher spawns — agents, workers, monitors — inherits it, the
    same single-variable wiring the JSONL sink uses.
    """
    tid = os.environ.get(TRACE_ID_ENV)
    if not tid:
        tid = secrets.token_hex(8)
        os.environ[TRACE_ID_ENV] = tid
    return tid


def current_span_id() -> Optional[str]:
    """The innermost open span on this thread, else the inherited parent span
    (a child process's spans/events parent to the span its spawner held open)."""
    stack = _stack()
    if stack:
        return stack[-1]
    return os.environ.get(PARENT_SPAN_ENV) or None


def _context() -> tuple[Optional[str], Optional[str]]:
    return trace_id(), current_span_id()


# Upgrade the events layer's env-only default to the span-stack-aware provider.
events.set_context_provider(_context)


def child_env() -> dict[str, str]:
    """Env delta handing this process's trace context to a child it spawns.

    The trace id is usually already exported process-wide (``ensure_trace_id``);
    the parent span is per-call-site — a worker spawned during round 3 must
    parent to round 3's span, not to whatever the env held at launcher start.
    """
    env: dict[str, str] = {}
    tid = trace_id()
    if tid:
        env[TRACE_ID_ENV] = tid
    sid = current_span_id()
    if sid:
        env[PARENT_SPAN_ENV] = sid
    return env


@contextmanager
def span(source: str, name: str, **payload: Any):
    """Context manager emitting a paired ``span_begin``/``span_end``.

    The new span's id is pushed onto the thread-local stack BEFORE the begin
    event is recorded, so both span events (and every ``record()`` inside the
    block) carry it as their envelope ``span_id``; the parent linkage travels in
    the begin event's ``parent_id`` payload. Yields the span id (useful for
    handing to threads or asserting pairing in tests).
    """
    sid = secrets.token_hex(8)
    parent = current_span_id()
    stack = _stack()
    stack.append(sid)
    t0 = time.perf_counter()
    record(source, "span_begin", span=name, parent_id=parent, **payload)
    failure: Optional[str] = None
    try:
        yield sid
    except BaseException as e:
        failure = repr(e)
        raise
    finally:
        try:
            record(
                source, "span_end", span=name,
                duration_s=time.perf_counter() - t0,
                ok=failure is None,
                **({"error": failure} if failure else {}),
            )
        finally:
            # Pop AFTER span_end so the end event still carries this span's id;
            # tolerate mispaired exits (a generator-held span closed late).
            if stack and stack[-1] == sid:
                stack.pop()
            else:
                try:
                    stack.remove(sid)
                except ValueError:
                    pass


def traced(source: str, name: Optional[str] = None):
    """Decorator form of :func:`span` (``@prof``'s causal sibling: same timing
    payload, but begin/end pairing and parent linkage instead of one record)."""

    def deco(fn):
        label = name or getattr(fn, "__name__", "call")

        def wrapped(*args, **kwargs):
            with span(source, label):
                return fn(*args, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", label)
        wrapped.__wrapped__ = fn
        return wrapped

    return deco
