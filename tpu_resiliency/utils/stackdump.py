"""All-thread Python stack capture for hang forensics.

When the watchdog decides a rank is hung, the most valuable artifact is the
one the reference (NVRx) never collects: *what every thread of the victim —
and of the ranks blocked waiting on it — was executing at that instant*.
This module is the capture half of the hang-forensics plane:

- :func:`capture_stacks` walks ``sys._current_frames()`` and renders each
  thread's Python stack (bounded frames, no locals — safe to serialize).
- :func:`dump_stacks` records the capture as ONE ``stack_dump`` event, which
  therefore lands in every attached sink: the shared JSONL, the metrics
  bridge (``tpu_stack_dumps_total{reason}``), and — the point — the
  flight-recorder ring (``utils/flight_recorder.py``), whose hot segment
  persists the dump within one ``write()`` even if the process is SIGKILLed
  moments later. A consolidated flight flush follows so the dump also appears
  in the ``flight-<rank>-<pid>.jsonl`` artifact the incident engine collects.
- :func:`install_signal_trigger` gives operators the on-demand path:
  ``kill -USR1 <worker pid>`` dumps without disturbing the workload. The
  handler itself only writes one byte to a self-pipe (async-signal-safe);
  a daemon watcher thread does the actual capture, so a signal landing while
  the main thread holds an event-sink lock can never deadlock — the same
  discipline as the flight recorder's signal flush.

Capture limits: a truly GIL-holding hang (a native call made without
releasing the GIL) blocks *every* Python thread, including the one trying to
capture — no in-process mechanism can observe that state while it lasts. The
capture fires the moment the GIL frees (chunk boundaries of
``Fault.GIL_SLEEP``, or the end of the native call); hangs parked in
GIL-releasing waits (collectives, ``block_until_ready``, socket reads, locks)
capture immediately.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import traceback
from typing import Optional

from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

#: frames kept per thread (deepest first is what forensics wants — keep the
#: leaf end of the stack when truncating)
MAX_FRAMES_PER_THREAD = 64
#: threads kept per capture (a runaway thread-leaking process must not turn
#: one dump event into megabytes)
MAX_THREADS = 64

#: the operator's on-demand dump signal
DUMP_SIGNAL = signal.SIGUSR1


def capture_stacks(max_frames: int = MAX_FRAMES_PER_THREAD) -> list[dict]:
    """Every thread's Python stack as JSON-serializable dicts.

    Each entry: ``{"name", "ident", "daemon", "main", "frames": [
    "file:line in func | source"]}`` — outermost frame first, truncated to the
    *deepest* ``max_frames`` (the leaf is where the thread is stuck).
    """
    frames_by_id = sys._current_frames()
    threads = {t.ident: t for t in threading.enumerate()}
    main_id = threading.main_thread().ident
    # Rank BEFORE truncating: a JAX process can carry hundreds of pool
    # threads, and the main thread (usually the one that is stuck) must
    # never be the one the cap drops.
    ranked = sorted(
        frames_by_id.items(),
        key=lambda kv: (
            kv[0] != main_id,
            threads[kv[0]].name if kv[0] in threads else f"thread-{kv[0]}",
        ),
    )
    out: list[dict] = []
    for ident, frame in ranked[:MAX_THREADS]:
        t = threads.get(ident)
        stack = traceback.extract_stack(frame)
        if len(stack) > max_frames:
            stack = stack[-max_frames:]
        rendered = [
            f"{s.filename}:{s.lineno} in {s.name}"
            + (f" | {s.line.strip()}" if s.line else "")
            for s in stack
        ]
        out.append(
            {
                "name": t.name if t is not None else f"thread-{ident}",
                "ident": ident,
                "daemon": bool(t.daemon) if t is not None else None,
                "main": bool(t is threading.main_thread()) if t is not None else False,
                "frames": rendered,
            }
        )
    # Main thread first, then by name — deterministic artifacts.
    out.sort(key=lambda d: (not d["main"], str(d["name"])))
    return out


def dump_stacks(reason: str, detail: str = "") -> list[dict]:
    """Capture and record one ``stack_dump`` event, then flush the flight ring.

    Returns the captured thread list (callers embedding it elsewhere reuse
    the same capture). Never raises — forensics must not kill the patient.
    """
    try:
        threads = capture_stacks()
    except Exception:
        log.exception("stack capture failed")
        return []
    try:
        record_event(
            "flight", "stack_dump",
            reason=reason,
            **({"detail": detail} if detail else {}),
            thread_count=len(threads),
            threads=threads,
        )
    except Exception:
        log.debug("stack_dump record failed", exc_info=True)
    try:
        # The ring already holds the stack_dump line (it is an events sink);
        # the flush writes the consolidated per-process artifact so the
        # incident engine's collect() finds it even after a clean exit.
        from tpu_resiliency.utils import flight_recorder

        flight_recorder.flush("stack_dump", detail=reason)
    except Exception:
        log.debug("flight flush after stack dump failed", exc_info=True)
    return threads


# -- operator signal path -----------------------------------------------------

_trigger_lock = threading.Lock()
_trigger_pipe: Optional[tuple[int, int]] = None


def _watcher(rfd: int) -> None:
    while True:
        try:
            data = os.read(rfd, 64)
        except OSError:
            return
        if not data:
            return
        dump_stacks("signal:SIGUSR1")


def install_signal_trigger() -> bool:
    """Chain a SIGUSR1 handler that requests a stack dump (idempotent).

    Returns True when installed. Main-thread-only (``signal.signal``
    restriction); safe no-op elsewhere. The previous disposition is chained
    so embedding applications keep their own SIGUSR1 semantics.
    """
    global _trigger_pipe
    if threading.current_thread() is not threading.main_thread():
        return False
    with _trigger_lock:
        if _trigger_pipe is not None:
            return True
        rfd, wfd = os.pipe()
        os.set_blocking(wfd, False)
        threading.Thread(
            target=_watcher, args=(rfd,), name="stackdump-usr1", daemon=True
        ).start()
        try:
            prev = signal.getsignal(DUMP_SIGNAL)

            def handler(signum, frame):
                try:
                    os.write(wfd, b"d")  # async-signal-safe; watcher dumps
                except OSError:
                    pass
                if callable(prev):
                    prev(signum, frame)

            signal.signal(DUMP_SIGNAL, handler)
        except (ValueError, OSError):
            try:
                os.close(rfd)
                os.close(wfd)
            except OSError:
                pass
            return False
        _trigger_pipe = (rfd, wfd)
        return True
