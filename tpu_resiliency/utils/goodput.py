"""Goodput ledger: attribute a job's wall clock to what it was actually doing.

Six PRs of telemetry record every rendezvous, restart, checkpoint stall, and
incident — but none of it answers the operator's first question: *what
fraction of the last hour was training?* This module closes that gap. Driven
by the same structured event stream everything else consumes (live tail or a
finished JSONL), a :class:`GoodputLedger` classifies the job's wall clock
into phases:

- ``train`` — the deltas between a rank's consecutive ``iteration_start``
  markers (strictly-consecutive iterations only, capped at
  :data:`~tpu_resiliency.utils.metrics.STEP_GAP_MAX_S` — a gap is downtime,
  not a long step);
- ``ckpt_stall`` — the caller-visible checkpoint windows:
  ``ckpt_foreground_blocked`` records, the ``ckpt.save.enqueue`` span, and
  the blocking save/load timings (``ckpt.save.*``, ``ckpt.load``,
  ``ckpt.local_load``);
- ``restart`` — the window from the first fault evidence (``worker_failed``,
  ``hang_detected``, ``restart_requested``, ...) to the next
  ``iteration_start`` (training actually resumed — detection, teardown,
  re-rendezvous, respawn, and the respawned interpreter's imports are all
  restart cost), plus the machinery's instrumented spans (``worker.spawn``,
  ``rendezvous.round``, ``inprocess.restart``) for segments outside any
  fault window;
- ``incident`` — open→close windows from the incident engine
  (``launcher/incident.py``); an incident still open at end-of-stream is
  charged through to the last observed timestamp;
- ``unattributed`` — the residue. A healthy training job keeps this small;
  a large residue is itself a finding (time the instrumentation cannot
  explain).

Attribution is **interval-based**, not duration-summed: each phase's raw
windows are merged into intervals on the job's wall-clock timeline and
higher-severity phases own overlaps (incident > restart > ckpt_stall >
train). Overlapping evidence — a sync save that emits both a foreground
record and its per-phase timings, or two ranks stalling simultaneously —
therefore never double-counts, and the five phases sum to the job's wall
clock *exactly*.

Surfaces:

- :meth:`GoodputLedger.summary` — the attribution document served by the
  launcher's ``/goodput`` endpoint and rendered by
  ``tpu-metrics-dump --goodput``;
- :meth:`GoodputLedger.publish` — routes per-phase attribution deltas
  through the event stream as ``goodput_update`` records, which
  ``observe_record`` maps to ``tpu_time_attributed_seconds_total{phase}``
  and ``tpu_goodput_ratio`` — so the live Prometheus view and a post-hoc
  ``aggregate()`` of the same stream agree.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

from tpu_resiliency.utils import events as events_mod
from tpu_resiliency.utils.metrics import step_gap_max_s

SCHEMA = "tpu-goodput-1"

#: attribution priority, highest first: a second claimed by two phases goes
#: to the more severe one (an incident's restart churn is incident time).
PHASES = ("incident", "restart", "ckpt_stall", "train")

#: spans whose duration is restart machinery (spawn, re-rendezvous, the
#: in-process restart sequence). The initial round's rendezvous/spawn counts
#: too: time-to-first-step is not goodput either.
RESTART_SPANS = frozenset({"worker.spawn", "rendezvous.round", "inprocess.restart"})

#: fault evidence that opens a restart window. The spans above cover the
#: machinery's instrumented segments, but most of a restart's wall-clock cost
#: sits BETWEEN them (failure detection, worker teardown, respawned-process
#: import). The window from the first fault evidence to the next
#: ``iteration_start`` (training actually resumed) is the restart cost an
#: operator experiences — that whole span is charged to ``restart``.
RESTART_EVIDENCE = frozenset({
    "failure_detected", "worker_failed", "restart_requested",
    "restart_signalled", "hang_detected", "health_terminated",
    "rank_terminated",
})

#: spans whose duration is a caller-visible checkpoint stall
CKPT_STALL_SPANS = frozenset({"ckpt.save.enqueue"})

#: blocking checkpoint timings. ``ckpt.save.write`` is foreground for sync
#: saves; a pipelined save's background mirror writes also carry the name —
#: charging those overlaps to ckpt_stall is the conservative direction for a
#: goodput SLO (never over-reports training time).
CKPT_STALL_TIMINGS = frozenset({
    "ckpt.save.d2h", "ckpt.save.serialize", "ckpt.save.replicate",
    "ckpt.save.write", "ckpt.async_save", "ckpt.load", "ckpt.local_load",
})


# -- interval algebra ---------------------------------------------------------


def merge_intervals(ivs: Iterable[tuple[float, float]]) -> list[tuple[float, float]]:
    """Union of half-open intervals, sorted and coalesced."""
    out: list[tuple[float, float]] = []
    for s, e in sorted((s, e) for s, e in ivs if e > s):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def subtract_intervals(
    a: list[tuple[float, float]], b: list[tuple[float, float]]
) -> list[tuple[float, float]]:
    """``a`` minus the union ``b``; both inputs must be merged/sorted."""
    out: list[tuple[float, float]] = []
    j = 0
    for s, e in a:
        cur = s
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < e:
            bs, be = b[k]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if be >= e:
                break
            k += 1
        if cur < e:
            out.append((cur, e))
    return out


def total_seconds(ivs: Iterable[tuple[float, float]]) -> float:
    return sum(e - s for s, e in ivs)


def _clip(
    ivs: Iterable[tuple[float, float]], lo: float, hi: float
) -> list[tuple[float, float]]:
    return [(max(s, lo), min(e, hi)) for s, e in ivs if min(e, hi) > max(s, lo)]


# -- the ledger ---------------------------------------------------------------


class GoodputLedger:
    """Streamed time attribution over event records (flat JSONL dict shape).

    Feed with :meth:`observe` (one record) or :meth:`observe_many`; read with
    :meth:`summary`. The ledger is cheap per record — interval merging and
    priority subtraction happen at summary time, not per event.
    """

    def __init__(self, *, max_step_s: Optional[float] = None):
        # Resolved at construction (not import) so $TPU_RESILIENCY_STEP_GAP_MAX
        # set by the launcher reaches every ledger built after it.
        self.max_step_s = step_gap_max_s() if max_step_s is None else max_step_s
        self._min_ts: Optional[float] = None
        self._max_ts: Optional[float] = None
        #: raw (unmerged) intervals per phase
        self._ivs: dict[str, list[tuple[float, float]]] = {
            p: [] for p in PHASES
        }
        #: pid -> (last iteration_start ts, last iteration)
        self._last_step: dict[Any, tuple[float, int]] = {}
        #: incident_id -> opened ts (charged to last_ts while still open)
        self._open_incidents: dict[Any, float] = {}
        #: first fault evidence of an unresolved restart window (closed by
        #: the next iteration_start; charged to last_ts if never resolved)
        self._restart_open: Optional[float] = None
        #: step stats: count, sum, max
        self._steps = 0
        self._step_sum = 0.0
        self._step_max = 0.0
        #: rank -> {"first_ts", "last_ts", "train_s", "ckpt_stall_s", "steps"}
        self._ranks: dict[int, dict[str, float]] = {}
        #: compile-cache outcomes (hit/miss/miss_corrupt) — restart-attribution
        #: color: a "hit" restart skipped re-compilation, a "miss" paid it
        self._compile_cache: dict[str, int] = {}
        #: per-phase seconds already published as goodput_update deltas
        self._published: dict[str, float] = {}

    # -- ingest -------------------------------------------------------------

    def observe_many(self, recs: Iterable[dict]) -> None:
        for rec in recs:
            if isinstance(rec, dict):
                self.observe(rec)

    def observe(self, rec: dict) -> None:
        kind = rec.get("kind")
        ts = rec.get("ts")
        if not isinstance(kind, str) or not isinstance(ts, (int, float)):
            return
        if kind == "goodput_update":
            return  # our own narration is derived, not evidence
        self._widen(ts)
        rank = rec.get("rank")
        if isinstance(rank, int):
            rs = self._ranks.setdefault(rank, {
                "first_ts": ts, "last_ts": ts,
                "train_s": 0.0, "ckpt_stall_s": 0.0, "steps": 0,
            })
            rs["first_ts"] = min(rs["first_ts"], ts)
            rs["last_ts"] = max(rs["last_ts"], ts)

        if kind in RESTART_EVIDENCE:
            if self._restart_open is None:
                self._restart_open = ts
        elif kind == "iteration_start":
            if self._restart_open is not None:
                # Training resumed: the restart window closes here, so the
                # respawned interpreter's import/init time is restart cost,
                # not unattributed residue.
                self._ivs["restart"].append((self._restart_open, ts))
                self._restart_open = None
            it = rec.get("iteration")
            if not isinstance(it, int):
                return
            pid = rec.get("pid")
            prev = self._last_step.get(pid)
            if (
                prev is not None and it == prev[1] + 1
                and 0 < ts - prev[0] <= self.max_step_s
            ):
                d = ts - prev[0]
                self._ivs["train"].append((prev[0], ts))
                self._steps += 1
                self._step_sum += d
                self._step_max = max(self._step_max, d)
                if isinstance(rank, int):
                    rs = self._ranks[rank]
                    rs["train_s"] += d
                    rs["steps"] += 1
            self._last_step[pid] = (ts, it)
        elif kind == "ckpt_foreground_blocked":
            self._stall(rec, ts, rank)
        elif kind == "timing" and rec.get("name") in CKPT_STALL_TIMINGS:
            self._stall(rec, ts, rank)
        elif kind == "span_end":
            span = rec.get("span")
            d = rec.get("duration_s")
            if not isinstance(d, (int, float)) or d <= 0:
                return
            if span in RESTART_SPANS:
                self._ivs["restart"].append((ts - d, ts))
                self._widen(ts - d)
            elif span in CKPT_STALL_SPANS:
                self._stall(rec, ts, rank)
        elif kind == "compile_cache":
            outcome = str(rec.get("outcome", "?"))
            self._compile_cache[outcome] = self._compile_cache.get(outcome, 0) + 1
        elif kind == "incident_opened":
            self._open_incidents.setdefault(rec.get("incident_id"), ts)
        elif kind == "incident_closed":
            opened = self._open_incidents.pop(rec.get("incident_id"), None)
            if opened is None:
                # Open fell outside the stream slice: the closed record still
                # knows how far back the fault reaches.
                ttr = rec.get("time_to_recover_s")
                opened = ts - ttr if isinstance(ttr, (int, float)) else ts
            self._ivs["incident"].append((opened, ts))

    def _widen(self, ts: float) -> None:
        """Extend the observed wall-clock window. Duration-carrying records
        widen it backward too — an interval's start is evidence the job was
        already live then, even when it precedes the first record's ts (a
        stream sliced mid-span, or a span whose begin marker was lost)."""
        if self._min_ts is None or ts < self._min_ts:
            self._min_ts = ts
        if self._max_ts is None or ts > self._max_ts:
            self._max_ts = ts

    def _stall(self, rec: dict, ts: float, rank: Any) -> None:
        d = rec.get("duration_s")
        if isinstance(d, (int, float)) and d > 0:
            self._ivs["ckpt_stall"].append((ts - d, ts))
            self._widen(ts - d)
            if isinstance(rank, int):
                self._ranks[rank]["ckpt_stall_s"] += d

    # -- read ---------------------------------------------------------------

    def summary(self) -> dict:
        """The attribution document. Phase seconds + ``unattributed`` sum to
        ``wall_clock_s`` exactly (intervals are clipped to the observed
        window and overlaps resolved by severity)."""
        if self._min_ts is None or self._max_ts is None:
            return {
                "schema": SCHEMA, "wall_clock_s": 0.0, "window": None,
                "phases": {p: 0.0 for p in (*PHASES, "unattributed")},
                "goodput_ratio": 0.0, "steps": 0,
                "step_seconds_mean": None, "step_seconds_max": None,
                "ranks": {}, "compile_cache": {},
            }
        lo, hi = self._min_ts, self._max_ts
        wall = hi - lo
        # Still-open incident/restart windows are charged through
        # end-of-stream: a job that never recovered was not training.
        incident_raw = self._ivs["incident"] + [
            (opened, hi) for opened in self._open_incidents.values()
        ]
        restart_raw = list(self._ivs["restart"])
        if self._restart_open is not None:
            restart_raw.append((self._restart_open, hi))
        raw = {**self._ivs, "incident": incident_raw, "restart": restart_raw}
        occupied: list[tuple[float, float]] = []
        phases: dict[str, float] = {}
        for phase in PHASES:
            merged = merge_intervals(_clip(raw[phase], lo, hi))
            own = subtract_intervals(merged, occupied)
            phases[phase] = round(total_seconds(own), 6)
            occupied = merge_intervals(occupied + own)
        attributed = total_seconds(occupied)
        phases["unattributed"] = round(max(0.0, wall - attributed), 6)
        ranks = {
            str(r): {
                "wall_clock_s": round(rs["last_ts"] - rs["first_ts"], 6),
                "train_s": round(rs["train_s"], 6),
                "ckpt_stall_s": round(rs["ckpt_stall_s"], 6),
                "steps": int(rs["steps"]),
            }
            for r, rs in sorted(self._ranks.items())
        }
        return {
            "schema": SCHEMA,
            "wall_clock_s": round(wall, 6),
            "window": [lo, hi],
            "phases": phases,
            "goodput_ratio": round(phases["train"] / wall, 6) if wall > 0 else 0.0,
            "steps": self._steps,
            "step_seconds_mean": (
                round(self._step_sum / self._steps, 6) if self._steps else None
            ),
            "step_seconds_max": (
                round(self._step_max, 6) if self._steps else None
            ),
            "ranks": ranks,
            # Restart-attribution color: how many process starts found a warm
            # compilation cache (skipped re-compile) vs paid a cold one.
            "compile_cache": dict(sorted(self._compile_cache.items())),
        }

    def publish(
        self, record: Optional[Callable[..., None]] = None
    ) -> dict:
        """Emit per-phase attribution deltas since the previous publish as a
        ``goodput_update`` event (default: through ``events.record``, feeding
        every live sink AND the shared JSONL so post-hoc aggregation replays
        the identical totals). Deltas are clamped at zero: counters are
        monotonic, and late-arriving higher-severity evidence (an incident
        window swallowing already-published train time) skews one publish
        rather than ever un-counting. Returns the summary it published."""
        summary = self.summary()
        deltas = {}
        for phase, seconds in summary["phases"].items():
            d = seconds - self._published.get(phase, 0.0)
            if d > 1e-6:
                deltas[phase] = round(d, 6)
            self._published[phase] = max(seconds, self._published.get(phase, 0.0))
        if deltas:
            (record or events_mod.record)(
                "goodput", "goodput_update",
                phases=deltas, ratio=summary["goodput_ratio"],
                wall_clock_s=summary["wall_clock_s"], steps=summary["steps"],
            )
        return summary


COMPARE_SCHEMA = "tpu-goodput-compare-1"


def compare(a, b) -> dict:
    """Per-phase attribution deltas + ratio delta between two runs (``a``
    minus ``b``). Accepts :class:`GoodputLedger` instances or their
    :meth:`~GoodputLedger.summary` documents.

    This is the autoscale scenario's acceptance arithmetic — "did the
    controlled run beat the no-controller baseline of the same seed?" — and
    a standalone operator tool (``tpu-metrics-dump --goodput --baseline``):
    a positive ``ratio_delta`` means run ``a`` spent a larger fraction of
    its wall clock training."""
    sa = a.summary() if hasattr(a, "summary") else dict(a)
    sb = b.summary() if hasattr(b, "summary") else dict(b)
    pa, pb = sa.get("phases") or {}, sb.get("phases") or {}
    wa, wb = sa.get("wall_clock_s") or 0.0, sb.get("wall_clock_s") or 0.0
    phases = {
        p: round(pa.get(p, 0.0) - pb.get(p, 0.0), 6)
        for p in sorted(set(pa) | set(pb))
    }
    # Fractional deltas normalize away different wall clocks (a controlled
    # run that finishes sooner must not look worse for being shorter).
    phase_frac = {
        p: round(
            (pa.get(p, 0.0) / wa if wa > 0 else 0.0)
            - (pb.get(p, 0.0) / wb if wb > 0 else 0.0),
            6,
        )
        for p in phases
    }
    ra = sa.get("goodput_ratio") or 0.0
    rb = sb.get("goodput_ratio") or 0.0
    return {
        "schema": COMPARE_SCHEMA,
        "wall_clock_s": [round(wa, 6), round(wb, 6)],
        "goodput_ratio": [ra, rb],
        "ratio_delta": round(ra - rb, 6),
        "phases": phases,
        "phase_frac": phase_frac,
        "steps_delta": int((sa.get("steps") or 0) - (sb.get("steps") or 0)),
    }


def render_compare(cmp: dict, out=None, labels=("run", "baseline")) -> None:
    """Operator view of one :func:`compare` document."""
    import sys

    out = sys.stdout if out is None else out
    ra, rb = cmp.get("goodput_ratio") or [0.0, 0.0]
    wa, wb = cmp.get("wall_clock_s") or [0.0, 0.0]
    print(
        f"goodput {labels[0]} {ra:.3f} vs {labels[1]} {rb:.3f} "
        f"(delta {cmp.get('ratio_delta', 0.0):+.3f}; wall {wa:.1f}s vs "
        f"{wb:.1f}s)",
        file=out,
    )
    print("per-phase delta (seconds / share of wall):", file=out)
    fr = cmp.get("phase_frac") or {}
    for phase in ("train", "ckpt_stall", "restart", "incident", "unattributed"):
        if phase not in (cmp.get("phases") or {}):
            continue
        d = cmp["phases"][phase]
        print(
            f"    {phase:<13} {d:>+9.2f} s  {100.0 * fr.get(phase, 0.0):+6.1f}%",
            file=out,
        )
    print(f"steps delta: {cmp.get('steps_delta', 0):+d}", file=out)


def render_table(summary: dict, out=None) -> None:
    """The operator view of one attribution document (offline twin of the
    launcher's ``/goodput`` endpoint — same numbers, table form)."""
    import sys

    out = sys.stdout if out is None else out
    wall = summary.get("wall_clock_s") or 0.0
    ratio = summary.get("goodput_ratio") or 0.0
    phases = summary.get("phases") or {}
    print(
        f"goodput: {ratio:.3f} "
        f"(train {phases.get('train', 0.0):.1f} s / wall {wall:.1f} s)",
        file=out,
    )
    print(f"phase attribution (job wall clock {wall:.1f} s):", file=out)
    for phase in ("train", "ckpt_stall", "restart", "incident", "unattributed"):
        s = phases.get(phase, 0.0)
        pct = (100.0 * s / wall) if wall > 0 else 0.0
        print(f"    {phase:<13} {s:>9.2f} s  {pct:5.1f}%", file=out)
    steps = summary.get("steps") or 0
    if steps:
        mean = summary.get("step_seconds_mean")
        mean_txt = f"{mean * 1e3:.1f} ms" if mean is not None else "-"
        print(f"steps: {steps} (mean {mean_txt})", file=out)
    ranks = summary.get("ranks") or {}
    if ranks:
        print("per-rank:", file=out)
        for r, rs in sorted(ranks.items(), key=lambda kv: int(kv[0])):
            print(
                f"    rank {r}: wall {rs['wall_clock_s']:.1f} s "
                f"train {rs['train_s']:.1f} s "
                f"ckpt {rs['ckpt_stall_s']:.2f} s steps {rs['steps']}",
                file=out,
            )
