"""Nested debug timers: lightweight elapsed-time logging for slow paths.

Analogue of the reference's ``debug_time`` context/decorator
(``checkpointing/utils.py:35-83``), used across its checkpoint machinery: nested
scopes log at DEBUG with indentation showing the call tree, so a slow save
decomposes at a glance (serialize → replicate → write → finalize). Also feeds a
``timing`` record into the structured event stream when a sink is attached.

Usage::

    from tpu_resiliency.utils.timers import debug_time

    with debug_time("save"):
        with debug_time("serialize"):
            ...
        with debug_time("replicate"):
            ...

    @debug_time("finalize")
    def _finalize(...): ...
"""

from __future__ import annotations

import functools
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

_depth = threading.local()


@contextmanager
def _timed(name: str, source: str):
    depth = getattr(_depth, "value", 0)
    _depth.value = depth + 1
    t0 = time.perf_counter()
    try:
        yield
        failure = None
    except BaseException as e:
        failure = repr(e)
        raise
    finally:
        _depth.value = depth
        elapsed = time.perf_counter() - t0
        log.debug("%s%s: %.3f ms", "  " * depth, name, elapsed * 1e3)
        if depth == 0:
            # Only roots go to the event stream; nested scopes stay in the log.
            # A raised block reports ok=False with the error (events.prof parity).
            record_event(
                source, "timing", name=name, duration_s=elapsed,
                ok=failure is None, **({"error": failure} if failure else {}),
            )


def debug_time(name: Optional[str] = None, source: str = "timer"):
    """Context manager when called with a name; decorator when applied to a fn."""
    if callable(name):  # bare @debug_time
        fn = name
        return debug_time(fn.__name__, source)(fn)

    def as_decorator(fn: Callable):
        label = name or getattr(fn, "__name__", "block")

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with _timed(label, source):
                return fn(*args, **kwargs)

        return wrapped

    class _Both:
        """Usable as ``with debug_time("x"):`` and ``@debug_time("x")``. Safe to
        share across threads: each ``with`` entry gets its own context manager
        (thread-local stack), so concurrent scopes never clobber each other."""

        def __init__(self):
            self._local = threading.local()

        def __call__(self, fn: Callable):
            return as_decorator(fn)

        def __enter__(self):
            cm = _timed(name or "block", source)
            stack = getattr(self._local, "stack", None)
            if stack is None:
                stack = self._local.stack = []
            stack.append(cm)
            return cm.__enter__()

        def __exit__(self, *exc):
            return self._local.stack.pop().__exit__(*exc)

    return _Both()
