"""Byte-flow ledger: one account of every byte the framework moves.

Bytes moved are the currency of ROADMAP's remaining perf work — erasure/delta
replication promises "5-10× fewer bytes per save", reshard promises ranged
fetches instead of whole mirrors — but until this module the evidence was
scattered across four unrelated metric families
(``tpu_ckpt_replication_bytes_total``, ``tpu_ckpt_write_bytes_total``,
``tpu_reshard_bytes_total``, ``tpu_store_bytes_total``) with no common
attribution. The :class:`ByteFlowLedger` is the ``GoodputLedger`` of bytes: a
reducer over the same event stream everything else consumes (live tail or
finished JSONL) that attributes every observed byte to a **(purpose,
direction, peer)** triple and reconciles its own totals against the per-family
counters — the *unaccounted residue is itself a metric*
(``tpu_byteflow_residue_bytes`` / ``tpu_byteflow_accounted_ratio``), because a
byte the instrumentation cannot explain is exactly the kind of byte a 5-10×
reduction claim would silently hide behind.

Attribution sources (all existing emitters; one new field — ``p2p_transfer``
events now carry their transfer ``tag``, whose prefix names the purpose):

======================  =========  ===========================================
event                   purpose    evidence
======================  =========  ===========================================
``p2p_transfer``        replicate  tag ``repl/`` or ``remir/`` (mirror fan-out)
``p2p_transfer``        retrieve   tag ``retr/`` (post-loss shard routing)
``p2p_transfer``        reshard    tag ``rread/`` (ranged-read wire op)
``p2p_transfer``        unknown    tag absent/foreign — the residue
``reshard_fetch``       reshard    assembled bytes, ``via`` local | peer
``ckpt_write_file``     ckpt_write container bytes to disk
``store_stats``         store      coordination-store wire bytes in/out
======================  =========  ===========================================

Surfaces: ``tpu-metrics-dump EVENTS --bytes`` renders the account (table or
``tpu-byteflow-1`` JSON), the launcher's :class:`TelemetryServer` feeds a live
ledger on every refresh and publishes deltas as ``byteflow_update`` events →
``tpu_byteflow_bytes_total{purpose,direction}`` through ``observe_record``, so
the live and post-hoc views agree; the chaos scenarios (``scenario_disk``,
``scenario_elastic``) gate on ``accounted_ratio ≥ 0.95``.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from tpu_resiliency.utils import events as events_mod

SCHEMA = "tpu-byteflow-1"

#: transfer-tag prefix → purpose (the p2p wire attribution table). Order
#: matters only for docs; prefixes are disjoint.
TAG_PURPOSES = (
    ("repl/", "replicate"),
    ("remir/", "replicate"),
    ("retr/", "retrieve"),
    ("rread/", "reshard"),
)

#: every purpose the ledger can emit (``unknown`` is the residue bucket)
PURPOSES = ("replicate", "retrieve", "reshard", "store", "ckpt_write", "unknown")

#: the per-family byte counters the ledger reconciles against — family name →
#: (counter family, how the ledger's rows map onto it)
FAMILIES = {
    "p2p": "tpu_ckpt_replication_bytes_total",
    "reshard": "tpu_reshard_bytes_total",
    "ckpt_write": "tpu_ckpt_write_bytes_total",
    "store": "tpu_store_bytes_total",
}


def tag_purpose(tag) -> str:
    if isinstance(tag, str):
        for prefix, purpose in TAG_PURPOSES:
            if tag.startswith(prefix):
                return purpose
    return "unknown"


class ByteFlowLedger:
    """Streamed byte attribution over event records (flat JSONL dict shape).

    Feed with :meth:`observe` / :meth:`observe_many`; read with
    :meth:`summary`; route deltas into the metrics plane with
    :meth:`publish`. Cheap per record: dict increments only."""

    def __init__(self) -> None:
        #: (purpose, direction, peer) -> [bytes, events]
        self._flows: dict[tuple[str, str, str], list] = {}
        #: family -> {"total": bytes, "attributed": bytes}
        self._families: dict[str, dict[str, int]] = {
            f: {"total": 0, "attributed": 0} for f in FAMILIES
        }
        #: per-(purpose/direction) bytes already published as deltas
        self._published: dict[str, float] = {}
        self._published_residue = 0.0

    # -- ingest -------------------------------------------------------------

    def observe_many(self, recs: Iterable[dict]) -> None:
        for rec in recs:
            if isinstance(rec, dict):
                self.observe(rec)

    def observe(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "byteflow_update":
            return  # our own narration is derived, not evidence
        if kind == "p2p_transfer":
            nbytes = rec.get("bytes")
            if not isinstance(nbytes, (int, float)) or nbytes <= 0:
                return
            direction = str(rec.get("direction", "?"))
            purpose = tag_purpose(rec.get("tag"))
            peer = rec.get("dst") if direction == "send" else rec.get("src")
            self._add(purpose, direction, _peer(peer), int(nbytes))
            fam = self._families["p2p"]
            fam["total"] += int(nbytes)
            if purpose != "unknown":
                fam["attributed"] += int(nbytes)
        elif kind == "reshard_fetch":
            nbytes = rec.get("bytes")
            if not isinstance(nbytes, (int, float)) or nbytes <= 0:
                return
            via = str(rec.get("via", "?"))
            # local = container slice read off this rank's own disk; peer =
            # the logical payload of ranged wire fetches (whose wire frames
            # are ALSO visible as rread/-tagged p2p rows — logical vs wire
            # views of the same move, kept as separate directions on purpose).
            direction = "read" if via == "local" else "fetch"
            peer = rec.get("holder") if via == "peer" else "local"
            self._add("reshard", direction, _peer(peer), int(nbytes))
            fam = self._families["reshard"]
            fam["total"] += int(nbytes)
            fam["attributed"] += int(nbytes)
        elif kind == "ckpt_write_file":
            nbytes = rec.get("bytes")
            if not isinstance(nbytes, (int, float)) or nbytes <= 0:
                return
            self._add(
                "ckpt_write", "write", str(rec.get("container", "?")),
                int(nbytes),
            )
            fam = self._families["ckpt_write"]
            fam["total"] += int(nbytes)
            fam["attributed"] += int(nbytes)
        elif kind == "store_stats":
            for field, direction in (("bytes_in", "in"), ("bytes_out", "out")):
                v = rec.get(field)
                if isinstance(v, (int, float)) and v > 0:
                    self._add("store", direction, "store", int(v))
                    fam = self._families["store"]
                    fam["total"] += int(v)
                    fam["attributed"] += int(v)

    def _add(self, purpose: str, direction: str, peer: str, nbytes: int) -> None:
        row = self._flows.get((purpose, direction, peer))
        if row is None:
            row = self._flows[(purpose, direction, peer)] = [0, 0]
        row[0] += nbytes
        row[1] += 1

    # -- read ---------------------------------------------------------------

    def summary(self) -> dict:
        """The attribution document (schema ``tpu-byteflow-1``)."""
        flows = [
            {
                "purpose": p, "direction": d, "peer": peer,
                "bytes": row[0], "events": row[1],
            }
            for (p, d, peer), row in sorted(
                self._flows.items(), key=lambda kv: (-kv[1][0], kv[0])
            )
        ]
        by_purpose = {p: 0 for p in PURPOSES}
        for f in flows:
            by_purpose[f["purpose"]] = by_purpose.get(f["purpose"], 0) + f["bytes"]
        by_purpose = {p: b for p, b in by_purpose.items() if b}
        families = {}
        total = attributed = 0
        for name, fam in sorted(self._families.items()):
            residue = fam["total"] - fam["attributed"]
            families[name] = {
                "counter": FAMILIES[name],
                "total": fam["total"],
                "attributed": fam["attributed"],
                "residue": residue,
                "residue_frac": (
                    round(residue / fam["total"], 6) if fam["total"] else 0.0
                ),
            }
            total += fam["total"]
            attributed += fam["attributed"]
        return {
            "schema": SCHEMA,
            "total_bytes": total,
            "attributed_bytes": attributed,
            "residue_bytes": total - attributed,
            "accounted_frac": round(attributed / total, 6) if total else 1.0,
            "by_purpose": by_purpose,
            "flows": flows,
            "families": families,
        }

    def reconcile(self, registry) -> dict:
        """Cross-check ledger family totals against a
        :class:`~tpu_resiliency.utils.metrics.MetricsRegistry` built from the
        same stream: both derive from one event set through independent code
        paths, so any drift means an emitter the ledger (or the counter
        mapping) does not understand. Returns per-family
        ``{counter, ledger, drift}`` rows."""
        snap = registry.snapshot().get("metrics") or {}
        out = {}
        for name, fam in sorted(self._families.items()):
            counter_total = sum(
                e.get("value") or 0.0 for e in snap.get(FAMILIES[name]) or []
            )
            out[name] = {
                "counter": FAMILIES[name],
                "counter_bytes": counter_total,
                "ledger_bytes": fam["total"],
                "drift_bytes": round(counter_total - fam["total"], 3),
            }
        return out

    def publish(self, record: Optional[Callable[..., None]] = None) -> dict:
        """Emit per-flow byte deltas since the previous publish as ONE
        ``byteflow_update`` event (default: through ``events.record``), the
        ``goodput_update`` discipline — replaying the stream reconstructs the
        live ``tpu_byteflow_*`` totals exactly. Returns the summary."""
        summary = self.summary()
        deltas: dict[str, int] = {}
        for (p, d, _peer_), row in self._flows.items():
            key = f"{p}/{d}"
            deltas[key] = deltas.get(key, 0) + row[0]
        moved = {}
        for key, total in sorted(deltas.items()):
            delta = total - self._published.get(key, 0)
            if delta > 0:
                moved[key] = delta
            self._published[key] = total
        residue_delta = summary["residue_bytes"] - self._published_residue
        self._published_residue = max(
            summary["residue_bytes"], self._published_residue
        )
        if moved or residue_delta > 0:
            (record or events_mod.record)(
                "byteflow", "byteflow_update",
                flows=moved,
                residue_bytes=max(0, residue_delta),
                accounted_ratio=summary["accounted_frac"],
                total_bytes=summary["total_bytes"],
            )
        return summary


def _peer(peer) -> str:
    if peer is None:
        return "?"
    return f"r{peer}" if isinstance(peer, int) else str(peer)


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GB"


def render_table(summary: dict, out=None, reconcile: Optional[dict] = None) -> None:
    """Operator view of one attribution document (the ``--bytes`` report)."""
    import sys

    out = sys.stdout if out is None else out
    total = summary.get("total_bytes") or 0
    frac = summary.get("accounted_frac")
    print(
        f"byte flow: {_fmt_bytes(total)} observed, "
        f"{100.0 * (frac or 0.0):.1f}% attributed "
        f"(residue {_fmt_bytes(summary.get('residue_bytes') or 0)})",
        file=out,
    )
    by_purpose = summary.get("by_purpose") or {}
    if by_purpose:
        print("by purpose:", file=out)
        for p in sorted(by_purpose, key=lambda k: -by_purpose[k]):
            share = 100.0 * by_purpose[p] / total if total else 0.0
            print(f"    {p:<11} {_fmt_bytes(by_purpose[p]):>12}  {share:5.1f}%",
                  file=out)
    flows = summary.get("flows") or []
    if flows:
        print("flows (purpose / direction / peer):", file=out)
        for f in flows[:20]:
            print(
                f"    {f['purpose']:<11} {f['direction']:<6} "
                f"{str(f['peer']):<10} {_fmt_bytes(f['bytes']):>12} "
                f"({f['events']} events)",
                file=out,
            )
        if len(flows) > 20:
            print(f"    ... {len(flows) - 20} more flows", file=out)
    fams = summary.get("families") or {}
    if fams:
        print("reconciliation vs metric families:", file=out)
        for name, fam in sorted(fams.items()):
            line = (
                f"    {fam['counter']:<36} {_fmt_bytes(fam['total']):>12}"
                f"  residue {_fmt_bytes(fam['residue'])}"
                f" ({100.0 * fam['residue_frac']:.1f}%)"
            )
            if reconcile and name in reconcile:
                drift = reconcile[name]["drift_bytes"]
                line += f"  counter drift {_fmt_bytes(drift)}"
            print(line, file=out)
