"""Lock-cheap per-operation accounting for the coordination store.

``platform/store.py`` is the plane every subsystem leans on — rendezvous,
barriers, metrics push, reshard holder-gather all ride it — and until this
module it exported zero self-telemetry: proving "the store is slow" meant
strace. :class:`OpStats` is the collector the store's event loop feeds inline:
per-op latency histograms split into **queue wait** (bytes on the socket →
dispatch) and **handle time** (the dispatch itself, parks excluded), bytes
in/out, live/peak connection counts, the request-dedup LRU hit rate, and a
top-K hot-key-prefix table kept by a space-saving sketch — bounded memory, no
unbounded per-key dict, no locks (the single loop thread owns every mutation;
``snapshot()`` reads are torn-tolerant by design, the way the loop's other
introspection ops already are).

Surfaces (see ``docs/observability.md``):

- the idempotent ``store_stats`` wire op → the ``tpu-store-stats-1`` document
  (:meth:`OpStats.snapshot` + the server's live conn/park counts);
- ``GET /storez`` on the launcher's :class:`TelemetryServer` (schema
  ``tpu-storez-1``), folded into ``/snapshot`` so fleetd gets it for free;
- periodic ``store_stats`` *events* carrying per-op deltas
  (:meth:`OpStats.take_deltas`) → ``tpu_store_ops_total{op}``,
  ``tpu_store_op_seconds{op}``, ``tpu_store_bytes_total{direction}``,
  ``tpu_store_conns`` through ``observe_record``, so the live Prometheus view
  and a post-hoc aggregation of the same stream agree;
- ``tpu-store-info ENDPOINT --stats`` renders the live document.

A broken collector must never break the op path: the store calls every method
through a containment shim that disables stats (and degrades the document to
an ``error`` field) on the first exception.
"""

from __future__ import annotations

import bisect
import time
from typing import Any, Iterable, Optional

SCHEMA = "tpu-store-stats-1"

#: Latency bucket upper bounds (seconds) tuned for an in-memory event-loop
#: store: dict-op dispatch is microseconds, a loaded loop's queue wait is
#: tens of microseconds to milliseconds, and anything beyond a second means
#: the loop is wedged behind something it should never be behind.
LATENCY_BOUNDS = (
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 5.0, 30.0,
)


class LatencyHist:
    """Fixed-bound bucket histogram: O(log buckets) observe, O(buckets) read.

    No reservoir, no lock — this runs inside the store's event loop where
    every nanosecond is tax on every op. Quantiles are bucket-interpolated
    (the Prometheus ``histogram_quantile`` estimate), which is exactly enough
    resolution to answer "p95 handle time by op"."""

    __slots__ = ("bounds", "counts", "count", "sum", "max")

    def __init__(self, bounds: Iterable[float] = LATENCY_BOUNDS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +Inf tail
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def observe(self, v: float) -> None:
        v = v if v > 0.0 else 0.0
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        if v > self.max:
            self.max = v

    def quantile(self, q: float) -> float:
        """Linear-interpolated bucket quantile; 0.0 when empty."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        cum = 0
        for i, n in enumerate(self.counts):
            if n == 0:
                continue
            if cum + n >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else self.max
                frac = (rank - cum) / n
                return lo + (hi - lo) * frac
            cum += n
        return self.max

    def doc(self) -> dict:
        return {
            "count": self.count,
            "sum_s": round(self.sum, 9),
            "p50_us": round(self.quantile(0.50) * 1e6, 3),
            "p95_us": round(self.quantile(0.95) * 1e6, 3),
            "p99_us": round(self.quantile(0.99) * 1e6, 3),
            "max_us": round(self.max * 1e6, 3),
        }


class SpaceSaving:
    """Misra-Gries / space-saving top-K frequency sketch.

    Tracks at most ``k`` keys; an unseen key evicts the current minimum and
    inherits its count as over-estimation ``err``. Every reported count is
    within ``err`` of the true count, and any key with true frequency above
    ``total/k`` is guaranteed present — exactly the guarantee a hot-key table
    needs, at k dict entries instead of one per key ever touched."""

    __slots__ = ("k", "counts", "errors", "total")

    def __init__(self, k: int = 32):
        self.k = k
        self.counts: dict[str, int] = {}
        self.errors: dict[str, int] = {}
        self.total = 0

    def add(self, key: str, weight: int = 1) -> None:
        self.total += weight
        counts = self.counts
        if key in counts:
            counts[key] += weight
            return
        if len(counts) < self.k:
            counts[key] = weight
            self.errors[key] = 0
            return
        victim = min(counts, key=counts.__getitem__)
        floor = counts.pop(victim)
        self.errors.pop(victim, None)
        counts[key] = floor + weight
        self.errors[key] = floor

    def items(self, top: Optional[int] = None) -> list[dict]:
        ranked = sorted(self.counts.items(), key=lambda kv: -kv[1])
        if top is not None:
            ranked = ranked[:top]
        return [
            {"prefix": key, "count": n, "err": self.errors.get(key, 0)}
            for key, n in ranked
        ]


def key_prefix(key: str, depth: int = 2) -> str:
    """The first ``depth`` path segments of a store key — the granularity the
    hot-prefix table aggregates at (``jobmetrics/<rdzv-id>``, not every
    per-incarnation leaf key)."""
    parts = key.split("/")
    return "/".join(parts[:depth]) if len(parts) > depth else key


class OpStats:
    """Per-op accounting fed by the store's single loop thread.

    Not thread-safe on purpose: the owner is the event loop, and a lock here
    would be pure tax on every op. Cross-thread readers (none today — the
    ``store_stats`` op runs on the loop) would at worst see a torn-but-valid
    snapshot."""

    #: 1-in-N sampling for the WHOLE collector: the server calls
    #: :meth:`note_op` for one op in SAMPLE and pays a single counter
    #: decrement for the rest — no clock read, no dict traffic. Every tally
    #: (count, errors, bytes) is scaled by SAMPLE back into op/byte units,
    #: so the documents read naturally but carry ±SAMPLE granularity: a hot
    #: op's figures are statistically exact, an op called twice ever may
    #: show 0 or 16 (one sample's weight). That trade is deliberate — exact per-op accounting was
    #: measured at 2-4 µs/op of py3.10 attribute traffic in situ, >5% of a
    #: ~35 µs loopback op (scripts/bench_store.py's overhead leg is the
    #: regression gate), and the rare-op forensics live elsewhere anyway
    #: (``barrier_census``, the exact live conn/park counts in the doc).
    SAMPLE = 16

    def __init__(self, top_k: int = 32):
        self.started_at = time.time()
        #: op -> [count, errors, bytes_in], sampled-scaled (op/byte units,
        #: ±SAMPLE granularity — see :data:`SAMPLE`)
        self.rows: dict[Any, list] = {}
        self._handle: dict[str, LatencyHist] = {}
        self._wait: dict[str, LatencyHist] = {}
        self.bytes_out = 0
        self.conns_total = 0
        self.conns_peak = 0
        self.dedup_hits = 0
        self.dedup_lookups = 0
        self.hot = SpaceSaving(top_k)
        #: per-counter values already reported by :meth:`take_deltas`
        self._published: dict[str, Any] = {
            "ops": {}, "op_seconds": {}, "bytes_in": 0, "bytes_out": 0,
        }

    # -- ingest (loop thread) ----------------------------------------------

    def note_conn(self, live: int) -> None:
        self.conns_total += 1
        if live > self.conns_peak:
            self.conns_peak = live

    def note_dedup(self, hit: bool) -> None:
        self.dedup_lookups += 1
        if hit:
            self.dedup_hits += 1

    def row_for(self, op) -> list:
        """Create-or-get the tally row for ``op`` (sampled-scaled
        [count, errors, bytes_in] — see :data:`SAMPLE`)."""
        if not isinstance(op, str):
            op = str(op)
        row = self.rows.get(op)
        if row is None:
            row = self.rows[op] = [0, 0, 0]
            self._handle[op] = LatencyHist()
            self._wait[op] = LatencyHist()
        return row

    def note_op(
        self,
        op: str,
        wait_s: float,
        handle_s: float,
        bytes_in: int,
        req: Optional[dict] = None,
        error: bool = False,
    ) -> None:
        """The SAMPLED arm — called for 1 op in :data:`SAMPLE`, so every
        tally is scaled by :data:`SAMPLE` to stay in op/byte units. Latency
        histograms and the hot-prefix sketch ride the same sample."""
        if not isinstance(op, str):
            op = str(op)
        row = self.rows.get(op)
        if row is None:
            row = self.row_for(op)
        row[0] += self.SAMPLE
        if error:
            row[1] += self.SAMPLE
        row[2] += bytes_in * self.SAMPLE
        self._handle[op].observe(handle_s)
        if wait_s >= 0.0:
            self._wait[op].observe(wait_s)
        if req is not None:
            key = req.get("key") or req.get("prefix") or req.get("name")
            if key:
                self.hot.add(key_prefix(str(key)), self.SAMPLE)

    @property
    def bytes_in(self) -> int:
        # Summed at read time, not accumulated per op — one fewer write on
        # the hot path; per-op rows already carry the exact figure.
        return sum(row[2] for row in self.rows.values())

    # -- read --------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``tpu-store-stats-1`` document body (the server adds its live
        conn/park/table counts on top)."""
        ops = {}
        for op in sorted(self.rows, key=str):
            count, errors, b_in = self.rows[op]
            ops[op] = {
                "count": count,
                "errors": errors,
                "bytes_in": b_in,
                # Sampled-scaled estimate of total handle seconds (every
                # figure in this table is 1-in-SAMPLE sampled, scaled back
                # to op/byte/second units).
                "seconds": round(self._handle[op].sum * self.SAMPLE, 9),
                "handle": self._handle[op].doc(),
                "wait": self._wait[op].doc(),
            }
        return {
            "schema": SCHEMA,
            "enabled": True,
            "sample": self.SAMPLE,
            "uptime_s": round(time.time() - self.started_at, 3),
            "bytes": {"in": self.bytes_in, "out": self.bytes_out},
            "conns_total": self.conns_total,
            "conns_peak": self.conns_peak,
            "dedup": {
                "hits": self.dedup_hits,
                "lookups": self.dedup_lookups,
                "hit_rate": (
                    round(self.dedup_hits / self.dedup_lookups, 6)
                    if self.dedup_lookups else 0.0
                ),
            },
            "ops": ops,
            "hot_prefixes": self.hot.items(top=16),
        }

    def take_deltas(self) -> Optional[dict]:
        """Counter movement since the previous call, for the periodic
        ``store_stats`` event — replaying the deltas reconstructs the same
        monotonic totals the live view holds (the ``goodput_update``
        discipline). Returns ``None`` when nothing moved."""
        pub = self._published
        ops: dict[str, int] = {}
        op_seconds: dict[str, float] = {}
        for op, row in self.rows.items():
            d = row[0] - pub["ops"].get(op, 0)
            if d > 0:
                ops[op] = d
                pub["ops"][op] = row[0]
            # sampled-scaled estimate (the only clocked figure in the event)
            est = self._handle[op].sum * self.SAMPLE
            ds = est - pub["op_seconds"].get(op, 0.0)
            if ds > 1e-9:
                op_seconds[op] = round(ds, 9)
                pub["op_seconds"][op] = est
        d_in = self.bytes_in - pub["bytes_in"]
        d_out = self.bytes_out - pub["bytes_out"]
        pub["bytes_in"] = self.bytes_in
        pub["bytes_out"] = self.bytes_out
        if not ops and d_in <= 0 and d_out <= 0:
            return None
        out: dict[str, Any] = {"ops": ops, "op_seconds": op_seconds}
        if d_in > 0:
            out["bytes_in"] = d_in
        if d_out > 0:
            out["bytes_out"] = d_out
        return out


def merge_stats_docs(
    docs: list[dict],
    successor_map: Optional[dict[int, int]] = None,
    failover_ops: Optional[dict[int, int]] = None,
) -> dict:
    """Fold per-shard ``tpu-store-stats-1`` documents into one clique view
    (``ShardedKVClient.store_stats`` and ``tpu-store-info --stats`` over a
    sharded endpoint list).

    Merge algebra mirrors the mergeable metrics registry: counters sum
    (op counts, errors, bytes, seconds, conns, dedup, keys, parked), gauges
    take the documented extreme (``uptime_s`` max). Quantiles cannot be
    re-derived from per-shard summaries, so the aggregate reports the
    **worst shard** per op (``p50/p95/p99/max`` maxima) — conservative for
    alerting, and each shard's exact document survives in the per-shard
    ``shards`` table the callers fold in alongside. ``backend`` merges to the
    single common value or a comma-joined set when shards disagree
    (mid-rolling-upgrade cliques render honestly instead of guessing).

    HA accounting: ``successor_map`` (shard → successor index, from a
    replicating clique client) annotates each unreachable shard's row with
    ``absorbed_by`` (the successor now serving its keyspace) and the
    successor's row with ``absorbing`` — and ``failover_ops`` (shard →
    client-observed failover count against that shard) lands as
    ``failover_ops`` **on the successor's row**, so ops that the dead shard
    can no longer report are counted where they were actually served instead
    of silently dropped: the clique-total the ``<5%`` opstats overhead gate
    reads stays a true total during degraded operation. The successor's own
    served-op counters already include the absorbed traffic (it served it);
    ``failover_ops`` is the *attribution* column, never double-summed into
    ``ops_total``.
    """
    enabled = [d for d in docs if d.get("enabled")]
    backends = sorted({
        str(d.get("backend", "threaded")) for d in docs if d.get("enabled")
    })
    out: dict[str, Any] = {
        "schema": SCHEMA,
        "enabled": bool(enabled),
        "aggregate_of": len(docs),
        "backend": ",".join(backends) if backends else "unknown",
        "uptime_s": max((d.get("uptime_s", 0.0) for d in enabled), default=0.0),
        "sample": max((d.get("sample", 0) for d in enabled), default=0),
    }
    for counter in ("conns", "parked", "barriers_open", "keys",
                    "dedup_entries", "conns_total", "conns_peak"):
        out[counter] = sum(int(d.get(counter, 0) or 0) for d in docs)
    out["bytes"] = {
        "in": sum((d.get("bytes") or {}).get("in", 0) for d in enabled),
        "out": sum((d.get("bytes") or {}).get("out", 0) for d in enabled),
    }
    hits = sum((d.get("dedup") or {}).get("hits", 0) for d in enabled)
    lookups = sum((d.get("dedup") or {}).get("lookups", 0) for d in enabled)
    out["dedup"] = {
        "hits": hits, "lookups": lookups,
        "hit_rate": round(hits / lookups, 6) if lookups else 0.0,
    }
    ops: dict[str, dict] = {}
    for d in enabled:
        for op, row in (d.get("ops") or {}).items():
            agg = ops.setdefault(op, {
                "count": 0, "errors": 0, "bytes_in": 0, "seconds": 0.0,
                "handle": {"count": 0, "p50_us": 0.0, "p95_us": 0.0,
                           "p99_us": 0.0, "max_us": 0.0},
                "wait": {"count": 0, "p50_us": 0.0, "p95_us": 0.0,
                         "p99_us": 0.0, "max_us": 0.0},
            })
            for k in ("count", "errors", "bytes_in"):
                agg[k] += row.get(k, 0)
            agg["seconds"] = round(agg["seconds"] + row.get("seconds", 0.0), 9)
            for split in ("handle", "wait"):
                src = row.get(split) or {}
                dst = agg[split]
                dst["count"] += src.get("count", 0)
                for q in ("p50_us", "p95_us", "p99_us", "max_us"):
                    dst[q] = max(dst[q], src.get(q, 0.0))
    out["ops"] = {op: ops[op] for op in sorted(ops)}
    hot = SpaceSaving(32)
    for d in enabled:
        for row in d.get("hot_prefixes") or []:
            try:
                hot.add(str(row["prefix"]), int(row["count"]))
            except (KeyError, TypeError, ValueError):
                continue
    out["hot_prefixes"] = hot.items(top=16)
    rows = [
        {
            "endpoint": d.get("endpoint", f"#{i}"),
            "enabled": bool(d.get("enabled")),
            # A doc with neither backend nor live conn counts never came from
            # a server at all (transport failure row); a reachable pre-epoll
            # server simply lacks the field.
            "backend": "unreachable"
            if "backend" not in d and "conns" not in d
            else str(d.get("backend", "threaded")),
            "ops_total": sum(
                r.get("count", 0) for r in (d.get("ops") or {}).values()
            ),
            "errors_total": sum(
                r.get("errors", 0) for r in (d.get("ops") or {}).values()
            ),
            "bytes_in": (d.get("bytes") or {}).get("in", 0),
            "bytes_out": (d.get("bytes") or {}).get("out", 0),
            "conns": d.get("conns", 0),
            "parked": d.get("parked", 0),
            "keys": d.get("keys", 0),
            **({"error": d["error"]} if d.get("error") else {}),
        }
        for i, d in enumerate(docs)
    ]
    if successor_map:
        for i, row in enumerate(rows):
            if row["backend"] != "unreachable":
                continue
            succ = successor_map.get(i)
            if succ is None or succ == i or not (0 <= succ < len(rows)):
                continue
            row["absorbed_by"] = rows[succ]["endpoint"]
            absorbing = rows[succ].setdefault("absorbing", [])
            absorbing.append(row["endpoint"])
    if failover_ops:
        total = 0
        for i, n_ops in sorted(failover_ops.items()):
            if n_ops <= 0:
                continue
            total += int(n_ops)
            succ = (successor_map or {}).get(i)
            tgt = succ if succ is not None and 0 <= succ < len(rows) else None
            if tgt is not None and tgt != i:
                rows[tgt]["failover_ops"] = (
                    rows[tgt].get("failover_ops", 0) + int(n_ops)
                )
        if total:
            out["failover"] = {
                "ops": total,
                "by_shard": {
                    int(i): int(n) for i, n in sorted(failover_ops.items()) if n > 0
                },
            }
    out["shards"] = rows
    return out
