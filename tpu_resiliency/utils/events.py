"""Structured event stream: machine-consumable records of the resiliency story.

Analogue of the reference's torchelastic events/metrics layer
(``fault_tolerance/_torch_elastic_compat/events/__init__.py`` — structured event
records with pluggable handlers — and ``metrics/api.py``'s ``@prof`` timing
decorator, used at ``launcher.py:247,548,640``). Log lines tell humans what
happened; this stream tells machines: every rendezvous round, restart, fault
detection, checkpoint save, and degraded-set transition is one self-describing
record.

Instrumented as built (the canonical emitter set — one record per decision):

- ``launcher``: rendezvous rounds, worker failures/promotions, restart requests,
  restart-budget charges, round success, control requests, budget exhaustion,
  SIGKILL escalation, ``launcher.job``/``launcher.round``/``worker.spawn`` spans.
- ``rendezvous``: round open/reopen/close records and the ``rendezvous.round``
  wait span.
- ``watchdog``: hang/health terminations, kill-ladder steps, per-rank heartbeat
  statistics on disconnect.
- ``inprocess``: iteration starts, restart signals, fn exceptions, rank
  terminations, stand-downs, completion, plus ``inprocess.restart`` and barrier
  spans.
- ``checkpoint``: save/load phase timings (d2h, serialize, replicate, write),
  ``ckpt_saved``/``ckpt_save_incomplete`` with byte counts, group rebuilds,
  async-save scheduling.
- ``ft``/``straggler``/``preemption`` (integrations): timeout calibrations,
  straggler reports, preemption sync points, training-finished markers.
- ``incident``/``remediation``/``flight`` (the incident plane,
  ``launcher/incident.py`` + ``telemetry/remediation.py`` +
  ``utils/flight_recorder.py``): incident open/close with SLO timings,
  remediation decisions and per-action outcomes, flight-recorder flushes.

Design:

- :class:`Event`: ``(ts, source, kind, payload)`` plus process identity (pid, rank
  when known) and, when tracing is active, ``trace_id``/``span_id`` causal context
  (``utils/tracing.py``) — everything JSON-serializable.
- Pluggable sinks registered per process (``add_sink``); the default wiring is
  environment-driven: ``TPU_RESILIENCY_EVENTS_FILE=<path>`` attaches a JSONL sink,
  so a launcher enables one stream for itself and every worker it spawns by
  exporting a single variable. JSONL lines are written in one ``write()`` call —
  atomic under POSIX append semantics for lines < PIPE_BUF, so all processes of a
  node can share one file.
- ``record(source, kind, **payload)``: fire-and-forget; a sink failure never
  breaks the workload (events are observability, not control flow).
- ``@prof``: times a callable and records a ``timing`` event with success/failure,
  the reference's ``@prof`` metric decorator.
- Consumers: ``tools/events_summary.py`` (timeline), ``tools/trace_export.py``
  (Chrome/Perfetto trace), ``tools/metrics_dump.py`` + ``utils/metrics.py``
  (aggregation); see ``docs/observability.md``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

EVENTS_FILE_ENV = "TPU_RESILIENCY_EVENTS_FILE"
#: Set to a path to ALSO bridge every record into the metrics registry and
#: snapshot it as JSON (``utils/metrics.py``); ``<pid>`` is inserted before the
#: extension so each process of a node drops its own snapshot (no clobbering).
METRICS_FILE_ENV = "TPU_RESILIENCY_METRICS_FILE"
#: Set to a directory to ALSO keep a crash-surviving flight-recorder ring of
#: this process's recent events (``utils/flight_recorder.py``) — the incident
#: plane's last-seconds-before-death record, persisted continuously so even a
#: SIGKILL leaves a dump behind.
FLIGHT_DIR_ENV = "TPU_RESILIENCY_FLIGHT_DIR"
#: Set to ``host:port[:prefix]`` to ALSO publish this process's metrics
#: snapshot to the coordination store every few seconds
#: (``utils/metrics.py:MetricsPublisher``) — the goodput plane's push path:
#: the launcher's telemetry endpoint merges the published snapshots into one
#: job-level view instead of scraping every rank's files.
METRICS_PUSH_ENV = "TPU_RESILIENCY_METRICS_PUSH"
#: Set to a job identity (the launcher exports its --rdzv-id when --fleet-dir
#: is on) to stamp ``job`` into every event's envelope. Fleet-scope consumers
#: (``tools/fleetd.py``, ``tpu-metrics-dump --job``, ``tpu-events-summary
#: --job``) use it to slice a stream several jobs share back to one job.
JOB_ENV = "TPU_RESILIENCY_JOB"

#: Envelope keys every JSONL record carries; payload keys that collide are
#: renamed ``p_<key>`` by ``to_json``. Consumers (events_summary, trace_export)
#: use this to split envelope from payload — one schema, one place.
#: ``trace_id``/``span_id`` are envelope members too (omitted when tracing is
#: inactive) so a payload key of the same name can never forge causal context;
#: same for ``job`` (fleet federation's job identity, from $TPU_RESILIENCY_JOB).
RESERVED_KEYS = ("ts", "source", "kind", "pid", "rank", "trace_id", "span_id",
                 "job")


@dataclasses.dataclass
class Event:
    ts: float
    source: str
    kind: str
    payload: dict
    pid: int = dataclasses.field(default_factory=os.getpid)
    rank: Optional[int] = None
    #: causal context (``utils/tracing.py``): the run's trace id and the span
    #: active when this event was recorded — None outside any trace
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    #: fleet job identity ($TPU_RESILIENCY_JOB) — None outside fleet scope
    job: Optional[str] = None

    def to_record(self) -> dict:
        """The flat dict shape a parsed JSONL line has (envelope + payload,
        colliding payload keys renamed ``p_<key>``) — what every stream
        consumer (``observe_record``, the ledgers, ``critpath``) eats, minus
        the JSON round trip. In-process sinks use this to feed the same code
        paths the offline tools run."""
        env = {
            "ts": self.ts,
            "source": self.source,
            "kind": self.kind,
            "pid": self.pid,
            "rank": self.rank,
        }
        # Lean lines: untraced processes pay zero bytes for the trace fields.
        if self.trace_id is not None:
            env["trace_id"] = self.trace_id
        if self.span_id is not None:
            env["span_id"] = self.span_id
        if self.job is not None:
            env["job"] = self.job
        return {
            **env,
            **{f"p_{k}" if k in RESERVED_KEYS else k: v
               for k, v in self.payload.items()},
        }

    def to_json(self) -> str:
        return json.dumps(self.to_record(), default=repr)


class JsonlSink:
    """Appends one JSON line per event. Safe to share across processes: each event
    is a single ``write()`` of one line."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(path, "a", buffering=1)

    def __call__(self, event: Event) -> None:
        with self._lock:
            self._f.write(event.to_json() + "\n")

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass


class LoggingSink:
    """Mirrors events into the standard log at DEBUG (for interleaved debugging)."""

    def __call__(self, event: Event) -> None:
        log.debug(f"[event] {event.to_json()}")


_sinks: list[Callable[[Event], None]] = []
_sinks_lock = threading.Lock()
_env_wired_for: Optional[str] = None

#: ``() -> (trace_id, span_id)`` supplier consulted by ``record``. The default
#: reads the tracing env vars directly so a process that never imports
#: ``utils/tracing`` still stamps inherited context onto its events;
#: ``utils/tracing`` swaps in its thread-local-aware provider on import.
#: (A hook, not an import: events must stay the dependency root.)
TRACE_ID_ENV = "TPU_RESILIENCY_TRACE_ID"
PARENT_SPAN_ENV = "TPU_RESILIENCY_PARENT_SPAN"


def _env_trace_context() -> tuple[Optional[str], Optional[str]]:
    return (
        os.environ.get(TRACE_ID_ENV) or None,
        os.environ.get(PARENT_SPAN_ENV) or None,
    )


_context_provider: Callable[[], tuple[Optional[str], Optional[str]]] = (
    _env_trace_context
)


def set_context_provider(
    fn: Callable[[], tuple[Optional[str], Optional[str]]]
) -> None:
    """Install the ``(trace_id, span_id)`` supplier stamped onto every event."""
    global _context_provider
    _context_provider = fn


def add_sink(sink: Callable[[Event], None], *, front: bool = False) -> None:
    """Register a sink. ``front=True`` puts it FIRST in dispatch order —
    reserved for crash-surviving sinks (the flight recorder): when a process
    dies mid-``record()`` (e.g. a SIGKILL racing a hang-forensics stack
    dump), the sink that persists the event must be the one that already
    ran."""
    with _sinks_lock:
        if front:
            _sinks.insert(0, sink)
        else:
            _sinks.append(sink)


def remove_sink(sink: Callable[[Event], None]) -> None:
    with _sinks_lock:
        try:
            _sinks.remove(sink)
        except ValueError:
            pass


def clear_sinks() -> None:
    with _sinks_lock:
        _sinks.clear()
    global _env_wired_for, _metrics_wired_for, _flight_wired_for, _push_wired_for
    _env_wired_for = None
    _metrics_wired_for = None
    _flight_wired_for = None
    _push_wired_for = None


_metrics_wired_for: Optional[str] = None
_flight_wired_for: Optional[str] = None
_push_wired_for: Optional[str] = None


def _wire_env_sink() -> None:
    """Attach (once per path) the JSONL sink named by $TPU_RESILIENCY_EVENTS_FILE
    and the metrics bridge named by $TPU_RESILIENCY_METRICS_FILE.
    Re-checked on every record so a launcher exporting the variable after import
    still takes effect, and forked/spawned children wire themselves lazily.
    The flight recorder named by $TPU_RESILIENCY_FLIGHT_DIR rides the same
    lazy wiring (flight_recorder.install registers itself as a sink)."""
    global _env_wired_for, _metrics_wired_for, _flight_wired_for, _push_wired_for
    path = os.environ.get(EVENTS_FILE_ENV)
    if path and path != _env_wired_for:
        with _sinks_lock:
            if _env_wired_for != path:
                try:
                    _sinks.append(JsonlSink(path))
                    _env_wired_for = path
                except OSError as e:
                    log.warning(f"cannot open events file {path!r}: {e}")
                    _env_wired_for = path  # don't retry every event
    mpath = os.environ.get(METRICS_FILE_ENV)
    if mpath and mpath != _metrics_wired_for:
        with _sinks_lock:
            if _metrics_wired_for != mpath:
                try:
                    # Lazy import: events is the dependency root; metrics
                    # imports events, never the reverse at module load.
                    from tpu_resiliency.utils.metrics import MetricsSink

                    base, ext = os.path.splitext(mpath)
                    _sinks.append(
                        MetricsSink(json_path=f"{base}.{os.getpid()}{ext or '.json'}")
                    )
                except Exception as e:
                    log.warning(f"cannot wire metrics snapshots to {mpath!r}: {e}")
                _metrics_wired_for = mpath
    ppath = os.environ.get(METRICS_PUSH_ENV)
    if ppath and ppath != _push_wired_for:
        with _sinks_lock:
            if _push_wired_for != ppath:
                try:
                    # Lazy import, same reason as the metrics bridge: events
                    # stays the dependency root.
                    from tpu_resiliency.utils.metrics import MetricsPublisher

                    _sinks.append(MetricsPublisher.from_env_spec(ppath))
                except Exception as e:
                    log.warning(f"cannot wire metrics push to {ppath!r}: {e}")
                _push_wired_for = ppath
    fpath = os.environ.get(FLIGHT_DIR_ENV)
    if fpath and fpath != _flight_wired_for:
        try:
            # Lazy import for the same reason as the metrics bridge: events
            # stays the dependency root. install() adds the sink itself.
            # Marked wired only on success so a transient mkdir/open failure
            # is retried on the next record, as the lazy wiring promises.
            from tpu_resiliency.utils import flight_recorder

            if flight_recorder.install_from_env() is not None:
                _flight_wired_for = fpath
        except Exception as e:
            log.warning(f"cannot wire flight recorder in {fpath!r}: {e}")


def record(source: str, kind: str, **payload: Any) -> None:
    """Record one event; never raises. ``rank`` is read from $RANK when present."""
    _wire_env_sink()
    with _sinks_lock:
        sinks = list(_sinks)
    if not sinks:
        return
    rank_s = os.environ.get("RANK")
    try:
        trace_id, span_id = _context_provider()
    except Exception:
        trace_id = span_id = None  # context is decoration, never control flow
    ev = Event(
        ts=time.time(),
        source=source,
        kind=kind,
        payload=payload,
        rank=int(rank_s) if rank_s and rank_s.isdigit() else None,
        trace_id=trace_id,
        span_id=span_id,
        job=os.environ.get(JOB_ENV) or None,
    )
    for sink in sinks:
        try:
            sink(ev)
        except Exception:
            log.debug("event sink failed", exc_info=True)


def prof(source: str, name: Optional[str] = None):
    """Decorator: time the call, record a ``timing`` event with success/failure
    (reference ``metrics/api.py`` ``@prof``)."""

    def deco(fn: Callable):
        label = name or getattr(fn, "__name__", "call")

        def wrapped(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                out = fn(*args, **kwargs)
            except BaseException as e:
                record(
                    source, "timing", name=label,
                    duration_s=time.perf_counter() - t0, ok=False, error=repr(e),
                )
                raise
            record(
                source, "timing", name=label,
                duration_s=time.perf_counter() - t0, ok=True,
            )
            return out

        wrapped.__name__ = getattr(fn, "__name__", label)
        wrapped.__wrapped__ = fn
        return wrapped

    return deco


def read_events(
    path: str,
    *,
    since: Optional[float] = None,
    until: Optional[float] = None,
) -> list[dict]:
    """Parse a JSONL event file (tolerates torn trailing lines).

    ``since``/``until`` stream-filter records by their ``ts`` while reading,
    so callers slicing a window out of a long-lived shared file (the incident
    engine closes incidents against a stream that can span days) never
    materialize its full history. When either bound is set, records without a
    numeric ``ts`` are dropped — they cannot be placed in the window."""
    out = []
    bounded = since is not None or until is not None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if bounded:
                    ts = rec.get("ts")
                    if not isinstance(ts, (int, float)):
                        continue
                    if since is not None and ts < since:
                        continue
                    if until is not None and ts > until:
                        continue
                out.append(rec)
    except OSError:
        pass
    return out
