"""Crash-surviving flight recorder: the last seconds before a death, always on disk.

The normal event sink (``$TPU_RESILIENCY_EVENTS_FILE``) dies with the process —
a SIGKILLed worker's final events may still sit in a userspace buffer, and a
worker whose filesystem path went away has nothing at all. Post-mortems care
about exactly those events: the span a rank died inside, the last heartbeat it
sent, the checkpoint phase it never finished. This module keeps a bounded ring
of each process's most recent events and guarantees it survives every way a
rank can die:

- **SIGKILL / OOM-kill** (uncatchable): the ring is *continuously* persisted.
  Every event is appended to a hot segment file (one ``write()`` per line, the
  same POSIX-append discipline as the JSONL sink); when the hot segment reaches
  ``capacity`` lines it is rotated to ``.prev`` (replacing the previous
  rotation). Between the two segments the last ``capacity``..``2×capacity``
  events are on disk within one write of real time — ``kill -9`` loses at most
  the event being written.
- **SIGTERM / SIGABRT** (the watchdog kill ladder's first rungs, and the
  launcher's graceful stop): a chained signal handler flushes a consolidated
  dump with the signal name before re-raising the previous disposition.
- **Unhandled exceptions** (``inprocess/wrap.py`` fn exceptions, interpreter
  ``sys.excepthook``): explicit ``flush(reason)`` calls, chained excepthook.

Layout under the flight directory (``$TPU_RESILIENCY_FLIGHT_DIR``, exported
once by the launcher like the events/metrics variables):

- ``flight-<rank>-<pid>.hot.jsonl`` / ``...prev.jsonl``: the live ring segments.
- ``flight-<rank>-<pid>.jsonl``: the consolidated dump written by ``flush``
  (ring contents + one trailing ``flight_flush`` record naming the reason).

``collect(dir)`` merges all three per (rank, pid) identity — consolidated dump
when present, stitched segments otherwise — which is what the launcher's
incident engine (``launcher/incident.py``) folds into incident artifacts.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

#: Re-exported from events (the envelope owner) — one name, one place.
from tpu_resiliency.utils import events as _events  # noqa: E402

FLIGHT_DIR_ENV = _events.FLIGHT_DIR_ENV

#: default ring capacity (events per segment; disk holds up to 2× this)
DEFAULT_CAPACITY = 512

#: fault signals that trigger a consolidated flush before the previous
#: disposition runs (SIGKILL is uncatchable — the hot segments cover it)
FLUSH_SIGNALS = (signal.SIGTERM, signal.SIGABRT)


def _identity() -> str:
    rank = os.environ.get("RANK")
    rank_part = rank if rank and rank.isdigit() else "x"
    return f"{rank_part}-{os.getpid()}"


class FlightRecorder:
    """Bounded event ring with continuous segment persistence + fault-flush.

    Registered as an ``events.add_sink`` sink (it receives every ``record()``
    the process makes); additionally installs chained SIGTERM/SIGABRT handlers
    and a chained ``sys.excepthook`` when asked (``install_handlers=True``,
    main thread only — ``signal.signal`` is a no-op elsewhere)."""

    def __init__(
        self,
        directory: str,
        capacity: int = DEFAULT_CAPACITY,
        install_handlers: bool = True,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.directory = directory
        self.capacity = capacity
        os.makedirs(directory, exist_ok=True)
        self._ident = _identity()
        self._ring: deque[str] = deque(maxlen=2 * capacity)
        self._lock = threading.Lock()
        self._hot_lines = 0
        self._hot_f = open(self._hot_path, "a", buffering=1)
        self._flushed_reason: Optional[str] = None
        self._closed = False
        if install_handlers:
            self._install_handlers()

    # -- paths --------------------------------------------------------------

    @property
    def _hot_path(self) -> str:
        return os.path.join(self.directory, f"flight-{self._ident}.hot.jsonl")

    @property
    def _prev_path(self) -> str:
        return os.path.join(self.directory, f"flight-{self._ident}.prev.jsonl")

    @property
    def dump_path(self) -> str:
        return os.path.join(self.directory, f"flight-{self._ident}.jsonl")

    # -- the sink -----------------------------------------------------------

    def __call__(self, event) -> None:
        """events.add_sink entry: one line into the ring + the hot segment."""
        try:
            line = event.to_json()
        except Exception:
            return
        with self._lock:
            if self._closed:
                return
            self._ring.append(line)
            try:
                self._hot_f.write(line + "\n")
                self._hot_lines += 1
                if self._hot_lines >= self.capacity:
                    self._rotate_locked()
            except (OSError, ValueError):
                pass  # persistence is best-effort; the in-memory ring remains

    def _rotate_locked(self) -> None:
        try:
            self._hot_f.close()
        except OSError:
            pass
        try:
            os.replace(self._hot_path, self._prev_path)
        except OSError:
            pass
        self._hot_f = open(self._hot_path, "a", buffering=1)
        self._hot_lines = 0

    # -- fault flush --------------------------------------------------------

    def flush(self, reason: str, detail: str = "") -> Optional[str]:
        """Write the consolidated dump (ring + trailing ``flight_flush``
        marker). Idempotent per reason sequence — later flushes rewrite the
        dump with the newest ring, so the deepest-in-the-death flush wins.
        Returns the dump path (None if the write failed).

        Signal-safe: flush() runs from the chained SIGTERM/SIGABRT handlers,
        which interrupt the main thread at an arbitrary point — possibly while
        it already holds ``self._lock`` inside ``__call__``. A blocking
        acquire there would deadlock the handler and turn a graceful stop into
        a hang, so the ring is snapshotted with a non-blocking acquire and,
        when the lock is held, copied without it (deque reads are GIL-atomic
        enough for a best-effort dump)."""
        marker = json.dumps(
            {
                "ts": time.time(),
                "source": "flight",
                "kind": "flight_flush",
                "pid": os.getpid(),
                "rank": _rank_or_none(),
                "reason": reason,
                **({"detail": detail} if detail else {}),
            }
        )
        acquired = self._lock.acquire(blocking=False)
        try:
            lines = None
            for _ in range(3):
                try:
                    lines = list(self._ring)
                    break
                except RuntimeError:  # deque mutated mid-iteration (lockless)
                    continue
            if lines is None:
                lines = []
            lines.append(marker)
            self._flushed_reason = reason
        finally:
            if acquired:
                self._lock.release()
        tmp = f"{self.dump_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write("\n".join(lines) + "\n")
            os.replace(tmp, self.dump_path)
            return self.dump_path
        except OSError:
            return None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            try:
                self._hot_f.close()
            except OSError:
                pass

    # -- handler chaining ---------------------------------------------------

    def _install_handlers(self) -> None:
        if threading.current_thread() is not threading.main_thread():
            return
        for sig in FLUSH_SIGNALS:
            try:
                prev = signal.getsignal(sig)
                signal.signal(sig, self._make_signal_handler(sig, prev))
            except (ValueError, OSError):
                pass  # non-main thread or unsupported signal
        prev_hook = sys.excepthook
        recorder = self

        def hook(exc_type, exc, tb):
            try:
                recorder.flush("unhandled_exception", detail=repr(exc))
            except Exception:
                pass
            prev_hook(exc_type, exc, tb)

        sys.excepthook = hook

    def _make_signal_handler(self, sig: int, prev):
        recorder = self

        def handler(signum, frame):
            try:
                recorder.flush(f"signal:{signal.Signals(signum).name}")
            except Exception:
                pass
            # Chain: a callable previous handler runs next; the default
            # disposition is re-raised so the process still dies by the signal
            # (a flight recorder must never convert a kill into survival).
            if callable(prev):
                prev(signum, frame)
            elif prev is signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            # SIG_IGN: honored — nothing more to do.

        return handler


# -- process-global wiring ---------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()
_wired_for: Optional[str] = None


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


def install(
    directory: str,
    capacity: int = DEFAULT_CAPACITY,
    install_handlers: bool = True,
) -> FlightRecorder:
    """Create (once per directory) the process recorder and register it as an
    events sink. Re-install with a new directory replaces the old recorder."""
    global _recorder, _wired_for
    from tpu_resiliency.utils import events

    with _recorder_lock:
        if _recorder is not None and _wired_for == directory:
            # Re-register if a clear_sinks() dropped us (idempotent: remove
            # first so repeated installs never double-feed the ring).
            events.remove_sink(_recorder)
            events.add_sink(_recorder, front=True)
            return _recorder
        if _recorder is not None:
            events.remove_sink(_recorder)
            _recorder.close()
        _recorder = FlightRecorder(
            directory, capacity=capacity, install_handlers=install_handlers
        )
        _wired_for = directory
        # FIRST in sink order: if the process dies mid-record (a SIGKILL
        # racing a stack dump captured in a starved-GIL window), the
        # crash-surviving ring must be the sink that already persisted it.
        events.add_sink(_recorder, front=True)
        return _recorder


def uninstall() -> None:
    """Detach and close the process recorder (tests/scenarios; workloads keep
    theirs for life — the ring must outlive everything except the process)."""
    global _recorder, _wired_for
    from tpu_resiliency.utils import events

    with _recorder_lock:
        if _recorder is not None:
            events.remove_sink(_recorder)
            _recorder.close()
        _recorder = None
        _wired_for = None


def install_from_env() -> Optional[FlightRecorder]:
    """Wire the recorder named by ``$TPU_RESILIENCY_FLIGHT_DIR`` (no-op when
    unset). Called lazily from the events layer so any process that records a
    single event self-installs, exactly like the JSONL/metrics env sinks."""
    path = os.environ.get(FLIGHT_DIR_ENV)
    if not path:
        return None
    try:
        return install(path)
    except OSError as e:
        log.warning(f"cannot install flight recorder in {path!r}: {e}")
        return None


def flush(reason: str, detail: str = "") -> Optional[str]:
    """Flush the process recorder if one is installed (safe no-op otherwise)."""
    rec = _recorder
    if rec is None:
        return None
    return rec.flush(reason, detail)


def _rank_or_none() -> Optional[int]:
    r = os.environ.get("RANK")
    return int(r) if r and r.isdigit() else None


# -- reading ------------------------------------------------------------------


def _read_lines(path: str) -> list[dict]:
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn mid-write line (the SIGKILL instant)
    except OSError:
        pass
    return out


def collect(directory: str) -> dict[str, list[dict]]:
    """All flight dumps under ``directory``, keyed ``"<rank>-<pid>"``.

    Per identity, the consolidated dump (``flush`` output) is preferred; when
    only the live segments exist (SIGKILL — no flush ever ran) the ``.prev``
    and ``.hot`` segments are stitched in order. Records are deduplicated by
    exact line identity (a flushed ring repeats segment contents)."""
    out: dict[str, list[dict]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    idents = set()
    for n in names:
        if n.startswith("flight-") and n.endswith(".jsonl"):
            ident = n[len("flight-"):-len(".jsonl")]
            for suffix in (".hot", ".prev"):
                if ident.endswith(suffix):
                    ident = ident[: -len(suffix)]
            idents.add(ident)
    for ident in sorted(idents):
        base = os.path.join(directory, f"flight-{ident}")
        records = _read_lines(f"{base}.prev.jsonl") + _read_lines(f"{base}.hot.jsonl")
        dump = _read_lines(f"{base}.jsonl")
        if dump:
            seen = {json.dumps(r, sort_keys=True) for r in dump}
            # Segment events newer than the flush (written between flush and
            # death) ride along after the dump.
            dump += [
                r for r in records
                if json.dumps(r, sort_keys=True) not in seen
            ]
            records = dump
        if records:
            # Stable ts order (flush markers and stitched segments can
            # interleave); ts-less garbage sinks to the front untouched.
            records.sort(key=lambda r: r.get("ts") if isinstance(
                r.get("ts"), (int, float)) else float("-inf"))
            out[ident] = records
    return out
