"""Thread-safe metrics registry with Prometheus exposition + events bridge.

The reference NVRx emits torchelastic-style structured events and ``@prof``
timings but ships no aggregation — its own tests grep log lines. This module is
the missing operator surface: Counter / Gauge / Histogram primitives behind a
registry, rendered either as Prometheus text exposition (scrapeable from a
sidecar) or as a JSON snapshot file, and fed from the structured event stream
two ways:

- **live**: :class:`MetricsSink` is an ``events.add_sink`` sink — one
  ``record()`` call feeds both the JSONL stream and the registry;
- **post-hoc**: :func:`aggregate` replays a finished run's JSONL into a fresh
  registry (``tools/metrics_dump.py``), so "how many restarts, p95 rendezvous
  time, checkpoint save latency" never again means replaying raw JSONL by hand.

Both paths share one kind→metric mapping (:func:`observe_record`): the live
sink converts each :class:`~tpu_resiliency.utils.events.Event` to the same flat
record shape the JSONL file holds and routes it through the identical code.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import random
import re
import threading
import time
from typing import Any, Iterable, Optional

from tpu_resiliency.utils.events import RESERVED_KEYS

#: Prometheus histogram bucket upper bounds (seconds) tuned for restart
#: machinery: sub-ms store ops up through multi-minute rendezvous holds.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

#: Sample reservoir cap per histogram: quantiles stay exact until a series
#: outgrows this, then degrade to uniform reservoir sampling (bounded RSS on a
#: multi-day run; the Prometheus buckets are exact regardless).
RESERVOIR_SIZE = 8192

#: Bucket bounds (MB/s) for shard-transfer throughput histograms: spans a
#: congested cross-host DCN link up through loopback/NVMe-class rates.
THROUGHPUT_BUCKETS_MBPS = (
    1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000, 25000,
)

#: Bucket bounds (seconds) for the checkpoint foreground-blocked window: the
#: pipelined engine targets sub-millisecond, the legacy blocking D2H path sits
#: in the tens-of-ms-to-seconds range — both must resolve on one histogram.
FOREGROUND_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not out or not _NAME_OK.match(out):
        out = "_" + out
    return out


class Counter:
    """Monotonic float counter."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Prometheus-style cumulative-bucket histogram + bounded sample reservoir.

    Buckets give exact exposition-format counts; the reservoir gives quantiles
    (exact below :data:`RESERVOIR_SIZE` observations, sampled beyond — the
    sampler is seeded so aggregating the same JSONL twice answers the same).
    """

    def __init__(self, buckets: Optional[Iterable[float]] = None) -> None:
        self._lock = threading.Lock()
        self.bounds = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +Inf tail
        self.count = 0
        self.sum = 0.0
        self._samples: list[float] = []
        self._rng = random.Random(0x5EED)

    def observe(self, v: float) -> None:
        v = float(v)
        if math.isnan(v):
            return
        with self._lock:
            self.count += 1
            self.sum += v
            self.bucket_counts[bisect.bisect_left(self.bounds, v)] += 1
            if len(self._samples) < RESERVOIR_SIZE:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < RESERVOIR_SIZE:
                    self._samples[j] = v

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile over the reservoir; NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            if not self._samples:
                return float("nan")
            ordered = sorted(self._samples)
        idx = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
        return ordered[idx]


class MetricsRegistry:
    """Name+labels → metric instance; the creation call is the lookup call.

    ``registry.counter("tpu_restarts_total", layer="injob").inc()`` creates the
    series on first use and returns the existing instance after — callers never
    pre-declare. A name is bound to one type and one label-key set for the
    registry's lifetime (Prometheus exposition requires it).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: name -> (kind, help)
        self._families: dict[str, tuple[str, str]] = {}
        #: (name, labels_tuple) -> metric
        self._series: dict[tuple, Any] = {}

    def _get(self, kind: str, ctor, name: str, help: str, labels: dict):
        name = _sanitize(name)
        key = (name, tuple(sorted(
            (_LABEL_BAD.sub("_", k), str(v)) for k, v in labels.items()
        )))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                self._families[name] = (kind, help)
            elif fam[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}, not {kind}"
                )
            m = self._series.get(key)
            if m is None:
                m = self._series[key] = ctor()
            return m

    # Positional-only metric/help/buckets params: the label namespace is open
    # (``name=...``, ``help=...`` are legitimate label keys).
    def counter(self, name: str, help: str = "", /, **labels) -> Counter:
        return self._get("counter", Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", /, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, help, labels)

    def histogram(
        self, name: str, help: str = "",
        buckets: Optional[Iterable[float]] = None, /, **labels,
    ) -> Histogram:
        return self._get(
            "histogram", lambda: Histogram(buckets), name, help, labels
        )

    def histograms(self, name: str) -> dict[tuple, Histogram]:
        """Every series of histogram family ``name`` keyed by its label tuple."""
        name = _sanitize(name)
        with self._lock:
            return {
                k[1]: m for k, m in self._series.items() if k[0] == name
                and isinstance(m, Histogram)
            }

    # -- rendering ---------------------------------------------------------

    @staticmethod
    def _label_str(labels: tuple, extra: str = "") -> str:
        parts = [f'{k}="{v}"' for k, v in labels]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    @staticmethod
    def _fmt(v: float) -> str:
        if v == math.inf:
            return "+Inf"
        if float(v).is_integer():
            return str(int(v))
        return repr(float(v))

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            families = dict(self._families)
            series = dict(self._series)
        lines: list[str] = []
        for name in sorted(families):
            kind, help = families[name]
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {kind}")
            for (sname, labels), m in sorted(series.items()):
                if sname != name:
                    continue
                if isinstance(m, (Counter, Gauge)):
                    lines.append(
                        f"{name}{self._label_str(labels)} {self._fmt(m.value)}"
                    )
                else:
                    cum = 0
                    for bound, n in zip(m.bounds, m.bucket_counts):
                        cum += n
                        le = self._label_str(labels, f'le="{self._fmt(bound)}"')
                        lines.append(f"{name}_bucket{le} {cum}")
                    le = self._label_str(labels, 'le="+Inf"')
                    lines.append(f"{name}_bucket{le} {m.count}")
                    lines.append(
                        f"{name}_sum{self._label_str(labels)} {self._fmt(m.sum)}"
                    )
                    lines.append(
                        f"{name}_count{self._label_str(labels)} {m.count}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON-serializable state: counters/gauges by series, histograms with
        count/sum/quantiles (the operator's one-call answer, no PromQL needed)."""
        with self._lock:
            families = dict(self._families)
            series = dict(self._series)
        out: dict = {"ts": time.time(), "metrics": {}}
        for (name, labels), m in sorted(series.items()):
            kind, help = families[name]
            entry: dict = {"type": kind, "labels": dict(labels)}
            if isinstance(m, (Counter, Gauge)):
                entry["value"] = m.value
            else:
                entry.update(
                    count=m.count,
                    sum=m.sum,
                    p50=m.quantile(0.50),
                    p90=m.quantile(0.90),
                    p95=m.quantile(0.95),
                    p99=m.quantile(0.99),
                )
            out["metrics"].setdefault(name, []).append(entry)
        return out

    def write_json(self, path: str) -> None:
        """Atomic snapshot-to-file (tmp + rename): a scraper reading the path
        mid-write never sees a torn document."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, indent=2, default=repr)
            f.write("\n")
        os.replace(tmp, path)


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (what :class:`MetricsSink` feeds)."""
    return _default_registry


# -- events → metrics bridge ------------------------------------------------

def observe_record(rec: dict, reg: MetricsRegistry) -> None:
    """Route one event record (JSONL dict or flattened Event) into metrics.

    The single kind→metric mapping shared by the live sink and the post-hoc
    aggregator; unknown kinds still land in ``tpu_events_total`` so a new
    emitter is visible before this table learns its name.
    """
    kind = rec.get("kind")
    if not isinstance(kind, str):
        return
    reg.counter("tpu_events_total", "structured events by kind", kind=kind).inc()
    if kind == "rendezvous_round":
        reg.counter(
            "tpu_rendezvous_rounds_total", "rendezvous rounds entered"
        ).inc()
        if isinstance(rec.get("world_size"), (int, float)):
            reg.gauge("tpu_world_size", "last observed world size").set(
                rec["world_size"]
            )
        if isinstance(rec.get("round"), (int, float)):
            reg.gauge("tpu_rendezvous_round", "last rendezvous round").set(
                rec["round"]
            )
    elif kind == "restart_requested":
        reg.counter(
            "tpu_restarts_total", "restart rounds by layer", layer="injob"
        ).inc()
    elif kind == "restart_signalled":
        reg.counter(
            "tpu_restarts_total", "restart rounds by layer", layer="inprocess"
        ).inc()
    elif kind == "restart_budget":
        if isinstance(rec.get("used"), (int, float)):
            reg.gauge(
                "tpu_restart_budget_used", "restart budget consumed"
            ).set(rec["used"])
    elif kind == "worker_failed":
        reg.counter("tpu_worker_failures_total", "worker process failures").inc()
    elif kind == "worker_promoted":
        reg.counter(
            "tpu_spare_promotions_total", "warm-spare promotions"
        ).inc()
    elif kind in ("hang_detected", "health_terminated"):
        reg.counter(
            "tpu_rank_terminations_total", "monitor-initiated terminations",
            cause="hang" if kind == "hang_detected" else "health",
        ).inc()
    elif kind == "kill_ladder":
        reg.counter(
            "tpu_kill_ladder_total", "termination signals by step",
            step=str(rec.get("step", "?")),
        ).inc()
    elif kind == "budget_exhausted":
        reg.counter(
            "tpu_budget_exhausted_total", "restart budget exhaustions"
        ).inc()
    elif kind == "ckpt_saved":
        reg.counter("tpu_ckpt_saves_total", "durable checkpoint saves").inc()
        if isinstance(rec.get("bytes"), (int, float)):
            reg.histogram(
                "tpu_ckpt_bytes", "checkpoint bytes per save",
                (2**10, 2**16, 2**20, 2**24, 2**27, 2**30, 2**33, 2**36),
            ).observe(rec["bytes"])
    elif kind == "ckpt_save_incomplete":
        reg.counter(
            "tpu_ckpt_save_failures_total", "coverage-failed checkpoint saves"
        ).inc()
    elif kind == "ckpt_quarantined":
        # A quarantine IS an integrity failure (stage says where it was
        # caught); the dedicated counter additionally tracks file volume.
        reg.counter(
            "tpu_ckpt_integrity_failures_total",
            "checkpoint integrity failures by ladder stage "
            "(local-read quarantine, peer-retrieve, replicate/stream receive)",
            stage=str(rec.get("stage", "?")),
        ).inc()
        reg.counter(
            "tpu_ckpt_quarantined_total",
            "checkpoint containers quarantined to *.corrupt for forensics",
        ).inc()
    elif kind == "ckpt_integrity_failure":
        reg.counter(
            "tpu_ckpt_integrity_failures_total",
            "checkpoint integrity failures by ladder stage "
            "(local-read quarantine, peer-retrieve, replicate/stream receive)",
            stage=str(rec.get("stage", "?")),
        ).inc()
    elif kind == "ckpt_unverified":
        reg.counter(
            "tpu_ckpt_unverified_total",
            "containers loaded/received without checksum verification "
            "(v1 format or foreign checksum algorithm)",
        ).inc()
    elif kind == "ckpt_fallback":
        reg.counter(
            "tpu_ckpt_fallback_total",
            "recovery-ladder fallbacks to an older checkpoint iteration",
        ).inc()
    elif kind == "ckpt_foreground_blocked":
        if isinstance(rec.get("duration_s"), (int, float)):
            reg.histogram(
                "tpu_ckpt_foreground_blocked_seconds",
                "caller-visible train-loop stall per checkpoint save",
                FOREGROUND_BUCKETS_S, engine=str(rec.get("engine", "?")),
            ).observe(rec["duration_s"])
    elif kind == "staging_pool":
        if isinstance(rec.get("pool_bytes"), (int, float)):
            reg.gauge(
                "tpu_ckpt_staging_pool_bytes",
                "host staging buffer pool size (allocated bytes)",
            ).set(rec["pool_bytes"])
        if isinstance(rec.get("in_use_bytes"), (int, float)):
            reg.gauge(
                "tpu_ckpt_staging_inuse_bytes",
                "host staging bytes currently leased to in-flight saves",
            ).set(rec["in_use_bytes"])
        outcome = rec.get("outcome")
        if outcome in ("hit", "miss", "wait"):
            reg.counter(
                "tpu_ckpt_staging_requests_total",
                "staging lease acquisitions by outcome",
                outcome=str(outcome),
            ).inc()
    elif kind == "ckpt_write_file":
        container = str(rec.get("container", "?"))
        if isinstance(rec.get("bytes"), (int, float)):
            reg.counter(
                "tpu_ckpt_write_bytes_total",
                "container bytes written by content class (main vs "
                "separation-hint file)",
                container=container,
            ).inc(rec["bytes"])
        if isinstance(rec.get("leaves"), (int, float)):
            reg.counter(
                "tpu_ckpt_write_leaves_total",
                "tensor leaves written by content class",
                container=container,
            ).inc(rec["leaves"])
    elif kind == "p2p_transfer":
        d = str(rec.get("direction", "?"))
        if isinstance(rec.get("bytes"), (int, float)):
            reg.counter(
                "tpu_ckpt_replication_bytes_total",
                "checkpoint shard bytes moved over p2p links",
                direction=d,
            ).inc(rec["bytes"])
        if isinstance(rec.get("mbps"), (int, float)):
            reg.histogram(
                "tpu_replication_mbps", "p2p shard transfer throughput (MB/s)",
                THROUGHPUT_BUCKETS_MBPS, direction=d,
            ).observe(rec["mbps"])
    elif kind == "store_retry":
        reg.counter(
            "tpu_store_retries_total",
            "store-client transparent transport retries by op and outcome "
            "(retried per attempt; recovered/exhausted once per call)",
            op=str(rec.get("op", "?")), outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "peer_degraded":
        reg.counter(
            "tpu_replication_peer_degraded_total",
            "replication peers dropped for a round after transfer-retry "
            "exhaustion (the save proceeded with reduced redundancy)",
        ).inc()
    elif kind == "chaos_inject":
        reg.counter(
            "chaos_faults_injected_total",
            "network faults injected by the chaos plan",
            kind=str(rec.get("fault", "?")), channel=str(rec.get("channel", "?")),
        ).inc()
    elif kind == "incident_opened":
        reg.counter(
            "tpu_incidents_total",
            "incidents opened by the incident engine, by trigger",
            trigger=str(rec.get("trigger", "?")),
        ).inc()
        reg.gauge(
            "tpu_incidents_open", "incidents currently open"
        ).inc()
    elif kind == "incident_closed":
        reg.gauge("tpu_incidents_open", "incidents currently open").dec()
        # Literal names on purpose: the docs-drift gate
        # (tests/utils/test_metrics_doc.py) extracts them by AST.
        if isinstance(rec.get("time_to_detect_s"), (int, float)):
            reg.histogram(
                "tpu_incident_time_to_detect_seconds",
                "fault evidence -> incident opened, per incident",
            ).observe(rec["time_to_detect_s"])
        if isinstance(rec.get("time_to_decide_s"), (int, float)):
            reg.histogram(
                "tpu_incident_time_to_decide_seconds",
                "incident opened -> first decision, per incident",
            ).observe(rec["time_to_decide_s"])
        if isinstance(rec.get("time_to_recover_s"), (int, float)):
            reg.histogram(
                "tpu_incident_time_to_recover_seconds",
                "fault evidence -> recovered, per incident",
            ).observe(rec["time_to_recover_s"])
        if isinstance(rec.get("steps_lost"), (int, float)):
            reg.counter(
                "tpu_incident_steps_lost_total",
                "training steps lost across incidents (resume gap)",
            ).inc(max(0.0, rec["steps_lost"]))
    elif kind == "remediation_action":
        reg.counter(
            "tpu_remediation_actions_total",
            "automated remediation actions by action and outcome",
            action=str(rec.get("action", "?")),
            outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "flight_flush":
        reg.counter(
            "tpu_flight_flushes_total",
            "flight-recorder consolidated dumps by reason",
            reason=str(rec.get("reason", "?")),
        ).inc()
    elif kind == "heartbeat_stats":
        if isinstance(rec.get("max_gap_s"), (int, float)):
            reg.histogram(
                "tpu_heartbeat_gap_seconds", "per-session max heartbeat gap"
            ).observe(rec["max_gap_s"])
    elif kind == "timing":
        d = rec.get("duration_s")
        if isinstance(d, (int, float)):
            reg.histogram(
                "tpu_timing_seconds", "@prof / debug_time durations",
                name=str(rec.get("name", "?")),
            ).observe(d)
        if rec.get("ok") is False:
            reg.counter(
                "tpu_timing_failures_total", "timed blocks that raised",
                name=str(rec.get("name", "?")),
            ).inc()
    elif kind == "span_end":
        d = rec.get("duration_s")
        if isinstance(d, (int, float)):
            reg.histogram(
                "tpu_span_seconds", "span durations by name",
                span=str(rec.get("span", "?")),
            ).observe(d)
        if rec.get("ok") is False:
            reg.counter(
                "tpu_span_failures_total", "spans that raised",
                span=str(rec.get("span", "?")),
            ).inc()


def aggregate(
    records: Iterable[dict], reg: Optional[MetricsRegistry] = None
) -> MetricsRegistry:
    """Replay a finished run's records into a (fresh by default) registry."""
    reg = MetricsRegistry() if reg is None else reg
    for rec in records:
        if isinstance(rec, dict):
            observe_record(rec, reg)
    return reg


class MetricsSink:
    """``events.add_sink`` bridge: one ``record()`` call feeds both streams.

    Optionally snapshots the registry to ``json_path`` at most every
    ``snapshot_interval`` seconds (piggybacked on event arrivals — no extra
    thread to leak into forked workers) plus once at interpreter exit, so the
    file always reflects the process's final state.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        json_path: Optional[str] = None,
        snapshot_interval: float = 10.0,
    ):
        self.registry = registry if registry is not None else get_registry()
        self.json_path = json_path
        self.snapshot_interval = snapshot_interval
        self._last_snapshot = 0.0
        if json_path is not None:
            import atexit

            atexit.register(self._final_snapshot)

    def _final_snapshot(self) -> None:
        try:
            self.registry.write_json(self.json_path)
        except Exception:
            pass  # observability, not control flow

    def __call__(self, event) -> None:
        # Same flat shape as the JSONL line (including the p_-rename of payload
        # keys that collide with the envelope), minus the json round-trip.
        rec = {
            "ts": event.ts, "source": event.source, "kind": event.kind,
            "pid": event.pid, "rank": event.rank,
            **{f"p_{k}" if k in RESERVED_KEYS else k: v
               for k, v in event.payload.items()},
        }
        observe_record(rec, self.registry)
        if self.json_path is not None:
            now = time.monotonic()
            if now - self._last_snapshot >= self.snapshot_interval:
                self._last_snapshot = now
                self.registry.write_json(self.json_path)
