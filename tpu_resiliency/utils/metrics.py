"""Thread-safe metrics registry with Prometheus exposition + events bridge.

The reference NVRx emits torchelastic-style structured events and ``@prof``
timings but ships no aggregation — its own tests grep log lines. This module is
the missing operator surface: Counter / Gauge / Histogram primitives behind a
registry, rendered either as Prometheus text exposition (scrapeable from a
sidecar) or as a JSON snapshot file, and fed from the structured event stream
two ways:

- **live**: :class:`MetricsSink` is an ``events.add_sink`` sink — one
  ``record()`` call feeds both the JSONL stream and the registry;
- **post-hoc**: :func:`aggregate` replays a finished run's JSONL into a fresh
  registry (``tools/metrics_dump.py``), so "how many restarts, p95 rendezvous
  time, checkpoint save latency" never again means replaying raw JSONL by hand.

Both paths share one kind→metric mapping (:func:`observe_record`): the live
sink converts each :class:`~tpu_resiliency.utils.events.Event` to the same flat
record shape the JSONL file holds and routes it through the identical code.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import random
import re
import threading
import time
from typing import Any, Iterable, Optional, Sequence

from tpu_resiliency.utils.events import RESERVED_KEYS
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

#: Prometheus histogram bucket upper bounds (seconds) tuned for restart
#: machinery: sub-ms store ops up through multi-minute rendezvous holds.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

#: Sample reservoir cap per histogram: quantiles stay exact until a series
#: outgrows this, then degrade to uniform reservoir sampling (bounded RSS on a
#: multi-day run; the Prometheus buckets are exact regardless).
RESERVOIR_SIZE = 8192

#: Bucket bounds (MB/s) for shard-transfer throughput histograms: spans a
#: congested cross-host DCN link up through loopback/NVMe-class rates.
THROUGHPUT_BUCKETS_MBPS = (
    1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000, 25000,
)

#: Bucket bounds (seconds) for the checkpoint foreground-blocked window: the
#: pipelined engine targets sub-millisecond, the legacy blocking D2H path sits
#: in the tens-of-ms-to-seconds range — both must resolve on one histogram.
FOREGROUND_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: Bucket bounds (seconds) for training-step wall clock (``tpu_step_seconds``):
#: toy CPU loops (ms) up through big-model steps (minutes).
STEP_BUCKETS_S = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0,
)

#: Symmetric bucket bounds (seconds) for the autoscale forecast error
#: (``tpu_autoscale_predicted_vs_realized`` observes realized − predicted):
#: a well-calibrated controller clusters around zero; the signed tails show
#: which direction the cost model misses in.
FORECAST_ERROR_BUCKETS_S = (
    -300.0, -60.0, -10.0, -1.0, -0.1, 0.0, 0.1, 1.0, 10.0, 60.0, 300.0,
)

#: An ``iteration_start`` delta larger than this is not a step — it's a gap
#: (hang, restart, operator pause) and must not pollute the step histogram or
#: the goodput ledger's ``train`` attribution (``utils/goodput.py`` shares it).
#: Default 300 s; tune per workload via ``$TPU_RESILIENCY_STEP_GAP_MAX`` (see
#: :func:`step_gap_max_s`) — a job whose legitimate steps include multi-minute
#: compiles or evals would otherwise see them misattributed as downtime.
STEP_GAP_MAX_S = 300.0

#: Env override for :data:`STEP_GAP_MAX_S` (seconds, must parse > 0).
STEP_GAP_ENV = "TPU_RESILIENCY_STEP_GAP_MAX"


def step_gap_max_s() -> float:
    """The effective step-gap cap: ``$TPU_RESILIENCY_STEP_GAP_MAX`` when it
    parses to a positive number, else the 300 s default. Read per call so the
    live sink, a post-hoc ``aggregate()``, and the goodput ledger all honor
    the same setting without restart-ordering surprises; an unparseable or
    non-positive value falls back rather than raising — a typo'd env var must
    not take down metrics."""
    raw = os.environ.get(STEP_GAP_ENV)
    if raw:
        try:
            v = float(raw)
            if v > 0:
                return v
        except ValueError:
            pass
    return STEP_GAP_MAX_S

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not out or not _NAME_OK.match(out):
        out = "_" + out
    return out


class Counter:
    """Monotonic float counter."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value.

    Each write stamps ``ts`` (wall clock) so cross-registry merges can keep
    last-writer-wins semantics: :meth:`merge_lww` takes the (ts, value) pair
    with the larger timestamp, value-tiebroken — a commutative, associative
    rule, so a tree of partial merges equals the flat merge.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self.ts = 0.0

    def set(self, v: float, ts: Optional[float] = None) -> None:
        with self._lock:
            self._value = float(v)
            self.ts = time.time() if ts is None else float(ts)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n
            self.ts = time.time()

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def merge_lww(self, v: float, ts: float) -> None:
        """Adopt ``(v, ts)`` iff it out-ranks the current write."""
        with self._lock:
            if (float(ts), float(v)) > (self.ts, self._value):
                self._value = float(v)
                self.ts = float(ts)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Prometheus-style cumulative-bucket histogram + bounded sample reservoir.

    Buckets give exact exposition-format counts; the reservoir gives quantiles
    (exact below :data:`RESERVOIR_SIZE` observations, sampled beyond — the
    sampler is seeded so aggregating the same JSONL twice answers the same).
    """

    def __init__(self, buckets: Optional[Iterable[float]] = None) -> None:
        self._lock = threading.Lock()
        self.bounds = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +Inf tail
        self.count = 0
        self.sum = 0.0
        self._samples: list[float] = []
        self._rng = random.Random(0x5EED)

    def observe(self, v: float) -> None:
        v = float(v)
        if math.isnan(v):
            return
        with self._lock:
            self.count += 1
            self.sum += v
            self.bucket_counts[bisect.bisect_left(self.bounds, v)] += 1
            if len(self._samples) < RESERVOIR_SIZE:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < RESERVOIR_SIZE:
                    self._samples[j] = v

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile over the reservoir; NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            if not self._samples:
                return float("nan")
            ordered = sorted(self._samples)
        idx = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
        return ordered[idx]

    def merge_counts(
        self, counts: Sequence[int], count: float, total: float
    ) -> None:
        """Bucket-wise add another histogram's state (same bounds required).

        The reservoir is NOT merged — a merged histogram answers exposition
        (buckets/count/sum) exactly; quantiles stay with the per-process
        registries that observed the raw samples."""
        counts = list(counts)
        if len(counts) != len(self.bucket_counts):
            raise ValueError(
                f"bucket count mismatch: {len(counts)} != "
                f"{len(self.bucket_counts)}"
            )
        with self._lock:
            for i, n in enumerate(counts):
                self.bucket_counts[i] += int(n)
            self.count += int(count)
            self.sum += float(total)


def _plain_json(value: Any) -> Any:
    """Restrict a value tree to plain, strict-JSON types.

    Non-finite floats become ``None`` (``NaN``/``Infinity`` are not JSON and
    don't round-trip), numeric-coercible scalars (numpy, Decimal, ...) are
    coerced to ``float``, and anything else is dropped to ``None`` with a
    warning — so a snapshot consumer (``merge``, a dashboard, a scraper)
    never meets a ``repr``-stringified object where a number belongs."""
    if value is None or isinstance(value, (str, bool, int)):
        return value
    if isinstance(value, float):
        return value if math.isfinite(value) else None
    if isinstance(value, dict):
        return {str(k): _plain_json(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_plain_json(v) for v in value]
    try:
        f = float(value)
        return f if math.isfinite(f) else None
    except (TypeError, ValueError):
        log.warning(
            f"dropping non-JSON value {type(value).__name__} from metrics snapshot"
        )
        return None


class MetricsRegistry:
    """Name+labels → metric instance; the creation call is the lookup call.

    ``registry.counter("tpu_restarts_total", layer="injob").inc()`` creates the
    series on first use and returns the existing instance after — callers never
    pre-declare. A name is bound to one type and one label-key set for the
    registry's lifetime (Prometheus exposition requires it).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: name -> (kind, help)
        self._families: dict[str, tuple[str, str]] = {}
        #: (name, labels_tuple) -> metric
        self._series: dict[tuple, Any] = {}
        #: scratch space for stateful bridge mappings (see :meth:`aux_state`)
        self._aux: dict[str, dict] = {}

    def aux_state(self, key: str) -> dict:
        """Per-registry scratch dict for stateful event→metric mappings.

        ``observe_record`` is mostly stateless, but some derivations need
        memory (e.g. ``tpu_step_seconds`` = delta between consecutive
        ``iteration_start`` records of one pid). Keeping that state ON the
        registry — not module-global — preserves live/post-hoc parity: the
        live sink and a fresh ``aggregate()`` replay each carry their own."""
        with self._lock:
            return self._aux.setdefault(key, {})

    def _get(self, kind: str, ctor, name: str, help: str, labels: dict):
        name = _sanitize(name)
        key = (name, tuple(sorted(
            (_LABEL_BAD.sub("_", k), str(v)) for k, v in labels.items()
        )))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                self._families[name] = (kind, help)
            elif fam[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}, not {kind}"
                )
            m = self._series.get(key)
            if m is None:
                m = self._series[key] = ctor()
            return m

    # Positional-only metric/help/buckets params: the label namespace is open
    # (``name=...``, ``help=...`` are legitimate label keys).
    def counter(self, name: str, help: str = "", /, **labels) -> Counter:
        return self._get("counter", Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", /, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, help, labels)

    def histogram(
        self, name: str, help: str = "",
        buckets: Optional[Iterable[float]] = None, /, **labels,
    ) -> Histogram:
        return self._get(
            "histogram", lambda: Histogram(buckets), name, help, labels
        )

    def histograms(self, name: str) -> dict[tuple, Histogram]:
        """Every series of histogram family ``name`` keyed by its label tuple."""
        name = _sanitize(name)
        with self._lock:
            return {
                k[1]: m for k, m in self._series.items() if k[0] == name
                and isinstance(m, Histogram)
            }

    # -- rendering ---------------------------------------------------------

    @staticmethod
    def _escape_label_value(v: str) -> str:
        """Prometheus text format 0.0.4 label-value escaping: backslash,
        double-quote, and line-feed — an unescaped peer address or file path
        must never produce unparseable exposition text."""
        return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

    @staticmethod
    def _escape_help(v: str) -> str:
        """HELP text escaping per 0.0.4: backslash and line-feed only."""
        return v.replace("\\", "\\\\").replace("\n", "\\n")

    @classmethod
    def _label_str(cls, labels: tuple, extra: str = "") -> str:
        parts = [f'{k}="{cls._escape_label_value(str(v))}"' for k, v in labels]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    @staticmethod
    def _fmt(v: float) -> str:
        if v == math.inf:
            return "+Inf"
        if float(v).is_integer():
            return str(int(v))
        return repr(float(v))

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            families = dict(self._families)
            series = dict(self._series)
        lines: list[str] = []
        for name in sorted(families):
            kind, help = families[name]
            if help:
                lines.append(f"# HELP {name} {self._escape_help(help)}")
            lines.append(f"# TYPE {name} {kind}")
            for (sname, labels), m in sorted(series.items()):
                if sname != name:
                    continue
                if isinstance(m, (Counter, Gauge)):
                    lines.append(
                        f"{name}{self._label_str(labels)} {self._fmt(m.value)}"
                    )
                else:
                    cum = 0
                    for bound, n in zip(m.bounds, m.bucket_counts):
                        cum += n
                        le = self._label_str(labels, f'le="{self._fmt(bound)}"')
                        lines.append(f"{name}_bucket{le} {cum}")
                    le = self._label_str(labels, 'le="+Inf"')
                    lines.append(f"{name}_bucket{le} {m.count}")
                    lines.append(
                        f"{name}_sum{self._label_str(labels)} {self._fmt(m.sum)}"
                    )
                    lines.append(
                        f"{name}_count{self._label_str(labels)} {m.count}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON-serializable state: counters/gauges by series, histograms with
        count/sum/quantiles AND raw buckets (the operator's one-call answer, no
        PromQL needed — and :meth:`merge`'s input format).

        Every value is a plain JSON type: non-finite floats become ``null``
        and anything non-coercible is dropped with a warning, so a snapshot
        round-trips through strict JSON and ``merge`` can trust its input."""
        with self._lock:
            families = dict(self._families)
            series = dict(self._series)
        out: dict = {"ts": time.time(), "metrics": {}}
        for (name, labels), m in sorted(series.items()):
            kind, help = families[name]
            entry: dict = {"type": kind, "labels": dict(labels), "help": help}
            if isinstance(m, Counter):
                entry["value"] = m.value
            elif isinstance(m, Gauge):
                entry["value"] = m.value
                entry["ts"] = m.ts
            else:
                entry.update(
                    count=m.count,
                    sum=m.sum,
                    p50=m.quantile(0.50),
                    p90=m.quantile(0.90),
                    p95=m.quantile(0.95),
                    p99=m.quantile(0.99),
                    buckets={
                        "bounds": list(m.bounds),
                        "counts": list(m.bucket_counts),
                    },
                )
            out["metrics"].setdefault(name, []).append(entry)
        return _plain_json(out)

    def merge(self, snapshot: dict, extra_labels: Optional[dict] = None) -> None:
        """Fold one :meth:`snapshot` document into this registry.

        The merge algebra (what makes a tree of partial merges equal the flat
        merge — associative AND commutative):

        - **counters** sum;
        - **gauges** are last-writer-wins by each entry's ``ts`` (value
          tie-break — see :meth:`Gauge.merge_lww`);
        - **histograms** add bucket-wise (bounds must match; count and sum
          add; quantile reservoirs are not transported — buckets are the
          merged truth).

        This is the aggregation step of the push path: every rank publishes
        its snapshot up the store topology and any node can fold the set —
        or a subtree's partial fold — into one job-level registry without
        ever touching another rank's files.

        ``extra_labels`` are stamped onto every series of the incoming
        snapshot *before* the fold (overriding same-named snapshot labels) —
        the fleet-federation step: merging two jobs' snapshots under distinct
        ``job=`` labels keeps their same-named series separate instead of
        summing ``tpu_restarts_total`` across unrelated jobs
        (``tools/fleetd.py``). Series that already carry the label from an
        earlier labelled merge re-merge idempotently, so a tree of labelled
        partial merges still equals the flat labelled merge.
        """
        metrics = snapshot.get("metrics") if isinstance(snapshot, dict) else None
        if not isinstance(metrics, dict):
            raise ValueError("not a metrics snapshot (missing 'metrics' dict)")
        default_ts = snapshot.get("ts")
        if not isinstance(default_ts, (int, float)):
            default_ts = 0.0
        extra = {
            str(k): str(v) for k, v in (extra_labels or {}).items()
        }
        for name, entries in sorted(metrics.items()):
            if not isinstance(entries, list):
                continue
            for e in entries:
                if not isinstance(e, dict):
                    continue
                kind = e.get("type")
                labels = {
                    str(k): str(v)
                    for k, v in (e.get("labels") or {}).items()
                }
                labels.update(extra)
                help = e.get("help") or ""
                if kind == "counter":
                    v = e.get("value")
                    if isinstance(v, (int, float)) and v > 0:
                        self.counter(name, help, **labels).inc(v)
                elif kind == "gauge":
                    v = e.get("value")
                    ts = e.get("ts")
                    if isinstance(v, (int, float)):
                        self.gauge(name, help, **labels).merge_lww(
                            v, ts if isinstance(ts, (int, float)) else default_ts
                        )
                elif kind == "histogram":
                    b = e.get("buckets") or {}
                    bounds = tuple(b.get("bounds") or ())
                    counts = b.get("counts") or []
                    if not bounds or len(counts) != len(bounds) + 1:
                        continue  # pre-merge-format snapshot: not mergeable
                    h = self.histogram(name, help, bounds, **labels)
                    if h.bounds != bounds:
                        raise ValueError(
                            f"histogram {name!r}: bucket bounds mismatch "
                            f"({h.bounds} != {bounds})"
                        )
                    h.merge_counts(counts, e.get("count") or 0, e.get("sum") or 0.0)

    def write_json(self, path: str) -> None:
        """Atomic snapshot-to-file (tmp + rename): a scraper reading the path
        mid-write never sees a torn document. The document is strict JSON
        (``snapshot`` already coerced or dropped anything that isn't)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, indent=2, allow_nan=False)
            f.write("\n")
        os.replace(tmp, path)


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (what :class:`MetricsSink` feeds)."""
    return _default_registry


# -- events → metrics bridge ------------------------------------------------

def observe_record(rec: dict, reg: MetricsRegistry) -> None:
    """Route one event record (JSONL dict or flattened Event) into metrics.

    The single kind→metric mapping shared by the live sink and the post-hoc
    aggregator; unknown kinds still land in ``tpu_events_total`` so a new
    emitter is visible before this table learns its name.
    """
    kind = rec.get("kind")
    if not isinstance(kind, str):
        return
    reg.counter("tpu_events_total", "structured events by kind", kind=kind).inc()
    if kind == "rendezvous_round":
        reg.counter(
            "tpu_rendezvous_rounds_total", "rendezvous rounds entered"
        ).inc()
        if isinstance(rec.get("world_size"), (int, float)):
            reg.gauge("tpu_world_size", "last observed world size").set(
                rec["world_size"]
            )
        if isinstance(rec.get("round"), (int, float)):
            reg.gauge("tpu_rendezvous_round", "last rendezvous round").set(
                rec["round"]
            )
    elif kind == "iteration_start":
        # Stateful derivation: a step's wall clock is the delta between this
        # rank's consecutive iteration_start markers. State lives on the
        # registry (aux_state) so the live sink and a post-hoc aggregate()
        # replay compute the identical histogram. Only a strictly-consecutive
        # iteration within the gap cap counts — a repeat after an in-process
        # restart, or a multi-minute gap, is downtime, not a step.
        ts, it = rec.get("ts"), rec.get("iteration")
        if isinstance(ts, (int, float)) and isinstance(it, int):
            st = reg.aux_state("step_timing")
            prev = st.get(rec.get("pid"))
            if (
                prev is not None and it == prev[1] + 1
                and 0 < ts - prev[0] <= step_gap_max_s()
            ):
                reg.histogram(
                    "tpu_step_seconds",
                    "training step wall clock (consecutive iteration_start "
                    "deltas per rank)",
                    STEP_BUCKETS_S,
                ).observe(ts - prev[0])
            st[rec.get("pid")] = (ts, it)
    elif kind == "goodput_update":
        # Emitted by the goodput ledger (utils/goodput.py) with per-phase
        # attribution DELTAS since its previous publish, so replaying the
        # stream reconstructs the same monotonic totals the live sink held.
        phases = rec.get("phases")
        if isinstance(phases, dict):
            for phase, delta in sorted(phases.items()):
                if isinstance(delta, (int, float)) and delta > 0:
                    reg.counter(
                        "tpu_time_attributed_seconds_total",
                        "job wall clock attributed by the goodput ledger "
                        "(train | ckpt_stall | restart | incident | "
                        "unattributed)",
                        phase=str(phase),
                    ).inc(delta)
        if isinstance(rec.get("ratio"), (int, float)):
            reg.gauge(
                "tpu_goodput_ratio",
                "fraction of job wall clock attributed to training",
            ).set(rec["ratio"])
    elif kind == "restart_requested":
        reg.counter(
            "tpu_restarts_total", "restart rounds by layer", layer="injob"
        ).inc()
    elif kind == "restart_signalled":
        reg.counter(
            "tpu_restarts_total", "restart rounds by layer", layer="inprocess"
        ).inc()
    elif kind == "restart_budget":
        if isinstance(rec.get("used"), (int, float)):
            reg.gauge(
                "tpu_restart_budget_used", "restart budget consumed"
            ).set(rec["used"])
    elif kind == "worker_failed":
        reg.counter("tpu_worker_failures_total", "worker process failures").inc()
    elif kind == "worker_promoted":
        # outcome: promoted | dead_at_promotion | cold_fallback (pre-label
        # events from older builds read as plain promotions)
        reg.counter(
            "tpu_spare_promotions_total",
            "warm-spare promotion attempts by outcome "
            "(promoted | dead_at_promotion | cold_fallback)",
            outcome=str(rec.get("outcome", "promoted")),
        ).inc()
    elif kind == "warm_spare_pool":
        if isinstance(rec.get("warm"), (int, float)):
            reg.gauge(
                "tpu_warm_spares_warm",
                "parked spares currently warm (ready to promote)",
            ).set(rec["warm"])
    elif kind == "rendezvous_fast_path":
        reg.counter(
            "tpu_rendezvous_fast_path_total",
            "restart fast-path rendezvous attempts by outcome "
            "(reused | shrink | abandoned)",
            outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "compile_cache":
        reg.counter(
            "tpu_compile_cache_total",
            "persistent compilation cache applications by outcome "
            "(hit | miss | miss_corrupt)",
            outcome=str(rec.get("outcome", "?")),
        ).inc()
        if isinstance(rec.get("bytes"), (int, float)):
            reg.gauge(
                "tpu_compile_cache_bytes",
                "persistent compilation cache size at last application",
            ).set(rec["bytes"])
    elif kind in ("hang_detected", "health_terminated"):
        reg.counter(
            "tpu_rank_terminations_total", "monitor-initiated terminations",
            cause="hang" if kind == "hang_detected" else "health",
        ).inc()
    elif kind == "kill_ladder":
        reg.counter(
            "tpu_kill_ladder_total", "termination signals by step",
            step=str(rec.get("step", "?")),
        ).inc()
    elif kind == "stack_dump":
        reg.counter(
            "tpu_stack_dumps_total",
            "all-thread stack captures by reason (hang forensics)",
            reason=str(rec.get("reason", "?")).split(":", 1)[0],
        ).inc()
    elif kind == "hang_census":
        # One census per hang verdict (the launcher's failure path), not per
        # /hangz scrape — scrapes are read-only so the suspect counter stays
        # "suspects per incident", not "suspects times curl".
        suspects = rec.get("suspects")
        if isinstance(suspects, list):
            for s in suspects:
                r = s.get("rank") if isinstance(s, dict) else s
                if isinstance(r, int):
                    reg.counter(
                        "tpu_hang_suspects_total",
                        "ranks implicated by a hang census, by rank",
                        rank=str(r),
                    ).inc()
        blocked = rec.get("blocked")
        if isinstance(blocked, dict):
            for r, secs in sorted(blocked.items()):
                if isinstance(secs, (int, float)):
                    reg.gauge(
                        "tpu_rank_blocked_seconds",
                        "per-rank stuck duration at the last hang census",
                        rank=str(r),
                    ).set(secs)
        if isinstance(rec.get("barrier_waiters"), (int, float)):
            reg.gauge(
                "tpu_barrier_waiters",
                "ranks parked in open barrier rounds at the last census",
            ).set(rec["barrier_waiters"])
    elif kind == "budget_exhausted":
        reg.counter(
            "tpu_budget_exhausted_total", "restart budget exhaustions"
        ).inc()
    elif kind == "ckpt_saved":
        reg.counter("tpu_ckpt_saves_total", "durable checkpoint saves").inc()
        if isinstance(rec.get("bytes"), (int, float)):
            reg.histogram(
                "tpu_ckpt_bytes", "checkpoint bytes per save",
                (2**10, 2**16, 2**20, 2**24, 2**27, 2**30, 2**33, 2**36),
            ).observe(rec["bytes"])
    elif kind == "ckpt_save_incomplete":
        reg.counter(
            "tpu_ckpt_save_failures_total", "coverage-failed checkpoint saves"
        ).inc()
    elif kind == "ckpt_quarantined":
        # A quarantine IS an integrity failure (stage says where it was
        # caught); the dedicated counter additionally tracks file volume.
        reg.counter(
            "tpu_ckpt_integrity_failures_total",
            "checkpoint integrity failures by ladder stage "
            "(local-read quarantine, peer-retrieve, replicate/stream receive)",
            stage=str(rec.get("stage", "?")),
        ).inc()
        reg.counter(
            "tpu_ckpt_quarantined_total",
            "checkpoint containers quarantined to *.corrupt for forensics",
        ).inc()
    elif kind == "ckpt_integrity_failure":
        reg.counter(
            "tpu_ckpt_integrity_failures_total",
            "checkpoint integrity failures by ladder stage "
            "(local-read quarantine, peer-retrieve, replicate/stream receive)",
            stage=str(rec.get("stage", "?")),
        ).inc()
    elif kind == "ckpt_unverified":
        reg.counter(
            "tpu_ckpt_unverified_total",
            "containers loaded/received without checksum verification "
            "(v1 format or foreign checksum algorithm)",
        ).inc()
    elif kind == "ckpt_fallback":
        reg.counter(
            "tpu_ckpt_fallback_total",
            "recovery-ladder fallbacks to an older checkpoint iteration",
        ).inc()
    elif kind == "ckpt_parity":
        # One event per erasure replication round on the sending rank.
        if isinstance(rec.get("received"), (int, float)):
            reg.counter(
                "tpu_ckpt_parity_blocks_total",
                "erasure blocks exchanged, by direction",
                direction="received",
            ).inc(rec["received"])
        if isinstance(rec.get("sent_blocks"), (int, float)):
            reg.counter(
                "tpu_ckpt_parity_blocks_total",
                "erasure blocks exchanged, by direction",
                direction="sent",
            ).inc(rec["sent_blocks"])
        if isinstance(rec.get("sent_bytes"), (int, float)):
            reg.counter(
                "tpu_ckpt_parity_bytes_total",
                "erasure block bytes shipped to clique peers (the wire cost "
                "that replaces (n-1)x full mirrors)",
            ).inc(rec["sent_bytes"])
    elif kind == "ckpt_parity_reconstruct":
        reg.counter(
            "tpu_ckpt_parity_reconstructions_total",
            "k-of-n shard reconstructions from erasure blocks, by outcome "
            "(a 'failed' outcome degraded to peer retrieve, never a "
            "false-positive container)",
            outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "ckpt_delta":
        # One event per delta replication round on the sending rank.
        reg.counter(
            "tpu_ckpt_delta_saves_total",
            "replication rounds shipped as chunk-diff delta frames",
        ).inc()
        for label, key in (("shipped", "frame_bytes"), ("full", "full_bytes")):
            if isinstance(rec.get(key), (int, float)):
                reg.counter(
                    "tpu_ckpt_delta_bytes_total",
                    "delta replication byte economy: frame bytes shipped vs "
                    "the full container bytes a mirror round would have moved",
                    kind=label,
                ).inc(rec[key])
        if isinstance(rec.get("chunks_changed"), (int, float)):
            reg.counter(
                "tpu_ckpt_delta_chunks_total",
                "chunks shipped by delta rounds (the dirty set)",
            ).inc(rec["chunks_changed"])
    elif kind == "ckpt_delta_applied":
        reg.counter(
            "tpu_ckpt_delta_applied_total",
            "received delta frames applied against held base containers, by "
            "outcome ('broken' = chain mismatch, mirror dropped for the "
            "round)",
            outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "world_resized":
        reg.counter(
            "tpu_world_resized_total",
            "elastic world-size transitions across rendezvous rounds, by "
            "direction",
            direction=str(rec.get("direction", "?")),
        ).inc()
    elif kind == "reshard_plan":
        # One event per participating rank per resharded resume, so the
        # counter reads as ranks-through-reshard by direction.
        reg.counter(
            "tpu_reshard_ranks_total",
            "ranks that completed a resharded checkpoint resume, by "
            "direction (shrink / grow / resplit)",
            direction=str(rec.get("direction", "?")),
        ).inc()
    elif kind == "reshard_fetch":
        if isinstance(rec.get("bytes"), (int, float)):
            reg.counter(
                "tpu_reshard_bytes_total",
                "bytes assembled into resharded local shards, by source "
                "(local container slice vs peer ranged fetch)",
                source=str(rec.get("via", "?")),
            ).inc(rec["bytes"])
    elif kind == "reshard_serve":
        reg.counter(
            "tpu_reshard_serve_ranges_total",
            "byte ranges served to resharding peers, by serve mode (parallel "
            "= bounded pread/verify worker pool, serial = single range or "
            "pool disabled)",
            mode=str(rec.get("mode", "?")),
        ).inc(rec.get("ranges", 1) or 1)
    elif kind == "reshard_overlap":
        reg.counter(
            "tpu_reshard_parallel_fetches_total",
            "peer range-fetch batches issued concurrently with local "
            "pread/assembly during resharded resume",
        ).inc(rec.get("fetches", 1) or 1)
        if isinstance(rec.get("duration_s"), (int, float)):
            reg.histogram(
                "tpu_reshard_overlap_seconds",
                "wall time of the overlapped fetch+assembly phase per "
                "resharded resume",
            ).observe(rec["duration_s"])
    elif kind == "ckpt_foreground_blocked":
        if isinstance(rec.get("duration_s"), (int, float)):
            reg.histogram(
                "tpu_ckpt_foreground_blocked_seconds",
                "caller-visible train-loop stall per checkpoint save",
                FOREGROUND_BUCKETS_S, engine=str(rec.get("engine", "?")),
            ).observe(rec["duration_s"])
    elif kind == "staging_pool":
        if isinstance(rec.get("pool_bytes"), (int, float)):
            reg.gauge(
                "tpu_ckpt_staging_pool_bytes",
                "host staging buffer pool size (allocated bytes)",
            ).set(rec["pool_bytes"])
        if isinstance(rec.get("in_use_bytes"), (int, float)):
            reg.gauge(
                "tpu_ckpt_staging_inuse_bytes",
                "host staging bytes currently leased to in-flight saves",
            ).set(rec["in_use_bytes"])
        outcome = rec.get("outcome")
        if outcome in ("hit", "miss", "wait"):
            reg.counter(
                "tpu_ckpt_staging_requests_total",
                "staging lease acquisitions by outcome",
                outcome=str(outcome),
            ).inc()
    elif kind == "ckpt_write_file":
        container = str(rec.get("container", "?"))
        if isinstance(rec.get("bytes"), (int, float)):
            reg.counter(
                "tpu_ckpt_write_bytes_total",
                "container bytes written by content class (main vs "
                "separation-hint file)",
                container=container,
            ).inc(rec["bytes"])
        if isinstance(rec.get("leaves"), (int, float)):
            reg.counter(
                "tpu_ckpt_write_leaves_total",
                "tensor leaves written by content class",
                container=container,
            ).inc(rec["leaves"])
    elif kind == "p2p_transfer":
        d = str(rec.get("direction", "?"))
        if isinstance(rec.get("bytes"), (int, float)):
            reg.counter(
                "tpu_ckpt_replication_bytes_total",
                "checkpoint shard bytes moved over p2p links",
                direction=d,
            ).inc(rec["bytes"])
        if isinstance(rec.get("mbps"), (int, float)):
            reg.histogram(
                "tpu_replication_mbps", "p2p shard transfer throughput (MB/s)",
                THROUGHPUT_BUCKETS_MBPS, direction=d,
            ).observe(rec["mbps"])
    elif kind == "store_stats":
        # Periodic self-telemetry deltas from the coordination store's event
        # loop (platform/store.py + utils/opstats.py): counters carry
        # movement since the previous emit, so replaying the stream
        # reconstructs the live totals exactly.
        ops = rec.get("ops")
        if isinstance(ops, dict):
            for op, n in sorted(ops.items()):
                if isinstance(n, (int, float)) and n > 0:
                    reg.counter(
                        "tpu_store_ops_total",
                        "coordination-store operations served, by op",
                        op=str(op),
                    ).inc(n)
        secs = rec.get("op_seconds")
        if isinstance(secs, dict):
            for op, s in sorted(secs.items()):
                if isinstance(s, (int, float)) and s > 0:
                    reg.counter(
                        "tpu_store_op_seconds",
                        "seconds of store event-loop handle time, by op "
                        "(rate ÷ tpu_store_ops_total rate = mean handle "
                        "latency; quantiles live in the store_stats doc)",
                        op=str(op),
                    ).inc(s)
        for field, direction in (("bytes_in", "in"), ("bytes_out", "out")):
            v = rec.get(field)
            if isinstance(v, (int, float)) and v > 0:
                reg.counter(
                    "tpu_store_bytes_total",
                    "coordination-store wire bytes by direction",
                    direction=direction,
                ).inc(v)
        if isinstance(rec.get("conns"), (int, float)):
            reg.gauge(
                "tpu_store_conns", "live coordination-store connections"
            ).set(rec["conns"])
    elif kind == "byteflow_update":
        # The byte-flow ledger's per-(purpose,direction) attribution deltas
        # (utils/byteflow.py) — same delta discipline as goodput_update.
        flows = rec.get("flows")
        if isinstance(flows, dict):
            for key, nbytes in sorted(flows.items()):
                if not isinstance(nbytes, (int, float)) or nbytes <= 0:
                    continue
                purpose, _, direction = str(key).partition("/")
                reg.counter(
                    "tpu_byteflow_bytes_total",
                    "bytes moved, attributed by the byte-flow ledger "
                    "(purpose: replicate | retrieve | reshard | store | "
                    "ckpt_write | unknown)",
                    purpose=purpose, direction=direction or "?",
                ).inc(nbytes)
        if isinstance(rec.get("residue_bytes"), (int, float)) and rec["residue_bytes"] > 0:
            reg.counter(
                "tpu_byteflow_residue_bytes",
                "bytes the ledger observed but could not attribute to a "
                "purpose (unknown-tag wire traffic) — the gap instrument",
            ).inc(rec["residue_bytes"])
        if isinstance(rec.get("accounted_ratio"), (int, float)):
            reg.gauge(
                "tpu_byteflow_accounted_ratio",
                "fraction of observed bytes the ledger attributed to a "
                "purpose (the ≥0.95 acceptance gate)",
            ).set(rec["accounted_ratio"])
    elif kind == "store_retry":
        reg.counter(
            "tpu_store_retries_total",
            "store-client transparent transport retries by op and outcome "
            "(retried per attempt; recovered/exhausted once per call)",
            op=str(rec.get("op", "?")), outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "store_failover":
        reg.counter(
            "tpu_store_failover_total",
            "clique-client shard failovers to the successor replica, by "
            "failed shard and outcome (read | mutate | barrier | absorbed "
            "once per failed-over op; replica_skipped once per degraded "
            "mirror write)",
            shard=str(rec.get("shard", "?")),
            outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "shard_epoch":
        reg.counter(
            "tpu_store_reshards_total",
            "clique shard-map epoch transitions by phase "
            "(migrating | settled | adopted)",
            outcome=str(rec.get("outcome", "?")),
        ).inc()
        if isinstance(rec.get("epoch"), (int, float)):
            reg.gauge(
                "tpu_store_epoch",
                "current clique shard-map epoch (0 = launch map, never "
                "resharded)",
            ).set(rec["epoch"])
    elif kind == "store_auto_reshard":
        reg.counter(
            "tpu_store_auto_reshards_total",
            "automatic shard respawns driven by the launcher supervisor "
            "(--store-auto-reshard), by outcome (ok | failed)",
            outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "coldtier_spilled":
        reg.counter(
            "tpu_coldtier_spills_total",
            "keyframe containers archived to the cold tier by the async "
            "spiller (one per finalized owner shard)",
        ).inc()
        if isinstance(rec.get("bytes"), (int, float)):
            reg.counter(
                "tpu_coldtier_bytes_total",
                "bytes shipped to the cold tier by the async spiller",
            ).inc(rec["bytes"])
    elif kind == "coldtier_degraded":
        reg.counter(
            "tpu_coldtier_degraded_total",
            "cold-tier spills dropped to local-only, by reason "
            "(upload-failed after retry exhaustion | breaker-open while the "
            "backend circuit breaker cools down); the save itself succeeded",
            reason=str(rec.get("reason", "?")),
        ).inc()
    elif kind == "coldtier_pruned":
        reg.counter(
            "tpu_coldtier_pruned_total",
            "cold-tier artifacts removed by keyframe-aware retention "
            "(--cold-keep), one per (iteration, owner)",
        ).inc()
    elif kind == "coldtier_fetch":
        reg.counter(
            "tpu_coldtier_fetch_total",
            "cold-tier restore fetches by mode (full | header | ranged) and "
            "outcome (ok | corrupt: manifest digest mismatch, restore "
            "refused fail-closed)",
            mode=str(rec.get("mode", "?")),
            outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "peer_degraded":
        reg.counter(
            "tpu_replication_peer_degraded_total",
            "replication peers dropped for a round after transfer-retry "
            "exhaustion (the save proceeded with reduced redundancy)",
        ).inc()
    elif kind == "chaos_inject":
        reg.counter(
            "chaos_faults_injected_total",
            "network faults injected by the chaos plan",
            kind=str(rec.get("fault", "?")), channel=str(rec.get("channel", "?")),
        ).inc()
    elif kind == "incident_opened":
        reg.counter(
            "tpu_incidents_total",
            "incidents opened by the incident engine, by trigger",
            trigger=str(rec.get("trigger", "?")),
        ).inc()
        reg.gauge(
            "tpu_incidents_open", "incidents currently open"
        ).inc()
    elif kind == "incident_closed":
        reg.gauge("tpu_incidents_open", "incidents currently open").dec()
        # Literal names on purpose: the docs-drift gate
        # (tests/utils/test_metrics_doc.py) extracts them by AST.
        if isinstance(rec.get("time_to_detect_s"), (int, float)):
            reg.histogram(
                "tpu_incident_time_to_detect_seconds",
                "fault evidence -> incident opened, per incident",
            ).observe(rec["time_to_detect_s"])
        if isinstance(rec.get("time_to_decide_s"), (int, float)):
            reg.histogram(
                "tpu_incident_time_to_decide_seconds",
                "incident opened -> first decision, per incident",
            ).observe(rec["time_to_decide_s"])
        if isinstance(rec.get("time_to_recover_s"), (int, float)):
            reg.histogram(
                "tpu_incident_time_to_recover_seconds",
                "fault evidence -> recovered, per incident",
            ).observe(rec["time_to_recover_s"])
        if isinstance(rec.get("steps_lost"), (int, float)):
            reg.counter(
                "tpu_incident_steps_lost_total",
                "training steps lost across incidents (resume gap)",
            ).inc(max(0.0, rec["steps_lost"]))
    elif kind == "autoscale_decision":
        reg.counter(
            "tpu_autoscale_decisions_total",
            "autoscale controller decisions by action and actuation outcome "
            "(advised = advise mode, never acted)",
            action=str(rec.get("action", "?")),
            outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "autoscale_outcome":
        # One per settled decision: the controller's forecast accuracy as a
        # first-class metric (realized minus predicted goodput delta).
        p, r = rec.get("predicted_delta_s"), rec.get("realized_delta_s")
        if isinstance(p, (int, float)) and isinstance(r, (int, float)):
            reg.histogram(
                "tpu_autoscale_predicted_vs_realized",
                "autoscale forecast error per settled decision "
                "(realized minus predicted goodput delta, seconds)",
                FORECAST_ERROR_BUCKETS_S,
                action=str(rec.get("action", "?")),
            ).observe(r - p)
    elif kind == "preemption_rescinded":
        reg.counter(
            "tpu_preemption_rescinded_total",
            "preemption notices withdrawn before their grace window elapsed "
            "(the deferred drain/save was cancelled)",
        ).inc()
    elif kind == "fleet_scrape":
        # One per fleetd scrape fan-out (tools/fleetd.py): how many jobs the
        # fleet control plane currently sees and what a full scrape costs.
        if isinstance(rec.get("jobs"), (int, float)):
            reg.gauge(
                "tpu_fleet_jobs",
                "jobs with a live discovery lease at the last fleet scrape",
            ).set(rec["jobs"])
        if isinstance(rec.get("unreachable"), (int, float)):
            reg.gauge(
                "tpu_fleet_jobs_unreachable",
                "leased jobs whose telemetry endpoint failed the last scrape",
            ).set(rec["unreachable"])
        if isinstance(rec.get("duration_s"), (int, float)):
            reg.histogram(
                "tpu_fleet_scrape_seconds",
                "wall clock of one full fleet scrape (parallel fan-out over "
                "every live job)",
            ).observe(rec["duration_s"])
    elif kind == "fleet_job_unreachable":
        # One per failed per-job scrape: the job stays on the scoreboard as
        # `unreachable`; this counter is the rate of that degradation.
        reg.counter(
            "tpu_fleet_scrape_errors_total",
            "per-job scrape failures during fleet aggregation, by job "
            "(the job is marked unreachable, the fleet endpoints keep serving)",
            job=str(rec.get("job", "?")),
        ).inc()
    elif kind == "remediation_action":
        reg.counter(
            "tpu_remediation_actions_total",
            "automated remediation actions by action and outcome",
            action=str(rec.get("action", "?")),
            outcome=str(rec.get("outcome", "?")),
        ).inc()
    elif kind == "flight_flush":
        reg.counter(
            "tpu_flight_flushes_total",
            "flight-recorder consolidated dumps by reason",
            reason=str(rec.get("reason", "?")),
        ).inc()
    elif kind == "heartbeat_stats":
        if isinstance(rec.get("max_gap_s"), (int, float)):
            reg.histogram(
                "tpu_heartbeat_gap_seconds", "per-session max heartbeat gap"
            ).observe(rec["max_gap_s"])
    elif kind == "timing":
        d = rec.get("duration_s")
        if isinstance(d, (int, float)):
            reg.histogram(
                "tpu_timing_seconds", "@prof / debug_time durations",
                name=str(rec.get("name", "?")),
            ).observe(d)
        if rec.get("ok") is False:
            reg.counter(
                "tpu_timing_failures_total", "timed blocks that raised",
                name=str(rec.get("name", "?")),
            ).inc()
    elif kind == "span_end":
        d = rec.get("duration_s")
        if isinstance(d, (int, float)):
            reg.histogram(
                "tpu_span_seconds", "span durations by name",
                span=str(rec.get("span", "?")),
            ).observe(d)
        if rec.get("ok") is False:
            reg.counter(
                "tpu_span_failures_total", "spans that raised",
                span=str(rec.get("span", "?")),
            ).inc()
    elif kind == "alert_fired":
        # Watchtower transitions (telemetry/watchtower.py) mirror the
        # incident counter+gauge pattern: total by rule/severity, plus the
        # currently-firing gauge the resolve decrements.
        reg.counter(
            "tpu_alerts_total",
            "watchtower alerts fired, by rule and severity",
            rule=str(rec.get("rule", "?")),
            severity=str(rec.get("severity", "?")),
        ).inc()
        reg.gauge(
            "tpu_alerts_active", "watchtower alerts currently firing"
        ).inc()
    elif kind == "alert_resolved":
        reg.gauge(
            "tpu_alerts_active", "watchtower alerts currently firing"
        ).dec()


def aggregate(
    records: Iterable[dict], reg: Optional[MetricsRegistry] = None
) -> MetricsRegistry:
    """Replay a finished run's records into a (fresh by default) registry."""
    reg = MetricsRegistry() if reg is None else reg
    for rec in records:
        if isinstance(rec, dict):
            observe_record(rec, reg)
    return reg


def flatten_event(event) -> dict:
    """One Event → the flat record shape its JSONL line would carry.

    The single flattening (including the ``p_``-rename of payload keys that
    collide with the envelope) shared by :class:`MetricsSink` and the
    watchtower's sink — live in-process consumers and post-hoc file replays
    must see byte-identical record shapes.
    """
    if hasattr(event, "to_record"):
        return event.to_record()
    rec = {
        "ts": event.ts, "source": event.source, "kind": event.kind,
        "pid": event.pid, "rank": event.rank,
        **{f"p_{k}" if k in RESERVED_KEYS else k: v
           for k, v in event.payload.items()},
    }
    if getattr(event, "job", None) is not None:
        rec["job"] = event.job
    return rec


class MetricsSink:
    """``events.add_sink`` bridge: one ``record()`` call feeds both streams.

    Optionally snapshots the registry to ``json_path`` at most every
    ``snapshot_interval`` seconds (piggybacked on event arrivals — no extra
    thread to leak into forked workers) plus once at interpreter exit, so the
    file always reflects the process's final state.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        json_path: Optional[str] = None,
        snapshot_interval: float = 10.0,
    ):
        self.registry = registry if registry is not None else get_registry()
        self.json_path = json_path
        self.snapshot_interval = snapshot_interval
        self._last_snapshot = 0.0
        if json_path is not None:
            import atexit

            atexit.register(self._final_snapshot)

    def _final_snapshot(self) -> None:
        try:
            self.registry.write_json(self.json_path)
        except Exception:
            pass  # observability, not control flow

    def __call__(self, event) -> None:
        # Same flat shape as the JSONL line (including the p_-rename of payload
        # keys that collide with the envelope), minus the json round-trip.
        observe_record(flatten_event(event), self.registry)
        if self.json_path is not None:
            now = time.monotonic()
            if now - self._last_snapshot >= self.snapshot_interval:
                self._last_snapshot = now
                self.registry.write_json(self.json_path)


class MetricsPublisher(MetricsSink):
    """``events.add_sink`` bridge that pushes snapshots up the coordination
    store instead of (or alongside) dropping files.

    The scale story: a scraper of an N-rank job must not open N per-rank
    snapshot files. Each rank periodically publishes its registry snapshot to
    one store key (``<prefix><identity>``) — piggybacked on event arrivals
    like :class:`MetricsSink`'s file snapshots, so no thread leaks into forked
    workers — and the launcher's telemetry endpoint folds the key range into
    one job-level registry with :meth:`MetricsRegistry.merge`. Because the
    merge is associative/commutative, intermediate nodes of a large store
    clique can fold subtrees before forwarding (the O(log N) aggregation path
    ROADMAP item 3 builds toward).

    The identity is ``r<rank>-<pid>`` (``p<pid>`` when rankless): a restarted
    rank publishes under a NEW key, and the merge sums both incarnations'
    counters instead of losing the first one to a same-key overwrite.

    A push failure never breaks the workload: errors are contained, and the
    next attempt waits out ``interval`` like a successful push would.
    """

    def __init__(
        self,
        host: str,
        port: int,
        prefix: str = "jobmetrics/default/",
        *,
        registry: Optional[MetricsRegistry] = None,
        interval: float = 2.0,
        identity: Optional[str] = None,
    ):
        # A PRIVATE registry by default: the publisher must not double-count
        # events into the process-wide registry another sink already feeds.
        super().__init__(registry=registry or MetricsRegistry())
        self._host = host
        self._port = port
        self._prefix = prefix
        self._interval = interval
        self._store: Any = None
        self._last_push = 0.0
        if identity is None:
            rank_s = os.environ.get("RANK")
            identity = (
                f"r{rank_s}-{os.getpid()}"
                if rank_s and rank_s.isdigit() else f"p{os.getpid()}"
            )
        self.identity = identity
        import atexit

        atexit.register(self._final_push)

    @classmethod
    def from_env_spec(cls, spec: str) -> "MetricsPublisher":
        """Parse ``host:port[:prefix]`` (the $TPU_RESILIENCY_METRICS_PUSH
        value the launcher exports to its workers)."""
        parts = spec.split(":", 2)
        if len(parts) < 2 or not parts[1].isdigit():
            raise ValueError(f"bad metrics-push spec {spec!r}: want host:port[:prefix]")
        host, port = parts[0] or "127.0.0.1", int(parts[1])
        prefix = parts[2] if len(parts) == 3 and parts[2] else "jobmetrics/default/"
        return cls(host, port, prefix)

    def _connect(self):
        if self._store is None:
            # Lazy import: metrics must not pull the platform layer in at
            # module load (events -> metrics stays the dependency root path).
            from tpu_resiliency.platform.shardstore import connect_store
            from tpu_resiliency.platform.store import AUTH_KEY_ENV

            self._store = connect_store(
                self._host, self._port, prefix=self._prefix,
                timeout=10.0, connect_retries=1, retry_budget=2.0,
                auth_key=os.environ.get(AUTH_KEY_ENV) or None,
            )
        return self._store

    def push(self) -> None:
        """Publish the current snapshot under this process's identity key."""
        self._connect().set(self.identity, self.registry.snapshot())

    def _final_push(self) -> None:
        try:
            self.push()
        except Exception:
            pass  # interpreter exit: the store may already be gone

    def close(self) -> None:
        if self._store is not None:
            try:
                self._store.close()
            except Exception:
                pass
            self._store = None

    def __call__(self, event) -> None:
        super().__call__(event)
        now = time.monotonic()
        if now - self._last_push >= self._interval:
            # Stamp BEFORE attempting: a dead store must not be re-dialed on
            # every single event (the interval is also the failure backoff).
            self._last_push = now
            try:
                self.push()
            except Exception:
                log.debug("metrics snapshot push failed", exc_info=True)
