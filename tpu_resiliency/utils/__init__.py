from tpu_resiliency.utils.logging import get_logger, RankLoggerAdapter

__all__ = ["get_logger", "RankLoggerAdapter"]
