"""Native extensions build. Pure-Python metadata lives in pyproject.toml.

Extensions are optional at runtime: every consumer falls back to a pure-Python path
when the compiled module is absent (e.g. `tpu_resiliency/inprocess/progress_watchdog.py`
falls back to a ctypes trampoline). Build in-place with:

    python setup.py build_ext --inplace
"""

from setuptools import Extension, setup

setup(
    ext_modules=[
        Extension(
            "tpu_resiliency._probe_native",
            sources=["native/probe.c"],
            extra_compile_args=["-O2", "-std=c11"],
        ),
        Extension(
            "tpu_resiliency._ringstats",
            sources=["native/ringstats.c"],
            extra_compile_args=["-O2", "-std=c11"],
        ),
    ]
)
