"""Store-channel hardening: transparent retry of idempotent ops, at-most-once
req_id dedup for non-idempotent ops, close()-during-retry, try_get on a dead
transport."""

import socket
import threading
import time

import pytest

from tpu_resiliency.exceptions import StoreError, StoreTimeoutError, StoreTransportError
from tpu_resiliency.platform import chaos, framing
from tpu_resiliency.platform.store import (
    CoordStore,
    KVClient,
    KVServer,
    _client_hello,
)
from tpu_resiliency.utils import events
from tpu_resiliency.utils.metrics import aggregate


@pytest.fixture(autouse=True)
def _clean_plan():
    chaos.clear_plan()
    yield
    chaos.clear_plan()


def _raw_conn(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=10.0)
    _client_hello(s, None)
    return s


# -- transparent retry (idempotent ops) -------------------------------------


@pytest.mark.chaos
def test_idempotent_ops_survive_one_reset_each(kv_server):
    """Acceptance: a single injected connection reset per op class surfaces NO
    caller-visible exception."""
    ops = [
        lambda st: st.set("k", 1),
        lambda st: st.get("k", timeout=1.0),
        lambda st: st.touch("hb/0"),
        lambda st: st.check(["k"]),
        lambda st: st.prefix_get(""),
        lambda st: st.client.stale_keys("hb/", 9999.0),
        lambda st: st.barrier_status("nope"),
        lambda st: st.ping(),
    ]
    seed_store = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
    seed_store.set("k", 1)
    seed_store.close()
    for i, op in enumerate(ops):
        # Plan installed BEFORE dialing: sockets are chaos-wrapped at connect
        # time. The first send frame of the fresh client is the op itself.
        plan = chaos.ChaosPlan.parse(f"{i}:store.send.reset@at=0")
        chaos.install_plan(plan)
        st = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        op(st)  # must not raise
        assert plan.schedule() == [("store", "send", "reset", 0)]
        chaos.clear_plan()
        st.close()


@pytest.mark.chaos
def test_retry_survives_truncated_response(kv_server):
    """Mid-frame truncation of a RESPONSE (recv side) reconnects and reissues."""
    seed_store = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
    seed_store.set("x", "v0")
    seed_store.close()
    # recv op indices on a fresh client: hello(0,1), set resp — none here, so
    # the get's response reads are ops 2,3; truncate the length prefix read.
    chaos.install_plan(chaos.ChaosPlan.parse("0:store.recv.truncate@at=2"))
    st = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
    assert st.get("x", timeout=2.0) == "v0"
    st.close()


@pytest.mark.chaos
def test_retry_emits_store_retry_events(kv_server):
    seen = []
    events.add_sink(seen.append)
    try:
        # Plan installed BEFORE the client dials: sockets are wrapped at
        # connect time, so a pre-existing connection is never chaosed.
        chaos.install_plan(chaos.ChaosPlan.parse("0:store.send.reset@at=0"))
        st = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        st.set("k", 1)
        chaos.clear_plan()
        st.close()
    finally:
        events.remove_sink(seen.append)
    kinds = [(e.kind, e.payload.get("outcome")) for e in seen if e.kind == "store_retry"]
    assert ("store_retry", "retried") in kinds
    assert ("store_retry", "recovered") in kinds
    # ...and the events→metrics bridge aggregates them into the counter.
    recs = [
        {"kind": e.kind, **e.payload} for e in seen if e.kind == "store_retry"
    ]
    reg = aggregate(recs)
    prom = reg.to_prometheus()
    assert 'tpu_store_retries_total{op="set",outcome="recovered"} 1' in prom


def test_breaker_makes_later_calls_fail_fast_and_recovers():
    """One exhausted retry budget opens the per-endpoint breaker: subsequent
    calls (any client of that endpoint) fail in milliseconds instead of each
    burning a fresh budget. A server coming back closes it again."""
    server = KVServer(host="127.0.0.1", port=0)
    port = server.port
    c1 = CoordStore("127.0.0.1", port, timeout=5.0, retry_budget=0.6)
    c2 = CoordStore("127.0.0.1", port, timeout=5.0, retry_budget=0.6)
    server.close()
    time.sleep(0.1)
    t0 = time.monotonic()
    with pytest.raises(StoreError):
        c1.set("k", 1)  # pays the full budget, trips the breaker
    first = time.monotonic() - t0
    assert first >= 0.4
    t0 = time.monotonic()
    for c in (c1, c2, c1):
        with pytest.raises(StoreError):
            c.set("k", 1)  # breaker open: fail fast, shared across clients
    assert time.monotonic() - t0 < 0.5 * 3
    # Same port comes back: breaker closes on the first success after cooldown.
    server2 = KVServer(host="127.0.0.1", port=port)
    try:
        deadline = time.monotonic() + 10.0
        while True:
            try:
                c1.set("k", 2)
                break
            except StoreError:
                assert time.monotonic() < deadline, "breaker never recovered"
                time.sleep(0.2)
        assert c2.get("k", timeout=2.0) == 2
    finally:
        c1.close()
        c2.close()
        server2.close()


def test_retry_budget_exhaustion_raises_transport_error():
    """No server at all: the retry budget must bound the stall and surface a
    StoreTransportError (a StoreError subclass — existing handlers still work)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listening here
    t0 = time.monotonic()
    with pytest.raises(StoreTransportError):
        KVClient("127.0.0.1", port, connect_retries=1, retry_budget=0.5)
    assert time.monotonic() - t0 < 10.0


# -- satellite: close() during _connect retry --------------------------------


def test_connect_retry_loop_honors_close():
    """close() while the client is reconnect-looping against a dead server must
    abort the loop promptly instead of sleeping out the remaining retries."""
    server = KVServer(host="127.0.0.1", port=0)
    client = CoordStore("127.0.0.1", server.port, timeout=5.0)
    server.close()

    errors = {}

    def call():
        try:
            # Dead server: _call retries _connect (many slow attempts).
            client.client._call({"op": "ping"})
        except Exception as e:
            errors["e"] = e
            errors["t"] = time.monotonic()

    t = threading.Thread(target=call)
    t.start()
    time.sleep(0.3)
    t0 = time.monotonic()
    client.close()
    t.join(timeout=5.0)
    assert not t.is_alive(), "call still retrying after close()"
    assert isinstance(errors["e"], StoreError)
    assert errors["t"] - t0 < 3.0, "close() did not interrupt the retry loop"


# -- satellite: try_get returns default on transport failure -----------------


def test_try_get_returns_default_on_dead_transport():
    server = KVServer(host="127.0.0.1", port=0)
    client = CoordStore("127.0.0.1", server.port, timeout=5.0,
                        retry_budget=0.3)
    assert client.try_get("missing") is None  # normal miss
    server.close()
    time.sleep(0.1)
    # Dead persistent socket + dead server: transport-level StoreError inside;
    # the opportunistic read must still just report the default.
    assert client.try_get("anything", default="fallback") == "fallback"
    client.close()
    # ...but a CLOSED client is a caller bug, not a transport blip.
    with pytest.raises(StoreError):
        client.try_get("anything")


# -- satellite: server-side req_id dedup -------------------------------------


def test_retried_add_with_req_id_applies_once(kv_server):
    """First attempt lands, response is lost, retry must replay — counter 1."""
    s1 = _raw_conn(kv_server.port)
    framing.send_obj(s1, {"op": "add", "key": "c", "amount": 1, "req_id": "r:1"})
    assert framing.recv_obj(s1)["value"] == 1
    s1.close()  # response "lost"; client reconnects
    s2 = _raw_conn(kv_server.port)
    framing.send_obj(s2, {"op": "add", "key": "c", "amount": 1, "req_id": "r:1"})
    assert framing.recv_obj(s2)["value"] == 1, "retried add double-applied"
    framing.send_obj(s2, {"op": "get", "key": "c", "timeout": 1.0})
    assert framing.recv_obj(s2)["value"] == 1
    # A DIFFERENT req_id is a genuinely new request.
    framing.send_obj(s2, {"op": "add", "key": "c", "amount": 1, "req_id": "r:2"})
    assert framing.recv_obj(s2)["value"] == 2
    s2.close()


def test_retried_list_append_and_cas_apply_once(kv_server):
    s = _raw_conn(kv_server.port)
    for _ in range(2):  # same req_id twice (retry)
        framing.send_obj(
            s, {"op": "list_append", "key": "l", "value": "x", "req_id": "r:la"})
        framing.recv_obj(s)
    framing.send_obj(s, {"op": "list_get", "key": "l"})
    assert framing.recv_obj(s)["value"] == ["x"], "retried list_append duplicated"
    # CAS: retry of a succeeded CAS must replay success, not observe-own-write.
    for _ in range(2):
        framing.send_obj(s, {"op": "cas", "key": "st", "expected": None,
                             "desired": "v1", "req_id": "r:cas"})
        ok, val = framing.recv_obj(s)["value"]
        assert ok and val == "v1", "retried CAS saw its own write as failure"
    s.close()


def test_retried_barrier_join_counts_one_arrival_across_reconnect(kv_server):
    """A blocking join arrives + parks; its connection dies; the retried join
    (same req_id, new connection) must re-wait — not overflow, not double-count
    — and release when the one missing rank arrives."""
    sA = _raw_conn(kv_server.port)
    framing.send_obj(sA, {"op": "barrier", "name": "b", "rank": 0,
                          "world_size": 2, "timeout": 20.0, "wait": True,
                          "req_id": "r:b0"})
    time.sleep(0.2)  # parked server-side
    sA.close()       # connection dies; arrival must stay
    sA2 = _raw_conn(kv_server.port)
    framing.send_obj(sA2, {"op": "barrier", "name": "b", "rank": 0,
                           "world_size": 2, "timeout": 20.0, "wait": True,
                           "req_id": "r:b0"})
    time.sleep(0.2)
    # Arrival count must still be 1 (not 2, which would release a 2-world round
    # with rank 1 missing).
    sQ = _raw_conn(kv_server.port)
    framing.send_obj(sQ, {"op": "barrier_status", "name": "b"})
    status = framing.recv_obj(sQ)["value"]
    assert status["arrived"] == {0}, status
    assert status["generation"] == 0, status
    # Rank 1 arrives: round releases; the retried join gets the generation.
    framing.send_obj(sQ, {"op": "barrier", "name": "b", "rank": 1,
                          "world_size": 2, "timeout": 20.0, "wait": True})
    assert framing.recv_obj(sQ)["value"] == 1
    got = framing.recv_obj(sA2)
    assert got == {"status": "ok", "value": 1}, got
    sA2.close()
    sQ.close()


def test_barrier_retry_after_release_replays_generation(kv_server):
    """Retry arriving AFTER the round released replays the recorded response."""
    sA = _raw_conn(kv_server.port)
    sB = _raw_conn(kv_server.port)
    framing.send_obj(sA, {"op": "barrier", "name": "b2", "rank": 0,
                          "world_size": 2, "timeout": 20.0, "wait": True,
                          "req_id": "r:x"})
    time.sleep(0.1)
    framing.send_obj(sB, {"op": "barrier", "name": "b2", "rank": 1,
                          "world_size": 2, "timeout": 20.0, "wait": True})
    assert framing.recv_obj(sB)["value"] == 1
    assert framing.recv_obj(sA)["value"] == 1  # original response delivered
    sA.close()
    # Late retry (the response above could have been lost in transit).
    sA2 = _raw_conn(kv_server.port)
    framing.send_obj(sA2, {"op": "barrier", "name": "b2", "rank": 0,
                           "world_size": 2, "timeout": 20.0, "wait": True,
                           "req_id": "r:x"})
    assert framing.recv_obj(sA2)["value"] == 1, "replay after release broken"
    sA2.close()
    sB.close()


@pytest.mark.chaos
def test_nonidempotent_ops_exact_under_injected_resets(kv_server):
    """End to end: adds through the real client under injected send resets and
    response truncations land exactly once each."""
    chaos.install_plan(chaos.ChaosPlan.parse(
        "0:store.send.reset@at=3;store.recv.truncate@at=8;store.send.truncate@at=12"
    ))
    st = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
    for _ in range(10):
        st.add("ctr", 1)
    chaos.clear_plan()
    assert st.get("ctr", timeout=2.0) == 10
    st.close()


def test_dedup_lru_is_bounded(kv_server):
    from tpu_resiliency.platform.store import _DEDUP_MAX

    s = _raw_conn(kv_server.port)
    for i in range(_DEDUP_MAX + 64):
        framing.send_obj(s, {"op": "add", "key": "n", "amount": 1,
                             "req_id": f"r:{i}"})
        framing.recv_obj(s)
    assert len(kv_server._dedup) <= _DEDUP_MAX
    s.close()
