import numpy as np
import pytest


def test_platform_and_counts():
    from tpu_resiliency.platform import device

    assert device.platform_kind() == "cpu"  # forced in conftest
    assert device.local_device_count() == 8
    assert device.global_device_count() == 8


def test_topology_probe():
    from tpu_resiliency.platform import device

    topo = device.probe_topology()
    assert topo.num_devices == 8
    assert topo.hosts() == [0]
    assert len(topo.devices_on_host(0)) == 8
    assert topo.host_of_device(topo.devices[0].device_id) == 0


def test_make_mesh():
    from tpu_resiliency.platform import device

    mesh = device.make_mesh({"dp": 2, "tp": 4})
    assert mesh.axis_names == ("dp", "tp")
    assert mesh.devices.shape == (2, 4)
    with pytest.raises(ValueError):
        device.make_mesh({"dp": 3})


def test_mesh_collective_runs():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_resiliency.platform import device

    mesh = device.make_mesh({"dp": 8})
    x = jnp.arange(16.0).reshape(8, 2)

    @jax.jit
    def total(v):
        return v.sum()

    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    np.testing.assert_allclose(total(xs), x.sum())


def test_device_liveness_probe():
    from tpu_resiliency.platform import device

    assert device.device_liveness_probe(timeout=60.0)
