import socket
import threading
import time

from tpu_resiliency.platform import ipc


def test_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    ipc.write_object(a, {"msg": "hello", "n": [1, 2, 3]})
    assert ipc.read_object(b) == {"msg": "hello", "n": [1, 2, 3]}
    a.close()
    b.close()


def test_receiver_collects_messages(tmp_uds_path):
    rx = ipc.IpcReceiver(tmp_uds_path)
    rx.start()
    try:
        for i in range(3):
            ipc.send_to(tmp_uds_path, {"i": i})
        deadline = time.time() + 5.0
        msgs = []
        while len(msgs) < 3 and time.time() < deadline:
            msgs += rx.fetch()
            time.sleep(0.01)
        assert sorted(m["i"] for m in msgs) == [0, 1, 2]
    finally:
        rx.stop()


def test_receiver_callback(tmp_uds_path):
    got = []
    evt = threading.Event()

    def cb(obj):
        got.append(obj)
        evt.set()

    rx = ipc.IpcReceiver(tmp_uds_path, on_message=cb)
    rx.start()
    try:
        ipc.send_to(tmp_uds_path, "ping")
        assert evt.wait(5.0)
        assert got == ["ping"]
    finally:
        rx.stop()
