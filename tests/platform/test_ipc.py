import socket
import threading
import time

from tpu_resiliency.platform import ipc


def test_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    ipc.write_object(a, {"msg": "hello", "n": [1, 2, 3]})
    assert ipc.read_object(b) == {"msg": "hello", "n": [1, 2, 3]}
    a.close()
    b.close()


def test_receiver_collects_messages(tmp_uds_path):
    rx = ipc.IpcReceiver(tmp_uds_path)
    rx.start()
    try:
        for i in range(3):
            ipc.send_to(tmp_uds_path, {"i": i})
        deadline = time.time() + 5.0
        msgs = []
        while len(msgs) < 3 and time.time() < deadline:
            msgs += rx.fetch()
            time.sleep(0.01)
        assert sorted(m["i"] for m in msgs) == [0, 1, 2]
    finally:
        rx.stop()


def test_receiver_callback(tmp_uds_path):
    got = []
    evt = threading.Event()

    def cb(obj):
        got.append(obj)
        evt.set()

    rx = ipc.IpcReceiver(tmp_uds_path, on_message=cb)
    rx.start()
    try:
        ipc.send_to(tmp_uds_path, "ping")
        assert evt.wait(5.0)
        assert got == ["ping"]
    finally:
        rx.stop()


def test_connect_retries_through_bind_listen_gap(tmp_uds_path):
    """The socket file appears at bind(); a loaded machine can deschedule the
    server before listen(). connect() must retry through both windows (no file
    yet, then ECONNREFUSED) instead of dying on a server milliseconds from
    ready — the 1-in-4 concurrency-soak flake."""
    path = tmp_uds_path

    def slow_server():
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)  # file exists now; connects get ECONNREFUSED
        time.sleep(0.4)
        srv.listen(1)
        conn, _ = srv.accept()
        ipc.write_object(conn, {"hello": 1})
        conn.close()
        srv.close()

    t = threading.Thread(target=slow_server, daemon=True)
    t.start()
    # Enter during the no-file / bound-not-listening windows.
    sock = ipc.connect(path, timeout=10.0)
    try:
        assert ipc.read_object(sock) == {"hello": 1}
    finally:
        sock.close()
    t.join(timeout=5)

    # And a server that never appears still fails, at the deadline.
    t0 = time.monotonic()
    try:
        ipc.connect(str(path) + ".absent", timeout=0.3)
        raise AssertionError("connect must fail for an absent server")
    except FileNotFoundError:
        pass
    assert 0.25 <= time.monotonic() - t0 < 5.0


def test_connect_restores_full_io_timeout_after_retries(tmp_uds_path):
    """A connect that lands late in the retry budget must still hand back a
    socket with the caller's FULL I/O timeout — not the leftover budget."""
    path = tmp_uds_path

    def slow_server():
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        time.sleep(0.6)
        srv.listen(1)
        srv.accept()  # hold the connection open

    t = threading.Thread(target=slow_server, daemon=True)
    t.start()
    sock = ipc.connect(path, timeout=1.0)  # ~0.4s of budget left at connect
    try:
        assert sock.gettimeout() == 1.0
    finally:
        sock.close()
