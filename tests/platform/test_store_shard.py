"""Sharded store clique (platform/shardstore.py): the client-side keyspace
partition must be invisible to every caller of the KVClient surface — keyed
ops route deterministically, fan-out ops merge losslessly, barriers and
watch-parks stay shard-local, and the aggregated store_stats document folds
the shards into one view with the shard map attached."""

import threading

import pytest

from tpu_resiliency.exceptions import BarrierOverflow, StoreTimeoutError
from tpu_resiliency.platform.shardstore import (
    CliqueStore,
    LocalClique,
    ShardedKVClient,
    connect_store,
    format_endpoints,
    parse_endpoints,
    shard_of,
)


@pytest.fixture
def clique():
    c = LocalClique(3)
    yield c
    c.close()


@pytest.fixture
def client(clique):
    c = ShardedKVClient(clique.endpoints, timeout=30.0)
    yield c
    c.close()


def test_shard_of_is_deterministic_and_spread():
    # Stable across calls (crc32, not salted hash()) and actually spreading.
    keys = [f"jobmetrics/default/{i}" for i in range(256)]
    first = [shard_of(k, 4) for k in keys]
    assert first == [shard_of(k, 4) for k in keys]
    assert len(set(first)) == 4  # all shards hit at 256 keys
    assert all(shard_of(k, 1) == 0 for k in keys)


def test_endpoint_spec_roundtrip():
    eps = [("127.0.0.1", 1000), ("10.0.0.2", 29511)]
    assert parse_endpoints(format_endpoints(eps)) == eps
    with pytest.raises(ValueError):
        parse_endpoints("  ,  ")


def test_keyed_ops_route_and_read_back(client, clique):
    # Keys land on exactly the shard the hash names — and only there.
    for i in range(32):
        client.set(f"k/{i}", i)
    assert len(client.prefix_get("k/")) == 32
    for i in range(32):
        owner = shard_of(f"k/{i}", 3)
        for si, srv in enumerate(clique.servers):
            held = f"k/{i}" in srv._data
            assert held == (si == owner), (i, si, owner)
    assert client.get("k/7", timeout=1.0) == 7
    assert client.add("ctr", 5) == 5
    ok, val = client.compare_set("cas", None, "v1")
    assert ok and client.get("cas", timeout=1.0) == "v1"
    assert client.delete("k/7") is True
    assert client.try_get("k/7", "gone") == "gone"


def test_fanout_ops_merge_across_shards(client):
    for i in range(24):
        client.set(f"m/{i}", i)
        client.touch(f"hb/{i}")
    client.list_append("l/x", 1)
    client.set_add("s/x", [1, 2])
    assert client.num_keys() == 24 + 24  # values + touch stamps (lists/sets live apart)
    assert len(client.prefix_get("m/")) == 24
    assert client.keys("m/") == sorted(f"m/{i}" for i in range(24))
    assert client.check([f"m/{i}" for i in range(24)])
    assert not client.check(["m/0", "m/nope"])
    assert client.stale_keys("hb/", max_age=3600.0) == {}
    assert client.prefix_clear("m/") == 24
    assert client.prefix_get("m/") == {}


def test_barrier_is_shard_local_and_released(client, clique):
    world = 4
    name = "elastic/round"
    owner = shard_of(name, 3)
    released = []

    def join(rank):
        client.barrier_join(name, rank, world, timeout=30.0)
        released.append(rank)

    threads = [threading.Thread(target=join, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert sorted(released) == list(range(world))
    # The barrier object lives on exactly the shard the name hashes to.
    for si, srv in enumerate(clique.servers):
        assert (name in srv._barriers) == (si == owner)
    # Census fans out and still finds it (by name, wherever it lives).
    assert name in client.barrier_names()
    st = client.barrier_status(name)
    assert st is not None and st["generation"] == 1
    # Overflow semantics intact through the shard route.
    client.barrier_join(name, 0, world, timeout=0.0, wait=False)
    with pytest.raises(BarrierOverflow):
        client.barrier_join(name, 0, world, timeout=0.0)


def test_parked_wait_wakes_through_the_shard(client):
    got = []

    def waiter():
        got.append(client.get("park/me", timeout=10.0))

    t = threading.Thread(target=waiter)
    t.start()
    client.set("park/me", "woken")
    t.join(10.0)
    assert got == ["woken"]
    with pytest.raises(StoreTimeoutError):
        client.get("park/never", timeout=0.05)


def test_store_stats_aggregates_shards(client, clique):
    for i in range(64):
        client.set(f"st/{i}", i)
    doc = client.store_stats()
    assert doc["enabled"] is True
    assert doc["backend"] == "epoll"
    assert doc["aggregate_of"] == 3
    assert doc["shard_map"]["nshards"] == 3
    assert doc["shard_map"]["hash"] == "crc32"
    assert len(doc["shards"]) == 3
    assert len(doc["shard_map"]["endpoints"]) == 3
    # Sampled tallies: the sum over shards accounts the storm's volume.
    assert sum(s["keys"] for s in doc["shards"]) == client.num_keys()
    # Every shard served some of the spread keyspace.
    assert all(s["backend"] == "epoll" for s in doc["shards"])


def test_clique_store_view_and_factory(clique, monkeypatch):
    cs = CliqueStore(clique.endpoints, prefix="ns/")
    try:
        cs.set("a", 1)
        assert cs.prefix_get("") == {"a": 1}
    finally:
        cs.close()
    # Factory: an explicit spec (or the env) yields a sharded view; a
    # 1-endpoint spec degenerates to the classic CoordStore.
    from tpu_resiliency.platform.shardstore import SHARDS_ENV
    from tpu_resiliency.platform.store import CoordStore

    st = connect_store("ignored", 1, shards=clique.spec)
    try:
        assert isinstance(st.client, ShardedKVClient)
        st.set("b", 2)
        assert st.get("b", timeout=1.0) == 2
    finally:
        st.close()
    one = format_endpoints(clique.endpoints[:1])
    st1 = connect_store("ignored", 1, shards=one)
    try:
        assert isinstance(st1, CoordStore)
        assert st1.client.port == clique.endpoints[0][1]
    finally:
        st1.close()
    monkeypatch.setenv(SHARDS_ENV, clique.spec)
    st2 = connect_store("127.0.0.1", clique.endpoints[0][1])
    try:
        assert isinstance(st2.client, ShardedKVClient)
        assert st2.get("b", timeout=1.0) == 2  # same keyspace as st
    finally:
        st2.close()


def test_dead_shard_fails_fast_not_silently(clique):
    """One dead shard: keyed ops against IT surface transport errors after
    that shard's own retry budget; keyed ops against live shards keep
    working; the aggregated stats degrade the dead shard's row only."""
    from tpu_resiliency.exceptions import StoreError

    c = ShardedKVClient(clique.endpoints, timeout=5.0, retry_budget=0.3)
    try:
        dead = 1
        clique.servers[dead].close()
        live_key = next(
            f"x/{i}" for i in range(64) if shard_of(f"x/{i}", 3) != dead
        )
        dead_key = next(
            f"x/{i}" for i in range(64) if shard_of(f"x/{i}", 3) == dead
        )
        c.set(live_key, "ok")
        assert c.get(live_key, timeout=1.0) == "ok"
        with pytest.raises(StoreError):
            c.set(dead_key, "nope")
        doc = c.store_stats()
        assert doc["enabled"] is True  # live shards still answer
        rows = {s["endpoint"]: s for s in doc["shards"]}
        dead_ep = f"{clique.endpoints[dead][0]}:{clique.endpoints[dead][1]}"
        assert rows[dead_ep]["enabled"] is False
        assert rows[dead_ep]["backend"] == "unreachable"
        # A clique client must also be CONSTRUCTIBLE while a shard is down
        # (shard connections are lazy): live-shard ops work immediately, the
        # dead shard only fails the op that actually routes to it.
        late = ShardedKVClient(
            clique.endpoints, timeout=5.0, connect_retries=1,
            retry_budget=0.3,
        )
        try:
            late.set(live_key, "still-ok")
            assert late.get(live_key, timeout=1.0) == "still-ok"
            with pytest.raises(StoreError):
                late.get(dead_key, timeout=0.1)
            assert late.store_stats()["enabled"] is True
        finally:
            late.close()
    finally:
        c.close()


def test_parallel_fanout_merge_is_order_independent(clique, client):
    """The prefix/scan/census fan-out runs shards CONCURRENTLY now: whatever
    order shards answer in, the merged result must be identical to the
    serial-era merge (disjoint keyspaces make this structural — this test
    pins it against regressions in the merge code)."""
    import random

    keys = [f"fan/{i}" for i in range(96)]
    for k in keys:
        client.set(k, k.upper())

    # Reference: per-shard serial merges in every shard permutation.
    per_shard = [
        clique.client().client._shard(i).prefix_get("fan/")
        for i in range(len(clique.endpoints))
    ]
    for perm in ([0, 1, 2], [2, 1, 0], [1, 2, 0]):
        merged = {}
        for i in perm:
            merged.update(per_shard[i])
        assert merged == client.prefix_get("fan/")

    # keys()/num_keys() agree with the merged view.
    assert client.keys("fan/") == sorted(merged)
    assert client.num_keys() >= len(keys)
    # Repeated concurrent fan-outs are stable (no racy partial merges).
    snap = client.prefix_get("fan/")
    for _ in range(8):
        assert client.prefix_get("fan/") == snap
    # And a keyed op mid-fan-out cannot corrupt the merge: clear returns the
    # exact number of keys the merged view showed.
    assert client.prefix_clear("fan/") == len(merged)
    assert client.prefix_get("fan/") == {}
