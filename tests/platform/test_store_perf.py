"""Slow-marked store-telemetry perf gates (scripts/bench_store.py harness):
the op-telemetry knob (default ON) must add <5% to client-observed p50 on a
seeded loopback op storm vs a ``stats_enabled=False`` control run, and the
storm harness itself must produce a sane latency curve + server-side account
— the regression anchors behind BENCH_store_baseline.json."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import bench_store  # noqa: E402

pytestmark = pytest.mark.slow


def test_telemetry_overhead_under_five_percent():
    """The <5% gate. Interleaved median-of-N on/off trials (background-load
    spikes hit both arms); one noise-guard retry — a real regression (the
    pre-sampling collector measured 8-18%) fails both batches, a scheduler
    hiccup does not."""
    res = bench_store.bench_overhead(clients=1, ops_per_client=1500, trials=9)
    if res["overhead_frac"] >= 0.05:
        retry = bench_store.bench_overhead(
            clients=1, ops_per_client=1500, trials=9
        )
        res = min((res, retry), key=lambda r: r["overhead_frac"])
    assert res["overhead_frac"] < 0.05, (
        f"op telemetry costs {100 * res['overhead_frac']:.1f}% p50 "
        f"(on {res['stats_on_p50_us']} us vs off {res['stats_off_p50_us']} us)"
    )


def test_scale_storm_holds_the_curve():
    """The sharded+tree "after" gate behind BENCH_store_scale.json: a
    1024-rank simulated storm (sharded clique, tree barrier DAG) must hold
    per-op p95 within 2× of a FRESH same-host flat 64-client measurement
    (host-relative, so shared-CI speed doesn't skew the ratio), the tree's
    critical-path hop count must win ≥4× at 256+, and the hash must actually
    spread the storm across the shards. One noise-guard retry, same policy
    as the overhead gate."""
    from tpu_resiliency.platform.treecomm import flat_hops, tree_hops

    assert flat_hops(256) / tree_hops(256, 8) >= 4.0
    assert flat_hops(4096) / tree_hops(4096, 8) >= 4.0

    flat64 = bench_store.bench_levels(levels=(64,), ops_per_client=300)
    flat_p95 = flat64["levels"][0]["p95_us"]
    storm = bench_store.bench_scale(ranks=1024, shards=2, procs=8, rounds=1)
    if storm["p95_us"] > 2.0 * flat_p95:
        storm = bench_store.bench_scale(ranks=1024, shards=2, procs=8,
                                        rounds=1)
    assert storm["p95_us"] <= 2.0 * flat_p95, (
        f"scale storm p95 {storm['p95_us']}us vs flat 64-client p95 "
        f"{flat_p95}us — the sharded curve no longer holds"
    )
    bal = storm["shard_balance"]
    assert bal["backend"] == "epoll"
    assert len(bal["per_shard_ops"]) == 2 and min(bal["per_shard_ops"]) > 0
    assert bal["busiest_shard_frac"] < 0.75, bal
    assert storm["hops"]["win"] >= 4.0


def test_storm_curve_and_server_account():
    """The latency-curve harness: client-observed quantiles are ordered and
    positive, and the server's own store_stats document accounts the storm
    (op counts in the right ballpark, wait/handle split populated)."""
    res = bench_store.bench_levels(levels=(1, 4), ops_per_client=400)
    by_clients = {r["clients"]: r for r in res["levels"]}
    for row in res["levels"]:
        assert 0 < row["p50_us"] <= row["p95_us"] <= row["p99_us"], row
        assert row["ops_per_s"] > 0
    # More concurrency on one loop means more queueing, never less.
    assert by_clients[4]["p50_us"] > by_clients[1]["p50_us"]
    stats = res["store_stats"]
    assert stats["enabled"] is True
    total_ops = sum(r["count"] for r in stats["ops"].values())
    real_ops = sum(r["ops"] for r in res["levels"])
    # Sampled estimate within a generous band of the true storm volume.
    assert 0.5 * real_ops <= total_ops <= 1.6 * real_ops, (total_ops, real_ops)
    hot = {r["prefix"] for r in stats["hot_prefixes"]}
    assert any(p.startswith("storm/") for p in hot), hot
    set_row = stats["ops"].get("set")
    assert set_row and set_row["handle"]["count"] > 0
    assert set_row["wait"]["count"] > 0


def test_failover_storm_holds_within_2x():
    """Storm-under-failover gate behind BENCH_store_scale.json's failover
    leg: with one shard SIGKILLed mid-clique, steady-state failover routing
    (successor reads + dedup'd mutate failover + skipped mirrors) must hold
    client-observed p95 within 2× of the healthy leg, and every op must
    still complete (no silent drops). One noise-guard retry, same policy as
    the other gates."""
    res = bench_store.bench_failover_storm(clients=4, ops_per_client=600,
                                           shards=3)
    if res["p95_ratio"] > 2.0:
        retry = bench_store.bench_failover_storm(clients=4,
                                                 ops_per_client=600, shards=3)
        res = min((res, retry), key=lambda r: r["p95_ratio"])
    assert res["degraded"]["ops"] == res["healthy"]["ops"], res
    assert res["p95_ratio"] <= 2.0, (
        f"degraded p95 {res['degraded']['p95_us']}us vs healthy "
        f"{res['healthy']['p95_us']}us — failover routing fell off the curve"
    )


def test_rendezvous_ladder_beats_flat():
    """The tree-laddered full rendezvous round (scattered joins + leader
    folds) must beat the flat CAS-retry ladder on wall clock at scale — the
    O(N) flat store-op bill is the thing the ladder exists to kill."""
    res = bench_store.bench_rendezvous_ladder(world=512, shards=2, procs=8)
    if res["wall_win"] <= 1.0:
        res = bench_store.bench_rendezvous_ladder(world=512, shards=2,
                                                  procs=8)
    assert res["wall_win"] > 1.0, (
        f"scattered ladder {res['scattered']['wall_s']}s vs flat "
        f"{res['flat']['wall_s']}s at world {res['world']}"
    )


def test_committed_bench_has_ha_legs():
    """The committed BENCH_store_scale.json must carry both PR legs at the
    gated thresholds: storm-under-failover p95 ≤ 2× healthy, and the
    4096-rank tree-laddered rendezvous beating the flat baseline."""
    import json

    path = os.path.join(REPO, "BENCH_store_scale.json")
    with open(path) as f:
        doc = json.load(f)
    fo = doc["failover"]
    assert fo["degraded"]["ops"] == fo["healthy"]["ops"], fo
    assert fo["p95_ratio"] <= 2.0, fo
    rl = doc["rendezvous_ladder"]
    assert rl["world"] >= 4096, rl
    assert rl["wall_win"] > 1.0, rl
