"""Slow-marked store-telemetry perf gates (scripts/bench_store.py harness):
the op-telemetry knob (default ON) must add <5% to client-observed p50 on a
seeded loopback op storm vs a ``stats_enabled=False`` control run, and the
storm harness itself must produce a sane latency curve + server-side account
— the regression anchors behind BENCH_store_baseline.json."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import bench_store  # noqa: E402

pytestmark = pytest.mark.slow


def test_telemetry_overhead_under_five_percent():
    """The <5% gate. Interleaved median-of-N on/off trials (background-load
    spikes hit both arms); one noise-guard retry — a real regression (the
    pre-sampling collector measured 8-18%) fails both batches, a scheduler
    hiccup does not."""
    res = bench_store.bench_overhead(clients=1, ops_per_client=1500, trials=9)
    if res["overhead_frac"] >= 0.05:
        retry = bench_store.bench_overhead(
            clients=1, ops_per_client=1500, trials=9
        )
        res = min((res, retry), key=lambda r: r["overhead_frac"])
    assert res["overhead_frac"] < 0.05, (
        f"op telemetry costs {100 * res['overhead_frac']:.1f}% p50 "
        f"(on {res['stats_on_p50_us']} us vs off {res['stats_off_p50_us']} us)"
    )


def test_storm_curve_and_server_account():
    """The latency-curve harness: client-observed quantiles are ordered and
    positive, and the server's own store_stats document accounts the storm
    (op counts in the right ballpark, wait/handle split populated)."""
    res = bench_store.bench_levels(levels=(1, 4), ops_per_client=400)
    by_clients = {r["clients"]: r for r in res["levels"]}
    for row in res["levels"]:
        assert 0 < row["p50_us"] <= row["p95_us"] <= row["p99_us"], row
        assert row["ops_per_s"] > 0
    # More concurrency on one loop means more queueing, never less.
    assert by_clients[4]["p50_us"] > by_clients[1]["p50_us"]
    stats = res["store_stats"]
    assert stats["enabled"] is True
    total_ops = sum(r["count"] for r in stats["ops"].values())
    real_ops = sum(r["ops"] for r in res["levels"])
    # Sampled estimate within a generous band of the true storm volume.
    assert 0.5 * real_ops <= total_ops <= 1.6 * real_ops, (total_ops, real_ops)
    hot = {r["prefix"] for r in stats["hot_prefixes"]}
    assert any(p.startswith("storm/") for p in hot), hot
    set_row = stats["ops"].get("set")
    assert set_row and set_row["handle"]["count"] > 0
    assert set_row["wait"]["count"] > 0
