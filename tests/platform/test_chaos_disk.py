"""The chaos ``disk`` channel: rule grammar, per-file deterministic schedules,
and each fault kind's observable effect on checkpoint containers."""

import os

import numpy as np
import pytest

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform import chaos

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_plan():
    chaos.clear_plan()
    yield
    chaos.clear_plan()


def _write(tmp_path, name="r0/iter_0000002_0_local.ckpt", n=1024):
    path = os.path.join(str(tmp_path), name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    ckpt_format.write_payload(path, b"h", [np.arange(n, dtype=np.float32)])
    return path


class TestGrammar:
    def test_disk_rules_parse_with_default_p(self):
        plan = chaos.ChaosPlan.parse(
            "9:disk.write.bitflip@peer=r0/iter_0000002_0_local.ckpt;"
            "disk.commit.torn-rename@at=1;disk.write.enospc@n=2;"
            "disk.write.slow-io@p=0.5,delay=0.001"
        )
        kinds = [r.kind for r in plan.rules]
        assert kinds == ["bitflip", "torn-rename", "enospc", "slow-io"]
        assert plan.rules[0].p == 1.0  # always-on kinds default p=1.0
        assert plan.rules[2].n == 2

    def test_network_kinds_still_require_schedule(self):
        with pytest.raises(ValueError, match="needs at= or p="):
            chaos.ChaosPlan.parse("1:store.send.reset")

    def test_disk_peer_names_holder_and_file(self):
        assert (
            chaos.disk_peer("/ssd/ckpt/s0/r1/iter_0000002_0_local.ckpt.dirty")
            == "r1/iter_0000002_0_local.ckpt"
        )


class TestBitflip:
    def test_deterministic_corruption_and_schedule(self, tmp_path):
        spec = "9:disk.write.bitflip@peer=r0/iter_0000002_0_local.ckpt"

        def run(sub):
            plan = chaos.ChaosPlan.parse(spec)
            chaos.install_plan(plan)
            try:
                path = _write(tmp_path / sub)
            finally:
                chaos.clear_plan()
            return plan.schedule(), open(path, "rb").read(), path

        s1, bytes1, p1 = run("a")
        s2, bytes2, _ = run("b")
        assert s1 == s2, "same-seed disk schedules diverged"
        assert bytes1 == bytes2, "bit-flip offsets not deterministic from seed"
        assert ckpt_format.verify_file(p1)[0] == "corrupt"
        with pytest.raises(CheckpointError):
            ckpt_format.read_payload(p1)

    def test_untargeted_files_untouched(self, tmp_path):
        chaos.install_plan(chaos.ChaosPlan.parse(
            "9:disk.write.bitflip@peer=r0/iter_0000002_0_local.ckpt"
        ))
        path = _write(tmp_path, name="r1/iter_0000002_0_local.ckpt")
        chaos.clear_plan()
        assert ckpt_format.verify_file(path)[0] == "ok"

    def test_wildcard_network_rules_never_touch_disk(self, tmp_path):
        chaos.install_plan(chaos.ChaosPlan.parse("5:*.*.reset@p=1.0"))
        path = _write(tmp_path)
        chaos.clear_plan()
        assert ckpt_format.verify_file(path)[0] == "ok"


class TestCommitFaults:
    @pytest.mark.parametrize("kind", ["truncate", "torn-rename"])
    def test_commit_fault_leaves_detectably_torn_file(self, tmp_path, kind):
        chaos.install_plan(chaos.ChaosPlan.parse(f"5:disk.commit.{kind}@at=0"))
        path = _write(tmp_path)
        chaos.clear_plan()
        assert os.path.exists(path), "commit faults still produce a visible file"
        status, detail = ckpt_format.verify_file(path)
        assert status == "corrupt" and "size mismatch" in detail
        with pytest.raises(CheckpointError, match="size mismatch"):
            ckpt_format.read_payload(path)


class TestEnospc:
    def test_enospc_raises_and_leaves_only_dirty(self, tmp_path):
        chaos.install_plan(chaos.ChaosPlan.parse("3:disk.write.enospc@at=0"))
        path = os.path.join(str(tmp_path), "r0", "iter_0000001_0_local.ckpt")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with pytest.raises(OSError) as exc:
            ckpt_format.write_payload(path, b"h", [np.ones(8, np.float32)])
        chaos.clear_plan()
        import errno

        assert exc.value.errno == errno.ENOSPC
        assert not os.path.exists(path)
        assert os.path.exists(path + ckpt_format.DIRTY_SUFFIX)


class TestSlowIo:
    def test_slow_io_delays_but_preserves_integrity(self, tmp_path):
        chaos.install_plan(chaos.ChaosPlan.parse(
            "4:disk.write.slow-io@n=1,delay=0.01"
        ))
        path = _write(tmp_path)
        chaos.clear_plan()
        assert ckpt_format.verify_file(path)[0] == "ok"


class TestEvents:
    def test_disk_injections_emit_chaos_events(self, tmp_path):
        from tpu_resiliency.utils import events

        seen = []
        events.add_sink(seen.append)
        chaos.install_plan(chaos.ChaosPlan.parse(
            "7:disk.write.bitflip@peer=r0/iter_0000002_0_local.ckpt,n=1"
        ))
        try:
            _write(tmp_path)
        finally:
            chaos.clear_plan()
            events.remove_sink(seen.append)
        inj = [e for e in seen if e.kind == "chaos_inject"]
        assert len(inj) == 1
        assert inj[0].payload["channel"] == "disk"
        assert inj[0].payload["fault"] == "bitflip"
        assert inj[0].payload["peer"] == "r0/iter_0000002_0_local.ckpt"
