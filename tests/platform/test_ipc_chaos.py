"""IPC channel hardening: connect cancellation, chaos absorption by the
connect retry loop and the receiver, monitor-client self-healing."""

import threading
import time

import pytest

from tpu_resiliency.platform import chaos, ipc

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_plan():
    chaos.clear_plan()
    yield
    chaos.clear_plan()


def test_connect_honors_cancel_during_retry(tmp_uds_path):
    """A caller shutting down while connect() retries against an absent server
    must get out promptly — not sleep out the full timeout."""
    cancel = threading.Event()
    errors = {}

    def dial():
        try:
            ipc.connect(tmp_uds_path, timeout=30.0, cancel=cancel)
        except Exception as e:
            errors["e"] = e
            errors["t"] = time.monotonic()

    t = threading.Thread(target=dial)
    t.start()
    time.sleep(0.3)  # solidly inside the retry loop
    t0 = time.monotonic()
    cancel.set()
    t.join(timeout=5.0)
    assert not t.is_alive(), "connect still retrying after cancel"
    assert isinstance(errors["e"], ConnectionAbortedError)
    assert errors["t"] - t0 < 1.0


def test_connect_retry_absorbs_injected_dial_faults(tmp_uds_path):
    """Injected resets at dial time are the same transient class the loop
    already retries — the connect still lands."""
    chaos.install_plan(chaos.ChaosPlan.parse("0:ipc.connect.reset@at=0+1"))
    rx = ipc.IpcReceiver(tmp_uds_path)
    rx.start()
    try:
        sock = ipc.connect(tmp_uds_path, timeout=10.0)
        sock.close()
    finally:
        rx.stop()
    plan = chaos.active_plan()
    assert [k for _, _, k, _ in plan.schedule()] == ["reset", "reset"]


def test_receiver_survives_truncated_and_eof_frames(tmp_uds_path):
    """Mid-frame truncation and EOF-on-accept drop only the affected message;
    the receiver keeps serving."""
    chaos.install_plan(chaos.ChaosPlan.parse(
        "0:ipc.accept.eof@at=1;ipc.send.truncate@at=3"
    ))
    rx = ipc.IpcReceiver(tmp_uds_path)
    rx.start()
    got = []
    try:
        for i in range(6):
            try:
                ipc.send_to(tmp_uds_path, {"i": i}, timeout=5.0)
            except (OSError, ConnectionError):
                pass  # the injected fault's victim
        deadline = time.time() + 5.0
        while len(got) < 4 and time.time() < deadline:
            got += rx.fetch()
            time.sleep(0.01)
    finally:
        rx.stop()
    indices = sorted(m["i"] for m in got)
    assert len(indices) >= 4, indices  # at most the 2 chaosed sends lost
    assert indices == sorted(set(indices))  # no duplicates


def test_monitor_client_heals_across_link_faults(tmp_uds_path):
    """The rank monitor link is self-healing: a reset or truncated reply
    reconnects + re-inits + replays, so heartbeats survive injected faults
    that previously would have crashed the rank."""
    from tpu_resiliency.watchdog.config import FaultToleranceConfig
    from tpu_resiliency.watchdog.monitor_client import RankMonitorClient
    from tpu_resiliency.watchdog.monitor_server import RankMonitorServer

    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=60.0,
        rank_heartbeat_timeout=60.0,
        workload_check_interval=0.5,
    )
    proc = RankMonitorServer.run_in_subprocess(cfg, tmp_uds_path,
                                               start_method="spawn")
    try:
        # Faults on the worker side of the link: one send reset, one reply
        # truncation, well inside the heartbeat sequence.
        chaos.install_plan(chaos.ChaosPlan.parse(
            "0:ipc.send.reset@at=2;ipc.recv.truncate@at=9"
        ))
        c = RankMonitorClient()
        c.init_workload_monitoring(socket_path=tmp_uds_path)
        for _ in range(6):
            c.send_heartbeat()  # must not raise
            time.sleep(0.02)
        c.shutdown_workload_monitoring()
        plan = chaos.active_plan()
        kinds = sorted(k for _, _, k, _ in plan.schedule())
        assert kinds == ["reset", "truncate"], plan.schedule()
    finally:
        chaos.clear_plan()
        proc.terminate()
        proc.join(timeout=10)
