"""KVServer scale soak: thousands of persistent clients against one coordinator.

The server is a single-threaded selector event loop (blocking requests park as
continuations, not threads), so live connections cost file descriptors rather
than stacks. This soak pins down the measured behavior at the advertised rank
counts — 4096 live connections, a full-world barrier, a world-wide heartbeat
tick, and the batched scans the detector/monitor paths rely on. The measured
numbers are recorded in the KVServer docstring (platform/store.py).
"""

import time

import pytest

from tpu_resiliency.platform.store import CoordStore


@pytest.fixture
def clients(kv_server):
    out = []
    yield out
    for c in out:
        try:
            c.close()
        except Exception:
            pass


@pytest.mark.parametrize("N", [1024, 4096])
def test_client_soak(kv_server, clients, N):
    import resource

    # Client + server socket per connection live in this one process, plus slack.
    need = 2 * N + 256
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if hard < need:
        pytest.skip(f"needs {need} fds, hard limit is {hard}")
    if soft < need:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (need, hard))
        except (ValueError, OSError):
            pytest.skip(f"needs {need} fds, limit is {soft}")

    t0 = time.perf_counter()
    for _ in range(N):
        clients.append(CoordStore("127.0.0.1", kv_server.port, timeout=120.0))
    connect_s = time.perf_counter() - t0

    # One small write + server-stamped heartbeat per rank (the per-tick pattern
    # of monitor processes).
    t0 = time.perf_counter()
    for i, c in enumerate(clients):
        c.set(f"soak/k/{i}", i)
        c.touch(f"soak/hb/{i}")
    write_s = time.perf_counter() - t0

    # Full-world barrier: every rank registers arrival (non-blocking joins — the
    # proxy-join path), then the last join releases the generation.
    t0 = time.perf_counter()
    for i, c in enumerate(clients):
        c.barrier_join("soak/barrier", i, N, timeout=0.0, wait=False)
    status = clients[0].barrier_status("soak/barrier")
    barrier_s = time.perf_counter() - t0
    assert status is not None and status["generation"] == 1

    # The batched reads the hot paths use: one prefix_get over the world's
    # summaries, one server-side stale scan over the world's heartbeats.
    t0 = time.perf_counter()
    everything = clients[0].prefix_get("soak/k/")
    scan = clients[0].stale_keys("soak/hb/", max_age=3600.0)
    read_s = time.perf_counter() - t0
    assert len(everything) == N
    assert scan == {}  # nothing stale

    total = connect_s + write_s + barrier_s + read_s
    print(
        f"\nsoak@{N}: connect {connect_s:.2f}s, {2 * N} ops {write_s:.2f}s "
        f"({2 * N / write_s:.0f} ops/s), barrier {barrier_s:.2f}s, "
        f"batched reads {read_s * 1e3:.1f}ms, total {total:.2f}s"
    )
    # Generous ceilings: the point is catching collapse (thread exhaustion,
    # quadratic scans), not micro-benchmarks on shared CI hardware.
    assert connect_s < 60.0
    assert write_s < 60.0
    assert barrier_s < 60.0
    assert read_s < 10.0


def test_concurrent_blocking_waiters(kv_server, clients):
    """128 clients blocking server-side in a waiting barrier join (each parked as a
    continuation on the event loop) must all release when the last rank joins."""
    import threading

    world = 128
    for _ in range(world):
        clients.append(CoordStore("127.0.0.1", kv_server.port, timeout=60.0))
    released = []
    lock = threading.Lock()

    def join(i):
        clients[i].barrier_join("soak/wait", i, world, timeout=30.0)
        with lock:
            released.append(i)

    threads = [threading.Thread(target=join, args=(i,)) for i in range(world - 1)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(0.3)  # everyone parked in the server-side wait
    clients[world - 1].barrier_join("soak/wait", world - 1, world, timeout=30.0)
    for t in threads:
        t.join(timeout=30.0)
    elapsed = time.perf_counter() - t0
    assert len(released) == world - 1
    assert elapsed < 30.0
