"""Successor-replicated clique HA (platform/shardstore.py): every key is
double-written to its primary and successor shard, reads/mutations/barriers
fail over once the primary's breaker opens, fan-outs absorb a dead shard via
its successor's replica keyspace, and the epoch protocol reshards a live
clique without a caller ever noticing. The 1-shard degeneracy contract:
replication enabled on a singleton clique must change NOTHING (successor ==
primary, zero double-writes)."""

import threading
import time

import pytest

from tpu_resiliency.platform.store import KVClient, KVServer
from tpu_resiliency.platform.shardstore import (
    EPOCH_KEY,
    LocalClique,
    ShardedKVClient,
    replicate_from_env,
    reshard_clique,
    shard_of,
    successor_of,
)
from tpu_resiliency.utils import events as tpu_events
from tpu_resiliency.utils.metrics import aggregate


@pytest.fixture
def seen():
    rec = []
    tpu_events.add_sink(rec.append)
    yield rec
    tpu_events.remove_sink(rec.append)


@pytest.fixture
def clique():
    c = LocalClique(3)
    yield c
    c.close()


@pytest.fixture
def client(clique):
    c = ShardedKVClient(
        clique.endpoints, timeout=30.0, connect_retries=2,
        retry_budget=0.3, replicate=True,
    )
    yield c
    c.close()


def key_on(shard: int, n: int, prefix: str = "ha/") -> str:
    """First key under ``prefix`` whose primary is ``shard``."""
    i = 0
    while True:
        k = f"{prefix}{i}"
        if shard_of(k, n) == shard:
            return k
        i += 1


def direct(clique, shard: int) -> KVClient:
    return KVClient("127.0.0.1", clique.servers[shard].port,
                    timeout=10.0, connect_retries=2, retry_budget=0.3)


def test_successor_math():
    assert successor_of(0, 3) == 1
    assert successor_of(2, 3) == 0
    # Singleton clique: the successor IS the primary — replication degenerates.
    assert successor_of(0, 1) == 0


def test_replicate_env_gate(monkeypatch):
    monkeypatch.delenv("TPU_RESILIENCY_STORE_REPLICATE", raising=False)
    assert replicate_from_env() is False
    monkeypatch.setenv("TPU_RESILIENCY_STORE_REPLICATE", "1")
    assert replicate_from_env() is True
    monkeypatch.setenv("TPU_RESILIENCY_STORE_REPLICATE", "off")
    assert replicate_from_env() is False


def test_double_write_lands_on_primary_and_successor(clique, client):
    k = key_on(0, 3)
    client.set(k, "v")
    d0, d1, d2 = (direct(clique, i) for i in range(3))
    try:
        assert d0.try_get(k) == "v"      # primary copy
        assert d1.try_get(k) == "v"      # successor replica
        assert d2.try_get(k) is None     # nowhere else
    finally:
        for d in (d0, d1, d2):
            d.close()


def test_one_shard_clique_degenerates_exactly(seen):
    """Satellite contract: replication on a 1-shard clique is a no-op —
    successor == primary, and a set mutates the key ONCE (the mirror branch
    never runs), byte-identical to a plain client."""
    single = LocalClique(1)
    try:
        repl = ShardedKVClient(single.endpoints, timeout=10.0, replicate=True)
        plain = ShardedKVClient(single.endpoints, timeout=10.0, replicate=False)
        try:
            # The server's version counter is global and bumps once per
            # mutation: a double-write by the replicated client would land
            # its key at version 2 and push the plain key to 3.
            repl.set("deg/replicated", 1)
            _, v_repl = repl.get_versioned("deg/replicated")
            assert v_repl == 1, "replicated set mutated the singleton twice"
            plain.set("deg/plain", 1)
            _, v_plain = plain.get_versioned("deg/plain")
            assert v_plain == 2
            assert not [e for e in seen if e.kind == "store_failover"]
        finally:
            repl.close()
            plain.close()
    finally:
        single.close()


def test_read_fails_over_to_successor(clique, client, seen):
    k = key_on(1, 3)
    client.set(k, 41)
    clique.servers[1].close()
    assert client.get(k, timeout=10.0) == 41  # served by shard 2's replica
    fo = [e for e in seen if e.kind == "store_failover"]
    assert any(e.payload.get("outcome") == "read" for e in fo), fo
    prom = aggregate(
        [{"kind": e.kind, **e.payload} for e in seen]
    ).to_prometheus()
    assert "tpu_store_failover_total" in prom


def test_failed_over_add_stays_exact(clique, client, seen):
    """The at-most-once dedup composed with the double-write: a counter
    keeps exact arithmetic across the failover boundary."""
    k = key_on(0, 3, prefix="ctr/")
    for _ in range(3):
        client.add(k, 1)
    clique.servers[0].close()
    for _ in range(2):
        client.add(k, 1)           # mutate failover onto shard 1
    assert client.get(k, timeout=10.0) == 5
    fo = [e for e in seen if e.kind == "store_failover"]
    assert any(e.payload.get("outcome") == "mutate" for e in fo), fo


def test_barrier_fails_over_mid_round(clique, seen):
    """SIGKILL-shaped loss of a barrier's shard mid-round: the parked joiner
    and the late joiner both complete on the successor's mirrored arrival
    ledger, with one release (same generation seen by both)."""
    name = key_on(2, 3, prefix="bar/")
    cs = [
        ShardedKVClient(clique.endpoints, timeout=30.0, connect_retries=2,
                        retry_budget=0.3, replicate=True)
        for _ in range(2)
    ]
    gens = {}
    try:
        t = threading.Thread(
            target=lambda: gens.__setitem__(
                0, cs[0].barrier_join(name, 0, 2, 20.0)
            )
        )
        t.start()
        time.sleep(0.3)            # rank 0 is parked on shard 2
        clique.servers[2].close()  # the primary dies mid-round
        gens[1] = cs[1].barrier_join(name, 1, 2, 20.0)
        t.join(20.0)
        assert not t.is_alive(), "parked joiner never failed over"
        assert gens[0] == gens[1] == 1, gens
        fo = [e for e in seen if e.kind == "store_failover"]
        assert any(e.payload.get("outcome") == "barrier" for e in fo), fo
    finally:
        for c in cs:
            c.close()


def test_fanout_absorbs_dead_shard(clique, client, seen):
    for i in range(30):
        client.set(f"fan/{i}", i)
    clique.servers[1].close()
    got = client.prefix_get("fan/")
    assert got == {f"fan/{i}": i for i in range(30)}
    assert set(client.keys("fan/")) == set(got)
    fo = [e for e in seen if e.kind == "store_failover"]
    assert any(e.payload.get("outcome") == "absorbed" for e in fo), fo


def test_store_stats_annotates_absorbing_successor(clique, client):
    client.set("st/one", 1)
    clique.servers[1].close()
    try:
        client.get(key_on(1, 3), timeout=0.5)   # tally at least one failover
    except Exception:
        pass
    doc = client.store_stats()
    assert doc["shard_map"]["replicate"] is True
    assert doc["shard_map"]["epoch"] == 0
    rows = doc["shards"]
    dead = [r for r in rows if r["backend"] == "unreachable"]
    assert len(dead) == 1
    assert dead[0]["absorbed_by"] == rows[2]["endpoint"]
    assert dead[0]["endpoint"] in rows[2].get("absorbing", [])
    assert doc.get("failover", {}).get("ops", 0) >= 1


def test_merge_stats_docs_ha_accounting():
    from tpu_resiliency.utils.opstats import merge_stats_docs

    docs = [
        {"enabled": True, "backend": "epoll", "endpoint": "h:1",
         "ops": {"set": {"count": 10}}},
        {"endpoint": "h:2", "error": "unreachable"},          # dead shard
        {"enabled": True, "backend": "epoll", "endpoint": "h:3",
         "ops": {"set": {"count": 20}}},
    ]
    out = merge_stats_docs(
        docs,
        successor_map={0: 1, 1: 2, 2: 0},
        failover_ops={1: 7},
    )
    rows = out["shards"]
    assert rows[1]["backend"] == "unreachable"
    assert rows[1]["absorbed_by"] == "h:3"
    assert rows[2]["absorbing"] == ["h:2"]
    assert rows[2]["failover_ops"] == 7
    assert out["failover"] == {"ops": 7, "by_shard": {1: 7}}
    # Attribution only: absorbed ops never double-sum into served totals.
    assert rows[2]["ops_total"] == 20


def test_reshard_grows_the_clique_live(clique, client, seen):
    extra = KVServer(host="127.0.0.1", port=0)
    try:
        for i in range(20):
            client.set(f"grow/{i}", i)
        doc = reshard_clique(client, clique.endpoints + [extra_ep(extra)])
        assert doc["epoch"] == 1 and doc["prev"] is None
        assert doc["migrated"] >= 20
        assert client._epoch == 1 and len(client.endpoints) == 4
        assert client.prefix_get("grow/") == {f"grow/{i}": i for i in range(20)}
        # New writes route per the NEW map (primary + successor of 4).
        k = key_on(3, 4, prefix="grow4/")
        client.set(k, "post")
        d = KVClient("127.0.0.1", extra.port, timeout=10.0)
        try:
            assert d.try_get(k) == "post"
        finally:
            d.close()
        kinds = [e.payload.get("outcome") for e in seen
                 if e.kind == "shard_epoch"]
        assert "migrating" in kinds and "settled" in kinds, kinds
    finally:
        extra.close()


def test_reshard_replaces_a_dead_shard(clique, client):
    for i in range(20):
        client.set(f"repl/{i}", i)
    clique.servers[1].close()          # dead — its keyspace lives on shard 2
    replacement = KVServer(host="127.0.0.1", port=0)
    try:
        new_eps = [clique.endpoints[0], extra_ep(replacement),
                   clique.endpoints[2]]
        doc = reshard_clique(client, new_eps)
        assert doc["epoch"] == 1
        assert client.prefix_get("repl/") == {f"repl/{i}": i for i in range(20)}
        # The replacement serves its slice of the new map.
        k = key_on(1, 3, prefix="repl2/")
        client.set(k, "fresh")
        d = KVClient("127.0.0.1", replacement.port, timeout=10.0)
        try:
            assert d.try_get(k) == "fresh"
        finally:
            d.close()
    finally:
        replacement.close()


def test_dual_route_window_covers_both_maps(clique, client):
    """With ``settle=False`` the transition window stays open: adopted
    clients dual-route (new-map writes land on the old map too; reads fall
    back to the old map for unmigrated keys) until a settling pass ends it."""
    extra = KVServer(host="127.0.0.1", port=0)
    old_eps = list(clique.endpoints)
    old_reader = ShardedKVClient(old_eps, timeout=10.0, replicate=True)
    try:
        client.set("win/seed", 0)
        new_eps = old_eps + [extra_ep(extra)]
        doc = reshard_clique(client, new_eps, settle=False)
        assert doc["prev"] is not None and client._prev_client is not None
        # New-map write reaches an old-map-only reader via the write-through.
        client.set("win/new", 1)
        assert old_reader.try_get("win/new") == 1
        # A key born on the OLD map mid-window is found via the read fallback.
        old_reader.set("win/straggler", 2)
        assert client.get("win/straggler", timeout=5.0) == 2
        # Settling (idempotent second pass, same endpoints) ends the window.
        doc = reshard_clique(client, new_eps)
        assert doc["prev"] is None and client._prev_client is None
    finally:
        old_reader.close()
        extra.close()


def extra_ep(server: KVServer) -> tuple:
    return ("127.0.0.1", server.port)


class TestAutoReshard:
    """Automatic shard respawn (launcher --store-auto-reshard): the
    supervisor notices a SIGKILL'd shard process, spawns a replacement, and
    drives reshard_clique onto the healed map — the operator runbook as a
    closed loop, audited as store_auto_reshard events."""

    def test_supervisor_respawns_sigkilled_shard(self, seen):
        from tpu_resiliency.platform.shardstore import (
            AutoReshardSupervisor,
            SpawnedClique,
        )

        clique = SpawnedClique(2)
        client = None
        sup = None
        try:
            client = ShardedKVClient(
                clique.endpoints, timeout=30.0, connect_retries=2,
                retry_budget=0.3, replicate=True,
            )
            for i in range(12):
                client.set(f"ar/{i}", i)
            victim = 1
            old_port = clique.endpoints[victim][1]
            clique.procs[victim].kill()
            clique.procs[victim].wait(10.0)
            sup = AutoReshardSupervisor(clique, client, interval=0.1, grace=0.2)
            sup.start()
            deadline = time.monotonic() + 30.0
            while sup.reshards == 0 and time.monotonic() < deadline:
                time.sleep(0.1)
            assert sup.reshards == 1, "supervisor never healed the clique"
            # The keyspace survived the kill + migration intact.
            assert client.prefix_get("ar/") == {f"ar/{i}": i for i in range(12)}
            # The replacement is a different server and answers directly.
            new_port = clique.endpoints[victim][1]
            assert new_port != old_port
            assert clique.procs[victim].poll() is None
            audits = [e for e in seen if e.kind == "store_auto_reshard"]
            assert audits and audits[-1].payload["outcome"] == "ok"
            assert audits[-1].payload["shard"] == victim
            # A healthy clique is left alone.
            time.sleep(0.5)
            assert sup.reshards == 1
        finally:
            if sup is not None:
                sup.stop()
            if client is not None:
                client.close()
            clique.close()
