"""Chaos layer unit tests: plan grammar, deterministic scheduling, socket
wrapper fault semantics, env wiring."""

import os
import socket
import threading

import pytest

from tpu_resiliency.platform import chaos

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_plan():
    chaos.clear_plan()
    yield
    chaos.clear_plan()


# -- grammar -----------------------------------------------------------------


def test_parse_full_spec():
    plan = chaos.ChaosPlan.parse(
        "42:store.send.reset@at=3;p2p.*.truncate@at=1+5,n=1;"
        "ipc.connect.delay@p=0.25,delay=0.2,jitter=0.1;"
        "p2p.connect.partition@peer=2,n=4"
    )
    assert plan.seed == 42
    r0, r1, r2, r3 = plan.rules
    assert (r0.channel, r0.op, r0.kind, r0.at, r0.n) == (
        "store", "send", "reset", frozenset({3}), 1)
    assert r1.op == "*" and r1.at == frozenset({1, 5}) and r1.n == 1
    assert r2.p == 0.25 and r2.delay == 0.2 and r2.jitter == 0.1 and r2.n is None
    assert r3.kind == "partition" and r3.peer == "2" and r3.n == 4


@pytest.mark.parametrize("bad", [
    "noseed",                      # missing seed separator
    "1:store.send",                # missing kind
    "1:bogus.send.reset@at=1",     # unknown channel
    "1:store.bogus.reset@at=1",    # unknown op
    "1:store.send.bogus@at=1",     # unknown kind
    "1:store.send.reset",          # no at=/p=
    "1:store.send.reset@wat=1",    # unknown param
])
def test_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        chaos.ChaosPlan.parse(bad)


def test_malformed_env_is_ignored_not_fatal(monkeypatch):
    monkeypatch.setenv(chaos.CHAOS_ENV, "not a spec")
    assert chaos.active_plan() is None


def test_env_wiring_and_precedence(monkeypatch):
    monkeypatch.setenv(chaos.CHAOS_ENV, "5:store.send.reset@at=0")
    plan = chaos.active_plan()
    assert plan is not None and plan.seed == 5
    # programmatic install overrides env until cleared
    mine = chaos.ChaosPlan.parse("6:ipc.send.eof@at=0")
    chaos.install_plan(mine)
    assert chaos.active_plan() is mine
    chaos.clear_plan()
    assert chaos.active_plan().seed == 5


# -- deterministic scheduling ------------------------------------------------


def test_at_rules_fire_at_exact_indices():
    plan = chaos.ChaosPlan.parse("0:store.send.reset@at=2+4")
    hits = [plan.check("store", "send") is not None for _ in range(6)]
    assert hits == [False, False, True, False, True, False]
    assert plan.schedule() == [
        ("store", "send", "reset", 2), ("store", "send", "reset", 4)]


def test_counters_are_per_channel_op():
    plan = chaos.ChaosPlan.parse("0:store.send.reset@at=1")
    assert plan.check("store", "recv") is None   # separate counter
    assert plan.check("p2p", "send") is None     # separate channel
    assert plan.check("store", "send") is None   # index 0
    assert plan.check("store", "send") is not None  # index 1


def test_budget_n_bounds_probabilistic_rule():
    plan = chaos.ChaosPlan.parse("0:store.send.reset@p=1.0,n=2")
    fired = sum(plan.check("store", "send") is not None for _ in range(10))
    assert fired == 2


def test_peer_scoped_rule_only_hits_that_peer():
    plan = chaos.ChaosPlan.parse("0:p2p.connect.partition@peer=3,p=1.0,n=10")
    assert plan.check("p2p", "connect", peer="1") is None
    assert plan.check("p2p", "connect", peer="3") is not None
    assert plan.check("p2p", "connect") is None  # unknown peer never matches


def test_schedule_is_reproducible_across_threads():
    def run():
        plan = chaos.ChaosPlan.parse("0:store.send.reset@at=5+11;store.recv.eof@at=3")
        def worker():
            for _ in range(10):
                plan.check("store", "send")
                plan.check("store", "recv")
        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return plan.schedule()

    assert run() == run() == [
        ("store", "recv", "eof", 3),
        ("store", "send", "reset", 5),
        ("store", "send", "reset", 11),
    ]


def test_random_spec_deterministic_and_covering():
    a, b = chaos.random_spec(99), chaos.random_spec(99)
    assert a == b
    plan = chaos.ChaosPlan.parse(a)
    per_channel = {}
    for r in plan.rules:
        per_channel.setdefault(r.channel, []).append(r.kind)
    assert set(per_channel) == set(chaos.CHANNELS)
    assert all(len(ks) == 2 for ks in per_channel.values())
    assert chaos.random_spec(99) != chaos.random_spec(100)


# -- socket wrapper ----------------------------------------------------------


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_wrap_is_identity_without_plan():
    a, b = _pair()
    try:
        assert chaos.wrap(a, "store") is a
    finally:
        a.close()
        b.close()


def test_reset_raises_and_closes():
    plan = chaos.ChaosPlan.parse("0:store.send.reset@at=1")
    a, b = _pair()
    wa = chaos.ChaosSocket(a, plan, "store")
    try:
        wa.sendall(b"ok")                 # index 0 passes through
        assert b.recv(16) == b"ok"
        with pytest.raises(ConnectionResetError):
            wa.sendall(b"boom")           # index 1 injected
        assert b.recv(16) == b""          # peer observes the close
    finally:
        a.close()
        b.close()


def test_truncate_delivers_partial_bytes_then_dies():
    plan = chaos.ChaosPlan.parse("0:store.send.truncate@at=0")
    a, b = _pair()
    wa = chaos.ChaosSocket(a, plan, "store")
    try:
        with pytest.raises(ConnectionResetError):
            wa.sendall(b"0123456789")
        got = b.recv(64)
        assert 1 <= len(got) <= 5          # a genuine partial frame
        assert b"0123456789".startswith(got)
        assert b.recv(64) == b""           # then EOF
    finally:
        a.close()
        b.close()


def test_recv_eof_and_stall():
    plan = chaos.ChaosPlan.parse("0:store.recv.stall@at=0,delay=0.01;store.recv.eof@at=2")
    a, b = _pair()
    wb = chaos.ChaosSocket(b, plan, "store")
    try:
        a.sendall(b"abcdef")
        assert wb.recv(1024) == b"a"       # stall: short single-byte read
        assert wb.recv(1024) == b"bcdef"   # index 1: clean
        assert wb.recv(1024) == b""        # index 2: injected EOF
    finally:
        a.close()
        b.close()


def test_connect_and_accept_hooks():
    plan = chaos.ChaosPlan.parse("0:ipc.connect.reset@at=0;ipc.accept.eof@at=0")
    chaos.install_plan(plan)
    with pytest.raises(ConnectionRefusedError):
        chaos.check_connect("ipc", peer="/tmp/x")
    assert chaos.check_accept("ipc") is True
    assert chaos.check_accept("ipc") is False
    assert plan.schedule() == [
        ("ipc", "accept", "eof", 0), ("ipc", "connect", "reset", 0)]
