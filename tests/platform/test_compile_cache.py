"""Persistent compilation cache: manifest integrity, the corrupt-entry →
cold-compile (never a crash) posture, env plumbing, and the compile_cache
event → tpu_compile_cache_total bridge."""

import json
import os
import subprocess
import sys

import pytest

from tpu_resiliency.platform import compile_cache
from tpu_resiliency.utils.metrics import MetricsRegistry, observe_record

JIT_SNIPPET = """
import json, os, sys, time
from tpu_resiliency.platform import device
device.apply_platform_env()
import jax, jax.numpy as jnp
t0 = time.monotonic()
f = jax.jit(lambda x: jnp.tanh(x @ x.T).sum())
val = float(jax.block_until_ready(f(jnp.ones((32, 32), jnp.float32))))
out = {"compile_ms": (time.monotonic() - t0) * 1e3, "val": val}
with open(sys.argv[1], "w") as fh:
    json.dump(out, fh)
"""


def _run_jit_worker(tmp_path, cache_dir, tag, extra_env=None):
    out = tmp_path / f"out_{tag}.json"
    events_file = tmp_path / f"events_{tag}.jsonl"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env[compile_cache.CACHE_DIR_ENV] = str(cache_dir)
    env["TPU_RESILIENCY_EVENTS_FILE"] = str(events_file)
    env.update(extra_env or {})
    r = subprocess.run(
        [sys.executable, "-c", JIT_SNIPPET, str(out)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    evs = [
        json.loads(ln) for ln in events_file.read_text().splitlines()
    ] if events_file.exists() else []
    cc = [e for e in evs if e.get("kind") == "compile_cache"]
    return json.loads(out.read_text()), cc


def _entries(cache_dir):
    return sorted(
        n for n in os.listdir(cache_dir) if n.endswith("-cache")
    )


def test_cold_then_warm_start_hits(tmp_path):
    cache = tmp_path / "cc"
    got0, cc0 = _run_jit_worker(tmp_path, cache, "cold")
    assert len(cc0) == 1 and cc0[0]["outcome"] == "miss", cc0
    assert _entries(cache), "no cache entries written"
    got1, cc1 = _run_jit_worker(tmp_path, cache, "warm")
    assert len(cc1) == 1 and cc1[0]["outcome"] == "hit", cc1
    assert cc1[0]["entries"] >= 1 and cc1[0]["bytes"] > 0
    assert got1["val"] == got0["val"]


def test_truncated_entry_is_purged_to_cold_compile(tmp_path):
    """The ckpt-style integrity posture: a truncated cache entry costs exactly
    one cold compile and an outcome=miss_corrupt event — never a crash."""
    cache = tmp_path / "cc"
    _run_jit_worker(tmp_path, cache, "seed")
    compile_cache.write_manifest(str(cache))
    victims = _entries(cache)
    assert victims
    for name in victims:
        p = cache / name
        with open(p, "r+b") as f:
            f.truncate(max(1, os.path.getsize(p) // 2))
    got, cc = _run_jit_worker(tmp_path, cache, "corrupt")
    assert len(cc) == 1 and cc[0]["outcome"] == "miss_corrupt", cc
    assert cc[0]["purged"] == len(victims)
    assert got["val"] == pytest.approx(got["val"])
    # The purged programs were re-compiled and re-cached.
    assert _entries(cache)


def test_sweep_leaves_unmanifested_entries_alone(tmp_path):
    cache = tmp_path / "cc"
    cache.mkdir()
    (cache / "newentry-cache").write_bytes(b"x" * 64)
    stats = compile_cache.sweep(str(cache))
    assert stats == {"entries": 1, "bytes": 64, "purged": 0, "unverified": 1}
    assert (cache / "newentry-cache").exists()


def test_manifest_roundtrip_and_mismatch_purge(tmp_path):
    cache = tmp_path / "cc"
    cache.mkdir()
    (cache / "a-cache").write_bytes(b"alpha")
    (cache / "b-cache").write_bytes(b"bravo")
    assert compile_cache.write_manifest(str(cache)) == 2
    # Flip a bit in one entry.
    (cache / "a-cache").write_bytes(b"alphA")
    stats = compile_cache.sweep(str(cache))
    assert stats["purged"] == 1
    assert not (cache / "a-cache").exists()
    assert (cache / "b-cache").exists()
    # A deleted (evicted) entry is NOT corruption.
    os.unlink(cache / "b-cache")
    compile_cache.write_manifest(str(cache))
    assert compile_cache.sweep(str(cache))["purged"] == 0


def test_corrupt_manifest_is_tolerated(tmp_path):
    cache = tmp_path / "cc"
    cache.mkdir()
    (cache / compile_cache.MANIFEST_NAME).write_text("{not json")
    (cache / "a-cache").write_bytes(b"alpha")
    stats = compile_cache.sweep(str(cache))
    assert stats["purged"] == 0 and stats["entries"] == 1


def test_observe_record_maps_compile_cache_events():
    reg = MetricsRegistry()
    observe_record(
        {"kind": "compile_cache", "outcome": "hit", "bytes": 4096}, reg
    )
    observe_record(
        {"kind": "compile_cache", "outcome": "miss_corrupt", "bytes": 0}, reg
    )
    snap = reg.snapshot()["metrics"]
    outcomes = {
        e["labels"]["outcome"]: e["value"]
        for e in snap["tpu_compile_cache_total"]
    }
    assert outcomes == {"hit": 1.0, "miss_corrupt": 1.0}
    assert snap["tpu_compile_cache_bytes"][0]["value"] == 0.0


def test_outcome_classification():
    assert compile_cache.outcome_of({"entries": 0, "purged": 0}) == "miss"
    assert compile_cache.outcome_of({"entries": 3, "purged": 0}) == "hit"
    assert compile_cache.outcome_of({"entries": 3, "purged": 1}) == "miss_corrupt"
