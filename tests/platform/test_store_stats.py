"""Store op telemetry: the ``store_stats`` wire op, the sampled collector,
version-skew containment, and the periodic ``store_stats`` events →
``tpu_store_*`` metrics parity."""

import time

import pytest

from tpu_resiliency.exceptions import StoreError
from tpu_resiliency.platform import store as store_mod
from tpu_resiliency.platform.store import KVClient, KVServer
from tpu_resiliency.utils import events
from tpu_resiliency.utils.metrics import aggregate
from tpu_resiliency.utils.opstats import (
    LatencyHist,
    OpStats,
    SpaceSaving,
    key_prefix,
)


@pytest.fixture(autouse=True)
def clean_sinks():
    events.clear_sinks()
    yield
    events.clear_sinks()


@pytest.fixture
def server():
    srv = KVServer(host="127.0.0.1", port=0)
    yield srv
    srv.close()


def _client(srv, **kw):
    return KVClient("127.0.0.1", srv.port, **kw)


# -- the wire op --------------------------------------------------------------


def test_store_stats_op_accounts_ops_and_bytes(server):
    c = _client(server)
    try:
        for i in range(200):
            c.set(f"jobs/a/k{i % 4}", i)
            assert c.get(f"jobs/a/k{i % 4}", timeout=1.0) == i
        doc = c.store_stats()
        assert doc["schema"] == "tpu-store-stats-1"
        assert doc["enabled"] is True
        # Sampled-scaled tallies: 200 of each, ±SAMPLE granularity — allow a
        # generous statistical band.
        for op in ("set", "get"):
            row = doc["ops"][op]
            assert 48 <= row["count"] <= 420, (op, row)  # wide: sampled estimate
            assert row["bytes_in"] > 0
            assert row["handle"]["count"] >= 3
            assert row["handle"]["p50_us"] > 0
            assert row["wait"]["count"] >= 1
            assert row["seconds"] > 0
        assert doc["bytes"]["in"] > 0 and doc["bytes"]["out"] > 0
        assert doc["conns"] == 1 and doc["conns_peak"] >= 1
        assert doc["parked"] == 0
        assert doc["keys"] == 4
    finally:
        c.close()


def test_hot_prefix_table_ranks_the_hot_namespace(server):
    c = _client(server)
    try:
        for i in range(400):
            c.set(f"hot/ns/k{i % 8}", i)
        for i in range(16):
            c.set(f"cold/ns/k{i}", i)
        hot = c.store_stats()["hot_prefixes"]
        assert hot, "no hot prefixes collected"
        assert hot[0]["prefix"] == "hot/ns"
    finally:
        c.close()


def test_park_depth_visible_while_barrier_waits(server):
    c = _client(server)
    waiter = _client(server)
    try:
        import threading

        t = threading.Thread(
            target=lambda: waiter.barrier_join("b/iter", 0, 2, timeout=10.0),
            daemon=True,
        )
        t.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            doc = c.store_stats()
            if doc["parked"] >= 1:
                break
            time.sleep(0.02)
        assert doc["parked"] >= 1, doc
        assert doc["barriers_open"] == 1
        # Release so teardown is clean.
        c.barrier_join("b/iter", 1, 2, timeout=5.0)
        t.join(5.0)
    finally:
        waiter.close()
        c.close()


def test_dedup_hit_rate_counts_replays(server):
    c = _client(server)
    try:
        # Same req_id twice: the second application must be a dedup hit.
        req = {"op": "add", "key": "ctr", "amount": 1, "req_id": "fixed:1"}
        assert c._call(dict(req)) == 1
        assert c._call(dict(req)) == 1  # replayed response, not re-applied
        doc = c.store_stats()
        assert doc["dedup"]["lookups"] >= 2
        assert doc["dedup"]["hits"] >= 1
        assert c.get("ctr", timeout=1.0) == 1
    finally:
        c.close()


def test_store_stats_is_idempotent_classified():
    assert "store_stats" in store_mod._IDEMPOTENT_OPS
    assert "store_stats" not in store_mod._NONIDEMPOTENT_OPS


# -- version skew -------------------------------------------------------------


def test_new_client_old_server_fails_fast_without_retry_burn(server, monkeypatch):
    """A pre-telemetry server answers ``store_stats`` with unknown-op: the
    client must surface StoreError in ONE round trip — server-side error
    responses are never transport-retried, so no retry budget burns."""
    monkeypatch.setattr(KVServer, "_op_store_stats", None)
    seen = []
    events.add_sink(seen.append)
    c = _client(server, retry_budget=8.0)
    try:
        t0 = time.monotonic()
        with pytest.raises(StoreError, match="unknown op"):
            c.store_stats()
        assert time.monotonic() - t0 < 1.0, "unknown-op burned a retry ladder"
        assert not [e for e in seen if e.kind == "store_retry"], (
            "unknown-op reply consumed transport retries"
        )
    finally:
        c.close()


def test_old_client_new_server_unaffected(server):
    """An old client simply never sends the op; every pre-existing op keeps
    its contract against the new server (the whole existing suite is the
    real assertion — this pins the cheap invariant)."""
    c = _client(server)
    try:
        c.set("k", 1)
        assert c.get("k", timeout=1.0) == 1
        assert c.add("ctr", 2) == 2
    finally:
        c.close()


# -- containment --------------------------------------------------------------


def test_crashing_collector_degrades_doc_never_op_path(server):
    c = _client(server)
    try:
        def boom(*a, **k):
            raise RuntimeError("collector bug")

        server._opstats.note_op = boom
        # Ops keep working while the broken collector gets disabled.
        for i in range(40):
            c.set(f"k{i}", i)
            assert c.get(f"k{i}", timeout=1.0) == i
        doc = c.store_stats()
        assert doc["enabled"] is False
        assert "collector bug" in doc.get("error", "")
        # Live server state still reported even with the collector dead.
        assert doc["conns"] == 1 and doc["keys"] == 40
        # And the server survives further traffic.
        assert c.add("ctr", 1) == 1
    finally:
        c.close()


def test_stats_disabled_server_serves_degraded_doc():
    srv = KVServer(host="127.0.0.1", port=0, stats_enabled=False)
    c = KVClient("127.0.0.1", srv.port)
    try:
        c.set("k", 1)
        doc = c.store_stats()
        assert doc["enabled"] is False
        assert doc["keys"] == 1
    finally:
        c.close()
        srv.close()


# -- periodic events → metrics parity ----------------------------------------


def test_periodic_store_stats_events_reach_metrics():
    seen = []
    events.add_sink(seen.append)
    srv = KVServer(host="127.0.0.1", port=0, stats_interval=0.05)
    c = KVClient("127.0.0.1", srv.port)
    try:
        for i in range(100):
            c.set(f"k{i % 4}", i)
        deadline = time.time() + 5
        while time.time() < deadline:
            if any(e.kind == "store_stats" for e in seen):
                break
            c.ping()
            time.sleep(0.05)
        evs = [e for e in seen if e.kind == "store_stats"]
        assert evs, "no periodic store_stats event emitted"
        p = evs[0].payload
        assert p["ops"].get("set", 0) > 0
        assert p["conns"] >= 1
    finally:
        c.close()
        srv.close()
    # Teardown emits the final deltas; the aggregated stream must show the
    # full tpu_store_* family set (live/post-hoc parity).
    prom = aggregate([e.to_record() for e in seen]).to_prometheus()
    assert 'tpu_store_ops_total{op="set"}' in prom
    assert "tpu_store_op_seconds" in prom
    assert 'tpu_store_bytes_total{direction="in"}' in prom
    assert 'tpu_store_bytes_total{direction="out"}' in prom
    assert "tpu_store_conns" in prom


def test_teardown_flushes_final_deltas():
    """A short-lived store (shorter than stats_interval) still leaves its
    totals in the stream: close() flushes the tail."""
    seen = []
    events.add_sink(seen.append)
    srv = KVServer(host="127.0.0.1", port=0, stats_interval=3600.0)
    c = KVClient("127.0.0.1", srv.port)
    for i in range(64):
        c.set(f"k{i % 2}", i)
    c.close()
    srv.close()
    evs = [e for e in seen if e.kind == "store_stats"]
    assert evs, "teardown did not flush store_stats deltas"
    assert sum(e.payload.get("ops", {}).get("set", 0) for e in evs) > 0


# -- collector unit coverage --------------------------------------------------


def test_latency_hist_quantiles_interpolate():
    h = LatencyHist()
    for _ in range(100):
        h.observe(3e-6)
    assert 2.5e-6 <= h.quantile(0.5) <= 5e-6
    assert h.count == 100 and h.max == pytest.approx(3e-6)
    doc = h.doc()
    assert doc["count"] == 100 and doc["p50_us"] > 0


def test_space_saving_guarantees_heavy_hitters():
    s = SpaceSaving(k=4)
    for i in range(1000):
        s.add("hot")
        s.add(f"cold{i}")  # churn far past capacity
    items = s.items()
    assert items[0]["prefix"] == "hot"
    assert items[0]["count"] >= 1000  # may over-estimate, never under
    assert len(s.counts) <= 4


def test_key_prefix_depth():
    assert key_prefix("a/b/c/d") == "a/b"
    assert key_prefix("a/b") == "a/b"
    assert key_prefix("flat") == "flat"


def test_opstats_deltas_are_monotone_and_resettable():
    st = OpStats()
    st.note_op("set", 1e-6, 2e-6, 100, {"key": "a/b/c"}, False)
    d1 = st.take_deltas()
    assert d1["ops"]["set"] == OpStats.SAMPLE
    assert d1["bytes_in"] == 100 * OpStats.SAMPLE
    assert st.take_deltas() is None  # nothing moved
    st.note_op("set", 1e-6, 2e-6, 50, None, True)
    d2 = st.take_deltas()
    assert d2["ops"]["set"] == OpStats.SAMPLE
    assert d2["bytes_in"] == 50 * OpStats.SAMPLE
