"""Store-contract tests — ported contract-first per SURVEY.md §7 'hard parts':
reentrant barriers, interruption records, completing barriers for dead ranks."""

import threading
import time

import pytest

from tpu_resiliency.exceptions import BarrierOverflow, BarrierTimeout, StoreTimeoutError
from tpu_resiliency.platform.store import CoordStore, KVServer, host_store


def test_basic_kv(coord_store):
    coord_store.set("a", {"x": 1})
    assert coord_store.get("a") == {"x": 1}
    assert coord_store.try_get("missing") is None
    assert coord_store.check(["a"])
    assert not coord_store.check(["a", "b"])
    assert coord_store.delete("a")
    assert not coord_store.delete("a")


def test_get_blocks_until_set(kv_server):
    c1 = CoordStore("127.0.0.1", kv_server.port)
    c2 = CoordStore("127.0.0.1", kv_server.port)
    result = {}

    def getter():
        result["v"] = c1.get("late", timeout=10.0)

    t = threading.Thread(target=getter)
    t.start()
    time.sleep(0.1)
    c2.set("late", 42)
    t.join(5.0)
    assert result["v"] == 42
    c1.close()
    c2.close()


def test_get_timeout(coord_store):
    with pytest.raises(StoreTimeoutError):
        coord_store.get("never", timeout=0.1)


def test_close_while_clients_block_raises_not_hangs():
    """A clean server close must fail parked waiters (blocking get AND barrier join)
    promptly with a store error — never leave them hanging to their full timeout."""
    from tpu_resiliency.exceptions import StoreError

    server = KVServer(host="127.0.0.1", port=0)
    c1 = CoordStore("127.0.0.1", server.port)
    c2 = CoordStore("127.0.0.1", server.port)
    errors = {}

    def blocked_get():
        try:
            c1.get("never", timeout=60.0)
        except Exception as e:
            errors["get"] = e

    def blocked_barrier():
        try:
            c2.barrier_join("b", 0, 2, timeout=60.0)
        except Exception as e:
            errors["barrier"] = e

    threads = [threading.Thread(target=blocked_get), threading.Thread(target=blocked_barrier)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(0.3)  # both parked server-side
    server.close()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive(), "client still blocked after server close"
    assert time.monotonic() - t0 < 15.0
    assert isinstance(errors["get"], StoreError)
    assert isinstance(errors["barrier"], (StoreError, BarrierTimeout))
    c1.close()
    c2.close()


def test_add_and_cas(coord_store):
    assert coord_store.add("ctr", 1) == 1
    assert coord_store.add("ctr", 5) == 6
    ok, val = coord_store.compare_set("state", None, "v1")
    assert ok and val == "v1"
    ok, val = coord_store.compare_set("state", "v0", "v2")
    assert not ok and val == "v1"
    ok, val = coord_store.compare_set("state", "v1", "v2")
    assert ok and val == "v2"


def test_lists_and_sets(coord_store):
    coord_store.list_append("records", {"rank": 3, "why": "exc"})
    coord_store.list_append("records", {"rank": 5, "why": "timeout"})
    recs = coord_store.list_get("records")
    assert [r["rank"] for r in recs] == [3, 5]
    coord_store.list_clear("records")
    assert coord_store.list_get("records") == []

    coord_store.set_add("terminated", [1, 2])
    coord_store.set_add("terminated", [2, 7])
    assert coord_store.set_get("terminated") == {1, 2, 7}


def _run_barrier(port, name, rank, world, timeout=10.0):
    c = CoordStore("127.0.0.1", port)
    try:
        c.barrier(name, rank, world, timeout)
    finally:
        c.close()


def test_barrier_releases_all(kv_server):
    world = 4
    threads = [
        threading.Thread(target=_run_barrier, args=(kv_server.port, "b0", r, world))
        for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
        assert not t.is_alive()


def test_barrier_reentrant(kv_server):
    """Same barrier name usable across iterations (reference reentrant_barrier)."""
    world = 3
    errors = []

    def worker(rank):
        c = CoordStore("127.0.0.1", kv_server.port)
        try:
            for _ in range(5):
                c.barrier("iter", rank, world, 10.0)
        except Exception as e:  # pragma: no cover
            errors.append(e)
        finally:
            c.close()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20.0)
        assert not t.is_alive()
    assert not errors


def test_barrier_timeout(coord_store):
    with pytest.raises(BarrierTimeout):
        coord_store.barrier("lonely", 0, 2, timeout=0.2)


def test_barrier_double_join_semantics(kv_server):
    """Duplicate non-blocking registrations and duplicate proxy joins are idempotent;
    a duplicate *waiting* join and a dead-marked rank arriving itself are errors."""
    c = CoordStore("127.0.0.1", kv_server.port)
    c.barrier_join("dj", rank=0, world_size=3, timeout=0.0, wait=False)
    c.barrier_join("dj", rank=0, world_size=3, timeout=0.0, wait=False)  # no overflow
    with pytest.raises(BarrierOverflow):  # rank 0 already registered this round
        c.barrier_join("dj", rank=0, world_size=3, timeout=0.5, wait=True)
    c.complete_barrier_for("dj", rank=1, world_size=3)
    c.complete_barrier_for("dj", rank=1, world_size=3)  # duplicate proxy: no-op
    with pytest.raises(BarrierOverflow):  # proxied-dead rank arriving itself
        c.barrier_join("dj", rank=1, world_size=3, timeout=0.5, wait=True)
    c.close()


def test_barrier_no_phantom_rerelease(kv_server):
    """A round covered entirely by proxies releases exactly once: late duplicate
    proxies must not bump the generation again (completers poll `generation >
    start_gen`, so a phantom release would fake a successful round)."""
    c = CoordStore("127.0.0.1", kv_server.port)
    for r in (0, 1):
        c.barrier_join("pr", rank=r, world_size=2, timeout=0.0, wait=False)
    assert c.barrier_status("pr")["generation"] == 1
    for _ in range(3):
        c.complete_barrier_for("pr", rank=1, world_size=2)
        c.barrier_join("pr", rank=1, world_size=2, timeout=0.0, wait=False, on_behalf=True)
    assert c.barrier_status("pr")["generation"] == 1
    c.close()


def test_barrier_elastic_world_resets_absences(kv_server):
    """Sticky absences die with the world size: after an elastic shrink the old
    rank numbering is meaningless, so a round at the new size must require every
    live rank — not release early on a stale absence."""
    c = CoordStore("127.0.0.1", kv_server.port)
    c.complete_barrier_for("ew", rank=2, world_size=3)
    for r in (0, 1):
        c.barrier_join("ew", rank=r, world_size=3, timeout=5.0, wait=False)
    assert c.barrier_status("ew")["generation"] == 1
    # New round at world 2: rank 2's stale absence must not count.
    c.barrier_join("ew", rank=0, world_size=2, timeout=0.0, wait=False)
    st = c.barrier_status("ew")
    assert st["generation"] == 1 and st["absent"] == set()
    c.barrier_join("ew", rank=1, world_size=2, timeout=0.0, wait=False)
    assert c.barrier_status("ew")["generation"] == 2
    c.close()


def test_barrier_del_is_exact(kv_server):
    """barrier_del drops exactly one name — iteration 1's cleanup must not take
    iteration 10's barrier with it (the prefix-match hazard, ADVICE r1)."""
    c = CoordStore("127.0.0.1", kv_server.port)
    c.barrier_join("barrier/iteration/1", rank=0, world_size=2, timeout=0.0, wait=False)
    c.barrier_join("barrier/iteration/10", rank=0, world_size=2, timeout=0.0, wait=False)
    assert c.barrier_del("barrier/iteration/1")
    assert c.barrier_status("barrier/iteration/1") is None
    assert c.barrier_status("barrier/iteration/10") is not None
    assert not c.barrier_del("barrier/iteration/1")  # already gone
    c.close()


def test_barrier_proxy_only_world_change_resets_absences(kv_server):
    """A round held open purely by proxy (on_behalf) joins re-opens cleanly when a
    real join arrives under a different world size: the stale absences refer to the
    old rank numbering and must not phantom-cover the new round (ADVICE r1)."""
    c = CoordStore("127.0.0.1", kv_server.port)
    c.complete_barrier_for("po", rank=3, world_size=4)  # proxy-only, round open at 4
    assert c.barrier_status("po")["absent"] == {3}
    c.barrier_join("po", rank=0, world_size=2, timeout=0.0, wait=False)
    st = c.barrier_status("po")
    assert st["absent"] == set() and st["generation"] == 0
    c.barrier_join("po", rank=1, world_size=2, timeout=0.0, wait=False)
    assert c.barrier_status("po")["generation"] == 1
    c.close()


def test_complete_barrier_for_dead_rank(kv_server):
    """A monitor completes the barrier on behalf of a dead rank
    (reference monitor_process.py:260-282)."""
    world = 3
    done = []

    def live(rank):
        c = CoordStore("127.0.0.1", kv_server.port)
        c.barrier("dead-rank", rank, world, 10.0)
        done.append(rank)
        c.close()

    threads = [threading.Thread(target=live, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    assert not done  # still waiting on rank 2
    monitor = CoordStore("127.0.0.1", kv_server.port)
    monitor.complete_barrier_for("dead-rank", rank=2, world_size=world)
    for t in threads:
        t.join(10.0)
        assert not t.is_alive()
    assert sorted(done) == [0, 1]
    monitor.close()


def test_scoped_views_isolate(coord_store):
    s0 = coord_store.scoped("iter0")
    s1 = coord_store.scoped("iter1")
    s0.set("k", "a")
    s1.set("k", "b")
    assert s0.get("k") == "a"
    assert s1.get("k") == "b"
    s0.set_add("terminated", [1])
    assert s1.set_get("terminated") == set()
    # every key-based op must stay inside the view's namespace
    assert s0.check(["k"]) and s1.check(["k"])
    assert s0.prefix_get() == {"k": "a"}
    assert s0.delete("k") and not s0.check(["k"])
    assert s1.get("k") == "b"  # sibling namespace untouched
    s0.list_append("l", 1)
    assert s0.list_get("l") == [1] and s1.list_get("l") == []
    s0.set("hb/4", 9.0)
    assert s0.prefix_get("hb/") == {"hb/4": 9.0} and s1.prefix_get("hb/") == {}


def test_auth_handshake():
    from tpu_resiliency.platform.store import KVServer

    server = KVServer(host="127.0.0.1", port=0, auth_key="sekrit")
    good = CoordStore("127.0.0.1", server.port, auth_key="sekrit", timeout=5.0)
    good.set("x", 1)
    assert good.get("x") == 1
    with pytest.raises(Exception):
        bad = CoordStore("127.0.0.1", server.port, auth_key="wrong", timeout=5.0,
                         connect_retries=1)
        bad.set("y", 2)  # server drops unauthenticated conns
    with pytest.raises(Exception):
        CoordStore("127.0.0.1", server.port, auth_key=None, timeout=5.0, connect_retries=1)
    good.close()
    server.close()


def test_silent_unauthenticated_conn_is_dropped():
    """A peer that connects but never answers the auth challenge must be evicted at
    the handshake deadline, not held open forever (fd-exhaustion vector)."""
    import socket as socket_mod

    from tpu_resiliency.platform.store import KVServer

    server = KVServer(host="127.0.0.1", port=0, auth_key="sekrit", auth_timeout=0.5)
    silent = socket_mod.create_connection(("127.0.0.1", server.port), timeout=5.0)
    silent.recv(4096)  # hello arrives; never send the MAC
    deadline = time.monotonic() + 10.0
    dropped = False
    while time.monotonic() < deadline:
        time.sleep(0.2)
        try:
            silent.settimeout(0.2)
            if silent.recv(4096) == b"":
                dropped = True
                break
        except socket_mod.timeout:
            continue
        except OSError:
            dropped = True
            break
    assert dropped, "unauthenticated connection was never dropped"
    # The server still serves authenticated clients afterwards.
    good = CoordStore("127.0.0.1", server.port, auth_key="sekrit", timeout=5.0)
    good.set("x", 1)
    assert good.get("x") == 1
    good.close()
    silent.close()
    server.close()


def test_nonloopback_bind_requires_auth(monkeypatch):
    from tpu_resiliency.platform.store import AUTH_KEY_ENV, KVServer

    monkeypatch.delenv(AUTH_KEY_ENV, raising=False)
    with pytest.raises(ValueError):
        KVServer(host="0.0.0.0", port=0)


def test_blocking_op_does_not_starve_fast_ops(kv_server):
    """A long barrier join must not hold the shared socket's lock (heartbeats keep
    flowing) — the reference's monitor cadence depends on this."""
    c = CoordStore("127.0.0.1", kv_server.port)

    def join_slow():
        try:
            c.barrier("slow", 0, 2, 8.0)
        except BarrierTimeout:
            pass

    t = threading.Thread(target=join_slow)
    t.start()
    time.sleep(0.3)
    start = time.monotonic()
    c.set("hb/0", time.time())
    elapsed = time.monotonic() - start
    assert elapsed < 2.0, f"heartbeat starved behind blocking barrier: {elapsed:.1f}s"
    # release the barrier so the thread exits quickly
    c.complete_barrier_for("slow", 1, 2)
    t.join(10.0)
    assert not t.is_alive()
    c.close()


def test_host_store():
    client, server = host_store(rank=0, host="127.0.0.1", port=0)
    assert server is not None
    client2, none = host_store(rank=1, host="127.0.0.1", port=server.port)
    assert none is None
    client.set("shared", 7)
    assert client2.get("shared") == 7
    client.close()
    client2.close()
    server.close()


def test_concurrent_clients_hammer(kv_server):
    """Many clients incrementing one counter — server-side atomicity."""
    N, per = 8, 50

    def worker():
        c = CoordStore("127.0.0.1", kv_server.port)
        for _ in range(per):
            c.add("hammer", 1)
        c.close()

    threads = [threading.Thread(target=worker) for _ in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    c = CoordStore("127.0.0.1", kv_server.port)
    assert c.get("hammer") == N * per
    c.close()


def test_sticky_absent_across_generations(kv_server):
    """A proxied-dead rank stays covered in every later round of the same barrier
    name, and a duplicate proxy racing a release can't plant a phantom arrival."""
    c = CoordStore("127.0.0.1", kv_server.port)
    world = 3
    c.complete_barrier_for("st", rank=2, world_size=world)

    def join(rank, out):
        cc = CoordStore("127.0.0.1", kv_server.port)
        try:
            cc.barrier_join("st", rank, world, timeout=10.0)
            out.append(rank)
        finally:
            cc.close()

    for gen in range(2):  # round 2 works WITHOUT re-proxying rank 2
        out = []
        threads = [threading.Thread(target=join, args=(r, out)) for r in (0, 1)]
        for t in threads:
            t.start()
        # duplicate proxy joins mid-round and post-release: must all be no-ops
        c.complete_barrier_for("st", rank=2, world_size=world)
        for t in threads:
            t.join(15.0)
        assert sorted(out) == [0, 1], f"round {gen}"
        c.complete_barrier_for("st", rank=2, world_size=world)

    with pytest.raises(BarrierOverflow):  # dead-marked rank rejoining is the signal
        c.barrier_join("st", rank=2, world_size=world, timeout=0.5)
    c.close()


def test_touch_and_stale_keys(kv_server):
    c = CoordStore("127.0.0.1", kv_server.port)
    c.touch("hb/0")
    c.set("hb/notnum", "x")  # non-numeric values are never reported stale
    assert c.stale_keys("hb/", 30.0) == {}
    time.sleep(0.05)
    stale = c.stale_keys("hb/", 0.01)
    assert set(stale) == {"hb/0"} and stale["hb/0"] > 0.0
    c.close()


def test_prefix_clear_all_tables(kv_server):
    c = CoordStore("127.0.0.1", kv_server.port)
    c.set("iter/0/flag", True)
    c.list_append("iter/0/recs", 1)
    c.set_add("iter/0/dead", [4])
    c.complete_barrier_for("iter/0/bar", rank=0, world_size=2)
    c.set("iter/1/flag", True)
    removed = c.prefix_clear("iter/0/")
    assert removed == 4
    assert c.prefix_get("iter/0/") == {}
    assert c.list_get("iter/0/recs") == []
    assert c.set_get("iter/0/dead") == set()
    assert c.barrier_status("iter/0/bar") is None
    assert c.prefix_get("iter/1/") == {"iter/1/flag": True}
    c.close()


def test_store_answers_probe(monkeypatch):
    """The liveness probe behind the launcher's join-vs-host decision: True
    only for a live server the caller can actually authenticate to."""
    from tpu_resiliency.platform.store import AUTH_KEY_ENV, store_answers

    # auth_key=None must test the MISSING-key branch, not an env fallback.
    monkeypatch.delenv(AUTH_KEY_ENV, raising=False)

    server = KVServer(host="127.0.0.1", port=0)
    try:
        assert store_answers("127.0.0.1", server.port)
    finally:
        server.close()
    # Dead server: instant False (connection refused), no stall.
    t0 = time.monotonic()
    assert not store_answers("127.0.0.1", server.port, timeout=1.0)
    assert time.monotonic() - t0 < 1.5

    auth = KVServer(host="127.0.0.1", port=0, auth_key="sekrit")
    try:
        assert store_answers("127.0.0.1", auth.port, auth_key="sekrit")
        # Without (or with the wrong) key the caller could not use the store:
        # the probe must not claim it is joinable.
        assert not store_answers("127.0.0.1", auth.port, auth_key=None)
        assert not store_answers("127.0.0.1", auth.port, auth_key="wrong", timeout=2.0)
    finally:
        auth.close()


def test_wait_changed_versions(kv_server):
    """Per-key mutation versions: every write kind wakes a watcher — including
    a set to the SAME value and a delete — and timeouts leave the version be."""
    c = CoordStore("127.0.0.1", kv_server.port, prefix="wc/")
    c.set("state", {"round": 0})
    _, v0 = c.get_versioned("state")
    assert v0 >= 1

    # No mutation: bounded timeout, unchanged.
    t0 = time.monotonic()
    changed, _, v = c.wait_changed("state", v0, timeout=0.3)
    assert not changed and v == v0 and time.monotonic() - t0 >= 0.25

    # A concurrent CAS wakes the parked watcher almost immediately.
    def mutate():
        time.sleep(0.15)
        m = CoordStore("127.0.0.1", kv_server.port, prefix="wc/")
        ok, _ = m.compare_set("state", {"round": 0}, {"round": 1})
        assert ok
        m.close()

    t = threading.Thread(target=mutate)
    t.start()
    t0 = time.monotonic()
    changed, value, v1 = c.wait_changed("state", v0, timeout=10.0)
    waited = time.monotonic() - t0
    t.join()
    assert changed and value == {"round": 1} and v1 > v0
    assert waited < 5.0, waited

    # Same-value set still counts as a change (watchers need the wake, e.g. a
    # leader re-asserting state).
    c.set("state", {"round": 1})
    changed, value, v2 = c.wait_changed("state", v1, timeout=5.0)
    assert changed and value == {"round": 1} and v2 > v1

    # Deletion is a change; value comes back None and the version entry drops
    # to 0 (bounded table: versions exist only for live keys).
    c.delete("state")
    changed, value, v3 = c.wait_changed("state", v2, timeout=5.0)
    assert changed and value is None and v3 == 0
    assert c.get_versioned("state") == (None, 0)

    # Re-creation lands past every previously observed version (global clock:
    # no ABA against any old seen_version).
    c.set("state", {"round": 2})
    _, v4 = c.get_versioned("state")
    assert v4 > v2

    # prefix_clear is a visible change too.
    c.prefix_clear("")
    changed, value, v5 = c.wait_changed("state", v4, timeout=5.0)
    assert changed and value is None and v5 == 0

    # touch participates in versioning (event-driven liveness watchers).
    c.touch("hb")
    _, vt = c.get_versioned("hb")
    assert vt > 0
    c.touch("hb")
    changed, _, vt2 = c.wait_changed("hb", vt, timeout=5.0)
    assert changed and vt2 > vt

    # A stale-but-nonzero seen_version returns instantly (no park).
    c.set("state", {"round": 3})
    t0 = time.monotonic()
    changed, _, _ = c.wait_changed("state", 1, timeout=10.0)
    assert changed and time.monotonic() - t0 < 2.0
    c.close()
