"""Store-contract tests — ported contract-first per SURVEY.md §7 'hard parts':
reentrant barriers, interruption records, completing barriers for dead ranks."""

import threading
import time

import pytest

from tpu_resiliency.exceptions import BarrierOverflow, BarrierTimeout, StoreTimeoutError
from tpu_resiliency.platform.store import CoordStore, KVServer, host_store


def test_basic_kv(coord_store):
    coord_store.set("a", {"x": 1})
    assert coord_store.get("a") == {"x": 1}
    assert coord_store.try_get("missing") is None
    assert coord_store.check(["a"])
    assert not coord_store.check(["a", "b"])
    assert coord_store.delete("a")
    assert not coord_store.delete("a")


def test_get_blocks_until_set(kv_server):
    c1 = CoordStore("127.0.0.1", kv_server.port)
    c2 = CoordStore("127.0.0.1", kv_server.port)
    result = {}

    def getter():
        result["v"] = c1.get("late", timeout=10.0)

    t = threading.Thread(target=getter)
    t.start()
    time.sleep(0.1)
    c2.set("late", 42)
    t.join(5.0)
    assert result["v"] == 42
    c1.close()
    c2.close()


def test_get_timeout(coord_store):
    with pytest.raises(StoreTimeoutError):
        coord_store.get("never", timeout=0.1)


def test_add_and_cas(coord_store):
    assert coord_store.add("ctr", 1) == 1
    assert coord_store.add("ctr", 5) == 6
    ok, val = coord_store.compare_set("state", None, "v1")
    assert ok and val == "v1"
    ok, val = coord_store.compare_set("state", "v0", "v2")
    assert not ok and val == "v1"
    ok, val = coord_store.compare_set("state", "v1", "v2")
    assert ok and val == "v2"


def test_lists_and_sets(coord_store):
    coord_store.record_interrupted({"rank": 3, "why": "exc"})
    coord_store.record_interrupted({"rank": 5, "why": "timeout"})
    recs = coord_store.get_interruption_records()
    assert [r["rank"] for r in recs] == [3, 5]
    coord_store.clear_interruption_records()
    assert coord_store.get_interruption_records() == []

    coord_store.record_terminated_ranks([1, 2])
    coord_store.record_terminated_ranks([2, 7])
    assert coord_store.get_terminated_ranks() == {1, 2, 7}


def test_heartbeats(coord_store):
    coord_store.send_heartbeat(0, 123.0)
    coord_store.send_heartbeat(3, 456.0)
    assert coord_store.get_heartbeats() == {0: 123.0, 3: 456.0}


def _run_barrier(port, name, rank, world, timeout=10.0):
    c = CoordStore("127.0.0.1", port)
    try:
        c.barrier(name, rank, world, timeout)
    finally:
        c.close()


def test_barrier_releases_all(kv_server):
    world = 4
    threads = [
        threading.Thread(target=_run_barrier, args=(kv_server.port, "b0", r, world))
        for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
        assert not t.is_alive()


def test_barrier_reentrant(kv_server):
    """Same barrier name usable across iterations (reference reentrant_barrier)."""
    world = 3
    errors = []

    def worker(rank):
        c = CoordStore("127.0.0.1", kv_server.port)
        try:
            for _ in range(5):
                c.barrier("iter", rank, world, 10.0)
        except Exception as e:  # pragma: no cover
            errors.append(e)
        finally:
            c.close()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20.0)
        assert not t.is_alive()
    assert not errors


def test_barrier_timeout(coord_store):
    with pytest.raises(BarrierTimeout):
        coord_store.barrier("lonely", 0, 2, timeout=0.2)


def test_barrier_double_join_overflow(kv_server):
    c = CoordStore("127.0.0.1", kv_server.port)
    c.barrier_join("dj", rank=0, world_size=3, timeout=0.0, wait=False)
    with pytest.raises(BarrierOverflow):
        c.barrier_join("dj", rank=0, world_size=3, timeout=0.0, wait=False)
    c.close()


def test_complete_barrier_for_dead_rank(kv_server):
    """A monitor completes the barrier on behalf of a dead rank
    (reference monitor_process.py:260-282)."""
    world = 3
    done = []

    def live(rank):
        c = CoordStore("127.0.0.1", kv_server.port)
        c.barrier("dead-rank", rank, world, 10.0)
        done.append(rank)
        c.close()

    threads = [threading.Thread(target=live, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    assert not done  # still waiting on rank 2
    monitor = CoordStore("127.0.0.1", kv_server.port)
    monitor.complete_barrier_for("dead-rank", rank=2, world_size=world)
    for t in threads:
        t.join(10.0)
        assert not t.is_alive()
    assert sorted(done) == [0, 1]
    monitor.close()


def test_scoped_views_isolate(coord_store):
    s0 = coord_store.scoped("iter0")
    s1 = coord_store.scoped("iter1")
    s0.set("k", "a")
    s1.set("k", "b")
    assert s0.get("k") == "a"
    assert s1.get("k") == "b"
    s0.record_terminated_ranks([1])
    assert s1.get_terminated_ranks() == set()
    # every key-based op must stay inside the view's namespace
    assert s0.check(["k"]) and s1.check(["k"])
    assert s0.prefix_get() == {"k": "a"}
    assert s0.delete("k") and not s0.check(["k"])
    assert s1.get("k") == "b"  # sibling namespace untouched
    s0.list_append("l", 1)
    assert s0.list_get("l") == [1] and s1.list_get("l") == []
    s0.send_heartbeat(4, 9.0)
    assert s0.get_heartbeats() == {4: 9.0} and s1.get_heartbeats() == {}


def test_auth_handshake():
    from tpu_resiliency.platform.store import KVServer

    server = KVServer(host="127.0.0.1", port=0, auth_key="sekrit")
    good = CoordStore("127.0.0.1", server.port, auth_key="sekrit", timeout=5.0)
    good.set("x", 1)
    assert good.get("x") == 1
    with pytest.raises(Exception):
        bad = CoordStore("127.0.0.1", server.port, auth_key="wrong", timeout=5.0,
                         connect_retries=1)
        bad.set("y", 2)  # server drops unauthenticated conns
    with pytest.raises(Exception):
        CoordStore("127.0.0.1", server.port, auth_key=None, timeout=5.0, connect_retries=1)
    good.close()
    server.close()


def test_nonloopback_bind_requires_auth(monkeypatch):
    from tpu_resiliency.platform.store import AUTH_KEY_ENV, KVServer

    monkeypatch.delenv(AUTH_KEY_ENV, raising=False)
    with pytest.raises(ValueError):
        KVServer(host="0.0.0.0", port=0)


def test_blocking_op_does_not_starve_fast_ops(kv_server):
    """A long barrier join must not hold the shared socket's lock (heartbeats keep
    flowing) — the reference's monitor cadence depends on this."""
    c = CoordStore("127.0.0.1", kv_server.port)

    def join_slow():
        try:
            c.barrier("slow", 0, 2, 8.0)
        except BarrierTimeout:
            pass

    t = threading.Thread(target=join_slow)
    t.start()
    time.sleep(0.3)
    start = time.monotonic()
    c.send_heartbeat(0)
    elapsed = time.monotonic() - start
    assert elapsed < 2.0, f"heartbeat starved behind blocking barrier: {elapsed:.1f}s"
    # release the barrier so the thread exits quickly
    c.complete_barrier_for("slow", 1, 2)
    t.join(10.0)
    assert not t.is_alive()
    c.close()


def test_host_store():
    client, server = host_store(rank=0, host="127.0.0.1", port=0)
    assert server is not None
    client2, none = host_store(rank=1, host="127.0.0.1", port=server.port)
    assert none is None
    client.set("shared", 7)
    assert client2.get("shared") == 7
    client.close()
    client2.close()
    server.close()


def test_concurrent_clients_hammer(kv_server):
    """Many clients incrementing one counter — server-side atomicity."""
    N, per = 8, 50

    def worker():
        c = CoordStore("127.0.0.1", kv_server.port)
        for _ in range(per):
            c.add("hammer", 1)
        c.close()

    threads = [threading.Thread(target=worker) for _ in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    c = CoordStore("127.0.0.1", kv_server.port)
    assert c.get("hammer") == N * per
    c.close()
