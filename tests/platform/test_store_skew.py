"""Version-skew contract for the coordination-plane scale work.

Two directions must keep working with zero wire changes:

1. **Old client → epoll server.** The event-loop server speaks the exact
   frame protocol the thread-per-connection ancestor did. A minimal
   "old-build" client — raw framing, no req_id nonces, no store_stats, no
   shard awareness — must round-trip every pre-scale op untouched.
2. **New client → 1-shard store.** Sharding degenerates at N=1 to today's
   layout exactly: same keys on the same single server, flat collectives,
   classic CoordStore behavior — so a rolling upgrade can ship the client
   first and flip the clique on later.
"""

import socket

import pytest

from tpu_resiliency.platform import framing
from tpu_resiliency.platform.shardstore import (
    LocalClique,
    ShardedKVClient,
    connect_store,
    format_endpoints,
)
from tpu_resiliency.platform.store import CoordStore, _client_hello


class OldWireClient:
    """A pre-scale-era client: one blocking socket, raw pickled frames, only
    the op fields that existed before req_id dedup and store_stats shipped.
    Deliberately NOT built on KVClient — the point is the wire, not the
    library."""

    def __init__(self, host: str, port: int):
        self.sock = socket.create_connection((host, port), timeout=10.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _client_hello(self.sock, None)

    def call(self, **req):
        framing.send_obj(self.sock, req)
        return framing.recv_obj(self.sock)

    def close(self):
        self.sock.close()


def test_old_wire_client_against_epoll_server(kv_server):
    c = OldWireClient("127.0.0.1", kv_server.port)
    try:
        assert c.call(op="ping")["value"] == "pong"
        assert c.call(op="set", key="skew/a", value=41)["status"] == "ok"
        assert c.call(op="get", key="skew/a", timeout=1.0)["value"] == 41
        assert c.call(op="add", key="skew/ctr", amount=2)["value"] == 2
        assert c.call(op="cas", key="skew/c", expected=None,
                      desired="v")["value"] == (True, "v")
        assert c.call(op="prefix_get", prefix="skew/")["value"] == {
            "skew/a": 41, "skew/ctr": 2, "skew/c": "v",
        }
        # Old-style barrier join: no req_id — server must not require one.
        resp = c.call(op="barrier", name="skew/b", rank=0, world_size=1,
                      timeout=5.0, wait=True)
        assert resp["status"] == "ok" and resp["value"] == 1
        # Unknown future op: one structured error frame, connection intact.
        resp = c.call(op="quantum_entangle", key="skew/a")
        assert resp["status"] == "error" and "unknown op" in resp["error"]
        assert c.call(op="ping")["value"] == "pong"
    finally:
        c.close()


def test_new_client_against_one_shard_degenerates(kv_server):
    """ShardedKVClient with one endpoint: every op lands on the single
    server exactly where a classic KVClient would put it — interoperable in
    both directions mid-flight."""
    sharded = ShardedKVClient([("127.0.0.1", kv_server.port)], timeout=30.0)
    classic = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
    try:
        sharded.set("skew/x", "from-sharded")
        assert classic.get("skew/x", timeout=1.0) == "from-sharded"
        classic.set("skew/y", "from-classic")
        assert sharded.get("skew/y", timeout=1.0) == "from-classic"
        assert sharded.prefix_get("skew/") == classic.prefix_get("skew/")
        assert sharded.num_keys() == classic.client.num_keys()
        # Barriers interoperate: arrivals from either client shape release
        # one server-side round.
        sharded.barrier_join("skew/b2", 0, 2, timeout=0.0, wait=False)
        classic.barrier_join("skew/b2", 1, 2, timeout=5.0)
        st = sharded.barrier_status("skew/b2")
        assert st is not None and st["generation"] == 1
        doc = sharded.store_stats()
        assert doc["shard_map"]["nshards"] == 1
        assert doc["backend"] == "epoll"
    finally:
        sharded.close()
        classic.close()


def test_factory_degenerates_without_spec(kv_server, monkeypatch):
    from tpu_resiliency.platform.shardstore import SHARDS_ENV

    monkeypatch.delenv(SHARDS_ENV, raising=False)
    st = connect_store("127.0.0.1", kv_server.port, prefix="p/")
    try:
        assert isinstance(st, CoordStore)
        st.set("k", 1)
        assert st.get("k", timeout=1.0) == 1
    finally:
        st.close()


def test_old_wire_client_against_a_clique_shard():
    """An old client pointed at ONE shard of a clique still works against
    that shard (the wire is unchanged); it simply sees only that shard's
    slice — the documented skew behavior, not a crash."""
    clique = LocalClique(2)
    new = ShardedKVClient(clique.endpoints, timeout=30.0)
    try:
        for i in range(8):
            new.set(f"sk/{i}", i)
        old = OldWireClient(*clique.endpoints[0])
        try:
            seen = old.call(op="prefix_get", prefix="sk/")["value"]
            whole = new.prefix_get("sk/")
            assert set(seen) <= set(whole)
            assert 0 < len(seen) < len(whole)  # a slice, not the world
        finally:
            old.close()
    finally:
        new.close()
        clique.close()
