"""Version-skew contract for the coordination-plane scale work.

Two directions must keep working with zero wire changes:

1. **Old client → epoll server.** The event-loop server speaks the exact
   frame protocol the thread-per-connection ancestor did. A minimal
   "old-build" client — raw framing, no req_id nonces, no store_stats, no
   shard awareness — must round-trip every pre-scale op untouched.
2. **New client → 1-shard store.** Sharding degenerates at N=1 to today's
   layout exactly: same keys on the same single server, flat collectives,
   classic CoordStore behavior — so a rolling upgrade can ship the client
   first and flip the clique on later.
"""

import socket

import pytest

from tpu_resiliency.platform import framing
from tpu_resiliency.platform.shardstore import (
    EPOCH_KEY,
    LocalClique,
    ShardedKVClient,
    connect_store,
    format_endpoints,
    reshard_clique,
    shard_of,
)
from tpu_resiliency.platform.store import (
    CoordStore,
    KVClient,
    KVServer,
    StoreError,
    StoreTransportError,
    _client_hello,
)
from tpu_resiliency.utils import events as tpu_events


class OldWireClient:
    """A pre-scale-era client: one blocking socket, raw pickled frames, only
    the op fields that existed before req_id dedup and store_stats shipped.
    Deliberately NOT built on KVClient — the point is the wire, not the
    library."""

    def __init__(self, host: str, port: int):
        self.sock = socket.create_connection((host, port), timeout=10.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _client_hello(self.sock, None)

    def call(self, **req):
        framing.send_obj(self.sock, req)
        return framing.recv_obj(self.sock)

    def close(self):
        self.sock.close()


def test_old_wire_client_against_epoll_server(kv_server):
    c = OldWireClient("127.0.0.1", kv_server.port)
    try:
        assert c.call(op="ping")["value"] == "pong"
        assert c.call(op="set", key="skew/a", value=41)["status"] == "ok"
        assert c.call(op="get", key="skew/a", timeout=1.0)["value"] == 41
        assert c.call(op="add", key="skew/ctr", amount=2)["value"] == 2
        assert c.call(op="cas", key="skew/c", expected=None,
                      desired="v")["value"] == (True, "v")
        assert c.call(op="prefix_get", prefix="skew/")["value"] == {
            "skew/a": 41, "skew/ctr": 2, "skew/c": "v",
        }
        # Old-style barrier join: no req_id — server must not require one.
        resp = c.call(op="barrier", name="skew/b", rank=0, world_size=1,
                      timeout=5.0, wait=True)
        assert resp["status"] == "ok" and resp["value"] == 1
        # Unknown future op: one structured error frame, connection intact.
        resp = c.call(op="quantum_entangle", key="skew/a")
        assert resp["status"] == "error" and "unknown op" in resp["error"]
        assert c.call(op="ping")["value"] == "pong"
    finally:
        c.close()


def test_new_client_against_one_shard_degenerates(kv_server):
    """ShardedKVClient with one endpoint: every op lands on the single
    server exactly where a classic KVClient would put it — interoperable in
    both directions mid-flight."""
    sharded = ShardedKVClient([("127.0.0.1", kv_server.port)], timeout=30.0)
    classic = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
    try:
        sharded.set("skew/x", "from-sharded")
        assert classic.get("skew/x", timeout=1.0) == "from-sharded"
        classic.set("skew/y", "from-classic")
        assert sharded.get("skew/y", timeout=1.0) == "from-classic"
        assert sharded.prefix_get("skew/") == classic.prefix_get("skew/")
        assert sharded.num_keys() == classic.client.num_keys()
        # Barriers interoperate: arrivals from either client shape release
        # one server-side round.
        sharded.barrier_join("skew/b2", 0, 2, timeout=0.0, wait=False)
        classic.barrier_join("skew/b2", 1, 2, timeout=5.0)
        st = sharded.barrier_status("skew/b2")
        assert st is not None and st["generation"] == 1
        doc = sharded.store_stats()
        assert doc["shard_map"]["nshards"] == 1
        assert doc["backend"] == "epoll"
    finally:
        sharded.close()
        classic.close()


def test_factory_degenerates_without_spec(kv_server, monkeypatch):
    from tpu_resiliency.platform.shardstore import SHARDS_ENV

    monkeypatch.delenv(SHARDS_ENV, raising=False)
    st = connect_store("127.0.0.1", kv_server.port, prefix="p/")
    try:
        assert isinstance(st, CoordStore)
        st.set("k", 1)
        assert st.get("k", timeout=1.0) == 1
    finally:
        st.close()


def _key_on(shard: int, n: int, prefix: str) -> str:
    i = 0
    while True:
        k = f"{prefix}{i}"
        if shard_of(k, n) == shard:
            return k
        i += 1


def test_lagging_client_adopts_epoch_after_transport_failure():
    """Epoch-transition skew, happy direction: a client still on the OLD
    shard map keeps working after the clique resharded out a shard it
    depends on — its transport exhaustion triggers a one-shot epoch probe,
    it adopts the new map, and the retried op succeeds against the migrated
    keyspace."""
    seen = []
    tpu_events.add_sink(seen.append)
    clique = LocalClique(2)
    replacement = KVServer(host="127.0.0.1", port=0)
    author = ShardedKVClient(clique.endpoints, timeout=10.0, replicate=True)
    lagging = ShardedKVClient(clique.endpoints, timeout=10.0,
                              connect_retries=2, retry_budget=0.3,
                              replicate=False)
    try:
        k = _key_on(1, 2, "mv/")
        author.set(k, "survives-the-reshard")
        new_eps = [clique.endpoints[0], ("127.0.0.1", replacement.port)]
        reshard_clique(author, new_eps)
        clique.servers[1].close()   # the resharded-out shard goes away
        # The lagging client (epoch 0) routes k to the dead old shard,
        # exhausts transport, adopts epoch 1 and retries on the new map.
        assert lagging.get(k, timeout=10.0) == "survives-the-reshard"
        assert lagging._epoch == 1
        assert lagging.endpoints == [tuple(e) for e in new_eps]
        adopted = [e for e in seen if e.kind == "shard_epoch"
                   and e.payload.get("outcome") == "adopted"]
        assert adopted, [e.kind for e in seen]
    finally:
        tpu_events.remove_sink(seen.append)
        lagging.close()
        author.close()
        replacement.close()
        clique.close()


def test_lagging_client_dual_routes_inside_open_window():
    """Epoch-transition skew mid-window: a lagging client that adopts an
    UNSETTLED epoch must dual-route — new-map writes reach old-map readers
    via the write-through, and keys born on the old map mid-window are
    found via the prev-map read fallback."""
    clique = LocalClique(2)
    extra = KVServer(host="127.0.0.1", port=0)
    author = ShardedKVClient(clique.endpoints, timeout=10.0, replicate=True)
    lagging = ShardedKVClient(clique.endpoints, timeout=10.0, replicate=True)
    old_reader = ShardedKVClient(clique.endpoints, timeout=10.0,
                                 replicate=True)
    try:
        new_eps = list(clique.endpoints) + [("127.0.0.1", extra.port)]
        reshard_clique(author, new_eps, settle=False)
        assert lagging._maybe_adopt_epoch(min_interval=0.0) is True
        assert lagging._epoch == 1
        assert lagging._prev_client is not None, \
            "unsettled adoption must open the dual-route window"
        lagging.set("skewwin/new", 7)
        assert old_reader.try_get("skewwin/new") == 7
        old_reader.set("skewwin/straggler", 8)
        assert lagging.get("skewwin/straggler", timeout=5.0) == 8
    finally:
        old_reader.close()
        lagging.close()
        author.close()
        extra.close()
        clique.close()


def test_malformed_epoch_doc_fails_closed():
    """Epoch-transition skew, fail-closed direction: when the clique moved
    to a map this client cannot parse, the adoption probe raises a clear
    StoreError naming the contract — never a silent wrong-map op."""
    clique = LocalClique(2)
    lagging = ShardedKVClient(clique.endpoints, timeout=10.0,
                              connect_retries=2, retry_budget=0.3,
                              replicate=False)
    anchor = KVClient("127.0.0.1", clique.servers[0].port, timeout=10.0)
    try:
        # A future-format document the epoch-0 client cannot follow.
        anchor.set(EPOCH_KEY, {"epoch": "v2-layout", "topology": "ring"})
        clique.servers[1].close()
        with pytest.raises(StoreError, match="malformed"):
            lagging.get(_key_on(1, 2, "mv/"), timeout=5.0)
    finally:
        anchor.close()
        lagging.close()
        clique.close()


def test_absent_epoch_doc_preserves_transport_error():
    """No epoch document at all: the probe finds nothing and the caller's
    original transport error surfaces untouched — a plain dead shard is not
    misreported as a reshard."""
    clique = LocalClique(2)
    lagging = ShardedKVClient(clique.endpoints, timeout=10.0,
                              connect_retries=2, retry_budget=0.3,
                              replicate=False)
    try:
        clique.servers[1].close()
        with pytest.raises(StoreTransportError):
            lagging.get(_key_on(1, 2, "mv/"), timeout=5.0)
    finally:
        lagging.close()
        clique.close()


def test_old_wire_client_against_a_clique_shard():
    """An old client pointed at ONE shard of a clique still works against
    that shard (the wire is unchanged); it simply sees only that shard's
    slice — the documented skew behavior, not a crash."""
    clique = LocalClique(2)
    new = ShardedKVClient(clique.endpoints, timeout=30.0)
    try:
        for i in range(8):
            new.set(f"sk/{i}", i)
        old = OldWireClient(*clique.endpoints[0])
        try:
            seen = old.call(op="prefix_get", prefix="sk/")["value"]
            whole = new.prefix_get("sk/")
            assert set(seen) <= set(whole)
            assert 0 < len(seen) < len(whole)  # a slice, not the world
        finally:
            old.close()
    finally:
        new.close()
        clique.close()
