"""Wire framing: v1 object frames, v2 bulk frames, and their coexistence.

The bulk protocol is the checkpoint-replication hot path (multi-GB shards), so
these tests pin the properties the perf work depends on: no extra payload
copies on receive, scatter-gather sends that never join, sendfile framing, and
clean self-discrimination between the two frame kinds on one stream.
"""

import os
import pickle
import socket
import threading

import numpy as np
import pytest

from tpu_resiliency.platform import framing


def _pair():
    a, b = socket.socketpair()
    return a, b


class TestRecvExact:
    def test_returns_view_over_single_buffer(self):
        a, b = _pair()
        try:
            a.sendall(b"hello world")
            got = framing.recv_exact(b, 11)
            # The zero-copy contract: one preallocated buffer, no bytes() tail.
            assert isinstance(got, memoryview)
            assert bytes(got) == b"hello world"
        finally:
            a.close()
            b.close()

    def test_chunked_arrival(self):
        a, b = _pair()
        try:
            payload = os.urandom(1 << 16)

            def drip():
                for i in range(0, len(payload), 4096):
                    a.sendall(payload[i : i + 4096])

            t = threading.Thread(target=drip)
            t.start()
            got = framing.recv_exact(b, len(payload))
            t.join()
            assert bytes(got) == payload
        finally:
            a.close()
            b.close()

    def test_eof_raises(self):
        a, b = _pair()
        try:
            a.sendall(b"abc")
            a.close()
            with pytest.raises(EOFError):
                framing.recv_exact(b, 10)
        finally:
            b.close()


class TestObjFrames:
    def test_roundtrip(self):
        a, b = _pair()
        try:
            framing.send_obj(a, {"k": [1, 2, 3]})
            assert framing.recv_obj(b) == {"k": [1, 2, 3]}
        finally:
            a.close()
            b.close()


class TestBulkFrames:
    def test_magic_cannot_alias_a_v1_length(self):
        # A v1 receiver reading a bulk frame sees the magic as an absurd length
        # and rejects it cleanly — the property that makes mixed streams safe.
        (as_len,) = framing.LEN.unpack(framing.BULK_MAGIC)
        assert as_len > framing.DEFAULT_MAX_FRAME
        a, b = _pair()
        try:
            threading.Thread(
                target=framing.send_bulk, args=(a, {"src": 0, "tag": "t"}, [b"x" * 64])
            ).start()
            with pytest.raises(ValueError, match="too large"):
                framing.recv_obj(b)
        finally:
            a.close()
            b.close()

    def test_scatter_gather_roundtrip(self):
        parts = [b"head", np.arange(1024, dtype=np.float32), b"", bytearray(b"tail")]
        joined = b"".join(bytes(memoryview(p).cast("B")) for p in parts)
        a, b = _pair()
        try:
            t = threading.Thread(
                target=framing.send_bulk, args=(a, {"src": 3, "tag": "s"}, parts)
            )
            t.start()
            kind, header, payload = framing.recv_any(b)
            t.join()
            assert kind == "bulk"
            assert header["src"] == 3 and header["tag"] == "s"
            assert header["nbytes"] == len(joined)
            assert bytes(payload) == joined
        finally:
            a.close()
            b.close()

    def test_many_parts_exceeding_iov_max(self):
        # Forces the sendmsg iovec batching path (Linux UIO_MAXIOV is 1024).
        parts = [bytes([i % 256]) * 7 for i in range(2500)]
        a, b = _pair()
        try:
            t = threading.Thread(
                target=framing.send_bulk, args=(a, {"src": 0, "tag": "m"}, parts)
            )
            t.start()
            kind, header, payload = framing.recv_any(b)
            t.join()
            assert kind == "bulk"
            assert bytes(payload) == b"".join(parts)
        finally:
            a.close()
            b.close()

    def test_recv_any_accepts_obj_frames(self):
        a, b = _pair()
        try:
            framing.send_obj(a, {"src": 1, "tag": "t", "blob": b"old"})
            kind, obj, payload = framing.recv_any(b)
            assert kind == "obj" and payload is None
            assert obj["blob"] == b"old"
        finally:
            a.close()
            b.close()

    def test_alloc_lands_payload_in_registered_buffer(self):
        dest = bytearray(128)

        def alloc(header):
            assert header["tag"] == "t"
            return dest

        a, b = _pair()
        try:
            t = threading.Thread(
                target=framing.send_bulk, args=(a, {"src": 0, "tag": "t"}, [b"y" * 100])
            )
            t.start()
            kind, header, payload = framing.recv_any(b, alloc=alloc)
            t.join()
            assert kind == "bulk"
            assert payload.obj is dest  # received in place, zero copies
            assert bytes(dest[:100]) == b"y" * 100
        finally:
            a.close()
            b.close()

    def test_alloc_too_small_falls_back_to_fresh_buffer(self):
        a, b = _pair()
        try:
            t = threading.Thread(
                target=framing.send_bulk, args=(a, {"src": 0, "tag": "t"}, [b"z" * 64])
            )
            t.start()
            kind, _, payload = framing.recv_any(b, alloc=lambda h: bytearray(8))
            t.join()
            assert kind == "bulk" and bytes(payload) == b"z" * 64
        finally:
            a.close()
            b.close()

    def test_oversized_bulk_rejected(self):
        a, b = _pair()
        try:
            hdr = pickle.dumps({"src": 0, "tag": "t", "nbytes": 1 << 40})
            a.sendall(framing.BULK_MAGIC + framing.LEN.pack(len(hdr)) + hdr)
            with pytest.raises(ValueError, match="too large"):
                framing.recv_any(b, max_frame=1 << 20)
        finally:
            a.close()
            b.close()


class TestSendBulkFile:
    def test_file_splice_roundtrip(self, tmp_path):
        payload = os.urandom(1 << 20)
        path = tmp_path / "shard.bin"
        path.write_bytes(payload)
        a, b = _pair()
        try:
            t = threading.Thread(
                target=framing.send_bulk_file, args=(a, {"src": 2, "tag": "f"}, str(path))
            )
            t.start()
            kind, header, got = framing.recv_any(b, max_frame=1 << 24)
            t.join()
            assert kind == "bulk"
            assert header["nbytes"] == len(payload)
            assert bytes(got) == payload
        finally:
            a.close()
            b.close()
