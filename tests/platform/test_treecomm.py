"""Tree collectives (platform/treecomm.py): correctness of the barrier and
all_gather protocols against flat-path semantics, hop-count math, reentrancy,
timeout-is-fatal, and the StoreComm integration that switches shapes on the
world-size floor."""

import threading

import pytest

from tpu_resiliency.exceptions import BarrierTimeout
from tpu_resiliency.platform import treecomm
from tpu_resiliency.platform.shardstore import LocalClique
from tpu_resiliency.platform.store import CoordStore
from tpu_resiliency.platform.treecomm import (
    TreeComm,
    children,
    flat_hops,
    parent,
    tree_depth,
    tree_hops,
)


def test_tree_topology_math():
    assert children(0, 9, 2) == [1, 2]
    assert children(1, 9, 2) == [3, 4]
    assert children(3, 9, 2) == [7, 8]
    assert children(4, 9, 2) == []  # clipped at world
    assert parent(8, 2) == 3 and parent(3, 2) == 1 and parent(1, 2) == 0
    assert tree_depth(1, 8) == 0
    assert tree_depth(9, 8) == 1
    assert tree_depth(256, 8) == 3
    # The acceptance gate's shape: tree wins ≥4× at 256+ ranks.
    for world in (256, 1024, 4096):
        assert flat_hops(world) / tree_hops(world, 8) >= 4.0, world
    # Monotone: hops grow ~log in world, flat grows linearly.
    assert tree_hops(4096, 8) < 2 * tree_hops(256, 8)


def _run_world(store_factory, world, fanout, body):
    out = [None] * world
    errs = []

    def run(i, st):
        try:
            out[i] = body(TreeComm(st, i, world, fanout=fanout), i)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append((i, e))

    stores = [store_factory() for _ in range(world)]
    try:
        threads = [
            threading.Thread(target=run, args=(i, stores[i]))
            for i in range(world)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
    finally:
        for s in stores:
            s.close()
    assert not errs, errs
    return out


@pytest.mark.parametrize("world,fanout", [(5, 2), (9, 2), (13, 3)])
def test_tree_barrier_releases_everyone(kv_server, world, fanout):
    def factory():
        return CoordStore("127.0.0.1", kv_server.port, timeout=30.0,
                          prefix="t/")

    def body(tc, i):
        r1 = tc.barrier("b", timeout=30.0)
        r2 = tc.barrier("b", timeout=30.0)  # reentrant: fixed keys, new round
        return (r1, r2)

    out = _run_world(factory, world, fanout, body)
    assert all(o == (1, 2) for o in out), out


def test_tree_all_gather_matches_flat_contract(kv_server):
    world, fanout = 9, 2

    def factory():
        return CoordStore("127.0.0.1", kv_server.port, timeout=30.0,
                          prefix="g/")

    def body(tc, i):
        a = tc.all_gather({"rank": i, "blob": b"v" * (i + 1)}, tag="ag",
                          timeout=30.0)
        b = tc.all_gather(i * 3, tag="ag", timeout=30.0)  # second round
        return (a, b)

    out = _run_world(factory, world, fanout, body)
    expect_a = [{"rank": i, "blob": b"v" * (i + 1)} for i in range(world)]
    expect_b = [i * 3 for i in range(world)]
    for a, b in out:
        assert a == expect_a
        assert b == expect_b
    # Round keys were GC'd by the root after the ack fan-in.
    probe = CoordStore("127.0.0.1", kv_server.port, timeout=5.0)
    try:
        assert probe.client.keys("g/ag/") == []
    finally:
        probe.close()


def test_tree_over_sharded_clique():
    """The compounding case: edges hash across shards; every shard serves a
    slice of the round and the result still matches the flat contract."""
    clique = LocalClique(3)
    try:
        world, fanout = 9, 2

        def body(tc, i):
            tc.barrier("b", timeout=30.0)
            return tc.all_gather(i, tag="ag", timeout=30.0)

        out = _run_world(lambda: clique.client(prefix="t/"), world, fanout, body)
        assert all(o == list(range(world)) for o in out)
        # The round's ops actually spread: more than one shard saw writes.
        touched = sum(1 for srv in clique.servers if srv._version_clock > 0)
        assert touched >= 2, "tree edges all hashed to one shard"
    finally:
        clique.close()


def test_tree_barrier_timeout_is_fatal(kv_server):
    """A missing member starves its ancestors: everyone who waits surfaces
    BarrierTimeout, the flat contract."""
    world, fanout = 5, 2
    stores = [
        CoordStore("127.0.0.1", kv_server.port, timeout=30.0, prefix="to/")
        for _ in range(world)
    ]
    errs = []

    def run(i):
        tc = TreeComm(stores[i], i, world, fanout=fanout)
        try:
            tc.barrier("b", timeout=0.6)
        except BarrierTimeout:
            errs.append(i)

    try:
        # Leaf 3 never joins: its parent 1 starves on the up edge, the root
        # starves on 1, and leaves 2/4 starve on the release that never comes.
        threads = [
            threading.Thread(target=run, args=(i,))
            for i in range(world) if i != 3
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10.0)
    finally:
        for s in stores:
            s.close()
    # Everyone blocked on 3's subtree (or on the release that never came)
    # timed out; nobody hung.
    assert sorted(errs) == [0, 1, 2, 4]


def test_storecomm_switches_shapes_on_world_floor(kv_server):
    from tpu_resiliency.checkpoint.comm import StoreComm

    def factory():
        return CoordStore("127.0.0.1", kv_server.port, timeout=30.0)

    # Below the floor: flat path (no TreeComm constructed).
    st = factory()
    try:
        flat = StoreComm(st, 0, [0, 1, 2], tree_min_world=17)
        assert flat._tree is None
        forced = StoreComm(st, 0, [0, 1, 2], tree_min_world=2, tree_fanout=2)
        assert forced._tree is not None
        assert forced._tree.world == 3
    finally:
        st.close()

    # Forced-tree StoreComm produces the flat all_gather's exact result.
    world = 9
    results = [None] * world
    stores = [factory() for _ in range(world)]

    def run(i):
        comm = StoreComm(stores[i], i, list(range(world)), timeout=30.0,
                         tree_min_world=2, tree_fanout=2)
        comm.barrier("b", timeout=30.0)
        results[i] = comm.all_gather((i, b"x" * i), tag="ag")
        assert comm.all_reduce_max(i, tag="mx") == world - 1

    try:
        threads = [threading.Thread(target=run, args=(i,)) for i in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
    finally:
        for s in stores:
            s.close()
    expect = [(i, b"x" * i) for i in range(world)]
    assert all(r == expect for r in results), results


def test_env_knobs_respected(kv_server, monkeypatch):
    from tpu_resiliency.checkpoint.comm import StoreComm

    monkeypatch.setenv(treecomm.TREE_MIN_ENV, "4")
    monkeypatch.setenv(treecomm.TREE_FANOUT_ENV, "3")
    st = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
    try:
        comm = StoreComm(st, 0, [0, 1, 2, 3])
        assert comm._tree is not None
        assert comm._tree.fanout == 3
        small = StoreComm(st, 0, [0, 1, 2])
        assert small._tree is None
    finally:
        st.close()


@pytest.mark.parametrize("src", [0, 4, 8])
def test_tree_broadcast_matches_flat_contract(kv_server, src):
    """broadcast fans the source's value down per-child keys: every index
    (root, mid-tree, leaf source) returns the same object, round keys are
    GC'd, and repeated rounds stay isolated."""
    world, fanout = 9, 2

    def factory():
        return CoordStore("127.0.0.1", kv_server.port, timeout=30.0,
                          prefix=f"bc{src}/")

    def body(tc, i):
        a = tc.broadcast(
            {"from": i} if i == src else None, src, tag="bc", timeout=30.0
        )
        b = tc.broadcast(
            ("second", i) if i == src else None, src, tag="bc", timeout=30.0
        )
        return (a, b)

    out = _run_world(factory, world, fanout, body)
    assert all(o == ({"from": src}, ("second", src)) for o in out), out
    probe = CoordStore("127.0.0.1", kv_server.port, timeout=5.0)
    try:
        assert probe.client.keys(f"bc{src}/bc/") == []
    finally:
        probe.close()


def test_storecomm_broadcast_goes_tree_above_floor(kv_server):
    """StoreComm.broadcast rides the tree above the world floor and returns
    the flat path's exact result either way (PR-14 headroom closed)."""
    from tpu_resiliency.checkpoint.comm import StoreComm

    world = 9
    results = [None] * world
    stores = [
        CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
        for _ in range(world)
    ]

    def run(i):
        comm = StoreComm(stores[i], i, list(range(world)), timeout=30.0,
                         tree_min_world=2, tree_fanout=2)
        assert comm._tree is not None
        results[i] = comm.broadcast(
            {"layout": "x" * 64} if i == 3 else None, src=3, tag="hdr"
        )

    try:
        threads = [threading.Thread(target=run, args=(i,)) for i in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
    finally:
        for s in stores:
            s.close()
    assert all(r == {"layout": "x" * 64} for r in results), results
