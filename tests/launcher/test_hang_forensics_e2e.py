"""ACCEPTANCE: hang forensics end to end under the real launcher.

A two-rank launch with FT monitors on. Rank 1 wedges (a GIL-holding sleep; a
compiled-device-hang variant rides the slow marker) while rank 0 blocks in a
store barrier waiting for it. The plane must prove, live and post-hoc:

- ``/hangz`` names the stuck rank, its section, and a stuck-duration while
  the job is still wedged (before the kill ladder completes);
- the watchdog's ``hang_detected`` cause carries the location beacon
  ("last seen in section=step ...");
- the incident artifact embeds (a) the barrier census with the victim listed
  missing and (b) the victim's multi-thread stack dump with the injected
  frame visible;
- ``tpu_rank_blocked_seconds`` and ``tpu_hang_suspects_total`` appear in the
  merged ``/metrics`` view, and ``tpu_stack_dumps_total`` aggregates from the
  events stream.
"""

import json
import os
import subprocess
import sys
import textwrap
import time
import urllib.request

import pytest

NPROC = 2

WORKER = textwrap.dedent(
    """
    import importlib, os, sys, threading, time
    from tpu_resiliency.platform.store import CoordStore
    from tpu_resiliency.utils import location
    from tpu_resiliency.utils.events import record
    from tpu_resiliency.watchdog.monitor_client import RankMonitorClient
    # importlib: the tools package re-exports the inject_fault FUNCTION as an
    # attribute, shadowing the module on plain `import ... as inj`.
    inj = importlib.import_module("tpu_resiliency.inprocess.tools.inject_fault")

    stop, fault_name = sys.argv[1], sys.argv[2]
    rank = int(os.environ["RANK"])
    round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
    inj.GIL_SLEEP_CHUNK_S = 3.0  # > hb timeout: no beat can land mid-chunk

    client = RankMonitorClient()
    client.init_workload_monitoring()

    # Background heartbeats: a healthy rank parked in a barrier keeps
    # beating; the GIL_SLEEP victim's beats stop because the chunked hold
    # freezes every thread.
    def beats():
        while True:
            try:
                client.send_heartbeat()
            except Exception:
                return
            time.sleep(0.25)

    threading.Thread(target=beats, daemon=True).start()

    store = CoordStore(
        os.environ["TPU_RESILIENCY_STORE_HOST"],
        int(os.environ["TPU_RESILIENCY_STORE_PORT"]),
        prefix="hangtest/",
    )

    for i in range(3):
        location.note_step(i)
        record("inprocess", "iteration_start", iteration=i)
        client.start_section("step")
        store.barrier(f"step-{round_no}-{i}", rank, 2, timeout=120.0)
        client.end_section("step")
        time.sleep(0.05)

    if round_no == 0:
        location.note_step(3)
        record("inprocess", "iteration_start", iteration=3)
        if rank == 1:
            # The victim: opens its section, then wedges. The monitor must
            # detect, capture stacks, and run the kill ladder.
            client.start_section("step")
            inj.inject_fault(getattr(inj.Fault, fault_name), duration=90.0)
            time.sleep(90)
            sys.exit(0)
        # Rank 0 blocks in the barrier the victim never reaches — the
        # census's "who never arrived" evidence. No section here: its own
        # watchdog must keep trusting the background heartbeats.
        try:
            store.barrier(f"step-0-3", rank, 2, timeout=300.0)
        except Exception:
            pass
        time.sleep(300)
        sys.exit(0)

    # Replacement round: hold until the test finishes scraping.
    deadline = time.time() + 120
    while not os.path.exists(stop) and time.time() < deadline:
        time.sleep(0.1)
    """
)


def _tail(tmp_path, n=3000):
    try:
        return (tmp_path / "launcher.out").read_text()[-n:]
    except OSError:
        return "<no launcher.out>"


def _get_json(port, path, timeout=5):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return json.loads(r.read())


def _get_text(port, path, timeout=5):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return r.read().decode()


def _launch(tmp_path, fault_name):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    stop = tmp_path / "stop"
    events_file = tmp_path / "events.jsonl"
    run_dir = tmp_path / "run"
    incidents = tmp_path / "incidents"
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "TPU_RESILIENCY_LOG_LEVEL": "INFO"})
    # File-backed output, NOT pipes: workers/monitors inherit the launcher's
    # stdio fds, so a PIPE would (a) never reach EOF for communicate() while
    # any child lives and (b) deadlock everything once full.
    out = open(tmp_path / "launcher.out", "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--nproc-per-node", str(NPROC), "--max-restarts", "2",
         "--rdzv-last-call", "0.2", "--monitor-interval", "0.1",
         "--telemetry-port", "0",
         "--ft-param-initial_rank_heartbeat_timeout", "15",
         "--ft-param-rank_heartbeat_timeout", "2.0",
         "--ft-param-workload_check_interval", "0.25",
         "--ft-param-rank_section_timeouts", "{step: 4.0}",
         "--ft-param-stack_dump_grace", "6.0",
         "--events-file", str(events_file), "--run-dir", str(run_dir),
         "--incidents-dir", str(incidents),
         str(script), str(stop), fault_name],
        stdout=out, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(tmp_path),
    )
    out.close()
    return proc, stop, events_file, run_dir, incidents


def _hang_forensics_flow(tmp_path, fault_name, injected_frame):
    proc, stop, events_file, run_dir, incidents = _launch(tmp_path, fault_name)
    hangz = None
    try:
        # -- port-file handshake ------------------------------------------
        port_file = run_dir / "telemetry.port"
        deadline = time.time() + 60
        while not port_file.exists():
            assert proc.poll() is None, _tail(tmp_path)
            assert time.time() < deadline, "telemetry.port never appeared"
            time.sleep(0.2)
        port = int(port_file.read_text().strip())

        # -- (a) /hangz names the stuck rank while the job is wedged ------
        deadline = time.time() + 120
        while time.time() < deadline:
            assert proc.poll() is None, _tail(tmp_path)
            try:
                doc = _get_json(port, "/hangz")
            except OSError:
                time.sleep(0.2)
                continue
            suspects = {s["rank"]: s for s in doc.get("suspects", [])}
            victim = next(
                (r for r in doc.get("ranks", []) if r.get("rank") == 1), None
            )
            if (
                1 in suspects
                and victim is not None
                and (victim.get("location") or {}).get("section") == "step"
                and isinstance(victim.get("stuck_s"), (int, float))
                and victim["stuck_s"] > 0
                and any("missing" in why for why in suspects[1]["reasons"])
            ):
                hangz = doc
                break
            time.sleep(0.2)
        assert hangz is not None, "/hangz never identified the stuck rank"
        blocked_barriers = [
            b for b in hangz["barriers"] if 1 in b.get("missing", [])
        ]
        assert blocked_barriers, hangz["barriers"]
        assert blocked_barriers[0]["waiters"] >= 1  # rank 0 parked, waiting

        # -- (b) incident artifact: census + the victim's stack dump ------
        deadline = time.time() + 180
        artifact = None
        while time.time() < deadline and artifact is None:
            assert proc.poll() is None, _tail(tmp_path)
            names = sorted(
                n for n in (os.listdir(incidents) if incidents.exists() else [])
                if n.startswith("incident-") and n.endswith(".json")
            )
            for n in names:
                with open(incidents / n) as f:
                    doc = json.load(f)
                if doc.get("census"):
                    artifact = doc
                    break
            time.sleep(0.3)
        assert artifact is not None, "no incident artifact with a census"
        census = artifact["census"]
        assert any(
            1 in b.get("missing", []) for b in census.get("barriers", [])
        ), "census does not list the victim as missing"
        assert any(s["rank"] == 1 for s in census.get("suspects", []))
        # The victim's dump must be IN the artifact: normally in its flight
        # ring (the flight sink runs first, so even a SIGKILL racing the
        # capture persists it), with the shared-stream event window as the
        # belt-and-braces second copy.
        dumps = [
            r for ident, recs in (artifact.get("flight") or {}).items()
            if ident.startswith("1-") for r in recs
            if r.get("kind") == "stack_dump"
        ]
        dumps += [
            r for r in artifact.get("events", [])
            if r.get("kind") == "stack_dump" and r.get("rank") == 1
        ]
        assert dumps, (
            f"victim stack dump missing from the artifact (flight idents "
            f"{list((artifact.get('flight') or {}))})"
        )
        best = max(dumps, key=lambda d: len(d.get("threads") or []))
        assert len(best["threads"]) >= 2, "expected a multi-thread dump"
        all_frames = [
            f for t in best["threads"] for f in t.get("frames", [])
        ]
        assert any(injected_frame in f for f in all_frames), (
            f"injected frame {injected_frame!r} not visible in "
            + "\n".join(all_frames[:80])
        )

        # The hang_detected cause carries the location beacon.
        from tpu_resiliency.utils.events import read_events

        hang_evs = [
            e for e in read_events(str(events_file))
            if e.get("kind") == "hang_detected"
        ]
        assert hang_evs, "no hang_detected event"
        assert any(
            "last seen in" in e.get("reason", "")
            and "section=step" in e.get("reason", "")
            for e in hang_evs
        ), [e.get("reason") for e in hang_evs]

        # -- (c) merged /metrics carries the new families ------------------
        deadline = time.time() + 60
        prom = ""
        while time.time() < deadline:
            prom = _get_text(port, "/metrics")
            if "tpu_hang_suspects_total" in prom:
                break
            time.sleep(0.3)
        assert 'tpu_hang_suspects_total{rank="1"}' in prom, prom[-2000:]
        assert 'tpu_rank_blocked_seconds{rank="1"}' in prom
        assert "tpu_barrier_waiters" in prom

        # -- clean shutdown ------------------------------------------------
        stop.touch()
        rc = proc.wait(timeout=120)
        assert rc == 0, _tail(tmp_path)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # -- post-hoc parity ---------------------------------------------------
    from tpu_resiliency.utils.events import read_events
    from tpu_resiliency.utils.metrics import aggregate

    reg = aggregate(read_events(str(events_file)))
    assert reg.counter("tpu_hang_suspects_total", rank="1").value >= 1
    assert reg.counter(
        "tpu_rank_terminations_total", cause="hang"
    ).value >= 1
    # At least the victim dumped (reason prefix "hang"); siblings usually too.
    total_dumps = sum(
        e.get("thread_count", 0) >= 1
        for e in read_events(str(events_file)) if e.get("kind") == "stack_dump"
    )
    assert total_dumps >= 1
    # tpu-incident-report renders the census table.
    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.tools.incident_report",
         str(tmp_path / "incidents")],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "hang census" in r.stdout
    assert "never arrived [1]" in r.stdout
    assert "stack dump" in r.stdout
    return hangz


def test_hang_forensics_gil_sleep(tmp_path):
    """The GIL-holding stall: beats freeze, detection fires mid-chunk, the
    capture lands in a chunk gap before the kill ladder."""
    _hang_forensics_flow(tmp_path, "GIL_SLEEP", "_gil_sleep")


@pytest.mark.slow
def test_hang_forensics_device_hang(tmp_path):
    """The compiled-while-loop device hang: heartbeats keep flowing (the wait
    releases the GIL), so the SECTION timeout is the detector, and the dump
    listener captures immediately."""
    _hang_forensics_flow(tmp_path, "DEVICE_HANG", "_device_hang")
