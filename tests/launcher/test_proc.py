"""WorkerGroup subprocess management: env injection, polling, error files, stop."""

import os
import signal
import textwrap
import time

from tpu_resiliency.launcher.errors import WorkerError, write_error_file
from tpu_resiliency.launcher.proc import GroupState, WorkerGroup


def wait_state(group, want, timeout=60.0):  # generous: interpreter startup is
    # multi-second here and stretches further under suite/soak load
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        state = group.poll()
        if state is want:
            return state
        time.sleep(0.05)
    return group.poll()


def test_success_and_env(tmp_path):
    out = tmp_path / "env_{rank}.txt"
    script = tmp_path / "w.py"
    script.write_text(
        textwrap.dedent(
            f"""
            import os
            path = {str(out)!r}.format(rank=os.environ["RANK"])
            with open(path, "w") as f:
                f.write(",".join(os.environ[k] for k in
                    ("RANK", "LOCAL_RANK", "WORLD_SIZE", "LOCAL_WORLD_SIZE",
                     "NODE_RANK", "TPU_FT_RESTART_COUNT")))
            """
        )
    )
    group = WorkerGroup(
        argv=[str(script)],
        nproc=2,
        base_env={"NODE_RANK": "3"},
        run_dir=str(tmp_path / "run"),
    )
    group.start(round_no=7, first_global_rank=6, world_size=8)
    assert wait_state(group, GroupState.SUCCEEDED) is GroupState.SUCCEEDED
    group.reap()
    assert group.exitcodes() == {6: 0, 7: 0}
    assert (tmp_path / "env_6.txt").read_text() == "6,0,8,2,3,7"
    assert (tmp_path / "env_7.txt").read_text() == "7,1,8,2,3,7"


def test_failure_collects_error_file(tmp_path):
    script = tmp_path / "boom.py"
    script.write_text(
        textwrap.dedent(
            """
            import os
            from tpu_resiliency.launcher.errors import record

            @record
            def main():
                if os.environ["RANK"] == "1":
                    raise ValueError("rank one always dies")

            main()
            """
        )
    )
    group = WorkerGroup(
        argv=[str(script)], nproc=2, base_env={}, run_dir=str(tmp_path / "run")
    )
    group.start(round_no=0, first_global_rank=0, world_size=2)
    assert wait_state(group, GroupState.FAILED) is GroupState.FAILED
    group.stop()
    failures = group.failures()
    assert [f.global_rank for f in failures] == [1]
    f = failures[0]
    assert f.exitcode == 1
    assert f.error is not None
    assert f.error.exception_type == "ValueError"
    assert "rank one always dies" in f.error.message
    assert "ValueError" in f.error.traceback
    assert "rank 1" in f.describe() and "ValueError" in f.describe()


def test_stop_terminates_sleepers(tmp_path):
    script = tmp_path / "sleep.py"
    script.write_text("import time; time.sleep(600)")
    group = WorkerGroup(
        argv=[str(script)], nproc=2, base_env={}, run_dir=str(tmp_path / "run")
    )
    group.start(round_no=0, first_global_rank=0, world_size=2)
    assert group.poll() is GroupState.RUNNING
    t0 = time.monotonic()
    group.stop(grace=5.0)
    assert time.monotonic() - t0 < 10.0
    codes = group.exitcodes()
    assert all(c is not None and c != 0 for c in codes.values())


def test_log_capture(tmp_path):
    script = tmp_path / "talk.py"
    script.write_text("import os, sys; print('out', os.environ['RANK']); print('err', file=sys.stderr)")
    group = WorkerGroup(
        argv=[str(script)],
        nproc=1,
        base_env={},
        run_dir=str(tmp_path / "run"),
        log_dir=str(tmp_path / "logs"),
    )
    group.start(round_no=2, first_global_rank=5, world_size=6)
    wait_state(group, GroupState.SUCCEEDED)
    group.reap()
    d = tmp_path / "logs" / "round_2" / "rank_5"
    assert (d / "stdout.log").read_text() == "out 5\n"
    assert (d / "stderr.log").read_text() == "err\n"


def test_error_file_roundtrip(tmp_path):
    path = str(tmp_path / "err.json")
    try:
        raise RuntimeError("direct write")
    except RuntimeError as e:
        write_error_file(e, path)
    err = WorkerError.from_file(path)
    assert err.message == "direct write" and err.exception_type == "RuntimeError"
    assert err.pid == os.getpid() and err.timestamp > 0


def test_wait_change_wakes_on_worker_exit(tmp_path):
    """Event-driven death detection: wait_change returns as soon as a worker
    exits instead of sleeping out its full timeout (the respawn path's
    detection segment must not be quantized by the poll interval)."""
    import time

    script = tmp_path / "w.py"
    script.write_text("import sys, time\ntime.sleep(0.3)\nsys.exit(3)\n")
    group = WorkerGroup(
        argv=[str(script)], nproc=1, base_env={}, run_dir=str(tmp_path / "run")
    )
    group.start(round_no=0, first_global_rank=0, world_size=1)
    t0 = time.monotonic()
    woke = group.wait_change(timeout=60.0)
    waited = time.monotonic() - t0
    assert woke, "no wake despite worker exit"
    assert waited < 50.0, f"wait_change slept {waited:.1f}s of its 60s timeout"
    assert group.poll() is GroupState.FAILED
    # Subsequent waits block again (the event auto-resets).
    assert not group.wait_change(timeout=0.05)
    group.stop()
