"""Restart fast-path rendezvous (round reuse): a replacement round with
unchanged agent membership closes with a single CAS + one confirmation
barrier instead of the full open/join/last-call/close ladder — and every
ineligibility (digest mismatch, dead member, store trouble mid-path) degrades
to the full ladder, never to a wrong world."""

import threading
import time

import pytest

from tpu_resiliency.exceptions import StoreError
from tpu_resiliency.launcher.rendezvous import (
    RendezvousSettings,
    StoreRendezvous,
    _membership_digest,
)
from tpu_resiliency.platform import chaos
from tpu_resiliency.platform.store import CoordStore


@pytest.fixture(autouse=True)
def _clean_plan():
    chaos.clear_plan()
    yield
    chaos.clear_plan()


def make_rdzv(port, node_id, **kw):
    defaults = dict(
        min_nodes=2,
        max_nodes=2,
        join_timeout=20.0,
        last_call_timeout=0.3,
        keep_alive_interval=0.1,
        keep_alive_timeout=2.0,
        poll_interval=0.05,
        fast_path_timeout=3.0,
    )
    defaults.update(kw)
    store = CoordStore("127.0.0.1", port, prefix="rdzv/")
    return StoreRendezvous(store, node_id, RendezvousSettings(**defaults)), store


def _place_all(nodes, prev_round=-1, timeout=30.0):
    """next_round() on every node concurrently; {node_id: outcome}."""
    outs, errs = {}, {}

    def run(nid, r):
        try:
            outs[nid] = r.next_round(prev_round)
        except Exception as e:  # surfaced by the caller's assert
            errs[nid] = e

    ts = [
        threading.Thread(target=run, args=(nid, r)) for nid, r in nodes
    ]
    for t in ts:
        t.start()
        time.sleep(0.02)  # deterministic join order on round 0
    for t in ts:
        t.join(timeout)
    assert not errs, errs
    assert len(outs) == len(nodes), (sorted(outs), errs)
    return outs


def test_unchanged_membership_rides_the_fast_path(kv_server):
    nodes = [make_rdzv(kv_server.port, n) for n in ("a", "b")]
    pairs = [("a", nodes[0][0]), ("b", nodes[1][0])]
    try:
        outs0 = _place_all(pairs)
        assert {o.round for o in outs0.values()} == {0}
        assert not any(o.fast for o in outs0.values())
        ranks0 = {nid: o.node_rank for nid, o in outs0.items()}
        nodes[0][0].request_restart("worker died")
        outs1 = _place_all(pairs, prev_round=0)
        assert {o.round for o in outs1.values()} == {1}
        assert all(o.fast for o in outs1.values()), outs1
        # Round reuse preserves the placement exactly.
        assert {nid: o.node_rank for nid, o in outs1.items()} == ranks0
        # The reused round carries the bumped restart epoch.
        assert all(o.epoch == 1 for o in outs1.values())
        # And fast rounds are themselves reusable.
        nodes[1][0].request_restart("again")
        outs2 = _place_all(pairs, prev_round=1)
        assert all(o.fast and o.round == 2 for o in outs2.values())
    finally:
        for r, s in nodes:
            r.stop_keepalive()
            s.close()


def test_membership_change_takes_the_full_ladder(kv_server):
    """A dead member changes the membership: the digest no longer matches and
    the replacement round must re-rank through the full ladder (here: the
    former spare gets promoted into the active set)."""
    nodes = [make_rdzv(kv_server.port, n) for n in ("a", "b", "c")]
    pairs = [(n, r) for n, (r, s) in zip(("a", "b", "c"), nodes)]
    try:
        outs0 = _place_all(pairs)
        assert outs0["c"].is_spare
        # "a" dies for good: keep-alive goes stale.
        nodes[0][0].leave()
        nodes[0][1].close()
        time.sleep(2.2)  # past keep_alive_timeout
        nodes[1][0].request_restart("a died")
        survivors = pairs[1:]
        outs1 = _place_all(survivors, prev_round=0)
        assert {o.round for o in outs1.values()} == {1}
        assert not any(o.fast for o in outs1.values()), outs1
        assert sorted(
            o.node_rank for o in outs1.values() if o.node_rank is not None
        ) == [0, 1]
    finally:
        for r, s in nodes[1:]:
            r.stop_keepalive()
            s.close()


def test_stale_membership_memory_does_not_reuse(kv_server):
    """A node whose remembered placement is for a DIFFERENT round than the
    stale state must not fast-close it."""
    rdzv, store = make_rdzv(kv_server.port, "a", min_nodes=1, max_nodes=1)
    try:
        out0 = rdzv.next_round()
        assert out0.round == 0 and not out0.fast
        # Forge memory for a different round: eligibility must fail.
        rdzv._last_membership = (7, _membership_digest(["a"], []))
        out1_state = store.try_get("state")
        assert out1_state["round"] == 0
        rdzv.request_restart("x")
        out1 = rdzv.next_round(0)
        assert out1.round == 1 and not out1.fast
    finally:
        rdzv.stop_keepalive()
        store.close()


def test_store_trouble_mid_fast_path_degrades_to_full_ladder(kv_server, monkeypatch):
    """A confirmation barrier that dies mid-fast-path abandons the reused
    round; the full ladder re-forms the world and both nodes still place."""
    nodes = [make_rdzv(kv_server.port, n) for n in ("a", "b")]
    pairs = [("a", nodes[0][0]), ("b", nodes[1][0])]
    try:
        outs0 = _place_all(pairs)
        assert {o.round for o in outs0.values()} == {0}
        # Node a's confirmation barrier raises StoreError once.
        real_join = nodes[0][1].barrier_join
        state = {"failed": False}

        def flaky_join(name, *a, **kw):
            if "fastbar/" in name and not state["failed"]:
                state["failed"] = True
                raise StoreError("injected: store lost mid-fast-path")
            return real_join(name, *a, **kw)

        monkeypatch.setattr(nodes[0][1], "barrier_join", flaky_join)
        nodes[0][0].request_restart("worker died")
        outs1 = _place_all(pairs, prev_round=0)
        assert state["failed"], "fast path never reached its barrier"
        # Both placed in the same (post-abandon) round via the full ladder.
        assert len({o.round for o in outs1.values()}) == 1
        assert {o.node_rank for o in outs1.values()} == {0, 1}
        assert not any(o.fast for o in outs1.values()), outs1
    finally:
        for r, s in nodes:
            r.stop_keepalive()
            s.close()


@pytest.mark.chaos
def test_chaos_reset_on_the_cas_still_places(kv_server):
    """Seeded connection resets across the fast path's store traffic (the CAS
    ride the store channel): the client's transparent retry or the ladder
    fallback must still place both nodes — never a wedge, never an error."""
    nodes = [make_rdzv(kv_server.port, n) for n in ("a", "b")]
    pairs = [("a", nodes[0][0]), ("b", nodes[1][0])]
    try:
        outs0 = _place_all(pairs)
        assert {o.round for o in outs0.values()} == {0}
        nodes[0][0].request_restart("worker died")
        # Resets at staggered call indices so the injection lands across the
        # dead-check / epoch-read / CAS sequence on both nodes' clients.
        chaos.install_plan(chaos.ChaosPlan.parse(
            "1234:store.send.reset@at=0+2+5"
        ))
        outs1 = _place_all(pairs, prev_round=0)
        assert len({o.round for o in outs1.values()}) == 1
        assert {o.node_rank for o in outs1.values()} == {0, 1}
    finally:
        chaos.clear_plan()
        for r, s in nodes:
            r.stop_keepalive()
            s.close()


def test_fast_path_disabled_by_setting(kv_server):
    nodes = [make_rdzv(kv_server.port, n, fast_path=False) for n in ("a", "b")]
    pairs = [("a", nodes[0][0]), ("b", nodes[1][0])]
    try:
        _place_all(pairs)
        nodes[0][0].request_restart("x")
        outs1 = _place_all(pairs, prev_round=0)
        assert {o.round for o in outs1.values()} == {1}
        assert not any(o.fast for o in outs1.values())
    finally:
        for r, s in nodes:
            r.stop_keepalive()
            s.close()
