"""Restart fast-path rendezvous (round reuse): a replacement round with
unchanged agent membership closes with a single CAS + one confirmation
barrier instead of the full open/join/last-call/close ladder — and every
ineligibility (digest mismatch, dead member, store trouble mid-path) degrades
to the full ladder, never to a wrong world."""

import threading
import time

import pytest

from tpu_resiliency.exceptions import StoreError
from tpu_resiliency.launcher.rendezvous import (
    RendezvousSettings,
    StoreRendezvous,
    _membership_digest,
)
from tpu_resiliency.platform import chaos
from tpu_resiliency.platform.store import CoordStore


@pytest.fixture(autouse=True)
def _clean_plan():
    chaos.clear_plan()
    yield
    chaos.clear_plan()


def make_rdzv(port, node_id, **kw):
    defaults = dict(
        min_nodes=2,
        max_nodes=2,
        join_timeout=20.0,
        last_call_timeout=0.3,
        keep_alive_interval=0.1,
        keep_alive_timeout=2.0,
        poll_interval=0.05,
        fast_path_timeout=3.0,
    )
    defaults.update(kw)
    store = CoordStore("127.0.0.1", port, prefix="rdzv/")
    return StoreRendezvous(store, node_id, RendezvousSettings(**defaults)), store


def _place_all(nodes, prev_round=-1, timeout=30.0):
    """next_round() on every node concurrently; {node_id: outcome}."""
    outs, errs = {}, {}

    def run(nid, r):
        try:
            outs[nid] = r.next_round(prev_round)
        except Exception as e:  # surfaced by the caller's assert
            errs[nid] = e

    ts = [
        threading.Thread(target=run, args=(nid, r)) for nid, r in nodes
    ]
    for t in ts:
        t.start()
        time.sleep(0.02)  # deterministic join order on round 0
    for t in ts:
        t.join(timeout)
    assert not errs, errs
    assert len(outs) == len(nodes), (sorted(outs), errs)
    return outs


def test_unchanged_membership_rides_the_fast_path(kv_server):
    nodes = [make_rdzv(kv_server.port, n) for n in ("a", "b")]
    pairs = [("a", nodes[0][0]), ("b", nodes[1][0])]
    try:
        outs0 = _place_all(pairs)
        assert {o.round for o in outs0.values()} == {0}
        assert not any(o.fast for o in outs0.values())
        ranks0 = {nid: o.node_rank for nid, o in outs0.items()}
        nodes[0][0].request_restart("worker died")
        outs1 = _place_all(pairs, prev_round=0)
        assert {o.round for o in outs1.values()} == {1}
        assert all(o.fast for o in outs1.values()), outs1
        # Round reuse preserves the placement exactly.
        assert {nid: o.node_rank for nid, o in outs1.items()} == ranks0
        # The reused round carries the bumped restart epoch.
        assert all(o.epoch == 1 for o in outs1.values())
        # And fast rounds are themselves reusable.
        nodes[1][0].request_restart("again")
        outs2 = _place_all(pairs, prev_round=1)
        assert all(o.fast and o.round == 2 for o in outs2.values())
    finally:
        for r, s in nodes:
            r.stop_keepalive()
            s.close()


def test_departed_active_swaps_in_spare_on_the_fast_path(kv_server):
    """A departed active whose absence is fully explained (keep-alive gone)
    no longer forces the full ladder: the shrink fast path backfills the
    vacated slot from the surviving spare and closes in one CAS + barrier."""
    names = ("a", "b", "c")
    nodes = {n: make_rdzv(kv_server.port, n) for n in names}
    pairs = [(n, nodes[n][0]) for n in names]
    closed = []
    try:
        _place_all(pairs)
        # Hand-close a spare-bearing round 1 (the shape a simultaneous
        # restart re-registration produces — a full close races the third
        # joiner into ``waiting``, so the natural path is timing-dependent)
        # and seed every survivor's reuse key against it.
        cur = nodes["a"][1].try_get("state")
        st1 = {
            "round": 1, "status": "closed", "seq": cur["seq"] + 1,
            "participants": {"a": 0, "b": 1, "c": 2}, "waiting": {},
            "active": ["a", "b"], "spares": ["c"], "epoch": 0,
            "expected": ["a", "b", "c"],
        }
        assert nodes["a"][0]._cas(cur, st1)
        digest = _membership_digest(["a", "b"], ["c"])
        for n in names:
            nodes[n][0]._last_membership = (1, digest)
        # The rank-0 active departs for good: keep-alive key dropped.
        nodes["a"][0].leave()
        nodes["a"][1].close()
        closed.append("a")
        nodes["b"][0].request_restart("a died")
        outs2 = _place_all(
            [(n, nodes[n][0]) for n in ("b", "c")], prev_round=1
        )
        assert {o.round for o in outs2.values()} == {2}
        # Fast path: surviving active compacts to rank 0, spare backfills.
        assert all(o.fast for o in outs2.values()), outs2
        assert outs2["b"].node_rank == 0
        assert outs2["c"].node_rank == 1
        assert all(o.spares == [] for o in outs2.values())
    finally:
        for n in names:
            if n not in closed:
                nodes[n][0].stop_keepalive()
                nodes[n][1].close()


def test_explained_shrink_takes_the_fast_path(kv_server):
    """A shrink with all survivors live rides the fast-path rounds: the
    exit-marked member is dropped, survivor ranks compact in order, and no
    open/join/last-call ladder runs (sub-second, not seconds)."""
    names = ("a", "b", "c")
    nodes = [
        make_rdzv(kv_server.port, n, min_nodes=2, max_nodes=3) for n in names
    ]
    pairs = [(n, r) for n, (r, s) in zip(names, nodes)]
    try:
        outs0 = _place_all(pairs)
        assert {o.node_rank for o in outs0.values()} == {0, 1, 2}
        # "c" is preempted: clean departure = exit mark + keep-alive drop.
        nodes[2][0].mark_exited()
        nodes[2][0].leave()
        nodes[2][1].close()
        nodes[0][0].request_restart("c preempted (shrink)")
        t0 = time.monotonic()
        outs1 = _place_all(pairs[:2], prev_round=0)
        elapsed = time.monotonic() - t0
        assert {o.round for o in outs1.values()} == {1}
        assert all(o.fast for o in outs1.values()), outs1
        assert outs1["a"].node_rank == 0 and outs1["b"].node_rank == 1
        assert all(o.active == ["a", "b"] for o in outs1.values())
        # The whole shrink round stays inside the warm-spare envelope —
        # far under the ladder's last-call + keep-alive grace alone.
        assert elapsed < 2.0, f"shrink round took {elapsed:.2f}s"
        # A shrink below min_nodes must NOT fast-close a splinter world:
        # with "b" also gone, eligibility fails and the ladder owns it.
        nodes[1][0].mark_exited()
        nodes[1][0].leave()
        nodes[1][1].close()
        state = nodes[0][1].try_get("state")
        assert state["round"] == 1
        assert nodes[0][0]._try_fast_reuse(state, 1) is False
    finally:
        for r, s in nodes[:1]:
            r.stop_keepalive()
            s.close()


def test_rejoining_node_clears_its_stale_exit_mark(kv_server):
    """An exit mark from an earlier life of a node_id must not shrink the
    live member out of the world: re-entering rendezvous retracts it."""
    nodes = [make_rdzv(kv_server.port, n) for n in ("a", "b")]
    pairs = [("a", nodes[0][0]), ("b", nodes[1][0])]
    try:
        # Forge a stale exit mark for "b" from a previous incarnation.
        nodes[1][1].set("exit/b", True)
        outs0 = _place_all(pairs)
        assert {o.round for o in outs0.values()} == {0}
        nodes[0][0].request_restart("worker died")
        outs1 = _place_all(pairs, prev_round=0)
        # Both still placed — the mark was cleared on (re)join, so the fast
        # path reuses the full cast instead of shrinking "b" away.
        assert {o.node_rank for o in outs1.values()} == {0, 1}
        assert all(o.fast for o in outs1.values()), outs1
        assert all(len(o.active) == 2 for o in outs1.values())
    finally:
        for r, s in nodes:
            r.stop_keepalive()
            s.close()


def test_stale_membership_memory_does_not_reuse(kv_server):
    """A node whose remembered placement is for a DIFFERENT round than the
    stale state must not fast-close it."""
    rdzv, store = make_rdzv(kv_server.port, "a", min_nodes=1, max_nodes=1)
    try:
        out0 = rdzv.next_round()
        assert out0.round == 0 and not out0.fast
        # Forge memory for a different round: eligibility must fail.
        rdzv._last_membership = (7, _membership_digest(["a"], []))
        out1_state = store.try_get("state")
        assert out1_state["round"] == 0
        rdzv.request_restart("x")
        out1 = rdzv.next_round(0)
        assert out1.round == 1 and not out1.fast
    finally:
        rdzv.stop_keepalive()
        store.close()


def test_store_trouble_mid_fast_path_degrades_to_full_ladder(kv_server, monkeypatch):
    """A confirmation barrier that dies mid-fast-path abandons the reused
    round; the full ladder re-forms the world and both nodes still place."""
    nodes = [make_rdzv(kv_server.port, n) for n in ("a", "b")]
    pairs = [("a", nodes[0][0]), ("b", nodes[1][0])]
    try:
        outs0 = _place_all(pairs)
        assert {o.round for o in outs0.values()} == {0}
        # Node a's confirmation barrier raises StoreError once.
        real_join = nodes[0][1].barrier_join
        state = {"failed": False}

        def flaky_join(name, *a, **kw):
            if "fastbar/" in name and not state["failed"]:
                state["failed"] = True
                raise StoreError("injected: store lost mid-fast-path")
            return real_join(name, *a, **kw)

        monkeypatch.setattr(nodes[0][1], "barrier_join", flaky_join)
        nodes[0][0].request_restart("worker died")
        outs1 = _place_all(pairs, prev_round=0)
        assert state["failed"], "fast path never reached its barrier"
        # Both placed in the same (post-abandon) round via the full ladder.
        assert len({o.round for o in outs1.values()}) == 1
        assert {o.node_rank for o in outs1.values()} == {0, 1}
        assert not any(o.fast for o in outs1.values()), outs1
    finally:
        for r, s in nodes:
            r.stop_keepalive()
            s.close()


@pytest.mark.chaos
def test_chaos_reset_on_the_cas_still_places(kv_server):
    """Seeded connection resets across the fast path's store traffic (the CAS
    ride the store channel): the client's transparent retry or the ladder
    fallback must still place both nodes — never a wedge, never an error."""
    nodes = [make_rdzv(kv_server.port, n) for n in ("a", "b")]
    pairs = [("a", nodes[0][0]), ("b", nodes[1][0])]
    try:
        outs0 = _place_all(pairs)
        assert {o.round for o in outs0.values()} == {0}
        nodes[0][0].request_restart("worker died")
        # Resets at staggered call indices so the injection lands across the
        # dead-check / epoch-read / CAS sequence on both nodes' clients.
        chaos.install_plan(chaos.ChaosPlan.parse(
            "1234:store.send.reset@at=0+2+5"
        ))
        outs1 = _place_all(pairs, prev_round=0)
        assert len({o.round for o in outs1.values()}) == 1
        assert {o.node_rank for o in outs1.values()} == {0, 1}
    finally:
        chaos.clear_plan()
        for r, s in nodes:
            r.stop_keepalive()
            s.close()


def test_fast_path_disabled_by_setting(kv_server):
    nodes = [make_rdzv(kv_server.port, n, fast_path=False) for n in ("a", "b")]
    pairs = [("a", nodes[0][0]), ("b", nodes[1][0])]
    try:
        _place_all(pairs)
        nodes[0][0].request_restart("x")
        outs1 = _place_all(pairs, prev_round=0)
        assert {o.round for o in outs1.values()} == {1}
        assert not any(o.fast for o in outs1.values())
    finally:
        for r, s in nodes:
            r.stop_keepalive()
            s.close()
