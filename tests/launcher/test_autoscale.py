"""AutoscaleController + CostModel: the decision matrix, hysteresis, rescind
handling, audit events with predicted AND realized deltas, actuation routing
through the remediation engine, and the /autoscale status document."""

import json
import time

import pytest

from tpu_resiliency.launcher.autoscale import (
    ACTION_CHECKPOINT,
    ACTION_EXCLUDE,
    ACTION_EXPAND,
    ACTION_NOOP,
    ACTION_SHRINK,
    ACTION_SWAP,
    AutoscaleController,
    ControllerView,
    CostModel,
    Notice,
)
from tpu_resiliency.telemetry.policy import HealthDecision
from tpu_resiliency.telemetry.remediation import RemediationEngine
from tpu_resiliency.utils import events


@pytest.fixture
def seen():
    captured = []
    events.add_sink(captured.append)
    yield captured
    events.remove_sink(captured.append)


def view(
    now=100.0, world=4, target=4, stragglers=None, spares=0, notices=(),
    step_s=0.02, steps_since_ckpt=50,
):
    return ControllerView(
        now=now, world_size=world, target_world=target,
        stragglers=dict(stragglers or {}), spares=spares,
        notices=list(notices), step_s=step_s,
        steps_since_ckpt=steps_since_ckpt,
    )


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def controller(mode="advise", clock=None, **kw):
    kw.setdefault("cost_model", CostModel(horizon_s=10.0))
    kw.setdefault("rescind_grace_s", 5.0)
    kw.setdefault("dwell_s", 2.0)
    kw.setdefault("decision_cooldown_s", 30.0)
    ctl = AutoscaleController(
        mode=mode, now_fn=clock or FakeClock(), **kw
    )
    return ctl


# -- the cost model ----------------------------------------------------------


class TestCostModel:
    def test_swap_beats_noop_under_a_straggler(self):
        m = CostModel(horizon_s=10.0, warm_restart_s=0.05)
        v = view(stragglers={2: 0.4}, spares=1)
        assert m.estimate(ACTION_SWAP, v) == pytest.approx(
            0.6 * 10.0 - 0.05
        )
        assert m.estimate(ACTION_NOOP, v) == 0.0

    def test_exclude_prices_the_capacity_loss(self):
        m = CostModel(horizon_s=10.0, reshard_s=0.1)
        v = view(stragglers={2: 0.4}, spares=0, world=4)
        # slow_frac 0.6 minus 1/4 capacity loss, times horizon, minus reshard
        assert m.estimate(ACTION_EXCLUDE, v) == pytest.approx(
            (0.6 - 0.25) * 10.0 - 0.1
        )

    def test_checkpoint_prices_unbanked_progress(self):
        m = CostModel(horizon_s=10.0, ckpt_s=0.2, p_preempt=0.5)
        n = Notice(key="r1", rank=1, noticed_at=99.0)
        v = view(notices=[n], step_s=0.1, steps_since_ckpt=20)
        assert m.estimate(ACTION_CHECKPOINT, v) == pytest.approx(
            0.5 * 2.0 - 0.2
        )
        # No notice pending: a proactive save is pure cost.
        assert m.estimate(ACTION_CHECKPOINT, view()) < 0

    def test_shrink_and_expand_signs(self):
        m = CostModel(horizon_s=10.0, cold_restart_s=1.0,
                      preempt_block_s=4.0, reshard_s=0.1)
        n = Notice(key="r1", rank=1, noticed_at=90.0)
        assert m.estimate(ACTION_SHRINK, view(notices=[n])) > 0
        grow = m.estimate(ACTION_EXPAND, view(world=3, target=4, spares=1))
        assert grow == pytest.approx(10.0 / 4 - 0.1)
        assert m.estimate(ACTION_EXPAND, view(world=4, target=4)) < 0

    def test_unknown_action_raises(self):
        with pytest.raises(ValueError):
            CostModel().estimate("teleport", view())

    def test_note_outcome_refines_and_clamps(self):
        m = CostModel(ewma_alpha=1.0)
        m.note_outcome(ACTION_SWAP, predicted=10.0, realized=5.0)
        assert m.corrections[ACTION_SWAP] == pytest.approx(0.5)
        v = view(stragglers={1: 0.0}, spares=1)
        # The correction halves the optimistic straggler term.
        assert m.estimate(ACTION_SWAP, v) == pytest.approx(
            1.0 * m.horizon_s * 0.5 - m.warm_restart_s
        )
        m.note_outcome(ACTION_SWAP, predicted=1.0, realized=-100.0)
        assert m.corrections[ACTION_SWAP] >= 0.25  # clamped, never zero/negative

    def test_from_bench_reads_repo_artifacts(self, tmp_path):
        with open(tmp_path / "BENCH_restart.json", "w") as f:
            json.dump({
                "in_job": {"respawn_ms": 500.0, "detect_ms": 100.0},
                "in_job_warm_spares": {"respawn_ms": 30.0, "detect_ms": 10.0},
            }, f)
        with open(tmp_path / "BENCH_reshard.json", "w") as f:
            json.dump({"ranged_s": 0.25}, f)
        m = CostModel.from_bench(str(tmp_path))
        assert m.cold_restart_s == pytest.approx(0.6)
        assert m.warm_restart_s == pytest.approx(0.04)
        assert m.reshard_s == pytest.approx(0.25)
        # Missing artifacts: defaults survive.
        d = CostModel.from_bench(str(tmp_path / "nope"))
        assert d.cold_restart_s == CostModel().cold_restart_s

    def test_from_bench_prefers_phase_decomposition(self, tmp_path):
        """A refreshed bench file with a ``phases`` block reprices the shrink
        delta: plan+fetch beats the top-line ranged_s (which still charges
        the local assembly that now hides under the overlapped fetch)."""
        with open(tmp_path / "BENCH_reshard.json", "w") as f:
            json.dump({"ranged_s": 0.25}, f)
        old = CostModel.from_bench(str(tmp_path))
        assert old.reshard_s == pytest.approx(0.25)
        v = view()
        priced_old = old.estimate(ACTION_SHRINK, v)
        # Refresh the artifact with the phase decomposition.
        with open(tmp_path / "BENCH_reshard.json", "w") as f:
            json.dump(
                {"ranged_s": 0.25,
                 "phases": {"plan_s": 0.01, "fetch_s": 0.03}}, f
            )
        new = CostModel.from_bench(str(tmp_path))
        assert new.reshard_s == pytest.approx(0.04)
        priced_new = new.estimate(ACTION_SHRINK, v)
        # The repriced model strictly raises the shrink delta.
        assert priced_new > priced_old
        assert priced_new - priced_old == pytest.approx(0.25 - 0.04)
        # A malformed phases block degrades to the top-line number.
        with open(tmp_path / "BENCH_reshard.json", "w") as f:
            json.dump(
                {"ranged_s": 0.25, "phases": {"plan_s": "x"}}, f
            )
        assert CostModel.from_bench(
            str(tmp_path)
        ).reshard_s == pytest.approx(0.25)


# -- deciding ----------------------------------------------------------------


class TestDecide:
    def test_healthy_job_is_silent(self, seen):
        ctl = controller()
        assert ctl.tick() is None
        assert not [e for e in seen if e.kind == "autoscale_decision"]

    def test_straggler_with_spares_decides_swap(self, seen):
        ctl = controller(spare_capacity_fn=lambda: 2)
        ctl.note_health(HealthDecision(
            degraded=frozenset({2}), newly_degraded=frozenset({2}),
            recovered=frozenset(), flagged=frozenset({2}),
            scores={2: 0.3, 0: 1.0},
        ))
        d = ctl.tick()
        assert d is not None and d.action == ACTION_SWAP
        assert d.victims == [2] and d.predicted_delta_s > 0
        assert d.outcome == "advised"  # advise mode never actuates
        evs = [e for e in seen if e.kind == "autoscale_decision"]
        assert len(evs) == 1
        assert evs[0].payload["predicted_delta_s"] == d.predicted_delta_s
        # Identical decision inside the cooldown is suppressed.
        assert ctl.tick() is None

    def test_straggler_without_spares_decides_exclude(self):
        ctl = controller(spare_capacity_fn=lambda: 0)
        ctl.note_world_size(4)
        ctl.note_health(HealthDecision(
            degraded=frozenset({1}), newly_degraded=frozenset({1}),
            recovered=frozenset(), flagged=frozenset({1}),
            scores={1: 0.1},
        ))
        d = ctl.tick()
        assert d is not None and d.action == ACTION_EXCLUDE

    def test_fresh_notice_checkpoints_then_shrinks_after_grace(self, seen):
        clock = FakeClock(100.0)
        ctl = controller(clock=clock)
        ctl.note_world_size(4)
        # Some unbanked progress so the proactive checkpoint prices > 0.
        t = 100.0
        for i in range(30):
            ctl.observe({"kind": "iteration_start", "iteration": i,
                         "ts": t + i * 0.02, "pid": 7})
        ctl.note_preemption("r3", rank=3)
        d1 = ctl.tick()
        assert d1 is not None and d1.action == ACTION_CHECKPOINT
        clock.t += ctl.rescind_grace_s + 0.1  # the rescind window closes
        d2 = ctl.tick()
        assert d2 is not None and d2.action == ACTION_SHRINK
        assert d2.victims == [3]

    def test_rescind_cancels_the_shrink(self):
        clock = FakeClock(100.0)
        ctl = controller(clock=clock)
        ctl.note_world_size(4)
        ctl.note_preemption("r3", rank=3)
        ctl.note_rescind("r3")
        clock.t += ctl.rescind_grace_s + 1.0
        assert ctl.tick() is None  # no notice left: nothing to shrink for
        assert ctl.status()["rescinds"] == 1

    def test_rescind_event_clears_the_notice(self):
        ctl = controller()
        ctl.observe({"kind": "preemption_sync_point", "ts": 100.0,
                     "rank": 2, "step": 9})
        assert len(ctl.status()["pending_notices"]) == 1
        ctl.observe({"kind": "preemption_rescinded", "ts": 101.0,
                     "rank": 2, "step": 14})
        assert not ctl.status()["pending_notices"]
        assert ctl.status()["rescinds"] == 1

    def test_expand_needs_dwell_and_capacity(self):
        clock = FakeClock(100.0)
        ctl = controller(clock=clock, spare_capacity_fn=lambda: 1)
        ctl.note_world_size(4)
        ctl.observe({"kind": "world_resized", "ts": 100.0, "to_world": 3,
                     "direction": "shrink"})
        ctl._last_resize_ts = clock.t  # a shrink just happened
        assert ctl.tick() is None  # inside the dwell: no flapping
        clock.t += ctl.dwell_s + 0.1
        d = ctl.tick()
        assert d is not None and d.action == ACTION_EXPAND


# -- acting ------------------------------------------------------------------


class TestAct:
    def test_swap_routes_through_remediation_engine(self, seen):
        restarts = []
        engine = RemediationEngine(
            spare_capacity_fn=lambda: 1,
            publish_degraded_fn=lambda d: None,
            request_restart_fn=restarts.append,
        )
        ctl = controller(mode="act", remediation=engine,
                         spare_capacity_fn=lambda: 1)
        ctl.note_health(HealthDecision(
            degraded=frozenset({2}), newly_degraded=frozenset({2}),
            recovered=frozenset(), flagged=frozenset({2}), scores={2: 0.2},
        ))
        d = ctl.tick()
        assert d.action == ACTION_SWAP and d.outcome == "ok"
        assert restarts, "swap never reached the restart actuator"
        # The engine audited it with its own remediation_action event.
        audits = [e for e in seen if e.kind == "remediation_action"]
        assert audits and audits[0].payload["action"] == "spare_swap"

    def test_ok_swap_clears_victims_no_exclude_cascade(self):
        """REGRESSION (found driving the real launcher in act mode): after a
        successful swap the stale straggler view fired a spurious exclude for
        the same victims on the next tick. An OK swap clears its victims
        optimistically; the next degraded_set re-establishes the truth."""
        spares = [1]
        engine = RemediationEngine(
            spare_capacity_fn=lambda: spares[0],
            publish_degraded_fn=lambda d: None,
            request_restart_fn=lambda r: spares.__setitem__(0, 0),
        )
        ctl = controller(mode="act", remediation=engine,
                         spare_capacity_fn=lambda: spares[0])
        ctl.note_health(HealthDecision(
            degraded=frozenset({2}), newly_degraded=frozenset({2}),
            recovered=frozenset(), flagged=frozenset({2}), scores={2: 0.2},
        ))
        d = ctl.tick()
        assert d.action == ACTION_SWAP and d.outcome == "ok"
        assert ctl.status()["stragglers"] == {}
        assert ctl.tick() is None  # no exclude cascade for the same ranks

    def test_engine_dry_run_audits_skip(self):
        engine = RemediationEngine(
            spare_capacity_fn=lambda: 1,
            publish_degraded_fn=lambda d: None,
            request_restart_fn=lambda r: None,
            dry_run=True,
        )
        ctl = controller(mode="act", remediation=engine,
                         spare_capacity_fn=lambda: 1)
        ctl.note_health(HealthDecision(
            degraded=frozenset({2}), newly_degraded=frozenset({2}),
            recovered=frozenset(), flagged=frozenset({2}), scores={2: 0.2},
        ))
        assert ctl.tick().outcome == "skipped"

    def test_shrink_uses_injected_actuator_and_consumes_notice(self):
        clock = FakeClock(100.0)
        shrunk = []
        ctl = controller(
            mode="act", clock=clock,
            shrink_fn=lambda victims, reason: shrunk.append(victims),
        )
        ctl.note_world_size(4)
        ctl.note_preemption("r1", rank=1, deadline=clock.t + 0.5)
        d = ctl.tick()
        assert d.action == ACTION_SHRINK and d.outcome == "ok"
        assert shrunk == [[1]]
        assert not ctl.status()["pending_notices"]  # consumed by the shrink

    def test_actuator_failure_is_audited_not_raised(self):
        clock = FakeClock(100.0)
        ctl = controller(
            mode="act", clock=clock,
            shrink_fn=lambda v, r: (_ for _ in ()).throw(RuntimeError("no")),
        )
        ctl.note_world_size(2)
        ctl.note_preemption("r1", rank=1, deadline=clock.t)
        assert ctl.tick().outcome == "failed"


# -- realized outcomes -------------------------------------------------------


class TestOutcomes:
    def test_every_decision_settles_with_a_realized_delta(self, seen):
        clock = FakeClock(100.0)
        ctl = controller(clock=clock, spare_capacity_fn=lambda: 1,
                         outcome_window_s=1.0)
        t = 100.0
        for i in range(10):
            ctl.observe({"kind": "iteration_start", "iteration": i,
                         "ts": t + i * 0.1, "pid": 7})
        ctl.note_health(HealthDecision(
            degraded=frozenset({2}), newly_degraded=frozenset({2}),
            recovered=frozenset(), flagged=frozenset({2}), scores={2: 0.2},
        ))
        d = ctl.tick()
        assert d is not None and not d.settled
        # Training continues; the window elapses in event time.
        for i in range(10, 40):
            ctl.observe({"kind": "iteration_start", "iteration": i,
                         "ts": t + i * 0.1, "pid": 7})
        clock.t += 2.0
        ctl.tick()  # settlement pass
        assert d.settled and d.realized_delta_s is not None
        outs = [e for e in seen if e.kind == "autoscale_outcome"]
        assert len(outs) == 1
        p = outs[0].payload
        assert p["decision_id"] == d.decision_id
        assert p["predicted_delta_s"] == d.predicted_delta_s
        assert p["realized_delta_s"] == d.realized_delta_s
        assert ctl.model.outcomes[ACTION_SWAP][0] == 1  # fed back to the model

    def test_finalize_settles_pending_decisions(self, seen):
        ctl = controller(spare_capacity_fn=lambda: 1, outcome_window_s=999.0)
        ctl.note_health(HealthDecision(
            degraded=frozenset({2}), newly_degraded=frozenset({2}),
            recovered=frozenset(), flagged=frozenset({2}), scores={2: 0.2},
        ))
        d = ctl.tick()
        assert not d.settled
        ctl.finalize()
        assert d.settled and d.realized_delta_s is not None
        assert [e for e in seen if e.kind == "autoscale_outcome"]


# -- signals + status --------------------------------------------------------


class TestSignals:
    def test_degraded_set_event_feeds_stragglers(self):
        ctl = controller()
        ctl.observe({"kind": "degraded_set", "ts": 1.0,
                     "degraded": [1, 3], "newly": [3],
                     "scores": {"1": 0.5, "3": 0.2, "0": 1.0}})
        st = ctl.status()["stragglers"]
        assert st == {"1": 0.5, "3": 0.2}
        # Recovery clears them.
        ctl.observe({"kind": "degraded_set", "ts": 2.0, "degraded": [],
                     "recovered": [1, 3], "scores": {}})
        assert ctl.status()["stragglers"] == {}

    def test_world_and_spares_from_events(self):
        ctl = controller()
        ctl.observe({"kind": "rendezvous_round", "ts": 1.0, "round": 0,
                     "world_size": 8})
        ctl.observe({"kind": "warm_spare_pool", "ts": 1.5, "warm": 3,
                     "parked": 3, "size": 3})
        v = ctl.view()
        assert v.world_size == 8 and v.target_world == 8 and v.spares == 3

    def test_ckpt_saved_resets_unbanked_steps(self):
        ctl = controller()
        for i in range(5):
            ctl.observe({"kind": "iteration_start", "iteration": i,
                         "ts": 1.0 + i, "pid": 3})
        assert ctl.view().steps_since_ckpt == 4
        ctl.observe({"kind": "ckpt_saved", "ts": 7.0, "bytes": 10})
        assert ctl.view().steps_since_ckpt == 0

    def test_poll_tails_an_events_file(self, tmp_path, seen):
        ev = tmp_path / "ev.jsonl"
        with open(ev, "w") as f:
            f.write(json.dumps({"kind": "degraded_set", "ts": 1.0,
                                "degraded": [1], "scores": {"1": 0.2}}) + "\n")
        ctl = controller(events_file=str(ev), spare_capacity_fn=lambda: 1)
        d = ctl.poll()
        assert d is not None and d.action == ACTION_SWAP
        # Torn trailing line does not advance the offset.
        with open(ev, "a") as f:
            f.write('{"kind": "torn')
        off = ctl._offset
        ctl.poll()
        assert ctl._offset == off

    def test_status_document_shape(self):
        ctl = controller(spare_capacity_fn=lambda: 1)
        ctl.note_health(HealthDecision(
            degraded=frozenset({2}), newly_degraded=frozenset({2}),
            recovered=frozenset(), flagged=frozenset({2}), scores={2: 0.2},
        ))
        ctl.tick()
        ctl.finalize()
        doc = ctl.status()
        assert doc["schema"] == "tpu-autoscale-1"
        assert doc["mode"] == "advise"
        assert doc["decisions_total"] == 1
        d = doc["decisions"][0]
        assert d["action"] == ACTION_SWAP
        assert d["predicted_delta_s"] is not None
        assert d["realized_delta_s"] is not None
        assert doc["forecast"]["settled"] == 1
        assert "warm_restart_s" in doc["cost_model"]
        json.dumps(doc)  # must be strict-JSON serializable

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError):
            AutoscaleController(mode="auto")

    def test_thread_start_stop(self, tmp_path):
        ctl = controller(events_file=str(tmp_path / "ev.jsonl"),
                         clock=time.time)
        ctl.interval = 0.05
        ctl.start()
        time.sleep(0.15)
        ctl.stop()
        assert ctl._thread is None


# -- the SLO watchtower's early warning --------------------------------------


PAGE = {"rule": "step_anomaly", "severity": "page", "fire_ts": 99.0}


class TestAlertBias:
    def test_checkpoint_prices_page_alert_risk(self):
        m = CostModel(horizon_s=10.0, ckpt_s=0.2, p_alert_risk=0.35)
        v = view(step_s=0.1, steps_since_ckpt=20)
        # No notice, no alert: a proactive save is pure cost...
        assert m.estimate(ACTION_CHECKPOINT, v) == pytest.approx(-0.2)
        # ...a page-grade alert puts the unbanked progress at alert risk...
        v_alert = view(step_s=0.1, steps_since_ckpt=20)
        v_alert.active_alerts = [PAGE]
        assert m.estimate(ACTION_CHECKPOINT, v_alert) == pytest.approx(
            0.35 * 2.0 - 0.2
        )
        # ...and a real notice still outranks it (p_preempt, not p_alert_risk).
        n = Notice(key="r1", rank=1, noticed_at=99.0)
        v_both = view(notices=[n], step_s=0.1, steps_since_ckpt=20)
        v_both.active_alerts = [PAGE]
        assert m.estimate(ACTION_CHECKPOINT, v_both) == pytest.approx(
            m.p_preempt * 2.0 - 0.2
        )
        # Warn-grade alerts do not move the model.
        v_warn = view(step_s=0.1, steps_since_ckpt=20)
        v_warn.active_alerts = [{"rule": "r", "severity": "warn"}]
        assert m.estimate(ACTION_CHECKPOINT, v_warn) == pytest.approx(-0.2)

    def test_page_alert_decides_checkpoint_before_any_verdict(self, seen):
        """The acceptance story: a page-severity early warning (no straggler
        verdict, no notice) banks progress via an advised checkpoint."""
        firing = []
        ctl = controller(active_alerts_fn=lambda: firing)
        for i in range(30):  # 29 unbanked 0.1s steps
            ctl.observe({"kind": "iteration_start", "iteration": i,
                         "ts": 60.0 + 0.1 * i, "pid": 1})
        assert ctl.tick() is None  # healthy and silent without the alert
        firing.append(dict(PAGE))
        d = ctl.tick()
        assert d is not None and d.action == ACTION_CHECKPOINT
        assert "step_anomaly" in d.reason and d.predicted_delta_s > 0
        doc = ctl.status()
        assert doc["active_alerts"] == [
            {"rule": "step_anomaly", "severity": "page"}
        ]
        evs = [e for e in seen if e.kind == "autoscale_decision"]
        assert [e.payload["action"] for e in evs] == [ACTION_CHECKPOINT]

    def test_crashing_alerts_fn_never_hurts(self):
        def boom():
            raise RuntimeError("watchtower gone")

        ctl = controller(active_alerts_fn=boom)
        assert ctl.view().active_alerts == []
        assert ctl.tick() is None
        assert ctl.status()["active_alerts"] == []

    def test_view_without_alerts_fn_defaults_empty(self):
        assert controller().view().active_alerts == []
        assert view().page_alerts() == []
