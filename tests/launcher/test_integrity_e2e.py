"""Acceptance: under a seeded disk-fault plan corrupting one rank's newest
shard at write time, a collective ``load()`` recovers a byte-identical tree
via peer retrieve without raising; with the replica also corrupted, all ranks
agree on and load the same older iteration. Both runs show
``ckpt_quarantined`` events and ``tpu_ckpt_integrity_failures_total`` in the
aggregated metrics, and the injection schedule reproduces from the seed.

Drives ``scripts/chaos_soak.py``'s disk scenario — the same harness operators
run by hand — rather than re-implementing it (the scenario itself asserts
recovery correctness and metric visibility; divergence raises)."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import chaos_soak  # noqa: E402

pytestmark = pytest.mark.chaos


def test_disk_fault_recovers_via_peer_and_reproduces():
    s1 = chaos_soak.scenario_disk(seed=77)
    s2 = chaos_soak.scenario_disk(seed=77)
    assert s1 == s2, "same-seed disk runs diverged in injection schedule"
    assert any(k == "bitflip" for _, _, k, _ in s1)
    assert all(ch == "disk" and op == "write" for ch, op, _, _ in s1)


def test_disk_fault_with_corrupt_replica_falls_back_groupwide():
    s1 = chaos_soak.scenario_disk(seed=77, fallback=True)
    s2 = chaos_soak.scenario_disk(seed=77, fallback=True)
    assert s1 == s2, "same-seed fallback runs diverged in injection schedule"
    # Both copies' write paths were hit (two distinct per-file index-0 flips).
    assert [i for _, _, _, i in s1].count(0) >= 2


def test_different_seeds_still_converge():
    """The recovery contract is seed-independent: any bitflip placement must
    be absorbed by the ladder."""
    chaos_soak.scenario_disk(seed=123456)
