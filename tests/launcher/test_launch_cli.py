"""CLI plumbing: ft-param extraction, script-arg boundaries, endpoint locality."""

from tpu_resiliency.launcher.launch import (
    endpoint_is_local,
    extract_ft_params,
    parse_nnodes,
    split_at_script,
)


def test_parse_nnodes():
    assert parse_nnodes("3") == (3, 3)
    assert parse_nnodes("2:5") == (2, 5)


def test_split_at_script():
    head, tail = split_at_script(
        ["--nproc-per-node", "2", "--no-ft-monitors", "train.py", "--lr", "3e-4"]
    )
    assert head == ["--nproc-per-node", "2", "--no-ft-monitors"]
    assert tail == ["train.py", "--lr", "3e-4"]


def test_ft_params_extracted_only_before_script():
    argv = [
        "--nproc-per-node", "1",
        "--ft-param-safety_factor", "2.5",
        "--ft-param-log_level=DEBUG",
        "train.py",
        "--ft-param-foo", "belongs-to-script",
    ]
    rest, ns = extract_ft_params(argv)
    assert rest == ["--nproc-per-node", "1", "train.py", "--ft-param-foo", "belongs-to-script"]
    assert ns.ft_param_safety_factor == "2.5"
    assert ns.ft_param_log_level == "DEBUG"
    assert not hasattr(ns, "ft_param_foo")


def test_endpoint_is_local():
    assert endpoint_is_local("127.0.0.1")
    assert endpoint_is_local("localhost")
    assert endpoint_is_local("")
    import socket

    assert endpoint_is_local(socket.gethostname())
    assert not endpoint_is_local("some-other-host.invalid")
