"""CLI plumbing: ft-param extraction, script-arg boundaries, endpoint locality."""

import os

from tpu_resiliency.launcher.launch import (
    endpoint_is_local,
    extract_ft_params,
    parse_nnodes,
    split_at_script,
)


def test_parse_nnodes():
    assert parse_nnodes("3") == (3, 3)
    assert parse_nnodes("2:5") == (2, 5)


def test_split_at_script():
    head, tail = split_at_script(
        ["--nproc-per-node", "2", "--no-ft-monitors", "train.py", "--lr", "3e-4"]
    )
    assert head == ["--nproc-per-node", "2", "--no-ft-monitors"]
    assert tail == ["train.py", "--lr", "3e-4"]


def test_ft_params_extracted_only_before_script():
    argv = [
        "--nproc-per-node", "1",
        "--ft-param-safety_factor", "2.5",
        "--ft-param-log_level=DEBUG",
        "train.py",
        "--ft-param-foo", "belongs-to-script",
    ]
    rest, ns = extract_ft_params(argv)
    assert rest == ["--nproc-per-node", "1", "train.py", "--ft-param-foo", "belongs-to-script"]
    assert ns.ft_param_safety_factor == "2.5"
    assert ns.ft_param_log_level == "DEBUG"
    assert not hasattr(ns, "ft_param_foo")


def test_endpoint_is_local():
    assert endpoint_is_local("127.0.0.1")
    assert endpoint_is_local("localhost")
    assert endpoint_is_local("")
    import socket

    assert endpoint_is_local(socket.gethostname())
    assert not endpoint_is_local("some-other-host.invalid")


def test_standalone_module_run(tmp_path):
    """--standalone --module: ephemeral private store, one node, python -m worker
    (reference --standalone/--module)."""
    import subprocess
    import sys
    import textwrap

    pkg = tmp_path / "trainmod.py"
    pkg.write_text(
        textwrap.dedent(
            f"""
            import os
            with open(r"{tmp_path}/mod_out.txt", "w") as f:
                f.write(os.environ["WORLD_SIZE"] + ":" + __name__)
            """
        )
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(tmp_path) + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--module", "--nproc-per-node", "1",
         "--no-ft-monitors", "--rdzv-last-call", "0.2",
         "--run-dir", str(tmp_path / "run"), "trainmod"],
        capture_output=True, text=True, timeout=120, env=env, cwd=str(tmp_path),
    )
    assert r.returncode == 0, r.stderr
    # Ran as a module: __name__ is __main__ under -m.
    assert (tmp_path / "mod_out.txt").read_text() == "1:__main__"


def test_module_excludes_no_python(tmp_path):
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--module", "--no-python", "x"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 2
    assert "mutually exclusive" in r.stderr


def test_rdzv_id_isolates_jobs_on_shared_store(tmp_path):
    """Two concurrent single-node jobs share one store endpoint but different
    --rdzv-id: neither sees the other's rendezvous (reference --rdzv-id)."""
    import socket
    import subprocess
    import sys
    import textwrap

    from tpu_resiliency.platform.store import KVServer

    # Externally hosted store (python -m tpu_resiliency.platform.store in prod):
    # it outlives both jobs, which a job-hosted store does not.
    server = KVServer(host="127.0.0.1", port=0)
    port = server.port
    script = tmp_path / "job.py"
    script.write_text(
        textwrap.dedent(
            f"""
            import os, sys, time
            time.sleep(1.0)  # overlap the two jobs
            with open(r"{tmp_path}/job_" + sys.argv[1] + ".txt", "w") as f:
                f.write(os.environ["WORLD_SIZE"])
            """
        )
    )
    env = dict(os.environ)
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tpu_resiliency.launcher.launch",
             "--nproc-per-node", "1", "--rdzv-endpoint", f"127.0.0.1:{port}",
             "--rdzv-id", name, "--no-ft-monitors", "--rdzv-last-call", "0.2",
             "--run-dir", str(tmp_path / f"run_{name}"),
             str(script), name],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(tmp_path),
        )
        for name in ("jobA", "jobB")
    ]
    try:
        for name, p in zip(("jobA", "jobB"), procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"{name}:\n{err}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.close()
    # Each job formed its OWN single-node world (no cross-job rendezvous merge).
    assert (tmp_path / "job_jobA.txt").read_text() == "1"
    assert (tmp_path / "job_jobB.txt").read_text() == "1"


def test_standalone_conflicts_with_explicit_rdzv():
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--rdzv-endpoint", "host0:29511", "x.py"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 2
    assert "--standalone conflicts" in r.stderr


def test_standalone_accepts_equivalent_nnodes_rejects_typed_endpoint():
    """Explicitness, not literal values, drives the --standalone conflict:
    `--nnodes 1:1` means one node (accepted); typing even the DEFAULT endpoint
    conflicts (it would be silently replaced by the ephemeral store)."""
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--rdzv-endpoint", "127.0.0.1:29511", "x.py"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 2 and "--standalone conflicts" in r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--nnodes", "2", "x.py"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 2 and "single node" in r.stderr
    # --nnodes 1:1 is consistent with --standalone: the job runs (worker exits 0).
    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--nnodes", "1:1", "--max-restarts", "0",
         "-m", "platform"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]


def test_malformed_nnodes_clean_error():
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--nnodes", "2x", "x.py"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 2
    assert "invalid --nnodes" in r.stderr


def test_live_store_on_endpoint_joins_without_bind_stall():
    """A second agent on a busy shared endpoint must connect as a client
    immediately (handshake probe), not wait out the 8 s EADDRINUSE window."""
    import time

    from tpu_resiliency.launcher.launch import host_or_connect_store
    from tpu_resiliency.platform.store import KVServer, store_answers

    server = KVServer(host="127.0.0.1", port=0)
    try:
        assert store_answers("127.0.0.1", server.port)
        t0 = time.monotonic()
        store, second_server, host, port = host_or_connect_store(
            f"127.0.0.1:{server.port}"
        )
        elapsed = time.monotonic() - t0
        assert second_server is None and port == server.port
        assert elapsed < 4.0, f"client join stalled {elapsed:.1f}s"
        store.set("k", 1)
        assert store.get("k", timeout=5.0) == 1
        store.close()
    finally:
        server.close()
    assert not store_answers("127.0.0.1", server.port)


def test_standalone_store_server_entry():
    """`python -m tpu_resiliency.platform.store HOST:0`: serves, answers a
    client, exits 0 on SIGTERM — the external store for multi-job endpoints."""
    import signal
    import subprocess
    import sys
    import time

    import threading

    p = subprocess.Popen(
        [sys.executable, "-m", "tpu_resiliency.platform.store", "127.0.0.1:0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    lines: list = []
    reader = threading.Thread(
        target=lambda: lines.extend(p.stdout), daemon=True
    )
    reader.start()  # never block the test thread on the pipe
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any("store serving on" in ln for ln in lines) or p.poll() is not None:
                break
            time.sleep(0.1)
        if p.poll() is not None:
            reader.join(2.0)  # drain the crash traceback before formatting
        line = next((ln for ln in lines if "store serving on" in ln), "")
        assert line, (
            f"server never announced (rc={p.poll()}):\n{''.join(lines)[-2000:]}"
        )
        port = int(line.rsplit(":", 1)[1])
        from tpu_resiliency.platform.store import CoordStore

        c = CoordStore("127.0.0.1", port, timeout=10.0)
        c.set("k", 42)
        assert c.get("k", timeout=5.0) == 42
        c.close()
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=15) == 0
    finally:
        if p.poll() is None:
            p.kill()
