"""Rendezvous state-machine tests: joins, spares, restart epochs, dead-node pruning."""

import threading
import time

import pytest

from tpu_resiliency.exceptions import FaultToleranceError
from tpu_resiliency.launcher.rendezvous import RendezvousSettings, StoreRendezvous
from tpu_resiliency.platform import treecomm
from tpu_resiliency.platform.store import CoordStore
from tpu_resiliency.utils import events as tpu_events


def make_rdzv(port, node_id, **kw):
    defaults = dict(
        min_nodes=1,
        max_nodes=1,
        join_timeout=20.0,
        last_call_timeout=0.3,
        keep_alive_interval=0.1,
        keep_alive_timeout=1.0,
        poll_interval=0.05,
    )
    defaults.update(kw)
    store = CoordStore("127.0.0.1", port, prefix="rdzv/")
    return StoreRendezvous(store, node_id, RendezvousSettings(**defaults)), store


def test_single_node(kv_server):
    rdzv, store = make_rdzv(kv_server.port, "n0")
    out = rdzv.next_round()
    assert out.round == 0 and out.node_rank == 0 and out.active == ["n0"]
    rdzv.stop_keepalive()
    store.close()


def test_multi_node_with_spare(kv_server):
    """3 joiners, max 2: first two by join order become active, third is a spare."""
    outs = {}

    def join(nid):
        rdzv, store = make_rdzv(kv_server.port, nid, min_nodes=2, max_nodes=2)
        outs[nid] = rdzv.next_round()
        rdzv.stop_keepalive()
        store.close()

    threads = [threading.Thread(target=join, args=(f"n{i}",)) for i in range(3)]
    for t in threads:
        t.start()
        time.sleep(0.05)  # deterministic join order
    for t in threads:
        t.join(20.0)
    assert len(outs) == 3
    rounds = {o.round for o in outs.values()}
    assert rounds == {0}
    actives = [nid for nid, o in outs.items() if not o.is_spare]
    spares = [nid for nid, o in outs.items() if o.is_spare]
    assert len(actives) == 2 and len(spares) == 1
    ranks = sorted(outs[nid].node_rank for nid in actives)
    assert ranks == [0, 1]


def test_restart_round_includes_former_spare(kv_server):
    """After a restart request, the next round re-ranks everyone — a former spare
    can be promoted when a former active departs."""
    r0, s0 = make_rdzv(kv_server.port, "a", min_nodes=2, max_nodes=2)
    r1, s1 = make_rdzv(kv_server.port, "b", min_nodes=2, max_nodes=2)
    r2, s2 = make_rdzv(kv_server.port, "c", min_nodes=2, max_nodes=2)
    outs = {}
    ts = []
    for nid, r in (("a", r0), ("b", r1), ("c", r2)):
        t = threading.Thread(target=lambda nid=nid, r=r: outs.update({nid: r.next_round()}))
        t.start()
        ts.append(t)
        time.sleep(0.05)
    for t in ts:
        t.join(20.0)
    assert outs["c"].is_spare
    round0 = outs["c"].round
    # Node "a" leaves for good; "b" requests a restart (as an agent would on a
    # worker failure); b and c re-rendezvous.
    r0.leave()
    s0.close()
    r1.request_restart("test")
    outs2 = {}
    ts = [
        threading.Thread(target=lambda nid=nid, r=r: outs2.update({nid: r.next_round(round0)}))
        for nid, r in (("b", r1), ("c", r2))
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(20.0)
    assert not outs2["b"].is_spare and not outs2["c"].is_spare
    assert outs2["b"].round > round0
    assert sorted([outs2["b"].node_rank, outs2["c"].node_rank]) == [0, 1]
    for r in (r1, r2):
        r.stop_keepalive()
    for s in (s1, s2):
        s.close()


def test_dead_node_pruned_from_open_round(kv_server):
    """A joiner that dies before the round closes must not block it forever: the
    leader prunes keep-alive-stale participants."""
    # Dead node joins the open round but never keeps alive again.
    r_dead, s_dead = make_rdzv(kv_server.port, "dead", min_nodes=2, max_nodes=3)
    s_dead_view = s_dead  # join state manually: register participant + one ka touch
    st = s_dead_view.try_get("state")
    assert st is None
    s_dead_view.set(
        "state",
        {
            "round": 0,
            "status": "open",
            "seq": 1,
            "participants": {"dead": 0},
            "waiting": {},
            "active": [],
            "spares": [],
        },
    )
    s_dead_view.touch("ka/dead")
    time.sleep(1.2)  # let the dead node's keep-alive go stale
    outs = {}

    def join(nid):
        rdzv, store = make_rdzv(kv_server.port, nid, min_nodes=2, max_nodes=3)
        outs[nid] = rdzv.next_round()
        rdzv.stop_keepalive()
        store.close()

    ts = [threading.Thread(target=join, args=(f"n{i}",)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(20.0)
    assert len(outs) == 2
    for o in outs.values():
        assert not o.is_spare
        assert set(o.active) == {"n0", "n1"}  # the dead joiner was pruned
    s_dead.close()


def test_join_timeout(kv_server):
    rdzv, store = make_rdzv(kv_server.port, "lonely", min_nodes=2, max_nodes=2, join_timeout=1.0)
    with pytest.raises(FaultToleranceError):
        rdzv.next_round()
    rdzv.stop_keepalive()
    store.close()


def test_signals_roundtrip(kv_server):
    rdzv, store = make_rdzv(kv_server.port, "n0")
    assert rdzv.restart_epoch() == 0
    rdzv.request_restart("why not")
    assert rdzv.restart_epoch() == 1
    assert rdzv.shutdown_reason() is None
    rdzv.request_shutdown("done testing")
    assert "done testing" in rdzv.shutdown_reason()
    rdzv.mark_done(4)
    assert rdzv.done_nodes(4) == {"n0"}
    rdzv.set_health(True)
    time.sleep(0.15)
    rdzv.store.touch("ka/n0")
    assert "n0" in rdzv.healthy_live_nodes()
    rdzv.set_health(False, "broke")
    assert "n0" not in rdzv.healthy_live_nodes()
    rdzv.stop_keepalive()
    store.close()


def test_scattered_join_ladder_above_tree_floor(kv_server, monkeypatch):
    """Worlds at/above the tree floor join via scattered per-node keys that
    the leader folds in batches — not per-joiner CAS on the one state key.
    Same outcome contract as the flat ladder (unique consecutive ranks, one
    round), plus: a fold event fires and the round's scratch join keys are
    GC'd at close."""
    monkeypatch.setenv(treecomm.TREE_MIN_ENV, "3")  # force the tree shape at world 4
    seen = []
    tpu_events.add_sink(seen.append)
    outs = {}

    def join(nid):
        rdzv, store = make_rdzv(kv_server.port, nid, min_nodes=4, max_nodes=4)
        try:
            outs[nid] = rdzv.next_round()
        finally:
            rdzv.stop_keepalive()
            store.close()

    try:
        threads = [threading.Thread(target=join, args=(f"n{i}",)) for i in range(4)]
        for t in threads:
            t.start()
            time.sleep(0.05)
        for t in threads:
            t.join(20.0)
        assert len(outs) == 4
        assert {o.round for o in outs.values()} == {0}
        assert not any(o.is_spare for o in outs.values())
        assert sorted(o.node_rank for o in outs.values()) == [0, 1, 2, 3]
        folded = [e for e in seen if e.kind == "rendezvous_join_folded"]
        assert folded, "no fold event — joins went through the flat CAS path"
        assert sum(e.payload["folded"] for e in folded) == 3  # opener self-seeds
    finally:
        tpu_events.remove_sink(seen.append)
    # Scratch keys for the closed round were cleared by the leader.
    gc_view = CoordStore("127.0.0.1", kv_server.port, prefix="rdzv/")
    try:
        assert gc_view.prefix_get("join/0/") == {}
    finally:
        gc_view.close()


def test_small_world_keeps_flat_join(kv_server, monkeypatch):
    """Below the tree floor the ladder must stay byte-identical to the
    pre-tree shape: no scattered keys, no fold events."""
    monkeypatch.setenv(treecomm.TREE_MIN_ENV, "17")
    seen = []
    tpu_events.add_sink(seen.append)
    outs = {}

    def join(nid):
        rdzv, store = make_rdzv(kv_server.port, nid, min_nodes=2, max_nodes=2)
        try:
            outs[nid] = rdzv.next_round()
        finally:
            rdzv.stop_keepalive()
            store.close()

    try:
        threads = [threading.Thread(target=join, args=(f"n{i}",)) for i in range(2)]
        for t in threads:
            t.start()
            time.sleep(0.05)
        for t in threads:
            t.join(20.0)
        assert sorted(o.node_rank for o in outs.values()) == [0, 1]
        assert not [e for e in seen if e.kind == "rendezvous_join_folded"]
    finally:
        tpu_events.remove_sink(seen.append)


def test_round_close_detection_is_event_driven(kv_server):
    """A follower must learn of the leader's round close via the store's
    wait_changed notification, not at its next poll tick: with a deliberately
    huge poll interval, both nodes still place within a couple of seconds."""
    outs = {}

    def join(nid):
        rdzv, store = make_rdzv(
            kv_server.port, nid, min_nodes=2, max_nodes=2, poll_interval=30.0,
            join_timeout=60.0,
        )
        try:
            outs[nid] = rdzv.next_round()
        finally:
            rdzv.stop_keepalive()
            store.close()

    t0 = time.monotonic()
    threads = [threading.Thread(target=join, args=(n,)) for n in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=45.0)
    elapsed = time.monotonic() - t0
    assert set(outs) == {"a", "b"}, outs
    assert {outs["a"].node_rank, outs["b"].node_rank} == {0, 1}
    assert elapsed < 10.0, (
        f"placement took {elapsed:.1f}s with poll_interval=30 — close "
        f"detection fell back to polling"
    )
