"""Acceptance: the launcher restart chain and a clique replication round both
converge under seeded network fault plans covering all three out-of-band
channels, and the injection schedule reproduces from the seed.

Drives ``scripts/chaos_soak.py``'s scenarios — the same harness operators run
by hand — rather than re-implementing them.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import chaos_soak  # noqa: E402

pytestmark = pytest.mark.chaos


def test_store_scenario_converges_and_reproduces():
    s1 = chaos_soak.scenario_store(seed=77)
    s2 = chaos_soak.scenario_store(seed=77)
    assert s1 == s2, "same-seed store runs diverged in injection schedule"
    kinds = {(op, k) for _, op, k, _ in s1}
    assert ("send", "reset") in kinds and ("send", "truncate") in kinds


def test_replication_scenario_converges_and_reproduces():
    s1 = chaos_soak.scenario_replication(seed=77)
    s2 = chaos_soak.scenario_replication(seed=77)
    assert s1 == s2, "same-seed replication runs diverged in injection schedule"
    kinds = {k for _, _, k, _ in s1}
    assert "reset" in kinds and "truncate" in kinds


def test_elastic_scenario_converges_and_reproduces():
    """The shrink-and-continue chain (seeded victim preemption → resharded
    resume → shrunken-layout save → re-expand): the (injection schedule,
    victim, per-rank byte split) tuple reproduces from the seed, and the
    byte-identity + strictly-fewer-peer-bytes assertions run inside the
    scenario."""
    e1 = chaos_soak.scenario_elastic(seed=77)
    e2 = chaos_soak.scenario_elastic(seed=77)
    assert e1 == e2, "same-seed elastic runs diverged"
    schedule, victim, splits = e1
    assert victim == 77 % 4
    directions = {d for _, d, _, _ in splits}
    assert directions == {"shrink", "grow"}
    # the victim's grow resume is pure peer fetch (its disk was wiped)
    victim_grow = [s for s in splits if s[0] == victim and s[1] == "grow"]
    assert victim_grow and victim_grow[0][2] == 0 and victim_grow[0][3] > 0


def test_launcher_restart_chain_under_chaos(tmp_path):
    """The real launcher + FT monitors: worker fails round 0, chaos hits the
    store and ipc channels (≥1 reset + ≥1 truncation each, per the events
    stream), and the chain still exits 0 with the worker recovered."""
    injected = chaos_soak.scenario_launcher(seed=77, workdir=str(tmp_path))
    assert injected[("store", "reset")] >= 1
    assert injected[("store", "truncate")] >= 1
    assert injected[("ipc", "reset")] >= 1
    assert injected[("ipc", "truncate")] >= 1


def test_mixed_scenario_converges_and_reproduces(tmp_path):
    """The multi-fault campaign (straggler + store/p2p resets + disk bitflip
    during an active save): the combined injection schedule reproduces from
    the seed and all three channels actually fired. The scenario asserts the
    incident/remediation acceptance surface internally (artifact chain, CLI
    exit 0, metric visibility)."""
    wd = str(tmp_path / "mixed")
    s1 = chaos_soak.scenario_mixed(seed=77, workdir=wd)
    s2 = chaos_soak.scenario_mixed(seed=77, workdir=wd)
    assert s1 == s2, "same-seed mixed runs diverged in injection schedule"
    channels = {c for c, _, _, _ in s1}
    assert channels == {"store", "p2p", "disk"}, channels
    # The smoke-leg contract: artifacts + events stream persist in workdir.
    assert os.path.exists(os.path.join(wd, "events.jsonl"))
    assert any(
        n.startswith("incident-") and n.endswith(".json")
        for n in os.listdir(os.path.join(wd, "incidents"))
    )


def test_autoscale_scenario_beats_baseline_and_reproduces(tmp_path):
    """ACCEPTANCE (autoscale PR): fluctuating capacity (notice + rescind +
    real preemption) + straggler + disk fault. scenario_autoscale internally
    runs the phase-priced controlled arm twice asserting identical (decision,
    action, victim) schedules, runs the serial-priced arm and the
    no-controller baseline, and asserts the strict goodput ordering
    phase-priced > serial-priced > baseline; here we additionally pin the
    decision sequence and check the smoke-leg file contract."""
    wd = str(tmp_path / "autoscale")
    schedule, victims, disk, ratios = chaos_soak.scenario_autoscale(
        seed=77, workdir=wd
    )
    assert [a for _, a, _ in schedule] == [
        "swap", "checkpoint", "shrink", "expand",
    ], schedule
    assert victims == (77 % 4, (77 // 4) % 4, (77 // 16) % 4)
    assert ratios[0] > ratios[1] > ratios[2], ratios
    assert disk, "the disk-fault leg never injected"
    # The smoke-leg contract: every arm's event stream persists for the
    # offline tpu-metrics-dump --goodput --baseline comparison.
    for name in ("controlled.jsonl", "controlled_serial_priced.jsonl",
                 "baseline.jsonl"):
        assert os.path.getsize(os.path.join(wd, name)) > 0


@pytest.mark.slow
def test_randomized_soak():
    """Long randomized soak: several random seeds through every scenario (the
    CLI asserts convergence + reproducibility internally)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--soak-runs", "4"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")},
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "chaos_soak: PASS" in r.stdout
