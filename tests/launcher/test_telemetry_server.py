"""TelemetryServer unit coverage: endpoint contract, merged view, port-file
handshake, incremental events tail, health semantics — no launcher needed."""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from tpu_resiliency.launcher.telemetry import PORT_FILE_NAME, TelemetryServer
from tpu_resiliency.utils import events
from tpu_resiliency.utils.metrics import MetricsRegistry


@pytest.fixture(autouse=True)
def clean_sinks():
    events.clear_sinks()
    old = os.environ.pop(events.EVENTS_FILE_ENV, None)
    yield
    events.clear_sinks()
    if old is not None:
        os.environ[events.EVENTS_FILE_ENV] = old


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode(), r.headers.get("Content-Type", "")


@pytest.fixture
def server(tmp_path):
    srv = TelemetryServer(
        port=0,
        port_file=str(tmp_path / "run" / PORT_FILE_NAME),
        events_file=str(tmp_path / "ev.jsonl"),
    )
    srv.start()
    yield srv, tmp_path
    srv.stop()


def test_port_file_handshake(server):
    srv, tmp_path = server
    port_file = tmp_path / "run" / PORT_FILE_NAME
    assert int(port_file.read_text().strip()) == srv.port
    srv.stop()
    assert not port_file.exists()  # handshake file is cleaned up


def test_metrics_endpoint_merges_pushed_snapshots(server):
    srv, _ = server
    # Two fake ranks' pushed snapshots + launcher-local registry.
    snaps = []
    for r in range(2):
        reg = MetricsRegistry()
        reg.counter("tpu_ckpt_saves_total", "saves").inc(3)
        snaps.append(reg.snapshot())
    srv.fetch_snapshots = lambda: snaps
    srv.registry.counter("tpu_ckpt_saves_total", "saves").inc(1)
    status, body, ctype = _get(srv.port, "/metrics")
    assert status == 200 and ctype.startswith("text/plain")
    assert "version=0.0.4" in ctype
    assert "tpu_ckpt_saves_total 7" in body  # 3 + 3 + 1: the summed view


def test_metrics_endpoint_survives_bad_snapshots(server):
    srv, _ = server
    srv.fetch_snapshots = lambda: [{"garbage": True}, None, 42]
    status, body, _ = _get(srv.port, "/metrics")
    assert status == 200  # unmergeable snapshots are skipped, not fatal


def test_goodput_endpoint_tails_events_incrementally(server):
    srv, tmp_path = server
    ev = tmp_path / "ev.jsonl"
    t0 = time.time()
    with open(ev, "w") as f:
        for i in range(3):
            f.write(json.dumps({
                "kind": "iteration_start", "iteration": i, "ts": t0 + i,
                "pid": 9, "rank": 0,
            }) + "\n")
    status, body, ctype = _get(srv.port, "/goodput")
    doc = json.loads(body)
    assert status == 200 and ctype.startswith("application/json")
    assert doc["schema"] == "tpu-goodput-1"
    assert doc["phases"]["train"] == pytest.approx(2.0)
    offset_after_first = srv._offset
    assert offset_after_first == ev.stat().st_size
    # Append more (plus a torn trailing line that must NOT advance offset).
    with open(ev, "a") as f:
        f.write(json.dumps({
            "kind": "iteration_start", "iteration": 3, "ts": t0 + 3,
            "pid": 9, "rank": 0,
        }) + "\n")
        f.write('{"kind": "torn')
    doc2 = json.loads(_get(srv.port, "/goodput")[1])
    assert doc2["phases"]["train"] == pytest.approx(3.0)
    assert srv._offset > offset_after_first
    assert srv._offset < ev.stat().st_size  # torn tail left for next refresh


def test_goodput_publish_lands_in_metrics_view(server):
    srv, tmp_path = server
    t0 = time.time()
    with open(tmp_path / "ev.jsonl", "w") as f:
        for i in range(2):
            f.write(json.dumps({
                "kind": "iteration_start", "iteration": i, "ts": t0 + i,
                "pid": 9, "rank": 0,
            }) + "\n")
    _get(srv.port, "/goodput")  # refresh publishes goodput_update
    _, body, _ = _get(srv.port, "/metrics")
    assert 'tpu_time_attributed_seconds_total{phase="train"}' in body
    assert "tpu_goodput_ratio 1" in body


def test_healthz_contract(server):
    srv, _ = server
    srv.health_ttl = 0.0  # cache off: this test swaps health_fn per scrape
    status, body, _ = _get(srv.port, "/healthz")
    assert status == 200 and json.loads(body) == {"healthy": True}
    srv.health_fn = lambda: {"healthy": False, "restarts_used": 9}
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(srv.port, "/healthz")
    assert ei.value.code == 503
    assert json.loads(ei.value.read())["restarts_used"] == 9
    # A crashing health_fn degrades to unhealthy, never to a 500.
    srv.health_fn = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(srv.port, "/healthz")
    assert ei.value.code == 503


def test_unknown_path_is_404_with_directory(server):
    srv, _ = server
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(srv.port, "/nope")
    assert ei.value.code == 404
    doc = json.loads(ei.value.read())
    assert set(doc["endpoints"]) == {
        "/metrics", "/metrics.json", "/goodput", "/healthz", "/hangz",
        "/autoscale", "/incidents", "/snapshot", "/storez", "/alerts",
    }


def test_metrics_json_is_the_mergeable_twin(server):
    """/metrics.json serves the merged registry as a snapshot document the
    fleet aggregator can MetricsRegistry.merge without parsing exposition."""
    srv, _ = server
    srv.registry.counter("tpu_ckpt_saves_total", "saves").inc(2)
    status, body, ctype = _get(srv.port, "/metrics.json")
    assert status == 200 and "json" in ctype
    doc = json.loads(body)
    merged = MetricsRegistry()
    merged.merge(doc, extra_labels={"job": "j"})
    assert merged.counter("tpu_ckpt_saves_total", "", job="j").value == 2


def test_incidents_endpoint_trims_artifacts(server, tmp_path):
    srv, _ = server
    # No incidents dir wired: an empty-but-valid feed.
    doc = json.loads(_get(srv.port, "/incidents")[1])
    assert doc["schema"] == "tpu-incidents-1" and doc["incidents"] == []
    inc_dir = tmp_path / "incidents"
    inc_dir.mkdir()
    art = {
        "schema": "tpu-incident-1", "id": "incident-5-1", "trigger": "hang",
        "outcome": "recovered", "ranks": [2], "opened_ts": 50.0,
        "closed_ts": 51.0, "fault_ts": 49.0,
        "slo": {"time_to_detect_s": 1.0},
        "events": [{}] * 7, "chain": [{}] * 3, "flight": {"r0": []},
        "census": {"big": "blob"},
    }
    (inc_dir / "incident-5-1.json").write_text(json.dumps(art))
    (inc_dir / "incident-9-torn.json").write_text('{"schema": "tpu-inc')
    (inc_dir / "flight-0-1.jsonl").write_text("not an artifact\n")
    srv.incidents_dir = str(inc_dir)
    doc = json.loads(_get(srv.port, "/incidents")[1])
    assert len(doc["incidents"]) == 1
    row = doc["incidents"][0]
    assert row["id"] == "incident-5-1" and row["trigger"] == "hang"
    # Heavy forensics trimmed to counts — the fleet feed stays light.
    assert row["events"] == 7 and row["chain"] == 3 and row["flight_dumps"] == 1
    assert "census" not in row


def test_snapshot_consolidates_one_scrape(server):
    """/snapshot: metrics + goodput + health (+hangz/autoscale when wired)
    in one GET — the fleet scrape's one-round-trip contract."""
    srv, tmp_path = server
    t0 = time.time()
    with open(tmp_path / "ev.jsonl", "w") as f:
        for i in range(3):
            f.write(json.dumps({
                "kind": "iteration_start", "iteration": i, "ts": t0 + i,
                "pid": 9, "rank": 0,
            }) + "\n")
    srv.census_fn = lambda: {"suspects": [], "ranks": [], "barriers": []}
    srv.snapshot_ttl = 0.0  # this test swaps census_fn between scrapes
    status, body, _ = _get(srv.port, "/snapshot")
    assert status == 200
    doc = json.loads(body)
    assert doc["schema"] == "tpu-job-snapshot-1"
    assert doc["job"] == "default"
    assert doc["goodput"]["phases"]["train"] == pytest.approx(2.0)
    assert doc["health"]["healthy"] is True
    assert doc["hangz"]["schema"] == "tpu-hangz-1"
    assert isinstance(doc["metrics"]["metrics"], dict)
    assert doc["incidents"] == []
    # A crashing census degrades its section, never the snapshot.
    srv.census_fn = lambda: (_ for _ in ()).throw(RuntimeError("wedged"))
    doc = json.loads(_get(srv.port, "/snapshot")[1])
    assert "wedged" in doc["hangz"]["error"]
    assert doc["goodput"]["phases"]["train"] > 0


def test_snapshot_ttl_collapses_scrape_storm(server):
    """REGRESSION (fleet PR): /snapshot is the fleet-scrape hot path — N
    fleet pollers hitting one job must cost ONE document build per TTL, not
    N ledger refreshes + registry merges + serializations."""
    srv, _ = server
    srv.snapshot_ttl = 30.0
    calls = []
    srv.census_fn = lambda: (calls.append(1), {"suspects": []})[1]
    b1 = _get(srv.port, "/snapshot")[1]
    b2 = _get(srv.port, "/snapshot")[1]
    assert b1 == b2 and len(calls) == 1
    srv.snapshot_ttl = 0.0  # TTL off: every scrape recomputes
    _get(srv.port, "/snapshot")
    assert len(calls) == 2


def test_healthz_ttl_caches_and_serializes_scrapes(server):
    """REGRESSION (autoscale PR): /healthz used to recompute the health
    decision per scrape with no guard — a scrape storm stacked concurrent
    health_fn runs. Two concurrent scrapes against a slow health_fn must cost
    ONE evaluation; the cache expires after the TTL."""
    import threading

    srv, _ = server
    srv.health_ttl = 0.4
    calls = []

    def slow_health():
        calls.append(time.monotonic())
        time.sleep(0.3)
        return {"healthy": True, "n": len(calls)}

    srv.health_fn = slow_health
    results = []
    threads = [
        threading.Thread(
            target=lambda: results.append(_get(srv.port, "/healthz"))
        )
        for _ in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(calls) == 1, "concurrent scrapes stacked health_fn runs"
    assert len(results) == 2
    assert all(json.loads(body)["n"] == 1 for _, body, _ in results)
    # TTL expiry: the next scrape recomputes.
    time.sleep(0.45)
    _get(srv.port, "/healthz")
    assert len(calls) == 2


def test_healthz_ttl_caches_the_failure_doc_too(server):
    srv, _ = server
    srv.health_ttl = 30.0
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("boom")

    srv.health_fn = boom
    for _ in range(3):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.port, "/healthz")
        assert ei.value.code == 503
    assert len(calls) == 1


def test_autoscale_endpoint(server):
    srv, _ = server
    # Without a controller: a degraded-but-valid document, never an error.
    status, body, ctype = _get(srv.port, "/autoscale")
    doc = json.loads(body)
    assert status == 200 and "json" in ctype
    assert doc["schema"] == "tpu-autoscale-1" and doc["mode"] == "off"
    # With one wired: the controller's status document verbatim.
    srv.autoscale_fn = lambda: {
        "schema": "tpu-autoscale-1", "mode": "advise", "decisions_total": 2,
        "decisions": [{"action": "swap", "predicted_delta_s": 1.2,
                       "realized_delta_s": 0.9}],
    }
    doc = json.loads(_get(srv.port, "/autoscale")[1])
    assert doc["mode"] == "advise" and doc["decisions_total"] == 2
    # A crashing controller degrades the document, never the endpoint.
    srv.autoscale_fn = lambda: (_ for _ in ()).throw(RuntimeError("dead"))
    status, body, _ = _get(srv.port, "/autoscale")
    assert status == 200 and "dead" in json.loads(body)["error"]


def test_hangz_serves_census(server):
    srv, _ = server
    census = {
        "ranks": [{"rank": 1, "stuck_s": 12.0, "where": "section=step"}],
        "barriers": [{"name": "b", "missing": [1], "waiters": 1}],
        "suspects": [{"rank": 1, "score": 2.0, "reasons": ["missing from 'b'"]}],
    }
    srv.census_fn = lambda: census
    status, body, ctype = _get(srv.port, "/hangz")
    assert status == 200 and "json" in ctype
    doc = json.loads(body)
    assert doc["schema"] == "tpu-hangz-1"
    assert doc["suspects"][0]["rank"] == 1
    assert doc["ranks"][0]["where"] == "section=step"
    # A wedged census source degrades the document, never the endpoint —
    # /hangz exists precisely for wedged moments.
    srv.census_fn = lambda: (_ for _ in ()).throw(RuntimeError("store gone"))
    status, body, _ = _get(srv.port, "/hangz")
    assert status == 200
    assert "store gone" in json.loads(body)["error"]


def test_hangz_without_census_source(server):
    srv, _ = server
    status, body, _ = _get(srv.port, "/hangz")
    assert status == 200
    doc = json.loads(body)
    assert doc["schema"] == "tpu-hangz-1" and "error" in doc


def test_local_events_feed_the_served_registry(server):
    """The server attaches a MetricsSink: launcher-process events appear in
    /metrics without any file round-trip; stop() detaches it."""
    srv, _ = server
    events.record("launcher", "worker_failed", global_rank=0)
    _, body, _ = _get(srv.port, "/metrics")
    assert "tpu_worker_failures_total 1" in body
    srv.stop()
    events.record("launcher", "worker_failed", global_rank=0)
    assert srv.registry.counter("tpu_worker_failures_total").value == 1


def test_storez_serves_and_degrades(server):
    srv, _ = server
    # No source wired: degraded doc, 200.
    status, body, _ = _get(srv.port, "/storez")
    assert status == 200
    doc = json.loads(body)
    assert doc["schema"] == "tpu-storez-1" and "error" in doc
    # Wired: wraps the store_stats document with the job identity.
    srv.store_stats_fn = lambda: {
        "schema": "tpu-store-stats-1", "enabled": True,
        "ops": {"set": {"count": 16}}, "conns": 2, "parked": 0,
    }
    doc = json.loads(_get(srv.port, "/storez")[1])
    assert doc["schema"] == "tpu-storez-1"
    assert doc["enabled"] is True and doc["ops"]["set"]["count"] == 16
    assert doc["job"] == srv.job
    # A crashing collector degrades the document, never the endpoint.
    srv.store_stats_fn = lambda: (_ for _ in ()).throw(RuntimeError("loop gone"))
    status, body, _ = _get(srv.port, "/storez")
    assert status == 200
    assert "loop gone" in json.loads(body)["error"]


def test_snapshot_folds_storez(server):
    srv, _ = server
    srv.store_stats_fn = lambda: {"enabled": True, "ops": {}}
    doc = json.loads(_get(srv.port, "/snapshot")[1])
    assert doc["storez"]["schema"] == "tpu-storez-1"
    assert doc["storez"]["enabled"] is True
    # Without the source the section is simply absent (fleetd contract:
    # sections appear when wired, never as mandatory nulls).
    srv.store_stats_fn = None
    srv._snapshot_cache = None
    doc = json.loads(_get(srv.port, "/snapshot")[1])
    assert "storez" not in doc


def test_refresh_feeds_byteflow_ledger(server):
    srv, tmp_path = server
    with open(tmp_path / "ev.jsonl", "w") as f:
        f.write(json.dumps({
            "ts": time.time(), "kind": "p2p_transfer", "direction": "send",
            "bytes": 2048, "dst": 1, "tag": "repl/0", "pid": 9,
        }) + "\n")
    _get(srv.port, "/goodput")  # refresh publishes byteflow_update too
    _, body, _ = _get(srv.port, "/metrics")
    assert 'tpu_byteflow_bytes_total{direction="send",purpose="replicate"} 2048' in body
    assert "tpu_byteflow_accounted_ratio 1" in body


# -- /alerts: the SLO watchtower's endpoint ----------------------------------

def _make_watchtower(**kw):
    from tpu_resiliency.telemetry.watchtower import AlertRule, Watchtower

    hot = AlertRule(
        name="hot",
        check=lambda store, now, p: (
            "ratio low"
            if any(v < 0.5 for _, v in store.query("tpu_goodput_ratio"))
            else None
        ),
        severity="page",
    )
    return Watchtower([hot], **kw)


def test_alerts_endpoint_serves_and_degrades(server):
    srv, tmp_path = server
    # Without a watchtower: a degraded-but-valid document, never an error.
    status, body, ctype = _get(srv.port, "/alerts")
    doc = json.loads(body)
    assert status == 200 and "json" in ctype
    assert doc["schema"] == "tpu-alerts-1" and doc["job"] == srv.job
    assert "no watchtower wired" in doc["error"]
    # With one wired: the events tail feeds it and the rule fires.
    srv.watchtower = _make_watchtower()
    with open(tmp_path / "ev.jsonl", "w") as f:
        f.write(json.dumps({
            "kind": "goodput_update", "ts": 100.0, "ratio": 0.2, "pid": 9,
        }) + "\n")
        f.write(json.dumps({
            "kind": "goodput_update", "ts": 120.0, "ratio": 0.2, "pid": 9,
        }) + "\n")
    doc = json.loads(_get(srv.port, "/alerts")[1])
    assert doc["schema"] == "tpu-alerts-1"
    assert [r["name"] for r in doc["rules"]] == ["hot"]
    assert doc["rules"][0]["state"] == "firing"
    assert [a["rule"] for a in doc["active"]] == ["hot"]
    # A crashing engine degrades the document, never the endpoint.
    class Wedged:
        def observe(self, rec):
            pass

        def status(self):
            raise RuntimeError("engine wedged")

        def stop(self):
            pass

    srv.watchtower = Wedged()
    status, body, _ = _get(srv.port, "/alerts")
    assert status == 200
    assert "engine wedged" in json.loads(body)["error"]


def test_alerts_crashing_rule_degrades_to_error_row(server):
    from tpu_resiliency.telemetry.watchtower import AlertRule, Watchtower

    srv, tmp_path = server
    srv.watchtower = Watchtower([AlertRule(
        name="buggy",
        check=lambda store, now, p: (_ for _ in ()).throw(ValueError("nan")),
    )])
    with open(tmp_path / "ev.jsonl", "w") as f:
        for ts in (10.0, 20.0):
            f.write(json.dumps({
                "kind": "goodput_update", "ts": ts, "ratio": 1.0, "pid": 9,
            }) + "\n")
    status, body, _ = _get(srv.port, "/alerts")
    assert status == 200  # a rule bug is a row-level fact, not an outage
    doc = json.loads(body)
    row = doc["rules"][0]
    assert row["name"] == "buggy" and "nan" in row["error"]
    assert doc["active"] == []


def test_snapshot_storm_costs_one_watchtower_evaluation(server):
    """REGRESSION (watchtower PR): the fleet-scrape hot path must not
    multiply watchtower evaluations — N concurrent /snapshot scrapes inside
    one TTL serve the alerts section from ONE status() call (the snapshot
    body is computed inside the lock, then cached)."""
    import threading

    srv, _ = server
    srv.snapshot_ttl = 30.0
    tower = _make_watchtower()
    calls = []
    real_status = tower.status

    def counting_status():
        calls.append(1)
        time.sleep(0.2)  # widen the race window: overlap would double-count
        return real_status()

    tower.status = counting_status
    srv.watchtower = tower
    results = []
    threads = [
        threading.Thread(
            target=lambda: results.append(_get(srv.port, "/snapshot"))
        )
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(results) == 4
    bodies = {body for _, body, _ in results}
    assert len(bodies) == 1, "scrapes inside one TTL must share one document"
    assert len(calls) == 1, "scrape storm stacked watchtower evaluations"
    doc = json.loads(bodies.pop())
    assert doc["alerts"]["schema"] == "tpu-alerts-1"
