"""ACCEPTANCE: the goodput plane end to end under the real launcher.

One two-rank launch with the telemetry server enabled (`--telemetry-port 0`)
and one injected fault must prove, live:

- `/metrics` serves the **merged** multi-rank view — a counter incremented on
  both ranks reads as the summed value (rank-pushed snapshots through the
  store, folded by `MetricsRegistry.merge`);
- `/goodput` attribution phases sum to the observed wall clock (within 5 %)
  with `unattributed` below 20 %, and the injected checkpoint save + restart
  visibly move `ckpt_stall` and `restart`;
- `/healthz` answers 200 while the job is healthy;

and offline, that `tpu-metrics-dump --goodput` over the same events file
agrees with what the live endpoint reported.
"""

import json
import os
import subprocess
import sys
import textwrap
import time
import urllib.request

import pytest

PROBES_PER_RANK = 5
NPROC = 2

WORKER = textwrap.dedent(
    """
    import os, sys, time
    import numpy as np
    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
    from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
    from tpu_resiliency.utils.events import record

    stop, ckpt_root = sys.argv[1], sys.argv[2]
    round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
    rank = int(os.environ["RANK"])

    if round_no >= 1:
        # The merged-view probe: emitted by BOTH surviving ranks exactly
        # PROBES times each, so /metrics must show the exact sum.
        for _ in range(5):
            record("test", "goodput_probe")

    def step(i):
        record("inprocess", "iteration_start", iteration=i)
        time.sleep(0.05)

    for i in range(10):
        step(i)
    if round_no == 0:
        if rank == 0:
            sys.exit(3)  # the injected fault: round 1 is the restart
        # rank 1 idles out round 0 until the launcher stops it
        time.sleep(60)
        sys.exit(0)

    # Round 1: a real (sync) checkpoint save mid-stream...
    m = LocalCheckpointManager(ckpt_root, rank=rank)
    m.save(1, PyTreeStateDict({"w": np.arange(1 << 20, dtype=np.float32)}),
           is_async=False)
    m.close()
    # ...then keep stepping until the test has scraped everything it needs.
    i = 10
    deadline = time.time() + 120
    while not os.path.exists(stop) and time.time() < deadline:
        step(i)
        i += 1
    """
)


def _get_json(port, path, timeout=5):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return r.status, json.loads(r.read())


def _get_text(port, path, timeout=5):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return r.read().decode()


def _probe_total(prom_text: str) -> float:
    for line in prom_text.splitlines():
        if line.startswith('tpu_events_total{kind="goodput_probe"}'):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def test_goodput_plane_end_to_end(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    stop = tmp_path / "stop"
    events_file = tmp_path / "events.jsonl"
    run_dir = tmp_path / "run"
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "TPU_RESILIENCY_LOG_LEVEL": "INFO"})
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--nproc-per-node", str(NPROC), "--max-restarts", "2",
         "--no-ft-monitors", "--rdzv-last-call", "0.2",
         "--monitor-interval", "0.1", "--telemetry-port", "0",
         "--events-file", str(events_file), "--run-dir", str(run_dir),
         str(script), str(stop), str(tmp_path / "ckpt")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(tmp_path),
    )
    live = None
    try:
        # -- port-file handshake ------------------------------------------
        port_file = run_dir / "telemetry.port"
        deadline = time.time() + 60
        while not port_file.exists():
            assert proc.poll() is None, proc.communicate()[1][-3000:]
            assert time.time() < deadline, "telemetry.port never appeared"
            time.sleep(0.2)
        port = int(port_file.read_text().strip())

        # -- merged multi-rank /metrics -----------------------------------
        # Both round-1 ranks emit the probe exactly PROBES_PER_RANK times;
        # the merged view must converge on the exact sum.
        want = float(PROBES_PER_RANK * NPROC)
        deadline = time.time() + 120
        prom = ""
        while time.time() < deadline:
            assert proc.poll() is None, proc.communicate()[1][-3000:]
            try:
                prom = _get_text(port, "/metrics")
            except OSError:
                time.sleep(0.3)
                continue
            if _probe_total(prom) == want:
                break
            time.sleep(0.3)
        assert _probe_total(prom) == want, (
            f"merged probe counter never reached {want}:\n"
            + "\n".join(ln for ln in prom.splitlines() if "probe" in ln)
        )
        # The goodput metrics ride the same scrape.
        assert "tpu_goodput_ratio" in prom
        assert "tpu_time_attributed_seconds_total" in prom
        assert "tpu_step_seconds_bucket" in prom

        # -- /goodput attribution -----------------------------------------
        deadline = time.time() + 120
        while time.time() < deadline:
            assert proc.poll() is None, proc.communicate()[1][-3000:]
            status, live = _get_json(port, "/goodput")
            assert status == 200
            ph = live["phases"]
            # Hold out for a settled picture: enough accumulated wall clock
            # that the (fixed-size) startup/teardown residue stays under the
            # acceptance bound in the OFFLINE view too.
            if (
                ph["train"] > 0 and ph["ckpt_stall"] > 0 and ph["restart"] > 0
                and live["wall_clock_s"] >= 10.0
                and ph["unattributed"] < 0.15 * live["wall_clock_s"]
            ):
                break
            time.sleep(0.4)
        ph = live["phases"]
        wall = live["wall_clock_s"]
        # Injected save + restart visibly moved their phases.
        assert ph["ckpt_stall"] > 0, live
        assert ph["restart"] > 0, live
        # Phases partition wall clock (within 5%) with bounded residue.
        assert abs(sum(ph.values()) - wall) <= 0.05 * wall, live
        assert ph["unattributed"] < 0.20 * wall, live
        assert 0 < live["goodput_ratio"] <= 1
        assert live["steps"] > 0
        assert set(live["ranks"]) == {"0", "1"}

        # -- /healthz -----------------------------------------------------
        status, health = _get_json(port, "/healthz")
        assert status == 200 and health["healthy"] is True
        assert health["restarts_used"] == 1  # the injected fault's round

        # -- shut down cleanly --------------------------------------------
        stop.touch()
        rc = proc.wait(timeout=120)
        assert rc == 0, proc.communicate()[1][-3000:]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()

    # -- offline agreement ------------------------------------------------
    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.tools.metrics_dump",
         str(events_file), "--goodput", "--format", "json"],
        capture_output=True, text=True, timeout=60, env=env,
    )
    assert r.returncode == 0, r.stderr
    offline = json.loads(r.stdout)
    oph = offline["phases"]
    # Exact partition offline too.
    assert abs(sum(oph.values()) - offline["wall_clock_s"]) <= 1e-3
    # The settled phases (no restarts or saves happen after the live
    # capture) must agree closely with the live endpoint...
    assert oph["restart"] == pytest.approx(ph["restart"], abs=0.75)
    assert oph["ckpt_stall"] == pytest.approx(ph["ckpt_stall"], abs=0.75)
    assert oph["incident"] == pytest.approx(ph["incident"], abs=0.1)
    # ...and train/wall only GROW between capture and exit, so the offline
    # ratio stays in the live ratio's neighborhood with the same verdicts.
    assert offline["goodput_ratio"] == pytest.approx(
        live["goodput_ratio"], abs=0.2
    )
    assert oph["unattributed"] < 0.20 * offline["wall_clock_s"], offline
    assert offline["steps"] >= live["steps"]
    # The human table renders from the same stream.
    r2 = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.tools.metrics_dump",
         str(events_file), "--goodput"],
        capture_output=True, text=True, timeout=60, env=env,
    )
    assert r2.returncode == 0 and "goodput:" in r2.stdout
    # Live/post-hoc metrics parity: the aggregated stream carries the same
    # summed probe counter the merged live view served.
    from tpu_resiliency.utils.events import read_events
    from tpu_resiliency.utils.metrics import aggregate

    reg = aggregate(read_events(str(events_file)))
    assert reg.counter("tpu_events_total", kind="goodput_probe").value == want
