"""Incident plane acceptance: engine unit behavior, the straggler →
remediation → recovery loop producing a causally-ordered artifact with
non-null SLO timings, and the crash-survival e2e — kill -9 of a worker
mid-step still yields that rank's flight-recorder dump in the incident
artifact written by the real launcher."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from tpu_resiliency.launcher.incident import (
    IncidentEngine,
    classify_phase,
    read_incident,
)
from tpu_resiliency.tools import incident_report
from tpu_resiliency.utils import events, flight_recorder


@pytest.fixture(autouse=True)
def clean():
    events.clear_sinks()
    saved = {
        k: os.environ.pop(k, None)
        for k in (events.EVENTS_FILE_ENV, events.FLIGHT_DIR_ENV,
                  events.TRACE_ID_ENV, events.PARENT_SPAN_ENV)
    }
    yield
    flight_recorder.uninstall()
    events.clear_sinks()
    for k, v in saved.items():
        if v is not None:
            os.environ[k] = v


class TestEngineUnit:
    def test_explicit_open_close_produces_schema_valid_artifact(self, tmp_path):
        eng = IncidentEngine(str(tmp_path / "inc"), node_id="n0", events_file=None)
        eng.attach()
        events.record("launcher", "worker_failed", global_rank=3, exitcode=-9,
                      detail="rank 3 exit -9")
        eng.open("worker_failed", detail="rank 3 exit -9", ranks=[3])
        events.record("launcher", "restart_requested", reason="rank 3 died")
        events.record("launcher", "rendezvous_round", round=1, world_size=2)
        path = eng.close(outcome="recovered")
        eng.detach()
        doc = read_incident(path)
        assert doc["trigger"] == "worker_failed" and doc["ranks"] == [3]
        phases = [m["phase"] for m in doc["chain"]]
        assert phases == ["detect", "decide", "act"]
        # Chain is ts-ordered (causal within one clock domain).
        tss = [m["ts"] for m in doc["chain"]]
        assert tss == sorted(tss)
        assert doc["slo"]["time_to_detect_s"] is not None
        assert doc["slo"]["time_to_recover_s"] is not None

    def test_second_fault_folds_into_open_incident(self, tmp_path):
        eng = IncidentEngine(str(tmp_path / "inc"), events_file=None)
        a = eng.open("worker_failed", ranks=[1])
        b = eng.open("worker_failed", ranks=[2])
        assert a == b
        path = eng.close()
        assert read_incident(path)["ranks"] == [1, 2]
        assert eng.close() is None  # nothing open anymore

    def test_auto_mode_opens_on_fault_kinds_and_closes_on_recovery(self, tmp_path):
        eng = IncidentEngine(str(tmp_path / "inc"), auto_open=True, events_file=None)
        eng.attach()
        events.record("checkpoint", "ckpt_fallback", from_iteration=5, to_iteration=4)
        assert eng.is_open
        events.record("launcher", "round_succeeded", round=1)
        eng.detach()
        assert not eng.is_open
        doc = read_incident(eng.artifacts[0])
        assert doc["trigger"] == "ckpt_fallback"
        assert doc["outcome"] == "recovered"

    def test_own_narration_never_retriggers(self, tmp_path):
        eng = IncidentEngine(str(tmp_path / "inc"), auto_open=True, events_file=None)
        eng.attach()
        events.record("launcher", "worker_failed", global_rank=0)
        assert eng.is_open
        events.record("launcher", "round_succeeded", round=1)
        assert not eng.is_open
        # The incident_opened/closed events the engine itself recorded must
        # not have opened a second incident.
        assert len(eng.artifacts) == 1
        eng.detach()

    def test_window_prefers_shared_events_file_and_filters_trace(self, tmp_path):
        ev_file = str(tmp_path / "ev.jsonl")
        now = time.time()
        with open(ev_file, "w") as f:
            for rec in [
                {"ts": now - 0.03, "source": "w", "kind": "worker_failed",
                 "pid": 1, "trace_id": "ours", "global_rank": 0},
                {"ts": now - 0.02, "source": "w", "kind": "restart_requested",
                 "pid": 1, "trace_id": "ours", "reason": "x"},
                {"ts": now - 0.01, "source": "other", "kind": "worker_failed",
                 "pid": 9, "trace_id": "theirs", "global_rank": 5},
            ]:
                f.write(json.dumps(rec) + "\n")
        eng = IncidentEngine(str(tmp_path / "inc"), events_file=ev_file)
        eng.attach()
        # Two local records make "ours" the dominant trace.
        os.environ[events.TRACE_ID_ENV] = "ours"
        events.record("launcher", "worker_failed", global_rank=0)
        eng.open("worker_failed", ranks=[0])
        path = eng.close()
        eng.detach()
        doc = read_incident(path)
        assert all(r.get("trace_id") != "theirs" for r in doc["events"])
        assert any(r["kind"] == "restart_requested" for r in doc["events"])

    def test_stale_run_history_cannot_dominate_trace(self, tmp_path):
        # A reused events file holds a LONGER previous run under another
        # trace, all outside the incident window: the dominant trace must be
        # computed over the window only, keeping this run's events.
        ev_file = str(tmp_path / "ev.jsonl")
        now = time.time()
        with open(ev_file, "w") as f:
            for i in range(50):  # yesterday's run, out-voting if counted
                f.write(json.dumps({
                    "ts": now - 86400 + i, "source": "w", "kind": "heartbeat",
                    "pid": 9, "trace_id": "yesterday",
                }) + "\n")
            for rec in [
                {"ts": now - 0.02, "source": "w", "kind": "worker_failed",
                 "pid": 1, "trace_id": "today", "global_rank": 0},
                {"ts": now - 0.01, "source": "w", "kind": "restart_requested",
                 "pid": 1, "trace_id": "today", "reason": "x"},
            ]:
                f.write(json.dumps(rec) + "\n")
        eng = IncidentEngine(str(tmp_path / "inc"), events_file=ev_file)
        eng.open("worker_failed", ranks=[0])
        path = eng.close()
        doc = read_incident(path)
        assert doc["trace_id"] == "today"
        assert any(r["kind"] == "restart_requested" for r in doc["events"])
        assert all(r.get("trace_id") != "yesterday" for r in doc["events"])

    def test_steps_lost_from_iteration_markers(self, tmp_path):
        eng = IncidentEngine(str(tmp_path / "inc"), events_file=None)
        eng.attach()
        events.record("inprocess", "iteration_start", iteration=7)
        events.record("inprocess", "fn_exception", iteration=7, error="boom")
        eng.open("fn_exception")
        events.record("inprocess", "iteration_start", iteration=5)  # resumed
        path = eng.close()
        eng.detach()
        assert read_incident(path)["slo"]["steps_lost"] == 2

    def test_classify_phase_table(self):
        assert classify_phase({"kind": "worker_failed"}) == "detect"
        assert classify_phase({"kind": "restart_requested"}) == "decide"
        assert classify_phase({"kind": "kill_ladder"}) == "act"
        assert classify_phase({"kind": "round_succeeded"}) == "recover"
        assert classify_phase({"kind": "degraded_set", "newly": [1]}) == "detect"
        assert classify_phase({"kind": "degraded_set", "recovered": [1]}) == "recover"
        assert classify_phase({"kind": "straggler_report"}) is None
        assert classify_phase(
            {"kind": "straggler_report", "stragglers_by_perf": [2]}
        ) == "detect"
        assert classify_phase(
            {"kind": "remediation_action", "action": "reinstate"}
        ) == "recover"
        assert classify_phase({"kind": "ckpt_saved"}) is None


class TestStragglerRemediationE2E:
    """Acceptance: an injected straggler drives policy → remediation
    (exclude) → recovery; the artifact carries the causally-ordered
    detect → decide → act → recover chain with non-null time-to-detect /
    time-to-recover, and the CLI renders it with exit 0."""

    def _report(self, perf):
        from tpu_resiliency.telemetry.reporting import Report

        return Report(
            rank=0, world_size=len(perf), iteration=0, section_names=("step",),
            relative_section_scores={"step": 1.0},
            individual_section_scores={"step": 1.0},
            perf_scores=dict(perf), z_scores={r: 0.0 for r in perf},
            ewma_scores=dict(perf),
        )

    def test_full_loop(self, tmp_path, capsys, coord_store):
        from tpu_resiliency.inprocess.coordination import RestartCoordinator
        from tpu_resiliency.telemetry.policy import HealthVectorPolicy
        from tpu_resiliency.telemetry.remediation import RemediationEngine

        inc_dir = str(tmp_path / "incidents")
        flight_recorder.install(inc_dir, capacity=64, install_handlers=False)
        eng = IncidentEngine(inc_dir, node_id="e2e", auto_open=True,
                             events_file=None)
        eng.attach()
        coord = RestartCoordinator(coord_store, world_size=2)
        ckpts = []
        remediation = RemediationEngine(
            checkpoint_fn=lambda: ckpts.append(1),
            publish_degraded_fn=coord.set_degraded,
        )
        policy = HealthVectorPolicy(patience=2, recovery=1, sinks=[remediation])
        slow = {0: 1.0, 1: 0.35}
        policy.observe(self._report(slow))
        policy.observe(self._report(slow))
        assert eng.is_open
        assert coord.degraded_ranks() == {1}  # the exclude actually landed
        policy.observe(self._report({0: 1.0, 1: 0.99}))
        eng.detach()
        assert not eng.is_open and eng.artifacts
        assert ckpts, "proactive checkpoint never ran"
        assert coord.degraded_ranks() == frozenset()

        doc = read_incident(eng.artifacts[0])
        chain = doc["chain"]
        # The causally-ordered chain: detect before decide before act before
        # the final recover.
        first_of = {p: next(i for i, m in enumerate(chain) if m["phase"] == p)
                    for p in ("detect", "decide", "act", "recover")}
        assert first_of["detect"] < first_of["decide"] < first_of["act"] \
            < max(i for i, m in enumerate(chain) if m["phase"] == "recover")
        tss = [m["ts"] for m in chain]
        assert tss == sorted(tss)
        assert doc["slo"]["time_to_detect_s"] is not None
        assert doc["slo"]["time_to_recover_s"] is not None
        assert doc["slo"]["time_to_recover_s"] >= 0
        # The remediation audit rode into the artifact.
        acted = [m for m in chain if m["kind"] == "remediation_action"]
        assert any("exclude" in m["summary"] for m in acted)
        # And the CLI renders it, exit 0.
        assert incident_report.main([eng.artifacts[0]]) == 0
        out = capsys.readouterr().out
        assert "DETECT" in out and "DECIDE" in out
        assert "ACT" in out and "RECOVER" in out


_KILLED_WORKER = textwrap.dedent(
    """
    import os, signal, sys, time
    from tpu_resiliency.utils import events

    round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
    for step in range(12):
        events.record("worker", "train_step", step=step, round=round_no)
    if round_no == 0:
        os.kill(os.getpid(), signal.SIGKILL)   # mid-step, no warning at all
    print("recovered in round", round_no)
    """
)


class TestKill9E2E:
    """Acceptance: kill -9 of a worker mid-step still yields that rank's
    flight-recorder dump inside the incident artifact the launcher writes."""

    def test_launcher_writes_artifact_with_flight_dump(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(_KILLED_WORKER)
        inc_dir = tmp_path / "incidents"
        events_file = tmp_path / "events.jsonl"
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu"})
        r = subprocess.run(
            [sys.executable, "-m", "tpu_resiliency.launcher.launch",
             "--standalone", "--nproc-per-node", "1", "--max-restarts", "2",
             "--no-ft-monitors", "--rdzv-last-call", "0.2",
             "--monitor-interval", "0.1",
             "--events-file", str(events_file),
             "--incidents-dir", str(inc_dir),
             "--run-dir", str(tmp_path / "run"), str(script)],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=str(tmp_path),
        )
        assert r.returncode == 0, r.stderr[-3000:]
        assert "recovered in round 1" in r.stdout

        artifacts = [n for n in os.listdir(inc_dir)
                     if n.startswith("incident-") and n.endswith(".json")]
        assert artifacts, os.listdir(inc_dir)
        doc = read_incident(str(inc_dir / sorted(artifacts)[0]))
        assert doc["trigger"] == "worker_failed"
        assert doc["outcome"] == "recovered"
        assert doc["slo"]["time_to_detect_s"] is not None
        assert doc["slo"]["time_to_recover_s"] is not None
        phases = {m["phase"] for m in doc["chain"]}
        assert {"detect", "decide", "act"} <= phases

        # THE crash-survival property: the SIGKILLed rank's ring is in the
        # artifact — train_step events from round 0, no flush marker (the
        # process never got to run one).
        flights = doc["flight"]
        rank0 = {
            ident: recs for ident, recs in flights.items()
            if ident.startswith("0-")
        }
        assert rank0, f"no rank-0 flight dump: {sorted(flights)}"
        killed = [
            recs for recs in rank0.values()
            if any(rec.get("kind") == "train_step" and rec.get("round") == 0
                   for rec in recs)
        ]
        assert killed, "killed worker's train_step ring missing"
        assert all(
            rec.get("kind") != "flight_flush" for rec in killed[0]
        ), "a SIGKILLed process cannot have flushed"

        # The CLI renders the artifact (exit 0) and names the flight dump.
        assert incident_report.main([str(inc_dir)]) == 0
