"""Warm-spare promotion: parked pre-imported interpreters serve restart rounds
without paying interpreter+import startup (the BENCH_restart respawn tax the
reference's cold ``start_processes`` path pays on every round)."""

import json
import os
import subprocess
import sys
import textwrap
import time

from tpu_resiliency.launcher.park import (
    PROMOTED_ENV,
    WarmSparePool,
    spawn_spare,
)


class TestShim:
    def _spawn(self, tmp_path, preload="json"):
        return spawn_spare(str(tmp_path), 0, preload=preload)

    def _wait_warm(self, spare, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if spare.warm:
                return
            assert spare.alive, "spare died while parking"
            time.sleep(0.02)
        raise AssertionError("spare never became warm")

    def test_unpark_runs_script_with_env_argv_and_logs(self, tmp_path, monkeypatch):
        script = tmp_path / "w.py"
        out = tmp_path / "out.json"
        script.write_text(
            textwrap.dedent(
                f"""
                import json, os, sys
                print("hello-from-worker")
                with open({str(out)!r}, "w") as f:
                    json.dump({{"rank": os.environ["RANK"],
                               "promoted": os.environ.get({PROMOTED_ENV!r}),
                               "stale": os.environ.get("TPU_TEST_STALE_VAR"),
                               "argv": sys.argv[1:]}}, f)
                """
            )
        )
        # Present in the launcher env at park time but ABSENT from the round
        # env: must not leak into the promoted worker (Popen(env=...) parity).
        monkeypatch.setenv("TPU_TEST_STALE_VAR", "leaky")
        spare = self._spawn(tmp_path)
        try:
            self._wait_warm(spare)
            stdout_path = str(tmp_path / "stdout.log")
            round_env = {
                k: v for k, v in os.environ.items() if k != "TPU_TEST_STALE_VAR"
            }
            proc = spare.unpark(
                [str(script), "--flag", "v"],
                {**round_env, "RANK": "3"},
                stdout=stdout_path,
            )
            assert proc.wait(timeout=30) == 0
            got = json.loads(out.read_text())
            assert got == {
                "rank": "3", "promoted": "1", "stale": None, "argv": ["--flag", "v"],
            }
            assert "hello-from-worker" in open(stdout_path).read()
        finally:
            spare.kill()

    def test_promoted_script_is_registered_main(self, tmp_path):
        """Pickle parity: a script-level class in a promoted worker must
        resolve as __main__.<name> (runpy.run_path would leave the shim bound
        to __main__ and break pickling / multiprocessing-spawn)."""
        script = tmp_path / "w.py"
        out = tmp_path / "ok"
        script.write_text(
            textwrap.dedent(
                f"""
                import pickle, sys

                class Payload:
                    x = 41

                if __name__ == "__main__":
                    blob = pickle.dumps(Payload())
                    assert type(pickle.loads(blob)).x == 41
                    assert sys.modules["__main__"].__file__ == {str(script)!r}
                    open({str(out)!r}, "w").close()
                """
            )
        )
        spare = self._spawn(tmp_path)
        try:
            self._wait_warm(spare)
            proc = spare.unpark([str(script)], dict(os.environ))
            assert proc.wait(timeout=30) == 0
            assert out.exists()
        finally:
            spare.kill()

    def test_launcher_death_releases_parked_spare(self, tmp_path):
        """The pipe EOF tether: a launcher that dies without close() — even
        while the spare is still importing — must not leak a parked
        interpreter."""
        import tpu_resiliency

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(tpu_resiliency.__file__)))
        parent = tmp_path / "parent.py"
        parent.write_text(
            textwrap.dedent(
                f"""
                import os, sys
                sys.path.insert(0, {repo_root!r})
                from tpu_resiliency.launcher.park import spawn_spare
                s = spawn_spare({str(tmp_path / "spares")!r}, 0, preload="json")
                print(s.proc.pid, flush=True)
                os._exit(1)  # crash without any cleanup
                """
            )
        )
        r = subprocess.run(
            [sys.executable, str(parent)], capture_output=True, text=True,
            timeout=60, env=dict(os.environ), cwd=repo_root,
        )
        pid = int(r.stdout.strip())
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return  # spare exited cleanly on EOF
            time.sleep(0.1)
        os.kill(pid, 9)
        raise AssertionError(f"orphaned spare pid {pid} still parked after 30s")

    def test_unpark_module_mode_and_failure_exit(self, tmp_path):
        spare = self._spawn(tmp_path)
        try:
            self._wait_warm(spare)
            # `-m platform` prints the platform string and exits 0.
            proc = spare.unpark(["-m", "platform"], dict(os.environ))
            assert proc.wait(timeout=30) == 0
        finally:
            spare.kill()
        bad = tmp_path / "bad.py"
        bad.write_text("import sys\nsys.exit(7)\n")
        spare = self._spawn(tmp_path)
        try:
            self._wait_warm(spare)
            proc = spare.unpark([str(bad)], dict(os.environ))
            assert proc.wait(timeout=30) == 7
        finally:
            spare.kill()

    def test_acquire_never_spawns_and_replenish_tops_up(self, tmp_path, monkeypatch):
        """The promotion hot path: acquire() (even one that reaps a dead spare)
        must NEVER block on a replacement Popen — spawning is replenish()'s
        job, run off the critical path."""
        import tpu_resiliency.launcher.park as park_mod

        pool = WarmSparePool(2, str(tmp_path), preload="json")
        try:
            deadline = time.monotonic() + 30
            while pool.warm_count < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.warm_count == 2
            # One spare "dies" (warm, so it's not a startup death).
            pool._spares[0].proc.kill()
            pool._spares[0].proc.wait(timeout=10)

            def forbidden_spawn(*a, **k):
                raise AssertionError("acquire() spawned a replacement spare")

            monkeypatch.setattr(park_mod, "spawn_spare", forbidden_spawn)
            got = pool.acquire()  # would raise if it tried to spawn
            assert got is not None
            assert pool._spares == []  # reaped + promoted, nothing spawned
            got.kill()
            monkeypatch.undo()
            assert pool.replenish() == 2
            assert len(pool._spares) == 2
        finally:
            pool.close()

    def test_pool_disables_after_systematic_startup_failure(self, tmp_path):
        """Doomed preloads (typo'd module) must not respawn dying interpreters
        forever: the pool notices consecutive startup deaths and disables."""
        pool = WarmSparePool(1, str(tmp_path), preload="definitely_not_a_module")
        try:
            deadline = time.monotonic() + 60
            while pool.size > 0 and time.monotonic() < deadline:
                assert pool.acquire() is None
                pool.replenish()
                time.sleep(0.2)
            assert pool.size == 0
            assert pool.acquire() is None
            assert pool.replenish() == 0
            assert pool._spares == []
        finally:
            pool.close()

    def test_pool_acquire_replenish_cycle_and_close(self, tmp_path):
        pool = WarmSparePool(2, str(tmp_path), preload="json")
        try:
            deadline = time.monotonic() + 30
            while pool.warm_count < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.warm_count == 2
            s1 = pool.acquire()
            assert s1 is not None
            s1.kill()
            pool.replenish()
            # Replenished: back to 2 eventually.
            deadline = time.monotonic() + 30
            while pool.warm_count < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.warm_count == 2
        finally:
            pool.close()
        assert pool.warm_count == 0

    def test_pool_stats_shape_for_healthz(self, tmp_path):
        """The /healthz `warm_spares` block: size/parked/warm/deepest."""
        pool = WarmSparePool(1, str(tmp_path), preload="json")
        try:
            deadline = time.monotonic() + 30
            while pool.warm_count < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.stats() == {
                "size": 1, "parked": 1, "warm": 1, "deepest": 1,
            }
        finally:
            pool.close()
        assert pool.stats()["parked"] == 0

    def test_acquire_prefers_deepest_park_depth(self, tmp_path):
        """With a runtime-warmed and an imports-only spare both parked, the
        promotion must take the deeper one."""
        import json as json_mod

        pool = WarmSparePool(2, str(tmp_path), preload="json")
        try:
            deadline = time.monotonic() + 30
            while pool.warm_count < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.warm_count == 2
            # Simulate one spare having completed the runtime warmup phase.
            deep = pool._spares[1]
            with open(deep.ready_file + ".tmp", "w") as f:
                json_mod.dump({"pid": deep.proc.pid, "depth": 2}, f)
            os.replace(deep.ready_file + ".tmp", deep.ready_file)
            got = pool.acquire()
            assert got is deep
            assert got.park_depth == 2
            got.kill()
        finally:
            pool.close()


class TestWarmupPhase:
    """The optional park warmup phase: depth protocol, crash accounting, and
    the promotion parity contract (warmup must not leak env/sys.path drift
    into the promoted worker)."""

    def _wait_warm(self, spare, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if spare.warm:
                return
            assert spare.alive, "spare died while parking"
            time.sleep(0.02)
        raise AssertionError("spare never became warm")

    def test_ready_file_reports_park_depth(self, tmp_path):
        """imports-only parks at depth 1; a completed warmup parks at depth 2
        — the ready file is the protocol."""
        shallow = spawn_spare(str(tmp_path / "a"), 0, preload="json")
        deep = spawn_spare(
            str(tmp_path / "b"), 0, preload="json", warmup="os:getcwd"
        )
        try:
            self._wait_warm(shallow)
            self._wait_warm(deep)
            assert shallow.park_depth == 1
            assert deep.park_depth == 2
            body = json.loads(open(deep.ready_file).read())
            assert body == {"pid": deep.proc.pid, "depth": 2}
        finally:
            shallow.kill()
            deep.kill()

    def test_runtime_warmup_parks_at_depth_2(self, tmp_path):
        """The built-in platform-safe warmup (device.warm_runtime) completes
        under JAX_PLATFORMS=cpu and reports depth 2."""
        spare = spawn_spare(str(tmp_path), 0, preload="json", warmup="runtime")
        try:
            self._wait_warm(spare, timeout=120.0)
            assert spare.park_depth == 2
        finally:
            spare.kill()

    def test_warmup_crash_is_a_startup_death(self, tmp_path):
        """A warmup that raises must kill the spare BEFORE its ready file
        exists, so the pool counts a startup death (and a doomed warmup
        disables the pool) instead of promoting a half-warm interpreter."""
        spare = spawn_spare(
            str(tmp_path), 0, preload="json", warmup="definitely_not_a_module:boom"
        )
        try:
            assert spare.proc.wait(timeout=60) != 0
            assert not os.path.exists(spare.ready_file)
        finally:
            spare.kill()
        pool = WarmSparePool(
            1, str(tmp_path / "pool"), preload="json",
            warmup="definitely_not_a_module:boom",
        )
        try:
            deadline = time.monotonic() + 60
            while pool.size > 0 and time.monotonic() < deadline:
                assert pool.acquire() is None
                pool.replenish()
                time.sleep(0.2)
            assert pool.size == 0
        finally:
            pool.close()

    def test_promoted_worker_env_and_sys_path_match_cold_spawn(self, tmp_path):
        """Promotion parity THROUGH the warmup phase: a runtime-warmed spare's
        promoted worker must see byte-identical os.environ and sys.path to a
        cold `python script.py` with the same round env (modulo the two
        promotion-marker vars, which exist by design)."""
        script = tmp_path / "dump.py"
        script.write_text(
            textwrap.dedent(
                """
                import json, os, sys
                with open(sys.argv[1], "w") as f:
                    json.dump({"env": dict(os.environ), "path": sys.path}, f)
                """
            )
        )
        round_env = dict(os.environ)
        round_env["TPU_TEST_ROUND_VAR"] = "x"
        cold_out = tmp_path / "cold.json"
        r = subprocess.run(
            [sys.executable, str(script), str(cold_out)],
            env=round_env, timeout=60, cwd=os.getcwd(),
        )
        assert r.returncode == 0
        spare = spawn_spare(str(tmp_path), 0, preload="json", warmup="runtime")
        try:
            self._wait_warm(spare, timeout=120.0)
            warm_out = tmp_path / "warm.json"
            proc = spare.unpark([str(script), str(warm_out)], round_env)
            assert proc.wait(timeout=60) == 0
        finally:
            spare.kill()
        cold = json.loads(cold_out.read_text())
        warm = json.loads(warm_out.read_text())
        markers = {PROMOTED_ENV, "TPU_FT_WARM_SPARE_DEPTH"}
        assert {k: v for k, v in warm["env"].items() if k not in markers} == cold["env"]
        assert warm["path"] == cold["path"]


def test_restart_round_promoted_from_warm_spare(tmp_path):
    """E2E through the real CLI: worker fails once, the restart round's worker
    is a PROMOTED spare (it sees $TPU_FT_WARM_SPARE), and the job succeeds."""
    script = tmp_path / "crash_once.py"
    marker = tmp_path / "crashed"
    result = tmp_path / "result.json"
    spares_dir = tmp_path / "run" / "spares"
    script.write_text(
        textwrap.dedent(
            f"""
            import glob, json, os, sys, time
            if not os.path.exists({str(marker)!r}):
                open({str(marker)!r}, "w").close()
                # Deterministic: crash only once a spare is parked-and-warm —
                # detection+rendezvous are now fast enough that an immediate
                # first-step crash can legitimately beat the spare's own
                # interpreter warm-up (the designed cold-spawn fallback).
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    ready = [p for p in
                             glob.glob(os.path.join({str(spares_dir)!r}, "ready_*"))
                             if not p.endswith(".tmp")]
                    if ready:
                        sys.exit(1)
                    time.sleep(0.05)
                sys.exit(17)  # never went warm: fail loudly, not flakily
            with open({str(result)!r}, "w") as f:
                json.dump({{"promoted": os.environ.get({PROMOTED_ENV!r}),
                           "restart": os.environ["TPU_FT_RESTART_COUNT"]}}, f)
            """
        )
    )
    env = dict(os.environ)
    env.setdefault("TPU_RESILIENCY_LOG_LEVEL", "INFO")
    events_file = tmp_path / "events.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--nproc-per-node", "1", "--max-restarts", "2",
         "--warm-spares", "1", "--warm-spare-preload", "json",
         "--no-ft-monitors", "--events-file", str(events_file),
         "--run-dir", str(tmp_path / "run"), str(script)],
        capture_output=True, text=True, timeout=180, env=env, cwd=str(tmp_path),
    )
    assert r.returncode == 0, r.stderr[-3000:]
    got = json.loads(result.read_text())
    assert got["promoted"] == "1", (got, r.stderr[-2000:])
    assert int(got["restart"]) >= 1
    # The promotion is a first-class structured event for operators. Round 0
    # may legitimately promote too (a spare can warm before the first round on
    # a slow host) — the restart round's promotion is the one that must exist.
    promoted = [
        json.loads(ln) for ln in events_file.read_text().splitlines()
        if '"worker_promoted"' in ln
    ]
    restart_promos = [e for e in promoted if e["round"] >= 1]
    assert restart_promos, promoted
    assert restart_promos[0]["global_rank"] == 0
    assert restart_promos[0]["worker_pid"] > 0
    assert restart_promos[0]["worker_pid"] != restart_promos[0]["pid"]
