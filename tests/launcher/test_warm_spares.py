"""Warm-spare promotion: parked pre-imported interpreters serve restart rounds
without paying interpreter+import startup (the BENCH_restart respawn tax the
reference's cold ``start_processes`` path pays on every round)."""

import json
import os
import subprocess
import sys
import textwrap
import time

from tpu_resiliency.launcher.park import (
    PROMOTED_ENV,
    WarmSparePool,
    spawn_spare,
)


class TestShim:
    def _spawn(self, tmp_path, preload="json"):
        return spawn_spare(str(tmp_path), 0, preload=preload)

    def _wait_warm(self, spare, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if spare.warm:
                return
            assert spare.alive, "spare died while parking"
            time.sleep(0.02)
        raise AssertionError("spare never became warm")

    def test_unpark_runs_script_with_env_argv_and_logs(self, tmp_path, monkeypatch):
        script = tmp_path / "w.py"
        out = tmp_path / "out.json"
        script.write_text(
            textwrap.dedent(
                f"""
                import json, os, sys
                print("hello-from-worker")
                with open({str(out)!r}, "w") as f:
                    json.dump({{"rank": os.environ["RANK"],
                               "promoted": os.environ.get({PROMOTED_ENV!r}),
                               "stale": os.environ.get("TPU_TEST_STALE_VAR"),
                               "argv": sys.argv[1:]}}, f)
                """
            )
        )
        # Present in the launcher env at park time but ABSENT from the round
        # env: must not leak into the promoted worker (Popen(env=...) parity).
        monkeypatch.setenv("TPU_TEST_STALE_VAR", "leaky")
        spare = self._spawn(tmp_path)
        try:
            self._wait_warm(spare)
            stdout_path = str(tmp_path / "stdout.log")
            round_env = {
                k: v for k, v in os.environ.items() if k != "TPU_TEST_STALE_VAR"
            }
            proc = spare.unpark(
                [str(script), "--flag", "v"],
                {**round_env, "RANK": "3"},
                stdout=stdout_path,
            )
            assert proc.wait(timeout=30) == 0
            got = json.loads(out.read_text())
            assert got == {
                "rank": "3", "promoted": "1", "stale": None, "argv": ["--flag", "v"],
            }
            assert "hello-from-worker" in open(stdout_path).read()
        finally:
            spare.kill()

    def test_promoted_script_is_registered_main(self, tmp_path):
        """Pickle parity: a script-level class in a promoted worker must
        resolve as __main__.<name> (runpy.run_path would leave the shim bound
        to __main__ and break pickling / multiprocessing-spawn)."""
        script = tmp_path / "w.py"
        out = tmp_path / "ok"
        script.write_text(
            textwrap.dedent(
                f"""
                import pickle, sys

                class Payload:
                    x = 41

                if __name__ == "__main__":
                    blob = pickle.dumps(Payload())
                    assert type(pickle.loads(blob)).x == 41
                    assert sys.modules["__main__"].__file__ == {str(script)!r}
                    open({str(out)!r}, "w").close()
                """
            )
        )
        spare = self._spawn(tmp_path)
        try:
            self._wait_warm(spare)
            proc = spare.unpark([str(script)], dict(os.environ))
            assert proc.wait(timeout=30) == 0
            assert out.exists()
        finally:
            spare.kill()

    def test_launcher_death_releases_parked_spare(self, tmp_path):
        """The pipe EOF tether: a launcher that dies without close() — even
        while the spare is still importing — must not leak a parked
        interpreter."""
        import tpu_resiliency

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(tpu_resiliency.__file__)))
        parent = tmp_path / "parent.py"
        parent.write_text(
            textwrap.dedent(
                f"""
                import os, sys
                sys.path.insert(0, {repo_root!r})
                from tpu_resiliency.launcher.park import spawn_spare
                s = spawn_spare({str(tmp_path / "spares")!r}, 0, preload="json")
                print(s.proc.pid, flush=True)
                os._exit(1)  # crash without any cleanup
                """
            )
        )
        r = subprocess.run(
            [sys.executable, str(parent)], capture_output=True, text=True,
            timeout=60, env=dict(os.environ), cwd=repo_root,
        )
        pid = int(r.stdout.strip())
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return  # spare exited cleanly on EOF
            time.sleep(0.1)
        os.kill(pid, 9)
        raise AssertionError(f"orphaned spare pid {pid} still parked after 30s")

    def test_unpark_module_mode_and_failure_exit(self, tmp_path):
        spare = self._spawn(tmp_path)
        try:
            self._wait_warm(spare)
            # `-m platform` prints the platform string and exits 0.
            proc = spare.unpark(["-m", "platform"], dict(os.environ))
            assert proc.wait(timeout=30) == 0
        finally:
            spare.kill()
        bad = tmp_path / "bad.py"
        bad.write_text("import sys\nsys.exit(7)\n")
        spare = self._spawn(tmp_path)
        try:
            self._wait_warm(spare)
            proc = spare.unpark([str(bad)], dict(os.environ))
            assert proc.wait(timeout=30) == 7
        finally:
            spare.kill()

    def test_pool_tops_up_after_reap_plus_promotion(self, tmp_path):
        """A dead spare reaped in the same acquire() that promotes a warm one
        must not shrink the pool below size."""
        pool = WarmSparePool(2, str(tmp_path), preload="json")
        try:
            deadline = time.monotonic() + 30
            while pool.warm_count < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.warm_count == 2
            # One spare "dies" (warm, so it's not a startup death).
            pool._spares[0].proc.kill()
            pool._spares[0].proc.wait(timeout=10)
            got = pool.acquire()
            assert got is not None
            assert len(pool._spares) == 2  # reap + promotion both replaced
            got.kill()
        finally:
            pool.close()

    def test_pool_disables_after_systematic_startup_failure(self, tmp_path):
        """Doomed preloads (typo'd module) must not respawn dying interpreters
        forever: the pool notices consecutive startup deaths and disables."""
        pool = WarmSparePool(1, str(tmp_path), preload="definitely_not_a_module")
        try:
            deadline = time.monotonic() + 60
            while pool.size > 0 and time.monotonic() < deadline:
                assert pool.acquire() is None
                time.sleep(0.2)
            assert pool.size == 0
            assert pool.acquire() is None
            assert pool._spares == []
        finally:
            pool.close()

    def test_pool_acquire_replenishes_and_closes(self, tmp_path):
        pool = WarmSparePool(2, str(tmp_path), preload="json")
        try:
            deadline = time.monotonic() + 30
            while pool.warm_count < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.warm_count == 2
            s1 = pool.acquire()
            assert s1 is not None
            s1.kill()
            # Replenished: back to 2 eventually.
            deadline = time.monotonic() + 30
            while pool.warm_count < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.warm_count == 2
        finally:
            pool.close()
        assert pool.warm_count == 0


def test_restart_round_promoted_from_warm_spare(tmp_path):
    """E2E through the real CLI: worker fails once, the restart round's worker
    is a PROMOTED spare (it sees $TPU_FT_WARM_SPARE), and the job succeeds."""
    script = tmp_path / "crash_once.py"
    marker = tmp_path / "crashed"
    result = tmp_path / "result.json"
    spares_dir = tmp_path / "run" / "spares"
    script.write_text(
        textwrap.dedent(
            f"""
            import glob, json, os, sys, time
            if not os.path.exists({str(marker)!r}):
                open({str(marker)!r}, "w").close()
                # Deterministic: crash only once a spare is parked-and-warm —
                # detection+rendezvous are now fast enough that an immediate
                # first-step crash can legitimately beat the spare's own
                # interpreter warm-up (the designed cold-spawn fallback).
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    ready = [p for p in
                             glob.glob(os.path.join({str(spares_dir)!r}, "ready_*"))
                             if not p.endswith(".tmp")]
                    if ready:
                        sys.exit(1)
                    time.sleep(0.05)
                sys.exit(17)  # never went warm: fail loudly, not flakily
            with open({str(result)!r}, "w") as f:
                json.dump({{"promoted": os.environ.get({PROMOTED_ENV!r}),
                           "restart": os.environ["TPU_FT_RESTART_COUNT"]}}, f)
            """
        )
    )
    env = dict(os.environ)
    env.setdefault("TPU_RESILIENCY_LOG_LEVEL", "INFO")
    events_file = tmp_path / "events.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--standalone", "--nproc-per-node", "1", "--max-restarts", "2",
         "--warm-spares", "1", "--warm-spare-preload", "json",
         "--no-ft-monitors", "--events-file", str(events_file),
         "--run-dir", str(tmp_path / "run"), str(script)],
        capture_output=True, text=True, timeout=180, env=env, cwd=str(tmp_path),
    )
    assert r.returncode == 0, r.stderr[-3000:]
    got = json.loads(result.read_text())
    assert got["promoted"] == "1", (got, r.stderr[-2000:])
    assert int(got["restart"]) >= 1
    # The promotion is a first-class structured event for operators. Round 0
    # may legitimately promote too (a spare can warm before the first round on
    # a slow host) — the restart round's promotion is the one that must exist.
    promoted = [
        json.loads(ln) for ln in events_file.read_text().splitlines()
        if '"worker_promoted"' in ln
    ]
    restart_promos = [e for e in promoted if e["round"] >= 1]
    assert restart_promos, promoted
    assert restart_promos[0]["global_rank"] == 0
    assert restart_promos[0]["worker_pid"] > 0
    assert restart_promos[0]["worker_pid"] != restart_promos[0]["pid"]
