"""End-to-end launcher tests: the real ``tpu-ft-launcher`` CLI run as a subprocess
against tiny worker scripts (the pattern of the reference's
``tests/fault_tolerance/test_launcher.py`` + ``_launcher_test_util.py``)."""

import os
import socket
import subprocess
import sys
import textwrap
import time

import pytest


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_launcher(args, script, tmp_path, timeout=120, extra_env=None, name="agent"):
    env = dict(os.environ)
    env.setdefault("TPU_RESILIENCY_LOG_LEVEL", "INFO")
    env.update(extra_env or {})
    cmd = (
        [sys.executable, "-m", "tpu_resiliency.launcher.launch"]
        + args
        + ["--run-dir", str(tmp_path / f"run_{name}"), str(script)]
    )
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout, env=env, cwd=str(tmp_path)
    )


def launch_async(args, script, tmp_path, extra_env=None, name="agent"):
    env = dict(os.environ)
    env.setdefault("TPU_RESILIENCY_LOG_LEVEL", "INFO")
    env.update(extra_env or {})
    cmd = (
        [sys.executable, "-m", "tpu_resiliency.launcher.launch"]
        + args
        + ["--run-dir", str(tmp_path / f"run_{name}"), str(script)]
    )
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env, cwd=str(tmp_path)
    )


def test_success_two_workers(tmp_path):
    script = tmp_path / "ok.py"
    out = tmp_path / "out_{}.txt"
    script.write_text(
        textwrap.dedent(
            f"""
            import os
            with open({str(out)!r}.format(os.environ["RANK"]), "w") as f:
                f.write(os.environ["WORLD_SIZE"])
            """
        )
    )
    r = run_launcher(
        ["--nproc-per-node", "2", "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
         "--no-ft-monitors", "--rdzv-last-call", "0.2"],
        script,
        tmp_path,
    )
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "out_0.txt").read_text() == "2"
    assert (tmp_path / "out_1.txt").read_text() == "2"


def test_restart_until_success(tmp_path):
    """Workers fail in rounds 0 and 1 and succeed in round 2: the launcher must
    restart twice and exit 0."""
    script = tmp_path / "flaky.py"
    script.write_text(
        textwrap.dedent(
            """
            import os, sys
            round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
            if round_no < 2:
                print(f"round {round_no}: failing", file=sys.stderr)
                sys.exit(3)
            print(f"round {round_no}: ok")
            """
        )
    )
    r = run_launcher(
        ["--nproc-per-node", "2", "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
         "--max-restarts", "4", "--no-ft-monitors", "--rdzv-last-call", "0.2",
         "--monitor-interval", "0.1"],
        script,
        tmp_path,
        extra_env={"TPU_RESILIENCY_LOG_LEVEL": "INFO"},
    )
    assert r.returncode == 0, r.stderr
    assert "requesting restart round" in r.stderr  # agent logged the restart rounds
    assert "round 2: ok" in r.stdout


def test_restart_budget_exhausted(tmp_path):
    script = tmp_path / "dead.py"
    script.write_text("raise RuntimeError('always broken')")
    r = run_launcher(
        ["--nproc-per-node", "1", "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
         "--max-restarts", "1", "--no-ft-monitors", "--rdzv-last-call", "0.2",
         "--monitor-interval", "0.1"],
        script,
        tmp_path,
    )
    assert r.returncode == 1
    assert "restart budget" in r.stderr
    assert "RuntimeError" in r.stderr  # failure diagnosis from the error file


def test_two_agents_elastic(tmp_path):
    """Two agents rendezvous into one world of 2 nodes × 1 proc."""
    port = free_port()
    script = tmp_path / "pair.py"
    script.write_text(
        textwrap.dedent(
            f"""
            import os
            with open(r"{tmp_path}/pair_" + os.environ["RANK"] + ".txt", "w") as f:
                f.write(os.environ["WORLD_SIZE"] + ":" + os.environ["NODE_RANK"])
            """
        )
    )
    args = ["--nproc-per-node", "1", "--nnodes", "2", "--rdzv-endpoint",
            f"127.0.0.1:{port}", "--no-ft-monitors", "--rdzv-last-call", "0.3",
            "--monitor-interval", "0.1"]
    p0 = launch_async(args + ["--node-id", "nodeA"], script, tmp_path, name="a")
    p1 = launch_async(args + ["--node-id", "nodeB"], script, tmp_path, name="b")
    out0, err0 = p0.communicate(timeout=120)
    out1, err1 = p1.communicate(timeout=120)
    assert p0.returncode == 0, err0
    assert p1.returncode == 0, err1
    texts = sorted(
        (tmp_path / f"pair_{r}.txt").read_text() for r in (0, 1)
    )
    assert texts == ["2:0", "2:1"]


def test_worker_hang_detected_by_ft_monitor(tmp_path):
    """A rank that stops heartbeating is killed by its monitor and the launcher
    restarts the job (heartbeat-based hang detection end to end)."""
    script = tmp_path / "hang.py"
    script.write_text(
        textwrap.dedent(
            """
            import os, time
            from tpu_resiliency.watchdog import RankMonitorClient

            round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
            c = RankMonitorClient()
            c.init_workload_monitoring()
            if round_no == 0:
                time.sleep(600)  # hang: no heartbeat ever arrives
            for _ in range(3):
                c.send_heartbeat()
                time.sleep(0.1)
            c.shutdown_workload_monitoring()
            print("recovered")
            """
        )
    )
    r = run_launcher(
        ["--nproc-per-node", "1", "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
         "--max-restarts", "2", "--rdzv-last-call", "0.2", "--monitor-interval", "0.1",
         "--ft-param-initial_rank_heartbeat_timeout", "3",
         "--ft-param-rank_heartbeat_timeout", "3",
         "--ft-param-workload_check_interval", "0.5"],
        script,
        tmp_path,
        timeout=180,
    )
    assert r.returncode == 0, r.stderr


def test_workload_control_shutdown(tmp_path):
    """A rank asks the launcher to shut the whole workload down."""
    script = tmp_path / "quitter.py"
    script.write_text(
        textwrap.dedent(
            """
            import os, time
            from tpu_resiliency.watchdog import RankMonitorClient, WorkloadAction

            c = RankMonitorClient()
            c.init_workload_monitoring()
            c.send_workload_control_request(WorkloadAction.ShutdownWorkload, "test says stop")
            time.sleep(600)  # the launcher should kill us
            """
        )
    )
    r = run_launcher(
        ["--nproc-per-node", "1", "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
         "--max-restarts", "3", "--rdzv-last-call", "0.2", "--monitor-interval", "0.1"],
        script,
        tmp_path,
    )
    assert r.returncode == 1
    assert "shut down" in r.stderr


def test_spare_promotion_after_failure(tmp_path):
    """nnodes 1:1 with two agents: one active, one spare. The active's worker fails
    in round 0; the restart round re-ranks both agents and the job finishes."""
    port = free_port()
    script = tmp_path / "flaky2.py"
    script.write_text(
        textwrap.dedent(
            """
            import os, sys
            if int(os.environ["TPU_FT_RESTART_COUNT"]) == 0:
                sys.exit(4)
            print("ok in round", os.environ["TPU_FT_RESTART_COUNT"])
            """
        )
    )
    args = ["--nproc-per-node", "1", "--nnodes", "1", "--rdzv-endpoint",
            f"127.0.0.1:{port}", "--no-ft-monitors", "--rdzv-last-call", "0.3",
            "--max-restarts", "3", "--monitor-interval", "0.1"]
    p0 = launch_async(args + ["--node-id", "nodeA"], script, tmp_path, name="a")
    time.sleep(0.1)
    p1 = launch_async(args + ["--node-id", "nodeB"], script, tmp_path, name="b")
    out0, err0 = p0.communicate(timeout=120)
    out1, err1 = p1.communicate(timeout=120)
    assert p0.returncode == 0, err0
    assert p1.returncode == 0, err1


def test_upscale_promotes_late_joiner(tmp_path):
    """`--nnodes 1:2` with upscaling: agent A starts alone (world of 1 node); agent
    B joins mid-run; the leader detects the waiting node, triggers an upscale
    restart round, and the re-formed world runs with WORLD_SIZE=2 (reference
    behavior: restart on num_nodes_waiting>0, ``launcher.py:333-346`` +
    ``_ft_rendezvous.py:302-338``)."""
    port = free_port()
    script = tmp_path / "upscale.py"
    script.write_text(
        textwrap.dedent(
            f"""
            import os, time
            ws = os.environ["WORLD_SIZE"]
            rank = os.environ["RANK"]
            rnd = os.environ["TPU_FT_RESTART_COUNT"]
            with open(r"{tmp_path}/world_" + rnd + "_" + rank + ".txt", "w") as f:
                f.write(ws)
            if ws == "1":
                time.sleep(600)  # park until the upscale round kills + re-ranks us
            print("done at world", ws)
            """
        )
    )
    args = ["--nproc-per-node", "1", "--nnodes", "1:2", "--upscaling-enabled",
            "--rdzv-endpoint", f"127.0.0.1:{port}", "--no-ft-monitors",
            "--rdzv-last-call", "0.3", "--max-restarts", "3",
            "--monitor-interval", "0.1"]
    # conftest pins TPU_RESILIENCY_LOG_LEVEL=WARNING; the upscale assertion below
    # reads the leader's INFO log line.
    info = {"TPU_RESILIENCY_LOG_LEVEL": "INFO"}
    p0 = launch_async(args + ["--node-id", "nodeA"], script, tmp_path,
                      extra_env=info, name="a")
    # Wait until nodeA's solo round 0 actually RAN (its parked worker wrote the
    # marker) before nodeB exists — otherwise nodeB could join round 0 directly
    # and the first round would legitimately form at world size 2.
    deadline = time.monotonic() + 60.0
    while not (tmp_path / "world_0_0.txt").exists():
        assert time.monotonic() < deadline, "nodeA never formed its solo round"
        assert p0.poll() is None, "nodeA exited before forming a round"
        time.sleep(0.1)
    p1 = launch_async(args + ["--node-id", "nodeB"], script, tmp_path,
                      extra_env=info, name="b")
    out0, err0 = p0.communicate(timeout=120)
    out1, err1 = p1.communicate(timeout=120)
    assert p0.returncode == 0, err0
    assert p1.returncode == 0, err1
    assert "upscale" in err0  # the leader logged the upscale restart request
    # Some round ran at world size 1 before the upscale...
    world1_rounds = [
        f for f in os.listdir(tmp_path)
        if f.startswith("world_") and (tmp_path / f).read_text() == "1"
    ]
    assert world1_rounds, "no round ever ran at world size 1"
    # ...and the final round ran with BOTH ranks at world size 2.
    final_round = max(
        int(f.split("_")[1]) for f in os.listdir(tmp_path) if f.startswith("world_")
    )
    finals = sorted(
        f for f in os.listdir(tmp_path) if f.startswith(f"world_{final_round}_")
    )
    assert finals == [f"world_{final_round}_0.txt", f"world_{final_round}_1.txt"]
    assert all((tmp_path / f).read_text() == "2" for f in finals)


def test_dead_agent_detected_and_spare_promoted(tmp_path):
    """SIGKILL the active agent mid-run: the spare must detect the stale keep-alive,
    trigger a restart round, get promoted, and finish the job alone."""
    import signal as sigmod

    port = free_port()
    script = tmp_path / "slowok.py"
    script.write_text("import time; time.sleep(8); print('done')")
    args = ["--nproc-per-node", "1", "--nnodes", "1", "--rdzv-endpoint",
            f"127.0.0.1:{port}", "--no-ft-monitors", "--rdzv-last-call", "0.3",
            "--max-restarts", "3", "--monitor-interval", "0.1",
            "--rdzv-keep-alive-interval", "0.2", "--rdzv-keep-alive-timeout", "2"]
    # nodeA hosts the store? No — killing it would kill the store. Host the store
    # in a dedicated third process: the spare (started first, so it binds) — but a
    # spare must be a late joiner. Instead host the store here in the test process.
    from tpu_resiliency.platform.store import KVServer

    server = KVServer(host="127.0.0.1", port=port)
    try:
        p0 = launch_async(args + ["--node-id", "nodeA"], script, tmp_path, name="a")
        time.sleep(2.0)  # nodeA becomes active and starts its worker
        p1 = launch_async(args + ["--node-id", "nodeB"], script, tmp_path, name="b")
        time.sleep(2.0)  # nodeB lands as waiting/spare
        p0.send_signal(sigmod.SIGKILL)
        p0.wait(timeout=10)
        out1, err1 = p1.communicate(timeout=120)
        assert p1.returncode == 0, err1
    finally:
        server.close()
        if p0.poll() is None:
            p0.kill()


def test_resilient_training_example(tmp_path):
    """The full-stack example (FT heartbeats + straggler sections + hierarchical
    checkpoints + injected crash) driven by the real launcher: crash in round 0,
    resume from the local checkpoint in round 1."""
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    p = launch_async(
        ["--nproc-per-node", "1", "--rdzv-endpoint", "127.0.0.1:0",
         "--max-restarts", "2", "--rdzv-last-call", "0.2",
         "--monitor-interval", "0.1",
         "--ft-param-initial_rank_heartbeat_timeout", "60",
         "--ft-param-rank_heartbeat_timeout", "60"],
        os.path.join(repo, "examples", "resilient_training.py"),
        tmp_path,
        extra_env={
            "JAX_PLATFORMS": "cpu",
            "TPU_RESILIENCY_LOG_LEVEL": "INFO",
            "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        },
        name="resilient",
    )
    try:
        out, err = p.communicate(timeout=300)
    except subprocess.TimeoutExpired:
        p.kill()
        out, err = p.communicate(timeout=30)
        raise AssertionError(f"launcher wedged:\n{out[-2000:]}\n{err[-2000:]}")
    assert p.returncode == 0, f"{out[-2000:]}\n{err[-2000:]}"
    assert "resumed" in out.lower() or "resumed" in err.lower(), (out[-1500:], err[-1500:])


def test_remote_restart_propagation_is_event_driven(tmp_path):
    """A peer node must observe another node's restart request via the store
    watch, not at its next poll tick: with a deliberately huge monitor
    interval, node A's worker failure still pulls node B into the next round
    within a couple of seconds (events-file timestamps, one host clock)."""
    import json

    port = free_port()
    script = tmp_path / "w.py"
    script.write_text(
        textwrap.dedent(
            f"""
            import os, sys, time
            round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
            if round_no == 0:
                if os.environ["NODE_RANK"] == "0":
                    time.sleep(2.0)  # both nodes settled into supervising
                    sys.exit(1)
                time.sleep(600)  # node B's worker parks; launcher must stop it
            """
        )
    )
    args = ["--nproc-per-node", "1", "--nnodes", "2", "--rdzv-endpoint",
            f"127.0.0.1:{port}", "--no-ft-monitors", "--rdzv-last-call", "0.3",
            "--max-restarts", "2", "--monitor-interval", "5.0"]
    ev_a, ev_b = tmp_path / "ev_a.jsonl", tmp_path / "ev_b.jsonl"
    p0 = launch_async(args + ["--node-id", "nodeA", "--events-file", str(ev_a)],
                      script, tmp_path, name="a")
    p1 = launch_async(args + ["--node-id", "nodeB", "--events-file", str(ev_b)],
                      script, tmp_path, name="b")
    out0, err0 = p0.communicate(timeout=240)
    out1, err1 = p1.communicate(timeout=240)
    assert p0.returncode == 0, err0[-3000:]
    assert p1.returncode == 0, err1[-3000:]

    evs_a = [json.loads(ln) for ln in ev_a.read_text().splitlines()]
    evs_b = [json.loads(ln) for ln in ev_b.read_text().splitlines()]
    # Node rank 0 (the crasher) is decided by join order — find the requester's
    # stream dynamically; the PEER's round-1 entry is the propagation endpoint.
    if any(e.get("kind") == "restart_requested" for e in evs_a):
        requester, peer = evs_a, evs_b
    else:
        requester, peer = evs_b, evs_a
    kinds = [sorted({e.get("kind") for e in s}) for s in (evs_a, evs_b)]
    t_restart = next(
        (e["ts"] for e in requester if e.get("kind") == "restart_requested"), None
    )
    assert t_restart is not None, f"no restart_requested in either stream: {kinds}"
    t_peer_round1 = next(
        (e["ts"] for e in peer
         if e.get("kind") == "rendezvous_round" and e.get("round", 0) >= 1),
        None,
    )
    assert t_peer_round1 is not None, f"peer never reached round 1: {kinds}"
    delta = t_peer_round1 - t_restart
    assert delta < 4.0, (
        f"peer reached round 1 only {delta:.1f}s after the restart request "
        f"(monitor interval was 5s — propagation fell back to polling)"
    )
