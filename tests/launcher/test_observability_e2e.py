"""Observability end to end: one injected worker fault under the real launcher
produces an events JSONL from which the trace export renders the full restart
span chain and the metrics dump answers the operator questions (restart count,
rendezvous p50/p95, checkpoint save latency) — the acceptance criteria of the
observability layer, all under JAX_PLATFORMS=cpu."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def fault_run(tmp_path_factory):
    """One launcher run, shared by the assertions below: the worker saves a
    local checkpoint every round, crashes in round 0, succeeds in round 1."""
    tmp_path = tmp_path_factory.mktemp("obs_e2e")
    script = tmp_path / "worker.py"
    ckpt_root = tmp_path / "ckpt"
    script.write_text(
        textwrap.dedent(
            f"""
            import os, sys
            import numpy as np
            from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
            from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict

            round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
            m = LocalCheckpointManager({str(ckpt_root)!r}, rank=0)
            m.save(
                round_no,
                PyTreeStateDict({{"w": np.arange(64, dtype=np.float32)}}),
                is_async=False,
            )
            if round_no == 0:
                sys.exit(3)
            print("recovered in round", round_no)
            """
        )
    )
    events_file = tmp_path / "run_events.jsonl"
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "TPU_RESILIENCY_LOG_LEVEL": "INFO"})
    r = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.launcher.launch",
         "--nproc-per-node", "1", "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
         "--max-restarts", "2", "--no-ft-monitors", "--rdzv-last-call", "0.2",
         "--monitor-interval", "0.1", "--events-file", str(events_file),
         "--run-dir", str(tmp_path / "run"), str(script)],
        capture_output=True, text=True, timeout=240, env=env, cwd=str(tmp_path),
    )
    assert r.returncode == 0, r.stderr[-3000:]
    records = [json.loads(ln) for ln in events_file.read_text().splitlines()]
    return tmp_path, events_file, records


def test_stream_covers_the_promised_record_set(fault_run):
    """The events.py docstring's contract, now instrumented: rendezvous,
    restart, and checkpoint decisions each leave a record."""
    _, _, records = fault_run
    kinds = {r["kind"] for r in records}
    assert {"rendezvous_round", "worker_failed", "restart_requested",
            "restart_budget", "ckpt_saved", "round_succeeded",
            "rendezvous_closed", "span_begin", "span_end"} <= kinds
    # Checkpoint latency decomposition rode along (debug_time roots).
    timing_names = {r.get("name") for r in records if r["kind"] == "timing"}
    assert "ckpt.save.write" in timing_names
    # ckpt_saved now carries the volume that explains the latency.
    saved = [r for r in records if r["kind"] == "ckpt_saved"]
    assert len(saved) == 2 and all(r.get("bytes", 0) > 0 for r in saved)


def test_one_trace_id_and_cross_process_parenting(fault_run):
    _, _, records = fault_run
    tids = {r.get("trace_id") for r in records}
    assert len(tids) == 1 and None not in tids, "trace id must span every process"
    pids = {r["pid"] for r in records}
    assert len(pids) >= 3  # launcher + two worker incarnations
    # The worker's records parent to the launcher round that spawned it:
    round_ids = {
        r["span_id"] for r in records
        if r["kind"] == "span_begin" and r.get("span") == "launcher.round"
    }
    launcher_pid = next(
        r["pid"] for r in records
        if r["kind"] == "span_begin" and r.get("span") == "launcher.job"
    )
    worker_saved = [r for r in records
                    if r["kind"] == "ckpt_saved" and r["pid"] != launcher_pid]
    assert worker_saved and all(
        r.get("span_id") in round_ids for r in worker_saved
    ), "worker events must carry the spawning round's span as their context"


def test_trace_export_renders_the_restart_span_chain(fault_run):
    tmp_path, events_file, _ = fault_run
    from tpu_resiliency.tools import trace_export
    from tpu_resiliency.utils.events import read_events

    out = tmp_path / "trace.json"
    assert trace_export.main([str(events_file), "-o", str(out)]) == 0
    doc = json.load(open(out))  # Perfetto-loadable: valid trace-event JSON
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    by_name = {}
    for e in slices:
        by_name.setdefault(e["name"], []).append(e)
    # The full restart chain: job → round 0 → (fault) → rendezvous → round 1.
    assert "launcher.job" in by_name
    assert len(by_name.get("launcher.round", [])) == 2
    assert len(by_name.get("rendezvous.round", [])) >= 2
    assert "worker.spawn" in by_name
    # The fault and the restart request appear as instants between the rounds.
    instants = {e["name"] for e in doc["traceEvents"] if e["ph"] == "i"}
    assert {"worker_failed", "restart_requested"} <= instants
    # Chain integrity in the raw stream: round spans parent to the job span.
    records = read_events(str(events_file))
    job = next(r for r in records
               if r["kind"] == "span_begin" and r.get("span") == "launcher.job")
    rounds = [r for r in records
              if r["kind"] == "span_begin" and r.get("span") == "launcher.round"]
    assert all(r["parent_id"] == job["span_id"] for r in rounds)


def test_metrics_dump_reports_the_headline_numbers(fault_run, capsys):
    tmp_path, events_file, _ = fault_run
    from tpu_resiliency.tools import metrics_dump
    from tpu_resiliency.utils.events import read_events
    from tpu_resiliency.utils.metrics import aggregate

    assert metrics_dump.main([str(events_file)]) == 0
    out = capsys.readouterr().out
    assert "in-job requested: 1" in out          # restart count
    assert "rendezvous round duration: n=" in out  # p50/p95 line
    assert "checkpoint save/load latency" in out
    # And the numbers behind the report are sane.
    reg = aggregate(read_events(str(events_file)))
    rdzv = reg.histograms("tpu_span_seconds")[(("span", "rendezvous.round"),)]
    assert rdzv.count >= 2
    assert 0 < rdzv.quantile(0.5) <= rdzv.quantile(0.95) < 120
    ckpt = reg.histograms("tpu_timing_seconds")[(("name", "ckpt.save.write"),)]
    assert ckpt.count == 2 and ckpt.quantile(0.95) < 60
    prom = reg.to_prometheus()
    assert 'tpu_restarts_total{layer="injob"} 1' in prom
    assert "tpu_ckpt_saves_total 2" in prom
