"""Layered in-job + in-process restart, end to end.

The core product scenario (reference
``examples/fault_tolerance/in_job_and_in_process_example.py`` +
``rank_monitor_state_machine.py:127-145``): workers wrapped with ``inprocess.Wrapper``
run under ``tpu-ft-launcher`` and share the launcher-hosted coordination store
(``TPU_RESILIENCY_STORE_EXTERNAL``). Two fault classes must route to the right layer:

(a) an exception inside the wrapped fn → the in-process layer restarts the function;
    the launcher never sees a failed worker (``TPU_FT_RESTART_COUNT`` stays 0);
(b) a worker process death → the in-job layer respawns the round; respawned wrappers
    form a fresh restart world scoped by the new launcher round.

Both restarters must narrate their state machines via the ``[NestedRestarter]``
log-line contract.
"""

import os
import socket
import subprocess
import sys
import textwrap

def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


WORKER = """
import os, sys, time

from tpu_resiliency.inprocess.nested_restarter import NestedRestarter
from tpu_resiliency.inprocess.wrap import CallWrapper, Wrapper

rank = int(os.environ["RANK"])
launcher_round = int(os.environ["TPU_FT_RESTART_COUNT"])
outdir = {outdir!r}

nr = NestedRestarter()


@Wrapper(
    initialize=nr.on_initialize,
    abort=nr.on_abort,
    completion=nr.on_completion,
    terminate=nr.on_terminate,
    monitor_interval=0.05,
    last_call_wait=0.1,
    soft_timeout=10.0,
    hard_timeout=20.0,
    heartbeat_interval=0.2,
    heartbeat_timeout=10.0,
    barrier_timeout=45.0,
    completion_timeout=45.0,
)
def train(call: CallWrapper):
    it = call.iteration
    with open(os.path.join(outdir, "trace_%d.log" % rank), "a") as f:
        f.write("round=%d iter=%d\\n" % (launcher_round, it))
    if launcher_round == 0:
        if it == 0 and rank == 1:
            # (a) handled by the in-process layer: the launcher must not notice.
            raise RuntimeError("inprocess-handled fault")
        if it >= 1 and rank == 1:
            # (b) process death: only the in-job layer can handle this.
            os._exit(13)
        # Healthy ranks park until a restart signal (or the launcher's respawn
        # tears us down as part of the in-job round).
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            time.sleep(0.05)
        sys.exit(9)  # parked forever: the test failed
    return "ok"


result = train()
print("WORKER_OK rank=%d round=%d result=%s" % (rank, launcher_round, result), flush=True)
"""


def test_layered_inprocess_then_injob_restart(tmp_path):
    outdir = tmp_path / "traces"
    outdir.mkdir()
    script = tmp_path / "layered.py"
    script.write_text(WORKER.format(outdir=str(outdir)))

    env = dict(os.environ)
    env["TPU_RESILIENCY_LOG_LEVEL"] = "INFO"
    log_dir = tmp_path / "logs"
    events_file = tmp_path / "events.jsonl"
    cmd = [
        sys.executable, "-m", "tpu_resiliency.launcher.launch",
        "--nproc-per-node", "2",
        "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
        "--max-restarts", "3",
        "--no-ft-monitors",
        "--rdzv-last-call", "0.2",
        "--monitor-interval", "0.1",
        "--run-dir", str(tmp_path / "run"),
        "--log-dir", str(log_dir),
        "--events-file", str(events_file),
        str(script),
    ]
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=240, env=env, cwd=str(tmp_path)
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"

    # --- fault (a): the in-process layer handled the exception -----------------
    # Rank 0's trace shows wrapper iterations 0 AND 1 within launcher round 0:
    # the function restarted without the launcher respawning anything.
    trace0 = (outdir / "trace_0.log").read_text().splitlines()
    assert "round=0 iter=0" in trace0
    assert "round=0 iter=1" in trace0

    # --- fault (b): the in-job layer respawned the round -----------------------
    # Both ranks re-entered at launcher round 1, wrapper iteration 0 (a fresh
    # in-process world scoped by the new launcher round), and completed.
    trace1 = (outdir / "trace_1.log").read_text().splitlines()
    assert "round=1 iter=0" in trace0
    assert "round=1 iter=0" in trace1
    worker_stdout = "".join(p.read_text() for p in sorted(log_dir.rglob("stdout.log")))
    assert "WORKER_OK rank=0 round=1" in worker_stdout
    assert "WORKER_OK rank=1 round=1" in worker_stdout

    # Exactly one in-job restart was charged: the exception in (a) consumed no
    # launcher budget, so no worker ever saw a round beyond 1.
    assert "round=2" not in worker_stdout
    assert not (log_dir / "round_2").exists()

    # --- the NestedRestarter log-line contract ---------------------------------
    # In-job lines narrate the launcher's state machine on the agent's stderr.
    injob = [ln for ln in r.stderr.splitlines() if "[NestedRestarter] name=[InJob]" in ln]
    assert any("state=initialize" in ln for ln in injob)
    assert any("state=handling_start" in ln for ln in injob)
    assert any("state=handling_completed" in ln for ln in injob)

    # In-process lines narrate each wrapper's machine on the worker's stderr
    # (captured per round/rank under --log-dir).
    worker_logs = sorted(log_dir.rglob("stderr.log"))
    assert worker_logs, f"no captured worker logs under {log_dir}"
    inproc = [
        ln
        for p in worker_logs
        for ln in p.read_text().splitlines()
        if "[NestedRestarter] name=[InProcess]" in ln
    ]
    assert any("state=initialize" in ln for ln in inproc)
    # Fault (a) drove some wrapper through a full handling cycle.
    assert any("state=handling_start" in ln for ln in inproc)
    assert any("state=handling_completed" in ln for ln in inproc)
    # The successful round finalized.
    assert any("state=finalized" in ln for ln in inproc)

    # --- the structured event stream tells the same story, machine-readably ----
    from tpu_resiliency.utils.events import read_events

    evs = read_events(str(events_file))
    kinds = [(e["source"], e["kind"]) for e in evs]
    assert ("launcher", "rendezvous_round") in kinds
    assert ("launcher", "worker_failed") in kinds
    assert ("launcher", "restart_requested") in kinds
    assert ("launcher", "round_succeeded") in kinds
    assert ("inprocess", "iteration_start") in kinds
    assert ("inprocess", "fn_exception") in kinds
    assert ("inprocess", "restart_signalled") in kinds
    assert ("inprocess", "completed") in kinds
    # The in-process layer handled fault (a) inside launcher round 0: its restart
    # events precede the in-job worker_failed record.
    first_inproc_restart = next(
        i for i, k in enumerate(kinds) if k == ("inprocess", "restart_signalled")
    )
    first_worker_failed = next(
        i for i, k in enumerate(kinds) if k == ("launcher", "worker_failed")
    )
    assert first_inproc_restart < first_worker_failed
    # Two rendezvous rounds total (0 and the respawn), each completed.
    rounds = {e["round"] for e in evs if e["kind"] == "rendezvous_round"}
    assert rounds == {0, 1}
    # Exactly one worker death was recorded, with its exit code.
    deaths = [e for e in evs if e["kind"] == "worker_failed"]
    assert len(deaths) == 1 and deaths[0]["exitcode"] == 13


SPARE_WORKER = """
import glob, json, os, sys, time

# Captured BEFORE any import of this script's own dependencies: in a promoted
# spare the pool's preload already put jax in sys.modules; a cold interpreter
# at this point has not.
jax_preloaded = "jax" in sys.modules

from tpu_resiliency.inprocess.wrap import CallWrapper, Wrapper

rank = int(os.environ["RANK"])
launcher_round = int(os.environ["TPU_FT_RESTART_COUNT"])
outdir = {outdir!r}
spares_dir = {spares_dir!r}


@Wrapper(
    monitor_interval=0.05,
    last_call_wait=0.1,
    soft_timeout=10.0,
    hard_timeout=20.0,
    heartbeat_interval=0.2,
    heartbeat_timeout=10.0,
    barrier_timeout=45.0,
    completion_timeout=45.0,
)
def train(call: CallWrapper):
    if launcher_round == 0:
        if rank == 1:
            # Die only once a spare is parked-and-warm, so the restart round
            # deterministically promotes instead of cold-spawning.
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                ready = [p for p in glob.glob(os.path.join(spares_dir, "ready_*"))
                         if not p.endswith(".tmp")]
                if len(ready) >= 2:
                    os._exit(13)
                time.sleep(0.05)
            sys.exit(17)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            time.sleep(0.05)
        sys.exit(9)
    return "ok"


result = train()
with open(os.path.join(outdir, "result_%d.json" % rank), "w") as f:
    json.dump({{"rank": rank, "round": launcher_round, "result": result,
               "promoted": os.environ.get("TPU_FT_WARM_SPARE"),
               "jax_preloaded": jax_preloaded}}, f)
"""


def test_layered_restart_round_served_by_warm_spares(tmp_path):
    """Full-stack integration in the PRODUCTION preload shape: the respawned
    round's workers are promoted warm spares that really did import jax while
    parked (asserted via sys.modules at script start), and the in-process
    Wrapper (store scoping, restart world, barriers) works identically inside
    a promoted interpreter. Deliberately pays the jax-preload cost the other
    warm-spare tests avoid — this is the one test of the default preload."""
    outdir = tmp_path / "out"
    outdir.mkdir()
    run_dir = tmp_path / "run"
    script = tmp_path / "spare_layered.py"
    script.write_text(
        SPARE_WORKER.format(outdir=str(outdir), spares_dir=str(run_dir / "spares"))
    )
    env = dict(os.environ)
    env["TPU_RESILIENCY_LOG_LEVEL"] = "INFO"
    cmd = [
        sys.executable, "-m", "tpu_resiliency.launcher.launch",
        "--nproc-per-node", "2",
        "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
        "--max-restarts", "2",
        "--warm-spares", "2",
        "--no-ft-monitors",
        "--rdzv-last-call", "0.2",
        "--monitor-interval", "0.1",
        "--run-dir", str(run_dir),
        str(script),
    ]
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=300, env=env, cwd=str(tmp_path)
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-3000:]}"
    import json

    for rank in (0, 1):
        got = json.loads((outdir / f"result_{rank}.json").read_text())
        assert got["round"] == 1 and got["result"] == "ok", got
        assert got["promoted"] == "1", got
        assert got["jax_preloaded"] is True, got
