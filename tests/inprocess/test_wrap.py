"""End-to-end restart-loop tests: forked rank processes + injected faults.

Models the reference's ``tests/inprocess/test_wrap.py`` enumeration (fault in fn,
process death, restart to success) using the fork-N-subprocess harness of SURVEY §4.
Each child runs the real Wrapper against the shared KV store; the parent asserts on
results sent back over a queue.
"""

import multiprocessing as mp
import os
import signal
import socket
import time

import pytest

from tpu_resiliency.exceptions import RestartAbort


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def fast_wrapper(**kw):
    from tpu_resiliency.inprocess.wrap import Wrapper

    # Generous timeouts: fault detection in these tests rides socket EOF (instant),
    # and tight heartbeat windows false-positive under parallel-suite CPU contention.
    defaults = dict(
        monitor_interval=0.05,
        last_call_wait=0.1,
        soft_timeout=10.0,
        hard_timeout=20.0,
        heartbeat_interval=0.2,
        heartbeat_timeout=10.0,
        barrier_timeout=45.0,
        completion_timeout=45.0,
    )
    defaults.update(kw)
    return Wrapper(**defaults)


def run_world(world, body, timeout=90.0, expect_exit=None, after_start=None):
    """Fork `world` children; each runs body(rank, result_q). Returns rank→result.

    ``after_start(port)`` runs in the parent once all children are forked — for
    tests that inject store state mid-run (e.g. simulating a monitor's proxy
    joins)."""
    port = free_port()
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = []
    for rank in range(world):
        def child(rank=rank):
            os.environ["RANK"] = str(rank)
            os.environ["WORLD_SIZE"] = str(world)
            os.environ["TPU_RESILIENCY_STORE_PORT"] = str(port)
            os.environ["TPU_RESILIENCY_STORE_HOST"] = "127.0.0.1"
            body(rank, q)

        p = ctx.Process(target=child, daemon=False)
        p.start()
        procs.append(p)
    if after_start is not None:
        after_start(port)
    results = {}
    deadline = time.monotonic() + timeout
    try:
        while len(results) < world and time.monotonic() < deadline:
            try:
                rank, payload = q.get(timeout=1.0)
                results[rank] = payload
            except Exception:
                if all(not p.is_alive() for p in procs) and q.empty():
                    break
    finally:
        for p in procs:
            p.join(timeout=15.0)
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5.0)
    return results, [p.exitcode for p in procs]


class TestSingleRank:
    def test_success_passthrough(self):
        def body(rank, q):
            @fast_wrapper()
            def train():
                return "done"

            q.put((rank, train()))

        results, codes = run_world(1, body)
        assert results == {0: "done"} and codes == [0]

    def test_restart_on_exception(self):
        def body(rank, q):
            from tpu_resiliency.inprocess.wrap import CallWrapper

            attempts = []

            @fast_wrapper()
            def train(call: CallWrapper):
                attempts.append(call.iteration)
                if len(attempts) < 3:
                    raise RuntimeError(f"boom {len(attempts)}")
                return ("ok", attempts)

            q.put((rank, train()))

        results, codes = run_world(1, body)
        assert results[0] == ("ok", [0, 1, 2])
        assert codes == [0]

    def test_retry_controller_aborts(self):
        def body(rank, q):
            from tpu_resiliency.inprocess.initialize import RetryController

            @fast_wrapper(initialize=RetryController(max_iterations=2))
            def train():
                raise RuntimeError("always fails")

            try:
                train()
                q.put((rank, "no-abort"))
            except RestartAbort:
                q.put((rank, "aborted"))

        results, codes = run_world(1, body)
        assert results == {0: "aborted"} and codes == [0]


class TestMultiRank:
    def test_peer_exception_restarts_everyone(self):
        def body(rank, q):
            from tpu_resiliency.inprocess.wrap import CallWrapper

            state = {"n": 0}

            @fast_wrapper()
            def train(call: CallWrapper):
                state["n"] += 1
                if call.iteration == 0 and rank == 1:
                    raise RuntimeError("rank1 fails round 0")
                # Survivors park until the restart signal arrives.
                deadline = time.monotonic() + 30.0
                while call.iteration == 0 and time.monotonic() < deadline:
                    time.sleep(0.05)
                return ("ok", call.iteration, call.frozen_state.active_world_size)

            q.put((rank, train()))

        results, codes = run_world(2, body)
        assert codes == [0, 0]
        # Both ranks completed on iteration 1 with the full world intact.
        assert results[0] == ("ok", 1, 2)
        assert results[1] == ("ok", 1, 2)

    def test_rank_death_shrinks_world(self):
        def body(rank, q):
            from tpu_resiliency.inprocess.wrap import CallWrapper

            @fast_wrapper()
            def train(call: CallWrapper):
                if call.iteration == 0 and rank == 1:
                    os._exit(7)  # hard death: monitor must report + proxy barriers
                deadline = time.monotonic() + 60.0
                while call.iteration == 0 and time.monotonic() < deadline:
                    time.sleep(0.05)
                return ("ok", call.iteration, call.frozen_state.active_world_size)

            q.put((rank, train()))

        results, codes = run_world(2, body, timeout=120.0)
        assert codes[1] == 7
        assert results[0] == ("ok", 1, 1)  # survivor re-entered with world 1

    def test_degraded_rank_demoted_without_dying(self):
        """The health-vector decisions loop (VERDICT r1 item 2): a slow-but-alive
        rank recorded degraded is excluded from the active world on the next
        restart round — a healthy spare takes its slot — without the slow rank
        ever dying."""

        def body(rank, q):
            from tpu_resiliency.inprocess.rank_assignment import DemoteDegraded
            from tpu_resiliency.inprocess.wrap import CallWrapper

            @fast_wrapper(rank_assignment=DemoteDegraded(max_active_world_size=2))
            def train(call: CallWrapper):
                fs = call.frozen_state
                if call.iteration == 0:
                    if rank == 0:
                        # Telemetry policy publishes: rank 1 is degraded.
                        call.coord.set_degraded({1})
                        time.sleep(0.3)
                        raise RuntimeError("force a restart round")
                    deadline = time.monotonic() + 30.0
                    while time.monotonic() < deadline:
                        time.sleep(0.05)
                return ("ok", call.iteration, fs.mode.name, fs.active_rank,
                        fs.active_world_size)

            q.put((rank, train()))

        results, codes = run_world(3, body, timeout=120.0)
        assert codes == [0, 0, 0]
        # Iteration 1: ranks 0 and 2 active; degraded rank 1 is alive but spent the
        # round in reserve (a reserve rank's wrapper returns None on completion).
        assert results[1] is None
        assert results[0] == ("ok", 1, "ACTIVE", 0, 2)
        assert results[2] == ("ok", 1, "ACTIVE", 1, 2)

    def test_system_exit_terminates_rank_not_restart(self):
        """SystemExit must terminate the raising rank (re-raised, rank recorded
        terminated) while peers restart without it — not spin the raiser through
        restart rounds (ADVICE r1: reference restarts only on Exception)."""

        def body(rank, q):
            from tpu_resiliency.inprocess.wrap import CallWrapper

            attempts = []

            @fast_wrapper()
            def train(call: CallWrapper):
                attempts.append(call.iteration)
                if rank == 1:
                    raise SystemExit(5)
                deadline = time.monotonic() + 60.0
                while call.iteration == 0 and time.monotonic() < deadline:
                    time.sleep(0.05)
                return ("ok", call.iteration, call.frozen_state.active_world_size)

            try:
                q.put((rank, train()))
            except SystemExit as e:
                q.put((rank, ("exit", e.code, len(attempts))))

        results, codes = run_world(2, body, timeout=120.0)
        # Rank 1 left exactly once — no restart loop for BaseException.
        assert results[1] == ("exit", 5, 1)
        # Rank 0 restarted into a world of 1.
        assert results[0] == ("ok", 1, 1)

    def test_spare_rank_activates_on_failure(self):
        """3 ranks, active world capped at 2: rank 2 starts as a reserve spare and
        takes over when rank 1 dies."""

        def body(rank, q):
            from tpu_resiliency.inprocess.rank_assignment import MaxActiveWorldSize
            from tpu_resiliency.inprocess.wrap import CallWrapper

            @fast_wrapper(rank_assignment=MaxActiveWorldSize(2))
            def train(call: CallWrapper):
                fs = call.frozen_state
                if call.iteration == 0 and rank == 1:
                    os._exit(5)
                deadline = time.monotonic() + 60.0
                while call.iteration == 0 and time.monotonic() < deadline:
                    time.sleep(0.05)
                return ("ok", call.iteration, fs.active_rank, fs.active_world_size)

            q.put((rank, train()))

        results, codes = run_world(3, body, timeout=120.0)
        assert codes[1] == 5
        # Survivors 0 and 2 are both active in iteration 1 (spare promoted).
        assert results[0][0] == "ok" and results[2][0] == "ok"
        assert results[0][3] == 2 and results[2][3] == 2


class TestStandDown:
    def test_proxy_completed_straggler_stands_down(self):
        """A rank that was proxy-completed out of a finishing round (declared dead
        while starved, but actually alive) must stand down cleanly when it discovers
        the job finished without it — clean None return and exit 0, not a crash on
        the dead coordinator (wrap.py job_done pre-check + server_linger)."""
        from tpu_resiliency.platform.store import CoordStore

        def body(rank, q):
            @fast_wrapper(server_linger=10.0)
            def train():
                if rank == 0:
                    time.sleep(0.3)
                    return "ok"
                # The straggler: sleeps through the whole completion round, then
                # faults into the restart path.
                time.sleep(4.0)
                raise RuntimeError("late fault on the straggler")

            q.put((rank, train()))
            if rank == 0:
                # Keep the process (and with it the lingering server) alive for the
                # straggler's full rescue window: >= server_linger, so the job_done
                # check cannot race the server's death under CI load.
                time.sleep(12.0)

        def proxy_straggler(port):
            # Simulate the straggler's watcher declaring it dead: proxy rank 1 into
            # the iteration-0 completion barrier so rank 0 finishes without it.
            time.sleep(1.5)
            mon = CoordStore("127.0.0.1", port, prefix="inprocess/")
            mon.barrier_join(
                "barrier/completion/0", 1, 2, timeout=0.0, wait=False, on_behalf=True
            )
            mon.close()

        results, codes = run_world(2, body, timeout=90.0, after_start=proxy_straggler)
        assert results.get(0) == "ok", results
        assert 1 in results and results[1] is None, results  # stood down cleanly
        assert codes == [0, 0]
