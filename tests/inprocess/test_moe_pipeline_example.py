"""The restart engine protecting the framework's most complex workload: the
pipelined+expert-parallel MoE example survives an injected fault and resumes from
its local checkpoint (examples/moe_pipeline_training.py driven end to end)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_moe_pipeline_example_restarts_and_resumes(tmp_path):
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "examples", "moe_pipeline_training.py"),
            "--steps", "8",
            "--fault-step", "3",
            "--ckpt-root", str(tmp_path),
        ],
        capture_output=True,
        text=True,
        timeout=560,
    )
    out = proc.stdout
    assert proc.returncode == 0, f"stdout:\n{out}\nstderr:\n{proc.stderr[-2000:]}"
    # Fault at step 3 after the step-2 checkpoint: the restart resumes at step 3.
    assert "RESUMED step=3" in out, out
    assert "DONE loss=" in out, out
