"""Miniature resilient training app: the analogue of the reference's
``tests/inprocess/app.py`` (a real distributed workload driven through real faults
by ``test_app.py``), re-designed TPU-first.

Each rank is a standalone JAX process with a local device mesh. The train loop is a
*sharded jitted* step on the tiny transformer (``models/transformer.py``): tokens are
sharded over the mesh's ``dp`` axis while params stay replicated, so XLA inserts the
cross-device gradient reduction — a real collective inside the step. Local
checkpoints are clique-replicated across ranks (factor 2), so every rank's disk holds
its peer's shard mirror.

The restart contract exercised end to end (SURVEY §7 step 5):

- iteration 0, world 2: train + replicated checkpoints; one rank is killed hard;
- iteration 1, world 1: the survivor re-enters with a RESHAPED mesh (the dp/tp split
  changes with the active world), re-jits, restores its own shard onto the new mesh's
  shardings, and reconstructs the dead rank's state from the clique mirror on its own
  disk (``LocalCheckpointManager.load_shard``) — no store gather, no dead-peer I/O.

Invoked as: ``python app.py <rank> <world> <steps> <kill_step> <ckpt_root>``
(RANK/WORLD_SIZE/TPU_RESILIENCY_STORE_* come from the environment, set by test_app).
Prints ``APP-RESULT {json}`` on success.
"""

import json
import os
import sys
import time

rank_arg, world_arg, steps_arg, kill_step_arg, ckpt_root = sys.argv[1:6]
RANK, WORLD = int(rank_arg), int(world_arg)
STEPS, KILL_STEP = int(steps_arg), int(kill_step_arg)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_resiliency.checkpoint import (
    CliqueReplicationStrategy,
    LocalCheckpointManager,
    PyTreeStateDict,
)
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.inprocess.wrap import CallWrapper, Wrapper
from tpu_resiliency.models.transformer import TransformerConfig, make_train_step, init_params
from tpu_resiliency.platform.store import CoordStore, store_addr_from_env

CFG = TransformerConfig.tiny(n_layers=1, d_model=32, d_ff=64, n_heads=2, n_kv_heads=1,
                             max_seq_len=16, dtype=jnp.float32)
BATCH, SEQ = 4, 16
SAVE_STEPS = (2, 4)


def build_mesh(active_world: int) -> Mesh:
    """The mesh RESHAPES with the world: 2 ranks → local (dp=2, tp=2);
    1 rank → local (dp=4, tp=1). Restart must re-jit against the new split."""
    devs = np.asarray(jax.devices()[:4])
    if active_world >= 2:
        return Mesh(devs.reshape(2, 2), ("dp", "tp"))
    return Mesh(devs.reshape(4, 1), ("dp", "tp"))


def make_ckpt_stack(store_prefix: str, rank: int, world: int):
    """Fresh per-iteration checkpoint stack. World >= 2: store comm + clique
    replication (factor 2). World 1: purely local."""
    if world < 2:
        return LocalCheckpointManager(ckpt_root, rank=rank)
    host, port = store_addr_from_env()
    store = CoordStore(host, port, prefix=store_prefix)
    comm = StoreComm(store.scoped("comm/"), rank, list(range(world)), timeout=60.0)
    ex = PeerExchange(store.scoped("px/"), rank, timeout=60.0)
    ex.start()  # bind the p2p listener + publish this rank's address
    repl = CliqueReplicationStrategy(
        StoreComm(store.scoped("repl/"), rank, list(range(world)), timeout=60.0),
        ex,
        replication_jump=1,
        replication_factor=2,
    )
    return LocalCheckpointManager(ckpt_root, rank=rank, comm=comm, replication=repl)


@Wrapper(
    monitor_interval=0.05,
    last_call_wait=0.1,
    # Generous progress timeouts: the first XLA compile of the sharded step runs
    # tens of seconds on CPU, and the watchdog's pending-call auto-heartbeat
    # cannot fire inside a long C++ call (same reality as the reference's 60 s
    # default soft timeout).
    soft_timeout=120.0,
    hard_timeout=240.0,
    heartbeat_interval=0.2,
    heartbeat_timeout=30.0,
    barrier_timeout=240.0,
    completion_timeout=240.0,
)
def train(call: CallWrapper):
    fs = call.frozen_state
    me, active_world, it = fs.initial_rank, fs.active_world_size, fs.iteration
    mesh = build_mesh(active_world)
    replicated = NamedSharding(mesh, P())
    tokens_sharding = NamedSharding(mesh, P("dp"))

    train_step, init_opt = make_train_step(CFG)
    step_jit = jax.jit(train_step)

    rng = np.random.default_rng(1234 + me)
    params = jax.device_put(init_params(jax.random.PRNGKey(0), CFG), replicated)
    opt_state = jax.device_put(init_opt(params), replicated)
    # The rank-owned shard: proves post-shrink reconstruction from clique mirrors.
    stats = jnp.zeros((8,), jnp.float32) + float(me) * 100.0

    mgr = make_ckpt_stack(f"app/iter{it}/", me, active_world if it == 0 else 1)
    start_step = 0
    recovered_stats = None
    latest = mgr.find_latest()
    if latest >= 0:
        shardings = [replicated] * len(
            jax.tree_util.tree_leaves({"params": params, "opt": opt_state, "stats": stats})
        )
        tree, meta = mgr.load_tree(latest, shardings=shardings)
        params, opt_state, stats = tree["params"], tree["opt"], tree["stats"]
        start_step = int(meta["iteration"]) + 1
        if active_world < WORLD:
            # Survivor path: rebuild the dead ranks' shards from local mirrors.
            recovered_stats = {}
            for owner in range(WORLD):
                if owner == me:
                    recovered_stats[owner] = np.asarray(stats)
                    continue
                hollow, tensors, _ = mgr.load_shard(owner, latest)
                sd = PyTreeStateDict.from_hollow(
                    hollow, tensors, shardings=[replicated] * len(tensors)
                )
                recovered_stats[owner] = np.asarray(sd.tree["stats"])

    mesh_shape = dict(mesh.shape)
    loss = jnp.zeros(())  # stays zero when the restored start_step is >= STEPS
    for step in range(start_step, STEPS):
        if it == 0 and me == 1 and step == KILL_STEP:
            os._exit(9)  # hard death: the survivor must carry on without us
        tokens = jax.device_put(
            jnp.asarray(rng.integers(0, CFG.vocab_size, (BATCH, SEQ)), jnp.int32),
            tokens_sharding,
        )
        params, opt_state, loss = step_jit(params, opt_state, tokens)
        stats = stats + 1.0
        call.ping()
        time.sleep(0.25)
        if it == 0 and step in SAVE_STEPS:
            mgr.save(
                step,
                PyTreeStateDict({"params": params, "opt": opt_state, "stats": stats}),
                is_async=False,
            )
    loss.block_until_ready()
    mgr.close()
    return {
        "rank": me,
        "iteration": it,
        "active_world": active_world,
        "mesh": mesh_shape,
        "start_step": start_step,
        "final_loss": float(loss),
        "stats": np.asarray(stats).tolist(),
        "recovered_stats": (
            {k: v.tolist() for k, v in recovered_stats.items()}
            if recovered_stats is not None
            else None
        ),
    }


if __name__ == "__main__":
    result = train()
    print("APP-RESULT " + json.dumps(result), flush=True)
