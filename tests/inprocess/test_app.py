"""End-to-end restart of a real sharded training app (``app.py``) through a hard
rank death — the analogue of the reference's ``tests/inprocess/test_app.py``.

Asserts the full recovery chain: death detection → in-process restart →
reassignment to a shrunken world → RESHAPED local mesh (dp/tp split changes) →
resume from the newest fully-covered replicated checkpoint → reconstruction of the
dead rank's shard from the survivor's clique mirror (``load_shard``)."""

import json
import os
import socket
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
APP = os.path.join(os.path.dirname(os.path.abspath(__file__)), "app.py")

STEPS = 10
KILL_STEP = 6  # after the step-4 replicated save has finalized


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_app_restart_reshards_and_recovers(tmp_path):
    port = free_port()
    ckpt_root = str(tmp_path / "ckpt")
    env_base = dict(os.environ)
    env_base["PYTHONPATH"] = REPO_ROOT + os.pathsep + env_base.get("PYTHONPATH", "")
    env_base["TPU_RESILIENCY_STORE_HOST"] = "127.0.0.1"
    env_base["TPU_RESILIENCY_STORE_PORT"] = str(port)
    env_base["TPU_RESILIENCY_LOG_LEVEL"] = "INFO"

    procs = []
    for rank in range(2):
        env = dict(env_base)
        env["RANK"] = str(rank)
        env["WORLD_SIZE"] = "2"
        procs.append(
            subprocess.Popen(
                [sys.executable, APP, str(rank), "2", str(STEPS), str(KILL_STEP), ckpt_root],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
                cwd=str(tmp_path),
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # Rank 1 died hard at KILL_STEP.
    assert outs[1][0] == 9, f"rank 1: rc={outs[1][0]}\n{outs[1][1]}\n{outs[1][2]}"

    # Rank 0 survived, restarted, finished.
    rc, out, err = outs[0]
    assert rc == 0, f"rank 0: rc={rc}\nstdout:\n{out}\nstderr:\n{err}"
    line = [ln for ln in out.splitlines() if ln.startswith("APP-RESULT ")][0]
    r = json.loads(line[len("APP-RESULT "):])

    # Re-entered on iteration 1 with the world shrunk to 1...
    assert r["iteration"] == 1 and r["active_world"] == 1, r
    # ...on a RESHAPED mesh: (dp=2, tp=2) at world 2 became (dp=4, tp=1).
    assert r["mesh"] == {"dp": 4, "tp": 1}, r
    # ...resumed from the step-4 replicated checkpoint (latest fully covered).
    assert r["start_step"] == 5, r
    assert r["final_loss"] == r["final_loss"]  # finite (not NaN)

    # The dead rank's shard was reconstructed from the survivor's clique mirror:
    # rank 1's stats row = 100 (rank base) + 5 steps counted before the save at
    # step 4; rank 0's own row = 0 + 5 at the save, then advanced to STEPS total.
    rec = r["recovered_stats"]
    assert rec is not None and set(rec) == {"0", "1"}, r
    assert rec["1"] == [105.0] * 8, r
    assert rec["0"] == [5.0] * 8, r
    # Own stats continued from the restored value through the remaining steps.
    assert r["stats"] == [float(STEPS)] * 8, r
