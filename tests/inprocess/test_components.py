"""Unit tests for the in-process restart building blocks (no multi-process)."""

import threading
import time

import pytest

from tpu_resiliency.exceptions import (
    HealthCheckError,
    InternalError,
    RestartAbort,
)
from tpu_resiliency.inprocess.attribution import Interruption
from tpu_resiliency.inprocess.compose import Compose, isinstance_or_composed
from tpu_resiliency.inprocess.coordination import RestartCoordinator
from tpu_resiliency.inprocess.finalize import Finalize, ThreadedFinalize
from tpu_resiliency.inprocess.health_check import FaultCounter, JaxHealthCheck
from tpu_resiliency.inprocess.initialize import RetryController
from tpu_resiliency.inprocess.monitor_thread import MonitorThread, RankShouldRestart
from tpu_resiliency.inprocess.progress_watchdog import ProgressWatchdog
from tpu_resiliency.inprocess.rank_assignment import (
    ActivateAllRanks,
    ActiveWorldSizeDivisibleBy,
    FillGaps,
    FilterCountGroupedByKey,
    Layer,
    LayerFlag,
    MaxActiveWorldSize,
    RankAssignmentCtx,
    ShiftRanks,
    Tree,
)
from tpu_resiliency.inprocess.state import Mode, State
from tpu_resiliency.inprocess.tools.inject_fault import Fault, InjectedFault, inject_fault
from tpu_resiliency.platform.store import CoordStore


def ctx_for(rank, world, terminated=()):
    return RankAssignmentCtx(
        State(rank=rank, world_size=world), frozenset(terminated)
    )


class TestStateAndFilters:
    def test_state_defaults(self):
        s = State(rank=3, world_size=8)
        assert s.initial_rank == 3 and s.active_rank == 3 and s.mode == Mode.INITIALIZED

    def test_activate_all(self):
        c = ActivateAllRanks()(ctx_for(4, 6, terminated={1, 2}))
        assert c.state.mode == Mode.ACTIVE
        assert c.state.active_rank == 2  # survivors [0,3,4,5] → index 2
        assert c.state.active_world_size == 4

    def test_shift_ranks_terminated_rank(self):
        c = ShiftRanks()(ctx_for(1, 4, terminated={1}))
        assert c.state.mode == Mode.TERMINATED and c.state.active_rank is None

    def test_fill_gaps_keeps_stable_slots(self):
        # world 6, terminate {1, 3} → survivors [0,2,4,5], n=4.
        # keep: 0,2 at own slots; movers 4,5 fill gaps [1,3].
        for rank, expect in [(0, 0), (2, 2), (4, 1), (5, 3)]:
            c = FillGaps()(ctx_for(rank, 6, terminated={1, 3}))
            assert (c.state.active_rank, c.state.mode) == (expect, Mode.ACTIVE)

    def test_max_active_world_size(self):
        c = MaxActiveWorldSize(2)(ctx_for(3, 4))
        assert c.state.mode == Mode.INACTIVE and c.state.active_world_size == 2

    def test_divisible_by(self):
        c = ActiveWorldSizeDivisibleBy(4)(ctx_for(5, 7, terminated={0}))
        # 6 survivors → active world 4; survivor idx of 5 is 4 → INACTIVE
        assert c.state.active_world_size == 4
        assert c.state.mode == Mode.INACTIVE

    def test_divisible_by_abort(self):
        with pytest.raises(RestartAbort):
            ActiveWorldSizeDivisibleBy(8)(ctx_for(0, 4, terminated={1}))

    def test_filter_count_grouped_by_key(self):
        # hosts of 2; host with a dead member is dropped entirely.
        a = FilterCountGroupedByKey(lambda r: r // 2, lambda n: n == 2)
        c = a(ctx_for(0, 6, terminated={1}))
        assert c.state.mode == Mode.INACTIVE  # host 0 lost rank 1 → rank 0 demoted
        c = a(ctx_for(2, 6, terminated={1}))
        assert c.state.mode == Mode.ACTIVE and c.state.active_rank == 0


class TestTree:
    def test_dissolve_under_min(self):
        # hosts of 2, min 2: losing one rank dissolves the host; RESERVE keeps the
        # survivor as a spare.
        tree = Tree(
            layers=[
                Layer(
                    min_ranks=2,
                    max_ranks=2,
                    key_or_fn=lambda r: r // 2,
                    flag=LayerFlag.RESERVE,
                )
            ]
        )
        c = tree(ctx_for(2, 8, terminated={3}))
        assert c.state.mode == Mode.INACTIVE  # rank 2's host dissolved
        c = tree(ctx_for(0, 8, terminated={3}))
        assert c.state.mode == Mode.ACTIVE and c.state.active_world_size == 6

    def test_backfill_across_hosts_within_slice(self):
        # Outer layer: slices of 4 (BACKFILL). Inner: hosts of 2 (min 2, RESERVE).
        # Terminating rank 3 dissolves host 1; its survivor (rank 2) backfills
        # slice 0 back toward capacity.
        tree = Tree(
            layers=[
                Layer(
                    min_ranks=2,
                    max_ranks=4,
                    key_or_fn=lambda r: r // 4,
                    flag=LayerFlag.BACKFILL,
                ),
                Layer(
                    min_ranks=2,
                    max_ranks=2,
                    key_or_fn=lambda r: r // 2,
                    flag=LayerFlag.RESERVE,
                ),
            ]
        )
        c = tree(ctx_for(2, 8, terminated={3}))
        assert c.state.mode == Mode.ACTIVE
        assert c.state.active_world_size == 7  # everyone alive stays active
        actives = set()
        for r in range(8):
            if r == 3:
                continue
            cc = tree(ctx_for(r, 8, terminated={3}))
            assert cc.state.mode == Mode.ACTIVE
            actives.add(cc.state.active_rank)
        assert actives == set(range(7))  # dense renumbering

    def test_world_size_filter(self):
        tree = Tree(
            layers=[Layer(min_ranks=1, key_or_fn=None)],
            world_size_filter=lambda n: (n // 4) * 4,
        )
        c = tree(ctx_for(0, 10, terminated={9}))
        assert c.state.active_world_size == 8


class TestPlugins:
    def test_retry_controller(self):
        s = State(rank=0, world_size=4)
        RetryController(max_iterations=3)(s.freeze())
        s.iteration = 3
        with pytest.raises(RestartAbort):
            RetryController(max_iterations=3)(s.freeze())

    def test_retry_controller_min_world(self):
        s = State(rank=0, world_size=2)
        with pytest.raises(RestartAbort):
            RetryController(min_world_size=4)(s.freeze())

    def test_fault_counter(self):
        st = State(rank=0, world_size=1)
        st.fn_exception = RuntimeError("local fault")
        faulted = st.freeze()
        fc = FaultCounter(max_rank_faults=2)
        fc(faulted)
        fc(faulted)
        with pytest.raises(HealthCheckError):
            fc(faulted)

    def test_fault_counter_ignores_peer_rounds(self):
        st = State(rank=0, world_size=2)
        clean = st.freeze()  # restart caused by a peer: fn_exception is None
        fc = FaultCounter(max_rank_faults=1)
        for _ in range(5):
            fc(clean)  # never raises: this rank did not fault

    def test_jax_health_check_passes(self):
        s = State(rank=0, world_size=1).freeze()
        assert JaxHealthCheck(timeout=60.0)(s) is s

    def test_threaded_finalize_runs(self):
        hits = []
        s = State(rank=0, world_size=1).freeze()
        ThreadedFinalize(timeout=5.0, fn=lambda: hits.append(1))(s)
        assert hits == [1]

    def test_threaded_finalize_timeout(self):
        s = State(rank=0, world_size=1).freeze()
        with pytest.raises(InternalError):
            ThreadedFinalize(timeout=0.2, fn=lambda: time.sleep(5))(s)

    def test_compose(self):
        f = Compose(lambda x: x + 1, lambda x: x * 2)
        assert f(3) == 8
        assert isinstance_or_composed(
            Compose(ThreadedFinalize(1.0, lambda: None)), Finalize
        )
        assert not isinstance_or_composed(Compose(lambda x: x), Finalize)

    def test_inject_fault_exc(self):
        with pytest.raises(InjectedFault):
            inject_fault(Fault.EXC)


class TestMonitorThread:
    def test_injects_until_acknowledged(self, kv_server):
        store = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        coord = RestartCoordinator(store, world_size=2)
        aborted = []
        lock = threading.RLock()
        mt = MonitorThread(
            coord,
            iteration=0,
            main_thread_id=threading.main_thread().ident,
            atomic_lock=lock,
            abort_fn=lambda: aborted.append(1),
            interval=0.05,
            last_call_wait=0.0,
        )
        mt.start()
        mt.arm()
        coord.record_interruption(0, 1, Interruption.EXCEPTION, "peer failed")
        caught = False
        deadline = time.monotonic() + 10.0
        try:
            while time.monotonic() < deadline:
                time.sleep(0.01)
        except RankShouldRestart:
            caught = True
        finally:
            mt.acknowledge()
            mt.shutdown()
        assert caught and aborted == [1] and mt.fired
        store.close()

    def test_atomic_section_defers_injection(self, kv_server):
        store = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        coord = RestartCoordinator(store, world_size=2)
        lock = threading.RLock()
        mt = MonitorThread(
            coord,
            iteration=0,
            main_thread_id=threading.main_thread().ident,
            atomic_lock=lock,
            interval=0.05,
            last_call_wait=0.0,
        )
        mt.start()
        mt.arm()
        interrupted_inside = False
        try:
            with lock:  # critical section: injection must not land here
                coord.record_interruption(0, 1, Interruption.EXCEPTION, "x")
                time.sleep(0.5)
                critical_done = True
        except RankShouldRestart:
            interrupted_inside = True
            critical_done = False
        # Outside the lock the injection is free to land.
        caught_outside = False
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                time.sleep(0.01)
        except RankShouldRestart:
            caught_outside = True
        finally:
            mt.acknowledge()
            mt.shutdown()
        assert not interrupted_inside and critical_done and caught_outside
        store.close()

    def test_clean_shutdown_without_interruption(self, kv_server):
        store = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        coord = RestartCoordinator(store, world_size=1)
        mt = MonitorThread(
            coord,
            iteration=0,
            main_thread_id=threading.main_thread().ident,
            atomic_lock=threading.RLock(),
            interval=0.05,
        )
        mt.start()
        mt.shutdown()
        assert not mt.fired
        store.close()


def _native_probe_built() -> bool:
    try:
        from tpu_resiliency import _probe_native  # noqa: F401

        return True
    except ImportError:
        return False


class TestProgressWatchdog:
    @pytest.mark.parametrize(
        "use_native",
        [
            False,
            pytest.param(
                True,
                marks=pytest.mark.skipif(
                    not _native_probe_built(), reason="_probe_native not built"
                ),
            ),
        ],
    )
    def test_auto_and_manual_timestamps(self, use_native):
        reports = []
        wd = ProgressWatchdog(
            interval=0.05, report=lambda k, t: reports.append(k), use_native=use_native
        )
        wd.start()
        time.sleep(0.5)  # main thread sleeping still executes pending calls
        wd.ping()
        wd.shutdown()
        kinds = set(reports)
        assert "auto" in kinds and "manual" in kinds

    def test_pause_stops_auto(self):
        reports = []
        wd = ProgressWatchdog(interval=0.05, report=lambda k, t: reports.append(k))
        wd.pause()
        wd.start()
        time.sleep(0.3)
        wd.shutdown()
        assert "auto" not in set(reports)


class TestCoordinator:
    def test_interruption_roundtrip(self, kv_server):
        store = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        coord = RestartCoordinator(store, world_size=4)
        assert not coord.is_interrupted(0)
        coord.record_interruption(0, 2, Interruption.SOFT_TIMEOUT, "slow")
        assert coord.is_interrupted(0)
        assert coord.wait_interrupted(0, timeout=1.0)
        recs = coord.get_interruptions(0)
        assert len(recs) == 1 and recs[0].rank == 2
        assert not coord.is_interrupted(1)  # per-iteration scoping
        store.close()

    def test_on_behalf_barrier_idempotent(self, kv_server):
        store = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        coord = RestartCoordinator(store, world_size=2)
        # Two watchers complete for the same dead rank; then the survivor joins.
        coord.complete_barriers_for(0, 1)
        coord.complete_barriers_for(0, 1)  # idempotent — no overflow
        coord.join_iteration_barrier(0, 0, timeout=5.0)
        coord.join_completion_barrier(0, 0, timeout=5.0)
        store.close()

    def test_terminated_accumulates(self, kv_server):
        store = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        coord = RestartCoordinator(store, world_size=4)
        coord.record_terminated([1])
        coord.record_terminated([3])
        assert coord.terminated_ranks() == frozenset({1, 3})
        store.close()


class TestCompletionAndGC:
    def test_completion_barrier_yields_to_interruption(self, kv_server):
        """A completer must abandon the completion wait as soon as a peer's fault is
        on record — not after the full barrier timeout (that stall would outlast the
        faulted rank's resync window and eject a healthy rank)."""
        from tpu_resiliency.inprocess.coordination import CompletionInterrupted

        store = CoordStore("127.0.0.1", kv_server.port)
        coord = RestartCoordinator(store, world_size=2)
        t0 = time.monotonic()

        def fault_soon():
            time.sleep(0.3)
            coord.record_interruption(0, 1, Interruption.EXCEPTION, "peer boom")

        threading.Thread(target=fault_soon, daemon=True).start()
        with pytest.raises(CompletionInterrupted):
            coord.join_completion_barrier(0, rank=0, timeout=60.0, poll_interval=0.05)
        assert time.monotonic() - t0 < 5.0
        store.close()

    def test_completion_barrier_releases(self, kv_server):
        store = CoordStore("127.0.0.1", kv_server.port)
        coord = RestartCoordinator(store, world_size=2)
        done = []

        def other():
            c2 = RestartCoordinator(CoordStore("127.0.0.1", kv_server.port), 2)
            c2.join_completion_barrier(0, rank=1, timeout=10.0, poll_interval=0.05)
            done.append(1)

        t = threading.Thread(target=other, daemon=True)
        t.start()
        coord.join_completion_barrier(0, rank=0, timeout=10.0, poll_interval=0.05)
        t.join(10.0)
        assert done == [1]
        store.close()

    def test_cleanup_iteration_reclaims_round_state(self, kv_server):
        store = CoordStore("127.0.0.1", kv_server.port)
        coord = RestartCoordinator(store, world_size=2)
        coord.record_interruption(3, 0, Interruption.SOFT_TIMEOUT, "slow")
        coord.complete_barriers_for(3, 0)
        coord.record_terminated([1])
        coord.cleanup_iteration(3)
        assert coord.get_interruptions(3) == []
        assert not coord.is_interrupted(3)
        assert store.barrier_status("barrier/iteration/3") is None
        # Cross-iteration state survives GC.
        assert coord.terminated_ranks() == frozenset({1})
        store.close()
