"""The health-vector decisions loop, closed end to end with REAL measurements
(BASELINE target 5): a slow-but-alive rank's section timings flow through the
Detector's scored report → ``HealthVectorPolicy`` debounce → the coordination
store's degraded set → ``DemoteDegraded`` benches the rank as a spare at the next
restart round — no hand-planted degraded state anywhere."""

import multiprocessing as mp
import os
import socket
import time


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


STEPS_PER_ROUND = 6
MAX_REPORT_ROUNDS = 8  # stop as soon as the demotion is agreed (patience 2)


def body(rank, world, port, q):
    # Spawned children do not run conftest: force the CPU platform before any
    # backend use, or the site-installed TPU plugin routes all three children's
    # scoring through the single real TPU tunnel (serialized, tens of seconds of
    # stall — enough to trip the progress watchdog on a healthy rank).
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ.update(
        RANK=str(rank),
        WORLD_SIZE=str(world),
        TPU_RESILIENCY_STORE_PORT=str(port),
        TPU_RESILIENCY_STORE_HOST="127.0.0.1",
    )
    from tpu_resiliency.inprocess.rank_assignment import DemoteDegraded
    from tpu_resiliency.inprocess.wrap import CallWrapper, Wrapper
    from tpu_resiliency.platform.store import CoordStore
    from tpu_resiliency.telemetry.detector import Detector
    from tpu_resiliency.telemetry.policy import HealthVectorPolicy

    @Wrapper(
        rank_assignment=DemoteDegraded(max_active_world_size=2),
        monitor_interval=0.05,
        last_call_wait=0.1,
        soft_timeout=45.0,
        hard_timeout=90.0,
        heartbeat_interval=0.2,
        # Hang detection is NOT this test's subject (measured slowness → scored
        # demotion is); a tight heartbeat window false-positives under CI load
        # and ejects a healthy-but-starved rank mid-completion.
        heartbeat_timeout=60.0,
        barrier_timeout=90.0,
        completion_timeout=90.0,
    )
    def train(call: CallWrapper):
        fs = call.frozen_state
        if fs.iteration >= 1:
            # Post-demotion round: actives finish; the demoted rank idles in
            # reserve inside the wrapper and returns None.
            return ("ok", fs.iteration, fs.mode.name, fs.active_world_size)

        # Telemetry spans the ACTIVE world (the spare's fn never runs): with the
        # active world capped at 2, iteration 0 actives are ranks {0, 1}.
        me, active_world = fs.active_rank, fs.active_world_size
        store = CoordStore("127.0.0.1", int(os.environ["TPU_RESILIENCY_STORE_PORT"]))
        policy = HealthVectorPolicy(
            patience=2,
            recovery=100,
            sinks=[lambda decision: call.coord.set_degraded(decision.degraded)],
        )
        Detector.initialize(
            rank=me,
            world_size=active_world,
            store=store.scoped("telemetry/"),
            gather_on_rank0=False,
            report_time_interval=3600.0,
        )
        try:
            for _ in range(MAX_REPORT_ROUNDS):
                for _ in range(STEPS_PER_ROUND):
                    with Detector.detection_section("step", profile_device=False):
                        # Rank 1 is genuinely 10x slower, measured for real (wide
                        # margin: host scheduling noise under CI load must not
                        # compress the ratio past the 0.75 threshold).
                        time.sleep(0.080 if rank == 1 else 0.008)
                report = Detector.generate_report()  # collective (store barrier)
                decision = policy.observe(report)
                # Same global report on every rank -> same decision -> all ranks
                # break on the same round (generate_report stays collective).
                if 1 in decision.degraded:
                    break
            assert 1 in decision.degraded, decision
        finally:
            Detector.shutdown()
            store.close()
        if rank == 0:
            time.sleep(0.2)  # let peers reach their park loops
            raise RuntimeError("force the restart round that applies the demotion")
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            time.sleep(0.02)
        return ("parked-forever", fs.iteration, fs.mode.name, fs.active_world_size)

    q.put((rank, train()))


def test_measured_slowness_demotes_through_the_full_loop():
    world = 3
    port = free_port()
    # Children call into JAX (Detector scoring); the pytest parent has a live,
    # multithreaded JAX backend, so fork()ed children can inherit a held lock and
    # deadlock under suite load. Spawn starts them clean.
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=body, args=(r, world, port, q)) for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    deadline = time.monotonic() + 180
    try:
        while len(results) < world and time.monotonic() < deadline:
            try:
                r, payload = q.get(timeout=1.0)
                results[r] = payload
            except Exception:
                if all(not p.is_alive() for p in procs):
                    break
    finally:
        for p in procs:
            p.join(timeout=20.0)
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5.0)

    # The measured-slow rank was demoted: it spent iteration 1 in reserve (a
    # reserve rank's wrapper returns None), while the healthy pair ran active.
    assert results[1] is None, results
    assert results[0] == ("ok", 1, "ACTIVE", 2), results
    assert results[2] == ("ok", 1, "ACTIVE", 2), results
