"""Device-fault injection kinds driving the device detectors end to end
(VERDICT r3 item 9; reference analogue: GPU_ERROR / GPU_SLEEP in
``inprocess/tools/inject_fault.py:34-47``, which exist to test the device-health
detectors specifically):

- ``Fault.DEVICE_ERROR`` kills the XLA runtime (dead platform + dropped caches/
  backends): the liveness probe reports dead, ``JaxHealthCheck`` raises, and a
  faulted rank is EXCLUDED by the restart round's health chain rather than
  respun forever against a dead device.
- ``Fault.DEVICE_HANG`` parks the main thread in an uninterruptible device wait
  (compiled never-terminating ``while_loop``): async exceptions cannot land, so
  only the monitor process's hard-timeout ladder (progress stall → termination
  signal) gets the rank out; the survivor then shrinks the world.

Children are fresh interpreters: both faults wreck process-global jax state.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_children(child_src: str, world: int, args_fn, timeout: float = 180.0):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    import tempfile

    with tempfile.TemporaryDirectory(prefix="device-faults-") as tmp:
        script = os.path.join(tmp, "child.py")
        with open(script, "w") as f:
            f.write(child_src)
        procs = [
            subprocess.Popen(
                [sys.executable, script] + [str(a) for a in args_fn(r)],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
                cwd=tmp,
            )
            for r in range(world)
        ]
        outs = {}
        try:
            for r, p in enumerate(procs):
                out, err = p.communicate(timeout=timeout)
                outs[r] = (p.returncode, out, err)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    return outs


PRIMITIVES_CHILD = textwrap.dedent(
    """
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tpu_resiliency.inprocess.health_check import HealthCheckError, JaxHealthCheck
    from tpu_resiliency.inprocess.tools.inject_fault import (
        Fault,
        heal_device_error,
        inject_fault,
    )
    from tpu_resiliency.platform.device import device_liveness_probe

    assert device_liveness_probe(timeout=15.0), "device dead before injection"
    inject_fault(Fault.DEVICE_ERROR)
    assert not device_liveness_probe(timeout=15.0), "probe missed the dead runtime"
    try:
        JaxHealthCheck(timeout=5.0)(None)
        raise AssertionError("JaxHealthCheck passed on a dead runtime")
    except HealthCheckError:
        pass
    heal_device_error()
    assert device_liveness_probe(timeout=15.0), "heal did not restore the runtime"
    print("DEVICE-FAULT-PRIMITIVES OK")
    """
)


def test_device_error_primitives():
    """DEVICE_ERROR flips the liveness probe and JaxHealthCheck; heal restores."""
    outs = _run_children(PRIMITIVES_CHILD, 1, lambda r: [])
    rc, out, err = outs[0]
    assert rc == 0, f"child failed:\n{out}\n{err[-3000:]}"
    assert "DEVICE-FAULT-PRIMITIVES OK" in out


ERROR_LADDER_CHILD = textwrap.dedent(
    """
    import json, os, sys

    os.environ.update(
        RANK="0",
        WORLD_SIZE="1",
        TPU_RESILIENCY_STORE_HOST="127.0.0.1",
        TPU_RESILIENCY_STORE_PORT=sys.argv[1],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tpu_resiliency.inprocess import (
        CallWrapper,
        JaxHealthCheck,
        RetryController,
        Wrapper,
    )
    from tpu_resiliency.inprocess.health_check import HealthCheckError
    from tpu_resiliency.inprocess.tools.inject_fault import Fault, inject_fault

    attempts = []

    @Wrapper(
        initialize=RetryController(max_iterations=5),
        health_check=JaxHealthCheck(timeout=5.0),
        monitor_interval=0.05,
        last_call_wait=0.1,
        soft_timeout=10.0,
        hard_timeout=30.0,
        heartbeat_interval=0.2,
        heartbeat_timeout=10.0,
        barrier_timeout=30.0,
        completion_timeout=30.0,
    )
    def train(call: CallWrapper):
        attempts.append(call.iteration)
        if call.iteration == 0:
            inject_fault(Fault.DEVICE_ERROR)
        # The workload's own device use fails against the dead runtime.
        return float(jax.block_until_ready(jnp.ones((2,)).sum()))

    try:
        train()
        print("LADDER-RESULT " + json.dumps({"outcome": "completed (BAD)"}))
    except HealthCheckError as e:
        print(
            "LADDER-RESULT "
            + json.dumps({"outcome": "health_excluded", "attempts": attempts})
        )
    """
)


def test_device_error_excludes_rank_via_health_check():
    """Full escalation: device dies mid-iteration → fn fault → restart round's
    JaxHealthCheck finds the runtime dead → rank excluded (HealthCheckError),
    NOT respun forever against a dead device."""
    outs = _run_children(ERROR_LADDER_CHILD, 1, lambda r: [free_port()])
    rc, out, err = outs[0]
    line = [ln for ln in out.splitlines() if ln.startswith("LADDER-RESULT ")]
    assert line, f"no result line:\n{out}\n{err[-3000:]}"
    payload = json.loads(line[0][len("LADDER-RESULT "):])
    assert payload["outcome"] == "health_excluded", payload
    # One real attempt; the health check stopped iteration 1 from re-entering.
    assert payload["attempts"] == [0], payload


HANG_CHILD = textwrap.dedent(
    """
    import json, os, sys, time

    rank = sys.argv[1]
    os.environ.update(
        RANK=rank,
        WORLD_SIZE="2",
        TPU_RESILIENCY_STORE_HOST="127.0.0.1",
        TPU_RESILIENCY_STORE_PORT=sys.argv[2],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tpu_resiliency.inprocess import CallWrapper, RetryController, Wrapper
    from tpu_resiliency.inprocess.tools.inject_fault import Fault, inject_fault

    @Wrapper(
        initialize=RetryController(max_iterations=4),
        monitor_interval=0.1,
        last_call_wait=0.1,
        soft_timeout=1.5,
        hard_timeout=4.0,
        heartbeat_interval=0.2,
        heartbeat_timeout=15.0,
        barrier_timeout=60.0,
        completion_timeout=60.0,
    )
    def train(call: CallWrapper):
        fs = call.frozen_state
        for _ in range(3):
            jax.block_until_ready(jnp.ones((2,)) + 1)
            call.ping()
        if call.iteration == 0 and fs.initial_rank == 1:
            inject_fault(Fault.DEVICE_HANG)  # never returns: pings stop here
        if call.iteration == 0:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                time.sleep(0.05)
            raise TimeoutError("restart never delivered")
        return {"iteration": call.iteration, "world": fs.active_world_size}

    result = train()
    print("HANG-RESULT " + json.dumps({"rank": rank, "result": result}), flush=True)
    """
)


def test_device_hang_killed_by_monitor_hard_timeout():
    """A rank wedged in an uninterruptible device wait stops reporting progress;
    its monitor PROCESS escalates (soft → hard → termination signal), and the
    survivor re-enters at world 1 — the only ladder that works when async
    exceptions cannot be delivered."""
    port = free_port()
    outs = _run_children(HANG_CHILD, 2, lambda r: [r, port], timeout=240.0)
    rc0, out0, err0 = outs[0]
    rc1, out1, err1 = outs[1]
    # The hung rank was killed by a signal (SIGTERM by default), not a clean exit.
    assert rc1 != 0, f"hung rank exited cleanly:\n{out1}\n{err1[-2000:]}"
    assert "HANG-RESULT" not in out1
    assert rc0 == 0, f"survivor failed:\n{out0}\n{err0[-3000:]}"
    line = [ln for ln in out0.splitlines() if ln.startswith("HANG-RESULT ")][0]
    payload = json.loads(line[len("HANG-RESULT "):])
    assert payload["result"] == {"iteration": 1, "world": 1}, payload
