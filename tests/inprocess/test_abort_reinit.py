"""The jax.distributed abort -> re-initialize path, driven end-to-end across real
processes (SURVEY §7's named hard part; reference analogue: NCCL communicator abort
+ process-group destroy in ``inprocess/abort.py:58-105``, which is THE load-bearing
abort there).

Two scenarios, both through the full Wrapper restart loop with
``AbortJaxDistributed`` in the abort chain:

- **exception fault**: rank 1 raises after finishing its collective steps; both
  ranks restart, shut down the world-2 distributed runtime, and re-initialize a
  fresh coordinator (new port) at iteration 1 — world size unchanged, runtime
  instance provably new.
- **rank death**: rank 1 dies; the survivor restarts alone, re-initializes with
  ``num_processes=1``, and completes — the world SHRANK across the re-init.

Faults land between steps (each rank finishes its per-round collectives before
faulting/parking): a collective already in flight against a dead peer blocks in
Gloo indefinitely, and that case belongs to the monitor process's hard-timeout
kill ladder, not the in-process layer (see ``platform/distributed.py`` docstring).

Children are fresh interpreters (subprocess, not fork): jax.distributed owns
process-global runtime state that must not leak in from a parent.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


CHILD = textwrap.dedent(
    """
    import json, os, sys, time

    rank = int(sys.argv[1])
    world = int(sys.argv[2])
    store_port = sys.argv[3]
    fault = sys.argv[4]                      # "raise" | "die"
    jd_ports = [int(p) for p in sys.argv[5].split(",")]  # coordinator port per iteration

    os.environ.update(
        RANK=str(rank),
        WORLD_SIZE=str(world),
        TPU_RESILIENCY_STORE_HOST="127.0.0.1",
        TPU_RESILIENCY_STORE_PORT=store_port,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from tpu_resiliency.inprocess import (
        AbortCompilationCache,
        AbortJaxDistributed,
        CallWrapper,
        Compose,
        RetryController,
        Wrapper,
    )
    from tpu_resiliency.platform import distributed as jdist

    @Wrapper(
        initialize=RetryController(max_iterations=4),
        abort=Compose(AbortJaxDistributed(), AbortCompilationCache()),
        monitor_interval=0.05,
        last_call_wait=0.1,
        soft_timeout=10.0,
        hard_timeout=30.0,
        heartbeat_interval=0.2,
        heartbeat_timeout=10.0,
        barrier_timeout=60.0,
        completion_timeout=60.0,
    )
    def train(call: CallWrapper):
        fs = call.frozen_state
        w, r = fs.active_world_size, fs.active_rank
        assert not jdist.client_active(), "abort left a stale distributed client"
        jdist.initialize(
            f"127.0.0.1:{jd_ports[call.iteration]}",
            num_processes=w,
            process_id=r,
            heartbeat_timeout=10.0,
        )
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = np.array(jax.devices())
        n_local = len(jax.local_devices())
        mesh = Mesh(devs, ("dp",))
        sh = NamedSharding(mesh, P("dp"))
        # Each process contributes rows valued initial_rank+1: the global sum
        # proves the collective crossed every live process.
        x = jax.make_array_from_process_local_data(
            sh, np.full((n_local,), fs.initial_rank + 1, np.float32)
        )
        total = None
        for _ in range(3):
            total = float(jax.jit(lambda a: a.sum())(x))
            call.ping()
        if call.iteration == 0 and fs.initial_rank == 1:
            if fault == "die":
                os._exit(9)
            raise RuntimeError("injected fault after round")
        if call.iteration == 0:
            # Park in Python until the restart exception lands (no collectives
            # with a possibly-dead peer).
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                time.sleep(0.05)
            raise TimeoutError("restart never delivered")
        # Orderly end-of-job teardown (coordinator last) so no rank's atexit
        # client disconnect races the coordinator service's death.
        jdist.shutdown_ordered(call.coord.store, r, w, iteration=call.iteration)
        return {
            "iteration": call.iteration,
            "world": w,
            "rank": r,
            "initial_rank": fs.initial_rank,
            "sum": total,
            "n_devices": len(devs),
        }

    result = train()
    print("ABORT-REINIT " + json.dumps({"rank": rank, "result": result}), flush=True)
    """
)


def _run(fault: str, timeout: float = 240.0):
    store_port = free_port()
    # One coordinator port per possible iteration (max_iterations=4 in the child).
    jd_ports = ",".join(str(free_port()) for _ in range(4))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    import tempfile

    with tempfile.TemporaryDirectory(prefix="abort-reinit-") as tmp:
        script = os.path.join(tmp, "child.py")
        with open(script, "w") as f:
            f.write(CHILD)
        procs = [
            subprocess.Popen(
                [sys.executable, script, str(r), "2", str(store_port), fault, jd_ports],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
                cwd=tmp,
            )
            for r in range(2)
        ]
        outs = {}
        try:
            for r, p in enumerate(procs):
                out, err = p.communicate(timeout=timeout)
                outs[r] = (p.returncode, out, err)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    results = {}
    for r, (rc, out, err) in outs.items():
        for ln in out.splitlines():
            if ln.startswith("ABORT-REINIT "):
                payload = json.loads(ln[len("ABORT-REINIT "):])
                results[payload["rank"]] = payload["result"]
    return outs, results


def test_exception_fault_reinitializes_new_coordinator():
    outs, results = _run("raise")
    rc0, out0, err0 = outs[0]
    rc1, out1, err1 = outs[1]
    assert rc0 == 0, f"rank0 failed:\n{out0}\n{err0[-3000:]}"
    assert rc1 == 0, f"rank1 failed:\n{out1}\n{err1[-3000:]}"
    # Both ranks re-entered at iteration 1, rebuilt a WORLD-2 runtime on the new
    # coordinator port, and the cross-process collective produced the same global
    # sum as before the fault: 2 procs x 2 devices x (1, 1, 2, 2) = 6.
    for r in (0, 1):
        assert results[r]["iteration"] == 1, results
        assert results[r]["world"] == 2, results
        assert results[r]["n_devices"] == 4, results
        assert results[r]["sum"] == 6.0, results


def test_rank_death_shrinks_world_across_reinit():
    outs, results = _run("die")
    rc0, out0, err0 = outs[0]
    assert rc0 == 0, f"rank0 failed:\n{out0}\n{err0[-3000:]}"
    assert outs[1][0] == 9  # the injected death
    # The survivor re-initialized alone: num_processes=1, only its own 2 local
    # devices, collective sum = its own contribution (1+1).
    assert set(results) == {0}, results
    assert results[0]["iteration"] == 1, results
    assert results[0]["world"] == 1, results
    assert results[0]["rank"] == 0, results
    assert results[0]["n_devices"] == 2, results
    assert results[0]["sum"] == 2.0, results
