"""Host staging buffer pool: signature keying, double-buffer recycling, and the
zero-large-allocation steady state the pipelined save engine rides on."""

import threading
import time

import numpy as np
import pytest

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.staging import (
    HostStagingPool,
    leaf_signature,
)
from tpu_resiliency.checkpoint.state_dict import leaf_specs
from tpu_resiliency.exceptions import CheckpointError


def specs_for(*arrays):
    return leaf_specs(list(arrays))


class TestSignature:
    def test_signature_covers_shape_and_dtype(self):
        a = specs_for(np.zeros((4, 4), np.float32), np.zeros(3, np.int32))
        b = specs_for(np.zeros((4, 4), np.float32), np.zeros(3, np.int32))
        c = specs_for(np.zeros((4, 4), np.float64), np.zeros(3, np.int32))
        assert leaf_signature(a) == leaf_signature(b)
        assert leaf_signature(a) != leaf_signature(c)


class TestPoolAccounting:
    def test_first_acquire_is_miss_then_hits(self):
        pool = HostStagingPool(depth=2)
        specs = specs_for(np.zeros((8, 8), np.float32))
        lease = pool.acquire(specs)
        assert (pool.hits, pool.misses) == (0, 1)
        lease.release()
        lease2 = pool.acquire(specs)
        assert (pool.hits, pool.misses) == (1, 1)
        # Leased accounting covers payload + alignment padding.
        assert pool.stats()["in_use_bytes"] >= lease2.nbytes
        lease2.release()
        assert pool.stats()["in_use_bytes"] == 0

    def test_steady_state_never_allocates(self):
        """The acceptance check: after warmup, saves of the same tree signature
        are pure pool hits — the pool's total byte footprint stops growing."""
        pool = HostStagingPool(depth=2)
        specs = specs_for(np.zeros((1 << 18,), np.float32), np.zeros(7, np.int64))
        # Warmup: both double-buffer slots get allocated.
        a, b = pool.acquire(specs), pool.acquire(specs)
        a.release(), b.release()
        allocated = pool.stats()["total_bytes"]
        misses = pool.misses
        for _ in range(6):
            lease = pool.acquire(specs)
            lease.release()
        assert pool.misses == misses, "steady state hit an allocation"
        assert pool.stats()["total_bytes"] == allocated

    def test_distinct_signatures_pool_separately(self):
        pool = HostStagingPool(depth=1)
        s1 = specs_for(np.zeros(4, np.float32))
        s2 = specs_for(np.zeros(8, np.float32))
        l1, l2 = pool.acquire(s1), pool.acquire(s2)
        assert pool.misses == 2
        l1.release(), l2.release()

    def test_depth_exhaustion_blocks_until_release(self):
        pool = HostStagingPool(depth=1)
        specs = specs_for(np.zeros(16, np.float32))
        lease = pool.acquire(specs)
        got = []

        def taker():
            got.append(pool.acquire(specs))

        t = threading.Thread(target=taker, daemon=True)
        t.start()
        time.sleep(0.1)
        assert not got, "third lease must wait for a release"
        lease.release()
        t.join(timeout=5.0)
        assert got and got[0].nbytes == lease.nbytes
        got[0].release()

    def test_depth_exhaustion_times_out(self):
        pool = HostStagingPool(depth=1)
        specs = specs_for(np.zeros(16, np.float32))
        pool.acquire(specs)  # never released
        with pytest.raises(CheckpointError, match="still leased"):
            pool.acquire(specs, timeout=0.1)

    def test_release_is_idempotent(self):
        pool = HostStagingPool(depth=2)
        lease = pool.acquire(specs_for(np.zeros(4, np.float32)))
        lease.release()
        lease.release()
        assert pool.stats()["in_use_bytes"] == 0

    def test_trim_drops_idle_buffers(self):
        pool = HostStagingPool(depth=2)
        specs = specs_for(np.zeros((64,), np.float32))
        pool.acquire(specs).release()
        assert pool.stats()["total_bytes"] > 0
        freed = pool.trim()
        assert freed > 0 and pool.stats()["total_bytes"] == 0
        # The signature can allocate again after a trim.
        pool.acquire(specs).release()


class TestLeaseViews:
    def test_fill_round_trips_through_container(self, tmp_path):
        pool = HostStagingPool()
        arrays = [
            np.arange(12, dtype=np.float32).reshape(3, 4),
            np.arange(5, dtype=np.int64),
        ]
        lease = pool.acquire(specs_for(*arrays))
        for i, a in enumerate(arrays):
            staged = lease.fill(i, a)
            np.testing.assert_array_equal(staged, a)
        # Staged views feed the zero-copy container path unchanged.
        prefix, views = ckpt_format.serialize_parts(b"h", lease.views)
        path = str(tmp_path / "staged.ckpt")
        ckpt_format.write_parts(path, [prefix, *views])
        hollow, tensors, _ = ckpt_format.read_payload(path)
        assert hollow == b"h"
        for got, want in zip(tensors, arrays):
            np.testing.assert_array_equal(got, want)
        lease.release()

    def test_fill_bfloat16(self):
        import jax.numpy as jnp

        arr = np.asarray(jnp.astype(jnp.arange(8), jnp.bfloat16))
        pool = HostStagingPool()
        lease = pool.acquire(leaf_specs([arr]))
        staged = lease.fill(0, arr)
        np.testing.assert_array_equal(
            np.asarray(staged, np.float32), np.arange(8, dtype=np.float32)
        )
        lease.release()

    def test_fill_rejects_size_mismatch(self):
        pool = HostStagingPool()
        lease = pool.acquire(specs_for(np.zeros(8, np.float32)))
        with pytest.raises(CheckpointError, match="signature says"):
            lease.fill(0, np.zeros(9, np.float32))
        lease.release()

    def test_views_are_aligned(self):
        pool = HostStagingPool()
        # Odd-sized first leaf must not misalign the second.
        lease = pool.acquire(specs_for(np.zeros(3, np.int8), np.zeros(4, np.float64)))
        for v in lease.views:
            addr = v.__array_interface__["data"][0]
            assert addr % 64 == 0
        lease.release()
