"""Checkpoint integrity plane: v2 container checksums, mixed-version loads,
quarantine, and the load() recovery ladder (local → peer retrieve → group
fallback)."""

import concurrent.futures as cf
import os
import pickle
import struct

import numpy as np
import pytest

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.local_manager import CkptID, LocalCheckpointManager
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform.store import CoordStore
from tpu_resiliency.utils import events


def run_ranks(world, fn, timeout=60.0):
    with cf.ThreadPoolExecutor(max_workers=world) as pool:
        futures = [pool.submit(fn, r) for r in range(world)]
        return [f.result(timeout=timeout) for f in futures]


@pytest.fixture
def make_store(kv_server):
    stores = []

    def factory():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
        stores.append(s)
        return s

    yield factory
    for s in stores:
        s.close()


@pytest.fixture
def sink():
    seen = []
    events.add_sink(seen.append)
    yield seen
    events.remove_sink(seen.append)


def _arrays():
    return [np.arange(256, dtype=np.float32), np.ones((3, 5), dtype=np.int32)]


def _flip(path, offset, mask=0x10):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ mask]))


def _write_v1(path, hollow=b"old", meta=None):
    """Hand-built TPURES01 container — what pre-integrity code wrote."""
    arr = np.arange(16, dtype=np.float32)
    header = pickle.dumps(
        {
            "hollow": hollow,
            "leaves": [{"shape": (16,), "dtype": "float32", "nbytes": 64}],
            "meta": meta or {},
        },
        protocol=pickle.HIGHEST_PROTOCOL,
    )
    with open(path, "wb") as f:
        f.write(ckpt_format.MAGIC_V1 + struct.pack("<Q", len(header)) + header)
        f.write(arr.tobytes())
    return arr


class TestFormatV2:
    def test_roundtrip_verifies_and_header_carries_crcs(self, tmp_path):
        path = str(tmp_path / "a.ckpt")
        written = ckpt_format.write_payload(path, b"hollow", _arrays(), meta={"it": 7})
        assert written == os.path.getsize(path)
        header = ckpt_format.read_header(path)
        assert all("crc32c" in s for s in header["leaves"])
        hollow, tensors, meta = ckpt_format.read_payload(path)
        assert hollow == b"hollow" and meta == {"it": 7}
        np.testing.assert_array_equal(tensors[0], _arrays()[0])
        assert ckpt_format.verify_file(path)[0] == "ok"

    def test_payload_bitflip_detected(self, tmp_path):
        path = str(tmp_path / "a.ckpt")
        ckpt_format.write_payload(path, b"hollow", _arrays())
        _flip(path, os.path.getsize(path) - 100)  # inside the payload
        assert ckpt_format.verify_file(path)[0] == "corrupt"
        with pytest.raises(CheckpointError, match="checksum mismatch"):
            ckpt_format.read_payload(path)

    def test_header_corruption_detected(self, tmp_path):
        path = str(tmp_path / "a.ckpt")
        ckpt_format.write_payload(path, b"hollow", _arrays())
        _flip(path, len(ckpt_format.MAGIC) + 12)  # inside the header pickle
        assert ckpt_format.verify_file(path)[0] == "corrupt"
        with pytest.raises(CheckpointError):
            ckpt_format.read_payload(path)

    def test_truncation_rejected_cleanly(self, tmp_path):
        """The satellite size-truncation check: a torn v2 file fails with a
        classified CheckpointError naming the size delta, not a pickle/struct
        leak or a silently short tree."""
        path = str(tmp_path / "a.ckpt")
        ckpt_format.write_payload(path, b"hollow", _arrays())
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 7)
        status, detail = ckpt_format.verify_file(path)
        assert status == "corrupt" and "size mismatch" in detail
        with pytest.raises(CheckpointError, match="size mismatch"):
            ckpt_format.read_payload(path)

    def test_striped_write_is_byte_identical_and_verifies(self, tmp_path):
        p1, p4 = str(tmp_path / "s1.ckpt"), str(tmp_path / "s4.ckpt")
        ckpt_format.write_payload(p1, b"h", _arrays(), stripes=1)
        ckpt_format.write_payload(p4, b"h", _arrays(), stripes=4)
        assert open(p1, "rb").read() == open(p4, "rb").read()
        assert ckpt_format.verify_file(p4)[0] == "ok"

    def test_v1_container_loads_with_unverified_event(self, tmp_path, sink):
        """Mixed-version load: a container written by pre-integrity code still
        loads under new code — verification skipped, ckpt_unverified emitted."""
        path = str(tmp_path / "v1.ckpt")
        arr = _write_v1(path, meta={"it": 3})
        hollow, tensors, meta = ckpt_format.read_payload(path)
        assert hollow == b"old" and meta == {"it": 3}
        np.testing.assert_array_equal(tensors[0], arr)
        assert any(e.kind == "ckpt_unverified" for e in sink)
        assert ckpt_format.verify_file(path)[0] == "unverified"

    def test_serialize_parts_carries_trailer_and_verifies(self):
        prefix, views = ckpt_format.serialize_parts(b"h", _arrays(), meta={"k": 1})
        joined = b"".join([prefix, *[bytes(v) for v in views]])
        assert ckpt_format.verify_container(joined) is True
        blob = bytearray(joined)
        blob[len(prefix) + 9] ^= 0x40
        with pytest.raises(CheckpointError, match="checksum mismatch"):
            ckpt_format.verify_container(blob)
        with pytest.raises(CheckpointError, match="checksum mismatch"):
            ckpt_format.deserialize_from_buffer(blob)

    def test_verify_container_passes_non_containers_through(self):
        assert ckpt_format.verify_container(b"raw-blob-not-a-container") is False
        assert ckpt_format.verify_container(b"") is False

    def test_streamed_container_with_checksummer_verifies(self, tmp_path):
        """The pipelined-save shape: header_prefix from specs, leaves streamed
        one at a time through a Checksummer, trailer last."""
        path = str(tmp_path / "stream.ckpt")
        arrays = _arrays()
        specs = [
            {"shape": a.shape, "dtype": a.dtype.name, "nbytes": a.nbytes}
            for a in arrays
        ]
        prefix = ckpt_format.header_prefix(b"h", specs, {"it": 5})

        def chunks():
            ck = ckpt_format.Checksummer(prefix)
            yield prefix
            for a in arrays:
                view = ckpt_format._raw_view(a)
                ck.add_leaf(view)
                yield view
            yield ck.trailer()

        written = ckpt_format.write_stream(path, chunks())
        assert written == os.path.getsize(path)
        assert ckpt_format.verify_file(path)[0] == "ok"
        hollow, tensors, meta = ckpt_format.read_payload(path)
        assert meta == {"it": 5}
        np.testing.assert_array_equal(tensors[1], arrays[1])

    def test_zero_leaf_container(self, tmp_path):
        path = str(tmp_path / "z.ckpt")
        ckpt_format.write_payload(path, b"skeleton-only", [])
        assert ckpt_format.verify_file(path)[0] == "ok"
        hollow, tensors, _ = ckpt_format.read_payload(path)
        assert hollow == b"skeleton-only" and tensors == []


def _tree(rank, it):
    return {"w": np.full((512,), rank * 10.0 + it, np.float32), "step": it}


def _mgr(make_store, tmp_path, rank, world, gen, keep=2):
    comm = StoreComm(
        make_store(), rank, list(range(world)), timeout=30.0, generation=gen
    )
    ex = PeerExchange(make_store(), rank, timeout=30.0)
    ex.start()
    strat = CliqueReplicationStrategy(
        comm, ex, replication_jump=1, replication_factor=world
    )
    mgr = LocalCheckpointManager(
        str(tmp_path), rank=rank, comm=comm, replication=strat, keep=keep
    )
    return mgr, ex


def _shard_path(tmp_path, holder, it, owner):
    return os.path.join(
        str(tmp_path), "s0", f"r{holder}", CkptID(it, owner).filename()
    )


class TestRecoveryLadder:
    def _save_two_iters(self, make_store, tmp_path, world=2):
        def body(rank):
            mgr, ex = _mgr(make_store, tmp_path, rank, world, gen=0)
            try:
                mgr.save(1, PyTreeStateDict(_tree(rank, 1)), is_async=False)
                mgr.save(2, PyTreeStateDict(_tree(rank, 2)), is_async=False)
                mgr.close()
            finally:
                ex.close()

        run_ranks(world, body, timeout=120.0)

    def test_corrupt_shard_recovers_from_peer_byte_identical(
        self, make_store, tmp_path, sink
    ):
        world = 2
        self._save_two_iters(make_store, tmp_path)
        _flip(_shard_path(tmp_path, 0, 2, 0), 150)

        def body(rank):
            mgr, ex = _mgr(make_store, tmp_path, rank, world, gen=1)
            try:
                hollow, tensors, meta = mgr.load()
                mgr.close()
                return meta["iteration"], np.asarray(tensors[0]).copy()
            finally:
                ex.close()

        results = run_ranks(world, body, timeout=120.0)
        for rank, (it, w) in enumerate(results):
            assert it == 2
            np.testing.assert_array_equal(
                w, np.full((512,), rank * 10.0 + 2, np.float32)
            )
        # Quarantined for forensics + recovered copy re-persisted and valid.
        rdir = os.path.join(str(tmp_path), "s0", "r0")
        assert any(".corrupt" in n for n in os.listdir(rdir))
        assert ckpt_format.verify_file(_shard_path(tmp_path, 0, 2, 0))[0] == "ok"
        assert any(e.kind == "ckpt_quarantined" for e in sink)

    def test_replica_also_corrupt_falls_back_to_older_iteration(
        self, make_store, tmp_path, sink
    ):
        world = 2
        self._save_two_iters(make_store, tmp_path)
        _flip(_shard_path(tmp_path, 0, 2, 0), 150)  # rank 0's own copy
        _flip(_shard_path(tmp_path, 1, 2, 0), 150)  # the clique mirror

        def body(rank):
            mgr, ex = _mgr(make_store, tmp_path, rank, world, gen=1)
            try:
                hollow, tensors, meta = mgr.load()
                mgr.close()
                return meta["iteration"], np.asarray(tensors[0]).copy()
            finally:
                ex.close()

        results = run_ranks(world, body, timeout=120.0)
        # ALL ranks converge on the same older iteration — the StoreComm
        # agreement round, not per-rank improvisation.
        for rank, (it, w) in enumerate(results):
            assert it == 1, f"rank {rank} resumed from {it}"
            np.testing.assert_array_equal(
                w, np.full((512,), rank * 10.0 + 1, np.float32)
            )
        assert any(e.kind == "ckpt_fallback" for e in sink)
        assert any(
            e.kind == "ckpt_integrity_failure" for e in sink
        ), "verify-on-receive never fired for the corrupt mirror"

    def test_single_rank_falls_back_locally(self, tmp_path, sink):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0, keep=2)
        mgr.save(1, PyTreeStateDict(_tree(0, 1)), is_async=False)
        mgr.save(2, PyTreeStateDict(_tree(0, 2)), is_async=False)
        _flip(_shard_path(tmp_path, 0, 2, 0), 150)
        hollow, tensors, meta = mgr.load()
        assert meta["iteration"] == 1
        np.testing.assert_array_equal(
            np.asarray(tensors[0]), np.full((512,), 1.0, np.float32)
        )
        mgr.close()

    def test_single_rank_all_corrupt_raises_checkpoint_error(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0, keep=2)
        mgr.save(1, PyTreeStateDict(_tree(0, 1)), is_async=False)
        mgr.save(2, PyTreeStateDict(_tree(0, 2)), is_async=False)
        _flip(_shard_path(tmp_path, 0, 1, 0), 150)
        _flip(_shard_path(tmp_path, 0, 2, 0), 150)
        with pytest.raises(CheckpointError, match="no intact checkpoint"):
            mgr.load()
        mgr.close()

    def test_pipelined_save_produces_verifiable_container(self, tmp_path):
        """The leaf-streaming save path (thread caller, async) must emit the
        same verifiable v2 container as the materialized path."""
        mgr = LocalCheckpointManager(str(tmp_path), rank=0)
        assert mgr.pipelined
        mgr.save(4, PyTreeStateDict(_tree(0, 4)), is_async=True)
        mgr.maybe_finalize(blocking=True)
        path = _shard_path(tmp_path, 0, 4, 0)
        assert ckpt_format.verify_file(path)[0] == "ok"
        hollow, tensors, meta = mgr.load(4)
        assert meta["iteration"] == 4
        mgr.close()


class TestQuarantineHousekeeping:
    def test_cleanup_sweeps_corrupt_keeping_newest_per_id(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0)
        mgr.save(1, PyTreeStateDict(_tree(0, 1)), is_async=False)
        mgr.close()
        rdir = os.path.join(str(tmp_path), "s0", "r0")
        base = CkptID(9, 0).filename()
        older = os.path.join(rdir, base + ".corrupt-1")
        newer = os.path.join(rdir, base + ".corrupt-2")
        other = os.path.join(rdir, CkptID(8, 0).filename() + ".corrupt-1")
        for i, p in enumerate((older, newer, other)):
            with open(p, "wb") as f:
                f.write(b"forensics")
            os.utime(p, (1000.0 + i, 1000.0 + i))
        mgr2 = LocalCheckpointManager(str(tmp_path), rank=0)
        names = set(os.listdir(rdir))
        assert os.path.basename(newer) in names
        assert os.path.basename(older) not in names
        assert os.path.basename(other) in names  # newest of ITS id
        mgr2.close()

    def test_quarantined_files_never_count_as_inventory(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0, keep=2)
        mgr.save(1, PyTreeStateDict(_tree(0, 1)), is_async=False)
        mgr.save(2, PyTreeStateDict(_tree(0, 2)), is_async=False)
        _flip(_shard_path(tmp_path, 0, 2, 0), 150)
        assert mgr.find_latest() == 2  # not yet discovered
        mgr.load()  # quarantines iter 2, falls back
        assert mgr.find_latest() == 1  # quarantine removed it from coverage
        mgr.close()


class TestUniformErrorClassification:
    def test_read_blob_missing_file_raises_checkpoint_error(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0)
        with pytest.raises(CheckpointError, match="unreadable shard"):
            mgr._read_blob(3, 0)
        mgr.close()

    def test_read_local_shard_wraps_all_damage_as_checkpoint_error(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0)
        path = _shard_path(tmp_path, 0, 5, 0)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(b"garbage that is not a container at all")
        with pytest.raises(CheckpointError, match="bad magic"):
            mgr._read_local_shard(5, 0)
        mgr.close()

    def test_corrupt_hollow_pickle_classified(self, tmp_path):
        """A v1 container whose hollow bytes are damaged must fail as
        CheckpointError naming the path (pickle raises half a dozen types)."""
        mgr = LocalCheckpointManager(str(tmp_path), rank=0)
        path = _shard_path(tmp_path, 0, 6, 0)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        header = pickle.dumps(
            {
                "hollow": b"\x80\x04corrupt-pickle",
                "leaves": [],
                "meta": {},
            }
        )
        with open(path, "wb") as f:
            f.write(ckpt_format.MAGIC_V1 + struct.pack("<Q", len(header)) + header)
        with pytest.raises(CheckpointError, match="corrupt hollow skeleton"):
            mgr._read_local_shard(6, 0)
        mgr.close()

    def test_out_of_range_placeholder_index_classified(self):
        from tpu_resiliency.checkpoint.state_dict import (
            PyTreeStateDict,
            TensorPlaceholder,
        )

        sd = PyTreeStateDict.__new__(PyTreeStateDict)
        sd._tree = {"w": TensorPlaceholder(shape=(4,), dtype="float32", index=7)}
        sd._hollow = True
        sd._tensors = None
        sd._shardings = None
        with pytest.raises(CheckpointError, match="out of range"):
            sd.insert_tensors([np.zeros(4, np.float32)])


class TestKeepRetention:
    def test_default_keeps_only_newest(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0)
        mgr.save(1, PyTreeStateDict(_tree(0, 1)), is_async=False)
        mgr.save(2, PyTreeStateDict(_tree(0, 2)), is_async=False)
        assert {i.iteration for i in mgr.local_ids()} == {2}
        mgr.close()

    def test_keep_two_retains_fallback_rung(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0, keep=2)
        for it in (1, 2, 3):
            mgr.save(it, PyTreeStateDict(_tree(0, it)), is_async=False)
        assert {i.iteration for i in mgr.local_ids()} == {2, 3}
        mgr.close()


class TestCkptInfoVerify:
    def test_verify_cli_flags_corruption(self, tmp_path, capsys):
        from tpu_resiliency.tools import ckpt_info

        mgr = LocalCheckpointManager(str(tmp_path), rank=0, keep=2)
        mgr.save(1, PyTreeStateDict(_tree(0, 1)), is_async=False)
        mgr.save(2, PyTreeStateDict(_tree(0, 2)), is_async=False)
        mgr.close()
        assert ckpt_info.main([str(tmp_path), "--verify"]) == 0
        out = capsys.readouterr().out
        assert "[OK" in out and "[CORRUPT" not in out and "0 corrupt" in out
        _flip(_shard_path(tmp_path, 0, 2, 0), 150)
        assert ckpt_info.main([str(tmp_path), "--verify"]) == 1
        out = capsys.readouterr().out
        assert "[CORRUPT" in out

    def test_scan_reports_quarantined_files(self, tmp_path, capsys):
        from tpu_resiliency.tools import ckpt_info

        mgr = LocalCheckpointManager(str(tmp_path), rank=0, keep=2)
        mgr.save(1, PyTreeStateDict(_tree(0, 1)), is_async=False)
        mgr.save(2, PyTreeStateDict(_tree(0, 2)), is_async=False)
        _flip(_shard_path(tmp_path, 0, 2, 0), 150)
        mgr.load()  # quarantines + falls back
        mgr.close()
        assert ckpt_info.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "quarantined corrupt container" in out
