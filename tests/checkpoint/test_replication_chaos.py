"""Replication data plane under injected network faults: per-peer send retry
with re-hello, graceful per-round degradation, byte-identical convergence."""

import concurrent.futures as cf
import threading

import pytest

from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform import chaos
from tpu_resiliency.platform.store import CoordStore
from tpu_resiliency.utils import events
from tpu_resiliency.utils.metrics import aggregate

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_plan():
    chaos.clear_plan()
    yield
    chaos.clear_plan()


def _payload(rank: int, n: int = 1 << 18) -> bytes:
    return bytes(bytearray((rank * 31 + i) % 251 for i in range(n)))


def _clique(kv_server, world, rank, stores, timeout=20.0, send_retries=3):
    def mk():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=60.0)
        stores.append(s)
        return s

    comm = StoreComm(mk(), rank, list(range(world)), timeout=60.0)
    ex = PeerExchange(mk(), rank, timeout=timeout, send_retries=send_retries)
    ex.start()
    return CliqueReplicationStrategy(
        comm, ex, replication_jump=1, replication_factor=world
    ), ex


def _run_world(kv_server, world, body, timeout=120.0):
    stores = []
    exchanges = []
    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            futs = [pool.submit(body, r, stores, exchanges) for r in range(world)]
            return [f.result(timeout=timeout) for f in futs]
    finally:
        for ex in exchanges:
            ex.close()
        for s in stores:
            s.close()


def test_send_retry_survives_reset_and_truncation(kv_server):
    """Sender-visible faults (reset, mid-frame truncation, refused dial) are
    retried with a fresh hello: every mirror lands byte-identical, nobody
    degrades."""
    chaos.install_plan(chaos.ChaosPlan.parse(
        "1:p2p.send.reset@at=2;p2p.send.truncate@at=6;p2p.connect.reset@at=4"
    ))
    world = 3

    def body(rank, stores, exchanges):
        strat, ex = _clique(kv_server, world, rank, stores)
        exchanges.append(ex)
        held = strat.replicate(_payload(rank))
        assert strat.last_degraded == set(), strat.last_degraded
        return rank, held

    for rank, held in _run_world(kv_server, world, body):
        assert set(held) == {0, 1, 2}
        for owner, blob in held.items():
            assert bytes(blob) == _payload(owner), (rank, owner)


def test_partitioned_peer_degrades_round_instead_of_failing_save(kv_server):
    """A peer whose dials are partitioned exhausts retries: the save completes
    with reduced redundancy, the peer lands in last_degraded, and one
    peer_degraded event (→ tpu_replication_peer_degraded_total) is emitted per
    degraded peer."""
    seen = []
    events.add_sink(seen.append)
    chaos.install_plan(chaos.ChaosPlan.parse("2:p2p.connect.partition@peer=2"))
    world = 3

    def body(rank, stores, exchanges):
        strat, ex = _clique(kv_server, world, rank, stores,
                            timeout=4.0, send_retries=2)
        exchanges.append(ex)
        held = strat.replicate(_payload(rank))  # must NOT raise
        return rank, held, strat.last_degraded

    try:
        out = sorted(_run_world(kv_server, world, body))
    finally:
        events.remove_sink(seen.append)
    r0, r1, r2 = out
    # Ranks 0/1 could not reach 2; their saves still completed.
    assert 2 in r0[2] and 2 in r1[2]
    assert _payload(1) == bytes(r0[1][1]), "surviving mirror corrupt"
    # Rank 2 received nothing (its peers' sends all failed) but saved its own.
    assert r2[2] == {0, 1}
    degraded_events = [e for e in seen if e.kind == "peer_degraded"]
    assert len(degraded_events) >= 2
    reg = aggregate([{"kind": e.kind, **e.payload} for e in degraded_events])
    assert ("tpu_replication_peer_degraded_total" in reg.to_prometheus())


def test_recv_side_truncation_degrades_not_raises(kv_server):
    """A mirror truncated on the RECEIVE side is silent loss from the sender's
    view — the receiver drops the frame and degrades that peer rather than
    failing its save."""
    world = 2

    def body(rank, stores, exchanges):
        strat, ex = _clique(kv_server, world, rank, stores,
                            timeout=3.0, send_retries=1)
        exchanges.append(ex)
        held = strat.replicate(_payload(rank, n=1 << 20))
        return rank, held, strat.last_degraded

    # recv ops: store-channel recvs don't count here (separate channel); p2p
    # recv indices cover hellos + payload reads across both ranks. Injecting a
    # couple of EOFs mid-window loses at most those frames.
    chaos.install_plan(chaos.ChaosPlan.parse("3:p2p.recv.truncate@at=6+7"))
    out = sorted(_run_world(kv_server, world, body))
    # Whatever was lost degraded gracefully; whatever arrived is intact.
    for rank, held, degraded in out:
        for owner, blob in held.items():
            if owner != rank:
                assert bytes(blob) == _payload(owner, n=1 << 20)
        peer = 1 - rank
        assert (peer in held) != (peer in degraded), (rank, held.keys(), degraded)


def test_send_retries_exhaustion_raises_checkpoint_error(kv_server):
    """Outside the replicate() degrade envelope, a hard-down peer surfaces
    CheckpointError after the bounded retries — not an OSError leak."""
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        stores.append(s)
        return s

    ex = PeerExchange(mk(), 0, timeout=2.0, send_retries=2)
    ex.start()
    ex2 = PeerExchange(mk(), 1, timeout=2.0, send_retries=2)
    ex2.start()
    try:
        chaos.install_plan(chaos.ChaosPlan.parse("4:p2p.connect.partition@peer=1"))
        with pytest.raises(CheckpointError, match="after 2 attempt"):
            ex.send(1, "t", b"payload")
    finally:
        ex.close()
        ex2.close()
        for s in stores:
            s.close()


def test_schedule_reproducible_across_same_seed_runs(kv_server):
    """Same seed, same workload → identical injection schedule (the acceptance
    reproducibility clause) — and different seeds give different schedules for
    probabilistic plans."""
    world = 2

    def run(spec):
        plan = chaos.ChaosPlan.parse(spec)
        chaos.install_plan(plan)

        def body(rank, stores, exchanges):
            strat, ex = _clique(kv_server, world, rank, stores)
            exchanges.append(ex)
            strat.replicate(_payload(rank, n=1 << 16))
            return True

        _run_world(kv_server, world, body)
        chaos.clear_plan()
        return plan.schedule()

    spec = "11:p2p.send.reset@at=1;p2p.send.truncate@at=3"
    s1, s2 = run(spec), run(spec)
    assert s1 == s2
    assert ("p2p", "send", "reset", 1) in s1
    assert ("p2p", "send", "truncate", 3) in s1
