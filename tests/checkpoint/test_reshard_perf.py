"""Perf acceptance for elastic reshard (slow; tier-1 deselects ``-m slow``).

Runs ``scripts/bench_reshard.py`` at a CI-sized payload and asserts the
ACCEPTANCE byte claim: the ranged-fetch path moves strictly fewer peer bytes
than a full-mirror retrieve of the same shrink. Also gates the COMMITTED
artifacts: ``BENCH_reshard.json`` (sub-second shrink-to-trainable at 64 MB,
1 GB leg with a strictly larger overlap speedup) and
``BENCH_replication.json`` (the composed delta×erasure leg)."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@pytest.mark.slow
def test_ranged_fetch_moves_strictly_fewer_bytes(tmp_path):
    out = tmp_path / "bench.json"
    r = subprocess.run(
        [
            sys.executable,
            # 64 MB is the ROADMAP item-4 gate point: smaller payloads are
            # dominated by fixed costs (collectives, plan build) and the
            # wall-clock comparison stops measuring the serve path.
            os.path.join(REPO_ROOT, "scripts", "bench_reshard.py"),
            "--mb", "64", "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-3000:]
    res = json.loads(out.read_text())
    assert res["full_peer_bytes"] > 0, res
    # The acceptance criterion: strictly fewer bytes on the wire than a
    # full-mirror retrieve (here the survivor's new block is a fraction of
    # the source shard, so the margin is structural, not noise).
    assert res["ranged_peer_bytes"] < res["full_peer_bytes"], res
    assert res["bytes_ratio"] < 0.9, res
    # And the local-slice path did real work (mirrors served in place).
    assert res["ranged_local_bytes"] > 0, res
    # ROADMAP item 4 gate (flipped by the TPURES03 chunk manifest): with
    # range serving verifying only touched chunks — no serve-side
    # whole-container CRC pass — elastic resume must beat the full-mirror
    # retrieve-and-slice recovery on wall clock, not just bytes.
    assert res["speedup"] > 1.0, res


@pytest.mark.slow
def test_committed_bench_has_subsecond_resume_and_1g_scaling():
    """Gate the COMMITTED ``BENCH_reshard.json``: the sub-second elastic
    resume claim (shrink-to-trainable < 1 s at 64 MB) plus the 1 GB leg
    whose overlap speedup must EXCEED the 64 MB speedup — the parallel
    serve/fetch/assembly win grows with payload, so a regression in the
    overlap plumbing shows up here before it shows up in production."""
    doc = json.loads(
        open(os.path.join(REPO_ROOT, "BENCH_reshard.json")).read()
    )
    assert doc["mb"] == 64, doc
    assert doc["ranged_s"] < 1.0, doc
    # phases must be present and well-formed: CostModel.from_bench prefers
    # them over ranged_s when repricing the autoscale controller.
    ph = doc["phases"]
    assert ph["plan_s"] >= 0 and ph["fetch_s"] > 0, ph
    assert ph["plan_s"] + ph["fetch_s"] <= doc["ranged_s"], doc
    leg = doc["leg_1g"]
    assert leg["mb"] == 1024, leg
    assert leg["speedup"] > doc["speedup"], (leg["speedup"], doc["speedup"])


@pytest.mark.slow
def test_bench_reshard_1g_leg_regenerates_and_holds(tmp_path):
    """Re-run the 1 GB leg end to end (the slow CI lane): the regenerated
    point must itself clear both perf gates, not just the committed one."""
    out = tmp_path / "bench1g.json"
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "bench_reshard.py"),
            "--mb", "64", "--with-1g", "--assert-subsecond",
            "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-3000:]
    res = json.loads(out.read_text())
    assert res["leg_1g"]["speedup"] > res["speedup"], res


@pytest.mark.slow
def test_committed_bench_replication_composed_leg():
    """Gate the COMMITTED ``BENCH_replication.json`` composed leg: at 5%
    dirty a steady-state round ships delta frames erasure-coded — ≥20×
    fewer wire bytes than full mirrors, per-rank wire cost ≤ (1+1/k)× the
    frame, and the k-of-n frame reconstruction ran byte-identical."""
    doc = json.loads(
        open(os.path.join(REPO_ROOT, "BENCH_replication.json")).read()
    )
    leg = doc["delta_erasure"]
    assert leg["dirty_frac"] == 0.05, leg
    assert leg["bytes_win"] >= 20.0, leg
    assert leg["payload_ratio"] <= 1 + 1 / leg["k"] + 0.05, leg
    assert leg["reconstruct_ok"] is True, leg
