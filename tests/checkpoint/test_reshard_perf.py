"""Perf acceptance for elastic reshard (slow; tier-1 deselects ``-m slow``).

Runs ``scripts/bench_reshard.py`` at a CI-sized payload and asserts the
ACCEPTANCE byte claim: the ranged-fetch path moves strictly fewer peer bytes
than a full-mirror retrieve of the same shrink. The committed 64 MB results
live in ``BENCH_reshard.json``."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@pytest.mark.slow
def test_ranged_fetch_moves_strictly_fewer_bytes(tmp_path):
    out = tmp_path / "bench.json"
    r = subprocess.run(
        [
            sys.executable,
            # 64 MB is the ROADMAP item-4 gate point: smaller payloads are
            # dominated by fixed costs (collectives, plan build) and the
            # wall-clock comparison stops measuring the serve path.
            os.path.join(REPO_ROOT, "scripts", "bench_reshard.py"),
            "--mb", "64", "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-3000:]
    res = json.loads(out.read_text())
    assert res["full_peer_bytes"] > 0, res
    # The acceptance criterion: strictly fewer bytes on the wire than a
    # full-mirror retrieve (here the survivor's new block is a fraction of
    # the source shard, so the margin is structural, not noise).
    assert res["ranged_peer_bytes"] < res["full_peer_bytes"], res
    assert res["bytes_ratio"] < 0.9, res
    # And the local-slice path did real work (mirrors served in place).
    assert res["ranged_local_bytes"] > 0, res
    # ROADMAP item 4 gate (flipped by the TPURES03 chunk manifest): with
    # range serving verifying only touched chunks — no serve-side
    # whole-container CRC pass — elastic resume must beat the full-mirror
    # retrieve-and-slice recovery on wall clock, not just bytes.
    assert res["speedup"] > 1.0, res
