"""Cold tier: spill containment, manifest-gated visibility, restore-anywhere.

Same simulated multi-rank pattern as test_local.py: N "ranks" as threads, each
with its own store client + peer exchange against one KVServer. The cold tier
under test is a FilesystemStore in tmp_path — the artifact layout and manifest
schema are backend-independent, so everything proven here holds for any
ObjectStore implementation.
"""

import concurrent.futures as cf
import json
import os
import pickle
import struct

import numpy as np
import pytest

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint import reshard as R
from tpu_resiliency.checkpoint.coldtier import (
    ColdTier,
    FilesystemStore,
    artifact_key,
    cold_from_env,
    manifest_key,
)
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.local_manager import CkptID, LocalCheckpointManager
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform import chaos
from tpu_resiliency.platform.store import CoordStore
from tpu_resiliency.utils import events


def run_ranks(world, fn, timeout=60.0):
    """Run fn(rank) on the given ranks as threads; raise the first failure."""
    ranks = world if isinstance(world, (list, tuple)) else range(world)
    with cf.ThreadPoolExecutor(max_workers=len(list(ranks))) as pool:
        futures = [pool.submit(fn, r) for r in ranks]
        return [f.result(timeout=timeout) for f in futures]


@pytest.fixture
def make_store(kv_server):
    stores = []

    def factory():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
        stores.append(s)
        return s

    yield factory
    for s in stores:
        s.close()


@pytest.fixture
def sink():
    seen = []
    events.add_sink(seen.append)
    yield seen
    events.remove_sink(seen.append)


@pytest.fixture(autouse=True)
def _no_chaos():
    yield
    chaos.clear_plan()


def _tree(rank):
    return {"w": np.full((8,), float(rank) + 0.5, dtype=np.float32), "step": rank}


def _cold(tmp_path, rank=0, **kw):
    return ColdTier(FilesystemStore(str(tmp_path / "cold")), rank=rank, **kw)


class TestFilesystemStore:
    def test_put_get_range_stat_list_delete(self, tmp_path):
        fs = FilesystemStore(str(tmp_path))
        n = fs.put("a/b.bin", [b"hello ", b"world"])
        assert n == 11
        assert fs.get("a/b.bin") == b"hello world"
        assert fs.get_range("a/b.bin", 6, 5) == b"world"
        assert fs.stat("a/b.bin") == 11
        assert fs.list() == ["a/b.bin"]
        fs.delete("a/b.bin")
        assert fs.list() == []

    def test_rejects_traversal_keys(self, tmp_path):
        fs = FilesystemStore(str(tmp_path))
        for bad in ("/abs", "../up", "a/../../b", ""):
            with pytest.raises(ValueError):
                fs.put(bad, [b"x"])

    def test_in_flight_uploads_invisible_to_list(self, tmp_path):
        fs = FilesystemStore(str(tmp_path))
        fs.put("k.bin", [b"x"])
        # A crashed uploader's leftover temp must never surface as an object.
        with open(os.path.join(str(tmp_path), "k2.bin.upload"), "wb") as f:
            f.write(b"partial")
        assert fs.list() == ["k.bin"]


class TestSpill:
    def test_spill_via_manager_and_manifest_schema(self, tmp_path, sink):
        cold = _cold(tmp_path)
        mgr = LocalCheckpointManager(str(tmp_path / "work"), rank=0, cold=cold)
        mgr.save(3, PyTreeStateDict(_tree(0)), is_async=False)
        assert cold.flush(timeout=30.0)
        mgr.close()

        assert cold.coverage() == {3: {0}}
        doc = cold.manifest(3, 0)
        assert doc["format"] == "tpu-coldtier-1"
        assert doc["iteration"] == 3 and doc["owner"] == 0
        assert doc["keyframe"] is True
        assert doc["prefix_len"] > 0 and doc["bytes"] > doc["prefix_len"]
        for leaf in doc["leaves"]:
            assert leaf["nbytes"] >= 0 and "crc32c" in leaf
            assert "chunks" in leaf  # v3 containers carry chunk manifests
        spilled = [e for e in sink if e.kind == "coldtier_spilled"]
        assert len(spilled) == 1 and spilled[0].payload["iteration"] == 3

    def test_non_keyframe_spills_are_skipped(self, tmp_path):
        cold = _cold(tmp_path)
        assert cold.spill(5, 0, "unused", keyframe=False) is False
        assert cold.coverage() == {}

    def test_torn_upload_leaves_no_visible_manifest(self, tmp_path, sink):
        """The commit-semantics satellite: a torn artifact commit must never
        be followed by a manifest — the iteration stays invisible."""
        akey = artifact_key(0, 1, 0)
        chaos.install_plan(
            chaos.ChaosPlan.parse(f"11:cold.commit.torn-rename@peer={akey}")
        )
        cold = _cold(tmp_path, retries=2, backoff_s=0.01)
        mgr = LocalCheckpointManager(str(tmp_path / "work"), rank=0, cold=cold)
        mgr.save(1, PyTreeStateDict(_tree(0)), is_async=False)
        assert cold.flush(timeout=30.0)
        mgr.close()

        assert cold.coverage() == {}
        assert cold.store.list() == []  # no manifest, no torn artifact kept
        degraded = [e for e in sink if e.kind == "coldtier_degraded"]
        assert degraded and degraded[-1].payload["reason"] == "upload-failed"
        # The save itself still succeeded locally.
        mgr2 = LocalCheckpointManager(str(tmp_path / "work"), rank=0, cold=False)
        assert mgr2.find_latest() == 1
        mgr2.close()

    def test_enospc_degrades_to_local_only(self, tmp_path, sink):
        chaos.install_plan(chaos.ChaosPlan.parse("7:cold.write.enospc"))
        cold = _cold(tmp_path, retries=2, backoff_s=0.01)
        mgr = LocalCheckpointManager(str(tmp_path / "work"), rank=0, cold=cold)
        mgr.save(1, PyTreeStateDict(_tree(0)), is_async=False)
        assert cold.flush(timeout=30.0)

        assert cold.coverage() == {}
        assert [e.payload["reason"] for e in sink if e.kind == "coldtier_degraded"] \
            == ["upload-failed"]
        # Local tier is untouched: save landed and loads.
        hollow, tensors, meta = mgr.load(1)
        np.testing.assert_array_equal(
            np.asarray(tensors[0]), _tree(0)["w"]
        )
        mgr.close()

    def test_breaker_opens_after_repeated_failures(self, tmp_path, sink):
        chaos.install_plan(chaos.ChaosPlan.parse("7:cold.write.enospc"))
        cold = _cold(
            tmp_path, retries=1, backoff_s=0.01,
            breaker_threshold=1, breaker_cooldown_s=300.0,
        )
        src = str(tmp_path / "src.ckpt")
        ckpt_format.write_blob(
            src,
            ckpt_format.serialize_to_bytes(
                b"h", [np.zeros(4, np.float32)], meta={}
            ),
        )
        cold.spill(1, 0, src)
        assert cold.flush(timeout=30.0)
        cold.spill(2, 0, src)
        assert cold.flush(timeout=30.0)
        reasons = [e.payload["reason"] for e in sink if e.kind == "coldtier_degraded"]
        assert reasons == ["upload-failed", "breaker-open"]

    def test_slow_store_never_blocks_save_foreground(self, tmp_path):
        """fg regression for the degraded path: a pathologically slow backend
        must not stretch the save call — spilling is fully asynchronous."""

        class SlowStore(FilesystemStore):
            def put(self, key, slices):
                import time as _t
                _t.sleep(2.0)
                return super().put(key, slices)

        cold = ColdTier(SlowStore(str(tmp_path / "cold")), rank=0)
        mgr = LocalCheckpointManager(str(tmp_path / "work"), rank=0, cold=cold)
        import time as _t
        t0 = _t.monotonic()
        mgr.save(1, PyTreeStateDict(_tree(0)), is_async=False)
        fg = _t.monotonic() - t0
        assert fg < 1.5, f"save foreground blocked on the cold tier ({fg:.2f}s)"
        assert cold.flush(timeout=30.0)
        assert cold.coverage() == {1: {0}}
        mgr.close()

    def test_unverifiable_container_is_refused(self, tmp_path, sink):
        cold = _cold(tmp_path, retries=1)
        bad = str(tmp_path / "bad.ckpt")
        with open(bad, "wb") as f:
            f.write(b"not a container at all")
        cold.spill(1, 0, bad)
        assert cold.flush(timeout=30.0)
        assert cold.coverage() == {}
        assert any(e.kind == "coldtier_degraded" for e in sink)


class TestRestore:
    def test_fresh_workdir_restores_from_cold(self, tmp_path):
        cold = _cold(tmp_path)
        mgr = LocalCheckpointManager(str(tmp_path / "work"), rank=0, cold=cold)
        mgr.save(2, PyTreeStateDict(_tree(0)), is_async=False)
        assert cold.flush(timeout=30.0)
        mgr.close()

        mgr2 = LocalCheckpointManager(
            str(tmp_path / "fresh"), rank=0, cold=_cold(tmp_path)
        )
        assert mgr2.find_latest() == 2
        hollow, tensors, meta = mgr2.load(2)
        assert meta["iteration"] == 2
        np.testing.assert_array_equal(np.asarray(tensors[0]), _tree(0)["w"])
        mgr2.close()

    def test_corrupt_cold_artifact_fails_closed(self, tmp_path, sink):
        cold = _cold(tmp_path)
        mgr = LocalCheckpointManager(str(tmp_path / "work"), rank=0, cold=cold)
        mgr.save(1, PyTreeStateDict(_tree(0)), is_async=False)
        assert cold.flush(timeout=30.0)
        mgr.close()

        # Flip a payload byte in the archived artifact, leaving the manifest.
        doc = cold.manifest(1, 0)
        apath = os.path.join(str(tmp_path / "cold"), artifact_key(0, 1, 0))
        with open(apath, "r+b") as f:
            f.seek(doc["prefix_len"] + 2)
            b = f.read(1)
            f.seek(doc["prefix_len"] + 2)
            f.write(bytes([b[0] ^ 0x40]))

        assert cold.verify(1, 0)[0] == "corrupt"
        with pytest.raises(CheckpointError):
            cold.fetch(1, 0, str(tmp_path / "out.ckpt"))
        assert not os.path.exists(str(tmp_path / "out.ckpt"))
        with pytest.raises(CheckpointError):
            cold.fetch_ranges(1, 0, [(0, 0, 8)])
        fetches = [e for e in sink if e.kind == "coldtier_fetch"]
        assert all(e.payload["outcome"] == "corrupt" for e in fetches)

    def test_ranged_fetch_is_partial_and_byte_exact(self, tmp_path):
        cold = _cold(tmp_path)
        arr = np.arange(4096, dtype=np.float32)
        src = str(tmp_path / "src.ckpt")
        ckpt_format.write_blob(
            src, ckpt_format.serialize_to_bytes(b"h", [arr], meta={})
        )
        cold.spill(1, 0, src)
        assert cold.flush(timeout=30.0)
        got = cold.fetch_ranges(1, 0, [(0, 16, 64)])
        assert bytes(got[0]) == arr.tobytes()[16:80]


class TestColdReshard:
    GLOBAL = np.arange(48, dtype=np.float32).reshape(12, 4)

    def _layout(self, ranks):
        return R.TreeLayout(
            [("dp", len(ranks))], list(ranks),
            [R.LeafSpec(self.GLOBAL.shape, "float32", ("dp",))],
        )

    def _save_world(self, make_store, tmp_path, ranks, iterations, gen=0):
        layout = self._layout(ranks)
        root = str(tmp_path / "work")

        def body(rank):
            comm = StoreComm(
                make_store(), rank, list(ranks), timeout=30.0, generation=gen
            )
            ex = PeerExchange(make_store(), rank, timeout=10.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                cold = _cold(tmp_path, rank=rank)
                mgr = LocalCheckpointManager(
                    root, rank=rank, comm=comm, replication=strat,
                    cold=cold, keep=len(iterations),
                )
                for it in iterations:
                    tree = {
                        "w": R.slice_local([self.GLOBAL], layout, rank)[0]
                        + float(it),
                        "step": it,
                    }
                    mgr.save(
                        it, PyTreeStateDict(tree), is_async=False,
                        layout=layout,
                    )
                assert cold.flush(timeout=30.0)
                mgr.close()
            finally:
                ex.close()

        run_ranks(list(ranks), body, timeout=120.0)
        return root

    def _cold_load(self, make_store, tmp_path, ranks, gen):
        def body(rank):
            comm = StoreComm(
                make_store(), rank, list(ranks), timeout=30.0, generation=gen
            )
            ex = PeerExchange(make_store(), rank, timeout=10.0)
            ex.start()
            try:
                mgr = LocalCheckpointManager(
                    str(tmp_path / "fresh"), rank=rank, comm=comm,
                    cold=_cold(tmp_path, rank=rank),
                )
                hollow, tensors, meta = mgr.load_resharded()
                mgr.close()
                return meta, [np.asarray(t).copy() for t in tensors]
            finally:
                ex.close()

        return run_ranks(list(ranks), body, timeout=120.0)

    def test_fresh_world_resumes_from_cold_on_smaller_world(
        self, make_store, tmp_path
    ):
        """The tentpole restore-anywhere path: world-3 job dies, fresh world-2
        launcher with an EMPTY workdir assembles byte-identical state from
        the cold tier alone."""
        self._save_world(make_store, tmp_path, [0, 1, 2], [4])
        out = self._cold_load(make_store, tmp_path, [0, 1], gen=1)
        tgt = self._layout([0, 1])
        for rank, (meta, tensors) in zip([0, 1], out):
            assert meta["iteration"] == 4
            want = R.slice_local([self.GLOBAL], tgt, rank)[0] + 4.0
            np.testing.assert_array_equal(tensors[0], want)

    def test_cold_bitflip_climbs_to_older_iteration(
        self, make_store, tmp_path, sink
    ):
        """Seeded corruption of the newest cold iteration: the group must
        agree to discard it and climb to the next-older covered iteration —
        corrupt bytes are never restored, and no rank diverges."""
        self._save_world(make_store, tmp_path, [0, 1, 2], [1, 2])
        colddir = str(tmp_path / "cold")
        probe = ColdTier(FilesystemStore(colddir))
        # Corrupt EVERY owner's iter-2 artifact (inside the sharded "w" leaf,
        # the one every target rank must fetch) so no alternative copy heals it.
        for owner in (0, 1, 2):
            doc = probe.manifest(2, owner)
            off = doc["prefix_len"]
            for leaf in doc["leaves"]:
                if leaf["nbytes"] == max(l["nbytes"] for l in doc["leaves"]):
                    break
                off += leaf["nbytes"]
            apath = os.path.join(colddir, artifact_key(0, 2, owner))
            with open(apath, "r+b") as f:
                f.seek(off + 2)
                b = f.read(1)
                f.seek(off + 2)
                f.write(bytes([b[0] ^ 0x01]))

        out = self._cold_load(make_store, tmp_path, [0, 1], gen=1)
        tgt = self._layout([0, 1])
        for rank, (meta, tensors) in zip([0, 1], out):
            assert meta["iteration"] == 1, "must climb below the corrupt iter"
            want = R.slice_local([self.GLOBAL], tgt, rank)[0] + 1.0
            np.testing.assert_array_equal(tensors[0], want)


class TestVersionSkew:
    def test_v2_era_workdir_restores_from_v3_cold_tier(self, tmp_path, sink):
        """Skew: a workdir whose local containers predate chunk manifests
        (TPURES02) coexists with a cold tier written by v3 code — coverage
        merges both rungs and the cold iteration restores cleanly."""
        # v3-era job wrote iteration 2 to the cold tier.
        cold = _cold(tmp_path)
        mgr = LocalCheckpointManager(str(tmp_path / "work"), rank=0, cold=cold)
        mgr.save(2, PyTreeStateDict(_tree(0)), is_async=False)
        assert cold.flush(timeout=30.0)
        mgr.close()

        # v2-era workdir: hand-built TPURES02 container at iteration 1.
        old_root = str(tmp_path / "old")
        arr = np.full((8,), 9.25, dtype=np.float32)
        views = [ckpt_format._raw_view(np.ascontiguousarray(arr))]
        leaf_crcs = [ckpt_format.crc32c(v) for v in views]
        header = {
            "hollow": pickle.dumps("v2-skeleton"),
            "leaves": [
                {"shape": arr.shape, "dtype": arr.dtype.name,
                 "nbytes": arr.nbytes, "crc32c": leaf_crcs[0]}
            ],
            "meta": {"iteration": 1},
        }
        hb = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
        prefix = ckpt_format.MAGIC_V2 + struct.pack("<Q", len(hb)) + hb
        trailer = ckpt_format.build_trailer(
            leaf_crcs, ckpt_format._container_crc(prefix, leaf_crcs)
        )
        mgr2 = LocalCheckpointManager(
            old_root, rank=0, cold=_cold(tmp_path)
        )
        v2_path = mgr2._path(CkptID(1, 0))
        os.makedirs(os.path.dirname(v2_path), exist_ok=True)
        with open(v2_path, "wb") as f:
            f.write(prefix)
            for v in views:
                f.write(v)
            f.write(trailer)

        # Coverage sees the local v2 iteration AND the cold v3 iteration.
        assert mgr2.find_latest() == 2
        hollow, tensors, meta = mgr2.load(2)
        np.testing.assert_array_equal(np.asarray(tensors[0]), _tree(0)["w"])
        # The v2-era local container still loads below it.
        hollow1, tensors1, meta1 = mgr2.load(1)
        np.testing.assert_array_equal(np.asarray(tensors1[0]), arr)
        mgr2.close()


class TestRetention:
    def _container(self, tmp_path, name="src.ckpt"):
        src = str(tmp_path / name)
        ckpt_format.write_blob(
            src,
            ckpt_format.serialize_to_bytes(
                b"h", [np.zeros(16, np.float32)], meta={}
            ),
        )
        return src

    def test_cold_keep_prunes_oldest_with_events(self, tmp_path, sink):
        cold = _cold(tmp_path, keep=2)
        src = self._container(tmp_path)
        for it in (1, 2, 3, 4):
            cold.spill(it, 0, src)
            assert cold.flush(timeout=30.0)
        assert sorted(cold.coverage()) == [3, 4]
        pruned = sorted(
            e.payload["iteration"] for e in sink if e.kind == "coldtier_pruned"
        )
        assert pruned == [1, 2]

    def test_delta_base_is_never_orphaned(self, tmp_path):
        unlimited = _cold(tmp_path)  # no retention while seeding
        src = self._container(tmp_path)
        for it in (1, 2):
            unlimited.spill(it, 0, src)
            assert unlimited.flush(timeout=30.0)
        # Iter 3 names iter 1 as its delta base — retention with keep=1 must
        # keep {3} plus its base {1}, pruning only 2.
        cold = _cold(tmp_path, keep=1)
        cold.spill(3, 0, src, delta_base=1)
        assert cold.flush(timeout=30.0)
        assert sorted(cold.coverage()) == [1, 3]


class TestEnvWiring:
    def test_cold_from_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv("TPU_RESILIENCY_COLD_DIR", raising=False)
        assert cold_from_env() is None
        monkeypatch.setenv("TPU_RESILIENCY_COLD_DIR", str(tmp_path / "cold"))
        monkeypatch.setenv("TPU_RESILIENCY_COLD_KEEP", "5")
        cold = cold_from_env(session=1, rank=2)
        assert cold is not None and cold.keep == 5 and cold.rank == 2
        assert "cold" in cold.store.describe()

    def test_manager_defaults_to_env_cold_tier(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_RESILIENCY_COLD_DIR", str(tmp_path / "cold"))
        mgr = LocalCheckpointManager(str(tmp_path / "work"), rank=0)
        try:
            assert mgr.cold is not None
            mgr.save(1, PyTreeStateDict(_tree(0)), is_async=False)
            assert mgr.cold.flush(timeout=30.0)
            assert mgr.cold.coverage() == {1: {0}}
        finally:
            mgr.close()

    def test_cold_false_disables(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_RESILIENCY_COLD_DIR", str(tmp_path / "cold"))
        mgr = LocalCheckpointManager(str(tmp_path / "work"), rank=0, cold=False)
        assert mgr.cold is None
        mgr.close()
