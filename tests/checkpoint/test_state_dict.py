"""Tensor-aware state dict, container format, and whole-tree async checkpointer.

Models the reference's checkpointing unit tests (``tests/checkpointing/unit/``): tmp-dir
round-trips, async save + finalize, structure checks — no hardware assumptions.
"""

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.async_ckpt import AsyncCheckpointer
from tpu_resiliency.checkpoint.async_core import (
    AsyncCallsQueue,
    AsyncRequest,
    ThreadAsyncCaller,
)
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict, TensorPlaceholder
from tpu_resiliency.exceptions import CheckpointError


def make_tree():
    return {
        "params": {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4), "b": jnp.ones(4)},
        "step": 7,
        "opt": [jnp.zeros((2, 2)), {"m": jnp.full((3,), 2.5)}],
        "name": "flagship",
    }


class TestPyTreeStateDict:
    def test_pop_insert_roundtrip(self):
        tree = make_tree()
        sd = PyTreeStateDict(tree)
        tensors = sd.pop_tensors()
        assert sd.is_hollow
        assert len(tensors) == 4
        # Hollow skeleton is picklable and contains placeholders.
        blob = pickle.dumps(sd.hollow_tree)
        hollow = pickle.loads(blob)
        leaves = jax.tree_util.tree_leaves(
            hollow, is_leaf=lambda x: isinstance(x, TensorPlaceholder)
        )
        assert sum(isinstance(leaf, TensorPlaceholder) for leaf in leaves) == 4
        sd.insert_tensors(tensors)
        assert not sd.is_hollow
        restored = sd.tree
        np.testing.assert_array_equal(restored["params"]["w"], tree["params"]["w"])
        assert restored["step"] == 7 and restored["name"] == "flagship"

    def test_host_copy_and_device_restore(self):
        sd = PyTreeStateDict(make_tree())
        sd.pop_tensors()
        sd.copy_tensors_to_host()
        assert all(isinstance(t, np.ndarray) for t in sd.tensors())
        sd.restore_tensor_device()
        assert all(isinstance(t, jax.Array) for t in sd.tensors())
        sd.insert_tensors(sd.tensors())
        np.testing.assert_array_equal(
            np.asarray(sd.tree["params"]["b"]), np.ones(4, dtype=np.float32)
        )

    def test_double_pop_raises(self):
        sd = PyTreeStateDict(make_tree())
        sd.pop_tensors()
        with pytest.raises(CheckpointError):
            sd.pop_tensors()

    def test_insert_wrong_count(self):
        sd = PyTreeStateDict(make_tree())
        sd.pop_tensors()
        with pytest.raises(CheckpointError):
            sd.insert_tensors([np.zeros(1)])

    def test_non_array_leaves_preserved(self):
        sd = PyTreeStateDict({"a": 1, "b": "x", "c": None})
        assert sd.pop_tensors() == []
        sd.insert_tensors([])
        assert sd.tree == {"a": 1, "b": "x", "c": None}


class TestContainerFormat:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "c.ckpt")
        arrays = [np.arange(6, dtype=np.float64).reshape(2, 3), np.ones(3, np.int32)]
        ckpt_format.write_payload(path, b"hollow", arrays, meta={"it": 3})
        hollow, tensors, meta = ckpt_format.read_payload(path)
        assert hollow == b"hollow" and meta == {"it": 3}
        np.testing.assert_array_equal(tensors[0], arrays[0])
        np.testing.assert_array_equal(tensors[1], arrays[1])
        assert not os.path.exists(path + ckpt_format.DIRTY_SUFFIX)

    def test_bytes_roundtrip(self):
        arrays = [np.random.default_rng(0).standard_normal((4, 4)).astype(np.float32)]
        blob = ckpt_format.serialize_to_bytes(b"h", arrays, meta={"k": 1})
        hollow, tensors, meta = ckpt_format.deserialize_from_bytes(blob)
        assert hollow == b"h" and meta == {"k": 1}
        np.testing.assert_array_equal(tensors[0], arrays[0])

    def test_bad_magic(self, tmp_path):
        path = str(tmp_path / "bad.ckpt")
        with open(path, "wb") as f:
            f.write(b"NOTMAGIC" + b"\0" * 32)
        with pytest.raises(CheckpointError):
            ckpt_format.read_payload(path)

    def test_bfloat16_roundtrip(self, tmp_path):
        path = str(tmp_path / "bf16.ckpt")
        arr = jnp.astype(jnp.arange(8), jnp.bfloat16)
        ckpt_format.write_payload(path, b"", [np.asarray(arr)])
        _, tensors, _ = ckpt_format.read_payload(path)
        np.testing.assert_array_equal(
            np.asarray(tensors[0], np.float32), np.arange(8, dtype=np.float32)
        )


class TestAsyncCore:
    def test_thread_caller_runs(self, tmp_path):
        marker = tmp_path / "done"
        caller = ThreadAsyncCaller()
        caller.schedule(AsyncRequest(async_fn=lambda: marker.write_text("ok")))
        assert caller.wait(10.0)
        caller.raise_if_failed()
        assert marker.read_text() == "ok"

    def test_thread_caller_error_surfaces(self):
        caller = ThreadAsyncCaller()

        def boom():
            raise RuntimeError("disk full")

        caller.schedule(AsyncRequest(async_fn=boom))
        caller.wait(10.0)
        with pytest.raises(CheckpointError, match="disk full"):
            caller.raise_if_failed()

    def test_queue_fifo_finalize(self):
        order = []
        q = AsyncCallsQueue(caller="thread")
        for i in range(3):
            q.schedule_async_request(
                AsyncRequest(
                    async_fn=lambda: None,
                    finalize_fns=(lambda i=i: order.append(i),),
                )
            )
            q.maybe_finalize_async_calls(blocking=True)
        assert order == [0, 1, 2]
        assert q.num_unfinalized_calls == 0
        q.close()

    def test_failed_save_never_finalizes(self):
        """Regression: a failed save must be dequeued when its error is raised —
        a later poll must not run its finalize_fns as if it succeeded."""
        q = AsyncCallsQueue(caller="thread")
        finalized = []

        def boom():
            raise RuntimeError("disk full")

        q.schedule_async_request(
            AsyncRequest(async_fn=boom, finalize_fns=(lambda: finalized.append(1),))
        )
        with pytest.raises(CheckpointError):
            q.maybe_finalize_async_calls(blocking=True)
        assert q.maybe_finalize_async_calls(blocking=True) == []
        assert finalized == [] and q.num_unfinalized_calls == 0
        q.close()

    def test_preload_runs_synchronously(self):
        events = []
        q = AsyncCallsQueue(caller="thread")
        q.schedule_async_request(
            AsyncRequest(
                async_fn=lambda: events.append("async"),
                preload_fn=lambda: events.append("preload"),
            )
        )
        assert events[0] == "preload"
        q.finalize_all()
        q.close()


class TestAsyncCheckpointer:
    def test_async_save_load_roundtrip(self, tmp_path):
        path = str(tmp_path / "model.ckpt")
        tree = make_tree()
        ckpt = AsyncCheckpointer()
        ckpt.async_save(tree, path, meta={"iteration": 11})
        ckpt.finalize_all()
        loaded, meta = AsyncCheckpointer.load(path)
        assert meta["iteration"] == 11
        np.testing.assert_array_equal(
            np.asarray(loaded["params"]["w"]), np.asarray(tree["params"]["w"])
        )
        assert loaded["step"] == 7
        assert isinstance(loaded["params"]["w"], jax.Array)
        ckpt.close()

    def test_changed_scalar_leaves_are_persisted(self, tmp_path):
        """Same treedef, different non-array leaf values: both must round-trip
        (regression: a structure-keyed hollow cache wrote stale step counters)."""
        ckpt = AsyncCheckpointer()
        tree = make_tree()
        ckpt.async_save(tree, str(tmp_path / "a.ckpt"))
        ckpt.finalize_all()
        tree2 = dict(tree, step=9999)
        ckpt.async_save(tree2, str(tmp_path / "b.ckpt"))
        ckpt.finalize_all()
        assert AsyncCheckpointer.load(str(tmp_path / "a.ckpt"))[0]["step"] == 7
        assert AsyncCheckpointer.load(str(tmp_path / "b.ckpt"))[0]["step"] == 9999
        ckpt.close()

    def test_per_rank_paths(self, tmp_path):
        ckpt = AsyncCheckpointer()
        ckpt.save({"x": jnp.ones(2)}, str(tmp_path / "s.ckpt"), rank=3)
        assert os.path.exists(tmp_path / "s.r3.ckpt")
        tree, _ = AsyncCheckpointer.load(str(tmp_path / "s.ckpt"), rank=3)
        np.testing.assert_array_equal(np.asarray(tree["x"]), np.ones(2, np.float32))
        ckpt.close()


class TestStripedWrites:
    def test_striped_container_byte_identical(self, tmp_path):
        """A 4-way striped write produces the SAME file as the sequential one
        (pwrite-at-offset into one container), so readers never change."""
        rng = np.random.default_rng(0)
        arrays = [
            np.asarray(rng.standard_normal(s), np.float32)
            for s in [(64, 64), (7,), (128, 3), (1,), (33, 5), (256,)]
        ]
        p1 = str(tmp_path / "seq.ckpt")
        p4 = str(tmp_path / "striped.ckpt")
        ckpt_format.write_payload(p1, b"hollow", arrays, meta={"it": 1}, stripes=1)
        ckpt_format.write_payload(p4, b"hollow", arrays, meta={"it": 1}, stripes=4)
        with open(p1, "rb") as f1, open(p4, "rb") as f4:
            assert f1.read() == f4.read()
        hollow, tensors, meta = ckpt_format.read_payload(p4)
        assert hollow == b"hollow" and meta == {"it": 1}
        for got, want in zip(tensors, arrays):
            np.testing.assert_array_equal(got, want)

    def test_stripes_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv(ckpt_format.STRIPES_ENV, "3")
        assert ckpt_format._effective_stripes(None) == 3
        monkeypatch.setenv(ckpt_format.STRIPES_ENV, "bogus")
        assert ckpt_format._effective_stripes(None) == 1
        assert ckpt_format._effective_stripes(4) == 4

    def test_striped_blob_roundtrip(self, tmp_path):
        blob = np.random.default_rng(1).integers(0, 255, 3 << 20, np.uint8).tobytes()
        path = str(tmp_path / "blob.bin")
        ckpt_format.write_blob(path, blob, stripes=4)
        with open(path, "rb") as f:
            assert f.read() == blob


class TestSeparationHint:
    def test_routed_file_and_merged_load(self, tmp_path):
        tree = {
            "params": {"w": np.ones((4, 4), np.float32)},
            "opt_state": {"m": np.full((4, 4), 2.0, np.float32)},
            "step": 11,
        }
        path = str(tmp_path / "model.ckpt")
        ckpt = AsyncCheckpointer()
        ckpt.async_save(tree, path, meta={"it": 11}, separation_hint="opt_state")
        ckpt.finalize_all()
        # Two container files: main (params+step) and the routed optimizer file
        # (named by the save's pair token).
        assert (tmp_path / "model.ckpt").exists()
        assert len(list(tmp_path.glob("model.opt_state.*.ckpt"))) == 1
        main_tree, _ = AsyncCheckpointer.load(path)
        assert "opt_state" not in main_tree
        merged, meta = AsyncCheckpointer.load(path, separation_hint="opt_state")
        assert meta == {"it": 11}
        assert merged["step"] == 11
        np.testing.assert_array_equal(merged["opt_state"]["m"], tree["opt_state"]["m"])
        np.testing.assert_array_equal(merged["params"]["w"], tree["params"]["w"])

    def test_hint_requires_mapping_key(self, tmp_path):
        import pytest

        from tpu_resiliency.exceptions import CheckpointError

        ckpt = AsyncCheckpointer()
        with pytest.raises(CheckpointError):
            ckpt.async_save({"a": 1}, str(tmp_path / "x.ckpt"), separation_hint="b")


class TestStripedDominantLeaf:
    def test_single_huge_leaf_stripes_byte_identical(self, tmp_path):
        """Byte-range striping works when one leaf dominates the payload
        (whole-leaf grouping would leave all but one writer idle)."""
        rng = np.random.default_rng(2)
        arrays = [
            np.asarray(rng.standard_normal((1 << 20,)), np.float32),  # ~4 MiB
            np.asarray([1.0], np.float32),
        ]
        p1 = str(tmp_path / "seq.ckpt")
        p4 = str(tmp_path / "striped.ckpt")
        ckpt_format.write_payload(p1, b"h", arrays, stripes=1)
        ckpt_format.write_payload(p4, b"h", arrays, stripes=4)
        with open(p1, "rb") as f1, open(p4, "rb") as f4:
            assert f1.read() == f4.read()


class TestTornPairDetection:
    def test_crash_between_renames_keeps_old_pair_loadable(self, tmp_path):
        """A crash after the new hint file landed but before the main file's
        commit rename must leave the PREVIOUS generation fully loadable (the
        r4 advisor's durability finding: fixed-name hints destroyed it)."""
        path = str(tmp_path / "m.ckpt")
        tree1 = {"params": {"w": np.ones((2,), np.float32)}, "opt": {"m": np.zeros((2,), np.float32)}}
        ckpt = AsyncCheckpointer()
        ckpt.async_save(tree1, path, separation_hint="opt")
        ckpt.finalize_all()
        # Simulate the torn window: generation 2's token-named hint file exists,
        # main never committed (writer died before its rename).
        ckpt_format.write_payload(
            str(tmp_path / ("m.opt." + "ab" * 8 + ".ckpt")),
            b"h",
            [np.full((2,), 9.0, np.float32)],
            meta={"_pair_token": "ab" * 8},
        )
        merged, _ = AsyncCheckpointer.load(path, separation_hint="opt")
        np.testing.assert_array_equal(merged["opt"]["m"], tree1["opt"]["m"])

    def test_token_mismatch_refused(self, tmp_path):
        path = str(tmp_path / "m.ckpt")
        tree = {"params": {"w": np.ones((2,), np.float32)}, "opt": {"m": np.zeros((2,), np.float32)}}
        ckpt = AsyncCheckpointer()
        ckpt.async_save(tree, path, separation_hint="opt")
        ckpt.finalize_all()
        import shutil

        # Corrupt: a file at the token-named path whose internal token differs
        # (take a different save's hint file and drop it on the expected name).
        (hint_file,) = tmp_path.glob("m.opt.*.ckpt")
        ckpt.async_save(tree, str(tmp_path / "other.ckpt"), separation_hint="opt")
        ckpt.finalize_all()
        (other_hint,) = tmp_path.glob("other.opt.*.ckpt")
        shutil.copy(str(other_hint), str(hint_file))
        import pytest as _pytest

        from tpu_resiliency.exceptions import CheckpointError

        with _pytest.raises(CheckpointError, match="torn"):
            AsyncCheckpointer.load(path, separation_hint="opt")

    def test_superseded_hint_files_pruned_after_commit(self, tmp_path):
        path = str(tmp_path / "m.ckpt")
        ckpt = AsyncCheckpointer()
        for step in range(3):
            tree = {"params": {"w": np.full((2,), float(step), np.float32)},
                    "opt": {"m": np.full((2,), float(step), np.float32)}}
            ckpt.async_save(tree, path, separation_hint="opt")
            ckpt.finalize_all()
        # Only the committed generation's hint file survives cleanup.
        assert len(list(tmp_path.glob("m.opt.*.ckpt"))) == 1
        merged, _ = AsyncCheckpointer.load(path, separation_hint="opt")
        np.testing.assert_array_equal(
            merged["opt"]["m"], np.full((2,), 2.0, np.float32)
        )

    def test_overlapping_saves_to_same_path_serialize(self, tmp_path):
        """Back-to-back async saves to one path without an intervening finalize
        must serialize: they share the .dirty tmp file AND the hint-file
        cleanup (one save would prune the other's just-written hint)."""
        path = str(tmp_path / "m.ckpt")
        ckpt = AsyncCheckpointer()
        for step in range(4):
            tree = {"params": {"w": np.full((64,), float(step), np.float32)},
                    "opt": {"m": np.full((64,), float(step), np.float32)}}
            ckpt.async_save(tree, path, separation_hint="opt")
        ckpt.finalize_all()
        merged, _ = AsyncCheckpointer.load(path, separation_hint="opt")
        np.testing.assert_array_equal(
            merged["opt"]["m"], np.full((64,), 3.0, np.float32)
        )
        assert len(list(tmp_path.glob("m.opt.*.ckpt"))) == 1

    def test_glob_metachars_in_path_still_pruned(self, tmp_path):
        sweep = tmp_path / "run[1]"
        sweep.mkdir()
        path = str(sweep / "m.ckpt")
        ckpt = AsyncCheckpointer()
        for step in range(2):
            tree = {"a": {"x": np.full((2,), float(step), np.float32)},
                    "b": {"y": np.full((2,), float(step), np.float32)}}
            ckpt.async_save(tree, path, separation_hint="b")
            ckpt.finalize_all()
        assert len(list(sweep.glob("m.b.*.ckpt"))) == 1
        merged, _ = AsyncCheckpointer.load(path, separation_hint="b")
        np.testing.assert_array_equal(merged["b"]["y"], np.full((2,), 1.0, np.float32))

    def test_numpy_meta_round_trips(self, tmp_path):
        """User meta holding numpy arrays must not break the pair check
        (dict != on arrays raises ValueError; tokens alone are compared)."""
        path = str(tmp_path / "m.ckpt")
        tree = {"a": {"x": np.ones((2,), np.float32)}, "b": {"y": np.ones((2,), np.float32)}}
        ckpt = AsyncCheckpointer()
        ckpt.async_save(tree, path, meta={"rng": np.arange(4)}, separation_hint="b")
        ckpt.finalize_all()
        merged, meta = AsyncCheckpointer.load(path, separation_hint="b")
        np.testing.assert_array_equal(meta["rng"], np.arange(4))
        assert "_pair_token" not in meta

    def test_single_d2h_pair_roundtrip_strips_token(self, tmp_path):
        path = str(tmp_path / "t.ckpt")
        tree = {"a": {"x": np.arange(4, dtype=np.float32)}, "b": {"y": np.arange(3, dtype=np.float32)}, "n": 7}
        ckpt = AsyncCheckpointer()
        ckpt.async_save(tree, path, meta={"it": 2}, separation_hint="b")
        ckpt.finalize_all()
        merged, meta = AsyncCheckpointer.load(path, separation_hint="b")
        assert meta == {"it": 2}  # token stripped
        assert merged["n"] == 7
        np.testing.assert_array_equal(merged["b"]["y"], tree["b"]["y"])
        np.testing.assert_array_equal(merged["a"]["x"], tree["a"]["x"])


class TestStripedEdgeCases:
    def test_single_leaf_payload_stripes(self, tmp_path):
        """Byte-range striping splits WITHIN one fused-parameter leaf."""
        arr = [np.arange(1 << 20, dtype=np.float32)]
        p1, p4 = str(tmp_path / "s1.ckpt"), str(tmp_path / "s4.ckpt")
        ckpt_format.write_payload(p1, b"h", arr, stripes=1)
        ckpt_format.write_payload(p4, b"h", arr, stripes=4)
        with open(p1, "rb") as f1, open(p4, "rb") as f4:
            assert f1.read() == f4.read()

    def test_all_empty_leaves_striped(self, tmp_path):
        path = str(tmp_path / "e.ckpt")
        ckpt_format.write_payload(
            path, b"h", [np.zeros((0,), np.float32), np.zeros((0,), np.int32)],
            stripes=4,
        )
        hollow, tensors, _ = ckpt_format.read_payload(path)
        assert [t.size for t in tensors] == [0, 0]

    def test_direct_load_strips_pair_token(self, tmp_path):
        path = str(tmp_path / "d.ckpt")
        tree = {"a": {"x": np.ones((2,), np.float32)}, "b": {"y": np.ones((2,), np.float32)}}
        ckpt = AsyncCheckpointer()
        ckpt.async_save(tree, path, meta={"it": 4}, separation_hint="b")
        ckpt.finalize_all()
        # Loading either file of the pair directly keeps user meta clean.
        _, meta_main = AsyncCheckpointer.load(path)
        (hint_file,) = tmp_path.glob("d.b.*.ckpt")
        _, meta_hint = AsyncCheckpointer.load(str(hint_file))
        assert meta_main == {"it": 4} and meta_hint == {"it": 4}
