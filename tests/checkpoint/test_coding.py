"""Checkpoint byte-economy plane (checkpoint/coding/): RS codec algebra,
erasure replication + the reconstruct-from-parity recovery rung, delta
checkpoint chains, the TPURES03 chunk manifest, and format-version skew
(TPURES02 containers in a TPURES03 world)."""

import concurrent.futures as cf
import itertools
import os
import pickle
import struct

import numpy as np
import pytest

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.coding import (
    DeltaTracker,
    ErasureReplicationStrategy,
    apply_delta,
    encode_delta,
    is_block,
    is_delta,
    replication_from_env,
)
from tpu_resiliency.checkpoint.coding import delta as delta_mod
from tpu_resiliency.checkpoint.coding import rs
from tpu_resiliency.checkpoint.coding import strategy as coding_mod
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.local_manager import (
    CkptID,
    LocalCheckpointManager,
    block_filename,
)
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform.store import CoordStore
from tpu_resiliency.utils import events


def run_ranks(ranks, fn, timeout=90.0):
    with cf.ThreadPoolExecutor(max_workers=len(ranks)) as pool:
        futures = [pool.submit(fn, r) for r in ranks]
        return [f.result(timeout=timeout) for f in futures]


@pytest.fixture
def make_store(kv_server):
    stores = []

    def factory():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
        stores.append(s)
        return s

    yield factory
    for s in stores:
        s.close()


@pytest.fixture
def sink():
    seen = []
    events.add_sink(seen.append)
    yield seen
    events.remove_sink(seen.append)


# -- RS codec -----------------------------------------------------------------


class TestRS:
    @pytest.mark.parametrize("k,m", [(1, 1), (2, 1), (3, 1), (3, 2), (7, 3)])
    def test_any_k_of_n_reconstructs(self, k, m):
        rng = np.random.default_rng(k * 100 + m)
        data = rng.integers(0, 256, 997 * k + 13, dtype=np.uint8).tobytes()
        blocks, orig = rs.split(data, k)
        coded = {i: b for i, b in enumerate(blocks)}
        coded.update({k + j: p for j, p in enumerate(rs.encode(blocks, m))})
        for drop in itertools.islice(
            itertools.combinations(range(k + m), m), 10
        ):
            have = {i: b for i, b in coded.items() if i not in drop}
            rec = rs.reconstruct(k, m, have, want=list(range(k)))
            assert bytes(rs.join([rec[i] for i in range(k)], orig)) == data

    def test_too_few_blocks_raises(self):
        data = b"x" * 100
        blocks, orig = rs.split(data, 3)
        coded = {0: blocks[0]}  # 1 of 3 required
        with pytest.raises(CheckpointError, match="cannot reconstruct"):
            rs.reconstruct(3, 1, coded)

    def test_split_join_pads_and_strips(self):
        data = b"abcdefg"  # 7 bytes over k=3 -> 3-byte blocks, 2 pad bytes
        blocks, orig = rs.split(data, 3)
        assert orig == 7 and all(b.nbytes == 3 for b in blocks)
        assert bytes(rs.join(blocks, orig)) == data


# -- block artifacts ----------------------------------------------------------


class TestBlockArtifact:
    def test_roundtrip_and_magic_probe(self):
        block = np.frombuffer(b"B" * 64, dtype=np.uint8)
        parts = coding_mod.build_block_parts(2, 7, 3, 1, 1, block, 190, 0xABCD)
        blob = b"".join(bytes(p) for p in parts)
        assert is_block(blob) and not is_delta(blob)
        header, view = coding_mod.parse_block(blob)
        assert (header["owner"], header["iteration"]) == (2, 7)
        assert bytes(view) == b"B" * 64

    def test_corrupt_block_rejected(self):
        block = np.frombuffer(b"B" * 64, dtype=np.uint8)
        parts = coding_mod.build_block_parts(0, 1, 2, 1, 0, block, 128, 1)
        blob = bytearray(b"".join(bytes(p) for p in parts))
        blob[-5] ^= 0x20  # flip a payload byte
        with pytest.raises(CheckpointError, match="checksum mismatch"):
            coding_mod.parse_block(bytes(blob))

    def test_mixed_generation_reconstruction_rejected(self):
        data = os.urandom(300)
        blocks, orig = rs.split(data, 2)
        parity = rs.encode(blocks, 1)
        arts = [
            b"".join(bytes(p) for p in coding_mod.build_block_parts(
                0, 1, 2, 1, 0, blocks[0], orig, 111))
        ]
        arts.append(
            b"".join(bytes(p) for p in coding_mod.build_block_parts(
                0, 1, 2, 1, 2, parity[0], orig, 222))  # different digest
        )
        with pytest.raises(CheckpointError, match="mismatched generations"):
            coding_mod.reconstruct_container(arts)


# -- factory ------------------------------------------------------------------


def test_replication_from_env(monkeypatch, make_store):
    comm = None  # strategies tolerate comm=None at construction
    ex = object()
    monkeypatch.delenv("TPU_RESILIENCY_CKPT_CODING", raising=False)
    s = replication_from_env(comm, ex, 1, 2)
    assert type(s) is CliqueReplicationStrategy
    monkeypatch.setenv("TPU_RESILIENCY_CKPT_CODING", "erasure")
    s = replication_from_env(comm, ex, 1, 3)
    assert isinstance(s, ErasureReplicationStrategy) and s.parity == 1
    s = replication_from_env(comm, ex, 1, 4, coding="erasure:2")
    assert s.parity == 2
    with pytest.raises(CheckpointError):
        replication_from_env(comm, ex, 1, 2, coding="erasure:2")  # k < 1
    with pytest.raises(CheckpointError):
        replication_from_env(comm, ex, 1, 2, coding="banana")


# -- erasure e2e over real managers ------------------------------------------


WORLD3 = [0, 1, 2]


def _tree(rank, it, n=200_000):
    return {"w": np.full((n,), rank * 10.0 + it, np.float32), "step": it}


def _erasure_body(root, make_store, rank, gen, *, save_iters=(), wipe=False,
                  load=False, pipelined=False, world=WORLD3):
    comm = StoreComm(make_store(), rank, list(world), timeout=60.0,
                     generation=gen)
    ex = PeerExchange(make_store(), rank, timeout=30.0)
    ex.start()
    try:
        strat = ErasureReplicationStrategy(
            comm, ex, replication_jump=1, replication_factor=len(world),
            parity=1,
        )
        mgr = LocalCheckpointManager(
            root, rank=rank, comm=comm, replication=strat, keep=2,
            pipelined=pipelined,
        )
        if wipe:
            mgr.wipe()
        for it in save_iters:
            mgr.save(it, PyTreeStateDict(_tree(rank, it)),
                     is_async=pipelined)
            mgr.maybe_finalize(blocking=True)
        out = None
        if load:
            hollow, tensors, meta = mgr.load()
            out = (meta["iteration"], np.asarray(tensors[0]).copy())
        mgr.close()
        return out, sorted(mgr.block_ids())
    finally:
        ex.close()


class TestErasureE2E:
    @pytest.mark.parametrize("pipelined", [False, True])
    def test_save_distributes_one_block_per_peer(
        self, tmp_path, make_store, sink, pipelined
    ):
        root = str(tmp_path / "ckpt")
        out = run_ranks(WORLD3, lambda r: _erasure_body(
            root, make_store, r, 0, save_iters=(1,), pipelined=pipelined))
        for rank, (_, blocks) in zip(WORLD3, out):
            # Each rank holds exactly one block of each peer's shard, and
            # the assigned index equals this rank's clique position.
            owners = sorted(b[1] for b in blocks)
            assert owners == sorted(set(WORLD3) - {rank})
            assert all(b[2] == rank and b[3] == 2 and b[4] == 1 for b in blocks)
        parity_events = [e for e in sink if e.kind == "ckpt_parity"]
        assert len(parity_events) == len(WORLD3)
        for e in parity_events:
            # Wire economy: k=2, m=1 -> sent ≤ (1 + 1/k) x payload.
            assert e.payload["sent_bytes"] <= 1.6 * e.payload["payload_bytes"]

    def test_lost_rank_reconstructs_byte_identical_no_mirror_fallback(
        self, tmp_path, make_store, sink
    ):
        """ACCEPTANCE: the recovery-ladder e2e — a lost rank's shard comes
        back from parity blocks byte-identically, with zero full-mirror
        transfers and zero iteration fallback."""
        root = str(tmp_path / "ckpt")
        run_ranks(WORLD3, lambda r: _erasure_body(
            root, make_store, r, 0, save_iters=(1,)))
        own = open(os.path.join(root, "s0", "r0",
                                CkptID(1, 0).filename()), "rb").read()
        out = run_ranks(WORLD3, lambda r: _erasure_body(
            root, make_store, r, 1, wipe=(r == 0), load=True))
        for rank, (loaded, _) in zip(WORLD3, out):
            it, w = loaded
            assert it == 1
            np.testing.assert_array_equal(
                w, np.full((200_000,), rank * 10.0 + 1, np.float32))
        # The reconstructed container was re-persisted byte-identically.
        assert open(os.path.join(root, "s0", "r0",
                                 CkptID(1, 0).filename()), "rb").read() == own
        recon = [e for e in sink if e.kind == "ckpt_parity_reconstruct"]
        assert [e.payload["outcome"] for e in recon] == ["ok"]
        assert not [e for e in sink if e.kind == "ckpt_fallback"]
        # Zero full-mirror fallback: no whole-container retrieve transfer —
        # every p2p payload in the recovery round is a block artifact
        # (retr/…/b/ tags), never a mirror (retr/…/m/ tags).
        mirror_sends = [
            e for e in sink
            if e.kind == "p2p_transfer" and "/m/" in str(e.payload.get("tag"))
        ]
        assert not mirror_sends

    def test_corrupt_parity_block_degrades_to_peer_retrieve(
        self, tmp_path, make_store, sink
    ):
        """A flipped bit in a parity block must NEVER reconstruct silently:
        reconstruction fails closed, and when a real mirror exists (mixed
        clique / previously recovered container) the ladder's peer-retrieve
        rung serves it byte-identically."""
        root = str(tmp_path / "ckpt")
        run_ranks(WORLD3, lambda r: _erasure_body(
            root, make_store, r, 0, save_iters=(1,)))
        own_path = os.path.join(root, "s0", "r0", CkptID(1, 0).filename())
        own = open(own_path, "rb").read()
        # Rank 1 also holds a REAL mirror of rank 0's shard (the shape a
        # mixed-version peer or an earlier recovery leaves behind).
        mirror_path = os.path.join(root, "s0", "r1", CkptID(1, 0).filename())
        with open(mirror_path, "wb") as f:
            f.write(own)
        # Corrupt one of the surviving blocks of rank 0's shard.
        for holder in (1, 2):
            d = os.path.join(root, "s0", f"r{holder}")
            for name in os.listdir(d):
                if name.endswith(".ecblk") and "_0_b" in name:
                    p = os.path.join(d, name)
                    blob = bytearray(open(p, "rb").read())
                    blob[-3] ^= 0x40
                    open(p, "wb").write(bytes(blob))
        out = run_ranks(WORLD3, lambda r: _erasure_body(
            root, make_store, r, 1, wipe=(r == 0), load=True))
        it, w = out[0][0]
        assert it == 1
        np.testing.assert_array_equal(
            w, np.full((200_000,), 1.0, np.float32))
        assert open(own_path, "rb").read() == own
        # The rung order is visible in the events: a failed reconstruction,
        # then a successful peer retrieve; never a fallback.
        recon = [e for e in sink if e.kind == "ckpt_parity_reconstruct"]
        assert recon and recon[0].payload["outcome"] == "failed"
        assert not [e for e in sink if e.kind == "ckpt_fallback"]

    def test_coverage_counts_reconstructible_shards(
        self, tmp_path, make_store
    ):
        """find_latest must agree with what the ladder can deliver: after
        the owner's disk is wiped, the iteration stays covered because the
        blocks reconstruct it."""
        root = str(tmp_path / "ckpt")
        run_ranks(WORLD3, lambda r: _erasure_body(
            root, make_store, r, 0, save_iters=(1,)))

        def probe(rank):
            comm = StoreComm(make_store(), rank, WORLD3, timeout=60.0,
                             generation=1)
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = ErasureReplicationStrategy(
                    comm, ex, replication_jump=1,
                    replication_factor=len(WORLD3), parity=1)
                mgr = LocalCheckpointManager(
                    root, rank=rank, comm=comm, replication=strat)
                if rank == 0:
                    mgr.wipe()
                latest = mgr.find_latest()
                mgr.close()
                return latest
            finally:
                ex.close()

        assert run_ranks(WORLD3, probe) == [1, 1, 1]

    pass


# -- streaming erasure encode -------------------------------------------------


class TestStreamingEncode:
    @pytest.mark.parametrize("k,m", [(1, 0), (1, 1), (2, 1), (3, 1), (3, 2),
                                     (5, 3), (7, 2)])
    def test_blocks_byte_identical_to_copy_path(self, k, m):
        """Every coded block off the streaming path (multi-part payload,
        view-served data blocks, accumulated parity) matches the classic
        split-copy + encode path byte for byte — including the zero-pad
        tail of the last data block."""
        rng = np.random.default_rng(k * 31 + m)
        for total in (1, 13, 64 * 1024 + 7, 256 * 1024):
            payload = rng.integers(0, 256, total, dtype=np.uint8).tobytes()
            parts = [payload[: total // 3], payload[total // 3 : 2 * total // 3],
                     payload[2 * total // 3 :]]
            ref_blocks, ref_len = coding_mod._split_parts(parts, k)
            ref = ref_blocks + rs.encode(ref_blocks, m)
            views, tot, bl, parity = coding_mod.encode_payload(parts, k, m)
            assert tot == ref_len
            for i in range(k + m):
                got = coding_mod.coded_block(views, tot, bl, parity, k, i)
                gb = (b"".join(bytes(p) for p in got)
                      if isinstance(got, list) else bytes(memoryview(got)))
                assert gb == ref[i].tobytes(), (total, k, m, i)

    def test_prefed_encoder_reused_and_mismatch_falls_back(self):
        parts = [os.urandom(10_000), os.urandom(5_000)]
        enc = rs.StreamingEncoder(15_000, 2, 1, window=333)
        for p in parts:
            enc.update(p)
        views, tot, bl, parity = coding_mod.encode_payload(
            parts, 2, 1, encoder=enc)
        assert parity[0] is enc.parity[0]  # reused, no re-encode
        # Geometry mismatch (different k): silently re-streams.
        _, _, _, parity2 = coding_mod.encode_payload(parts, 3, 1, encoder=enc)
        ref_blocks, _ = coding_mod._split_parts(parts, 3)
        assert parity2[0].tobytes() == rs.encode(ref_blocks, 1)[0].tobytes()

    def test_parity1_is_pure_xor(self):
        """The RAID-5 fast path survives streaming: m=1 parity equals the
        XOR-reduce of the data blocks."""
        payload = os.urandom(4096 * 3)
        views, tot, bl, parity = coding_mod.encode_payload([payload], 3, 1)
        blocks, _ = rs.split(payload, 3)
        want = blocks[0] ^ blocks[1] ^ blocks[2]
        assert parity[0].tobytes() == want.tobytes()

    def test_streaming_alloc_stays_small(self):
        """Steady-state allocation gate: streaming a 32 MB payload through
        the encoder (m=1) must not allocate payload-sized scratch — the
        parity block plus O(window) temporaries only."""
        import tracemalloc

        total = 32 * (1 << 20)
        chunk = bytes(1 << 20)
        enc = rs.StreamingEncoder(total, 3, 1)
        enc.update(chunk)  # warm the code path before measuring
        tracemalloc.start()
        for _ in range(31):
            enc.update(chunk)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert enc.parity_blocks()[0].nbytes >= total // 3
        assert peak < 1 << 20, f"peak transient alloc {peak} >= 1 MB"

    def test_overfeed_and_early_parity_read_raise(self):
        enc = rs.StreamingEncoder(100, 2, 1)
        enc.update(b"x" * 60)
        with pytest.raises(CheckpointError, match="past the declared total"):
            enc.update(b"y" * 41)
        with pytest.raises(CheckpointError, match="parity read after"):
            enc.parity_blocks()


# -- delta x erasure composition ----------------------------------------------


def _delta_frame_fixture(tmp_path, dirty=128):
    """A (frame, base_path, want_container_bytes) triple: base container on
    disk, new container differing in a few chunks, encoded as a frame."""
    arr = np.zeros(1 << 21, dtype=np.uint8)
    arr[:] = 3
    prefix, views = ckpt_format.serialize_parts(
        b"hollow", [arr], meta={"iteration": 1})
    base_path = str(tmp_path / "base.ckpt")
    ckpt_format.write_parts(base_path, [prefix, *views])
    info = ckpt_format.parse_trailer_v3(views[-1])
    base = {
        "iteration": 1,
        "leaf_sizes": [arr.nbytes],
        "chunk_size": info.chunk_size,
        "leaf_chunks": info.leaf_chunk_crcs([arr.nbytes]),
        "container_crc": info.container_crc,
    }
    new = arr.copy()
    new[:dirty] += 9
    p2, v2 = ckpt_format.serialize_parts(
        b"hollow", [new], meta={"iteration": 2})
    frame, _ = encode_delta(0, 2, base, p2, v2[:-1], bytes(v2[-1]))
    want = b"".join([p2, *[bytes(memoryview(v).cast("B")) for v in v2]])
    return frame, base_path, want


class TestDeltaErasureComposition:
    def test_k_of_n_frame_reconstruction_round_trips(self, tmp_path):
        """ACCEPTANCE: a delta frame erasure-coded into k+m blocks
        reconstructs byte-identically from any k of them, and the applied
        container round-trips byte-identically against the base."""
        frame, base_path, want = _delta_frame_fixture(tmp_path)
        k, m = 3, 2
        views, tot, bl, parity = coding_mod.encode_payload([frame], k, m)
        meta = coding_mod._payload_meta([frame])
        digest = meta.pop("digest")
        arts = {}
        for i in range(k + m):
            blk = coding_mod.coded_block(views, tot, bl, parity, k, i)
            arts[i] = b"".join(
                bytes(p) for p in coding_mod.build_block_parts(
                    0, 2, k, m, i, blk, tot, digest, **meta))
        for drop in itertools.islice(
            itertools.combinations(range(k + m), m), 6
        ):
            got = coding_mod.reconstruct_container(
                [a for i, a in arts.items() if i not in drop])
            assert got == frame
            assert is_delta(got)
        out_path = str(tmp_path / "applied.ckpt")
        apply_delta(frame, base_path, out_path)
        assert open(out_path, "rb").read() == want

    def test_corrupt_frame_block_fails_closed(self, tmp_path):
        frame, _, _ = _delta_frame_fixture(tmp_path)
        views, tot, bl, parity = coding_mod.encode_payload([frame], 2, 1)
        meta = coding_mod._payload_meta([frame])
        digest = meta.pop("digest")
        # Wrong digest in the artifacts: reconstruction must not return a
        # frame whose whole-frame CRC disagrees with the recorded identity.
        arts = []
        for i in (0, 1):
            blk = coding_mod.coded_block(views, tot, bl, parity, 2, i)
            arts.append(b"".join(
                bytes(p) for p in coding_mod.build_block_parts(
                    0, 2, 2, 1, i, blk, tot, digest ^ 1, **meta)))
        with pytest.raises(CheckpointError, match="digest mismatch"):
            coding_mod.reconstruct_container(arts)


def _delta_erasure_body(root, make_store, rank, gen, *, iters=(),
                        interval=4, load=False, world=WORLD3,
                        pipelined=False):
    comm = StoreComm(make_store(), rank, list(world), timeout=60.0,
                     generation=gen)
    ex = PeerExchange(make_store(), rank, timeout=30.0)
    ex.start()
    try:
        strat = ErasureReplicationStrategy(
            comm, ex, replication_jump=1, replication_factor=len(world),
            parity=1)
        mgr = LocalCheckpointManager(
            root, rank=rank, comm=comm, replication=strat, keep=2,
            delta_interval=interval, pipelined=pipelined)
        for it in iters:
            arr = np.full((1 << 21,), float(rank), np.float32)
            arr[:128] += it  # ~small dirty fraction between saves
            mgr.save(it, PyTreeStateDict({"w": arr, "step": it}),
                     is_async=pipelined)
            mgr.maybe_finalize(blocking=True)
        out = None
        if load:
            hollow, tensors, meta = mgr.load()
            out = (meta["iteration"], np.asarray(tensors[0]).copy())
        mgr.close()
        return out, sorted(mgr.block_ids())
    finally:
        ex.close()


class TestDeltaErasureE2E:
    @pytest.mark.parametrize("pipelined", [False, True])
    def test_delta_round_codes_the_frame(
        self, tmp_path, make_store, sink, pipelined
    ):
        """Iteration 2 is a delta round under erasure: the parity exchange
        codes the FRAME (payload_bytes collapses), peers hold block
        artifacts for it, and the wire still moves ≤ (1+1/k)× the frame."""
        root = str(tmp_path / "ckpt")
        out = run_ranks(WORLD3, lambda r: _delta_erasure_body(
            root, make_store, r, 0, iters=(1, 2), pipelined=pipelined))
        for rank, (_, blocks) in zip(WORLD3, out):
            assert sorted({b[0] for b in blocks}) == [1, 2]
        deltas = [e for e in sink if e.kind == "ckpt_delta"]
        assert len(deltas) == len(WORLD3)  # iteration 2, every rank
        parity = {e.payload["payload_bytes"]: e for e in sink
                  if e.kind == "ckpt_parity"}
        small, big = min(parity), max(parity)
        # One dirty chunk of an 8-chunk container: the frame round's coded
        # payload collapses to ~prefix+trailer+1 chunk (the ≥20× win at 5%
        # dirty on a wide container is BENCH_replication's gate).
        assert small * 4 < big  # frame rounds vs keyframe rounds
        for e in parity.values():
            k = e.payload["k"]
            assert e.payload["sent_bytes"] <= 1.1 * (
                e.payload["payload_bytes"] * (1 + 1 / k)) + 4096 * k

    def test_lost_owner_delta_generation_reconstructs(
        self, tmp_path, make_store, sink
    ):
        """The owner loses its NEWEST (delta-generation) container but keeps
        the base: the ladder reconstructs the frame from peer blocks and
        applies it against the local base, byte-identically."""
        root = str(tmp_path / "ckpt")
        run_ranks(WORLD3, lambda r: _delta_erasure_body(
            root, make_store, r, 0, iters=(1, 2)))
        newest = os.path.join(root, "s0", "r0", CkptID(2, 0).filename())
        own = open(newest, "rb").read()
        os.unlink(newest)
        out = run_ranks(WORLD3, lambda r: _delta_erasure_body(
            root, make_store, r, 1, load=True))
        for rank, (loaded, _) in zip(WORLD3, out):
            it, w = loaded
            assert it == 2
            want = np.full((1 << 21,), float(rank), np.float32)
            want[:128] += 2
            np.testing.assert_array_equal(w, want)
        assert open(newest, "rb").read() == own
        applied = [e for e in sink if e.kind == "ckpt_delta_applied"]
        assert [e.payload["outcome"] for e in applied] == ["ok"]
        assert not [e for e in sink if e.kind == "ckpt_fallback"]

    def test_lost_base_breaks_chain_and_ladder_falls_back(
        self, tmp_path, make_store, sink
    ):
        """The owner loses its whole disk: iteration 2's frame reconstructs
        but cannot apply (no base), so the group agrees to fall back to the
        keyframe generation — never assembling from a wrong base."""
        root = str(tmp_path / "ckpt")
        run_ranks(WORLD3, lambda r: _delta_erasure_body(
            root, make_store, r, 0, iters=(1, 2)))
        import shutil
        shutil.rmtree(os.path.join(root, "s0", "r0"))
        out = run_ranks(WORLD3, lambda r: _delta_erasure_body(
            root, make_store, r, 1, load=True))
        for rank, (loaded, _) in zip(WORLD3, out):
            it, w = loaded
            assert it == 1  # keyframe generation
            want = np.full((1 << 21,), float(rank), np.float32)
            want[:128] += 1
            np.testing.assert_array_equal(w, want)
        broken = [e for e in sink if e.kind == "ckpt_delta_applied"
                  and e.payload["outcome"] == "broken"]
        assert broken
        assert [e for e in sink if e.kind == "ckpt_fallback"]


# -- delta chain --------------------------------------------------------------


class TestDeltaTracker:
    def test_interval_cadence(self):
        t = DeltaTracker(3)
        assert t.enabled
        sizes = [1024]
        assert t.eligible(sizes) is None  # no base yet
        t.note_saved(1, sizes, 256, [[1, 2, 3, 4]], 99, keyframe=True)
        assert t.eligible(sizes) is not None  # delta 1 of cycle
        t.note_saved(2, sizes, 256, [[1, 2, 3, 5]], 98, keyframe=False)
        assert t.eligible(sizes) is not None  # delta 2 of cycle
        t.note_saved(3, sizes, 256, [[1, 2, 3, 6]], 97, keyframe=False)
        assert t.eligible(sizes) is None  # keyframe due (interval=3)
        t.note_saved(4, sizes, 256, [[9, 2, 3, 6]], 96, keyframe=True)
        assert t.eligible(sizes) is not None
        assert t.eligible([2048]) is None  # signature moved
        t.reset()
        assert t.eligible(sizes) is None

    def test_env_default(self, monkeypatch):
        monkeypatch.setenv(delta_mod.DELTA_ENV, "5")
        assert DeltaTracker().interval == 5
        monkeypatch.setenv(delta_mod.DELTA_ENV, "bogus")
        assert DeltaTracker().interval == 0


class TestDeltaFrames:
    def _container(self, arr, it):
        prefix, views = ckpt_format.serialize_parts(
            b"hollow", [arr], meta={"iteration": it}
        )
        return prefix, views

    def test_encode_apply_roundtrip(self, tmp_path):
        cs = ckpt_format.DEFAULT_CHUNK
        base_arr = np.zeros(cs * 3 // 4, dtype=np.uint8)  # sub-chunk leaf
        base_arr[:] = 7
        p1, v1 = self._container(base_arr, 1)
        base_path = str(tmp_path / "base.ckpt")
        ckpt_format.write_parts(base_path, [p1, *v1])
        info = ckpt_format.parse_trailer_v3(v1[-1])
        base = {
            "iteration": 1,
            "leaf_sizes": [base_arr.nbytes],
            "chunk_size": info.chunk_size,
            "leaf_chunks": info.leaf_chunk_crcs([base_arr.nbytes]),
            "container_crc": info.container_crc,
        }
        new_arr = base_arr.copy()
        new_arr[5] = 9
        p2, v2 = self._container(new_arr, 2)
        frame, stats = encode_delta(0, 2, base, p2, v2[:-1], bytes(v2[-1]))
        assert is_delta(frame)
        assert stats["chunks_changed"] == 1
        out_path = str(tmp_path / "applied.ckpt")
        apply_delta(frame, base_path, out_path)
        want = b"".join([p2, *[bytes(memoryview(v).cast("B")) for v in v2]])
        assert open(out_path, "rb").read() == want
        assert ckpt_format.verify_file(out_path)[0] == "ok"

    def test_broken_chain_fails_closed(self, tmp_path):
        arr = np.arange(4096, dtype=np.uint8)
        p1, v1 = self._container(arr, 1)
        base_path = str(tmp_path / "base.ckpt")
        ckpt_format.write_parts(base_path, [p1, *v1])
        info = ckpt_format.parse_trailer_v3(v1[-1])
        base = {
            "iteration": 1,
            "leaf_sizes": [arr.nbytes],
            "chunk_size": info.chunk_size,
            "leaf_chunks": info.leaf_chunk_crcs([arr.nbytes]),
            "container_crc": info.container_crc,
        }
        new = arr.copy()
        new[0] ^= 1
        p2, v2 = self._container(new, 2)
        frame, _ = encode_delta(0, 2, base, p2, v2[:-1], bytes(v2[-1]))
        # A DIFFERENT base on disk (stale generation): digest mismatch.
        other = np.arange(4096, dtype=np.uint8)[::-1].copy()
        p3, v3 = self._container(other, 1)
        ckpt_format.write_parts(base_path, [p3, *v3])
        with pytest.raises(CheckpointError, match="stale or divergent"):
            apply_delta(frame, base_path, str(tmp_path / "out.ckpt"))
        # Missing base entirely.
        with pytest.raises(CheckpointError, match="unusable"):
            apply_delta(frame, str(tmp_path / "gone.ckpt"),
                        str(tmp_path / "out.ckpt"))


def _delta_body(root, make_store, rank, *, iters, interval, world=(0, 1),
                pipelined=False, skip_base_mirror=False):
    comm = StoreComm(make_store(), rank, list(world), timeout=60.0)
    ex = PeerExchange(make_store(), rank, timeout=30.0)
    ex.start()
    try:
        strat = CliqueReplicationStrategy(
            comm, ex, replication_jump=1, replication_factor=len(world))
        mgr = LocalCheckpointManager(
            root, rank=rank, comm=comm, replication=strat,
            delta_interval=interval, keep=2, pipelined=pipelined)
        for it in iters:
            arr = np.full((1 << 21,), float(rank), np.float32)
            arr[: 128] += it  # small dirty fraction
            mgr.save(it, PyTreeStateDict({"w": arr, "step": it}),
                     is_async=pipelined)
            mgr.maybe_finalize(blocking=True)
            if skip_base_mirror and it == iters[0] and rank == 1:
                # Simulate a joiner that missed the keyframe: drop the
                # mirror of rank 0's base before the delta round.
                p = os.path.join(root, "s0", "r1", CkptID(it, 0).filename())
                os.unlink(p)
        hollow, tensors, meta = mgr.load()
        mgr.close()
        return meta["iteration"], np.asarray(tensors[0]).copy()
    finally:
        ex.close()


class TestDeltaE2E:
    @pytest.mark.parametrize("pipelined", [False, True])
    def test_chain_round_trips_byte_identical(
        self, tmp_path, make_store, sink, pipelined
    ):
        root = str(tmp_path / "ckpt")
        out = run_ranks([0, 1], lambda r: _delta_body(
            root, make_store, r, iters=(1, 2, 3), interval=4,
            pipelined=pipelined))
        for rank, (it, w) in zip([0, 1], out):
            assert it == 3
            want = np.full((1 << 21,), float(rank), np.float32)
            want[:128] += 3
            np.testing.assert_array_equal(w, want)
        deltas = [e for e in sink if e.kind == "ckpt_delta"]
        applied = [e for e in sink if e.kind == "ckpt_delta_applied"]
        assert len(deltas) == 4  # iters 2 and 3, both ranks
        assert all(e.payload["outcome"] == "ok" for e in applied)
        # Byte economy: the frame is a small fraction of the container.
        for e in deltas:
            assert e.payload["frame_bytes"] * 4 < e.payload["full_bytes"]
        # Mirrors are byte-identical to the sender's own container.
        for rank in (0, 1):
            own = open(os.path.join(
                root, "s0", f"r{rank}", CkptID(3, rank).filename()), "rb").read()
            mirror = open(os.path.join(
                root, "s0", f"r{1 - rank}", CkptID(3, rank).filename()),
                "rb").read()
            assert own == mirror, rank

    def test_keyframe_cadence_respected(self, tmp_path, make_store, sink):
        root = str(tmp_path / "ckpt")
        run_ranks([0, 1], lambda r: _delta_body(
            root, make_store, r, iters=(1, 2, 3, 4, 5), interval=3))
        deltas = sorted(
            e.payload["iteration"] for e in sink if e.kind == "ckpt_delta"
        )
        # interval=3: keyframes at 1 and 4; deltas at 2, 3 and 5 (per rank).
        assert deltas == [2, 2, 3, 3, 5, 5]

    def test_broken_chain_drops_mirror_and_ladder_survives(
        self, tmp_path, make_store, sink
    ):
        """A peer missing the base container cannot apply the delta: the
        mirror is skipped (ckpt_delta_applied{broken}), the owner's copy
        still covers the iteration, and load() serves everyone."""
        root = str(tmp_path / "ckpt")
        out = run_ranks([0, 1], lambda r: _delta_body(
            root, make_store, r, iters=(1, 2), interval=4,
            skip_base_mirror=True))
        for rank, (it, w) in zip([0, 1], out):
            assert it == 2
        broken = [
            e for e in sink
            if e.kind == "ckpt_delta_applied"
            and e.payload["outcome"] == "broken"
        ]
        assert broken and broken[0].payload["owner"] == 0
        # The dropped mirror really is absent; coverage rode the owner copy.
        assert not os.path.exists(
            os.path.join(root, "s0", "r1", CkptID(2, 0).filename()))


# -- TPURES03 chunk manifest + version skew ----------------------------------


def _write_v2(path, arrays, meta=None):
    """Hand-built TPURES02 container — what pre-chunk code wrote."""
    views = [ckpt_format._raw_view(np.ascontiguousarray(a)) for a in arrays]
    leaf_crcs = [ckpt_format.crc32c(v) for v in views]
    header = {
        "hollow": pickle.dumps("v2-skeleton"),
        "leaves": [
            {"shape": a.shape, "dtype": a.dtype.name, "nbytes": a.nbytes,
             "crc32c": c}
            for a, c in zip(arrays, leaf_crcs)
        ],
        "meta": meta or {},
    }
    hb = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    prefix = ckpt_format.MAGIC_V2 + struct.pack("<Q", len(hb)) + hb
    trailer = ckpt_format.build_trailer(
        leaf_crcs, ckpt_format._container_crc(prefix, leaf_crcs)
    )
    with open(path, "wb") as f:
        f.write(prefix)
        for v in views:
            f.write(v)
        f.write(trailer)
    return b"".join([prefix, *[bytes(v) for v in views], trailer])


class TestFormatSkew:
    def test_v3_writers_and_chunk_manifest(self, tmp_path):
        path = str(tmp_path / "c.ckpt")
        arr = np.arange(ckpt_format.DEFAULT_CHUNK // 2, dtype=np.uint8)
        ckpt_format.write_payload(path, b"h", [arr, arr[: 100]])
        with open(path, "rb") as f:
            assert f.read(8) == b"TPURES03"
        header, prefix_len, info = ckpt_format.read_trailer(path)
        assert info.chunk_size == ckpt_format.DEFAULT_CHUNK
        assert len(info.chunk_crcs) == 2  # one per (sub-chunk) leaf
        rep = ckpt_format.chunk_report(path)
        assert rep["status"] == "ok" and not any(
            leaf["bad"] for leaf in rep["leaves"]
        )

    def test_chunk_corruption_located(self, tmp_path):
        path = str(tmp_path / "c.ckpt")
        cs = 4096
        os.environ[ckpt_format.CHUNK_ENV] = str(cs)
        try:
            arr = np.zeros(cs * 3, dtype=np.uint8)
            ckpt_format.write_payload(path, b"h", [arr])
        finally:
            del os.environ[ckpt_format.CHUNK_ENV]
        header, prefix_len, info = ckpt_format.read_trailer(path)
        assert info.chunk_size == cs and len(info.chunk_crcs) == 3
        with open(path, "r+b") as f:
            f.seek(prefix_len + cs + 17)  # inside chunk 1
            f.write(b"\xff")
        status, detail = ckpt_format.verify_file(path)
        assert status == "corrupt" and "chunk 1" in detail
        rep = ckpt_format.chunk_report(path)
        assert rep["leaves"][0]["bad"] == [1]

    def test_v2_container_loads_fully_verified(self, tmp_path, sink):
        path = str(tmp_path / "v2.ckpt")
        arr = np.arange(5000, dtype=np.float32)
        _write_v2(path, [arr], meta={"iteration": 3})
        hollow, tensors, meta = ckpt_format.read_payload(path)
        np.testing.assert_array_equal(tensors[0], arr)
        assert meta == {"iteration": 3}
        assert ckpt_format.verify_file(path)[0] == "ok"
        # No unverified event: v2 is verified at leaf granularity.
        assert not [e for e in sink if e.kind == "ckpt_unverified"]
        # ...but it has no chunk manifest.
        _, _, info = ckpt_format.read_trailer(path)
        assert info.chunk_crcs is None
        assert ckpt_format.chunk_report(path)["chunk_size"] is None
        # And a corrupted v2 payload is still caught (whole-leaf CRC).
        with open(path, "r+b") as f:
            f.seek(-300, 2)
            f.write(b"\x00\x01\x02")
        assert ckpt_format.verify_file(path)[0] == "corrupt"

    def test_v2_blob_replicates_and_verifies_on_receive(self, tmp_path):
        arr = np.arange(999, dtype=np.int32)
        blob = _write_v2(str(tmp_path / "x.ckpt"), [arr])
        assert ckpt_format.verify_container(blob) is True
        bad = bytearray(blob)
        bad[len(blob) - 100] ^= 0x40  # payload byte
        with pytest.raises(CheckpointError):
            ckpt_format.verify_container(bytes(bad))

    def test_mixed_clique_v2_mirror_retrieves_byte_identical(
        self, tmp_path, make_store
    ):
        """TPURES03 ↔ TPURES02 skew: rank 1 holds rank 0's shard as a v2
        container (written by old code); the retrieve rung serves it and the
        round-trip is byte-identical."""
        root = str(tmp_path / "ckpt")
        arr = np.arange(20000, dtype=np.float32)
        # Seed the disk layout an old-code clique left behind: rank 1 holds
        # its OWN v3 container plus a v2 mirror of rank 0's shard; rank 0's
        # disk is empty (the lost rank).
        r1 = os.path.join(root, "s0", "r1")
        os.makedirs(r1, exist_ok=True)
        v2_blob = _write_v2(
            os.path.join(r1, CkptID(1, 0).filename()), [arr],
            meta={"iteration": 1},
        )
        own = np.full((64,), 11.0, np.float32)
        ckpt_format.write_payload(
            os.path.join(r1, CkptID(1, 1).filename()),
            pickle.dumps("own-skeleton"), [own], meta={"iteration": 1},
        )

        def body(rank):
            comm = StoreComm(make_store(), rank, [0, 1], timeout=60.0)
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2)
                mgr = LocalCheckpointManager(
                    root, rank=rank, comm=comm, replication=strat)
                hollow_t, tensors, meta = mgr.load()
                mgr.close()
                return np.asarray(tensors[0]).copy()
            finally:
                ex.close()

        out = run_ranks([0, 1], body)
        np.testing.assert_array_equal(out[0], arr)
        # The retrieved v2 shard was re-persisted byte-identically.
        p0 = os.path.join(root, "s0", "r0", CkptID(1, 0).filename())
        assert open(p0, "rb").read() == v2_blob
