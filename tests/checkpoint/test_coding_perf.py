"""Perf acceptance for the checkpoint byte-economy plane (slow; tier-1
deselects ``-m slow``).

Runs ``scripts/bench_replication.py`` at a CI-sized payload and asserts the
two ACCEPTANCE byte claims against the same arithmetic the committed
``BENCH_replication.json`` records:

- **erasure**: wire bytes per rank per save ≤ ``(1 + 1/k)×`` the payload
  (full mirrors move ``(world-1)×``);
- **delta** (steady state, small dirty fraction): ≥5× fewer replication
  bytes than a full-mirror round.
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@pytest.mark.slow
def test_erasure_and_delta_byte_economy(tmp_path):
    out = tmp_path / "bench.json"
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "bench_replication.py"),
            "--mb", "48", "--world", "3", "--rounds", "2",
            "--dirty-frac", "0.05", "--alloc-mb", "2",
            "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-3000:]
    res = json.loads(out.read_text())
    er = res["erasure"]
    # k-of-n: one block per peer, owner's block implicit — the wire moves
    # ~payload, never (world-1)x payload. Small slack for artifact headers.
    assert er["payload_ratio"] <= (1 + 1 / er["k"]) + 0.05, er
    assert er["payload_ratio"] < er["mirror_payload_ratio"] / 1.5, er
    # Delta at 5% dirty chunks: ≥5x fewer bytes than the full mirror round
    # (48 MB / 1 MiB chunks = 48 chunks; ~5% dirty ships a handful).
    de = res["delta"]
    assert de["full_bytes"] >= 5 * de["frame_bytes"], de
    assert de["bytes_ratio"] <= 0.2, de
