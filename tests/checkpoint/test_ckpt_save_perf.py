"""Perf acceptance for the pipelined snapshot engine (slow; tier-1 deselects
``-m slow``). Runs ``scripts/bench_ckpt_save.py`` end to end at a CI-sized
payload and asserts the save-side claims: the caller-visible foreground window
of a pipelined save is at most 0.25× the synchronous jax.device_get engine's,
end-to-end latency does not regress, and the warm save's peak transient host
allocation stays under 1 MB (staging-pool hit). The committed 256 MB / 1 GB
results live in ``BENCH_ckpt_save.json``."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
def test_pipelined_foreground_window_vs_sync_baseline(tmp_path):
    out = tmp_path / "bench.json"
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "bench_ckpt_save.py"),
            "--mb", "48", "--world", "2", "--rounds", "3", "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr
    results = json.loads(out.read_text())
    (size,) = results["sizes"]
    # The headline gate: the train loop's stall shrinks to at most a quarter
    # of the blocking-D2H engine's (the committed 256 MB run shows ~100×).
    assert size["fg_ratio"] <= 0.25, size
    # Pipelining must not buy foreground latency with end-to-end latency.
    assert size["pipelined_e2e_ms"] <= size["sync_e2e_ms"] * 1.25, size
    # Steady state rode the pool: second+ saves allocated nothing large.
    assert size["staging"]["hits"] >= 1, size
    assert size["staging"]["misses"] <= 2, size
    assert results["steady_state_peak_alloc_mb"] < 1.0, results
    # Cold-tier non-interference (the BENCH_ckpt_save.json foreground-window
    # gate): attaching the durable cold tier must leave the caller-visible
    # save window unchanged within noise — a synchronous upload would add
    # the whole container's write time and fail by a mile — while every
    # keyframe (world x rounds) still lands in the object store, undegraded.
    cold = size["cold"]
    assert cold["spills"] == 2 * 3, cold
    assert cold["degraded"] == 0, cold
    assert cold["spilled_bytes"] > 0, cold
    assert cold["cold_fg_ms"] <= max(
        cold["base_fg_ms"] * 2.0, cold["base_fg_ms"] + 25.0
    ), cold
