"""Elastic resharding: plan algebra properties, the ranged-read wire op, and
end-to-end resumes across changed worlds (shrink, grow, changed DP/TP split)
with byte-identical reassembled global state."""

import concurrent.futures as cf
import os

import numpy as np
import pytest

from tpu_resiliency.checkpoint import reshard as R
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform.store import CoordStore
from tpu_resiliency.utils import events


def run_ranks(world, fn, timeout=90.0):
    with cf.ThreadPoolExecutor(max_workers=len(world)) as pool:
        futures = [pool.submit(fn, r) for r in world]
        return [f.result(timeout=timeout) for f in futures]


@pytest.fixture
def make_store(kv_server):
    stores = []

    def factory():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
        stores.append(s)
        return s

    yield factory
    for s in stores:
        s.close()


@pytest.fixture
def sink():
    seen = []
    events.add_sink(seen.append)
    yield seen
    events.remove_sink(seen.append)


def _mem_read(locals_by_rank):
    def read(owner, leaf, off, n):
        flat = locals_by_rank[owner][leaf].reshape(-1).view(np.uint8)
        return flat[off : off + n].tobytes()

    return read


def _reassemble_global(layout, locals_by_rank, leaf):
    spec = layout.leaves[leaf]
    out = np.zeros(spec.global_shape, dtype=np.dtype(spec.dtype))
    filled = np.zeros(spec.global_shape, dtype=np.int32)
    for r in layout.ranks:
        b = layout.box(leaf, r)
        sl = tuple(slice(o, o + s) for o, s in zip(b.offset, b.shape))
        out[sl] = locals_by_rank[r][leaf]
        filled[sl] += 1
    return out, filled


class TestPlanAlgebra:
    def _random_case(self, seed):
        rng = np.random.default_rng(seed)
        worlds = [(1, 1), (2, 1), (3, 1), (4, 1), (2, 2), (6, 1), (2, 3), (1, 2)]
        src_axes = list(zip(["dp", "tp"], worlds[rng.integers(0, len(worlds))]))
        tgt_axes = list(zip(["dp", "tp"], worlds[rng.integers(0, len(worlds))]))
        n = int(np.prod([s for _, s in src_axes]))
        m = int(np.prod([s for _, s in tgt_axes]))
        leaves, arrays = [], []
        for _ in range(int(rng.integers(1, 4))):
            ndim = int(rng.integers(1, 4))
            shape = tuple(int(rng.integers(2, 13)) for _ in range(ndim))
            options: list = [None, "dp", "tp"]
            spec_raw = [options[rng.integers(0, 3)] for _ in range(ndim)]
            # one axis per dim, no repeats across dims
            seen: set = set()
            spec = tuple(
                a if a is None or (a not in seen and not seen.add(a)) else None
                for a in spec_raw
            )
            leaves.append(R.LeafSpec(shape, "float32", spec))
            arrays.append(rng.standard_normal(shape).astype(np.float32))
        src = R.TreeLayout(src_axes, list(range(n)), leaves)
        tgt = R.TreeLayout(tgt_axes, list(range(m)), leaves)
        return src, tgt, arrays

    @pytest.mark.parametrize("seed", range(10))
    def test_round_trip_byte_identical_and_exact_cover(self, seed):
        """Property sweep: N→M→N round-trips byte-identically, and the M-world
        reassembly covers every global index exactly once."""
        src, tgt, arrays = self._random_case(seed)
        plan = R.build_plan(src, tgt)  # build_plan runs validate()
        locals_src = {r: R.slice_local(arrays, src, r) for r in src.ranks}
        locals_tgt = {
            r: R.assemble_rank(plan, r, _mem_read(locals_src))
            for r in tgt.ranks
        }
        for i, arr in enumerate(arrays):
            got, filled = _reassemble_global(tgt, locals_tgt, i)
            assert np.array_equal(got, arr), (seed, i)
            # every global index written by at least one target rank; replicas
            # write identical bytes so exact-once is proven per-rank by
            # validate() and globally by full coverage here
            assert (filled > 0).all(), (seed, i)
        back = R.build_plan(tgt, src)
        locals_rt = {
            r: R.assemble_rank(back, r, _mem_read(locals_tgt))
            for r in src.ranks
        }
        for r in src.ranks:
            for a, b in zip(locals_rt[r], locals_src[r]):
                assert a.tobytes() == b.tobytes(), (seed, r)

    def test_balanced_blocks_survive_non_divisible_shrink(self):
        src = R.TreeLayout(
            [("dp", 4)], [0, 1, 2, 3],
            [R.LeafSpec((10, 3), "float32", ("dp",))],
        )
        tgt = src.retarget([0, 1, 2])
        plan = R.build_plan(src, tgt)
        # 10 rows over 3 ranks: balanced 3/3/4 split
        assert [plan.target.box(0, r).shape[0] for r in (0, 1, 2)] == [3, 3, 4]
        g = [np.arange(30, dtype=np.float32).reshape(10, 3)]
        locals_src = {r: R.slice_local(g, src, r) for r in src.ranks}
        for r in tgt.ranks:
            out = R.assemble_rank(plan, r, _mem_read(locals_src))
            assert np.array_equal(out[0], R.slice_local(g, tgt, r)[0])

    def test_validate_catches_tampered_plan(self):
        src = R.TreeLayout(
            [("dp", 2)], [0, 1], [R.LeafSpec((8,), "float32", ("dp",))]
        )
        plan = R.build_plan(src, src.retarget([0, 1]))
        rp = plan.for_rank(0)
        rp.segments[0].ranges[0] = R.Range(0, 4, 8)  # shift → gap at 0
        with pytest.raises(CheckpointError, match="gap|overlap"):
            plan.validate()

    def test_missing_sources_named_in_error(self):
        src = R.TreeLayout(
            [("dp", 4)], [0, 1, 2, 3],
            [R.LeafSpec((8, 2), "float32", ("dp",))],
        )
        plan = R.build_plan(src, src.retarget([0, 1]))
        plan.require_available([0, 1, 2, 3])
        with pytest.raises(CheckpointError, match=r"\[2, 3\]"):
            plan.require_available([0, 1])

    def test_replicas_grouped_as_one_cell(self):
        # params sharded only over tp: the dp axis replicates them — each tp
        # cell lists BOTH dp ranks as interchangeable owners.
        src = R.TreeLayout(
            [("dp", 2), ("tp", 2)], [0, 1, 2, 3],
            [R.LeafSpec((4, 8), "float32", (None, "tp"))],
        )
        cells = src.cells(0)
        assert [owners for _, owners in cells] == [(0, 2), (1, 3)]
        # losing one dp replica of each cell still covers a shrink
        plan = R.build_plan(src, src.retarget([0, 1]))
        plan.require_available([2, 3])

    def test_layout_meta_roundtrip(self):
        src = R.TreeLayout(
            [("dp", 2), ("tp", 2)], [0, 1, 2, 3],
            [
                R.LeafSpec((8, 4), "float32", ("dp", "tp")),
                R.LeafSpec((3,), "int32", (None,)),
            ],
        )
        rt = R.TreeLayout.from_meta(src.to_meta())
        assert rt.to_meta() == src.to_meta()
        assert R.extract_layout({"layout": src.to_meta()}).to_meta() == src.to_meta()
        assert R.extract_layout({}) is None
        with pytest.raises(CheckpointError):
            R.TreeLayout.from_meta({"schema": "bogus"})

    def test_retarget_rescales_dp_and_rejects_impossible(self):
        src = R.TreeLayout(
            [("dp", 4), ("tp", 2)], list(range(8)),
            [R.LeafSpec((16,), "float32", ("dp",))],
        )
        tgt = src.retarget(list(range(6)))
        assert dict(tgt.axes) == {"dp": 3, "tp": 2}
        with pytest.raises(CheckpointError, match="non-dp"):
            src.retarget(list(range(5)))
        explicit = src.retarget(list(range(8)), axes={"dp": 2, "tp": 4})
        assert dict(explicit.axes) == {"dp": 2, "tp": 4}

    def test_layout_validation_errors(self):
        with pytest.raises(CheckpointError, match="unknown axis"):
            R.TreeLayout(
                [("dp", 2)], [0, 1], [R.LeafSpec((4,), "float32", ("tp",))]
            )
        with pytest.raises(CheckpointError, match="more than one dim"):
            R.TreeLayout(
                [("dp", 2)], [0, 1],
                [R.LeafSpec((4, 4), "float32", ("dp", "dp"))],
            )
        with pytest.raises(CheckpointError, match="describe"):
            R.TreeLayout(
                [("dp", 3)], [0, 1], [R.LeafSpec((4,), "float32", (None,))]
            )
        with pytest.raises(CheckpointError, match="geometry mismatch"):
            R.build_plan(
                R.TreeLayout(
                    [("dp", 1)], [0], [R.LeafSpec((4,), "float32", (None,))]
                ),
                R.TreeLayout(
                    [("dp", 1)], [0], [R.LeafSpec((5,), "float32", (None,))]
                ),
            )

    def test_for_local_tree_aligns_with_pop_order(self):
        import jax

        from tpu_resiliency.parallel.mesh import checkpoint_layout
        from tpu_resiliency.platform.device import make_mesh

        mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices("cpu")[:4])
        tree = {
            "a": np.ones((4, 3), np.float32),  # dp-sharded rows (local view)
            "step": 7,                          # non-array leaf: skipped
            "z": np.ones((2, 5), np.float32),  # tp-sharded cols
        }
        from jax.sharding import PartitionSpec as P

        specs = {"a": P("dp"), "step": None, "z": P(None, "tp")}
        layout = checkpoint_layout(mesh, tree, specs)
        assert dict(layout.axes) == {"dp": 2, "tp": 2}
        assert [l.global_shape for l in layout.leaves] == [(8, 3), (2, 10)]
        # pop order == tree order of array leaves
        sd = PyTreeStateDict(dict(tree))
        popped = sd.pop_tensors()
        assert [tuple(t.shape) for t in popped] == [(4, 3), (2, 5)]


class TestRangedReadOp:
    def _pair(self, make_store):
        exs = []
        for rank in (0, 1):
            ex = PeerExchange(make_store(), rank, timeout=10.0)
            ex.start()
            exs.append(ex)
        return exs

    def test_fetch_ranges_roundtrip_with_crcs(self, make_store):
        ex0, ex1 = self._pair(make_store)
        try:
            payload = bytes(range(256)) * 4
            served = []

            def handler(req):
                served.append(req)
                return {"tag": "extra"}, [
                    payload[off : off + n] for _, off, n in req["ranges"]
                ]

            ex1.serve_ranges(handler)
            header, parts = ex0.fetch_ranges(
                1, {"ranges": [[0, 16, 32], [0, 512, 64]]}
            )
            assert header["ok"] and header["tag"] == "extra"
            assert bytes(parts[0]) == payload[16:48]
            assert bytes(parts[1]) == payload[512:576]
            assert header["crc_algo"] and len(header["crc32c"]) == 2
            assert served and served[0]["ranges"] == [[0, 16, 32], [0, 512, 64]]
        finally:
            ex0.close()
            ex1.close()

    def test_unserved_peer_is_a_classified_error(self, make_store):
        ex0, ex1 = self._pair(make_store)
        try:
            with pytest.raises(CheckpointError, match="serves no ranged reads"):
                ex0.fetch_ranges(1, {"ranges": [[0, 0, 4]]}, timeout=10.0)
        finally:
            ex0.close()
            ex1.close()

    def test_handler_exception_becomes_error_reply(self, make_store):
        ex0, ex1 = self._pair(make_store)
        try:
            def handler(req):
                raise CheckpointError("no such shard on this rank")

            ex1.serve_ranges(handler)
            with pytest.raises(CheckpointError, match="no such shard"):
                ex0.fetch_ranges(1, {"ranges": [[0, 0, 4]]}, timeout=10.0)
        finally:
            ex0.close()
            ex1.close()

    def test_concurrent_fetches_use_distinct_reply_tags(self, make_store):
        ex0, ex1 = self._pair(make_store)
        try:
            ex1.serve_ranges(
                lambda req: ({}, [bytes([req["ranges"][0][1] % 251]) * 8])
            )
            with cf.ThreadPoolExecutor(4) as pool:
                futs = [
                    pool.submit(
                        ex0.fetch_ranges, 1, {"ranges": [[0, i, 8]]}
                    )
                    for i in range(4)
                ]
                for i, f in enumerate(futs):
                    _, parts = f.result(timeout=30)
                    assert bytes(parts[0]) == bytes([i % 251]) * 8
        finally:
            ex0.close()
            ex1.close()


GLOBAL = np.arange(24 * 6, dtype=np.float32).reshape(24, 6)


class TestReshardE2E:
    """ACCEPTANCE: a checkpoint saved at N ranks loads at M ranks — shrink,
    grow, AND a changed DP/TP split of the same N — with byte-identical
    reassembled global state, the peer path moving only newly-owned ranges."""

    def _save_world(self, make_store, tmp_path, layout, factor=2, gen=0):
        root = str(tmp_path / "ckpt")

        def body(rank):
            comm = StoreComm(
                make_store(), rank, list(layout.ranks), timeout=30.0,
                generation=gen,
            )
            ex = PeerExchange(make_store(), rank, timeout=10.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=factor
                )
                mgr = LocalCheckpointManager(
                    root, rank=rank, comm=comm, replication=strat
                )
                tree = {
                    "w": R.slice_local([GLOBAL], layout, rank)[0],
                    "step": 11,
                }
                mgr.save(
                    1, PyTreeStateDict(tree), is_async=False, layout=layout
                )
                mgr.close()
            finally:
                ex.close()

        run_ranks(list(layout.ranks), body)
        return root

    def _load_world(
        self, make_store, root, world, gen, axes=None, target=None,
        iteration=None,
    ):
        def body(rank):
            comm = StoreComm(
                make_store(), rank, world, timeout=30.0, generation=gen
            )
            ex = PeerExchange(make_store(), rank, timeout=10.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    root, rank=rank, comm=comm, replication=strat
                )
                hollow, tensors, meta = mgr.load_resharded(
                    target=target, axes=axes, iteration=iteration
                )
                mgr.close()
                return hollow, [np.asarray(t).copy() for t in tensors], meta
            finally:
                ex.close()

        return run_ranks(world, body)

    def test_shrink_grow_and_resplit_byte_identical(
        self, make_store, tmp_path, sink
    ):
        src = R.TreeLayout(
            [("dp", 4)], [0, 1, 2, 3],
            [R.LeafSpec(GLOBAL.shape, "float32", ("dp",))],
        )
        root = self._save_world(make_store, tmp_path, src)

        # -- shrink 4 → 3 (rank 3 preempted; its state lives on in r2's
        # mirror), then the shrunken world checkpoints at ITS OWN layout —
        # the "shrink, keep training" half of the elastic story.
        tgt3 = src.retarget([0, 1, 2])

        def shrink_and_save(rank):
            comm = StoreComm(
                make_store(), rank, [0, 1, 2], timeout=30.0, generation=1
            )
            ex = PeerExchange(make_store(), rank, timeout=10.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    root, rank=rank, comm=comm, replication=strat, keep=2
                )
                hollow, tensors, meta = mgr.load_resharded()
                resumed = [np.asarray(t).copy() for t in tensors]
                layout = R.TreeLayout.from_meta(meta["layout"])
                mgr.save(
                    2, PyTreeStateDict({"w": resumed[0], "step": 12}),
                    is_async=False, layout=layout,
                )
                mgr.close()
                return hollow, resumed, meta
            finally:
                ex.close()

        out = run_ranks([0, 1, 2], shrink_and_save)
        locals3 = {}
        for rank, (hollow, tensors, meta) in zip([0, 1, 2], out):
            want = R.slice_local([GLOBAL], tgt3, rank)[0]
            assert np.array_equal(tensors[0], want), rank
            assert hollow["step"] == 11
            assert meta["layout"]["ranks"] == [0, 1, 2]
            locals3[rank] = tensors
        got, _ = _reassemble_global(tgt3, locals3, 0)
        assert np.array_equal(got, GLOBAL)

        # -- grow 3 → 4 (rank 3 returns with a wiped disk; newest iteration
        # is the shrunken world's save, so the resume is a true grow)
        import shutil

        shutil.rmtree(os.path.join(root, "s0", "r3"))
        out4 = self._load_world(make_store, root, [0, 1, 2, 3], gen=2)
        for rank, (hollow, tensors, meta) in zip([0, 1, 2, 3], out4):
            want = R.slice_local([GLOBAL], src, rank)[0]
            assert np.array_equal(tensors[0], want), rank
            assert hollow["step"] == 12
            assert meta["iteration"] == 2

        # -- changed split, same N: iteration 1's dp4 layout → dp2·tp2 (leaf
        # stays dp-sharded; tp replicates it, so pairs hold identical halves)
        out_rs = self._load_world(
            make_store, root, [0, 1, 2, 3], gen=3, axes={"dp": 2, "tp": 2},
            iteration=1,
        )
        tgt_rs = src.retarget([0, 1, 2, 3], axes={"dp": 2, "tp": 2})
        for rank, (hollow, tensors, meta) in zip([0, 1, 2, 3], out_rs):
            want = R.slice_local([GLOBAL], tgt_rs, rank)[0]
            assert np.array_equal(tensors[0], want), rank

        plans = [e for e in sink if e.kind == "reshard_plan"]
        directions = {e.payload["direction"] for e in plans}
        assert {"shrink", "grow", "resplit"} <= directions
        fetches = [e for e in sink if e.kind == "reshard_fetch"]
        assert any(e.payload["via"] == "peer" for e in fetches)
        assert any(e.payload["via"] == "local" for e in fetches)

    def test_reshard_metrics_aggregate(self, make_store, tmp_path, sink):
        src = R.TreeLayout(
            [("dp", 2)], [0, 1], [R.LeafSpec((8, 3), "float32", ("dp",))]
        )
        root = self._save_world(make_store, tmp_path, src)
        self._load_world(make_store, root, [0], gen=1)
        from tpu_resiliency.utils.metrics import aggregate

        reg = aggregate([{"kind": e.kind, **e.payload} for e in sink])
        prom = reg.to_prometheus()
        assert "tpu_reshard_bytes_total" in prom
        assert 'direction="shrink"' in prom
        assert "tpu_reshard_ranks_total" in prom

    def test_uncoverable_shrink_names_missing_ranks(
        self, make_store, tmp_path
    ):
        src = R.TreeLayout(
            [("dp", 4)], [0, 1, 2, 3],
            [R.LeafSpec(GLOBAL.shape, "float32", ("dp",))],
        )
        root = self._save_world(make_store, tmp_path, src)
        # Destroy every copy of ranks 2 and 3 (own shards AND mirrors):
        import shutil

        shutil.rmtree(os.path.join(root, "s0", "r2"))
        shutil.rmtree(os.path.join(root, "s0", "r3"))

        def body(rank):
            comm = StoreComm(
                make_store(), rank, [0, 1], timeout=30.0, generation=1
            )
            ex = PeerExchange(make_store(), rank, timeout=10.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    root, rank=rank, comm=comm, replication=strat
                )
                with pytest.raises(CheckpointError) as exc:
                    mgr.load_resharded()
                mgr.close()
                return str(exc.value)
            finally:
                ex.close()

        msgs = run_ranks([0, 1], body)
        for m in msgs:
            assert "[2, 3]" in m, m

    def test_save_rejects_layout_disagreeing_with_tensors(self, tmp_path):
        """REGRESSION (found by the forked-process verify driver): a layout
        whose leaves are listed in tree-insertion order while the pytree
        flattens sorted-key first must fail AT SAVE TIME with a geometry
        error — not surface later as an unexplainable reshard
        'no live holder'."""
        mgr = LocalCheckpointManager(str(tmp_path / "ckpt"), rank=0, comm=None)
        # tree flattens sorted: "a" (2,2) then "z" (4,); layout lists them
        # swapped — the classic insertion-order mistake.
        bad = R.TreeLayout(
            [("dp", 1)], [0],
            [R.LeafSpec((4,), "float32", (None,)),
             R.LeafSpec((2, 2), "float32", (None,))],
        )
        sd = PyTreeStateDict(
            {"z": np.zeros((4,), np.float32), "a": np.zeros((2, 2), np.float32)}
        )
        with pytest.raises(CheckpointError, match="sorted-key"):
            mgr.save(1, sd, is_async=False, layout=bad)
        # leaf-count mismatch is also a save-time error
        sd2 = PyTreeStateDict({"a": np.zeros((2, 2), np.float32)})
        with pytest.raises(CheckpointError, match="leaves"):
            mgr.save(1, sd2, is_async=False, layout=bad)
        mgr.close()

    def test_load_rejects_header_disagreeing_layout(self, tmp_path):
        """Metas written before save-time validation existed (or hand-edited)
        must be cross-checked against the container's own header at load."""
        import pickle

        root = str(tmp_path / "ckpt")
        mgr = LocalCheckpointManager(root, rank=0, comm=None)
        good = R.TreeLayout(
            [("dp", 1)], [0], [R.LeafSpec((4,), "float32", (None,))]
        )
        mgr.save(
            1, PyTreeStateDict({"w": np.zeros((4,), np.float32)}),
            is_async=False, layout=good,
        )
        # Corrupt the EMBEDDED layout only (shape lie), rewriting the
        # container so its checksums stay valid.
        from tpu_resiliency.checkpoint import format as ckpt_format

        path = os.path.join(root, "s0", "r0", "iter_0000001_0_local.ckpt")
        hollow, tensors, meta = ckpt_format.read_payload(path)
        meta["layout"]["leaves"][0]["global_shape"] = [400]
        ckpt_format.write_payload(path, hollow, tensors, meta=meta)
        with pytest.raises(CheckpointError, match="container holds"):
            mgr.load_resharded()
        mgr.close()

    def test_explicit_iteration_fails_hard_without_fallback(
        self, make_store, tmp_path
    ):
        src = R.TreeLayout(
            [("dp", 1)], [0], [R.LeafSpec((4, 6), "float32", ("dp",))]
        )
        root = self._save_world(make_store, tmp_path, src, factor=1)
        mgr = LocalCheckpointManager(root, rank=0, comm=None)
        with pytest.raises(CheckpointError, match="iteration 9"):
            mgr.load_resharded(iteration=9)
        mgr.close()

    def test_single_rank_local_only_reshard(self, make_store, tmp_path):
        """comm=None world of one: a 2-rank checkpoint whose containers all
        sit on rank 0's disk (own shard + mirror) reshards to one rank with
        zero network."""
        src = R.TreeLayout(
            [("dp", 2)], [0, 1], [R.LeafSpec((6, 2), "float32", ("dp",))]
        )
        root = self._save_world(make_store, tmp_path, src)
        mgr = LocalCheckpointManager(root, rank=0, comm=None)
        hollow, tensors, meta = mgr.load_resharded()
        assert tensors[0].shape == (6, 2)
        assert np.array_equal(
            tensors[0], R.slice_local([GLOBAL[:6, :2].copy()], src.retarget([0]), 0)[0]
        )
        mgr.close()

    def test_placeholder_shapes_synced_to_target_world(
        self, make_store, tmp_path
    ):
        """The mesh-aware restore contract: after a resharded load the hollow
        skeleton's placeholders describe the TARGET world's local blocks (the
        saving world's shapes would mislead shape-driven sharding specs), and
        ``load_resharded_tree`` rebuilds a full tree from them."""
        from tpu_resiliency.checkpoint.state_dict import TensorPlaceholder

        src = R.TreeLayout(
            [("dp", 2)], [0, 1], [R.LeafSpec((8, 4), "float32", ("dp",))]
        )
        root = self._save_world(make_store, tmp_path, src)
        mgr = LocalCheckpointManager(root, rank=0, comm=None)
        hollow, tensors, meta = mgr.load_resharded()  # dp2 -> dp1
        import jax

        phs = [
            l
            for l in jax.tree_util.tree_flatten(
                hollow, is_leaf=lambda x: isinstance(x, TensorPlaceholder)
            )[0]
            if isinstance(l, TensorPlaceholder)
        ]
        assert [p.shape for p in phs] == [(8, 4)]  # target-local, not (4, 4)
        tree, meta2 = mgr.load_resharded_tree()
        assert tree["step"] == 11
        assert np.asarray(tree["w"]).shape == (8, 4)
        assert np.array_equal(
            np.asarray(tree["w"]), np.asarray(GLOBAL[:8, :4])
        )
        mgr.close()

    def test_corrupt_local_copy_falls_to_peer(self, make_store, tmp_path, sink):
        """A survivor whose mirror went bad mid-life quarantines it and
        ranged-fetches from the other replica holder instead."""
        src = R.TreeLayout(
            [("dp", 2)], [0, 1], [R.LeafSpec((8, 4), "float32", ("dp",))]
        )
        root = self._save_world(make_store, tmp_path, src, factor=2)
        # Flip a payload byte in rank 0's OWN shard copy; the mirror in r1
        # stays intact, so rank 0's reshard must fetch from rank 1.
        path = os.path.join(root, "s0", "r0", "iter_0000001_0_local.ckpt")
        with open(path, "r+b") as f:
            f.seek(os.path.getsize(path) - 40)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0x20]))

        def body(rank):
            comm = StoreComm(
                make_store(), rank, [0, 1], timeout=30.0, generation=1
            )
            ex = PeerExchange(make_store(), rank, timeout=10.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    root, rank=rank, comm=comm, replication=strat
                )
                hollow, tensors, meta = mgr.load_resharded()
                mgr.close()
                return [np.asarray(t).copy() for t in tensors]
            finally:
                ex.close()

        out = run_ranks([0, 1], body)
        for rank, tensors in zip([0, 1], out):
            want = R.slice_local([GLOBAL[:8, :4].copy()], src, rank)[0]
            assert np.array_equal(tensors[0], want), rank
        # Chunked (TPURES03) containers verify lazily per touched chunk, so
        # the corruption surfaces at the chunk-verify stage; a pre-chunk
        # container would have been caught by the whole-file reshard-verify.
        assert any(
            e.kind == "ckpt_quarantined"
            and e.payload.get("stage") in ("reshard-verify", "chunk-verify")
            for e in sink
        )
