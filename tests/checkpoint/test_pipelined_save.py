"""Pipelined async-D2H snapshot engine: foreground window, staging reuse,
leaf-streaming writes/replication, conflict backoff, and abandon-mid-write.

Multi-rank pieces follow the repo's loopback pattern (threads against one
KVServer); everything runs on the CPU backend."""

import concurrent.futures as cf
import os
import threading
import time
import tracemalloc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.async_ckpt import AsyncCheckpointer
from tpu_resiliency.checkpoint.async_core import AsyncCallsQueue, AsyncRequest
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy
from tpu_resiliency.checkpoint.staging import HostStagingPool
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils import events as events_mod
from tpu_resiliency.platform.store import CoordStore


@pytest.fixture
def capture_events():
    captured = []
    sink = captured.append
    events_mod.add_sink(sink)
    yield captured
    events_mod.remove_sink(sink)


def make_tree(scale=1.0):
    return {
        "params": {"w": jnp.full((256, 256), scale, jnp.float32),
                   "b": jnp.ones(256)},
        "opt": {"m": jnp.zeros((256, 256))},
        "step": 7,
    }


class TestPipelinedCheckpointer:
    def test_roundtrip_and_steady_state_pool_hit(self, tmp_path, capture_events):
        ckpt = AsyncCheckpointer()
        assert ckpt.pipelined
        for step in range(3):
            tree = dict(make_tree(float(step)), step=step)
            ckpt.async_save(tree, str(tmp_path / f"s{step}.ckpt"))
            ckpt.finalize_all()
        misses_after_warmup = ckpt.staging.misses
        tree = dict(make_tree(9.0), step=9)
        ckpt.async_save(tree, str(tmp_path / "steady.ckpt"))
        ckpt.finalize_all()
        # The acceptance gate: a steady-state save is a pure staging-pool hit —
        # no new large host buffers were allocated for it.
        assert ckpt.staging.misses == misses_after_warmup
        assert ckpt.staging.hits >= 1
        loaded, _ = AsyncCheckpointer.load(str(tmp_path / "steady.ckpt"))
        np.testing.assert_array_equal(
            np.asarray(loaded["params"]["w"]), np.full((256, 256), 9.0, np.float32)
        )
        assert loaded["step"] == 9
        # Instrumentation: the foreground window and the enqueue span exist.
        kinds = [(e.kind, e.payload) for e in capture_events]
        fg = [p for k, p in kinds if k == "ckpt_foreground_blocked"]
        assert fg and all(p["engine"] == "pipelined" for p in fg)
        spans = [p for k, p in kinds if k == "span_begin"]
        assert any(p.get("span") == "ckpt.save.enqueue" for p in spans)
        assert any(k == "staging_pool" for k, _ in kinds)
        ckpt.close()

    def test_steady_state_save_has_no_large_allocations(self, tmp_path):
        """Zero host allocations > 1 MB once the pool is warm: resolve lands in
        the leased buffer, the header pickle is KBs, and the streaming writer
        pushes views straight to the file."""
        ckpt = AsyncCheckpointer()
        for step in range(2):  # warm both double-buffer slots
            ckpt.async_save(make_tree(float(step)), str(tmp_path / f"w{step}.ckpt"))
            ckpt.finalize_all()
        tree = make_tree(3.0)
        jax.block_until_ready(tree)
        tracemalloc.start()
        base, _ = tracemalloc.get_traced_memory()
        ckpt.async_save(tree, str(tmp_path / "steady.ckpt"))
        ckpt.finalize_all()
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert peak - base < (1 << 20), (
            f"steady-state save allocated {peak - base} B (> 1 MiB)"
        )
        ckpt.close()

    def test_separation_hint_pipelined(self, tmp_path):
        tree = {
            "params": {"w": jnp.ones((64, 64), jnp.float32)},
            "opt_state": {"m": jnp.full((64, 64), 2.0, jnp.float32)},
            "step": 11,
        }
        path = str(tmp_path / "m.ckpt")
        ckpt = AsyncCheckpointer()
        ckpt.async_save(tree, path, meta={"it": 11}, separation_hint="opt_state")
        ckpt.finalize_all()
        merged, meta = AsyncCheckpointer.load(path, separation_hint="opt_state")
        assert meta == {"it": 11}
        np.testing.assert_array_equal(
            np.asarray(merged["opt_state"]["m"]),
            np.full((64, 64), 2.0, np.float32),
        )
        assert merged["step"] == 11
        ckpt.close()

    def test_per_file_leaf_counts_emitted(self, tmp_path, capture_events):
        tree = {
            "a": {"x": jnp.ones(8), "y": jnp.ones(8)},
            "b": {"z": jnp.ones(8)},
        }
        ckpt = AsyncCheckpointer()
        ckpt.async_save(tree, str(tmp_path / "m.ckpt"), separation_hint="b")
        ckpt.finalize_all()
        per_file = [
            e.payload for e in capture_events if e.kind == "ckpt_write_file"
        ]
        by_container = {p["container"]: p for p in per_file}
        assert by_container["hint"]["leaves"] == 1
        assert by_container["main"]["leaves"] == 2
        assert by_container["main"]["bytes"] > 0
        ckpt.close()

    def test_pipelined_requires_thread_caller(self):
        with pytest.raises(CheckpointError, match="thread"):
            AsyncCheckpointer(caller="process", pipelined=True)

    def test_process_caller_falls_back_to_materialized(self, tmp_path):
        ckpt = AsyncCheckpointer(caller="process")
        assert not ckpt.pipelined
        ckpt.async_save({"x": jnp.ones(4)}, str(tmp_path / "p.ckpt"))
        ckpt.finalize_all()
        tree, _ = AsyncCheckpointer.load(str(tmp_path / "p.ckpt"))
        np.testing.assert_array_equal(np.asarray(tree["x"]), np.ones(4, np.float32))
        ckpt.close()


class TestHostSnapshot:
    def test_resolve_order_independent(self):
        sd = PyTreeStateDict({"a": jnp.arange(4.0), "b": jnp.arange(3.0)})
        sd.pop_tensors()
        snap = sd.copy_tensors_to_host_async()
        # Out-of-order resolution (the separation-hint file order).
        b = snap.resolve(1)
        a = snap.resolve(0)
        np.testing.assert_array_equal(a, np.arange(4, dtype=np.float32))
        np.testing.assert_array_equal(b, np.arange(3, dtype=np.float32))
        assert snap.nbytes == 28

    def test_staged_snapshot_views_alias_lease(self):
        pool = HostStagingPool()
        sd = PyTreeStateDict({"a": jnp.arange(16.0)})
        sd.pop_tensors()
        snap = sd.copy_tensors_to_host_async(pool=pool)
        arr = snap.resolve(0)
        view = snap.resolve_view(0)
        assert view.nbytes == arr.nbytes
        assert pool.stats()["in_use_bytes"] > 0
        snap.release()
        assert pool.stats()["in_use_bytes"] == 0
        snap.release()  # idempotent


class TestConflictBackoff:
    def test_conflicting_save_timeout_names_paths(self, tmp_path):
        # A sync_fn that never agrees: the first save can never finalize, so a
        # second save to the same path must give up with the paths in the error
        # instead of spinning forever (the old behavior).
        ckpt = AsyncCheckpointer(sync_fn=lambda done: False, conflict_timeout=0.4)
        path = str(tmp_path / "c.ckpt")
        ckpt.async_save({"x": jnp.ones(4)}, path)
        t0 = time.monotonic()
        with pytest.raises(CheckpointError, match="c.ckpt"):
            ckpt.async_save({"x": jnp.zeros(4)}, path)
        elapsed = time.monotonic() - t0
        assert 0.3 <= elapsed < 5.0
        # Cleanup: let the queue drop the stuck save without the veto.
        ckpt.queue._sync_fn = None
        ckpt.finalize_all()
        ckpt.close()

    def test_backoff_grows_and_caps(self, tmp_path, monkeypatch):
        sleeps = []
        real_sleep = time.sleep
        monkeypatch.setattr(time, "sleep", lambda s: (sleeps.append(s), real_sleep(0))[1])
        # The sync_fn vetoes finalization for 9 agreement rounds, then agrees:
        # the conflict loop backs off through its full schedule with no
        # deadline truncation, then the save clears and scheduling proceeds.
        votes = []

        def sync_fn(done):
            votes.append(done)
            return len(votes) > 9

        ckpt = AsyncCheckpointer(sync_fn=sync_fn, conflict_timeout=30.0)
        ckpt.CONFLICT_BACKOFF_MAX = 0.016
        path = str(tmp_path / "b.ckpt")
        ckpt.async_save({"x": jnp.ones(4)}, path)
        ckpt.async_save({"x": jnp.zeros(4)}, path)  # waits via backoff, succeeds
        waits = [s for s in sleeps if s > 0]
        assert waits, "no backoff sleeps recorded"
        assert waits[0] == pytest.approx(ckpt.CONFLICT_BACKOFF_INITIAL)
        assert max(waits) <= 0.016 + 1e-9
        # Non-decreasing: exponential growth to the cap, not a hot fixed spin.
        assert waits == sorted(waits)
        ckpt.finalize_all()
        ckpt.close()

    def test_non_conflicting_paths_overlap_freely(self, tmp_path):
        ckpt = AsyncCheckpointer()
        for i in range(3):
            ckpt.async_save({"x": jnp.full(4, float(i))}, str(tmp_path / f"{i}.ckpt"))
        ckpt.finalize_all()
        for i in range(3):
            tree, _ = AsyncCheckpointer.load(str(tmp_path / f"{i}.ckpt"))
            np.testing.assert_array_equal(
                np.asarray(tree["x"]), np.full(4, float(i), np.float32)
            )
        ckpt.close()


class TestAbandonMidWrite:
    def test_abandon_leaves_dirty_residue_and_no_finalize(self, tmp_path):
        """Restart path: abandon() while the ThreadAsyncCaller's save is
        mid-write. The interrupted write must leave only the .dirty temp file
        (never a committed container), finalize_fns must not run, and a
        subsequent save to the same path must succeed."""
        path = str(tmp_path / "shard.ckpt")
        q = AsyncCallsQueue(caller="thread")
        mid_write = threading.Event()
        release = threading.Event()
        finalized = []

        def chunks():
            yield b"PARTIAL!"
            mid_write.set()
            release.wait(10.0)
            raise RuntimeError("interrupted by restart")

        q.schedule_async_request(
            AsyncRequest(
                async_fn=lambda: ckpt_format.write_stream(path, chunks()),
                finalize_fns=(lambda: finalized.append(1),),
            )
        )
        assert mid_write.wait(10.0)
        assert os.path.exists(path + ckpt_format.DIRTY_SUFFIX)
        release.set()
        abandoned = q.abandon()  # logs the local failure, never finalizes
        assert abandoned == [0]
        assert finalized == []
        assert os.listdir(tmp_path) == ["shard.ckpt" + ckpt_format.DIRTY_SUFFIX]
        # A fresh save to the same path commits cleanly over the residue.
        ckpt = AsyncCheckpointer()
        ckpt.async_save({"x": jnp.ones(4)}, path)
        ckpt.finalize_all()
        tree, _ = AsyncCheckpointer.load(path)
        np.testing.assert_array_equal(np.asarray(tree["x"]), np.ones(4, np.float32))
        assert not os.path.exists(path + ckpt_format.DIRTY_SUFFIX)
        ckpt.close()
        q.close()

    def test_abandon_releases_staging_lease(self, tmp_path):
        """cleanup_fns run even on the abandon path — the pool must get its
        buffer back or every restart leaks a full-tree staging lease."""
        ckpt = AsyncCheckpointer()
        ckpt.async_save(make_tree(), str(tmp_path / "a.ckpt"))
        ckpt.queue.abandon()
        deadline = time.monotonic() + 5.0
        while ckpt.staging.stats()["in_use_bytes"] and time.monotonic() < deadline:
            time.sleep(0.01)
        assert ckpt.staging.stats()["in_use_bytes"] == 0
        ckpt.close()


def _loopback_world(kv_server, world, body, timeout=60.0):
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
        stores.append(s)
        return s

    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            futs = [pool.submit(body, r, mk) for r in range(world)]
            return [f.result(timeout=timeout) for f in futs]
    finally:
        for s in stores:
            s.close()


class TestPipelinedManagerClique:
    def test_leaf_streaming_replication_round_trips(self, kv_server, tmp_path):
        world = 3

        def body(rank, mk):
            comm = StoreComm(mk(), rank, list(range(world)), timeout=30.0)
            ex = PeerExchange(mk(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=world
                )
                mgr = LocalCheckpointManager(
                    str(tmp_path), rank=rank, comm=comm, replication=strat
                )
                assert mgr.pipelined
                for it in (1, 2):
                    sd = PyTreeStateDict(
                        {"w": jnp.full((1 << 16,), float(rank * 10 + it)),
                         "step": it}
                    )
                    mgr.save(it, sd)
                    mgr.maybe_finalize(blocking=True)
                held = sorted((i.iteration, i.owner) for i in mgr.local_ids())
                assert held == [(2, o) for o in range(world)], held
                # Mirror payload integrity: another rank's shard byte-for-byte.
                peer = (rank + 1) % world
                _, tensors, meta = mgr.load_shard(peer)
                assert meta["iteration"] == 2
                np.testing.assert_array_equal(
                    tensors[0],
                    np.full((1 << 16,), float(peer * 10 + 2), np.float32),
                )
                # Steady state: second save reused the first save's buffers.
                assert mgr.staging.hits >= 1
                return mgr.staging.misses
            finally:
                ex.close()

        misses = _loopback_world(kv_server, world, body, timeout=90.0)
        assert all(m == 1 for m in misses), misses

    def test_mixed_version_peer_gets_streamed_payload(self, kv_server, tmp_path):
        """A v1 peer must still receive byte-identical shards from a streaming
        sender (chunks buffered into one legacy frame at close)."""
        world = 2

        def body(rank, mk):
            comm = StoreComm(mk(), rank, list(range(world)), timeout=30.0)
            # Rank 1 pins the legacy protocol: the streamed send must fall back.
            ex = PeerExchange(mk(), rank, timeout=30.0,
                              protocol=1 if rank == 1 else None)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=world
                )
                mgr = LocalCheckpointManager(
                    str(tmp_path), rank=rank, comm=comm, replication=strat
                )
                sd = PyTreeStateDict({"w": jnp.full((4096,), float(rank))})
                mgr.save(5, sd)
                mgr.maybe_finalize(blocking=True)
                peer = 1 - rank
                _, tensors, _ = mgr.load_shard(peer)
                np.testing.assert_array_equal(
                    tensors[0], np.full((4096,), float(peer), np.float32)
                )
            finally:
                ex.close()

        _loopback_world(kv_server, world, body, timeout=60.0)


class TestReplicationStreamUnit:
    def test_disabled_strategy_yields_inert_stream(self, kv_server):
        store = CoordStore("127.0.0.1", kv_server.port, timeout=10.0)
        try:
            comm = StoreComm(store, 0, [0], timeout=10.0)
            ex = PeerExchange(store, 0, timeout=10.0)
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=1
            )
            rs = strat.start_stream(128)
            assert not rs.active
            rs.open()
            rs.send_chunk(memoryview(b"x" * 128))
            assert rs.finish() == {}
        finally:
            store.close()
