"""Perf acceptance for the streaming replication data plane (slow; tier-1
deselects ``-m slow``). Runs ``scripts/bench_replication.py`` end to end at a
CI-sized payload and asserts the zero-copy claim: peak extra allocation of a
transfer on the v2 path is at most 1.25× the payload (the single receive
buffer plus protocol overhead), and the streaming path beats the pickled-blob
path. The committed 256 MB results live in ``BENCH_replication.json``."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
def test_streaming_path_is_zero_copy_and_faster(tmp_path):
    out = tmp_path / "bench.json"
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "bench_replication.py"),
            "--mb", "32", "--rounds", "2", "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr
    results = json.loads(out.read_text())
    # The zero-copy assertion: one receive buffer (1.0×) + bounded overhead.
    assert results["alloc_ratio_new"] <= 1.25, results
    # The old path materializes the shard repeatedly; the gap must be real even
    # at CI payload sizes (the committed 256 MB run shows the full margin).
    assert results["speedup"] >= 1.5, results
    assert results["new_mbps"] > results["old_mbps"], results
