"""Streaming replication data plane: version negotiation, zero-copy receive,
inbox hygiene, degraded-sender routing, and batched store collectives.

Same simulated multi-rank pattern as ``test_local.py`` (N "ranks" as threads
against one KVServer), focused on the v2 wire protocol and its compatibility
story: a v2 sender falls back to pickled-blob frames for a v1 receiver, a v2
receiver accepts v1 frames, and either pairing round-trips a shard
byte-identically.
"""

import concurrent.futures as cf

import numpy as np
import pytest

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.replication import (
    CliqueReplicationStrategy,
    ExchangePlan,
)
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform.store import CoordStore


def run_ranks(world, fn, timeout=60.0):
    with cf.ThreadPoolExecutor(max_workers=world) as pool:
        futures = [pool.submit(fn, r) for r in range(world)]
        return [f.result(timeout=timeout) for f in futures]


@pytest.fixture
def make_store(kv_server):
    stores = []

    def factory():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
        stores.append(s)
        return s

    yield factory
    for s in stores:
        s.close()


def _shard_parts():
    """A small but real container: header prefix + two leaf views."""
    tensors = [np.arange(256, dtype=np.float32), np.ones((3, 5), dtype=np.int32)]
    prefix, views = ckpt_format.serialize_parts(b"hollow", tensors, meta={"it": 7})
    return prefix, views, b"".join([prefix, *[bytes(v) for v in views]])


class TestSerializeParts:
    def test_parts_concatenate_to_blob_form(self):
        prefix, views, joined = _shard_parts()
        tensors = [np.arange(256, dtype=np.float32), np.ones((3, 5), dtype=np.int32)]
        assert joined == ckpt_format.serialize_to_bytes(b"hollow", tensors, meta={"it": 7})
        assert ckpt_format.parts_nbytes(prefix, views) == len(joined)

    def test_deserialize_from_buffer_is_zero_copy(self):
        _, _, joined = _shard_parts()
        buf = bytearray(joined)  # writable source so aliasing is observable
        hollow, tensors, meta = ckpt_format.deserialize_from_buffer(buf)
        assert hollow == b"hollow" and meta == {"it": 7}
        assert not tensors[0].flags["OWNDATA"]  # views over buf, not copies
        # Mutating the buffer mutates the view — proof there was no copy.
        t0_first_off = joined.index(np.float32(1.0).tobytes())
        buf[t0_first_off : t0_first_off + 4] = np.float32(99.0).tobytes()
        assert float(tensors[0][1]) == 99.0

    def test_write_parts_matches_write_blob(self, tmp_path):
        prefix, views, joined = _shard_parts()
        a, b = str(tmp_path / "a.ckpt"), str(tmp_path / "b.ckpt")
        ckpt_format.write_parts(a, [prefix, *views])
        ckpt_format.write_blob(b, joined)
        with open(a, "rb") as fa, open(b, "rb") as fb:
            assert fa.read() == fb.read()


class TestMixedVersionPeers:
    """A new-protocol sender talking to an old-frame receiver (and vice versa)
    must round-trip a shard byte-identically — the rolling-upgrade contract."""

    @pytest.mark.parametrize(
        "sender_proto, receiver_proto", [(2, 1), (1, 2), (2, 2), (1, 1)]
    )
    def test_roundtrip_byte_identical(self, make_store, sender_proto, receiver_proto):
        prefix, views, joined = _shard_parts()
        protos = {0: sender_proto, 1: receiver_proto}

        def body(rank):
            ex = PeerExchange(make_store(), rank, timeout=30.0, protocol=protos[rank])
            ex.start()
            try:
                if rank == 0:
                    ex.send_parts(1, "shard", [prefix, *views])
                    return None
                got = ex.recv(0, "shard", timeout=30.0)
                hollow, tensors, meta = ckpt_format.deserialize_from_buffer(got)
                assert meta == {"it": 7}
                np.testing.assert_array_equal(
                    np.asarray(tensors[0]), np.arange(256, dtype=np.float32)
                )
                return bytes(got)
            finally:
                ex.close()

        results = run_ranks(2, body)
        assert results[1] == joined

    def test_send_file_to_old_peer(self, make_store, tmp_path):
        _, _, joined = _shard_parts()
        path = tmp_path / "shard.ckpt"
        path.write_bytes(joined)

        def body(rank):
            proto = 1 if rank == 1 else None
            ex = PeerExchange(make_store(), rank, timeout=30.0, protocol=proto)
            ex.start()
            try:
                if rank == 0:
                    ex.send_file(1, "f", str(path))
                    return None
                return bytes(ex.recv(0, "f", timeout=30.0))
            finally:
                ex.close()

        assert run_ranks(2, body)[1] == joined

    def test_clique_with_one_v1_member(self, make_store):
        """A whole replicate round still converges when one member speaks v1."""
        world = 2

        def body(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            ex = PeerExchange(
                make_store(), rank, timeout=30.0, protocol=1 if rank == 1 else None
            )
            ex.start()
            try:
                strat = CliqueReplicationStrategy(comm, ex, 1, 2)
                held = strat.replicate(f"shard-{rank}".encode())
                return {o: bytes(b).decode() for o, b in held.items()}
            finally:
                ex.close()

        results = run_ranks(world, body)
        assert results[0] == {0: "shard-0", 1: "shard-1"}
        assert results[1] == {0: "shard-0", 1: "shard-1"}


class TestRecvInto:
    def test_preregistered_buffer_receives_in_place(self, make_store):
        payload = np.arange(4096, dtype=np.float32)

        def body(rank):
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                if rank == 0:
                    # Let the receiver register first so the fast path is hit.
                    import time

                    time.sleep(0.2)
                    ex.send_parts(1, "t", [payload])
                    return None
                dest = bytearray(payload.nbytes)
                n = ex.recv_into(0, "t", dest, timeout=30.0)
                assert n == payload.nbytes
                got = np.frombuffer(dest, dtype=np.float32)
                np.testing.assert_array_equal(got, payload)
                return True
            finally:
                ex.close()

        assert run_ranks(2, body)[1] is True

    def test_copies_when_frame_raced_ahead(self, make_store):
        def body(rank):
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                if rank == 0:
                    ex.send(1, "t", b"payload!")
                    return None
                # Wait for the frame to be fully inboxed, THEN register.
                got = ex.recv(0, "t", timeout=30.0)
                with ex._cond:
                    ex._inbox[(0, "t")] = [got]
                dest = bytearray(32)
                n = ex.recv_into(0, "t", dest, timeout=5.0)
                assert bytes(dest[:n]) == b"payload!"
                return True
            finally:
                ex.close()

        assert run_ranks(2, body)[1] is True


class TestInboxPurge:
    def test_purge_drops_matching_tags_only(self, make_store):
        def body(rank):
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                if rank == 0:
                    ex.send(1, "repl/7", b"stale")
                    ex.send(1, "keep/1", b"live")
                    return None
                # Both frames delivered before purging (recv blocks until then).
                live = bytes(ex.recv(0, "keep/1", timeout=30.0))
                with ex._cond:
                    ex._inbox[(0, "keep/1")] = [live]
                deadline_probe = ex.recv(0, "repl/7", timeout=30.0)
                with ex._cond:
                    ex._inbox[(0, "repl/7")] = [deadline_probe]
                assert ex.purge("repl/") == 1
                with pytest.raises(CheckpointError):
                    ex.recv(0, "repl/7", timeout=0.2)
                return bytes(ex.recv(0, "keep/1", timeout=5.0))
            finally:
                ex.close()

        assert run_ranks(2, body)[1] == b"live"

    def test_rebuild_purges_abandoned_round_frames(self, make_store):
        """Frames from a pre-rebuild round must not be mis-delivered to the new
        world's round 0 under the reused tag (the inbox-leak satellite)."""
        world = 2

        def body(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(comm, ex, 1, 2)
                if rank == 0:
                    # A peer's send from an abandoned round lands in rank 1's
                    # inbox under repl/0 — the tag the post-rebuild round reuses.
                    ex.send(1, "repl/0", b"stale-round")
                comm.barrier("staged")
                if rank == 1:
                    # Frame is in flight or delivered; wait for it.
                    probe = ex.recv(0, "repl/0", timeout=30.0)
                    with ex._cond:
                        ex._inbox[(0, "repl/0")] = [probe]
                comm.barrier("delivered")
                new_comm = StoreComm(
                    make_store(), rank, list(range(world)), timeout=30.0, generation=1
                )
                strat.rebuild(new_comm)
                if rank == 1:
                    assert not ex._inbox, ex._inbox
                new_comm.barrier("purged")
                held = strat.replicate(f"fresh-{rank}".encode())
                return {o: bytes(b).decode() for o, b in held.items()}
            finally:
                ex.close()

        results = run_ranks(world, body)
        assert results[1] == {0: "fresh-0", 1: "fresh-1"}


class TestExchangePlanDegradedRouting:
    def test_avoided_rank_skipped_when_healthy_holder_exists(self):
        plan = ExchangePlan.build(
            wanted={0: 0}, holders={1: {0}, 2: {0}}, avoid={1}
        )
        assert list(plan.sends) == [2]

    def test_avoided_rank_chosen_only_as_sole_holder(self):
        plan = ExchangePlan.build(wanted={0: 0}, holders={1: {0}}, avoid={1})
        assert list(plan.sends) == [1]
        assert plan.recvs == {0: [(1, 0)]}

    def test_load_balance_ties_break_by_rank_order(self):
        # Two transfers, two equally-loaded healthy holders: each sends one,
        # and the first (lowest dst) picks the lowest-ranked holder.
        plan = ExchangePlan.build(
            wanted={0: 0, 1: 1}, holders={2: {0, 1}, 3: {0, 1}}
        )
        assert plan.sends == {2: [(0, 0)], 3: [(1, 1)]}

    def test_avoid_does_not_unbalance_healthy_senders(self):
        # Degraded rank 4 holds everything; healthy 2 and 3 split the load.
        plan = ExchangePlan.build(
            wanted={0: 0, 1: 1},
            holders={2: {0, 1}, 3: {0, 1}, 4: {0, 1}},
            avoid={4},
        )
        assert sorted(plan.sends) == [2, 3]

    def test_no_live_holder_raises(self):
        with pytest.raises(CheckpointError, match="no live holder"):
            ExchangePlan.build(wanted={0: 5}, holders={1: {2}}, avoid={1})


class TestAllGatherBatching:
    def test_one_value_fetch_round_trip_per_collective(self, make_store):
        """The acceptance assertion: all_gather issues exactly one ``prefix_get``
        and zero polled ``get``\\ s per collective, per rank."""
        world = 3
        counts = [{"get": 0, "prefix_get": 0} for _ in range(world)]

        def body(rank):
            store = make_store()
            real_get, real_prefix_get = store.client.get, store.client.prefix_get

            def counting_get(key, timeout=None):
                counts[rank]["get"] += 1
                return real_get(key, timeout)

            def counting_prefix_get(prefix):
                counts[rank]["prefix_get"] += 1
                return real_prefix_get(prefix)

            store.client.get = counting_get
            store.client.prefix_get = counting_prefix_get
            comm = StoreComm(store, rank, list(range(world)), timeout=30.0)
            out = [comm.all_gather(rank * 10 + i) for i in range(2)]
            return out

        results = run_ranks(world, body)
        for rank in range(world):
            assert results[rank] == [[0, 10, 20], [1, 11, 21]]
            assert counts[rank]["prefix_get"] == 2  # one per collective
            assert counts[rank]["get"] == 0  # no per-peer polling

    def test_leader_cleans_round_namespace(self, make_store):
        world = 2
        stores = {}

        def body(rank):
            stores[rank] = make_store()
            comm = StoreComm(stores[rank], rank, list(range(world)), timeout=30.0)
            out = comm.all_gather(f"v{rank}")
            comm.barrier("post")  # ensure leader's clear has run everywhere
            return out

        results = run_ranks(world, body)
        assert results == [["v0", "v1"]] * world
        leftover = [
            k for k in stores[0].client.keys("") if "/ag/" in k and "/b" not in k
        ]
        assert leftover == [], leftover
