"""Multi-rank local checkpointing: comm, replication cliques, manager coverage.

Simulated multi-rank pattern per SURVEY §4: N "ranks" as threads, each with its own
store client + peer exchange against one KVServer — the JAX-host analogue of the
reference's Gloo-on-CPU multi-process fixtures.
"""

import concurrent.futures as cf
import pickle

import numpy as np
import pytest
from hypothesis import given as hyp_given, settings as hyp_settings, strategies as hyp_st

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.local_manager import CkptID, LocalCheckpointManager
from tpu_resiliency.checkpoint.replication import (
    CliqueReplicationStrategy,
    ExchangePlan,
    parse_group_sequence,
)
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform.store import CoordStore


def run_ranks(world, fn, timeout=60.0):
    """Run fn(rank) on `world` threads; raise the first failure."""
    with cf.ThreadPoolExecutor(max_workers=world) as pool:
        futures = [pool.submit(fn, r) for r in range(world)]
        return [f.result(timeout=timeout) for f in futures]


@pytest.fixture
def make_store(kv_server):
    stores = []

    def factory():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
        stores.append(s)
        return s

    yield factory
    for s in stores:
        s.close()


class TestParseGroupSequence:
    def test_adjacent(self):
        assert parse_group_sequence(1, 2, 4) == [[0, 1], [2, 3]]

    def test_jump_spans_hosts(self):
        # jump=2 (ranks per host), factor=2, world=8: mirrors on different hosts.
        assert parse_group_sequence(2, 2, 8) == [[0, 2], [1, 3], [4, 6], [5, 7]]

    def test_factor_one_identity(self):
        assert parse_group_sequence(1, 1, 3) == [[0], [1], [2]]

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            parse_group_sequence(2, 2, 6)


class TestExchangePlan:
    def test_balanced_holder_choice(self):
        # Ranks 0,1 lost their shards; both 2 and 3 hold both shards.
        plan = ExchangePlan.build(
            wanted={0: 0, 1: 1}, holders={2: {0, 1}, 3: {0, 1}}
        )
        senders = sorted(src for src in plan.sends)
        assert senders == [2, 3]  # load-balanced, not both from rank 2

    def test_no_holder_raises(self):
        with pytest.raises(CheckpointError):
            ExchangePlan.build(wanted={0: 0}, holders={1: {5}})


class TestStoreComm:
    def test_all_gather_ordered(self, make_store):
        world = 4

        def body(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            return comm.all_gather(rank * 10)

        for result in run_ranks(world, body):
            assert result == [0, 10, 20, 30]

    def test_broadcast(self, make_store):
        world = 3

        def body(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            return comm.broadcast({"cfg": 1} if rank == 1 else None, src=1)

        assert run_ranks(world, body) == [{"cfg": 1}] * world

    def test_all_reduce_and(self, make_store):
        world = 3

        def body(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            return comm.all_reduce_and(rank != 1)

        assert run_ranks(world, body) == [False] * world

    def test_rounds_do_not_collide(self, make_store):
        world = 2

        def body(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            out = [comm.all_gather(f"{rank}-{i}") for i in range(3)]
            return out

        for result in run_ranks(world, body):
            assert result == [["0-0", "1-0"], ["0-1", "1-1"], ["0-2", "1-2"]]


class TestPeerExchange:
    def test_send_recv(self, make_store):
        world = 2

        def body(rank):
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                ex.send(1 - rank, "t", f"hello-{rank}".encode())
                return bytes(ex.recv(1 - rank, "t")).decode()
            finally:
                ex.close()

        assert run_ranks(world, body) == ["hello-1", "hello-0"]

    def test_tag_isolation(self, make_store):
        world = 2

        def body(rank):
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                if rank == 0:
                    ex.send(1, "b", b"B")
                    ex.send(1, "a", b"A")
                    return None
                return (ex.recv(0, "a"), ex.recv(0, "b"))
            finally:
                ex.close()

        assert run_ranks(world, body)[1] == (b"A", b"B")

    def test_authenticated_exchange(self, make_store):
        """With an auth key, peers bind off-loopback and must pass the HMAC
        challenge; an unauthenticated client is rejected."""
        world = 2

        def body(rank):
            ex = PeerExchange(make_store(), rank, timeout=30.0, auth_key="s3cret")
            ex.start()
            try:
                ex.send(1 - rank, "t", f"auth-{rank}".encode())
                got = bytes(ex.recv(1 - rank, "t")).decode()
                if rank == 0:
                    # A keyless client cannot deliver to an authenticated peer.
                    bad = PeerExchange(make_store(), 7, timeout=5.0, auth_key=None)
                    try:
                        bad.send(1, "t", b"evil")
                        delivered = True
                    except Exception:
                        delivered = False
                    return (got, delivered)
                return got
            finally:
                ex.close()

        results = run_ranks(world, body)
        assert results[0] == ("auth-1", False)
        assert results[1] == "auth-0"

    def test_non_loopback_bind_requires_key(self, make_store):
        ex = PeerExchange(make_store(), 0, auth_key=None)
        with pytest.raises(ValueError):
            ex.start(host="0.0.0.0")


class TestCliqueReplication:
    def test_replicate_within_clique(self, make_store):
        world = 4

        def body(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                held = strat.replicate(f"shard-{rank}".encode())
                return {owner: bytes(blob).decode() for owner, blob in held.items()}
            finally:
                ex.close()

        results = run_ranks(world, body)
        assert results[0] == {0: "shard-0", 1: "shard-1"}
        assert results[3] == {2: "shard-2", 3: "shard-3"}


def _tree(rank):
    return {"w": np.full((4,), float(rank), dtype=np.float32), "step": rank}


class TestLocalCheckpointManager:
    def test_single_rank_roundtrip(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0)
        sd = PyTreeStateDict(_tree(0))
        mgr.save(10, sd, is_async=True)
        mgr.maybe_finalize(blocking=True)
        assert mgr.find_latest() == 10
        hollow, tensors, meta = mgr.load(10)
        assert meta["iteration"] == 10
        restored = PyTreeStateDict.__new__(PyTreeStateDict)
        restored._tree, restored._hollow, restored._tensors = hollow, True, None
        restored._shardings = None
        restored.insert_tensors(tensors)
        np.testing.assert_array_equal(np.asarray(restored.tree["w"]), np.zeros(4))
        mgr.close()

    def test_prunes_old_iterations(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0)
        mgr.save(1, PyTreeStateDict(_tree(0)), is_async=False)
        mgr.save(2, PyTreeStateDict(_tree(0)), is_async=False)
        assert {i.iteration for i in mgr.local_ids()} == {2}
        mgr.close()

    def test_dirty_files_cleaned_on_init(self, tmp_path):
        mgr = LocalCheckpointManager(str(tmp_path), rank=0)
        mgr.save(1, PyTreeStateDict(_tree(0)), is_async=False)
        dirty = mgr._path(CkptID(9, 0)) + ckpt_format.DIRTY_SUFFIX
        with open(dirty, "wb") as f:
            f.write(b"junk")
        mgr.close()
        mgr2 = LocalCheckpointManager(str(tmp_path), rank=0)
        import os

        assert not os.path.exists(dirty)
        assert mgr2.find_latest() == 1
        mgr2.close()

    def test_distributed_save_load_with_replication(self, tmp_path, make_store):
        world = 4

        def body(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    str(tmp_path), rank=rank, comm=comm, replication=strat
                )
                mgr.save(5, PyTreeStateDict(_tree(rank)), is_async=True)
                mgr.maybe_finalize(blocking=True)
                latest = mgr.find_latest()
                hollow, tensors, meta = mgr.load(latest)
                mgr.close()
                return latest, float(tensors[0][0])
            finally:
                ex.close()

        results = run_ranks(world, body, timeout=120.0)
        assert all(latest == 5 for latest, _ in results)
        assert [v for _, v in results] == [0.0, 1.0, 2.0, 3.0]

    def test_lost_rank_recovers_from_mirror(self, tmp_path, make_store):
        """Rank 1's storage is wiped after save; load must route from its clique peer."""
        world = 2

        def save_phase(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    str(tmp_path), rank=rank, comm=comm, replication=strat
                )
                mgr.save(3, PyTreeStateDict(_tree(rank)), is_async=False)
                mgr.close()
            finally:
                ex.close()

        run_ranks(world, save_phase)

        # Simulate rank 1 landing on a fresh host: wipe its directory.
        import shutil, os

        shutil.rmtree(os.path.join(str(tmp_path), "s0", "r1"))

        def load_phase(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    str(tmp_path), rank=rank, comm=comm, replication=strat
                )
                latest = mgr.find_latest()
                hollow, tensors, meta = mgr.load(latest)
                mgr.close()
                return latest, float(tensors[0][0])
            finally:
                ex.close()

        results = run_ranks(world, load_phase, timeout=120.0)
        assert results == [(3, 0.0), (3, 1.0)]


class TestForkCallerGuard:
    def test_refuses_fork_over_live_backend(self):
        """The suite's conftest initializes JAX, so a fork here duplicates runtime
        threads into the child — schedule must refuse (the documented hazard)."""
        import jax
        import pytest

        from tpu_resiliency.checkpoint.async_core import AsyncRequest, ForkAsyncCaller
        from tpu_resiliency.exceptions import CheckpointError

        jax.devices()  # ensure the backend client exists
        caller = ForkAsyncCaller()
        with pytest.raises(CheckpointError, match="initialized JAX backend"):
            caller.schedule(AsyncRequest(async_fn=lambda: None))

    def test_explicit_override_forks(self, tmp_path):
        import warnings

        import jax
        import pytest

        from tpu_resiliency.checkpoint.async_core import AsyncRequest, ForkAsyncCaller

        if jax.default_backend() != "cpu":
            pytest.skip("forking over a live accelerator client is the documented UB")

        marker = tmp_path / "wrote"
        caller = ForkAsyncCaller(unsafe_allow_fork_with_backend=True)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)  # multithreaded fork
            caller.schedule(AsyncRequest(async_fn=_touch_file, async_fn_args=(str(marker),)))
        assert caller.wait(timeout=30.0)
        caller.raise_if_failed()
        assert marker.exists()


def _touch_file(path):
    with open(path, "w") as f:
        f.write("ok")


class TestGroupSequenceFor:
    def test_divisible_matches_parse(self):
        from tpu_resiliency.checkpoint.replication import group_sequence_for

        assert group_sequence_for(range(8), 2, 2) == parse_group_sequence(2, 2, 8)

    def test_gapped_rank_ids_group_by_position(self):
        from tpu_resiliency.checkpoint.replication import group_sequence_for

        # Survivors [0,2,5,7] with jump 2: spacing follows placement ORDER.
        assert group_sequence_for([7, 0, 5, 2], 2, 2) == [[0, 5], [2, 7]]

    def test_remainder_merges_into_last_clique(self):
        from tpu_resiliency.checkpoint.replication import group_sequence_for

        assert group_sequence_for(range(3), 1, 2) == [[0, 1, 2]]
        assert group_sequence_for(range(5), 1, 2) == [[0, 1], [2, 3, 4]]

    def test_no_full_block_consecutive_cliques(self):
        from tpu_resiliency.checkpoint.replication import group_sequence_for

        # jump 4 x factor 2 needs 8 ranks; with 5 the spacing degrades rather
        # than leaving anyone unmirrored — a singleton tail folds into its
        # neighbor (a 1-clique would hold zero mirrors).
        assert group_sequence_for(range(5), 4, 2) == [[0, 1], [2, 3, 4]]

    def test_single_rank(self):
        from tpu_resiliency.checkpoint.replication import group_sequence_for

        assert group_sequence_for([3], 1, 2) == [[3]]


class TestRebuildAfterReassignment:
    def test_rebuild_remirrors_and_next_save_covers(self, tmp_path, make_store):
        """VERDICT r3 item 7: world 4 saves with cliques [0,1],[2,3]; rank 3 dies;
        survivors rebuild over [0,1,2], the orphaned rank-2 shard gets re-mirrored,
        a wiped rank still recovers, and the next save is coverage-complete."""
        world = 4

        def save_phase(rank):
            comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    str(tmp_path), rank=rank, comm=comm, replication=strat
                )
                mgr.save(2, PyTreeStateDict(_tree(rank)), is_async=False)
                mgr.close()
            finally:
                ex.close()

        run_ranks(world, save_phase, timeout=120.0)

        # Rank 3 is dead. Survivors' managers (still configured for the old
        # world) adopt the new group.
        survivors = [0, 1, 2]

        def rebuild_phase(rank):
            import os

            stale_comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    stale_comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    str(tmp_path), rank=rank, comm=stale_comm, replication=strat
                )
                assert strat.my_group in ([0, 1], [2, 3])
                new_comm = StoreComm(
                    make_store(), rank, survivors, timeout=30.0, generation=1
                )
                mgr.rebuild_group(new_comm)
                # Remainder merged: one clique of all three survivors.
                assert strat.my_group == [0, 1, 2]
                # Rank 2's shard (old mirror lived only on dead rank 3) is now
                # mirrored on every new clique peer (rank 2 additionally still
                # holds the dead rank's stale mirror — harmless, pruned at the
                # next save's retention pass).
                held = {i.owner for i in mgr.local_ids() if i.iteration == 2}
                assert held >= {0, 1, 2}, held
                # The DEAD rank's shard (sole copy was rank 2's mirror) was
                # re-spread: every survivor can now serve the reshard path.
                assert 3 in held, held
                hollow3, t3, _ = mgr.load_shard(3, 2)
                assert float(t3[0][0]) == 3.0
                new_comm.barrier("post-remirror")
                if rank == 2:  # rank 2 lands on fresh storage
                    for name in os.listdir(mgr._dir):
                        os.unlink(os.path.join(mgr._dir, name))
                new_comm.barrier("post-wipe")
                latest = mgr.find_latest()
                assert latest == 2, latest
                hollow, tensors, meta = mgr.load(latest)
                val = float(tensors[0][0])
                # The next save must be coverage-complete over the NEW group
                # (finalize raises otherwise).
                mgr.save(5, PyTreeStateDict(_tree(rank + 10)), is_async=False)
                latest2 = mgr.find_latest()
                mgr.close()
                return val, latest2
            finally:
                ex.close()

        results = run_ranks(3, lambda r: rebuild_phase(survivors[r]), timeout=120.0)
        assert [v for v, _ in results] == [0.0, 1.0, 2.0]
        assert all(l == 5 for _, l in results)


class TestLazyCliqueReplication:
    def test_groups_bind_at_first_use(self, make_store):
        from tpu_resiliency.checkpoint.replication import LazyCliqueReplicationStrategy

        world = 2

        def body(rank):
            # The comm is only KNOWABLE after "rank assignment settles": the
            # factory defers its construction to first replicate().
            ex = PeerExchange(make_store(), rank, timeout=30.0)
            ex.start()
            try:
                strat = LazyCliqueReplicationStrategy(
                    lambda: StoreComm(make_store(), rank, [0, 1], timeout=30.0),
                    ex,
                    replication_jump=1,
                    replication_factor=2,
                )
                assert strat.comm is None and strat.groups is None
                held = strat.replicate(f"blob-{rank}".encode())
                assert strat.my_group == [0, 1]
                return {o: bytes(b).decode() for o, b in held.items()}
            finally:
                ex.close()

        results = run_ranks(world, body, timeout=60.0)
        assert results[0] == {0: "blob-0", 1: "blob-1"}
        assert results[1] == {0: "blob-0", 1: "blob-1"}


class TestGroupSequenceProperties:
    """Hypothesis invariants for the remainder-folding clique math — the logic a
    reassignment bug would corrupt silently."""

    @hyp_settings(max_examples=200, deadline=None)
    @hyp_given(
        ranks=hyp_st.sets(hyp_st.integers(0, 500), min_size=1, max_size=64),
        jump=hyp_st.integers(1, 8),
        factor=hyp_st.integers(1, 8),
    )
    def test_partition_and_no_singletons(self, ranks, jump, factor):
        from tpu_resiliency.checkpoint.replication import group_sequence_for

        groups = group_sequence_for(ranks, jump, factor)
        flat = [r for g in groups for r in g]
        # Exact partition: every active rank in exactly one clique.
        assert sorted(flat) == sorted(ranks)
        assert len(flat) == len(set(flat))
        # No unmirrored rank unless replication is off or world is 1.
        if factor >= 2 and len(ranks) >= 2:
            assert all(len(g) >= 2 for g in groups), groups
        # Full-spacing blocks never exceed jump*factor; folded tails are
        # bounded by one extra block's worth of members.
        assert all(len(g) <= jump * factor + factor for g in groups)
