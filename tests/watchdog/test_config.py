import argparse
import signal

import pytest

from tpu_resiliency.watchdog import FaultToleranceConfig


def test_defaults_match_reference_envelope():
    cfg = FaultToleranceConfig()
    assert cfg.initial_rank_heartbeat_timeout == 3600.0
    assert cfg.rank_heartbeat_timeout == 2700.0
    assert cfg.workload_check_interval == 5.0
    assert cfg.safety_factor == 5.0
    assert cfg.rank_termination_signal == int(signal.SIGKILL)


def test_yaml_nested_section(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text(
        """
trainer:
  exp:
    fault_tolerance:
      rank_heartbeat_timeout: 120
      safety_factor: 3.0
      rank_termination_signal: SIGTERM
"""
    )
    cfg = FaultToleranceConfig.from_yaml_file(str(p))
    assert cfg.rank_heartbeat_timeout == 120
    assert cfg.safety_factor == 3.0
    assert cfg.rank_termination_signal == int(signal.SIGTERM)


def test_yaml_missing_section(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("foo: {bar: 1}\n")
    with pytest.raises(ValueError):
        FaultToleranceConfig.from_yaml_file(str(p))


def test_unknown_key_rejected():
    with pytest.raises(ValueError):
        FaultToleranceConfig.from_dict({"not_a_knob": 1})


def test_cli_overrides():
    args = argparse.Namespace(
        ft_param_rank_heartbeat_timeout="90",
        ft_param_safety_factor="2.5",
        ft_param_enable_health_checks="true",
        other_arg=7,
    )
    cfg = FaultToleranceConfig.from_args(args)
    assert cfg.rank_heartbeat_timeout == 90
    assert cfg.safety_factor == 2.5
    assert cfg.enable_health_checks is True


def test_cli_unknown_param():
    args = argparse.Namespace(ft_param_bogus="1")
    with pytest.raises(ValueError):
        FaultToleranceConfig.from_args(args)


def test_roundtrip_yaml(tmp_path):
    cfg = FaultToleranceConfig(rank_heartbeat_timeout=42.0)
    p = tmp_path / "out.yaml"
    cfg.to_yaml_file(str(p))
    cfg2 = FaultToleranceConfig.from_yaml_file(str(p))
    assert cfg2.rank_heartbeat_timeout == 42.0
