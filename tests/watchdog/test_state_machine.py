import logging

import pytest

from tpu_resiliency.exceptions import InternalError
from tpu_resiliency.watchdog import LOG_MARKER, RestarterState, RestarterStateMachine


def test_happy_path_transitions(caplog):
    sm = RestarterStateMachine("InJob")
    with caplog.at_level(logging.INFO, logger="tpu_resiliency"):
        sm.initialize()
        sm.handling_start("reason='hb timeout'")
        sm.handling_processing()
        sm.handling_completed()
        sm.handling_start()  # another fault round
        sm.handling_processing()
        sm.handling_completed()
        sm.finalized()
    lines = [r.message for r in caplog.records if LOG_MARKER in r.message]
    assert len(lines) == 8
    # the machine-parseable contract used by layered restart
    assert lines[0] == f"{LOG_MARKER} name=[InJob] state=initialize"
    assert lines[1].startswith(f"{LOG_MARKER} name=[InJob] state=handling_start reason=")


def test_illegal_transition_strict():
    sm = RestarterStateMachine("InJob", strict=True)
    with pytest.raises(InternalError):
        sm.handling_processing()  # from UNINITIALIZED


def test_illegal_transition_lenient(caplog):
    sm = RestarterStateMachine("InJob", strict=False)
    with caplog.at_level(logging.WARNING, logger="tpu_resiliency"):
        sm.handling_processing()
    assert sm.state is RestarterState.HANDLING_PROCESSING


def test_health_checks(tmp_path):
    from tpu_resiliency.watchdog import CallbackHealthCheck, SysfsCounterCheck

    ok = CallbackHealthCheck(lambda: True, "ok")
    bad = CallbackHealthCheck(lambda: 1 / 0, "raises")
    assert ok() and not bad()

    counter = tmp_path / "dev0" / "link_downed"
    counter.parent.mkdir()
    counter.write_text("0")
    check = SysfsCounterCheck(str(tmp_path / "*" / "link_downed"))
    assert check()  # baseline
    assert check()  # unchanged
    counter.write_text("1")
    assert not check()  # counter increased
    assert not check()  # sticky
    check.reset()
    assert check()
