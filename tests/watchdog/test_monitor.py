"""Live monitor-server/client integration: real forked monitor process, real UDS."""

import multiprocessing as mp
import os
import signal
import sys
import time

import pytest

from tpu_resiliency.exceptions import FaultToleranceError
from tpu_resiliency.watchdog import (
    FaultToleranceConfig,
    HeartbeatTimeouts,
    RankInfo,
    RankMonitorClient,
    RankMonitorServer,
)


@pytest.fixture
def monitor(tmp_uds_path):
    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=None,
        rank_heartbeat_timeout=None,
        workload_check_interval=0.2,
    )
    proc = RankMonitorServer.run_in_subprocess(cfg, tmp_uds_path, start_method="spawn")
    yield tmp_uds_path, cfg
    proc.terminate()
    proc.join(5.0)


def _client(path, rank=0):
    c = RankMonitorClient()
    c.init_workload_monitoring(
        socket_path=path,
        rank_info=RankInfo(global_rank=rank, local_rank=rank, host="h", pid=os.getpid()),
    )
    return c


def test_init_and_heartbeats(monitor):
    path, _ = monitor
    c = _client(path)
    assert c.cfg.workload_check_interval == 0.2
    for _ in range(5):
        c.send_heartbeat()
        time.sleep(0.01)
    assert c.timeouts_calc.hb_count == 5
    c.shutdown_workload_monitoring()


def test_sections_roundtrip(monitor):
    path, _ = monitor
    c = _client(path)
    c.start_section("setup")
    c.end_section("setup")
    c.start_section("step")
    c.end_all_sections()
    with pytest.raises(FaultToleranceError):
        c.end_section("step")  # already closed by end_all
    c.shutdown_workload_monitoring()


def test_calculated_timeouts_update_server(monitor):
    path, _ = monitor
    c = _client(path)
    c.send_heartbeat()
    time.sleep(0.05)
    c.send_heartbeat()
    t = c.calculate_and_set_hb_timeouts()
    assert t.calculated and t.are_valid
    # state dict round trip
    state = c.state_dict()
    c2 = RankMonitorClient()
    c2.load_state_dict(state)
    assert c2._loaded_state["hb_timeouts"].calculated
    c.shutdown_workload_monitoring()


def _hang_victim(path, ready_q):
    """Child process: connects, heartbeats once with tight timeouts, then hangs."""
    from tpu_resiliency.watchdog import HeartbeatTimeouts, RankInfo, RankMonitorClient
    from tpu_resiliency.watchdog.data import UpdateTimeoutsMsg

    c = RankMonitorClient()
    c.init_workload_monitoring(
        socket_path=path,
        rank_info=RankInfo(global_rank=0, local_rank=0, host="h", pid=os.getpid()),
    )
    c._request(
        UpdateTimeoutsMsg(
            hb_timeouts=HeartbeatTimeouts(initial=0.5, subsequent=0.5, calculated=True)
        )
    )
    c.send_heartbeat()
    ready_q.put(os.getpid())
    time.sleep(60)  # simulated hang: no more heartbeats
    sys.exit(0)


def test_hang_detection_kills_rank(tmp_uds_path):
    """The reference heartbeat-path contract (SURVEY §3.2): monitor detects the missed
    heartbeat and terminates the rank with the configured signal."""
    cfg = FaultToleranceConfig(workload_check_interval=0.2, rank_termination_signal=signal.SIGTERM)
    mon = RankMonitorServer.run_in_subprocess(cfg, tmp_uds_path, start_method="spawn")
    ctx = mp.get_context("fork")
    ready_q = ctx.Queue()
    victim = ctx.Process(target=_hang_victim, args=(tmp_uds_path, ready_q))
    victim.start()
    ready_q.get(timeout=10.0)
    victim.join(15.0)
    assert not victim.is_alive(), "hung rank was not terminated by the monitor"
    assert victim.exitcode == -signal.SIGTERM
    mon.terminate()
    mon.join(5.0)


def test_section_timeout_detection(tmp_uds_path):
    """A section left open past its timeout triggers termination."""
    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=None,
        rank_heartbeat_timeout=None,
        rank_section_timeouts={"step": 0.4},
        workload_check_interval=0.1,
        rank_termination_signal=signal.SIGTERM,
    )
    mon = RankMonitorServer.run_in_subprocess(cfg, tmp_uds_path, start_method="spawn")

    def victim_main(path):
        c = RankMonitorClient()
        c.init_workload_monitoring(
            socket_path=path,
            rank_info=RankInfo(global_rank=0, local_rank=0, host="h", pid=os.getpid()),
        )
        c.start_section("step")
        time.sleep(60)

    ctx = mp.get_context("fork")
    victim = ctx.Process(target=victim_main, args=(tmp_uds_path,))
    victim.start()
    victim.join(15.0)
    assert not victim.is_alive()
    assert victim.exitcode == -signal.SIGTERM
    mon.terminate()
    mon.join(5.0)
