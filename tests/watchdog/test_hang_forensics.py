"""Hang-forensics plane units: location beacons, version-skew tolerance,
stack capture, the monitor's dump machinery, and the store barrier census."""

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from tpu_resiliency.utils import events, location, stackdump
from tpu_resiliency.utils.metrics import MetricsRegistry, observe_record
from tpu_resiliency.watchdog.config import FaultToleranceConfig
from tpu_resiliency.watchdog.data import (
    DumpStacksMsg,
    HeartbeatMsg,
    InitMsg,
    OkMsg,
    RankInfo,
    SectionAction,
    SectionMsg,
    StatusMsg,
)
from tpu_resiliency.watchdog.monitor_server import RankMonitorServer


@pytest.fixture
def sink_events():
    captured = []
    events.add_sink(captured.append)
    yield captured
    events.remove_sink(captured.append)


# -- location beacon ----------------------------------------------------------


def test_location_beacon_snapshot_and_describe():
    b = location.LocationBeacon()
    assert b.snapshot() == {"v": 1}
    b.note_step(7)
    b.enter_section("step")
    snap = b.snapshot()
    assert snap["step"] == 7 and snap["section"] == "step"
    assert snap["section_age_s"] >= 0 and "entered_at" in snap
    with b.barrier("rdzv/round-3"):
        snap = b.snapshot()
        assert snap["barrier"] == "rdzv/round-3"
        frag = location.describe(snap)
        assert "section=step" in frag and "barrier=rdzv/round-3" in frag
        assert "for " in frag
    assert "barrier" not in b.snapshot()
    # Nesting pops innermost-first; unknown names are no-ops.
    b.enter_section("inner")
    b.exit_section("nope")
    assert b.snapshot()["section"] == "inner"
    b.exit_section(None)
    assert "section" not in b.snapshot()
    # describe() tolerates garbage.
    assert location.describe(None) == ""
    assert location.describe({"v": 1}) == ""


def test_blocking_barrier_join_tags_the_beacon(kv_server, coord_store):
    done = threading.Event()

    def join():
        coord_store.barrier_join("census/b", rank=0, world_size=2, timeout=30.0)
        done.set()

    t = threading.Thread(target=join, daemon=True)
    t.start()
    deadline = time.time() + 5
    while "barrier" not in location.snapshot() and time.time() < deadline:
        time.sleep(0.01)
    assert location.snapshot().get("barrier") == "census/b"
    coord_store.barrier_join("census/b", rank=1, world_size=2, timeout=10.0)
    assert done.wait(10.0)
    t.join(5.0)
    assert "barrier" not in location.snapshot()


# -- monitor server: beacons + skew ------------------------------------------


def _server(**cfg_overrides):
    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=None, rank_heartbeat_timeout=None,
        **cfg_overrides,
    )
    srv = RankMonitorServer(cfg, socket_path="/tmp/unused-hang-forensics.sock")
    srv._dispatch(InitMsg(
        rank_info=RankInfo(global_rank=3, local_rank=0, host="h", pid=os.getpid()),
        capabilities={"dump_signal": False, "dump_poll": True},
    ))
    return srv


def test_heartbeat_and_section_carry_location():
    srv = _server()
    loc = {"v": 1, "section": "step", "section_age_s": 1.5, "step": 42}
    assert isinstance(srv._dispatch(HeartbeatMsg(rank=3, location=loc)), OkMsg)
    assert srv.session.location == loc
    loc2 = {"v": 1, "section": "checkpointing", "section_age_s": 0.1}
    srv._dispatch(SectionMsg(
        rank=3, action=SectionAction.OPEN, name="checkpointing", location=loc2,
    ))
    assert srv.session.location == loc2
    status = srv._dispatch(StatusMsg()).payload
    assert status["connected"] and status["rank"] == 3
    assert status["location"] == loc2
    assert status["location_age_s"] >= 0.1
    assert status["open_sections"].keys() == {"checkpointing"}


def test_version_skew_location_less_messages_tolerated():
    """A field-stripped (old-build) heartbeat/section must not poison the
    monitor: dispatch succeeds and the last good beacon is kept."""
    srv = _server()
    good = {"v": 1, "section": "step", "section_age_s": 0.5}
    srv._dispatch(HeartbeatMsg(rank=3, location=good))

    old_hb = HeartbeatMsg(rank=3)
    del old_hb.__dict__["location"]  # exactly what unpickling an old msg yields
    assert "location" not in old_hb.__dict__
    assert isinstance(srv._dispatch(old_hb), OkMsg)
    assert srv.session.location == good

    old_sec = SectionMsg(rank=3, action=SectionAction.OPEN, name="step")
    del old_sec.__dict__["location"]
    assert isinstance(srv._dispatch(old_sec), OkMsg)
    assert srv.session.location == good

    # The reverse skew: a NEW message with a malformed payload is no update.
    assert isinstance(
        srv._dispatch(HeartbeatMsg(rank=3, location="not-a-dict")), OkMsg
    )
    assert srv.session.location == good

    # Old-build InitMsg (no capabilities attr) re-inits cleanly too.
    old_init = InitMsg(
        rank_info=RankInfo(global_rank=3, local_rank=0, host="h", pid=os.getpid())
    )
    del old_init.__dict__["capabilities"]
    reply = srv._dispatch(old_init)
    assert reply.__class__.__name__ == "InitReplyMsg"
    assert srv.session.dump_signal_ok is False


def test_terminate_rank_folds_location_into_cause(sink_events):
    srv = _server(rank_termination_signal=signal.SIGTERM)
    victim = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"])
    try:
        srv._dispatch(InitMsg(
            rank_info=RankInfo(global_rank=3, local_rank=0, host="h", pid=victim.pid),
        ))
        srv._dispatch(HeartbeatMsg(rank=3, location={
            "v": 1, "section": "step", "barrier": "rdzv/round-3",
            "barrier_age_s": 600.0, "step": 12,
        }))
        srv._terminate_rank("heartbeat gap exceeded 45.0s", "hang", "heartbeat")
        hang = [e for e in sink_events if e.kind == "hang_detected"]
        assert len(hang) == 1
        p = hang[0].payload
        assert "last seen in" in p["reason"]
        assert "barrier=rdzv/round-3" in p["reason"]
        assert "section=step" in p["reason"]
        assert p["location"]["barrier"] == "rdzv/round-3"
        assert p["blocked_s"] >= 0
        assert victim.wait(timeout=10) == -signal.SIGTERM
    finally:
        if victim.poll() is None:
            victim.kill()
        victim.wait()


# -- stack capture ------------------------------------------------------------


def _stuck_in_native_wait(ev):
    ev.wait(30.0)  # lock wait: a GIL-releasing native park


def test_capture_stacks_sees_other_threads():
    ev = threading.Event()
    # Early-sorting name: the capture cap keeps the first MAX_THREADS by
    # (main-first, name) and a full test session leaks scores of pool
    # threads; a real worker never carries that many.
    t = threading.Thread(
        target=_stuck_in_native_wait, args=(ev,), name="00-parked"
    )
    t.start()
    try:
        threads = stackdump.capture_stacks()
        assert threads[0]["main"] is True  # main thread sorts first
        parked = [d for d in threads if d["name"] == "00-parked"]
        assert parked, [d["name"] for d in threads]
        assert any("_stuck_in_native_wait" in f for f in parked[0]["frames"])
    finally:
        ev.set()
        t.join(5.0)


def test_dump_stacks_records_event_and_counts(sink_events, tmp_path):
    from tpu_resiliency.utils import flight_recorder

    flight_recorder.install(str(tmp_path), install_handlers=False)
    try:
        stackdump.dump_stacks("hang: test", detail="rank 3")
        dumps = [e for e in sink_events if e.kind == "stack_dump"]
        assert len(dumps) == 1
        p = dumps[0].payload
        assert p["reason"] == "hang: test"
        assert p["thread_count"] == len(p["threads"]) >= 1
        assert any(
            "test_dump_stacks_records_event" in f
            for f in p["threads"][0]["frames"]
        )
        # The consolidated flight dump carries the capture (SIGKILL-proof:
        # the hot segment got it at record time already).
        dumped = flight_recorder.collect(str(tmp_path))
        assert any(
            r.get("kind") == "stack_dump"
            for recs in dumped.values() for r in recs
        )
        # Bridge: stack_dump -> tpu_stack_dumps_total{reason} (prefix only).
        reg = MetricsRegistry()
        observe_record(
            {"kind": "stack_dump", "reason": "hang: whatever detail"}, reg
        )
        assert reg.counter("tpu_stack_dumps_total", reason="hang").value == 1
    finally:
        flight_recorder.uninstall()


def test_hang_census_metrics_bridge():
    reg = MetricsRegistry()
    observe_record(
        {
            "kind": "hang_census",
            "suspects": [{"rank": 1, "score": 5.0, "reasons": ["missing"]}],
            "blocked": {"1": 12.5, "0": 0.2},
            "barrier_waiters": 3,
        },
        reg,
    )
    assert reg.counter("tpu_hang_suspects_total", rank="1").value == 1
    assert reg.gauge("tpu_rank_blocked_seconds", rank="1").value == 12.5
    assert reg.gauge("tpu_rank_blocked_seconds", rank="0").value == 0.2
    assert reg.gauge("tpu_barrier_waiters").value == 3


# -- dump request plumbing (real monitor subprocess) --------------------------


def test_dump_request_reaches_the_client(tmp_uds_path, sink_events):
    """Operator path end to end: a DumpStacksMsg at the monitor socket makes
    the connected client (this process) record a stack_dump event via its
    long-poll listener."""
    from tpu_resiliency.platform import ipc
    from tpu_resiliency.watchdog.monitor_client import RankMonitorClient

    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=None, rank_heartbeat_timeout=None,
        workload_check_interval=0.2,
    )
    mon = RankMonitorServer.run_in_subprocess(cfg, tmp_uds_path, start_method="spawn")
    client = RankMonitorClient()
    try:
        client.init_workload_monitoring(
            socket_path=tmp_uds_path,
            rank_info=RankInfo(global_rank=0, local_rank=0, host="h", pid=os.getpid()),
        )
        client.send_heartbeat()
        # Give the listener a beat to complete its generation sync.
        time.sleep(0.3)
        sock = ipc.connect(tmp_uds_path, timeout=5.0)
        try:
            ipc.write_object(sock, DumpStacksMsg(reason="operator-test"))
            reply = ipc.read_object(sock)
            assert isinstance(reply, OkMsg) and reply.payload["gen"] >= 1
        finally:
            sock.close()
        # Two deliveries race: the long-poll listener ("operator-test") and
        # the SIGUSR1 nudge ("signal:SIGUSR1") — the long-poll one must land.
        deadline = time.time() + 10
        while time.time() < deadline:
            if any(
                e.kind == "stack_dump" and e.payload.get("reason") == "operator-test"
                for e in sink_events
            ):
                break
            time.sleep(0.05)
        reasons = [
            e.payload.get("reason") for e in sink_events if e.kind == "stack_dump"
        ]
        assert "operator-test" in reasons, reasons
    finally:
        client.shutdown_workload_monitoring()
        mon.terminate()
        mon.join(5.0)


# -- barrier census (store) ---------------------------------------------------


def test_barrier_census_arrived_missing_and_release(kv_server, coord_store):
    client = coord_store.client
    # Nobody joined yet: census is empty.
    assert client.barrier_census() == {}
    coord_store.barrier_join("iter/0", rank=0, world_size=3, timeout=0.0, wait=False)
    time.sleep(0.05)
    coord_store.barrier_join("iter/0", rank=2, world_size=3, timeout=0.0, wait=False)
    census = client.barrier_census()
    assert set(census) == {"iter/0"}
    b = census["iter/0"]
    assert set(b["arrived"]) == {0, 2}
    assert b["missing"] == [1]
    assert b["absent"] == []
    assert b["world_size"] == 3
    # Rank 0 arrived first: its waiter age is the oldest.
    assert b["arrived"][0] >= b["arrived"][2] >= 0
    assert b["open_age_s"] >= b["arrived"][0]
    # Proxy-absent ranks are reported as absent, not missing.
    coord_store.complete_barrier_for("iter/0", rank=1, world_size=3)
    # Covering rank 1 releases the round; the census clears.
    assert client.barrier_census() == {}
    # StoreView scoping: names come back view-relative.
    coord_store.barrier_join("iter/1", rank=0, world_size=2, timeout=0.0, wait=False)
    scoped = coord_store.barrier_census()
    assert set(scoped) == {"iter/1"}
    assert scoped["iter/1"]["missing"] == [1]
    # Prefix filter on the raw client.
    assert client.barrier_census(prefix="nope/") == {}
