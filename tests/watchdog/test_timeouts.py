import pytest

from tpu_resiliency.exceptions import FaultToleranceError
from tpu_resiliency.watchdog import HeartbeatTimeouts, TimeoutsCalc


def test_hb_gap_tracking_injected_times():
    """Injected timestamps, no sleeping (reference test_timeouts_calc.py pattern)."""
    calc = TimeoutsCalc(safety_factor=5.0)
    calc.start_time = 100.0
    calc.update_on_heartbeat(103.0)  # initial gap 3
    calc.update_on_heartbeat(104.0)  # subsequent 1
    calc.update_on_heartbeat(106.5)  # subsequent 2.5
    t = calc.get_hb_timeouts()
    assert t.initial == pytest.approx(5.0 * 3.0)
    assert t.subsequent == pytest.approx(5.0 * 2.5)
    assert t.calculated


def test_initial_timeout_covers_subsequent_gap():
    calc = TimeoutsCalc(safety_factor=2.0)
    calc.start_time = 0.0
    calc.update_on_heartbeat(1.0)
    calc.update_on_heartbeat(11.0)  # subsequent gap 10 > initial gap 1
    t = calc.get_hb_timeouts()
    assert t.initial == pytest.approx(20.0)


def test_needs_two_heartbeats():
    calc = TimeoutsCalc()
    calc.start_time = 0.0
    calc.update_on_heartbeat(1.0)
    with pytest.raises(FaultToleranceError):
        calc.get_hb_timeouts()


def test_ema_merge_with_previous():
    calc = TimeoutsCalc(safety_factor=1.0)
    calc.start_time = 0.0
    calc.update_on_heartbeat(4.0)
    calc.update_on_heartbeat(6.0)
    prev = HeartbeatTimeouts(initial=8.0, subsequent=4.0, calculated=True)
    t = calc.get_hb_timeouts(previous=prev)
    assert t.initial == pytest.approx(0.5 * 4.0 + 0.5 * 8.0)
    assert t.subsequent == pytest.approx(0.5 * 2.0 + 0.5 * 4.0)


def test_sections():
    calc = TimeoutsCalc(safety_factor=2.0)
    calc.update_on_section_open("step", 10.0)
    calc.update_on_section_close("step", 11.5)
    calc.update_on_section_open("step", 20.0)  # out-of-section gap 8.5
    calc.update_on_section_close("step", 21.0)
    st = calc.get_section_timeouts()
    assert st.section["step"] == pytest.approx(2.0 * 1.5)
    assert st.out_of_section == pytest.approx(2.0 * 8.5)
    with pytest.raises(FaultToleranceError):
        calc.update_on_section_close("never-opened")


def test_store_synchronize_max(kv_server):
    import threading

    from tpu_resiliency.platform.store import CoordStore

    world = 3
    results = {}

    def run(rank):
        store = CoordStore("127.0.0.1", kv_server.port)
        calc = TimeoutsCalc(safety_factor=1.0)
        calc.start_time = 0.0
        calc.update_on_heartbeat(1.0 + rank)  # rank 2 has largest initial gap 3
        calc.update_on_heartbeat(2.0 + rank * 2)  # rank 2: gap 3
        calc.synchronize_all(store, rank, world)
        results[rank] = calc.get_hb_timeouts()
        store.close()

    threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    # all ranks agree on the MAX-merged gaps
    assert results[0].initial == results[1].initial == results[2].initial
    assert results[0].initial == pytest.approx(3.0)
    assert results[0].subsequent == pytest.approx(3.0)
