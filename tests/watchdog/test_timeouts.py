import pytest

from tpu_resiliency.exceptions import FaultToleranceError
from tpu_resiliency.watchdog import HeartbeatTimeouts, TimeoutsCalc


def test_hb_gap_tracking_injected_times():
    """Injected timestamps, no sleeping (reference test_timeouts_calc.py pattern)."""
    calc = TimeoutsCalc(safety_factor=5.0)
    calc.start_time = 100.0
    calc.update_on_heartbeat(103.0)  # initial gap 3
    calc.update_on_heartbeat(104.0)  # subsequent 1
    calc.update_on_heartbeat(106.5)  # subsequent 2.5
    t = calc.get_hb_timeouts()
    assert t.initial == pytest.approx(5.0 * 3.0)
    assert t.subsequent == pytest.approx(5.0 * 2.5)
    assert t.calculated


def test_initial_timeout_covers_subsequent_gap():
    calc = TimeoutsCalc(safety_factor=2.0)
    calc.start_time = 0.0
    calc.update_on_heartbeat(1.0)
    calc.update_on_heartbeat(11.0)  # subsequent gap 10 > initial gap 1
    t = calc.get_hb_timeouts()
    assert t.initial == pytest.approx(20.0)


def test_needs_two_heartbeats():
    calc = TimeoutsCalc()
    calc.start_time = 0.0
    calc.update_on_heartbeat(1.0)
    with pytest.raises(FaultToleranceError):
        calc.get_hb_timeouts()


def test_ema_merge_with_previous():
    calc = TimeoutsCalc(safety_factor=1.0)
    calc.start_time = 0.0
    calc.update_on_heartbeat(4.0)
    calc.update_on_heartbeat(6.0)
    prev = HeartbeatTimeouts(initial=8.0, subsequent=4.0, calculated=True)
    t = calc.get_hb_timeouts(previous=prev)
    assert t.initial == pytest.approx(0.5 * 4.0 + 0.5 * 8.0)
    assert t.subsequent == pytest.approx(0.5 * 2.0 + 0.5 * 4.0)


def test_sections():
    calc = TimeoutsCalc(safety_factor=2.0)
    calc.update_on_section_open("step", 10.0)
    calc.update_on_section_close("step", 11.5)
    calc.update_on_section_open("step", 20.0)  # out-of-section gap 8.5
    calc.update_on_section_close("step", 21.0)
    st = calc.get_section_timeouts()
    assert st.section["step"] == pytest.approx(2.0 * 1.5)
    assert st.out_of_section == pytest.approx(2.0 * 8.5)
    with pytest.raises(FaultToleranceError):
        calc.update_on_section_close("never-opened")


def test_store_synchronize_max(kv_server):
    import threading

    from tpu_resiliency.platform.store import CoordStore

    world = 3
    results = {}

    def run(rank):
        store = CoordStore("127.0.0.1", kv_server.port)
        calc = TimeoutsCalc(safety_factor=1.0)
        calc.start_time = 0.0
        calc.update_on_heartbeat(1.0 + rank)  # rank 2 has largest initial gap 3
        calc.update_on_heartbeat(2.0 + rank * 2)  # rank 2: gap 3
        calc.synchronize_all(store, rank, world)
        results[rank] = calc.get_hb_timeouts()
        store.close()

    threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    # all ranks agree on the MAX-merged gaps
    assert results[0].initial == results[1].initial == results[2].initial
    assert results[0].initial == pytest.approx(3.0)
    assert results[0].subsequent == pytest.approx(3.0)


def test_store_synchronize_sections_max_contract(kv_server):
    """VERDICT r3 Missing #6: the store-round section sync satisfies the
    reference's max-across-ranks contract (``timeouts_calc.py:74-91``): after
    ``synchronize_all`` every rank's section/out-of-section stats equal the
    element-wise MAX over ranks, all ranks produce IDENTICAL timeouts, and the
    contract holds across repeated sync epochs (reentrant barriers)."""
    import threading

    from tpu_resiliency.platform.store import CoordStore

    world = 4
    # rank r: step takes 1+r, ckpt takes 10-2r, out-of-section gap 0.5*r.
    step_d = {r: 1.0 + r for r in range(world)}
    ckpt_d = {r: 10.0 - 2 * r for r in range(world)}
    oos_d = {r: 0.5 * r for r in range(world)}
    results = {}
    errors = []

    def run(rank):
        try:
            store = CoordStore("127.0.0.1", kv_server.port)
            calc = TimeoutsCalc(safety_factor=2.0)
            t = 100.0
            calc.update_on_section_open("step", t)
            calc.update_on_section_close("step", t + step_d[rank])
            t += step_d[rank] + oos_d[rank]
            calc.update_on_section_open("ckpt", t)
            calc.update_on_section_close("ckpt", t + ckpt_d[rank])
            calc.synchronize_all(store, rank, world)
            merged_e1 = dict(calc.section_max_elapsed)
            oos_e1 = calc.out_of_section_max
            first = calc.get_section_timeouts()
            # Second epoch: a new, larger local observation on ONE rank must
            # propagate to every rank through a fresh sync round.
            if rank == 1:
                calc.update_on_section_open("step", 200.0)
                calc.update_on_section_close("step", 212.0)  # 12 s
            calc.synchronize_all(store, rank, world)
            second = calc.get_section_timeouts(previous=first)
            results[rank] = (first, second, merged_e1, oos_e1)
            store.close()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, repr(e)))

    threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(60.0)
    assert not errors, errors
    assert set(results) == set(range(world))

    # Epoch 1: merged stats are the global max on EVERY rank.
    for rank, (first, _, merged, oos) in results.items():
        assert merged["step"] == pytest.approx(max(step_d.values()))  # 4.0
        assert merged["ckpt"] == pytest.approx(max(ckpt_d.values()))  # 10.0
        assert oos >= max(oos_d.values())
        assert first.section["step"] == pytest.approx(2.0 * 4.0)
        assert first.section["ckpt"] == pytest.approx(2.0 * 10.0)
        assert first.calculated_sections == frozenset({"step", "ckpt"})
    # All ranks computed identical timeouts (the synchronized-values contract).
    firsts = [results[r][0] for r in range(world)]
    assert all(f.section == firsts[0].section for f in firsts)
    assert all(f.out_of_section == firsts[0].out_of_section for f in firsts)

    # Epoch 2: rank 1's 12 s step observation reached everyone, and the EMA
    # merge with epoch-1 values matches the reference formula on every rank.
    seconds = [results[r][1] for r in range(world)]
    assert all(s.section == seconds[0].section for s in seconds)
    assert seconds[0].section["step"] == pytest.approx(0.5 * (2.0 * 12.0) + 0.5 * 8.0)
