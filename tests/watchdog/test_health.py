"""TPU/host runtime health sources: runtime inventory + HBM pressure, host memory
pressure, and interconnect link-error monitoring with topology discovery — all with
injectable paths/thresholds so every branch is testable without hardware (the
reference's ``link_down_path_template`` pattern, ``health_check.py:325``)."""

import jax

from tpu_resiliency.watchdog import HostMemoryCheck, IciLinkCheck, TpuRuntimeCheck


class TestTpuRuntimeCheck:
    def test_healthy_on_live_runtime(self):
        check = TpuRuntimeCheck()
        assert check() is True
        assert check.last_failure is None

    def test_device_count_drop_detected(self):
        have = len(jax.local_devices())
        check = TpuRuntimeCheck(expect_devices=have + 1)
        assert check() is False
        assert "device count dropped" in check.last_failure
        assert "device count dropped" in check.describe()

    def test_hbm_threshold_not_tripped_on_cpu(self):
        # CPU devices report no usable memory stats: the criterion is skipped,
        # never false-positived.
        check = TpuRuntimeCheck(hbm_usage_threshold=0.0)
        assert check() is True


class TestHostMemoryCheck:
    def write_meminfo(self, tmp_path, total_kb, avail_kb):
        p = tmp_path / "meminfo"
        p.write_text(
            f"MemTotal:       {total_kb} kB\n"
            f"MemFree:        {avail_kb} kB\n"
            f"MemAvailable:   {avail_kb} kB\n"
            "Buffers:        0 kB\n"
        )
        return str(p)

    def test_healthy_above_floor(self, tmp_path):
        path = self.write_meminfo(tmp_path, 16_000_000, 8_000_000)
        assert HostMemoryCheck(0.05, meminfo_path=path)() is True

    def test_pressure_below_floor(self, tmp_path):
        path = self.write_meminfo(tmp_path, 16_000_000, 200_000)  # 1.25%
        assert HostMemoryCheck(0.05, meminfo_path=path)() is False

    def test_unreadable_meminfo_is_not_fatal(self, tmp_path):
        assert HostMemoryCheck(meminfo_path=str(tmp_path / "missing"))() is True
        bad = tmp_path / "bad"
        bad.write_text("garbage\n")
        assert HostMemoryCheck(meminfo_path=str(bad))() is True


class TestIciLinkCheck:
    def make_topology(self, tmp_path, n=4):
        for i in range(n):
            d = tmp_path / f"accel{i}"
            d.mkdir()
            (d / "link_downed").write_text("0\n")
        return IciLinkCheck(
            device_glob=str(tmp_path / "accel*"),
            link_down_path_template=str(tmp_path / "{device}" / "link_downed"),
        )

    def test_discovery_maps_devices_to_counters(self, tmp_path):
        check = self.make_topology(tmp_path)
        topo = check.discover()
        assert sorted(topo) == [f"accel{i}" for i in range(4)]
        assert all(path.endswith("link_downed") for path in topo.values())

    def test_counter_increase_flags_the_right_link(self, tmp_path):
        check = self.make_topology(tmp_path)
        assert check() is True  # baseline
        assert check() is True  # steady
        (tmp_path / "accel2" / "link_downed").write_text("3\n")
        assert check() is False
        assert check.failed_links == ["accel2"]
        assert "accel2" in check.describe()
        # Sticky until reset (the reference marks the node unhealthy, not flapping).
        (tmp_path / "accel2" / "link_downed").write_text("3\n")
        assert check() is False
        check.reset()
        assert check() is True  # new baseline accepted

    def test_missing_counter_files_are_skipped(self, tmp_path):
        (tmp_path / "accel9").mkdir()  # device without a counter file
        check = IciLinkCheck(
            device_glob=str(tmp_path / "accel*"),
            link_down_path_template=str(tmp_path / "{device}" / "link_downed"),
        )
        assert check.discover() == {}
        assert check() is True


class TestChecksFromConfig:
    def test_disabled_by_default(self):
        from tpu_resiliency.watchdog.config import FaultToleranceConfig
        from tpu_resiliency.watchdog.health import checks_from_config

        assert checks_from_config(FaultToleranceConfig()) == []

    def test_config_enables_builtin_sources(self, tmp_path):
        from tpu_resiliency.watchdog.config import FaultToleranceConfig
        from tpu_resiliency.watchdog.health import checks_from_config

        cfg = FaultToleranceConfig(
            enable_health_checks=True,
            host_memory_min_fraction=0.05,
            ici_link_device_glob=str(tmp_path / "accel*"),
            ici_link_down_path_template=str(tmp_path / "{device}" / "link_downed"),
        )
        checks = checks_from_config(cfg)
        kinds = [type(c).__name__ for c in checks]
        assert kinds == ["HostMemoryCheck", "IciLinkCheck"]

    def test_monitor_server_builds_from_config(self, tmp_path):
        from tpu_resiliency.watchdog.config import FaultToleranceConfig
        from tpu_resiliency.watchdog.monitor_server import RankMonitorServer

        cfg = FaultToleranceConfig(host_memory_min_fraction=0.01)
        srv = RankMonitorServer(cfg, socket_path=str(tmp_path / "m.sock"))
        assert [type(c).__name__ for c in srv.health_checks] == ["HostMemoryCheck"]
        # An explicit empty list disables the config-driven construction.
        srv2 = RankMonitorServer(cfg, socket_path=str(tmp_path / "m2.sock"), health_checks=[])
        assert srv2.health_checks == []

    def test_ft_param_cli_roundtrip(self):
        import argparse

        from tpu_resiliency.watchdog.config import FaultToleranceConfig

        ns = argparse.Namespace(ft_param_host_memory_min_fraction="0.07")
        cfg = FaultToleranceConfig.from_args(ns)
        assert cfg.host_memory_min_fraction == 0.07
