"""Multi-host training path: the FULL sharded train step (tensor-parallel params,
data-parallel gradient reduction, ring attention over the sequence axis) on a
global mesh spanning 2 real JAX processes — collectives cross a genuine process
boundary, not just virtual devices in one runtime.

This is the configuration the framework is designed around (SURVEY §7: "scale via
jax.sharding + collectives over a Mesh"); single-process virtual-device tests
cannot catch bugs in process-local shard bookkeeping (e.g. addressable-shard
assembly, per-process data feeding)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


CHILD = textwrap.dedent(
    """
    import json, sys

    import os
    proc_id = int(sys.argv[1]); coord_port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"127.0.0.1:{coord_port}", num_processes=2, process_id=proc_id)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_resiliency.models import transformer as tfm
    from tpu_resiliency.parallel import mesh as pmesh
    from tpu_resiliency.parallel.ring_attention import make_ring_attn_fn

    # Global mesh over 8 devices across 2 processes: dp spans the process
    # boundary (gradient all-reduce crosses hosts), sp and tp stay intra-process.
    devs = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devs, ("dp", "sp", "tp"))
    assert {d.process_index for d in devs[0].flatten()} == {0}
    assert {d.process_index for d in devs[1].flatten()} == {1}

    cfg = tfm.TransformerConfig.tiny(n_layers=2, dtype=jnp.float32)
    attn_fn = make_ring_attn_fn(mesh)
    train_step, init_opt = tfm.make_train_step(cfg, attn_fn=attn_fn)

    params = jax.device_put(
        tfm.init_params(jax.random.PRNGKey(0), cfg),
        pmesh.tree_shardings(mesh, pmesh.param_specs(cfg)),
    )
    opt_state = jax.jit(init_opt)(params)

    # Each process feeds ONLY its own dp shard of the global batch
    # (make_array_from_process_local_data): global [4, 32], local [2, 32].
    rng = np.random.default_rng(7)
    global_tokens = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    tok_sharding = NamedSharding(mesh, P("dp", "sp"))
    local_rows = global_tokens[proc_id * 2:(proc_id + 1) * 2]
    tokens = jax.make_array_from_process_local_data(tok_sharding, local_rows)

    step = jax.jit(train_step, donate_argnums=(0, 1))
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))

    print("MH-RESULT " + json.dumps({"proc": proc_id, "losses": losses}), flush=True)
    """
)


def test_train_step_spans_two_processes(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    coord_port = free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(p), str(coord_port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=str(tmp_path),
        )
        for p in range(2)
    ]
    results = {}
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"child failed:\n{out}\n{err}"
            line = [ln for ln in out.splitlines() if ln.startswith("MH-RESULT ")][0]
            r = json.loads(line[len("MH-RESULT "):])
            results[r["proc"]] = r["losses"]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # Both processes computed the identical global loss sequence (the gradient
    # all-reduce over dp crossed the process boundary), and training decreased it.
    assert results[0] == results[1]
    assert results[0][-1] < results[0][0]

    # Cross-check against a single-process dense run on the same data: the
    # distributed sharded step is THE SAME computation.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_resiliency.models import transformer as tfm

    cfg = tfm.TransformerConfig.tiny(n_layers=2, dtype=jnp.float32)
    train_step, init_opt = tfm.make_train_step(cfg)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt_state = init_opt(params)
    rng = np.random.default_rng(7)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    ref_losses = []
    step = jax.jit(train_step)
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
        ref_losses.append(float(loss))
    np.testing.assert_allclose(results[0], ref_losses, rtol=1e-4)


PIPELINE_CHILD = textwrap.dedent(
    """
    import json, sys

    import os
    proc_id = int(sys.argv[1]); coord_port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"127.0.0.1:{coord_port}", num_processes=2, process_id=proc_id)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_resiliency.models import moe
    from tpu_resiliency.parallel import mesh as pmesh
    from tpu_resiliency.parallel import pipeline as pl

    # Global mesh over 8 devices across 2 processes with pp OUTERMOST: each
    # process hosts one pipeline stage, so every microbatch's stage hop
    # (lax.ppermute on the activation carry) crosses the real process boundary —
    # the actual multi-host pipeline deployment.
    devs = np.array(jax.devices()).reshape(2, 2, 2, 1, 1)
    mesh = Mesh(devs, ("pp", "dp", "ep", "sp", "tp"))
    assert {d.process_index for d in devs[0].flatten()} == {0}
    assert {d.process_index for d in devs[1].flatten()} == {1}

    cfg = moe.MoEConfig.tiny(dtype=jnp.float32)
    specs = pmesh.moe_param_specs(cfg)
    specs["layers"] = pmesh.pipeline_layer_specs(specs["layers"])
    params = jax.device_put(
        moe.init_params(jax.random.PRNGKey(0), cfg),
        pmesh.tree_shardings(mesh, specs),
    )

    rng = np.random.default_rng(11)
    global_tokens = rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    # dp is intra-process here (pp is the cross-process axis), so every process's
    # devices cover the full dp extent: the process-local data IS the full batch
    # (replicated over pp/ep within the process).
    tok_sharding = NamedSharding(mesh, P("dp", None))
    tokens = jax.make_array_from_process_local_data(tok_sharding, global_tokens)

    with mesh:
        step, init_opt = pl.make_pipelined_train_step(cfg, mesh, n_micro=4, family="moe")
        opt = jax.jit(init_opt)(params)
        sj = jax.jit(step, donate_argnums=(0, 1))
        losses = []
        for _ in range(3):
            params, opt, loss = sj(params, opt, tokens)
            losses.append(float(loss))
    print("MH-PP-RESULT " + json.dumps({"proc": proc_id, "losses": losses}), flush=True)
    """
)


def test_pipeline_stage_hop_spans_two_processes(tmp_path):
    """MoE pipeline with one stage per process: ppermute stage hops and expert
    all-to-alls cross a genuine process boundary, and the loss matches the
    single-process unpipelined MoE run on the same data."""
    script = tmp_path / "pp_child.py"
    script.write_text(PIPELINE_CHILD)
    coord_port = free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(p), str(coord_port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=str(tmp_path),
        )
        for p in range(2)
    ]
    results = {}
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"child failed:\n{out}\n{err}"
            line = [ln for ln in out.splitlines() if ln.startswith("MH-PP-RESULT ")][0]
            r = json.loads(line[len("MH-PP-RESULT "):])
            results[r["proc"]] = r["losses"]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert results[0] == results[1]
    assert results[0][-1] < results[0][0]

    # Cross-check the first loss against the single-process unpipelined MoE
    # (aux-free: the router aux is per-microbatch in the pipeline, see
    # tests/models/test_pipeline.py).
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_resiliency.models import moe

    cfg = moe.MoEConfig.tiny(dtype=jnp.float32, router_aux_weight=0.0)
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(11)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
    ref_loss = float(jax.jit(lambda p, t: moe.loss_fn(p, t, cfg))(params, tokens))
    # The distributed run includes its (per-microbatch) aux term: compare the CE
    # part within the aux term's magnitude.
    assert abs(results[0][0] - ref_loss) < 0.05, (results[0][0], ref_loss)
