"""Pipeline parallelism: the GPipe microbatch schedule over ``pp`` computes exactly
the same function (and gradients) as the unpipelined scan, and composes with tp
(auto tensor parallelism) and ep (expert-parallel MoE) inside the stage body
(parallel/pipeline.py)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding

from tpu_resiliency.models import moe as moe_mod
from tpu_resiliency.models import transformer as tfm
from tpu_resiliency.parallel import mesh as pmesh
from tpu_resiliency.parallel import pipeline as pl


def _sharded(cfg, params, tokens, mesh, specs):
    specs = dict(specs)
    specs["layers"] = pmesh.pipeline_layer_specs(specs["layers"])
    params_s = jax.device_put(params, pmesh.tree_shardings(mesh, specs))
    tok_s = jax.device_put(tokens, NamedSharding(mesh, pmesh.batch_spec()))
    return params_s, tok_s


def test_dense_pipeline_exact_in_f32():
    cfg = tfm.TransformerConfig.tiny(dtype=jnp.float32, n_layers=4)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)

    mesh = pmesh.build_mesh(devices=jax.devices()[:8], dp=2, tp=2, pp=2)
    params_s, tok_s = _sharded(cfg, params, tokens, mesh, pmesh.param_specs(cfg))

    loss_ref = jax.jit(lambda p, t: tfm.loss_fn(p, t, cfg))(params, tokens)
    g_ref = jax.grad(lambda p: tfm.loss_fn(p, tokens, cfg))(params)
    with mesh:
        loss_fn = pl.make_pipelined_loss_fn(cfg, mesh, n_micro=4)
        loss_pl = jax.jit(loss_fn)(params_s, tok_s)
        g_pl = jax.jit(jax.grad(loss_fn))(params_s, tok_s)

    assert float(loss_pl) == pytest.approx(float(loss_ref), abs=1e-5)
    rel = jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max() / (jnp.abs(a).max() + 1e-9)), g_ref, g_pl
    )
    assert max(jax.tree.leaves(rel)) < 1e-4


def test_dense_pipeline_four_stages():
    cfg = tfm.TransformerConfig.tiny(dtype=jnp.float32, n_layers=4)
    params = tfm.init_params(jax.random.PRNGKey(2), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (6, 16), 0, cfg.vocab_size)

    mesh = pmesh.build_mesh(devices=jax.devices()[:8], dp=2, pp=4)
    params_s, tok_s = _sharded(cfg, params, tokens, mesh, pmesh.param_specs(cfg))

    loss_ref = jax.jit(lambda p, t: tfm.loss_fn(p, t, cfg))(params, tokens)
    with mesh:
        loss_fn = pl.make_pipelined_loss_fn(cfg, mesh, n_micro=3)
        loss_pl = jax.jit(loss_fn)(params_s, tok_s)
    assert float(loss_pl) == pytest.approx(float(loss_ref), abs=1e-5)


def test_bf16_pipeline_close():
    cfg = tfm.TransformerConfig.tiny()  # bf16 activations
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)
    mesh = pmesh.build_mesh(devices=jax.devices()[:8], dp=2, tp=2, pp=2)
    params_s, tok_s = _sharded(cfg, params, tokens, mesh, pmesh.param_specs(cfg))
    loss_ref = jax.jit(lambda p, t: tfm.loss_fn(p, t, cfg))(params, tokens)
    with mesh:
        loss_pl = jax.jit(pl.make_pipelined_loss_fn(cfg, mesh, n_micro=2))(params_s, tok_s)
    assert float(loss_pl) == pytest.approx(float(loss_ref), abs=0.05)


def test_moe_pipeline_with_expert_parallel():
    """The full (dp, pp, ep) composition: pipelined MoE matches the unpipelined MoE
    cross-entropy exactly (routing is per batch row, so microbatching cannot change
    it) and takes a finite optimizer step. The router aux term is *expected* to
    differ slightly: it is a product of batch means, computed per microbatch in the
    pipeline — so it is compared loosely and excluded from the exact check."""
    cfg = moe_mod.MoEConfig.tiny(dtype=jnp.float32, router_aux_weight=0.0)
    params = moe_mod.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)

    mesh = pmesh.build_mesh(devices=jax.devices()[:8], dp=2, pp=2, ep=2)
    params_s, tok_s = _sharded(cfg, params, tokens, mesh, pmesh.moe_param_specs(cfg))

    loss_ref = jax.jit(lambda p, t: moe_mod.loss_fn(p, t, cfg))(params, tokens)
    with mesh:
        loss_fn = pl.make_pipelined_loss_fn(cfg, mesh, n_micro=4, family="moe")
        loss_pl = jax.jit(loss_fn)(params_s, tok_s)
        step, init_opt = pl.make_pipelined_train_step(cfg, mesh, n_micro=4, family="moe")
        opt = jax.jit(init_opt)(params_s)
        p2, o2, l2 = jax.jit(step)(params_s, opt, tok_s)
    assert float(loss_pl) == pytest.approx(float(loss_ref), abs=1e-4)
    assert jnp.isfinite(l2)

    cfg_aux = moe_mod.MoEConfig.tiny(dtype=jnp.float32)  # default aux weight
    loss_ref_aux = jax.jit(lambda p, t: moe_mod.loss_fn(p, t, cfg_aux))(params, tokens)
    with mesh:
        loss_pl_aux = jax.jit(
            pl.make_pipelined_loss_fn(cfg_aux, mesh, n_micro=4, family="moe")
        )(params_s, tok_s)
    assert float(loss_pl_aux) == pytest.approx(float(loss_ref_aux), abs=0.02)


def test_pipeline_rejects_bad_configs():
    cfg = tfm.TransformerConfig.tiny(n_layers=3)
    mesh = pmesh.build_mesh(devices=jax.devices()[:8], dp=2, tp=2, pp=2)
    with pytest.raises(ValueError, match="not divisible"):
        pl.make_pipelined_loss_fn(cfg, mesh, n_micro=2)

    cfg4 = tfm.TransformerConfig.tiny(n_layers=4)
    mesh_sp = pmesh.build_mesh(devices=jax.devices()[:8], dp=2, sp=2, pp=2)
    with pytest.raises(ValueError, match="ring attention"):
        pl.make_pipelined_loss_fn(cfg4, mesh_sp, n_micro=2)

    mesh_ok = pmesh.build_mesh(devices=jax.devices()[:8], dp=4, pp=2)
    with pytest.raises(ValueError, match="n_micro"):
        pl.make_pipelined_loss_fn(cfg4, mesh_ok, n_micro=0)

    loss_fn = pl.make_pipelined_loss_fn(cfg4, mesh_ok, n_micro=4)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg4)
    tokens = jnp.zeros((6, 16), jnp.int32)  # 6 % 4 != 0
    with pytest.raises(ValueError, match="divisible by n_micro"):
        jax.jit(loss_fn)(params, tokens)
