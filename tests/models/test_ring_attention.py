"""Ring attention must be THE SAME function as dense causal attention, just
sharded: same outputs, same gradients, on a real multi-device mesh with the
sequence axis sharded and K/V blocks rotating over ``ppermute``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_resiliency.models import transformer as tfm
from tpu_resiliency.parallel import mesh as pmesh
from tpu_resiliency.parallel.ring_attention import make_ring_attn_fn


def make_mesh(dp, sp, tp):
    devs = np.asarray(jax.devices()[: dp * sp * tp]).reshape(dp, sp, tp)
    return Mesh(devs, ("dp", "sp", "tp"))


@pytest.mark.parametrize("dp,sp,tp", [(2, 2, 2), (2, 4, 1), (1, 8, 1)])
def test_kernel_matches_dense_attention(dp, sp, tp):
    mesh = make_mesh(dp, sp, tp)
    b, t, h, dh = 4, 32, 4, 16
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, t, h, dh)), jnp.float32) for _ in range(3)
    )

    dense = tfm._attention(q, k, v)

    spec = NamedSharding(mesh, P("dp", "sp", "tp", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    ring = jax.jit(make_ring_attn_fn(mesh))(qs, ks, vs)

    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)
    # The output stays sequence-sharded — no hidden full replication.
    assert not ring.sharding.is_fully_replicated


def test_forward_and_grads_match_dense():
    """Full transformer forward + loss grads: ring over an (dp=2, sp=2, tp=2) mesh
    vs dense on the same inputs."""
    mesh = make_mesh(2, 2, 2)
    cfg = tfm.TransformerConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = tfm.init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)

    dense_loss, dense_grads = jax.value_and_grad(tfm.loss_fn)(params, tokens, cfg)

    pshard = pmesh.tree_shardings(mesh, pmesh.param_specs(cfg))
    params_s = jax.device_put(params, pshard)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))
    attn_fn = make_ring_attn_fn(mesh)

    ring_loss, ring_grads = jax.jit(
        jax.value_and_grad(lambda p, tk: tfm.loss_fn(p, tk, cfg, attn_fn=attn_fn))
    )(params_s, tokens_s)

    np.testing.assert_allclose(float(ring_loss), float(dense_loss), rtol=1e-5)
    flat_d, _ = jax.tree_util.tree_flatten(dense_grads)
    flat_r, _ = jax.tree_util.tree_flatten(ring_grads)
    for gd, gr in zip(flat_d, flat_r):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=5e-4, atol=5e-5
        )


def test_default_split_exercises_all_axes():
    split = pmesh.default_split(8)
    assert split == {"dp": 2, "tp": 2, "sp": 2, "pp": 1, "ep": 1}
    assert split["sp"] > 1  # the sequence axis is real, not decorative
    # pp/ep get their own split: the MoE pipeline config covers both.
    moe_split = pmesh.moe_pipeline_split(8)
    assert moe_split["pp"] > 1 and moe_split["ep"] > 1
