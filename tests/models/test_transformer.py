import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_resiliency.models import transformer as tfm


@pytest.fixture(scope="module")
def tiny():
    cfg = tfm.TransformerConfig.tiny()
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_forward_shapes_and_finiteness(tiny):
    cfg, params = tiny
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = jax.jit(lambda p, t: tfm.forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_causality(tiny):
    """Changing a future token must not change past logits."""
    cfg, params = tiny
    t1 = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0, cfg.vocab_size)
    t2 = t1.at[0, 8].set((t1[0, 8] + 1) % cfg.vocab_size)
    l1 = tfm.forward(params, t1, cfg)
    l2 = tfm.forward(params, t2, cfg)
    np.testing.assert_allclose(
        np.asarray(l1[0, :8]), np.asarray(l2[0, :8]), rtol=2e-2, atol=2e-2
    )
    assert not np.allclose(np.asarray(l1[0, 8:]), np.asarray(l2[0, 8:]), atol=1e-3)


def test_train_step_reduces_loss(tiny):
    cfg, _ = tiny
    params = tfm.init_params(jax.random.PRNGKey(3), cfg)
    train_step, init_opt = tfm.make_train_step(cfg)
    step = jax.jit(train_step)
    opt_state = init_opt(params)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0, cfg.vocab_size)
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_sharded_train_step_8dev():
    from tpu_resiliency.parallel import mesh as pmesh

    cfg = tfm.TransformerConfig.tiny()
    mesh = pmesh.build_mesh(dp=2, tp=4)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    pshard = pmesh.tree_shardings(mesh, pmesh.param_specs(cfg))
    params = jax.device_put(params, pshard)
    train_step, init_opt = tfm.make_train_step(cfg)
    opt_state = init_opt(params)
    from jax.sharding import NamedSharding

    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size),
        NamedSharding(mesh, pmesh.batch_spec()),
    )
    with mesh:
        params2, opt2, loss = jax.jit(train_step)(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    # sharded result must match unsharded execution
    params_r = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt_r = init_opt(params_r)
    _, _, loss_r = jax.jit(train_step)(params_r, opt_r, jax.device_get(tokens))
    np.testing.assert_allclose(float(loss), float(loss_r), rtol=5e-2)


def test_graft_entry():
    import importlib.util
    import sys

    spec = importlib.util.spec_from_file_location("__graft_entry__", "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    shape = jax.eval_shape(fn, *args)
    assert shape.shape == (2, 32, 256)
    mod.dryrun_multichip(8)


def test_adapt_attn_fn_contract(tiny):
    """Custom attn fns get pre-repeated full-head K/V (their documented
    contract) and cannot be combined with position_offset."""
    import pytest

    cfg, params = tiny
    seen = {}

    def spy(q, k, v):
        seen["shapes"] = (q.shape, k.shape, v.shape)
        return tfm._attention(q, k, v)

    tokens = jax.random.randint(jax.random.PRNGKey(5), (1, 8), 0, cfg.vocab_size)
    tfm.forward(params, tokens, cfg, attn_fn=spy)
    qs, ks, vs = seen["shapes"]
    assert qs[2] == cfg.n_heads
    assert ks[2] == vs[2] == cfg.n_heads, "custom fn must see repeated K/V"

    with pytest.raises(ValueError, match="position_offset"):
        tfm.forward(params, tokens, cfg, attn_fn=spy, position_offset=2)

    # default path: offset shifts RoPE, so logits must differ from offset=0
    base = tfm.forward(params, tokens, cfg)
    off = tfm.forward(params, tokens, cfg, position_offset=3)
    assert not np.allclose(np.asarray(base), np.asarray(off))
