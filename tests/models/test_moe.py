"""MoE model family: routing invariants, expert-parallel sharding equivalence,
training-step sanity (models/moe.py)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding

from tpu_resiliency.models import moe
from tpu_resiliency.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def cfg():
    return moe.MoEConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return moe.init_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def tokens(cfg):
    return jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)


def test_forward_shapes_and_aux(cfg, params, tokens):
    logits, aux = jax.jit(lambda p, t: moe.forward(p, t, cfg))(params, tokens)
    assert logits.shape == (*tokens.shape, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    # aux = E * sum_e(frac_e * mean_prob_e) with sum(frac) = K, sum(prob) = 1:
    # minimized at perfect balance where it equals top_k exactly.
    assert float(aux) >= cfg.top_k - 1e-2
    assert jnp.isfinite(aux)


def test_routing_respects_topk_and_capacity(cfg, params, tokens):
    y = params["embed"].astype(cfg.dtype)[tokens]
    dispatch, combine, aux = moe._route(cfg, y, params["layers"]["w_router"][0])
    B, T = tokens.shape
    E, C = cfg.n_experts, cfg.capacity(T)
    assert dispatch.shape == (B, T, E, C)
    # Each token occupies at most top_k expert slots; each slot holds <= 1 token.
    per_token = dispatch.sum(axis=(2, 3))
    assert float(per_token.max()) <= cfg.top_k + 1e-6
    per_slot = dispatch.sum(axis=1)
    assert float(per_slot.max()) <= 1 + 1e-6
    # Combine weights live only where dispatch does, and sum to <= 1 per token.
    assert float(jnp.where(dispatch == 0, combine, 0.0).max()) == 0.0
    assert float(combine.sum(axis=(2, 3)).max()) <= 1 + 1e-5


def test_generous_capacity_admits_every_token(cfg, params, tokens):
    roomy = moe.MoEConfig.tiny(capacity_factor=8.0)
    y = params["embed"].astype(roomy.dtype)[tokens]
    dispatch, combine, _ = moe._route(roomy, y, params["layers"]["w_router"][0])
    per_token = dispatch.sum(axis=(2, 3))
    assert float(per_token.min()) == pytest.approx(roomy.top_k, abs=1e-6)
    # Renormalized top-k gates sum to 1 when nothing is dropped.
    assert jnp.allclose(combine.sum(axis=(2, 3)), 1.0, atol=1e-5)


def test_ep_sharded_matches_replicated(cfg, params, tokens):
    logits_ref, aux_ref = jax.jit(lambda p, t: moe.forward(p, t, cfg))(params, tokens)

    mesh = pmesh.build_mesh(devices=jax.devices()[:8], dp=4, ep=2)
    shardings = pmesh.tree_shardings(mesh, pmesh.moe_param_specs(cfg))
    params_s = jax.device_put(params, shardings)
    tok_s = jax.device_put(tokens, NamedSharding(mesh, pmesh.batch_spec()))
    with mesh:
        logits_s, aux_s = jax.jit(lambda p, t: moe.forward(p, t, cfg))(params_s, tok_s)

    # bf16 activations under a different collective schedule: tolerance is a few
    # bf16 ulps of the logit scale.
    assert float(jnp.abs(logits_s - logits_ref).max()) < 0.08
    assert float(jnp.abs(aux_s - aux_ref)) < 1e-3


def test_train_step_decreases_loss(cfg, params, tokens):
    step, init_opt = moe.make_train_step(cfg)
    opt = jax.jit(init_opt)(params)
    s = jax.jit(step)
    p, o = params, opt
    first = None
    for _ in range(5):
        p, o, loss = s(p, o, tokens)
        if first is None:
            first = float(loss)
    assert jnp.isfinite(loss)
    assert float(loss) < first
