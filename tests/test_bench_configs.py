"""BASELINE configs 1-3 replay harnesses run in CI (scripts/bench_configs.py):
section-timing parity at 64 ranks, heartbeat-replay hang detection at 256 ranks,
and 5%-slow-node detection at 1024 ranks — each must detect perfectly (F1=1.0)
and, for config 2, within the analytical latency budget."""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_baseline_configs_1_2_3(tmp_path):
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "bench_configs.py"),
            "--out-dir", str(tmp_path),
            "--iters", "3",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": REPO_ROOT},
    )
    assert r.returncode == 0, r.stderr

    results = {}
    for n in (1, 2, 3):
        with open(tmp_path / f"BENCH_config{n}.json") as f:
            results[n] = json.loads(f.read())

    assert results[1]["f1"] == 1.0 and results[1]["flagged"] == [17]
    assert results[1]["parity_semantics_ok"] is True

    assert results[2]["f1"] == 1.0
    # Detected within the analytical budget: hb_timeout + hb_interval + tick.
    assert results[2]["detection_latency_s"] <= results[2]["latency_budget_s"]
    # The 256-rank per-tick scan is microseconds, not milliseconds.
    assert results[2]["scan_us_per_tick"] < 10_000

    assert results[3]["f1"] == 1.0 and results[3]["ranks"] == 1024
