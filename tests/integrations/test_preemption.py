"""Preemption-synchronized checkpointing end to end: 2 ranks train under
jax.distributed; ONE rank receives the preemption notice (SIGTERM); the
coordination service broadcasts it, BOTH ranks hit the sync point at the same
step, save that step, and stop cleanly. No reference analogue — this is the
TPU-first maintenance-event/spot-reclaim story."""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


CHILD = textwrap.dedent(
    """
    import json, os, sys, time

    proc_id = int(sys.argv[1]); port = sys.argv[2]; out_dir = sys.argv[3]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tpu_resiliency.platform import distributed as jdist

    jdist.initialize(
        f"127.0.0.1:{port}", num_processes=2, process_id=proc_id,
        heartbeat_timeout=10.0,
    )
    import jax.numpy as jnp

    from tpu_resiliency.integrations import PreemptionCheckpointCallback
    from tpu_resiliency.integrations.loop import run_training

    saved = {}

    def save(state, step):
        with open(os.path.join(out_dir, f"preempt_save_r{proc_id}.json"), "w") as f:
            json.dump({"step": step, "w": float(state["w"])}, f)
        saved["step"] = step

    cb = PreemptionCheckpointCallback(on_preemption=save)

    def step_fn(state, step):
        time.sleep(0.05)  # give the notice a window to land mid-run
        return {"w": state["w"] + 1.0}

    print(f"READY {os.getpid()}", flush=True)
    ctx = run_training(step_fn, {"w": jnp.zeros(())}, num_steps=400, callbacks=[cb])
    # Coordinator-last teardown: without it, a peer's atexit disconnect races
    # the coordinator service's death and LOG(FATAL)s the peer.
    jdist.shutdown_graceful(proc_id, grace=3.0)
    print(
        "PREEMPT-RESULT "
        + json.dumps({"rank": proc_id, "stopped_at": ctx.step,
                      "saved": saved.get("step"), "should_stop": ctx.should_stop}),
        flush=True,
    )
    """
)


def test_one_rank_notice_synchronizes_all_saves(tmp_path):
    port = free_port()
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    import threading

    procs = []
    bufs: list[list[str]] = []
    readers = []
    for r in range(2):
        p = subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), str(tmp_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=str(tmp_path),
        )
        buf: list[str] = []
        t = threading.Thread(target=lambda p=p, b=buf: b.extend(p.stdout), daemon=True)
        t.start()
        procs.append(p)
        bufs.append(buf)
        readers.append(t)
    try:
        # Deliver the notice only after BOTH ranks printed READY (the handler
        # exists past that point) — a blind warmup sleep loses under machine
        # load: a SIGTERM landing while a rank still imports jax just kills it,
        # and the peer then dies in RegisterTask (observed in the 20x soak).
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            if all(any(ln.startswith("READY") for ln in b) for b in bufs):
                break
            dead = [r for r, p in enumerate(procs) if p.poll() is not None]
            if dead:
                readers[dead[0]].join(5.0)
                raise AssertionError(
                    f"rank {dead[0]} died during warmup "
                    f"(rc={procs[dead[0]].returncode}):\n"
                    + "".join(bufs[dead[0]])[-3000:]
                )
            time.sleep(0.1)
        else:
            raise AssertionError(
                "ranks never became READY:\n"
                + "\n---\n".join("".join(b)[-1500:] for b in bufs)
            )
        time.sleep(1.0)  # both stepping; the notice lands mid-run
        procs[1].send_signal(signal.SIGTERM)  # the preemption notice
        results = {}
        for r, p in enumerate(procs):
            p.wait(timeout=120)
            readers[r].join(10.0)
            out = "".join(bufs[r])
            assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"
            line = [ln for ln in out.splitlines() if ln.startswith("PREEMPT-RESULT ")]
            assert line, f"rank {r} no result:\n{out[-2000:]}"
            results[r] = json.loads(line[0][len("PREEMPT-RESULT "):])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # Both ranks saved the SAME step (the agreed sync point) and stopped there —
    # including rank 0, which never received a signal.
    assert results[0]["saved"] is not None and results[1]["saved"] is not None
    assert results[0]["saved"] == results[1]["saved"], results
    assert all(r["should_stop"] for r in results.values()), results
    saves = {}
    for r in range(2):
        with open(tmp_path / f"preempt_save_r{r}.json") as f:
            saves[r] = json.load(f)
    assert saves[0]["step"] == saves[1]["step"]
    # Before the 400-step horizon: the stop came from the notice, not completion.
    assert results[0]["stopped_at"] < 400


def test_no_distributed_client_is_noop():
    """Single-controller jobs (no coordination service) never trip the callback."""
    from tpu_resiliency.integrations import PreemptionCheckpointCallback
    from tpu_resiliency.integrations.loop import run_training

    fired = []
    cb = PreemptionCheckpointCallback(on_preemption=lambda s, i: fired.append(i))
    ctx = run_training(lambda s, i: s, {"w": 0}, num_steps=5, callbacks=[cb])
    assert ctx.step == 5 and not fired and cb.preempted_at is None


def test_notice_defers_until_inflight_save_commits(tmp_path, monkeypatch):
    """REGRESSION (elastic reshard PR): a preemption notice landing DURING an
    in-flight async save must wait for the commit/rename — otherwise the
    grace-window save interleaves with the background writer and the "latest"
    iteration at shrink time can be torn."""
    import time as time_mod

    import numpy as np

    from tpu_resiliency.checkpoint import format as ckpt_format
    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
    from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
    from tpu_resiliency.integrations import PreemptionCheckpointCallback
    from tpu_resiliency.integrations.loop import LoopContext

    root = str(tmp_path / "ckpt")
    mgr = LocalCheckpointManager(root, rank=0, comm=None)

    # Slow the background container write down so the async save is
    # deterministically still in flight when the notice fires.
    real_write_stream = ckpt_format.write_stream

    def slow_write_stream(path, chunks, fsync=True):
        time_mod.sleep(0.4)
        return real_write_stream(path, chunks, fsync=fsync)

    monkeypatch.setattr(ckpt_format, "write_stream", slow_write_stream)
    sd = PyTreeStateDict({"w": np.arange(64, dtype=np.float32), "step": 5})
    mgr.save(5, sd, is_async=True)

    observed = {}

    def on_preemption(state, step):
        # The contract under test: by the time the final save runs, the
        # in-flight save has fully committed — visible container, no torn
        # ``.dirty`` temp, nothing left in the async queue.
        rdir = os.path.join(root, "s0", "r0")
        names = os.listdir(rdir)
        observed["names"] = names
        observed["dirty"] = [n for n in names if n.endswith(".dirty")]
        observed["committed"] = "iter_0000005_0_local.ckpt" in names
        observed["queue_drained"] = mgr.queue.maybe_finalize_async_calls() == []

    monkeypatch.setattr(
        PreemptionCheckpointCallback, "_reached", staticmethod(lambda step: True)
    )
    cb = PreemptionCheckpointCallback(
        on_preemption=on_preemption, ckpt_manager=mgr
    )
    ctx = LoopContext(step=6, state={"w": None})
    cb.on_step_end(ctx)
    mgr.close()
    assert observed["committed"], observed
    assert not observed["dirty"], observed
    assert observed["queue_drained"], observed
    assert ctx.should_stop and cb.preempted_at == 6


class TestRescind:
    """Notice rescind (autoscale PR): a notice withdrawn inside the grace
    window emits ``preemption_rescinded``, cancels the pending deferred
    drain, and re-arms the callback — it must NOT force the drain path."""

    @staticmethod
    def _cb(reached_flags, **kw):
        import unittest.mock as mock

        from tpu_resiliency.integrations import PreemptionCheckpointCallback

        it = iter(reached_flags)
        patcher = mock.patch.object(
            PreemptionCheckpointCallback, "_reached",
            staticmethod(lambda step: next(it)),
        )
        return patcher, kw

    def test_rescind_cancels_deferred_drain_and_rearms(self):
        import unittest.mock as mock

        from tpu_resiliency.integrations import PreemptionCheckpointCallback
        from tpu_resiliency.integrations.loop import LoopContext
        from tpu_resiliency.utils import events

        seen = []
        events.add_sink(seen.append)
        drains, saves = [], []

        class Mgr:
            def maybe_finalize(self, blocking=False):
                drains.append(blocking)

        # Asserted at steps 1-2, cleared at step 3 (rescind), asserted again
        # 5-8 (a later REAL notice, sustained through the grace).
        flags = iter([True, True, False, False, True, True, True, True])
        try:
            with mock.patch.object(
                PreemptionCheckpointCallback, "_reached",
                staticmethod(lambda step: next(flags)),
            ):
                cb = PreemptionCheckpointCallback(
                    on_preemption=lambda s, i: saves.append(i),
                    ckpt_manager=Mgr(), grace_steps=3,
                )
                for step in range(1, 5):
                    ctx = LoopContext(step=step)
                    cb.on_step_end(ctx)
                    assert not ctx.should_stop
                # The rescind: no drain, no save, one event, re-armed.
                assert drains == [] and saves == []
                assert cb.rescinded == 1 and cb.preempted_at is None
                rescinds = [e for e in seen if e.kind == "preemption_rescinded"]
                assert len(rescinds) == 1
                assert rescinds[0].payload["noticed_step"] == 1
                assert rescinds[0].payload["step"] == 3
                # The later sustained notice fires normally after its grace.
                stopped_at = None
                for step in range(5, 9):
                    ctx = LoopContext(step=step)
                    cb.on_step_end(ctx)
                    if ctx.should_stop:
                        stopped_at = step
                        break
                assert stopped_at == 8  # noticed at 5, grace 3 → fires at 8
                assert drains == [True] and saves == [8]
                assert cb.preempted_at == 8
        finally:
            events.remove_sink(seen.append)

    def test_grace_zero_keeps_act_immediately_semantics(self):
        import unittest.mock as mock

        from tpu_resiliency.integrations import PreemptionCheckpointCallback
        from tpu_resiliency.integrations.loop import LoopContext

        saves = []
        with mock.patch.object(
            PreemptionCheckpointCallback, "_reached",
            staticmethod(lambda step: True),
        ):
            cb = PreemptionCheckpointCallback(
                on_preemption=lambda s, i: saves.append(i)
            )
            ctx = LoopContext(step=7)
            cb.on_step_end(ctx)
        assert saves == [7] and ctx.should_stop and cb.preempted_at == 7

    def test_negative_grace_rejected(self):
        from tpu_resiliency.integrations import PreemptionCheckpointCallback

        import pytest as _pytest

        with _pytest.raises(ValueError):
            PreemptionCheckpointCallback(
                on_preemption=lambda s, i: None, grace_steps=-1
            )


def test_drain_failure_does_not_eat_the_grace_window():
    """A broken background save must not block the final preemption save."""
    from tpu_resiliency.integrations import PreemptionCheckpointCallback
    from tpu_resiliency.integrations.loop import LoopContext

    order = []

    class BrokenMgr:
        def maybe_finalize(self, blocking=False):
            order.append(("drain", blocking))
            raise RuntimeError("background writer died")

    import unittest.mock as mock

    with mock.patch.object(
        PreemptionCheckpointCallback, "_reached", staticmethod(lambda s: True)
    ):
        cb = PreemptionCheckpointCallback(
            on_preemption=lambda s, i: order.append(("save", i)),
            ckpt_manager=BrokenMgr(),
        )
        cb.on_step_end(LoopContext(step=3))
    assert order == [("drain", True), ("save", 3)]
