"""Integration-layer tests: loop protocol + the four callbacks, mirroring the
reference's ``tests/ptl_resiliency/unit`` pattern (fake trainer driving callbacks,
real monitor server behind an env-var socket)."""

import os
import time

import jax
import jax.numpy as jnp
import pytest

from tpu_resiliency.integrations import (
    Callback,
    FaultToleranceCallback,
    FaultToleranceSectionsCallback,
    HierarchicalCheckpointCallback,
    LoopContext,
    StopTraining,
    StragglerDetectionCallback,
    run_training,
)
from tpu_resiliency.platform import ipc
from tpu_resiliency.telemetry.detector import Detector
from tpu_resiliency.watchdog.config import FaultToleranceConfig
from tpu_resiliency.watchdog.monitor_server import RankMonitorServer


class Recorder(Callback):
    def __init__(self):
        self.events = []

    def __getattribute__(self, name):
        if name.startswith("on_"):
            events = object.__getattribute__(self, "events")

            def hook(ctx, *a):
                events.append(name)

            return hook
        return object.__getattribute__(self, name)


def test_loop_hook_order_and_state_threading():
    rec = Recorder()

    def step(state, i):
        return state + 1

    ctx = run_training(
        step,
        state=0,
        num_steps=3,
        callbacks=[rec],
        checkpoint_every=2,
        checkpoint_fn=lambda s, i: None,
        validate_every=3,
        validate_fn=lambda s, i: {"val": s},
    )
    assert ctx.state == 3
    assert rec.events[0] == "on_train_start"
    assert rec.events[-1] == "on_train_end"
    assert rec.events.count("on_step_start") == 3
    assert rec.events.count("on_step_end") == 3
    assert rec.events.count("on_checkpoint_start") == 1
    assert rec.events.count("on_validation_start") == 1
    assert ctx.metrics["val"] == 3


def test_loop_stop_training_cooperative():
    class Stopper(Callback):
        def on_step_end(self, ctx):
            if ctx.step == 1:
                raise StopTraining

    ctx = run_training(lambda s, i: s + 1, 0, 10, callbacks=[Stopper()])
    assert ctx.state == 2  # stopped after step 1 completed


def test_loop_exception_fires_hook_and_propagates():
    seen = []

    class Witness(Callback):
        def on_exception(self, ctx, exc):
            seen.append(repr(exc))

    def step(state, i):
        if i == 1:
            raise ValueError("boom")
        return state

    with pytest.raises(ValueError):
        run_training(step, 0, 5, callbacks=[Witness()])
    assert seen and "boom" in seen[0]


@pytest.fixture
def monitor(tmp_path):
    sock = str(tmp_path / "m.sock")
    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=30.0,
        rank_heartbeat_timeout=30.0,
        workload_check_interval=0.5,
    )
    proc = RankMonitorServer.run_in_subprocess(cfg, sock, start_method="spawn")
    old = os.environ.get(ipc.MONITOR_SOCKET_ENV)
    os.environ[ipc.MONITOR_SOCKET_ENV] = sock
    yield sock
    if old is None:
        os.environ.pop(ipc.MONITOR_SOCKET_ENV, None)
    else:
        os.environ[ipc.MONITOR_SOCKET_ENV] = old
    proc.terminate()
    proc.join(timeout=10)


def test_ft_callback_heartbeats_and_finished_flag(monitor, tmp_path):
    from tpu_resiliency.utils import events

    flag = str(tmp_path / "finished.flag")
    sd_path = str(tmp_path / "ft_state.pkl")
    cb = FaultToleranceCallback(
        autoresume=True, finished_flag_path=flag, state_dict_path=sd_path
    )
    seen = []
    events.add_sink(seen.append)
    try:
        ctx = run_training(lambda s, i: s + 1, 0, 5, callbacks=[cb])
    finally:
        events.remove_sink(seen.append)
    assert ctx.state == 5
    assert cb.machine.heartbeats >= 5
    assert cb.machine.finished
    assert os.path.exists(flag)
    assert os.path.exists(sd_path)  # calculated timeouts persisted
    # Both FT milestones are on the structured event stream.
    kinds = {e.kind for e in seen if e.source == "ft"}
    assert {"timeouts_calculated", "training_finished"} <= kinds, kinds
    tc = next(e for e in seen if e.kind == "timeouts_calculated")
    assert tc.payload["initial_s"] > 0 and tc.payload["subsequent_s"] > 0

    # Second run: the finished flag short-circuits training (autoresume contract).
    cb2 = FaultToleranceCallback(autoresume=True, finished_flag_path=flag)
    ctx2 = run_training(lambda s, i: s + 1, 0, 5, callbacks=[cb2])
    assert ctx2.state == 0 and ctx2.should_stop


def test_ft_callback_simulated_fault(monitor):
    from tpu_resiliency.integrations.ft_callbacks import SimulatedFault

    cb = FaultToleranceCallback(simulated_fault_step=2)
    with pytest.raises(SimulatedFault, match="simulated fault"):
        run_training(lambda s, i: s + 1, 0, 5, callbacks=[cb])
    assert cb.machine.exception_seen and not cb.machine.finished


def test_ft_sections_callback(monitor):
    cb = FaultToleranceSectionsCallback()
    ctx = run_training(
        lambda s, i: s + 1,
        0,
        4,
        callbacks=[cb],
        checkpoint_every=2,
        checkpoint_fn=lambda s, i: None,
    )
    assert ctx.state == 4
    calc = cb.client.timeouts_calc
    assert set(calc.section_max_elapsed) >= {"setup", "step", "checkpointing"}
    assert all(v >= 0 for v in calc.section_max_elapsed.values())


def test_straggler_callback_reports(monkeypatch):
    if Detector.initialized:
        Detector.shutdown()
    cb = StragglerDetectionCallback(report_time_interval=0.0, threshold=0.75)

    def step(state, i):
        time.sleep(0.002)
        return state + 1

    ctx = run_training(step, 0, 20, callbacks=[cb])
    assert ctx.state == 20
    assert cb.last_report is not None
    assert any("train_step" in n for n in cb.last_report.section_names)
    assert not Detector.initialized  # shut down on train end


def test_straggler_callback_profiles_programs():
    """profile_programs_every wires the XLA-profiler capture into the loop: jitted
    programs executed inside profiled steps join the scored matrix as prog/
    signals (host-PjitFunction fallback on the CPU backend)."""
    import jax
    import jax.numpy as jnp

    if Detector.initialized:
        Detector.shutdown()
    cb = StragglerDetectionCallback(
        report_time_interval=0.0, profile_programs_every=2
    )

    @jax.jit
    def work(x):
        return jnp.tanh(x * 2.0).sum()

    def step(state, i):
        jax.block_until_ready(work(jnp.full((32,), float(i))))
        return state + 1

    ctx = run_training(step, 0, 24, callbacks=[cb])
    assert ctx.state == 24
    assert cb.last_report is not None
    assert any(n.startswith("prog/") for n in cb.last_report.section_names), (
        cb.last_report.section_names
    )
    # The window closed with training (no leaked process-global trace).
    assert cb._program_profiler is not None and not cb._program_profiler.active


def test_straggler_callback_profiles_ops():
    """profile_ops adds the per-op/scope granularity from the same windows:
    op/... signals join the scored matrix alongside prog/... (PjRt client
    per-op line on the CPU backend)."""
    import jax
    import jax.numpy as jnp

    if Detector.initialized:
        Detector.shutdown()
    cb = StragglerDetectionCallback(
        report_time_interval=0.0, profile_programs_every=2, profile_ops=True
    )

    @jax.jit
    def work(x):
        return jnp.tanh(x @ x).sum()

    def step(state, i):
        jax.block_until_ready(work(jnp.full((64, 64), float(i))))
        return state + 1

    ctx = run_training(step, 0, 24, callbacks=[cb])
    assert ctx.state == 24
    assert cb.last_report is not None
    names = cb.last_report.section_names
    assert any(n.startswith("prog/") for n in names), names
    assert any(n.startswith("op/") for n in names), names


def test_hierarchical_checkpoint_callback(tmp_path):
    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager

    mgr = LocalCheckpointManager(str(tmp_path / "local"), rank=0)
    cb = HierarchicalCheckpointCallback(
        local_manager=mgr,
        global_dir=str(tmp_path / "global"),
        local_every=2,
        global_every=4,
        to_state_dict=lambda s: {"w": s},
        from_state_dict=lambda s, loaded: loaded["w"],
    )
    os.makedirs(str(tmp_path / "global"), exist_ok=True)

    def step(state, i):
        return state + jnp.ones(())

    ctx = run_training(step, jnp.zeros(()), 8, callbacks=[cb])
    assert float(ctx.state) == 8.0
    # Local checkpoints exist for steps 2,4,6,8; global for 4,8.
    assert mgr.find_latest() == 8
    assert cb.latest_global_step() == 8

    # Restore path: local is newest → used.
    ctx2 = LoopContext()
    ctx2.state = jnp.zeros(())
    assert cb.restore_latest(ctx2)
    assert float(ctx2.state) == 8.0 and ctx2.start_step == 8
    cb.close()


def test_checkpoint_callback_prefers_newest_tier(tmp_path):
    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager

    mgr = LocalCheckpointManager(str(tmp_path / "local"), rank=0)
    cb = HierarchicalCheckpointCallback(
        local_manager=mgr,
        global_dir=str(tmp_path / "global"),
        local_every=3,
        global_every=4,
        to_state_dict=lambda s: {"w": s},
        from_state_dict=lambda s, loaded: loaded["w"],
    )
    os.makedirs(str(tmp_path / "global"), exist_ok=True)
    ctx = run_training(lambda s, i: s + jnp.ones(()), jnp.zeros(()), 4, callbacks=[cb])
    # local at step 3, global at step 4 → global wins.
    ctx2 = LoopContext()
    ctx2.state = jnp.zeros(())
    assert cb.restore_latest(ctx2)
    assert ctx2.start_step == 4 and float(ctx2.state) == 4.0
    cb.close()


def test_checkpoint_callback_rank_suffixed_global_restore(tmp_path):
    """Global checkpoints saved with a rank suffix must be discoverable again."""
    cb = HierarchicalCheckpointCallback(
        global_dir=str(tmp_path / "g"),
        global_every=2,
        rank=0,
        to_state_dict=lambda s: {"w": s},
        from_state_dict=lambda s, loaded: loaded["w"],
    )
    os.makedirs(str(tmp_path / "g"), exist_ok=True)
    run_training(lambda s, i: s + jnp.ones(()), jnp.zeros(()), 4, callbacks=[cb])
    assert cb.latest_global_step() == 4
    ctx = LoopContext()
    ctx.state = jnp.zeros(())
    assert cb.restore_latest(ctx)
    assert ctx.start_step == 4 and float(ctx.state) == 4.0
    cb.close()


def test_checkpoint_callback_driven_by_loop_brackets(monitor, tmp_path):
    """save_now wired as checkpoint_fn: saves happen inside the loop's checkpoint
    brackets so the sections callback attributes them to 'checkpointing'."""
    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager

    mgr = LocalCheckpointManager(str(tmp_path / "local"), rank=0)
    ckpt_cb = HierarchicalCheckpointCallback(
        local_manager=mgr,
        local_every=2,
        to_state_dict=lambda s: {"w": s},
        from_state_dict=lambda s, loaded: loaded["w"],
        driven_by_loop=True,
    )
    sections = FaultToleranceSectionsCallback()
    ctx = run_training(
        lambda s, i: s + jnp.ones(()),
        jnp.zeros(()),
        4,
        callbacks=[sections, ckpt_cb],
        checkpoint_every=ckpt_cb.cadence,
        checkpoint_fn=ckpt_cb.save_now,
    )
    assert float(ctx.state) == 4.0
    assert mgr.find_latest() == 4
    assert sections.client.timeouts_calc.section_max_elapsed.get("checkpointing", 0) > 0
    ckpt_cb.close()


def test_cooperative_stop_does_not_write_finished_flag(monitor, tmp_path):
    flag = str(tmp_path / "f.flag")

    class StopAtTwo(Callback):
        def on_step_end(self, ctx):
            if ctx.step == 2:
                raise StopTraining

    cb = FaultToleranceCallback(autoresume=True, finished_flag_path=flag)
    run_training(lambda s, i: s + 1, 0, 100, callbacks=[cb, StopAtTwo()])
    assert not os.path.exists(flag)  # job is NOT finished — must be rescheduled


def test_straggler_report_emits_structured_event():
    """Every report lands on the structured event stream as a machine-readable
    twin of the log lines (the reference's events/metrics-stream role)."""
    from tpu_resiliency.utils import events

    if Detector.initialized:
        Detector.shutdown()
    seen = []
    events.add_sink(seen.append)
    try:
        cb = StragglerDetectionCallback(report_time_interval=0.0)
        ctx = run_training(lambda s, i: s + 1, 0, 20, callbacks=[cb])
        assert ctx.state == 20
    finally:
        events.remove_sink(seen.append)
    reports = [e for e in seen if e.kind == "straggler_report"]
    assert reports, [e.kind for e in seen]
    ev = reports[-1]
    assert ev.source == "telemetry"
    assert set(ev.payload) >= {"step", "perf_scores", "stragglers_by_perf",
                               "stragglers_by_section"}
    assert ev.payload["perf_scores"].get("0") == 1.0  # single healthy rank (str keys: on-disk schema)
    assert ev.payload["stragglers_by_perf"] == []
