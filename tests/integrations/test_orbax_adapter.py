"""The loop protocol driving orbax (code this framework didn't write): save via
hooks, crash, rebuild the manager, restore, and finish — the ecosystem-adapter
proof (VERDICT r3 item 10; reference analogue:
``ptl_resiliency/local_checkpoint_callback.py:101-203``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_resiliency.integrations import OrbaxCheckpointCallback
from tpu_resiliency.integrations.loop import LoopContext, run_training


def _step_fn(state, step):
    return {"w": state["w"] + 1.0, "step": jnp.asarray(step)}


def _init_state():
    return {"w": jnp.zeros((4,)), "step": jnp.asarray(0)}


def test_save_restore_roundtrip(tmp_path):
    cb = OrbaxCheckpointCallback(str(tmp_path / "orbax"), every=2)
    ctx = run_training(_step_fn, _init_state(), num_steps=6, callbacks=[cb])
    assert float(ctx.state["w"][0]) == 6.0
    assert cb.latest_step() == 5  # saves after steps 1, 3, 5
    cb.close()

    # A fresh process/manager (post-crash) restores the newest step and resumes.
    cb2 = OrbaxCheckpointCallback(str(tmp_path / "orbax"), every=2)
    ctx2 = LoopContext()
    ctx2.state = _init_state()
    assert cb2.restore_latest(ctx2)
    assert ctx2.start_step == 6
    np.testing.assert_array_equal(np.asarray(ctx2.state["w"]), np.full((4,), 6.0))

    # Resume the loop from the restored step and run to 8.
    ctx3 = run_training(
        _step_fn, ctx2.state, num_steps=8, callbacks=[cb2], ctx=ctx2
    )
    assert float(ctx3.state["w"][0]) == 8.0
    assert cb2.latest_step() == 7
    cb2.close()


def test_restore_empty_returns_false(tmp_path):
    cb = OrbaxCheckpointCallback(str(tmp_path / "empty"), every=2)
    ctx = LoopContext()
    ctx.state = _init_state()
    assert not cb.restore_latest(ctx)
    assert ctx.start_step == 0
    cb.close()


def test_retention_prunes_old_steps(tmp_path):
    cb = OrbaxCheckpointCallback(str(tmp_path / "keep"), every=1, max_to_keep=2)
    run_training(_step_fn, _init_state(), num_steps=5, callbacks=[cb])
    cb.manager.wait_until_finished()
    steps = sorted(cb.manager.all_steps())
    assert steps == [3, 4], steps
    cb.close()


def test_composes_with_local_tier(tmp_path):
    """Both tiers on one loop: orbax global saves + the framework's local-manager
    saves, from independent callbacks."""
    from tpu_resiliency.checkpoint import LocalCheckpointManager, PyTreeStateDict
    from tpu_resiliency.integrations import HierarchicalCheckpointCallback

    local_mgr = LocalCheckpointManager(str(tmp_path / "local"), rank=0)
    local_cb = HierarchicalCheckpointCallback(
        local_manager=local_mgr, local_every=2
    )
    orbax_cb = OrbaxCheckpointCallback(str(tmp_path / "orbax"), every=3)
    run_training(
        _step_fn, _init_state(), num_steps=6, callbacks=[local_cb, orbax_cb]
    )
    local_mgr.queue.maybe_finalize_async_calls(blocking=True)
    # Local tier records steps-completed (6); orbax records the 0-based step (5).
    assert local_mgr.find_latest() == 6
    assert orbax_cb.latest_step() == 5
    orbax_cb.close()
    local_cb.close()
